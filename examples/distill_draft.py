"""Learned-drafting demo (ISSUE 16): distill a draft, serve, hot-swap.

The full loop on a CPU-sized model, end to end:

  1. TRAIN a tiny GPT-2 target on a seeded successor-permutation
     language (token t+1 = succ[token t]) — depth has to do real work,
     or a truncated draft is trivially close to its teacher;
  2. DISTILL a 1-layer student with multi-token proposal heads against
     the target's logits over a logged-traffic corpus (DistillTrainer:
     the unchanged Trainer loop under the hood);
  3. SERVE with the UNTRAINED truncated warm start and measure
     acceptance;
  4. HOT-SWAP the distilled draft in MID-STREAM via set_draft_params —
     resident requests keep their token-for-token identity (speculative
     decoding is lossless under any draft; the demo asserts bitwise
     parity vs generate()) while acceptance and decode throughput jump.

Run anywhere:

    JAX_PLATFORMS=cpu python examples/distill_draft.py

A fleet does the same swap in one call: ReplicaRouter.set_draft_params
broadcasts a DistillTrainer checkpoint path to every replica (see
README "Learned drafting").
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import generate, make_draft
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.serving import ServingEngine
from pytorchdistributed_tpu.training import (
    DistillTrainer,
    Trainer,
    distill_corpus,
    token_cross_entropy_loss,
)


def main():
    parser = argparse.ArgumentParser(description="distill-draft demo")
    parser.add_argument("--target-steps", type=int, default=150)
    parser.add_argument("--distill-epochs", type=int, default=24)
    parser.add_argument("--spec-k", type=int, default=4)
    parser.add_argument("--requests", type=int, default=6)
    args = parser.parse_args()

    cfg = gpt2_config("test", num_layers=4, max_seq_len=128)
    model = GPT2(cfg)
    spec_k = args.spec_k

    # -- 1. train the target on the successor-permutation language ----
    succ = np.random.default_rng(11).permutation(cfg.vocab_size)

    def rows(rng, n, s):
        out = np.empty((n, s), np.int32)
        out[:, 0] = rng.integers(0, cfg.vocab_size, n)
        for t in range(1, s):
            out[:, t] = succ[out[:, t - 1]]
        return out

    trainer = Trainer(model, optax.adamw(3e-3), token_cross_entropy_loss,
                      log_every=10**9)
    rng = np.random.default_rng(5)

    def lm_batch():
        r = rows(rng, 16, 96)
        return {"tokens": r[:, :-1], "targets": r[:, 1:]}

    trainer.init(lm_batch())
    m = None
    for _ in range(args.target_steps):
        m = trainer.train_step(lm_batch())
    params = jax.device_get(trainer.state.params)
    print(f"target trained: {args.target_steps} steps, "
          f"ce {float(m['loss']):.4f}")

    # -- 2. distill the draft (truncated warm start + proposal heads) --
    corpus = distill_corpus(model, params, seed=7, num_batches=4,
                            batch_size=8, seq_len=64, max_new_tokens=12)
    dt = DistillTrainer(model, params, num_layers=1,
                        spec_heads=spec_k - 1)
    dt.init(corpus[0])
    first = last = None
    for _ in range(args.distill_epochs):
        for b in corpus:
            mm = dt.train_step(b)
            if first is None:
                first = float(mm["loss"])
    last = float(mm["loss"])
    print(f"distilled: {args.distill_epochs} epochs, "
          f"kl {first:.4f} -> {last:.4f}")
    _, distilled = dt.draft()

    # -- 3. serve on the UNTRAINED truncated warm start ---------------
    warm_model, warm = make_draft(model, params, num_layers=1,
                                  spec_heads=spec_k - 1)
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=32,
                           block_size=16, spec_k=spec_k,
                           draft_config=warm_model.cfg, draft_params=warm,
                           adaptive_k=True)
    engine.warmup(prompt_lens=(32,))
    prng = np.random.default_rng(3)
    prompts = [prng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in (9, 14, 7, 11, 6, 13)][:args.requests]
    for p in prompts:
        engine.submit(p, max_new_tokens=24)
        engine.step()
    engine.run_until_idle()
    s0 = engine.summary()
    print(f"truncated draft ({engine.draft_params_hash()}): "
          f"acceptance {s0['acceptance_rate']:.3f}, "
          f"{s0['tokens_per_target_forward']:.2f} tokens/target-forward")

    # -- 4. hot-swap the distilled draft MID-STREAM --------------------
    reqs = [engine.submit(p, max_new_tokens=24) for p in prompts]
    engine.step()
    engine.set_draft_params(distilled)
    print(f"hot-swap mid-stream -> draft {engine.draft_params_hash()} "
          f"(swap #{engine.draft_swaps})")
    engine.run_until_idle()
    s1 = engine.summary()
    drafted = s1["draft_tokens"] - s0["draft_tokens"]
    accepted = s1["accepted_tokens"] - s0["accepted_tokens"]
    print(f"distilled draft: acceptance {accepted / drafted:.3f} "
          f"over the swapped phase (fleet swap: "
          f"ReplicaRouter.set_draft_params(checkpoint=...))")

    # losslessness: streams that crossed the swap are bitwise-equal to
    # plain generate()
    import dataclasses

    dm = GPT2(dataclasses.replace(cfg, decode=True))
    for p, r in zip(prompts, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=24)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0])
    print("bitwise parity vs generate() across the swap: OK")
    engine.close()


if __name__ == "__main__":
    main()
