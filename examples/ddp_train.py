"""DDP training example — the framework's `ddp_gpus.py` equivalent.

The reference launches one process per GPU and wraps the model in DDP
(reference ddp_gpus.py). On TPU the same job is ONE process per host with the
batch sharded over a device mesh; gradient all-reduce happens inside the
jitted step. Run on CPU with a simulated 8-chip mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/ddp_train.py --max_epochs 3 --batch_size 32

or on TPU hardware with no flags at all.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import optax

import pytorchdistributed_tpu as ptd
from pytorchdistributed_tpu.data import DataLoader, SyntheticRegressionDataset
from pytorchdistributed_tpu.models import LinearRegression
from pytorchdistributed_tpu.training import Trainer, mse_loss


def main():
    # Same CLI as the reference (ddp_gpus.py:88-92).
    parser = argparse.ArgumentParser(description="distributed training job")
    parser.add_argument("--max_epochs", type=int, default=5)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--strategy", choices=["dp", "fsdp"], default="dp")
    args = parser.parse_args()

    ptd.init_process_group()
    try:
        dataset = SyntheticRegressionDataset(size=2048, in_dim=20, out_dim=1)
        loader = DataLoader(dataset, batch_size=args.batch_size)
        trainer = Trainer(
            LinearRegression(),
            optax.sgd(1e-3),
            mse_loss,
            strategy=args.strategy,
        )
        trainer.fit(loader, max_epochs=args.max_epochs)
    finally:
        ptd.destroy_process_group()


if __name__ == "__main__":
    main()
