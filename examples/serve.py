"""Train-then-SERVE demo — the continuous-batching half of the north
star's "serves heavy traffic" goal (serving/ServingEngine), on the same
tiny identity-task Llama as examples/generate.py.

Unlike the one-shot generate() call, requests here arrive staggered with
different prompt lengths, budgets and sampling params; the engine admits
each into a KV-cache slot as one frees, decodes all resident requests in
one compiled tick per step, and streams tokens per request. Run anywhere:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/serve.py --steps 200

or on TPU hardware with no flags. Pass --telemetry-dir to also get the
serving spans + metric JSONL (readable with
`python -m pytorchdistributed_tpu.telemetry merge-trace <dir>`).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

import pytorchdistributed_tpu as ptd
from pytorchdistributed_tpu.models import Llama, llama_config
from pytorchdistributed_tpu.serving import SamplingParams, ServingEngine
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss


def main():
    parser = argparse.ArgumentParser(description="train + serve demo")
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--num-slots", type=int, default=3)
    parser.add_argument("--requests", type=int, default=6)
    parser.add_argument("--telemetry-dir", type=str, default=None)
    parser.add_argument("--block-size", type=int, default=0,
                        help="> 0: serve from the paged KV engine "
                             "(block-table pool + radix prefix reuse + "
                             "chunked prefill; README 'Paged KV cache')")
    parser.add_argument("--spec-k", type=int, default=0,
                        help="> 0: speculative decoding — a draft model "
                             "proposes this many tokens per target "
                             "forward, losslessly verified (README "
                             "'Speculative decoding'; implies the paged "
                             "engine, default block size 16)")
    parser.add_argument("--draft-layers", type=int, default=0,
                        help="with --spec-k: build the draft by "
                             "truncating the trained model to its first "
                             "N layers (0 = self-draft with the full "
                             "model, acceptance ~1)")
    parser.add_argument("--compile-cache", type=str, default=None,
                        help="persistent AOT executable cache directory "
                             "(README 'Cold start & elastic recovery'): "
                             "first run compiles + serializes every "
                             "serving program, later runs deserialize "
                             "them — restart reaches its first token "
                             "with zero XLA compiles. PTD_COMPILE_CACHE "
                             "works too")
    parser.add_argument("--replicas", type=int, default=1,
                        help="> 1: serve through the health-checked "
                             "ReplicaRouter over this many in-process "
                             "engine replicas (README 'Replicated "
                             "serving & failover')")
    parser.add_argument("--prefill-replicas", type=int, default=0,
                        help="with --decode-replicas: DISAGGREGATED "
                             "topology (README 'Disaggregated serving') "
                             "— this many prefill-role replicas chunk-"
                             "prefill each prompt, then hand the KV "
                             "blocks to a decode-role replica over the "
                             "KV stream; overrides --replicas and "
                             "implies the paged engine")
    parser.add_argument("--decode-replicas", type=int, default=0,
                        help="decode-role replica count for the "
                             "disaggregated topology (see "
                             "--prefill-replicas)")
    parser.add_argument("--moe-experts", type=int, default=0,
                        help="> 0: train and serve a Switch-MoE model "
                             "(README 'Expert parallelism') — expert "
                             "kernels shard over an 'expert' mesh axis "
                             "sized from the device count, training "
                             "routes through the explicit all_to_all "
                             "dispatch, and the engine ticks on the "
                             "same dp x expert mesh")
    parser.add_argument("--autoscale", action="store_true",
                        help="serve a seeded flash-crowd trace through "
                             "the SLO autoscaler (README 'Autoscaling & "
                             "multi-tenancy'): the fleet starts at "
                             "--replicas, warm-joins replicas into the "
                             "crowd with zero fresh compiles, and "
                             "drains back to baseline after it passes")
    parser.add_argument("--tenants", type=int, default=0,
                        help="> 0: multi-tenant admission — requests "
                             "carry round-robin tenant tags (t0 gets a "
                             "10x share under --autoscale), the WDRR "
                             "scheduler keeps the token split weighted-"
                             "fair, and the summary prints the per-"
                             "tenant table")
    parser.add_argument("--sessions", action="store_true",
                        help="multi-turn demo (README 'Persistent "
                             "sessions & KV tiering'): a seeded "
                             "conversation mix replayed through a "
                             "sessioned router — later turns REATTACH "
                             "the parked KV (HBM or the store's DRAM "
                             "tier) instead of re-prefilling; implies "
                             "the paged engine and the router path")
    parser.add_argument("--trace", action="store_true",
                        help="fleet-wide request tracing (README "
                             "'Distributed request tracing'): every "
                             "request carries a TraceContext through "
                             "queue/admission/prefill/handoff/decode, "
                             "and the run ends with the per-stage "
                             "critical-path + SLO-debt report (needs "
                             "--telemetry-dir; serves via the router)")
    parser.add_argument("--chaos", action="store_true",
                        help="with --replicas > 1: crash replica 0 "
                             "mid-trace — watch the router redispatch "
                             "its streams to a survivor with the SAME "
                             "tokens")
    args = parser.parse_args()
    if args.sessions:
        if args.autoscale:
            parser.error("--sessions and --autoscale are separate "
                         "demos — run them one at a time")
        if not args.block_size:
            args.block_size = 16  # sessions require the paged engine
    if args.trace and not args.telemetry_dir:
        parser.error("--trace needs --telemetry-dir (spans are "
                     "trace_rank*.jsonl files in the run dir)")
    if args.spec_k and not args.block_size:
        args.block_size = 16  # spec requires the paged engine
    roles = None
    if args.prefill_replicas or args.decode_replicas:
        if not (args.prefill_replicas and args.decode_replicas):
            parser.error("--prefill-replicas and --decode-replicas go "
                         "together (a disaggregated fleet needs both "
                         "halves)")
        if args.spec_k:
            parser.error("--spec-k and the disaggregated topology are "
                         "mutually exclusive (KV handoff carries no "
                         "draft state)")
        from pytorchdistributed_tpu.serving import ROLE_DECODE, ROLE_PREFILL

        roles = ([ROLE_PREFILL] * args.prefill_replicas
                 + [ROLE_DECODE] * args.decode_replicas)
        args.replicas = len(roles)
        if not args.block_size:
            args.block_size = 16  # KV handoff requires the paged engine

    ptd.init_process_group()
    mesh, moe_kw, loss = ptd.create_mesh(), {}, token_cross_entropy_loss
    if args.moe_experts:
        if args.replicas > 1 or roles:
            parser.error("--moe-experts serves through one expert-sharded "
                         "engine (replicated/disaggregated topologies "
                         "would need per-replica meshes)")
        import jax

        from pytorchdistributed_tpu.runtime.mesh import MeshConfig
        from pytorchdistributed_tpu.training import (
            moe_token_cross_entropy_loss,
        )

        ndev = jax.device_count()
        ep = next((e for e in (4, 2, 8)
                   if ndev % e == 0 and args.moe_experts % e == 0), 1)
        mesh = ptd.create_mesh(MeshConfig(data=ndev // ep, expert=ep))
        moe_kw = dict(moe_experts=args.moe_experts)
        loss = moe_token_cross_entropy_loss
    cfg = llama_config("test", max_seq_len=64, **moe_kw)
    model = Llama(cfg)
    trainer = Trainer(model, optax.adamw(3e-3), loss,
                      mesh=mesh, strategy="dp", log_every=50)

    # identity task: target[t] = token[t] — greedy serving visibly repeats
    # each prompt's last token (the learned behavior), so mixed-length
    # continuations are easy to eyeball
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, cfg.vocab_size, (32, 32)).astype(np.int32)
    batch = {"tokens": tokens, "targets": tokens.copy()}
    for _ in range(args.steps):
        metrics = trainer.train_step(batch)
        float(metrics["loss"])  # force the async dispatch each step
    print(f"trained {args.steps} steps, loss {float(metrics['loss']):.4f}")

    params = {"params": trainer.state.params["params"]}
    spec_kw = {}
    if args.spec_k and args.draft_layers:
        from pytorchdistributed_tpu.inference import truncated_draft

        draft, draft_params = truncated_draft(model, params,
                                              args.draft_layers)
        spec_kw = dict(draft_config=draft.cfg, draft_params=draft_params)

    if (args.replicas > 1 or args.autoscale or args.tenants
            or args.trace or args.sessions):
        # REPLICATED serving (ISSUE 9): the router owns N engines,
        # balances on their health snapshots and — with --chaos — shows
        # lossless mid-stream failover: the crashed replica's streams
        # resume on a survivor with identical tokens. --autoscale /
        # --tenants (ISSUE 15) ride the same router path, so a
        # 1-replica fleet works too.
        from pytorchdistributed_tpu.serving import ReplicaRouter

        # no --chaos: leave the router's default ("auto") so the
        # PTD_FAULTS env contract keeps working through the demo
        router_kw = {}
        if args.trace:
            # request tracing (ISSUE 17): every submit mints a
            # TraceContext; the run ends with the merged critical-path
            # report over trace_rank*.jsonl
            router_kw["trace"] = True
        if args.chaos:
            # the supported chaos contract — the same spec syntax
            # `run.py --faults` / PTD_FAULTS accept; the router fires
            # it at its own tick counter (one submit = one tick here,
            # so this kills replica 0 mid-trace)
            from pytorchdistributed_tpu.faults import (
                FaultInjector,
                FaultPlan,
            )

            spec = (f"replica_crash@tick={max(2, args.requests // 2)},"
                    f"replica=0")
            print(f"--- chaos armed: {spec} ---")
            router_kw["faults"] = FaultInjector(FaultPlan.parse(spec))
        store = None
        if args.sessions:
            # persistent sessions (ISSUE 18): the router owns the
            # host-DRAM store tier; engines park finished session
            # streams in HBM and demote the eldest into it
            from pytorchdistributed_tpu.serving import SessionStore

            store = SessionStore(None, dram_bytes=64 << 20)
            router_kw["session_store"] = store
        names = ["default"]
        if args.tenants:
            # equal WDRR weights: fairness comes from the scheduler,
            # not from handicapping the hot tenant's quota
            from pytorchdistributed_tpu.serving import TenantConfig

            names = [f"t{i}" for i in range(args.tenants)]
            router_kw["tenants"] = {n: TenantConfig(weight=1.0)
                                    for n in names}
        router = ReplicaRouter(
            model, params, replicas=args.replicas, roles=roles,
            engine_kwargs=dict(num_slots=args.num_slots,
                               prefill_bucket=16,
                               block_size=args.block_size,
                               spec_k=args.spec_k,
                               compile_cache=args.compile_cache or "auto",
                               **spec_kw),
            warmup_lens=(16,), telemetry_dir=args.telemetry_dir,
            **router_kw)
        router.warmup()
        router.install_sigterm_drain()
        if args.autoscale:
            # a seeded flash crowd on the fake-clock replay driver: the
            # autoscaler warm-joins replicas into the breach (zero
            # fresh compiles — in-process joins share the jit cache)
            # and the post-crowd drain removes them gracefully
            from pytorchdistributed_tpu.serving import (
                Autoscaler,
                FakeClock,
                SLOConfig,
                TenantTraffic,
                make_trace,
                replay,
            )

            mix = tuple(
                TenantTraffic(n, share=(10.0 if i == 0 and len(names) > 1
                                        else 1.0))
                for i, n in enumerate(names))
            trace = make_trace(
                seed=0, duration_s=3.0, base_qps=4.0, shape="flash",
                peak_mult=20.0, tenants=mix,
                vocab_size=cfg.vocab_size, prompt_cap=12, new_cap=8)
            clk = FakeClock()
            # TTFT is wall-clock, not fake-clock — neutralized so host
            # step timing isn't a control input in a demo run
            asc = Autoscaler(
                router,
                SLOConfig(queue_high=3.0, shed_rate_max=1.0,
                          ttft_target_ms=1e9),
                min_replicas=args.replicas,
                max_replicas=args.replicas + 2, breach_ticks=2,
                clear_ticks=25, up_cooldown_s=0.3, down_cooldown_s=0.2,
                clock=clk)
            print(f"--- flash crowd: {len(trace)} requests over "
                  f"{sorted({t.tenant for t in trace})} ---")
            reqs = replay(router, trace, clock=clk, tick_s=0.02,
                          autoscaler=asc)
            for _ in range(3000):   # drain back down to baseline
                router.step()
                asc.step()
                clk.advance(0.02)
                st = router.pool_state()["fleet"]
                if (st["healthy"] == args.replicas
                        and st["draining"] == 0):
                    break
            for d in asc.decisions:
                print(f"  {d['action']} replica={d['replica']} "
                      f"why={','.join(d['why'])} "
                      f"queue={d['m_queue_depth']:.1f}")
            done = sum(1 for r in reqs if r.finish_reason
                       in ("length", "stop"))
            print(f"served {done}/{len(reqs)} "
                  f"(shed {sum(1 for r in reqs if r.finish_reason == 'shed')})")
            print("autoscaler summary:", asc.summary())
        elif args.sessions:
            # a seeded multi-turn mix on the fake-clock replay driver:
            # each turn submits only after the previous finished and
            # its think gap elapsed, carrying the full history — later
            # turns reattach the parked KV instead of re-prefilling
            from pytorchdistributed_tpu.serving import (
                make_conversations,
                replay_conversations,
            )

            convs = make_conversations(
                seed=0, duration_s=6.0, session_rate=0.8,
                vocab_size=cfg.vocab_size, turns_cap=4, turn_cap=10,
                new_cap=6, think_mean_s=0.3)
            print(f"--- {len(convs)} conversations, "
                  f"{sum(len(c.turns) for c in convs)} turns ---")
            out = replay_conversations(router, convs, tick_s=0.02,
                                       max_seq_len=cfg.max_seq_len)
            for c in convs:
                for t, r in enumerate(out[c.session_id]):
                    hops = "->".join(map(str, r.replicas))
                    print(f"  {c.session_id} turn {t} (replica {hops},"
                          f" {r.finish_reason}): "
                          f"{len(r.prompt)} ctx -> {list(r.tokens)}")
            sess = router.summary().get("sessions", {})
            print(f"session reattaches {sess.get('reattach')} "
                  f"fallbacks {sess.get('fallbacks')} "
                  f"demotes {sess.get('demotes')}")
        else:
            reqs = []
            for i in range(args.requests):
                prompt = rng.integers(1, cfg.vocab_size,
                                      (int(rng.integers(3, 12)),)
                                      ).astype(np.int32)
                sampling = (SamplingParams() if i % 2 == 0 else
                            SamplingParams(temperature=0.7, top_k=8,
                                           seed=i))
                reqs.append(router.submit(prompt, max_new_tokens=8,
                                          sampling=sampling,
                                          tenant=names[i % len(names)]))
                router.step()
            router.run_until_idle()
            for r in reqs:
                hops = "->".join(map(str, r.replicas))
                print(f"req {r.id} (replica {hops}, {r.tenant}, "
                      f"{r.finish_reason}, retries {r.retries}): "
                      f"{r.prompt.tolist()} -> {r.tokens}")
        print("router summary:", router.summary())
        router.close()
        if store is not None:
            print("session store:", store.stats())
            store.close()
        if args.trace:
            from pytorchdistributed_tpu.telemetry.tracing import (
                render_trace,
            )

            print()
            print(render_trace(args.telemetry_dir, top=args.requests))
        ptd.destroy_process_group()
        return

    engine = ServingEngine(
        model, params,
        num_slots=args.num_slots, prefill_bucket=16,
        block_size=args.block_size, spec_k=args.spec_k, **spec_kw,
        mesh=mesh if args.moe_experts else None,
        telemetry_dir=args.telemetry_dir,
        compile_cache=args.compile_cache or "auto")
    engine.warmup(prompt_lens=(16,))

    # staggered mixed-length traffic: more requests than slots, per-request
    # budgets and sampling — the queue drains as slots retire
    reqs = []
    for i in range(args.requests):
        prompt = rng.integers(1, cfg.vocab_size,
                              (int(rng.integers(3, 12)),)).astype(np.int32)
        sampling = (SamplingParams() if i % 2 == 0 else
                    SamplingParams(temperature=0.7, top_k=8, seed=i))
        reqs.append(engine.submit(prompt, max_new_tokens=8,
                                  sampling=sampling))
        engine.step()  # arrivals interleave with decoding
    engine.run_until_idle()

    for r in reqs:
        print(f"req {r.id} (slot {r.slot}, {r.finish_reason}, "
              f"ttft {r.ttft_s * 1e3:.1f} ms): "
              f"{r.prompt.tolist()} -> {r.new_tokens}")
    print("summary:", engine.summary())
    engine.close()
    ptd.destroy_process_group()


if __name__ == "__main__":
    main()
