"""Lesson-3 parity: model / pipeline parallelism + the split-size sweep
(reference 03_model_parallel.ipynb).

The reference splits ResNet-50 across two GPUs by hand, adds micro-batch
pipelining, then sweeps the split size and saves `split_size_tradeoff.png`
(cells 5, 12, 13). The TPU-native equivalents:

  * "model parallel"  -> tensor parallelism (--tensor N): layers sharded
    *within* by the TP rule tables, no manual .to(device) hops;
  * "pipeline parallel" -> GPipe over the pipe mesh axis (--pipe N);
  * the split-size sweep -> micro-batch count sweep, same tradeoff curve
    (bubble fraction vs per-micro-batch overhead).

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/model_parallel.py --sweep

writes split_size_tradeoff.png next to this script (matplotlib optional).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _trainer(num_microbatches: int, *, pipe: int, tensor: int):
    import jax.numpy as jnp
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    model = GPT2(gpt2_config(
        "test", num_layers=4, vocab_size=512, dtype=jnp.float32,
        pipeline_stages=pipe, pipeline_microbatches=num_microbatches))
    mesh = create_mesh(pipe=pipe, tensor=tensor)
    return Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                   mesh=mesh, strategy="tp" if tensor > 1 else "dp",
                   log_every=10**9)


def _time_step(trainer, batch, repeats: int = 5) -> float:
    trainer.train_step(batch)  # compile
    t0 = time.perf_counter()
    for _ in range(repeats):
        m = trainer.train_step(batch)
    float(m["loss"])
    return (time.perf_counter() - t0) / repeats


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pipe", type=int, default=2)
    parser.add_argument("--tensor", type=int, default=2)
    parser.add_argument("--sweep", action="store_true",
                        help="micro-batch sweep -> split_size_tradeoff.png")
    args = parser.parse_args()

    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 512, (32, 128)).astype(np.int32),
        "targets": rng.integers(0, 512, (32, 128)).astype(np.int32),
    }

    if not args.sweep:
        tr = _trainer(4, pipe=args.pipe, tensor=args.tensor)
        for step in range(5):
            m = tr.train_step(batch)
            print(f"step {step}: loss={float(m['loss']):.4f}")
        print(f"mean step time: {_time_step(tr, batch) * 1000:.1f} ms "
              f"(pipe={args.pipe}, tensor={args.tensor})")
        return

    # The reference sweeps split_size over [1,3,5,8,10,12,20,40,60]
    # (03_model_parallel.ipynb:589); micro-batch counts must divide the
    # batch, so the sweep grid differs but the tradeoff is the same.
    sizes = [1, 2, 4, 8, 16, 32]
    means, stds = [], []
    for m in sizes:
        tr = _trainer(m, pipe=args.pipe, tensor=1)
        times = [_time_step(tr, batch, repeats=1) for _ in range(5)]
        means.append(float(np.mean(times)))
        stds.append(float(np.std(times)))
        print(f"microbatches={m}: {means[-1]*1000:.1f} ± "
              f"{stds[-1]*1000:.1f} ms")

    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        fig, ax = plt.subplots(figsize=(7, 4))
        ax.errorbar(sizes, [t * 1000 for t in means],
                    yerr=[t * 1000 for t in stds], marker="o")
        ax.set_xscale("log", base=2)
        ax.set_xlabel("pipeline micro-batches (the reference's split_size)")
        ax.set_ylabel("step time (ms)")
        ax.set_title("GPipe micro-batch tradeoff "
                     "(reference: split_size_tradeoff.png)")
        out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "split_size_tradeoff.png")
        fig.savefig(out, dpi=120, bbox_inches="tight")
        print(f"wrote {out}")
    except ImportError:
        print("matplotlib unavailable; sweep numbers printed above")


if __name__ == "__main__":
    main()
