"""Lesson-2 parity, torchrun variant (reference ddp_gpus_torchrun.py).

Identical training job to examples/ddp_train.py, but rank/world_size come
from the launcher's env contract instead of explicit arguments — the delta
between the reference's two scripts IS the lesson (SURVEY.md §3.2). Launch
with the framework's torchrun equivalent:

    python -m pytorchdistributed_tpu.run --nproc-per-node 2 \
        --devices-per-proc 1 examples/ddp_torchrun.py --max_epochs 3

Each process builds its dataset locally (no cross-process pickling — the
other deliberate delta from the spawn variant, SURVEY.md §3.2).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser(description="torchrun-style DDP job")
    parser.add_argument("--max_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=32)
    args = parser.parse_args()

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # launcher requested the per-proc CPU sim (--devices-per-proc): the
        # ambient jax pre-import may have baked another platform into config.
        # On real TPU hosts JAX_PLATFORMS is unset and this is a no-op.
        import jax
        jax.config.update("jax_platforms", "cpu")

    import optax

    import pytorchdistributed_tpu as ptd
    from pytorchdistributed_tpu.data import (
        DataLoader,
        SyntheticRegressionDataset,
    )
    from pytorchdistributed_tpu.models import LinearRegression
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    ptd.init_process_group()  # rank/world from env — no explicit args
    try:
        dataset = SyntheticRegressionDataset(size=2048, in_dim=20, out_dim=1)
        loader = DataLoader(dataset, batch_size=args.batch_size)
        trainer = Trainer(LinearRegression(), optax.sgd(1e-3), mse_loss)
        trainer.fit(loader, max_epochs=args.max_epochs)
        print(f"[rank {ptd.get_rank()}] done", flush=True)
    finally:
        ptd.destroy_process_group()


if __name__ == "__main__":
    main()
