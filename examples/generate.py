"""Train-then-generate example — the working version of the reference's
inference ambition (the llama-7b `device_map="auto"` cell,
03_model_parallel.ipynb:86-89, which never ran).

Trains a tiny Llama on a synthetic identity task (predict the current
token), then samples continuations with the KV-cache decode loop — greedy
generation visibly repeats the prompt's last token, the learned behavior. Run anywhere:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/generate.py --steps 200

or on TPU hardware with no flags.
"""

import argparse
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

import jax
import jax.numpy as jnp
import pytorchdistributed_tpu as ptd
from pytorchdistributed_tpu.models import Llama, llama_config
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss


def main():
    parser = argparse.ArgumentParser(description="train + generate demo")
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--top_k", type=int, default=None)
    args = parser.parse_args()

    ptd.init_process_group()
    cfg = llama_config("test", max_seq_len=64)
    model = Llama(cfg)
    trainer = Trainer(model, optax.adamw(3e-3), token_cross_entropy_loss,
                      mesh=ptd.create_mesh(), strategy="dp", log_every=50)

    # identity task: target[t] = token[t] — generalizes to unseen prompts,
    # so greedy generation visibly repeats the prompt's last token forever
    rng = np.random.default_rng(0)
    tokens = rng.integers(1, cfg.vocab_size, (32, 32)).astype(np.int32)
    batch = {"tokens": tokens, "targets": tokens.copy()}
    for step in range(args.steps):
        metrics = trainer.train_step(batch)
        # force the async dispatch each step: XLA:CPU's collective
        # rendezvous deadlocks past ~dozens of queued 8-device programs
        # (Trainer.fit's per-step logging does this for real jobs)
        float(metrics["loss"])
    print(f"trained {args.steps} steps, loss "
          f"{float(metrics['loss']):.4f}")

    gen_model = Llama(dataclasses.replace(cfg, decode=True))
    prompt = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 8)), jnp.int32)
    out = ptd.generate(gen_model, {"params": trainer.state.params["params"]},
                       prompt, max_new_tokens=12,
                       temperature=args.temperature, top_k=args.top_k,
                       rng=jax.random.key(0))
    for row in np.asarray(out):
        print("prompt:", row[:8].tolist(), "->", row[8:].tolist())
    ptd.destroy_process_group()


if __name__ == "__main__":
    main()
