"""Lesson-1 parity: single-process data parallelism
(reference 01_multi_gpus_data_parallelism.ipynb).

The reference wraps a 4-layer MLP in `nn.DataParallel`, which scatters each
batch across GPUs from ONE Python process — then spends a markdown cell
explaining why that design is slow (GIL, master-GPU bottleneck; cell 0).

On TPU the single-process form is the *good* path, not the anti-pattern:
one process drives all local chips, the batch is sharded by layout (not
scattered by threads), and outputs never gather to a master chip unless the
program asks. This example runs the same 4-layer MLP forward on every local
device and prints the per-device batch split the reference prints
("In Model: input size ...", cell 6). (Batch 32, not the notebook's 30:
SPMD layouts split evenly — uneven DataParallel scatter was part of the
critiqued design.)

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/data_parallel.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from pytorchdistributed_tpu.data.loader import shard_batch
from pytorchdistributed_tpu.models import MLP
from pytorchdistributed_tpu.runtime.mesh import batch_leaf_sharding, create_mesh


def main():
    mesh = create_mesh()  # all devices on the "data" axis
    model = MLP(features=(10, 20, 10, 5))  # the notebook's 4-layer demo net
    rng = np.random.default_rng(0)

    params = model.init(jax.random.key(0), np.zeros((1, 10), np.float32))
    apply = jax.jit(model.apply)

    n_dev = len(jax.devices())
    print(f"running on {n_dev} device(s): batch 32 splits into "
          f"{32 // n_dev} rows/device")
    for step in range(3):
        batch = {"x": rng.random((32, 10), dtype=np.float32)}
        batch = shard_batch(batch, lambda v: batch_leaf_sharding(mesh, v.ndim))
        out = apply(params, batch["x"])
        # the reference prints input/output sizes from inside the model
        # (cell 6); here the sharding itself is the evidence
        shards = batch["x"].sharding.shard_shape(batch["x"].shape)
        print(f"step {step}: In Model: per-device input {shards}, "
              f"Outside: output size {out.shape}")


if __name__ == "__main__":
    main()
