"""Elastic training end to end: checkpoint as you go, die, resume.

The workflow the launcher's restart/resize machinery exists for — the
reference uses torchrun's elastic agent but never configures it beyond
--nproc_per_node (reference ddp_gpus_torchrun.py:102); here the full
loop is live:

    python -m pytorchdistributed_tpu.run --nproc-per-node 2 \
        --devices-per-proc 1 --max-restarts 2 --heartbeat-timeout 60 \
        examples/elastic_train.py --max_epochs 3 \
        --checkpoint_dir /tmp/elastic_ckpt --die_at_step 28

Rank 0 kills itself at step 28 of its first life (--die_at_step, the
fault injection — well past the step-8/16 periodic checkpoints, so a
save has durably FINALIZED: orbax saves are async, and a save initiated
moments before the crash legitimately doesn't survive it; resume then
falls back to the previous finalized step); the agent detects the
failure, relaunches the group,
and the second incarnation's ``fit(resume=True)`` restores the latest
sharded checkpoint and fast-forwards past the already-trained batches —
the run finishes with the same loss an uninterrupted job produces
(asserted exactly in tests/test_launch.py::
test_elastic_restart_resumes_real_training). The demo is one-shot per
checkpoint_dir: the died-once marker and the finished checkpoint both
live there, so a second identical invocation injects no fault and
resumes a completed run — `rm -rf` the directory to replay it (the
script prints a reminder). Capacity-reduction resize
(--elastic-min-nproc) needs a PERSISTENTLY failing rank and a
world-size-independent data shard, which this one-shot script doesn't
stage — see tests/test_launch.py::test_elastic_resize_* for that
workflow.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    parser = argparse.ArgumentParser(description="elastic training job")
    parser.add_argument("--max_epochs", type=int, default=3)
    parser.add_argument("--batch_size", type=int, default=32)
    parser.add_argument("--checkpoint_dir", type=str,
                        default="/tmp/ptd_elastic_ckpt")
    parser.add_argument("--checkpoint_every_steps", type=int, default=8)
    parser.add_argument("--die_at_step", type=int, default=0,
                        help="rank 0 exits at this step on its FIRST life "
                             "(0 = no fault injection)")
    args = parser.parse_args()

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    import optax

    import pytorchdistributed_tpu as ptd
    from pytorchdistributed_tpu.data import (
        DataLoader,
        SyntheticRegressionDataset,
    )
    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    ptd.init_process_group()
    try:
        dataset = SyntheticRegressionDataset(size=2048, in_dim=20, out_dim=1)
        loader = DataLoader(dataset, batch_size=args.batch_size)

        died_marker = os.path.join(args.checkpoint_dir, "died_once")
        if (args.die_at_step and ptd.get_rank() == 0
                and os.path.exists(died_marker)):
            print(f"[rank 0] marker {died_marker} present: fault injection "
                  f"off (rm -rf {args.checkpoint_dir} to replay the demo)",
                  flush=True)
        if args.die_at_step and ptd.get_rank() == 0 \
                and not os.path.exists(died_marker):
            # fault injection: wrap the loader so rank 0's first life ends
            # mid-epoch, after some checkpoints exist (the marker file is
            # the "only once" memory that survives the relaunch)
            real_iter = type(loader).__iter__

            class DieMidEpoch:
                def __init__(self, inner):
                    self._inner = inner
                    self.sampler = inner.sampler
                    self.batch_size = inner.batch_size
                    self._step = 0

                def set_epoch(self, epoch):
                    self._inner.set_epoch(epoch)

                def __len__(self):
                    return len(self._inner)

                def __iter__(self):
                    for batch in real_iter(self._inner):
                        self._step += 1
                        if self._step == args.die_at_step:
                            os.makedirs(args.checkpoint_dir, exist_ok=True)
                            open(died_marker, "w").close()
                            print(f"[rank 0] injected failure at step "
                                  f"{self._step}", flush=True)
                            os._exit(17)
                        yield batch

            loader = DieMidEpoch(loader)

        trainer = Trainer(MLP(features=(64, 1)), optax.sgd(1e-3), mse_loss,
                          checkpoint_dir=args.checkpoint_dir,
                          checkpoint_every_steps=args.checkpoint_every_steps)
        metrics = trainer.fit(loader, max_epochs=args.max_epochs,
                              resume=True)
        print(f"[rank {ptd.get_rank()}] done: {metrics}", flush=True)
    finally:
        ptd.destroy_process_group()


if __name__ == "__main__":
    main()
