"""Long-context training via sequence/context parallelism.

The reference has no long-context story at all (SURVEY.md §5: its only
"ring" is ring-allreduce of gradients, 02_ddp.ipynb:33-47); this example is
the framework-native one. The sequence dim is sharded over the "seq" mesh
axis, so each device holds S/n tokens of every batch row and attention runs
as either:

  * ring   — K/V shards rotate around the ICI ring (`lax.ppermute`), each
    hop folded into the flash recurrence; O(S_local · block) memory in
    forward AND backward (custom_vjp reverse ring, ops/ring_attention.py),
    the choice when S per device is the binding constraint;
  * ulysses — two all-to-alls re-shard heads↔sequence so each device runs
    full-sequence flash attention for its head subset; cheaper in
    communication when heads ≥ shards (ops/ulysses.py).

Run on the CPU sim (no TPU needed):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context.py --attention ring --seq_shards 4

The loss printed must match `--attention dense --seq_shards 1` to fp32
tolerance — context parallelism is a layout choice, not an approximation
(tests/test_attention.py pins this).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--attention", default="ring",
                        choices=["ring", "ulysses", "dense"])
    parser.add_argument("--seq_shards", type=int, default=4)
    parser.add_argument("--seq_len", type=int, default=512)
    parser.add_argument("--batch_size", type=int, default=8,
                        help="must be divisible by the data-axis size "
                             "(devices / seq_shards)")
    parser.add_argument("--steps", type=int, default=10)
    args = parser.parse_args()

    import jax.numpy as jnp
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    # data axis takes whatever devices the seq axis leaves over
    mesh = create_mesh(data=-1, seq=args.seq_shards)
    cfg = gpt2_config("test", num_layers=4, max_seq_len=args.seq_len,
                      attention=args.attention, dtype=jnp.float32)
    trainer = Trainer(GPT2(cfg), optax.adamw(1e-3),
                      token_cross_entropy_loss, mesh=mesh, strategy="dp",
                      log_every=5)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(
            0, cfg.vocab_size,
            (args.batch_size, args.seq_len)).astype(np.int32),
        "targets": rng.integers(
            0, cfg.vocab_size,
            (args.batch_size, args.seq_len)).astype(np.int32),
    }
    for step in range(args.steps):
        metrics = trainer.train_step(batch)
        if (step + 1) % 5 == 0:
            print(f"step {step + 1} | loss {float(metrics['loss']):.4f} | "
                  f"{args.attention} x{args.seq_shards} seq shards")


if __name__ == "__main__":
    main()
