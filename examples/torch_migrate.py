"""Migrate a PyTorch model into the TPU framework and keep working.

The reference lives in the torch ecosystem; this is the bridge for its
users: take a ``GPT2LMHeadModel`` (here randomly initialized — substitute
``from_pretrained(...)`` where downloads are available), relay its
``state_dict`` into this framework (models/torch_import.py), verify the
logits agree with the torch forward, fine-tune a few sharded DDP steps,
and sample from the result with the KV-cache decode loop. Run anywhere:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/torch_migrate.py

or on TPU hardware with no flags.
"""

import argparse
import dataclasses
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.models.torch_import import gpt2_params_from_torch
from pytorchdistributed_tpu.runtime.mesh import create_mesh
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss
from pytorchdistributed_tpu.training.trainer import TrainState


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=30)
    args = parser.parse_args()

    import torch
    import transformers

    # 1. the torch model (stand-in for a pretrained checkpoint)
    hf_cfg = transformers.GPT2Config(
        vocab_size=256, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        activation_function="gelu_new",
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()

    # 2. import the weights
    cfg = gpt2_config("test", vocab_size=256, dtype=jnp.float32,
                      attention="dense", scan_layers=False)
    params = gpt2_params_from_torch(hf.state_dict(), cfg)

    # 3. parity check against the torch forward
    tokens = np.random.default_rng(0).integers(0, 256, (2, 16))
    with torch.no_grad():
        want = hf(torch.asarray(tokens)).logits.numpy()
    got = GPT2(cfg).apply(params, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)
    print(f"parity: imported logits match torch "
          f"(max |Δ| = {np.abs(np.asarray(got) - want).max():.2e})")

    # 4. fine-tune, sharded DDP over every device
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 256, (32, 17)).astype(np.int32)
    batch = {"tokens": toks[:, :-1], "targets": toks[:, 1:]}
    tr = Trainer(GPT2(cfg), optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(), strategy="dp", log_every=10)
    tr.init(batch)
    tr.state = TrainState(step=tr.state.step,
                          params=jax.device_put(params,
                                                tr.state_shardings.params),
                          opt_state=tr.state.opt_state)
    metrics = None
    for _ in range(args.steps):
        metrics = tr.train_step(batch)
    loss = f", loss {float(metrics['loss']):.4f}" if metrics else ""
    print(f"fine-tuned {args.steps} steps on "
          f"{tr.mesh.devices.size} device(s){loss}")

    # 5. sample with the KV-cache decode loop
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    out = generate(dm, tr.state.params,
                   jnp.asarray(tokens[:, :8], jnp.int32),
                   max_new_tokens=8, temperature=0.0)
    print(f"generated: {np.asarray(out)[:, 8:].tolist()}")


if __name__ == "__main__":
    main()
