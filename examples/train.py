"""Generic config-driven training entrypoint.

The five BASELINE.json benchmark configs are presets:

    python examples/train.py --preset resnet18_cifar_smoke
    python examples/train.py --preset gpt2_medium_fsdp --backend cpu-sim8 \
        --model_size test --batch_size 16

Any config field is a flag (--strategy fsdp --tensor 2 ...); --backend
selects {auto, tpu, cpu-sim<N>} per SURVEY.md §5's config-system plan.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorchdistributed_tpu.config import (  # noqa: E402
    make_trainer,
    parse_cli,
    select_backend,
)


def main():
    cfg = parse_cli()
    select_backend(cfg.backend)

    import pytorchdistributed_tpu as ptd

    ptd.init_process_group()
    try:
        trainer, loader = make_trainer(cfg)
        trainer.fit(loader, cfg.max_epochs, resume=cfg.resume)
    finally:
        ptd.destroy_process_group()


if __name__ == "__main__":
    main()
