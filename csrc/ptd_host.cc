// Native host-side data path (SURVEY.md §2b: torch's data loader leans on
// ATen's C++ indexing kernels + pinned-memory copies; the TPU-native analog
// is this host library feeding jax.device_put).
//
// The loader's hot loop is one vectorized gather per batch
// (data/datasets.py: ds[indices]); numpy's fancy indexing is single-threaded
// and, for the ~40MB image batches of BASELINE config[1], measurably behind
// a parallel row copy. This library provides:
//
//   ptd_gather    — multi-threaded row gather (any row size, any dtype via
//                   byte rows)
//   ptd_version   — ABI check for the ctypes loader
//
// Built with `make -C csrc` into pytorchdistributed_tpu/_native/; the
// Python side (pytorchdistributed_tpu/_native/__init__.py) falls back to
// numpy when the library is absent, so the framework never hard-depends on
// the toolchain.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

int32_t ptd_version() { return 1; }

// Gather rows: out[i, :] = src[indices[i], :]; rows are raw bytes
// (row_bytes = product of trailing dims * itemsize). n_threads <= 0 picks
// the hardware concurrency, capped so small batches stay single-threaded.
void ptd_gather(const uint8_t* src, int64_t n_src_rows, int64_t row_bytes,
                const int64_t* indices, int64_t n_idx, uint8_t* out,
                int32_t n_threads) {
  (void)n_src_rows;  // bounds are validated Python-side
  if (n_threads <= 0) {
    int64_t by_work = (n_idx * row_bytes) / (1 << 20);  // ~1MB per thread min
    int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
    n_threads = static_cast<int32_t>(
        std::max<int64_t>(1, std::min(hw, by_work)));
  }
  if (n_threads <= 1) {
    for (int64_t i = 0; i < n_idx; ++i) {
      std::memcpy(out + i * row_bytes, src + indices[i] * row_bytes,
                  row_bytes);
    }
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = std::min(n_idx, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i) {
        std::memcpy(out + i * row_bytes, src + indices[i] * row_bytes,
                    row_bytes);
      }
    });
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"
