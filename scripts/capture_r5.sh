#!/bin/bash
# Round-5 tunnel-return capture: everything owed to the chip, one shot.
#
#   bash scripts/capture_r5.sh            # -> BENCH_r05_local.jsonl
#
# 1. The full r4 runbook (headline re-captures + fused-norms and Llama
#    remat/batch A/Bs) — scripts/capture_r4.sh.
# 2. First-ever rows for the two families that had none: BERT-base MLM
#    (post-LN released architecture) and ViT-L/16 (BASELINE configs 2/4).
# 3. The TPU-gated tests the CPU suite always skips: Mosaic lowering
#    smokes and the ring check_vma=True evidence run (VERDICT r4 #8) —
#    pytest WITHOUT the conftest CPU override so jax.default_backend()
#    is the chip.
set -u
cd "$(dirname "$0")/.."
out=${1:-BENCH_r05_local.jsonl}

bash scripts/capture_r4.sh "$out"

stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }
for b in bert vit; do
  echo "{\"capture\": \"$b\", \"at\": \"$(stamp)\"}" >> "$out"
  if timeout 1800 python bench.py --bench "$b" >> "$out" \
      2> "/tmp/capture_${b}.err"; then
    echo "capture $b: ok"
  else
    echo "{\"capture\": \"$b\", \"failed\": true, \"rc\": $?}" >> "$out"
    echo "capture $b: FAILED (see /tmp/capture_${b}.err)"
  fi
done

echo "{\"capture\": \"tpu_gated_tests\", \"at\": \"$(stamp)\"}" >> "$out"
# pytest exits 0 on an all-skip run, so "rc 0" alone could fabricate
# hardware evidence on a CPU rig — require real passes and zero skips.
if timeout 1800 python -m pytest tests/test_attention.py -q -rs \
    -k "tpu or check_vma" -p no:cacheprovider --noconftest \
    > /tmp/capture_tpu_tests.log 2>&1 \
    && grep -qE "[0-9]+ passed" /tmp/capture_tpu_tests.log \
    && ! grep -qE "[0-9]+ skipped" /tmp/capture_tpu_tests.log; then
  echo '{"capture": "tpu_gated_tests", "passed": true}' >> "$out"
  echo "capture tpu_gated_tests: ok"
else
  echo '{"capture": "tpu_gated_tests", "passed": false}' >> "$out"
  echo "capture tpu_gated_tests: FAILED or skipped (see "\
"/tmp/capture_tpu_tests.log)"
fi

echo "capture complete -> $out"
