#!/usr/bin/env python
"""Summarize a capture JSONL (scripts/capture_r*.sh output) as a table.

Interleaves of ``{"capture": label, "at": ...}`` stamps and bench.py result
lines are folded into one row per capture: label, metric, value,
vs_baseline, and the r3 builder-reported claim it verifies (BASELINE.md
"Recorded absolute numbers"), so the verified-or-corrected call in the
runbook (BASELINE.md "Tunnel-return capture runbook" step 1) is one read.

    python scripts/summarize_capture.py BENCH_r05_local.jsonl
"""
from __future__ import annotations

import json
import sys

# r3 builder-reported claims under verification (BASELINE.md tables).
R3_CLAIMS = {
    "gpt2s_train_tokens_per_s": 119623.4,
    "gpt2m_train_tokens_per_s": 46035.7,
    "llama1b_train_tokens_per_s": 18449.3,
    "resnet50_train_img_per_s": 2256.2,
    "gpt2s_decode_tokens_per_s": 3833.0,
}


def main() -> None:
    path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_r05_local.jsonl"
    label = "?"
    rows = []
    for line in open(path):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "capture" in rec and "at" in rec and "metric" not in rec:
            label = rec["capture"]
        elif rec.get("failed"):
            rows.append((label, "FAILED rc=%s" % rec.get("rc"), "", "", ""))
        elif "metric" in rec:
            claim = R3_CLAIMS.get(rec["metric"])
            delta = ("%+.1f%%" % (100 * (rec["value"] / claim - 1))
                     if claim else "")
            rows.append((label, rec["metric"], "%.1f" % rec["value"],
                         str(rec.get("vs_baseline", "")), delta))
        elif "passed" in rec:
            rows.append((label, "passed=%s" % rec["passed"], "", "", ""))
    w = [max(len(r[i]) for r in rows + [("label", "metric", "value",
                                         "vs_base", "vs_r3claim")])
         for i in range(5)]
    hdr = ("label", "metric", "value", "vs_base", "vs_r3claim")
    for r in [hdr] + rows:
        print("  ".join(str(c).ljust(w[i]) for i, c in enumerate(r)))


if __name__ == "__main__":
    main()
