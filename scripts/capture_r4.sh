#!/bin/bash
# Tunnel-return bench capture (BASELINE.md "Tunnel-return capture runbook").
# One shot: verified re-capture of every headline bench, then the r4 A/B
# knobs (fused norms; Llama remat/batch sweep). Each bench runs in its own
# process; a wedged tunnel fail-fasts via bench.py's probe (rc=2).
#
#   bash scripts/capture_r4.sh            # -> BENCH_r04_local.jsonl
#   bash scripts/capture_r4.sh out.jsonl
set -u
cd "$(dirname "$0")/.."
out=${1:-BENCH_r04_local.jsonl}
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

run() {  # run <label> <env...> -- <bench>
  local label=$1; shift
  local envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done
  shift
  local bench=$1
  echo "{\"capture\": \"$label\", \"at\": \"$(stamp)\"}" >> "$out"
  if env ${envs[@]+"${envs[@]}"} timeout 1800 python bench.py --bench "$bench" \
      >> "$out" 2> "/tmp/capture_${label}.err"; then
    echo "capture $label: ok"
  else
    echo "{\"capture\": \"$label\", \"failed\": true, \"rc\": $?}" >> "$out"
    echo "capture $label: FAILED (see /tmp/capture_${label}.err)"
  fi
}

# 1. verified re-capture of the r3 claims (VERDICT r3 next #1)
for b in gpt2 gpt2medium llama1b resnet50 generate longcontext sweep; do
  run "$b" -- "$b"
done
# 2. fused-norms A/B (flip TransformerConfig.fused_norms default iff it wins)
run llama1b_fused PTD_FUSED_NORMS=1 -- llama1b
run gpt2medium_fused PTD_FUSED_NORMS=1 -- gpt2medium
# 3. Llama remat-policy and batch headroom probes
run llama1b_dots_norms PTD_REMAT_POLICY=dots_norms -- llama1b
run llama1b_bs12 PTD_BENCH_BS=12 PTD_REMAT_POLICY=dots_norms -- llama1b

echo "capture complete -> $out"
