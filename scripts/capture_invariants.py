"""Re-capture the committed compiled-artifact invariants.

    python scripts/capture_invariants.py             # all configs
    python scripts/capture_invariants.py gpt2s_2l    # a subset

Prints a ready-to-paste COMMITTED dict for
tests/test_compiled_invariants.py. The field list is derived from
`utils.hlo.compiled_invariants` itself, so every census it grows —
including the per-config model-flops ("flops") and per-device
collective-bytes ("comm_bytes") pair that feeds telemetry
StepAccounting's MFU/comm math — is stamped into the paste block
automatically. Run on the same frozen image the suite runs on (the
numbers are XLA-version-dependent by design — the image pins the
version). Record any deliberate change in BASELINE.md.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from pytorchdistributed_tpu._jax_compat import (  # noqa: E402
    supports_partial_auto_shard_map,
)
from pytorchdistributed_tpu.utils.hlo import compiled_invariants  # noqa: E402
from tests.test_compiled_invariants import (  # noqa: E402
    BUILDERS,
    PIPELINE_CONFIGS,
    SERVING_NAMES,
    decode_lowered,
    serving_lowered,
)


def main() -> None:
    names = sys.argv[1:] or list(BUILDERS) + ["decode"] + list(SERVING_NAMES)
    print("COMMITTED = {")
    for name in names:
        if (name in PIPELINE_CONFIGS
                and not supports_partial_auto_shard_map()):
            # same gate as the test: this jax cannot lower the pipeline
            # schedules' partial-auto shard_map — keep the old committed
            # entry rather than capturing garbage
            print(f"    # {name}: SKIPPED (partial-auto shard_map "
                  f"unsupported by this jax) — previous entry kept",
                  flush=True)
            continue
        if name == "decode":  # the one-shot decode pin (DECODE_COMMITTED)
            inv = compiled_invariants(decode_lowered().compile())
        elif name in SERVING_NAMES:  # the serving pins (SERVE_COMMITTED)
            inv = compiled_invariants(serving_lowered(name).compile())
        else:
            trainer, batch = BUILDERS[name]()
            inv = compiled_invariants(trainer.lower_step(batch).compile())
        print(f'    "{name}": {{')
        # derive the field list from the dict so a new invariant in
        # utils/hlo.py can never be silently dropped from the paste block;
        # dict-valued censuses (collectives, int8_ops) print last
        scalar = [k for k in inv if not isinstance(inv[k], dict)]
        for key in scalar:
            print(f'        "{key}": {inv[key]},')
        for key in (k for k in inv if isinstance(inv[k], dict)):
            print(f'        "{key}": {inv[key]},')
        print("    },")
    print("}")


if __name__ == "__main__":
    main()
