#!/usr/bin/env python
"""Long chaos-soak runner (ISSUE 19): `bench.py --mode soak` with the
knobs as flags instead of env vars, for multi-minute/overnight legs.

    python scripts/soak.py --duration 600 --qps 4 --out BENCH_soak.json

The exit code is the invariant verdict: 0 only when every continuously
checked invariant held (no orphans, no compliant-tenant sheds, bounded
SLO debt, zero fresh traces on survivors, all streams terminal) — so a
soak can gate CI. The full report (per-fault-class MTTR table
included) lands in --out as one JSON object.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--duration", type=float, default=300.0,
                   help="soak length in seconds (default 300)")
    p.add_argument("--qps", type=float, default=3.0)
    p.add_argument("--peak", type=float, default=3.0,
                   help="diurnal peak multiplier")
    p.add_argument("--replicas", type=int, default=2)
    p.add_argument("--max-replicas", type=int, default=3)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--faults", type=str, default=None,
                   help="fault-grammar spec (see faults/chaos.py); "
                        "default mixes crash/hang/slow + wire faults")
    p.add_argument("--out", type=str, default="BENCH_soak.json")
    args = p.parse_args()

    os.environ["PTD_SOAK_DURATION"] = str(args.duration)
    os.environ["PTD_SOAK_QPS"] = str(args.qps)
    os.environ["PTD_SOAK_PEAK"] = str(args.peak)
    os.environ["PTD_SOAK_REPLICAS"] = str(args.replicas)
    os.environ["PTD_SOAK_MAX_REPLICAS"] = str(args.max_replicas)
    os.environ["PTD_SOAK_SEED"] = str(args.seed)
    if args.faults is not None:
        os.environ["PTD_SOAK_FAULTS"] = args.faults

    from bench import bench_soak

    result = bench_soak()
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    ok = bool(result.get("ok"))
    print(f"soak: {'PASS' if ok else 'FAIL'}  "
          f"attainment={result.get('value')}  "
          f"faults_injected={result.get('faults_injected')}  "
          f"-> {args.out}")
    if not ok:
        for v in result.get("invariants", {}).get("violations", []):
            print(f"  violation: {v}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
