"""Pytest bootstrap: run all tests on an 8-device CPU simulation.

This is the TPU analog of the reference's "gloo CPU smoke" config
(BASELINE.json configs[0]): `--xla_force_host_platform_device_count=8` gives
a single process 8 XLA CPU devices, so every pjit/shard_map code path —
including multi-chip sharding — executes without TPU hardware (SURVEY.md §4).

Must run before the first `import jax` anywhere in the test session.
"""

import os

# Force CPU even when the ambient environment points JAX at a TPU
# (JAX_PLATFORMS=axon): the suite must be hermetic and multi-"chip".
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax at interpreter startup (to
# register the TPU tunnel backend), so JAX_PLATFORMS=axon is already baked
# into jax.config by the time this file runs. Override it post-import —
# legal as long as no backend has been initialized yet.
import jax

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must run on the CPU simulator"

# Persistent XLA compile cache: repeat suite runs skip recompiling
# unchanged programs (the compiled-invariant tripwires lower flagship-width
# steps — ~30-100 s each cold, seconds warm). Keyed on the optimized HLO,
# so a genuine program change always recompiles; /tmp scopes it to the
# machine, not the repo. GATED on current-jax images: on the 0.4.x-era
# jaxlib the cache is WRONG for donated-state programs — a cache-hit
# train step silently drops the batch_stats EMA update (reproduced:
# test_resnet_eval_uses_ema_stats passes cold, fails on the second run
# with a warm cache and nothing else changed) — so correctness wins over
# repeat-run compile time there.
from pytorchdistributed_tpu._jax_compat import has_native_check_vma

if has_native_check_vma():
    jax.config.update("jax_compilation_cache_dir", "/tmp/ptd_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "quick: fast smoke subset (target <3 min on 1 core; "
        "every test not marked slow)")
    config.addinivalue_line(
        "markers", "slow: heavyweight tests excluded from -m quick")


# The `-m quick` smoke allowlist (VERDICT r3 #9): one fast representative
# per subsystem, curated so the subset runs in <3 min on the 1-core driver
# rig (the full suite takes ~24 min there). Matched by substring; kept
# central — one list to re-tune instead of decorators across 17 files.
_QUICK = (
    "test_data.py::TestShardedSampler",       # sampler contract (numpy)
    "test_data.py::TestDatasets",
    "test_data.py::TestDataLoader",
    "test_norms.py",                          # fused-norm equivalence
    "test_utils.py",                          # meters, guards, trace tools
    "test_mesh.py",                           # mesh/axis construction
    "test_auto.py",                           # sharding-ladder planner
    "test_native.py",                         # C++ gather + ctypes fallback
    "test_config.py::test_cli",               # flag parsing (no model init)
    "test_trainer.py::test_reference_training_job_runs",  # e2e 8-dev DDP
    "test_trainer.py::test_accum_steps_validations",
    "test_trainer.py::test_dp_equivalence_8dev_vs_1dev",
    "test_trainer.py::test_evaluate_matches_train_loss",
    "test_pipeline.py::test_gpipe_spmd_matches_sequential",
    "test_pipeline.py::test_one_f_one_b_matches_sequential_grads",
    "test_attention.py::test_flash_matches_dense",  # Pallas kernel math
    "test_quant.py::TestQuantDot",            # int8 quant-dot numerics
    "test_quant.py::test_parity_dp",          # int8_fwd vs bf16 loss curve
    "test_moe.py::test_single_expert_is_dense_mlp",
    "test_moe.py::test_moe_aux_loss_uniform_at_balance",
    # expert-parallel MoE (ISSUE 14): a2a-vs-dense parity (fp32 exact,
    # int8 tol), chunked-overlap bitwise, top-2 per-token reference +
    # the k-major capacity-race edge, and the expert-sharded serving
    # bitwise + zero-recompile tripwire
    "test_moe.py::test_expert_parallel_a2a_matches_single_device",
    "test_moe.py::test_expert_parallel_int8_parity",
    "test_moe.py::test_moe_chunked_overlap_bitwise",
    "test_moe.py::test_top2_matches_per_token_reference",
    "test_moe.py::test_top2_first_choices_win_capacity_race",
    "test_moe.py::test_moe_serving_bitwise_vs_generate_expert_sharded",
    # torch->TPU logit parity — everything except the resnet EVALUATE
    # smoke (~33 s: full eval loop on imported weights; the bitwise
    # logits parity test right above it already pins import
    # correctness, so the smoke rides the full tier — tier-1 sits AT
    # the 870 s budget and this is the lowest-marginal-value block)
    "test_torch_import.py::test_gpt2_import_matches_torch_logits",
    "test_torch_import.py::test_generate_on_imported_weights_matches_torch_greedy",
    "test_torch_import.py::test_llama_import_matches_torch_logits",
    "test_torch_import.py::test_bert_import_matches_torch_logits",
    "test_torch_import.py::test_vit_import_matches_torch_logits",
    "test_torch_import.py::test_imported_weights_survive_checkpoint_roundtrip",
    "test_torch_import.py::test_llama_import_rejects_tied_embeddings",
    "test_torch_import.py::test_resnet50_import_matches_torch_logits",
    "test_torch_import.py::test_resnet50_import_rejects_same_padding_config",
    "test_torch_import.py::test_resnet50_import_rejects_class_mismatch",
    "test_torch_import.py::test_llama_import_rejects_eps_mismatch",
    # telemetry subsystem: tracer/accounting/tripwire units + the
    # single-process end-to-end smoke (train with telemetry on → report);
    # the 2-process report run stays full-suite-only
    "test_telemetry.py::test_span_tracer_chrome_roundtrip",
    "test_telemetry.py::test_span_overhead_under_budget",
    "test_telemetry.py::test_collective_bytes_parses_shapes",
    "test_telemetry.py::test_step_accounting_mlp_hand_computed",
    "test_telemetry.py::test_anomaly_detector_non_finite_and_spike",
    "test_telemetry.py::test_tripwires_fire_on_injected_nan_loss",
    "test_telemetry.py::test_telemetry_smoke_end_to_end",
    # compiled-artifact tripwires: the structural (test-size) tier + the
    # analytic-FLOPs pins; the flagship-width tier stays full-suite-only
    # (CPU compiles are ~30-100 s each cold)
    "test_compiled_invariants.py::test_structural_invariants",
    "test_compiled_invariants.py::test_analytic_flops_formula_pinned",
    # latency-hiding collectives (ISSUE 5): ring-primitive numerics +
    # routing/fallback units, the fp32 and int8 tp parity anchors, the
    # zero-recompile tripwire, the census parser unit and the satellite
    # units (ring_schedule / all_to_all validation / prefetch depth +
    # Trainer knobs) plus the structural comm_stall_frac pins; the bf16
    # parity trio and the census-decomposition test stay full-suite-only
    # (each builds multiple trainers) — quick-tier ring-census coverage
    # is the committed tp4_dp2_ring* pins in test_structural_invariants
    "test_overlap.py::TestRingPrimitives",
    "test_overlap.py::TestRouting",
    "test_overlap.py::test_parity_tp_fp32_exact",
    "test_overlap.py::test_parity_tp_int8",
    "test_overlap.py::test_zero_steadystate_recompiles",
    "test_overlap.py::test_overlap_census_parses_async_pairs",
    "test_overlap.py::test_ring_schedule",
    "test_overlap.py::test_all_to_all_validates_axes",
    "test_overlap.py::test_prefetch_depth_zero_is_synchronous",
    "test_overlap.py::test_trainer_prefetch_knob",
    "test_compiled_invariants.py::test_comm_stall_frac_pinned",
    # serving engine (ISSUE 3): the HLO pins for the tick/prefill pair
    # (+--quant variants), the greedy-parity-vs-generate() anchor, the
    # zero-recompile steady-state guarantee, and the generate() bucketing
    # retrace tripwire; the tp-mesh / stress / telemetry serving tests
    # stay full-suite-only (multi-second compiles)
    "test_compiled_invariants.py::test_serving_invariants",
    "test_serving.py::test_parity_greedy_gpt2",
    "test_serving.py::test_zero_recompiles_steady_state",
    "test_inference.py::test_bucketed_trace_count_regression",
    # faults/chaos subsystem (ISSUE 4): spec/retry/injector units plus
    # the two single-process fault-injection picks (nan tripwire+watchdog,
    # corrupt-latest fallback + verify CLI) and the injected ckpt_corrupt
    # hook; the run.py multi-process chaos scenarios (crash-resume
    # continuity, hang relaunch, preemption, signal forwarding) stay
    # full-suite-only — each spawns real worker processes
    "test_faults.py::TestFaultPlan",
    "test_faults.py::TestRetry",
    "test_faults.py::TestInjector",
    "test_faults.py::test_nan_injection_trips_watchdog",
    "test_faults.py::test_corrupt_latest_checkpoint_falls_back",
    "test_faults.py::test_ckpt_corrupt_injection_and_fallback",
    # in-graph diagnostics (ISSUE 6): the whole file is quick-tier by
    # design — units, the sow/collect chain, trainer integration, the
    # nan-provenance end-to-end drive and the zero-recompile tripwire
    # all run on test-size models (satellite: regressions trip in
    # tier-1); plus the HLO byte-identity pin for diagnostics-off
    "test_diagnostics.py",
    "test_compiled_invariants.py::test_diag_off_hlo_byte_identical",
    # paged KV cache (ISSUE 7): the whole file is quick-tier by design —
    # allocator/radix units, the bitwise paged-attention parity ladder
    # (ragged + block-boundary + trash-garbage), the Pallas pool-native
    # twin, paged-engine parity vs generate() (incl. int8, GQA/RoPE,
    # unrolled layers), prefix-reuse hits, chunked-prefill interleaving,
    # preempt-requeue bitwise continuity, the every-exit-path block-leak
    # invariant, the paged zero-recompile tripwire and the report CLI's
    # serving table — all on test-size models. The paged HLO pins ride
    # the already-quick test_serving_invariants parametrization.
    "test_paging.py",
    # speculative decoding (ISSUE 8): rejection-kernel units + the
    # chi-squared losslessness check, offline generate_speculative
    # bitwise parity (self-draft, truncated draft, int8, GQA/RoPE,
    # stop ids), and the serving engine's spec tick (greedy parity
    # incl. prefix hits + preemption, seeded determinism, zero
    # recompiles, telemetry columns) — all on test-size models. The
    # spec HLO pin rides test_serving_invariants.
    "test_spec.py::TestSpeculativeAccept",
    "test_spec.py::test_slot_filtered_probs_matches_sampler_distribution",
    "test_spec.py::test_offline_greedy_bitwise_gpt2",
    "test_spec.py::test_offline_greedy_bitwise_llama_gqa_rope",
    "test_spec.py::test_offline_greedy_bitwise_int8fwd",
    "test_spec.py::test_offline_greedy_bitwise_truncated_draft",
    "test_spec.py::test_offline_greedy_bitwise_stop_ids",
    "test_spec.py::test_offline_falls_back_when_context_tight",
    "test_spec.py::test_truncated_draft_validations",
    "test_spec.py::test_engine_spec_parity_greedy",
    # (engine_spec_parity_llama_and_int8 — ~26 s of llama+int8 spec
    # breadth — moved to the full tier for the 870 s budget; the greedy
    # /truncated-draft/preemption/int8fwd quick parities keep spec
    # decode pinned bitwise)
    "test_spec.py::test_engine_spec_parity_truncated_draft",
    "test_spec.py::test_engine_spec_prefix_hits_stay_bitwise",
    "test_spec.py::test_engine_spec_preemption_stays_bitwise",
    "test_spec.py::test_engine_spec_zero_recompiles_and_determinism",
    "test_spec.py::test_engine_spec_requires_paged",
    "test_spec.py::test_engine_spec_telemetry_rows",
    # learned drafting (ISSUE 16): the make_draft validation walls, the
    # engine swap refusal walls, the fleet-wide architecture refusal,
    # and the ISSUE-mandated in-process fleet broadcast (same-structure
    # tree swapped mid-stream on 2 replicas: bitwise vs generate(),
    # per-replica identity in summary/telemetry/report). Everything
    # that touches distill_corpus's teacher-generate compile or trains
    # — distill loss smoke, corpus determinism, offline bitwise
    # anchors, adaptive-k retrace tripwire, engine mid-stream swap,
    # checkpoint round-trip, the SUBPROCESS wire-op e2e and the example
    # run — stays full-suite-only: tier-1 sits within ~2% of its 870 s
    # budget, so quick-tier additions here are capped at the ~25 s the
    # fleet-swap anchor plus walls cost.
    "test_spec.py::test_make_draft_validations",
    "test_spec.py::test_engine_draft_hot_swap_refusals",
    "test_distill.py::test_router_inprocess_fleet_swap_midstream_bitwise",
    "test_distill.py::test_router_refuses_mismatched_draft_fleet_wide",
    # replica router chaos suite (ISSUE 9): fault-spec units, the
    # resume-from-tokens engine satellite, crash-mid-stream bitwise
    # parity (dense + paged), the hang watchdog bound, NaN quarantine +
    # rejoin, overload shedding, SIGTERM drain, zero recompiles across
    # a failover, seeded determinism across a failover, telemetry +
    # report table — all in-process on the shared test-size engine
    # geometry (the file rides test_serving/test_paging's compiles).
    # The SUBPROCESS-mode test (spawns jax-importing workers) stays
    # full-suite-only.
    "test_router.py::test_serving_fault_specs_parse_and_fire_once",
    "test_router.py::test_engine_resume_from_tokens_dense_and_paged",
    "test_router.py::test_engine_resume_seeded_sampling_continues_stream",
    "test_router.py::test_engine_health_snapshot_and_finite_probe",
    "test_router.py::test_crash_midstream_greedy_bitwise_dense",
    "test_router.py::test_crash_midstream_greedy_bitwise_paged",
    "test_router.py::test_retry_budget_exhausted_fails_request",
    "test_router.py::test_hang_detected_within_watchdog_bound",
    "test_router.py::test_nan_replica_quarantined_then_rejoins_after_warmup",
    "test_router.py::test_shed_under_overload_keeps_queue_bounded",
    "test_router.py::test_sigterm_drain_finishes_resident_streams_no_orphans",
    "test_router.py::test_zero_steadystate_recompiles_across_failover",
    "test_router.py::test_seeded_sampling_determinism_across_failover",
    "test_router.py::test_router_telemetry_rows_and_report_table",
    # elastic recovery (ISSUE 10): compile-cache core units (key
    # anatomy, round-trip, quarantine-on-defect, publish race), the
    # engine/trainer warm-start zero-compile + bitwise anchors, the
    # CLI (ls/verify/gc/prewarm), the replica-worker checkpoint key,
    # and the in-process router auto-respawn pair — all on the
    # suite-shared test-size geometry. The SUBPROCESS respawn e2e
    # (spawns jax-importing workers) stays full-tier-only.
    "test_compile_cache.py::test_key_components_all_enter_the_digest",
    "test_compile_cache.py::test_roundtrip_miss_then_hit_bitwise",
    "test_compile_cache.py::test_corrupt_payload_quarantined_then_clean",
    "test_compile_cache.py::test_version_mismatch_quarantined",
    "test_compile_cache.py::test_concurrent_publish_race_is_safe",
    "test_compile_cache.py::test_engine_warm_start_zero_compiles_bitwise",
    "test_compile_cache.py::test_engine_paged_warm_start_bitwise",
    "test_compile_cache.py::test_warmup_collapses_to_one_round_with_cache",
    "test_compile_cache.py::test_cache_failure_falls_back_to_jit",
    "test_compile_cache.py::test_cli_ls_verify_gc",
    "test_compile_cache.py::test_cli_prewarm_then_worker_starts_all_hits",
    "test_compile_cache.py::test_worker_checkpoint_key_restores",
    "test_compile_cache.py::test_worker_checkpoint_absent_falls_back",
    "test_compile_cache.py::test_trainer_warm_restart_zero_jit_compiles",
    "test_compile_cache.py::test_trainer_cache_keyed_on_lowered_hlo",
    "test_compile_cache.py::test_router_respawn_rejoins_and_serves",
    "test_compile_cache.py::test_router_respawn_budget_exhausts",
    "test_compile_cache.py::test_respawn_warmup_timeout_declares",
    # prefill/decode disaggregation (ISSUE 12): FleetPrefixIndex +
    # radix local/remote-split units, the wire codec round-trip, the
    # KV export/import bitwise anchors (ragged block-boundary lengths,
    # seeded sampling, prefix-hit offset export), import validation
    # walls, the disagg router parity + both mid-handoff death
    # scenarios, deterministic fleet prefix shipping, the disagg
    # zero-recompile tripwire and the report columns — all in-process
    # on the suite-shared test-size geometry. The SUBPROCESS e2e
    # (spawns jax-importing workers) stays full-suite-only.
    "test_disagg.py::test_fleet_prefix_index_units",
    "test_disagg.py::test_radix_remote_split_and_frontier",
    "test_disagg.py::test_kv_payload_wire_roundtrip",
    "test_disagg.py::test_kv_roundtrip_bitwise_ragged_lengths",
    "test_disagg.py::test_kv_roundtrip_bitwise_seeded_sampling",
    "test_disagg.py::test_kv_export_after_prefix_hit_bitwise",
    "test_disagg.py::test_import_validation_walls",
    "test_disagg.py::test_disagg_router_bitwise_and_handoffs",
    "test_disagg.py::test_disagg_decode_death_after_import_is_lossless",
    "test_disagg.py::test_disagg_prefill_death_with_parked_streams_is_lossless",
    "test_disagg.py::test_fleet_prefix_steering_ships_blocks",
    # KV compression over the stream (ISSUE 13): compressed-block
    # handoff + rejection walls + int8 fleet shipping (the subprocess
    # int8 wire run stays full-suite-only with its bf16 sibling)
    "test_disagg.py::test_kv_roundtrip_int8_compressed_blocks",
    "test_disagg.py::test_import_rejects_dtype_and_version_mismatch",
    "test_disagg.py::test_fleet_prefix_ships_int8_blocks",
    "test_disagg.py::test_zero_recompiles_steady_state_disagg",
    "test_disagg.py::test_report_cli_renders_disagg_columns",
    # SLO-aware autoscaling + multi-tenant admission (ISSUE 15): the
    # traffic-generator determinism/shape units, the WDRR fairness and
    # per-tenant cap/rate properties (hot tenant at 10x cannot shed a
    # compliant one), the fake-clock autoscaler hysteresis/cooldown/
    # bounds/role-aware units against a stub router, the signal-ring
    # stats, the tombstoned add/remove lifecycle, the closed-loop
    # flash-crowd -> warm scale-up -> drain-down demo (zero fresh XLA
    # traces across joins), lossless tenant preemption, and the
    # per-request KV window override walls + bitwise anchor — all
    # in-process. The SUBPROCESS autoscale e2e stays full-tier-only.
    "test_autoscale.py::test_traffic_determinism_and_validation",
    "test_autoscale.py::test_traffic_shapes_tenant_mix_and_prefixes",
    "test_autoscale.py::test_wdrr_weighted_token_fairness_and_priority_tiers",
    "test_autoscale.py::test_admission_per_tenant_caps_and_rate_bucket",
    "test_autoscale.py::test_hot_tenant_at_10x_cannot_shed_compliant_tenant",
    "test_autoscale.py::test_pressure_clamps_kv_windows_by_priority",
    "test_autoscale.py::test_admission_deque_protocol_roundtrip",
    "test_autoscale.py::test_autoscaler_hysteresis_cooldown_and_bounds",
    "test_autoscale.py::test_autoscaler_role_aware_disagg_pools",
    "test_autoscale.py::test_signal_ring_bounded_stats_and_snapshot",
    "test_autoscale.py::test_router_add_remove_replica_tombstone_history",
    "test_autoscale.py::test_flash_crowd_autoscales_warm_and_drains_back",
    "test_autoscale.py::test_router_preempts_over_budget_tenant_losslessly",
    "test_autoscale.py::test_router_rejects_incompatible_kv_override_loudly",
    "test_autoscale.py::test_per_request_window_override_bitwise",
    "test_autoscale.py::test_kv_override_rejection_walls",
    "test_autoscale.py::test_engine_preempt_request_lossless_and_states",
    # distributed request tracing (ISSUE 17): context/wire units, the
    # critical-path exact-tiling sweep + TTFT clip, SLO-debt
    # attribution, chrome-lane tid coercion, the KV-payload origin/
    # trace carry, CLI + report tables, the in-process disagg fleet
    # e2e (handoff + injected failover, 100% connected chains, stage
    # sums tile the terminal latency) and the off-means-off pin (zero
    # recompiles, identical event streams). The SUBPROCESS wire e2e
    # (spawns jax-importing workers) stays full-suite-only.
    "test_tracing.py::test_trace_context_wire_roundtrip",
    "test_tracing.py::test_tracer_rows_and_clock_anchor",
    "test_tracing.py::test_critical_path_exact_tiling_and_ttft_clip",
    "test_tracing.py::test_slo_debt_attribution_and_tracer_ledger",
    "test_tracing.py::test_chrome_trace_lanes_and_tid_coercion",
    "test_tracing.py::test_kv_payload_wire_carries_origin_and_trace",
    "test_tracing.py::test_trace_cli_and_report_section",
    "test_tracing.py::test_fleet_trace_connected_across_handoff_and_failover",
    "test_tracing.py::test_tracing_off_is_off",
    # persistent sessions + tiered KV hierarchy (ISSUE 18): the store
    # tier/LRU/tenant-cap/corruption/CLI units and the FleetSessionIndex
    # + conversation-generator units are pure host work (<0.1 s); the
    # engine/router anchors (park/adopt/demote/store reattach bitwise,
    # kv_window wire carry, export/seed ship, all-tiers router flow +
    # restart, drain cross-replica reattach, conversation replay, int8
    # + seeded store round-trip) ride the suite-shared test-size
    # geometry and the programs test_paging/test_router/test_disagg
    # already compiled — ~25 s incremental, warm. The SUBPROCESS wire
    # e2e (spawns jax-importing workers) stays full-suite-only.
    "test_sessions.py::test_session_id_validation",
    "test_sessions.py::test_fleet_session_index_units",
    "test_sessions.py::test_store_lru_demotion_and_tenant_caps",
    "test_sessions.py::test_store_restart_corruption_torn_and_version",
    "test_sessions.py::test_store_cli_ls_verify_gc",
    "test_sessions.py::test_conversation_generator_determinism",
    "test_sessions.py::test_engine_and_router_session_walls",
    "test_sessions.py::test_engine_sessions_park_adopt_store_bitwise",
    "test_sessions.py::test_parked_sessions_never_deadlock_admission",
    "test_sessions.py::test_engine_sessions_seeded_and_int8_bitwise",
    "test_sessions.py::test_kv_window_override_rides_wire",
    "test_sessions.py::test_replica_ship_export_seed_bitwise",
    "test_sessions.py::test_router_sessions_all_tiers_bitwise",
    "test_sessions.py::test_router_cross_replica_reattach_when_owner_drains",
    "test_sessions.py::test_conversation_replay_drives_reattaches",
    # -- chaos soak (ISSUE 19): the rate-based fault grammar, the wire
    # manglers against a bare os.pipe, session-tier I/O faults, the
    # MTTR join, and the in-process mini-soak twin (seeded diurnal
    # trace + ChaosSchedule + live autoscaler + strict invariants) —
    # a few seconds warm, dominated by the mini-soak. The timeout-
    # ladder test (real sleeps) and the SUBPROCESS soak (real workers,
    # wall clock) stay full-suite-only: tier-1 has no slack for them.
    "test_chaos.py::test_chaos_grammar_rate_specs_parse_and_walls",
    "test_chaos.py::test_chaos_schedule_deterministic_and_targeted",
    "test_chaos.py::test_mangle_recv_wire_kinds",
    "test_chaos.py::test_torn_wire_line_is_protocol_fault_not_crash",
    "test_chaos.py::test_wire_drop_keeps_op_pending",
    "test_chaos.py::test_session_store_io_faults_absorbed_and_fallback",
    "test_chaos.py::test_autoscaler_holds_scaledown_while_degraded",
    "test_chaos.py::test_recovery_table_and_report_section",
    "test_chaos.py::test_mini_soak_invariants_and_fairness_under_chaos",
)


def pytest_collection_modifyitems(items):
    """`-m quick` = the allowlist above; everything else is marked slow.
    `pytest tests/` (no -m) remains the full suite."""
    import pytest

    for item in items:
        if any(s in item.nodeid for s in _QUICK):
            item.add_marker(pytest.mark.quick)
        else:
            item.add_marker(pytest.mark.slow)


_EXIT_STATUS = [None]


def pytest_sessionfinish(session, exitstatus):
    _EXIT_STATUS[0] = int(exitstatus)


def pytest_unconfigure(config):
    # Interpreter teardown after a full tier-1 run costs ~30 s: GC and
    # XLA-client destructors walk hundreds of compiled executables and
    # device arrays accumulated across ~330 tests, after every test has
    # already passed or failed. That dead time counts against the
    # tier-1 wall-clock budget, so skip it: once the terminal summary
    # is out, flush and exit with the session's real status. (No
    # coverage/teardown-dependent plugins are in play; pytest's tmp
    # dirs are reaped lazily by later runs.)
    if _EXIT_STATUS[0] is not None:
        import os as _os
        import sys as _sys

        _sys.stdout.flush()
        _sys.stderr.flush()
        _os._exit(_EXIT_STATUS[0])
