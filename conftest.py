"""Pytest bootstrap: run all tests on an 8-device CPU simulation.

This is the TPU analog of the reference's "gloo CPU smoke" config
(BASELINE.json configs[0]): `--xla_force_host_platform_device_count=8` gives
a single process 8 XLA CPU devices, so every pjit/shard_map code path —
including multi-chip sharding — executes without TPU hardware (SURVEY.md §4).

Must run before the first `import jax` anywhere in the test session.
"""

import os

# Force CPU even when the ambient environment points JAX at a TPU
# (JAX_PLATFORMS=axon): the suite must be hermetic and multi-"chip".
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize imports jax at interpreter startup (to
# register the TPU tunnel backend), so JAX_PLATFORMS=axon is already baked
# into jax.config by the time this file runs. Override it post-import —
# legal as long as no backend has been initialized yet.
import jax

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", "tests must run on the CPU simulator"
