"""Mesh construction tests (SURVEY.md §7 step 1)."""

import jax
import pytest

from pytorchdistributed_tpu.runtime.mesh import (
    Axis,
    MeshConfig,
    batch_sharding,
    create_mesh,
    data_parallel_size,
    local_mesh,
    mesh_shape,
)


def test_default_mesh_is_pure_data_parallel():
    mesh = create_mesh()
    assert mesh.shape[Axis.DATA] == len(jax.devices()) == 8
    assert all(mesh.shape[a] == 1 for a in Axis.ALL if a != Axis.DATA)


def test_kwarg_axis_sizes():
    mesh = create_mesh(tensor=4)
    assert mesh.shape[Axis.TENSOR] == 4
    assert mesh.shape[Axis.DATA] == 2


def test_full_config_resolution():
    cfg = MeshConfig(data=2, fsdp=2, tensor=2)
    sizes = cfg.resolve(8)
    assert sizes == {
        Axis.DATA: 2,
        Axis.FSDP: 2,
        Axis.EXPERT: 1,
        Axis.PIPE: 1,
        Axis.SEQ: 1,
        Axis.TENSOR: 2,
    }
    mesh = create_mesh(cfg)
    assert mesh_shape(mesh)[Axis.FSDP] == 2


def test_bad_product_raises():
    with pytest.raises(ValueError, match="devices"):
        MeshConfig(data=3, tensor=2).resolve(8)


def test_two_unknown_axes_raise():
    with pytest.raises(ValueError, match="-1"):
        MeshConfig(data=-1, fsdp=-1).resolve(8)


def test_indivisible_inference_raises():
    with pytest.raises(ValueError, match="divisible"):
        MeshConfig(tensor=3).resolve(8)


def test_local_mesh_subset():
    mesh = local_mesh(4)
    assert mesh.devices.size == 4


def test_batch_sharding_covers_dp_axes():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    s = batch_sharding(mesh)
    assert s.spec[0] == (Axis.DATA, Axis.FSDP)
    assert data_parallel_size(mesh) == 4


def test_batch_sharding_with_seq():
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    s = batch_sharding(mesh, seq_axis=True)
    assert s.spec[1] == Axis.SEQ


def test_sharded_array_round_trip():
    import jax.numpy as jnp

    mesh = create_mesh()
    x = jnp.arange(16.0).reshape(8, 2)
    xs = jax.device_put(x, batch_sharding(mesh))
    assert len(xs.sharding.device_set) == 8
    assert (jax.device_get(xs) == jax.device_get(x)).all()
