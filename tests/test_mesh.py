"""Mesh construction tests (SURVEY.md §7 step 1)."""

import jax
import numpy as np
import pytest

from pytorchdistributed_tpu.runtime.mesh import (
    Axis,
    MeshConfig,
    batch_sharding,
    create_mesh,
    data_parallel_size,
    local_mesh,
    mesh_shape,
)


def test_default_mesh_is_pure_data_parallel():
    mesh = create_mesh()
    assert mesh.shape[Axis.DATA] == len(jax.devices()) == 8
    assert all(mesh.shape[a] == 1 for a in Axis.ALL if a != Axis.DATA)


def test_kwarg_axis_sizes():
    mesh = create_mesh(tensor=4)
    assert mesh.shape[Axis.TENSOR] == 4
    assert mesh.shape[Axis.DATA] == 2


def test_full_config_resolution():
    cfg = MeshConfig(data=2, fsdp=2, tensor=2)
    sizes = cfg.resolve(8)
    assert sizes == {
        Axis.DATA: 2,
        Axis.FSDP: 2,
        Axis.EXPERT: 1,
        Axis.PIPE: 1,
        Axis.SEQ: 1,
        Axis.TENSOR: 2,
    }
    mesh = create_mesh(cfg)
    assert mesh_shape(mesh)[Axis.FSDP] == 2


def test_bad_product_raises():
    with pytest.raises(ValueError, match="devices"):
        MeshConfig(data=3, tensor=2).resolve(8)


def test_two_unknown_axes_raise():
    with pytest.raises(ValueError, match="-1"):
        MeshConfig(data=-1, fsdp=-1).resolve(8)


def test_indivisible_inference_raises():
    with pytest.raises(ValueError, match="divisible"):
        MeshConfig(tensor=3).resolve(8)


def test_local_mesh_subset():
    mesh = local_mesh(4)
    assert mesh.devices.size == 4


def test_batch_sharding_covers_dp_axes():
    mesh = create_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    s = batch_sharding(mesh)
    assert s.spec[0] == (Axis.DATA, Axis.FSDP)
    assert data_parallel_size(mesh) == 4


def test_batch_sharding_with_seq():
    mesh = create_mesh(MeshConfig(data=2, seq=4))
    s = batch_sharding(mesh, seq_axis=True)
    assert s.spec[1] == Axis.SEQ


def test_sharded_array_round_trip():
    import jax.numpy as jnp

    mesh = create_mesh()
    x = jnp.arange(16.0).reshape(8, 2)
    xs = jax.device_put(x, batch_sharding(mesh))
    assert len(xs.sharding.device_set) == 8
    assert (jax.device_get(xs) == jax.device_get(x)).all()


class _FakeDev:
    """A device with only topology attributes — what the hybrid layout
    fallback keys on."""

    def __init__(self, id, slice_index):
        self.id = id
        self.slice_index = slice_index

    def __repr__(self):
        return f"d{self.id}s{self.slice_index}"


def test_hybrid_layout_data_axis_spans_slices():
    """Multi-slice rule (SURVEY.md §5): only the data axis crosses DCN.
    Every non-data mesh row must stay within one slice; the data axis must
    touch all slices."""
    from pytorchdistributed_tpu.runtime.mesh import hybrid_device_array

    # 2 slices x 8 devices, interleaved ids to exercise the sort
    devs = [_FakeDev(i, slice_index=i % 2) for i in range(16)]
    shape = (4, 2, 1, 1, 1, 2)  # data=4 (2 per slice), fsdp=2, tensor=2
    arr = hybrid_device_array(2, shape, devs)
    assert arr.shape == shape
    slice_of = np.vectorize(lambda d: d.slice_index)(arr)
    # data rows 0-1 on slice 0, rows 2-3 on slice 1
    for i in range(shape[0]):
        row = slice_of[i]
        assert (row == row.flat[0]).all(), (
            f"data row {i} mixes slices: intra-slice axes would ride DCN")
    assert set(slice_of[:, 0, 0, 0, 0, 0]) == {0, 1}, (
        "data axis does not span both slices")


def test_hybrid_layout_validates_divisibility():
    from pytorchdistributed_tpu.runtime.mesh import hybrid_device_array

    devs = [_FakeDev(i, 0) for i in range(8)]
    with pytest.raises(ValueError, match="multiple of"):
        hybrid_device_array(3, (8, 1, 1, 1, 1, 1), devs)


def test_multislice_mesh_trains_on_cpu_sim():
    """The vit_l16_multihost topology (num_slices=2) builds a mesh on the
    CPU sim via the reshape fallback and runs a real sharded step."""
    import numpy as _np
    import optax

    from pytorchdistributed_tpu.models import LinearRegression
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    mesh = create_mesh(MeshConfig(data=-1, num_slices=2))
    assert mesh.shape[Axis.DATA] == 8
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss, mesh=mesh)
    batch = {"x": _np.random.rand(32, 20).astype(_np.float32),
             "y": _np.random.rand(32, 1).astype(_np.float32)}
    assert _np.isfinite(float(tr.train_step(batch)["loss"]))
