"""Process-group lifecycle tests (reference ddp_setup contract)."""

import pytorchdistributed_tpu as ptd
from pytorchdistributed_tpu.runtime import dist


def test_single_process_init_and_teardown():
    ptd.init_process_group()
    assert ptd.is_initialized()
    assert ptd.get_rank() == 0
    assert ptd.get_world_size() == 1
    assert dist.is_main_process()
    dist.barrier()  # no-op single-process
    ptd.destroy_process_group()
    assert not ptd.is_initialized()


def test_init_is_idempotent():
    ptd.init_process_group()
    ptd.init_process_group()
    assert ptd.is_initialized()
    ptd.destroy_process_group()


def test_torchrun_env_contract(monkeypatch):
    # Single-process values resolved from env, torchrun style
    # (reference ddp_gpus_torchrun.py:14-19).
    monkeypatch.setenv("WORLD_SIZE", "1")
    monkeypatch.setenv("RANK", "0")
    monkeypatch.setenv("LOCAL_RANK", "0")
    ptd.init_process_group()
    assert ptd.get_world_size() == 1
    assert dist.get_local_rank() == 0
    ptd.destroy_process_group()
