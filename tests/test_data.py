"""Data-layer tests: the DistributedSampler contract (SURVEY.md §7 step 2)."""

import numpy as np
import pytest

from pytorchdistributed_tpu.data import (
    ArrayDataset,
    DataLoader,
    ShardedSampler,
    SyntheticImageDataset,
    SyntheticRegressionDataset,
    SyntheticTokenDataset,
)


class TestShardedSampler:
    def test_disjoint_and_complete_partition(self):
        # "no overlapping samples between gpus" (reference ddp_gpus.py:75).
        shards = [
            set(ShardedSampler(100, 4, r, drop_last=True))
            for r in range(4)
        ]
        all_idx = set().union(*shards)
        assert sum(len(s) for s in shards) == 100
        assert len(all_idx) == 100

    def test_padding_when_not_divisible(self):
        samplers = [ShardedSampler(10, 4, r) for r in range(4)]
        assert all(len(s) == 3 for s in samplers)
        union = set().union(*(set(s) for s in samplers))
        assert union == set(range(10))  # every sample appears

    def test_valid_mask_marks_wraparound_padding(self):
        # 10 over 4 replicas → 3 each, 2 pads; pads land at the global tail
        # (the last rank), and valid_mask flags exactly those positions.
        samplers = [ShardedSampler(10, 4, r) for r in range(4)]
        masks = [s.valid_mask() for s in samplers]
        assert all(m.all() for m in masks[:3])
        np.testing.assert_array_equal(masks[3], [True, False, False])
        # drop_last never pads
        assert ShardedSampler(10, 4, 1, drop_last=True).valid_mask().all()

    def test_drop_last_truncates(self):
        s = ShardedSampler(10, 4, 0, drop_last=True)
        assert len(s) == 2

    def test_set_epoch_reshuffles(self):
        s = ShardedSampler(64, 2, 0, seed=7)
        e0 = s.local_indices().tolist()
        s.set_epoch(1)
        e1 = s.local_indices().tolist()
        assert e0 != e1

    def test_deterministic_across_replicas(self):
        # Every rank must derive the SAME global permutation (SPMD
        # requirement), differing only in the slice it takes.
        a = ShardedSampler(64, 2, 0, seed=3)._global_indices()
        b = ShardedSampler(64, 2, 1, seed=3)._global_indices()
        np.testing.assert_array_equal(a, b)

    def test_no_shuffle_is_arange(self):
        s = ShardedSampler(8, 2, 1, shuffle=False)
        assert s.local_indices().tolist() == [4, 5, 6, 7]

    def test_bad_rank_raises(self):
        with pytest.raises(ValueError):
            ShardedSampler(8, 2, 2)


class TestDatasets:
    def test_regression_shapes(self):
        # The reference MyTrainDataset contract (ddp_gpus.py:57-66).
        ds = SyntheticRegressionDataset(size=2048, in_dim=20, out_dim=1)
        assert len(ds) == 2048
        batch = ds[np.array([0, 5, 7])]
        assert batch["x"].shape == (3, 20)
        assert batch["y"].shape == (3, 1)

    def test_image_dataset_nhwc(self):
        ds = SyntheticImageDataset(size=16, image_size=32)
        b = ds[np.arange(4)]
        assert b["image"].shape == (4, 32, 32, 3)
        assert b["label"].dtype == np.int32

    def test_token_dataset_shift(self):
        ds = SyntheticTokenDataset(size=4, seq_len=16)
        b = ds[np.arange(4)]
        np.testing.assert_array_equal(b["tokens"][:, 1:], b["targets"][:, :-1])

    def test_mismatched_arrays_raise(self):
        with pytest.raises(ValueError):
            ArrayDataset({"a": np.zeros((3, 2)), "b": np.zeros((4, 2))})


class TestDataLoader:
    def test_batches_and_len(self):
        ds = SyntheticRegressionDataset(size=64, in_dim=4, out_dim=1)
        dl = DataLoader(ds, batch_size=8, num_replicas=2, rank=0)
        batches = list(dl)
        assert len(batches) == len(dl) == 4
        assert batches[0]["x"].shape == (8, 4)

    def test_epoch_changes_batches(self):
        ds = SyntheticRegressionDataset(size=64, in_dim=4, out_dim=1)
        dl = DataLoader(ds, batch_size=8, num_replicas=1, rank=0, seed=1)
        first = next(iter(dl))["x"]
        dl.set_epoch(1)
        second = next(iter(dl))["x"]
        assert not np.array_equal(first, second)

    def test_replicas_see_disjoint_data(self):
        ds = SyntheticRegressionDataset(size=64, in_dim=4, out_dim=1)
        seen = []
        for rank in range(2):
            dl = DataLoader(ds, batch_size=8, num_replicas=2, rank=rank)
            seen.append(
                {tuple(row) for batch in dl for row in batch["x"]}
            )
        assert not (seen[0] & seen[1])


class TestDeviceFeeding:
    def test_shard_batch_lays_out_on_mesh(self):
        import jax
        from pytorchdistributed_tpu.data.loader import shard_batch
        from pytorchdistributed_tpu.runtime.mesh import batch_sharding, create_mesh

        mesh = create_mesh()
        ds = SyntheticRegressionDataset(size=64, in_dim=4, out_dim=1)
        dl = DataLoader(ds, batch_size=16, num_replicas=1, rank=0)
        dev = shard_batch(next(iter(dl)), batch_sharding(mesh))
        assert isinstance(dev["x"], jax.Array)
        assert len(dev["x"].sharding.device_set) == 8

    def test_prefetch_preserves_order_and_count(self):
        from pytorchdistributed_tpu.data.loader import prefetch_to_device
        from pytorchdistributed_tpu.runtime.mesh import batch_sharding, create_mesh

        mesh = create_mesh()
        ds = SyntheticRegressionDataset(size=64, in_dim=4, out_dim=1)
        dl = DataLoader(ds, batch_size=8, num_replicas=1, rank=0)
        host = [b["x"] for b in dl]
        dev = [b["x"] for b in prefetch_to_device(iter(dl), batch_sharding(mesh))]
        assert len(dev) == len(host)
        np.testing.assert_allclose(np.asarray(dev[0]), host[0])


# ---- on-disk real-data path (data/files.py) -------------------------------


def _write_fake_cifar(root, n_per_batch=20):
    """The standard cifar-10-batches-py pickle layout, tiny."""
    import pickle

    d = root / "cifar-10-batches-py"
    d.mkdir()
    rng = np.random.default_rng(0)
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        data = {
            b"data": rng.integers(0, 256, (n_per_batch, 3072), dtype=np.uint8),
            b"labels": rng.integers(0, 10, (n_per_batch,)).tolist(),
        }
        with open(d / name, "wb") as f:
            pickle.dump(data, f)
    return root


def test_cifar10_loads_and_converts(tmp_path):
    from pytorchdistributed_tpu.data import load_cifar10

    _write_fake_cifar(tmp_path)
    ds = load_cifar10(tmp_path)
    assert len(ds) == 100  # 5 batches x 20
    assert (tmp_path / "train_images.npy").exists()  # one-time conversion
    batch = ds[np.arange(8)]
    assert batch["image"].shape == (8, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    assert 0.0 <= batch["image"].min() and batch["image"].max() <= 1.0
    assert batch["label"].dtype == np.int32
    # second load goes straight to the mmap, same content
    again = load_cifar10(tmp_path)[np.arange(8)]
    np.testing.assert_array_equal(batch["image"], again["image"])
    test = load_cifar10(tmp_path, "test")
    assert len(test) == 20


def test_cifar10_absent_returns_none(tmp_path):
    from pytorchdistributed_tpu.data import load_cifar10

    assert load_cifar10(tmp_path) is None


def test_mapped_dataset_gather_matches_mmap(tmp_path):
    from pytorchdistributed_tpu.data import MappedImageDataset

    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (64, 8, 8, 3), dtype=np.uint8)
    np.save(tmp_path / "train_images.npy", imgs)
    np.save(tmp_path / "train_labels.npy",
            rng.integers(0, 5, (64,), dtype=np.int32))
    ds = MappedImageDataset(tmp_path)
    assert ds.num_classes == 5
    idx = np.asarray([5, 0, 63, 5], dtype=np.int64)
    batch = ds[idx]
    np.testing.assert_allclose(batch["image"],
                               imgs[idx].astype(np.float32) / 255.0)


def test_preset_trains_on_real_cifar(tmp_path):
    """The resnet18_cifar_smoke preset picks up real CIFAR-10 when
    --data_dir has it (VERDICT r1 item 5)."""
    from pytorchdistributed_tpu.config import parse_cli, make_trainer
    from pytorchdistributed_tpu.data.files import MappedImageDataset

    _write_fake_cifar(tmp_path)
    cfg = parse_cli(["--preset", "resnet18_cifar_smoke",
                     "--data_dir", str(tmp_path), "--batch_size", "16",
                     "--backend", "auto"])
    trainer, loader = make_trainer(cfg)
    assert isinstance(loader.dataset, MappedImageDataset)
    batch = next(iter(loader))
    assert np.isfinite(float(trainer.train_step(batch)["loss"]))


def test_mapped_token_dataset_windows_stream(tmp_path):
    from pytorchdistributed_tpu.data import MappedTokenDataset, load_tokens

    rng = np.random.default_rng(2)
    stream = rng.integers(0, 500, (1000,), dtype=np.int32)
    np.save(tmp_path / "train_tokens.npy", stream)
    ds = MappedTokenDataset(tmp_path, seq_len=32)
    assert len(ds) == 1000 // 33
    b = ds[np.asarray([0, 3])]
    assert b["tokens"].shape == (2, 32) and b["tokens"].dtype == np.int32
    # causal contract: targets are the next token of the same window
    np.testing.assert_array_equal(b["tokens"][0], stream[:32])
    np.testing.assert_array_equal(b["targets"][0], stream[1:33])
    np.testing.assert_array_equal(b["tokens"][1], stream[3 * 33:3 * 33 + 32])
    assert load_tokens(tmp_path, 32) is not None
    assert load_tokens(tmp_path / "nope", 32) is None


def test_mapped_token_dataset_2d_and_validation(tmp_path):
    from pytorchdistributed_tpu.data import MappedTokenDataset

    rng = np.random.default_rng(3)
    np.save(tmp_path / "train_tokens.npy",
            rng.integers(0, 99, (10, 17), dtype=np.int32))
    ds = MappedTokenDataset(tmp_path, seq_len=16)
    assert len(ds) == 10 and ds.vocab_size <= 99
    with pytest.raises(ValueError, match="seq_len"):
        MappedTokenDataset(tmp_path, seq_len=64)


def test_lm_preset_trains_on_real_tokens(tmp_path):
    """The gpt2 preset picks up a pre-tokenized corpus from --data_dir."""
    from pytorchdistributed_tpu.config import parse_cli, make_trainer
    from pytorchdistributed_tpu.data.files import MappedTokenDataset

    rng = np.random.default_rng(4)
    np.save(tmp_path / "train_tokens.npy",
            rng.integers(0, 128, (40 * 65,), dtype=np.int32))
    cfg = parse_cli(["--model", "gpt2", "--model_size", "test",
                     "--seq_len", "64", "--data_dir", str(tmp_path),
                     "--batch_size", "8", "--backend", "auto"])
    trainer, loader = make_trainer(cfg)
    assert isinstance(loader.dataset, MappedTokenDataset)
    batch = next(iter(loader))
    assert np.isfinite(float(trainer.train_step(batch)["loss"]))


def test_token_dataset_rejects_negative_ids_and_caches_meta(tmp_path):
    import json

    from pytorchdistributed_tpu.data import MappedTokenDataset

    arr = np.arange(-1, 65, dtype=np.int32)  # contains -1
    np.save(tmp_path / "train_tokens.npy", arr)
    with pytest.raises(ValueError, match="negative"):
        MappedTokenDataset(tmp_path, seq_len=32)
    np.save(tmp_path / "train_tokens.npy", np.abs(arr))
    ds = MappedTokenDataset(tmp_path, seq_len=32)
    meta = tmp_path / "train_tokens.meta.json"
    assert meta.exists() and json.loads(meta.read_text())["max"] == 64
    # stale sidecar (different shape) is ignored and rewritten
    np.save(tmp_path / "train_tokens.npy",
            np.arange(200, dtype=np.int32) % 7)
    ds = MappedTokenDataset(tmp_path, seq_len=32)
    assert ds.vocab_size == 7


def test_mlm_dataset_contract():
    from pytorchdistributed_tpu.data import MLMDataset, SyntheticTokenDataset

    base = SyntheticTokenDataset(size=64, seq_len=400, vocab_size=100, seed=1)
    ds = MLMDataset(base, 100, mask_rate=0.15, seed=2)
    assert len(ds) == 64
    idx = np.arange(16)
    b = ds[idx]
    assert set(b) == {"tokens", "targets", "loss_mask"}
    # targets are the ORIGINAL tokens; corruption only where masked
    orig = base[idx]["tokens"]
    np.testing.assert_array_equal(b["targets"], orig)
    np.testing.assert_array_equal(
        np.where(b["loss_mask"] == 0, b["tokens"], 0),
        np.where(b["loss_mask"] == 0, orig, 0))
    rate = b["loss_mask"].mean()
    assert 0.12 < rate < 0.18  # ~15% of 6400 positions
    # of selected positions, most become mask_id (80%), some random/kept
    sel = b["loss_mask"].astype(bool)
    frac_masked = (b["tokens"][sel] == ds.mask_id).mean()
    assert 0.7 < frac_masked < 0.9
    # deterministic in (seed, indices); different indices get new masks
    again = ds[idx]
    np.testing.assert_array_equal(b["tokens"], again["tokens"])
    other = ds[np.arange(16, 32)]
    assert other["loss_mask"].mean() > 0
    # random replacements never emit the reserved mask id
    rand_pos = (b["tokens"] != b["targets"]) & (b["tokens"] != ds.mask_id)
    assert (b["tokens"][rand_pos] != ds.mask_id).all()
    # negative indices alias their positive counterparts (numpy-style)
    last = ds[len(ds) - 1]
    np.testing.assert_array_equal(ds[-1]["tokens"], last["tokens"])
    # per-SAMPLE determinism (ADVICE r2): a sample's mask depends only on
    # (seed, index), not on which other indices share the fetch — val
    # losses comparable across batch sizes / replica counts
    a01 = ds[np.array([0, 1])]
    a05 = ds[np.array([0, 5])]
    solo = ds[0]
    np.testing.assert_array_equal(a01["tokens"][0], a05["tokens"][0])
    np.testing.assert_array_equal(a01["tokens"][0], solo["tokens"])
    np.testing.assert_array_equal(a01["loss_mask"][0], solo["loss_mask"])


def test_bert_preset_uses_mlm_masking():
    from pytorchdistributed_tpu.config import parse_cli, make_trainer
    from pytorchdistributed_tpu.data import MLMDataset

    cfg = parse_cli(["--model", "bert", "--model_size", "test",
                     "--seq_len", "64", "--batch_size", "8",
                     "--backend", "auto", "--dataset_size", "64"])
    trainer, loader = make_trainer(cfg)
    assert isinstance(loader.dataset, MLMDataset)
    batch = next(iter(loader))
    assert "loss_mask" in batch and batch["loss_mask"].any()
    assert np.isfinite(float(trainer.train_step(batch)["loss"]))
