"""Hardware-independent perf tripwires (VERDICT r4 #2).

Two rounds of TPU-tunnel downtime left every perf claim unverifiable on
hardware; these tests make the *compiled artifact* the guarded surface so
a wedged tunnel can never again blind a whole round. For each committed
config the train step is AOT-lowered from abstract state on the 8-device
CPU sim (`Trainer.lower_step` — no params materialized, nothing executed)
and its executable's invariants are asserted against committed numbers:

  * collective-op census of the optimized HLO (exact — a collective
    appearing, vanishing, or changing kind is always a deliberate event);
  * per-device flops from XLA cost analysis (exact — catches fusion /
    partitioning changes that alter the op mix);
  * arg bytes, exact: params + opt state + batch (r3's regression — BN
    buffers riding the optimizer tree — was exactly this number growing);
  * alias bytes, exact: the DONATION tripwire — if the train step's
    state donation silently breaks (jax only warns), this number drops
    and a model sized near HBM would OOM holding two state copies;
  * peak temp bytes (±2%: buffer assignment may legitimately wiggle with
    compiler-internal ordering; a real activation-footprint regression is
    far larger).

Two tiers: STRUCTURAL configs (test-size widths, every parallelism
strategy — dp / fsdp / tp x dp / 1F1B pipeline / ring / Ulysses) compile
in seconds and run in `-m quick`; FLAGSHIP configs (bench.py's real
widths, depth cut to 2 layers so CPU compile stays in budget — per-layer
structure is what regresses, the committed number absorbs the depth) run
in the full suite.

When a change trips one of these ON PURPOSE (a new collective pattern, a
deliberate memory/flops tradeoff): re-capture with
`python scripts/capture_invariants.py [names...]`, update COMMITTED
below, and record the why in BASELINE.md next to the bench baselines —
same ritual as COMMITTED_BASELINES in bench.py.
"""

from __future__ import annotations

import numpy as np
import pytest

from pytorchdistributed_tpu._jax_compat import (
    supports_partial_auto_shard_map,
)
from pytorchdistributed_tpu.utils.hlo import compiled_invariants

# The 1F1B / GPipe schedules need shard_map with axis_names ⊂ mesh axes;
# jax versions whose shard_map had to be backfilled (0.4.x) cannot lower
# that shape at all (spmd partitioner aborts) — the pipeline configs skip
# there instead of failing on an environment limitation.
PIPELINE_CONFIGS = ("pp4_1f1b", "gpt2s_4l_pp4")

# ---------------------------------------------------------------------------
# config builders: name -> (trainer, sample_batch)


def _lm_batch(batch, seq, vocab=128):
    rng = np.random.default_rng(0)
    return {
        "tokens": rng.integers(0, vocab, (batch, seq)).astype(np.int32),
        "targets": rng.integers(0, vocab, (batch, seq)).astype(np.int32),
    }


def _gpt2_trainer(cfg_kw, mesh_kw, strategy, *, opt=None, loss=None):
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    return Trainer(
        GPT2(gpt2_config(**cfg_kw)), opt or optax.adamw(3e-4),
        loss or token_cross_entropy_loss,
        mesh=create_mesh(**mesh_kw), strategy=strategy, log_every=10**9)


def _structural(cfg_kw, mesh_kw, strategy):
    cfg_kw = dict(size="test", **cfg_kw)
    return lambda: (_gpt2_trainer(cfg_kw, mesh_kw, strategy),
                    _lm_batch(32, 64))


def _moe_structural():
    # Switch-MoE over the expert axis: the one strategy signature the
    # other structural configs miss (one-hot dispatch lowering to
    # all_to_all; the aux loss rides the "losses" collection)
    def build():
        from pytorchdistributed_tpu.training import (
            moe_token_cross_entropy_loss,
        )

        return (_gpt2_trainer(dict(size="test", moe_experts=4),
                              dict(data=2, expert=4), "tp",
                              loss=moe_token_cross_entropy_loss),
                _lm_batch(32, 64))

    return build


def _flagship_gpt2(size, mesh_kw=None, strategy="dp", **extra):
    # bench_gpt2's committed config (bench.py) at depth 2: unrolled, no
    # remat, dense attention (the CPU stand-in for the Pallas kernels),
    # adamw, batch 8 x 1024. mesh_kw/strategy/extra let the fsdp variant
    # reuse the same recipe.
    cfg = dict(size=size, num_layers=2, attention="dense", remat=False,
               scan_layers=False)
    cfg.update(extra)
    return lambda: (_gpt2_trainer(cfg, mesh_kw or dict(data=8), strategy),
                    _lm_batch(8, 1024, vocab=50257))


def _flagship_llama():
    # bench_llama1b's committed config at depth 2: adafactor, fused
    # chunked-CE head, dots_all remat, unrolled.
    import optax

    from pytorchdistributed_tpu.models import Llama, llama_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        fused_token_cross_entropy_loss,
    )

    def build():
        cfg = llama_config("1b", num_layers=2, max_seq_len=1024,
                           attention="dense", remat=True,
                           remat_policy="dots_all", scan_layers=False)
        tr = Trainer(Llama(cfg), optax.adafactor(3e-3),
                     fused_token_cross_entropy_loss,
                     mesh=create_mesh(data=8), strategy="dp",
                     log_every=10**9)
        return tr, _lm_batch(8, 1024, vocab=32000)

    return build


def _flagship_resnet():
    # bench_resnet50's committed config (bf16 compute, sync-BN EMA,
    # sgd+momentum) at batch 32 instead of 256: CPU compile budget; the
    # per-image structure (conv fusions, BN stats, the single grad
    # all-reduce) is batch-size independent.
    import optax

    from pytorchdistributed_tpu.models import resnet50
    from pytorchdistributed_tpu.parallel import Policy
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, cross_entropy_loss

    def build():
        tr = Trainer(resnet50(), optax.sgd(0.1, momentum=0.9),
                     cross_entropy_loss, mesh=create_mesh(data=8),
                     strategy="dp", precision=Policy.bf16(),
                     log_every=10**9)
        rng = np.random.default_rng(0)
        batch = {
            "image": rng.standard_normal((32, 224, 224, 3)).astype(
                np.float32),
            "label": rng.integers(0, 1000, (32,)).astype(np.int32),
        }
        return tr, batch

    return build


BUILDERS = {
    # tier 1: structural — every strategy's collective signature (quick)
    "dp8": _structural({}, dict(data=8), "dp"),
    "fsdp8": _structural({}, dict(fsdp=8), "fsdp"),
    "tp4_dp2": _structural({}, dict(data=2, tensor=4), "tp"),
    # the int8 quantized step's structural signature (ops/quant.py):
    # same dp program with the weight matmuls quantized — the int8_ops
    # census pins the convert/dot mix (5 weight-matmul sites x 2 operand
    # converts forward; int8_fwd keeps the backward in bf16, so the int
    # dot count is the forward sites only)
    "dp8_int8fwd": _structural(dict(quant="int8_fwd"), dict(data=8), "dp"),
    "tp4_dp2_int8fwd": _structural(dict(quant="int8_fwd"),
                                   dict(data=2, tensor=4), "tp"),
    # the ring collective-matmul step (ISSUE 5): same tp x dp program
    # with the QKV/out/MLP projections decomposed into ppermute rings —
    # the "overlap" census pins the ring signature (12 rings per block
    # body x (tp-1)=3 hops, on top of the partitioner's own permutes),
    # and its int8 twin pins the quantized-payload composition (the
    # gather ring ships s8 + fp32 scales)
    "tp4_dp2_ring": _structural(dict(overlap="ring"),
                                dict(data=2, tensor=4), "tp"),
    "tp4_dp2_ring_int8fwd": _structural(
        dict(overlap="ring", quant="int8_fwd"),
        dict(data=2, tensor=4), "tp"),
    "pp4_1f1b": _structural(
        dict(num_layers=4, pipeline_stages=4, pipeline_microbatches=8,
             pp_schedule="1f1b"),
        dict(data=2, pipe=4), "dp"),
    "ring_seq2": _structural(dict(attention="ring"),
                             dict(data=4, seq=2), "dp"),
    "ulysses_seq2": _structural(dict(attention="ulysses"),
                                dict(data=4, seq=2), "dp"),
    "moe_ep4": _moe_structural(),
    # tier 2: flagship widths, depth 2 (full suite)
    "gpt2s_2l": _flagship_gpt2("small"),
    "gpt2m_2l": _flagship_gpt2("medium"),
    # BASELINE config[3]'s actual recipe at depth 2: medium + ZeRO-3 +
    # activation checkpointing. The structural fsdp config is test-width,
    # where min_weight_size leaves most params replicated — only real
    # widths exercise the real shard/gather structure (the fused-CE bug
    # was invisible at test width for the same reason).
    "gpt2m_2l_fsdp8": _flagship_gpt2("medium", mesh_kw=dict(fsdp=8),
                                     strategy="fsdp", remat=True),
    # the fused 1F1B schedule at real width (the most intricate step
    # builder): 4 layers over 4 stages, 8 micro-batches, pipe x dp mesh
    "gpt2s_4l_pp4": _flagship_gpt2(
        "small", mesh_kw=dict(data=2, pipe=4), num_layers=4,
        pipeline_stages=4, pipeline_microbatches=8, pp_schedule="1f1b",
        scan_layers=True),  # the 1F1B stage decomposition requires it
    "llama1b_2l": _flagship_llama(),
    # the quantized flagship (ISSUE 1 acceptance): bench_gpt2's committed
    # recipe at depth 2 with --quant int8_fwd — per-device flops and the
    # int8 convert/dot mix are the committed tripwire for the quantized
    # train step at real widths (the int8 LM-head dot against the 50257
    # vocab dominates; a site silently falling back to bf16 changes
    # int8_ops immediately)
    "gpt2s_2l_int8fwd": _flagship_gpt2("small", quant="int8_fwd"),
    "resnet50_b32": _flagship_resnet(),
}

QUICK_NAMES = ("dp8", "fsdp8", "tp4_dp2", "dp8_int8fwd", "tp4_dp2_int8fwd",
               "tp4_dp2_ring", "tp4_dp2_ring_int8fwd",
               "pp4_1f1b", "ring_seq2", "ulysses_seq2", "moe_ep4")

# Captured by scripts/capture_invariants.py on the frozen image's
# jax/XLA; deterministic (verified identical across cold and cache-warm
# compiles). Update ritual in the module docstring.
#
# FULL RE-CAPTURE (ISSUE 1 / the jax 0.4.x image): the committed numbers
# are XLA-version-dependent BY DESIGN, and the current frozen image pins
# an older jax/XLA than the one the r5 numbers were captured on (the r5
# toolchain fused the dp grad all-reduces into ~2; this XLA leaves ~18-30
# unfused, partitions some MoE/TP einsums differently, and runs the flash
# kernels' dense stand-ins through different fusions). Every capturable
# config was re-pinned on this image 2026-08-04 (BASELINE.md entry); the
# two pipeline configs keep their r5 entries because this jax cannot
# lower partial-auto shard_map at all — they SKIP with that reason and
# re-arm unchanged on a capable image. What the numbers say, this
# capture: ring rotates KV 8 times (collective-permute 8) where Ulysses
# all-to-alls heads 8 times — the two CP dialects' signature difference
# survives the XLA version change; resnet50's all-reduces are sync-BN's
# per-layer batch statistics (unfused here); the *_int8fwd configs are
# the quantized-training tripwires — their int8_ops census pins the
# convert/dot mix (2 converts per weight-matmul site; int8_fwd = forward
# sites only carry int dots, the backward stays bf16) and their flops sit
# ~2% over the bf16 twin (the absmax/rescale elementwise adds — the
# arithmetic the MXU's 2x int8 rate pays for).
COMMITTED: dict[str, dict] = {
    "dp8": {
        "flops": 131339560.0,
        "temp_bytes": 9105272,
        "arg_bytes": 1399816,
        "alias_bytes": 1397768,
        "collectives": {"all-reduce": 18, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 282372, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    "fsdp8": {
        "flops": 267927088.0,
        "temp_bytes": 41244096,
        "arg_bytes": 186184,
        "alias_bytes": 184136,
        "collectives": {"all-reduce": 20, "all-gather": 16,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 5, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 8478980, 'all-gather': 3805696, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 327680, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    "tp4_dp2": {
        "flops": 134253744.0,
        "temp_bytes": 10039872,
        "arg_bytes": 439432,
        "alias_bytes": 431240,
        "collectives": {"all-reduce": 35, "all-gather": 11,
                        "reduce-scatter": 0, "collective-permute": 5,
                        "all-to-all": 4, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 1454532, 'all-gather': 1966080, 'reduce-scatter': 0, 'collective-permute': 24576, 'all-to-all': 524288, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    # the quantized structural signatures: same programs as dp8/tp4_dp2
    # with the weight matmuls int8. Under dp the collective census must
    # NOT move (18 == 18: per-channel scales are shard-local there, so
    # quantization changes arithmetic only); under TP it legitimately
    # DOES (39/17 vs 35/11: a contraction over a tensor-sharded dim turns
    # the absmax into a cross-shard max — ops/quant.py's sharding note),
    # which is exactly why the TP pair is pinned separately. int8_ops:
    # 10 = 5 weight-matmul sites x 2 operand converts under dp; TP shards
    # the converts so more s8-producing instructions appear; 5 int dots
    # either way
    "dp8_int8fwd": {
        "flops": 134337312.0,
        "temp_bytes": 9075064,
        "arg_bytes": 1399816,
        "alias_bytes": 1397768,
        "collectives": {"all-reduce": 18, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 10, "int_dots": 5},
        "comm_bytes": {'all-reduce': 282372, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    "tp4_dp2_int8fwd": {
        "flops": 136199872.0,
        "temp_bytes": 9813128,
        "arg_bytes": 439432,
        "alias_bytes": 431240,
        "collectives": {"all-reduce": 39, "all-gather": 17,
                        "reduce-scatter": 0, "collective-permute": 5,
                        "all-to-all": 4, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 25, "int_dots": 5},
        "comm_bytes": {'all-reduce': 1463236, 'all-gather': 1658880, 'reduce-scatter': 0, 'collective-permute': 24576, 'all-to-all': 524288, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    # the ring collective-matmul signatures (ISSUE 5), captured
    # 2026-08-04 on this image. What the numbers say: collective-permute
    # 41 = the partitioner's own 5 (as in tp4_dp2) + 12 rings x (tp-1)=3
    # hops — 4 projection sites (qkv/out/wi/wo) x 3 rings each (fwd,
    # bwd-dx, bwd-dw) in the one scanned block body; the monolithic
    # census's all-gather 11 / all-to-all 4 collapse to 5 / 1 because the
    # gathers now ride the rings. The int8 twin adds 6 permutes (the two
    # column fwd rings ship a second array — the fp32 row scales next to
    # the s8 payload) yet its ppermute BYTES drop 2383872 → 2095104: the
    # int8 payload is a quarter the fp32 chunk, the ISSUE's comm-bytes÷4
    # claim in census form. int8_ops 34/17 > the monolithic tp twin's
    # 25/5: every ring chunk is its own int8 dot (12 int dots across the
    # 4 sites' rings + the LM-head/CE sites), the per-chunk scales are
    # extra s8-producing converts. flops sit ~14% over tp4_dp2 — the
    # fp32 ring accumulators and dynamic-update-slices the cost model
    # bills; the MXU-rate win this buys is a hardware question the bench
    # A/B (PTD_OVERLAP) answers, not the sim.
    "tp4_dp2_ring": {
        "flops": 153246608.0,
        "temp_bytes": 8630152,
        "arg_bytes": 439432,
        "alias_bytes": 431240,
        "collectives": {"all-reduce": 33, "all-gather": 5,
                        "reduce-scatter": 0, "collective-permute": 41,
                        "all-to-all": 1, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 623048, 'all-gather': 163840, 'reduce-scatter': 0, 'collective-permute': 2383872, 'all-to-all': 131072, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
        "overlap": {'async_pairs': {'all-reduce': 0, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0}, 'unpaired_starts': 0, 'overlapped_ops': 0, 'ppermute': 41},
    },
    "tp4_dp2_ring_int8fwd": {
        "flops": 159973456.0,
        "temp_bytes": 8599968,
        "arg_bytes": 439432,
        "alias_bytes": 431240,
        "collectives": {"all-reduce": 33, "all-gather": 7,
                        "reduce-scatter": 0, "collective-permute": 47,
                        "all-to-all": 1, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 34, "int_dots": 17},
        "comm_bytes": {'all-reduce': 623048, 'all-gather': 172544, 'reduce-scatter': 0, 'collective-permute': 2095104, 'all-to-all': 131072, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
        "overlap": {'async_pairs': {'all-reduce': 0, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0}, 'unpaired_starts': 0, 'overlapped_ops': 0, 'ppermute': 47},
    },
    # r5 entry KEPT (not capturable on this image — partial-auto
    # shard_map; the test skips with that reason rather than failing)
    "pp4_1f1b": {
        "flops": 89115424.0,
        "temp_bytes": 2992960,
        "arg_bytes": 806152,
        "alias_bytes": 797960,
        "collectives": {"all-reduce": 3, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 2,
                        "all-to-all": 3, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "ring_seq2": {
        "flops": 117956672.0,
        "temp_bytes": 8259392,
        "arg_bytes": 1399816,
        "alias_bytes": 1397768,
        "collectives": {"all-reduce": 38, "all-gather": 6,
                        "reduce-scatter": 0, "collective-permute": 8,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 720392, 'all-gather': 196608, 'reduce-scatter': 0, 'collective-permute': 409600, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    "ulysses_seq2": {
        "flops": 119991728.0,
        "temp_bytes": 8193824,
        "arg_bytes": 1399816,
        "alias_bytes": 1397768,
        "collectives": {"all-reduce": 38, "all-gather": 6,
                        "reduce-scatter": 0, "collective-permute": 2,
                        "all-to-all": 8, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 720392, 'all-gather': 196608, 'reduce-scatter': 0, 'collective-permute': 16384, 'all-to-all': 524288, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    # ISSUE 14 recapture: the explicit a2a dispatch (ops/overlap.
    # expert_a2a_ffn) replaced the auto-partitioned one-hot einsums,
    # which XLA used to lower as all-gather + all-reduce with a GLOBAL
    # capacity buffer. Grouped per-shard capacity cut per-device flops
    # 852M -> 198M and temp bytes 45.7M -> 8.4M, and the 4 all-to-alls
    # in the scanned layer body are exactly the contract: dispatch +
    # combine forward, and both exchange directions again in backward.
    "moe_ep4": {
        "flops": 197734688.0,
        "temp_bytes": 8367224,
        "arg_bytes": 1399816,
        "alias_bytes": 1391624,
        "collectives": {'all-reduce': 23, 'all-gather': 3,
                        'reduce-scatter': 0, 'collective-permute': 0,
                        'all-to-all': 4, 'ragged-all-to-all': 0,
                        'collective-broadcast': 0},
        "int8_ops": {'s8_values': 0, 'int_dots': 0},
        "comm_bytes": {'all-reduce': 675624, 'all-gather': 34816, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 327680, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
        "a2a": {'count': 4, 'bytes': 327680},
    },
    "gpt2s_2l": {
        "flops": 348754477056.0,
        "temp_bytes": 1170860256,
        "arg_bytes": 642741256,
        "alias_bytes": 642733064,
        "collectives": {"all-reduce": 30, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 368633860, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    "gpt2m_2l": {
        "flops": 503503126528.0,
        "temp_bytes": 1583153440,
        "arg_bytes": 932483080,
        "alias_bytes": 932474888,
        "collectives": {"all-reduce": 30, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 516677636, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    # Census caveat, verified with a minimal probe on the r5 image:
    # XLA:CPU lowers the canonical grad reduce-scatter pattern as
    # all-reduce + slice — fsdp rows legitimately show reduce-scatter 0
    # here; on TPU the same programs get the ReduceScatterCreator pass.
    # The CPU census is still a valid tripwire, just not a bandwidth
    # model of the TPU lowering.
    "gpt2m_2l_fsdp8": {
        "flops": 507647164416.0,
        "temp_bytes": 1075243392,
        "arg_bytes": 116718088,
        "alias_bytes": 116709896,
        "collectives": {"all-reduce": 29, "all-gather": 49,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 2, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 310824964, 'all-gather': 411557888, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 8388608, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    # r5 entry KEPT (not capturable on this image — see pp4_1f1b)
    "gpt2s_4l_pp4": {
        "flops": 309091106816.0,
        "temp_bytes": 1861801464,
        "arg_bytes": 557711368,
        "alias_bytes": 557678600,
        "collectives": {"all-reduce": 27, "all-gather": 2,
                        "reduce-scatter": 0, "collective-permute": 2,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    # (the r5 fused-CE seq-chunking fix's zero-all-gather property —
    # BASELINE.md "First catch" — still holds under this XLA: no
    # all-gathers in the pure-DP llama program)
    "llama1b_2l": {
        "flops": 947184205824.0,
        "temp_bytes": 1510256960,
        "arg_bytes": 1011542024,
        "alias_bytes": 1011533832,
        "collectives": {"all-reduce": 18, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 1010868228, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    # the quantized flagship (ISSUE 1 acceptance): 18 converts / 9 int
    # dots = 2 unrolled layers x 4 weight-matmul sites + the tied LM
    # head; flops +0.4% over gpt2s_2l (absmax/rescale elementwise), temp
    # -4% (int8 operand buffers are a quarter the bf16 footprint)
    "gpt2s_2l_int8fwd": {
        "flops": 350091378688.0,
        "temp_bytes": 1124532448,
        "arg_bytes": 642741256,
        "alias_bytes": 642733064,
        "collectives": {"all-reduce": 30, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 18, "int_dots": 9},
        "comm_bytes": {'all-reduce': 368633860, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
    "resnet50_b32": {
        "flops": 98719342592.0,
        "temp_bytes": 425349288,
        "arg_bytes": 207077204,
        "alias_bytes": 204668740,
        "collectives": {"all-reduce": 375, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {'all-reduce': 102653096, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
    },
}

TEMP_BYTES_RTOL = 0.02


def _assert_invariants(name, inv, want):
    assert inv["collectives"] == want["collectives"], (
        f"{name}: collective census changed — deliberate? "
        f"got {inv['collectives']}, committed {want['collectives']}")
    assert inv["flops"] == want["flops"], (
        f"{name}: per-device flops changed: got {inv['flops']:.6g}, "
        f"committed {want['flops']:.6g}")
    assert inv["arg_bytes"] == want["arg_bytes"], (
        f"{name}: params+opt_state+batch bytes changed: got "
        f"{inv['arg_bytes']}, committed {want['arg_bytes']} (state bloat? "
        f"r3's BN-in-opt-tree bug was this number growing)")
    assert inv["alias_bytes"] == want["alias_bytes"], (
        f"{name}: donated/aliased bytes changed: got "
        f"{inv['alias_bytes']}, committed {want['alias_bytes']} — if it "
        f"DROPPED, state donation broke (jax only warns) and the step now "
        f"holds two copies of params+opt state")
    if "int8_ops" in want:
        assert inv["int8_ops"] == want["int8_ops"], (
            f"{name}: int8 convert/dot mix changed: got {inv['int8_ops']}, "
            f"committed {want['int8_ops']} — a quantized site silently "
            f"falling back to bf16 (or an int8 op leaking into a bf16 "
            f"config) shows up exactly here")
    if "overlap" in want:
        assert inv["overlap"] == want["overlap"], (
            f"{name}: overlap census changed: got {inv['overlap']}, "
            f"committed {want['overlap']} — the ppermute ring count / "
            f"async pairing signature of the latency-hiding path (a ring "
            f"site silently falling back to the monolithic collective, "
            f"or a hop appearing/vanishing, shows up exactly here)")
    if "comm_bytes" in want:
        assert inv["comm_bytes"] == want["comm_bytes"], (
            f"{name}: per-device collective bytes changed: got "
            f"{inv['comm_bytes']}, committed {want['comm_bytes']} — the "
            f"comm-volume half of the census, and a StepAccounting input: "
            f"either communication volume really moved (deliberate?) or "
            f"the telemetry comm-bytes/MFU math would now misreport")
    if "a2a" in want:
        assert inv["a2a"] == want["a2a"], (
            f"{name}: all-to-all census changed: got {inv['a2a']}, "
            f"committed {want['a2a']} — the expert-parallel MoE "
            f"dispatch/combine signature (2 fwd + 2 bwd per MoE layer "
            f"from ops/overlap.expert_a2a_ffn): the explicit exchange "
            f"either stopped lowering to a literal all_to_all or a pass "
            f"duplicated/split one, and the payload bytes pin the int8 "
            f"vs fp32 wire format")
    lo = want["temp_bytes"] * (1 - TEMP_BYTES_RTOL)
    hi = want["temp_bytes"] * (1 + TEMP_BYTES_RTOL)
    assert lo <= inv["temp_bytes"] <= hi, (
        f"{name}: peak temp memory moved >{TEMP_BYTES_RTOL:.0%}: got "
        f"{inv['temp_bytes']}, committed {want['temp_bytes']}")


def _check(name):
    if name in PIPELINE_CONFIGS and not supports_partial_auto_shard_map():
        pytest.skip("pipeline schedules need partial-auto shard_map "
                    "(axis_names ⊂ mesh axes), unsupported by this jax")
    trainer, batch = BUILDERS[name]()
    inv = compiled_invariants(trainer.lower_step(batch).compile())
    _assert_invariants(name, inv, COMMITTED[name])


@pytest.mark.parametrize("name", QUICK_NAMES)
def test_structural_invariants(name):
    _check(name)


@pytest.mark.parametrize(
    "name", [n for n in BUILDERS if n not in QUICK_NAMES])
def test_flagship_invariants(name):
    _check(name)


def test_diag_off_hlo_byte_identical(monkeypatch):
    """ISSUE 6 acceptance, wired into the capture_invariants flow: with
    diagnostics DISABLED, the compiled train step must be byte-identical
    to the pre-knob program — not "equal invariants", the same HLO text
    to the byte (the committed numeric pins above bound drift vs the
    pre-PR captures; this bounds the off-path's contribution to exactly
    zero). Covers all three off spellings (default, explicit "off",
    env "off") and sanity-checks that turning diagnostics ON does change
    the program — a knob whose on-path is invisible would mean the sow
    sites silently stopped collecting."""
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )
    from pytorchdistributed_tpu.utils.hlo import hlo_fingerprint

    batch = _lm_batch(32, 64)

    def fingerprint(diagnostics):
        tr = Trainer(GPT2(gpt2_config("test")), optax.adamw(3e-4),
                     token_cross_entropy_loss, mesh=create_mesh(data=8),
                     strategy="dp", log_every=10**9,
                     diagnostics=diagnostics)
        return hlo_fingerprint(tr.lower_step(batch).compile())

    monkeypatch.delenv("PTD_DIAGNOSTICS", raising=False)
    base = fingerprint(None)
    assert fingerprint("off") == base, (
        "Trainer(diagnostics='off') compiled a DIFFERENT program than the "
        "default — the off path must add nothing")
    monkeypatch.setenv("PTD_DIAGNOSTICS", "scalars")
    assert fingerprint("off") == base, (
        "explicit diagnostics='off' must beat the PTD_DIAGNOSTICS env")
    assert fingerprint(None) != base, (
        "PTD_DIAGNOSTICS=scalars left the program unchanged — the "
        "diagnostics sow/step sites are not collecting")


DECODE_COMMITTED: dict = {
    "flops": 226509897728.0,
    "temp_bytes": 666758832,
    "arg_bytes": 214252552,
    "alias_bytes": 0,  # generate() does not donate — no state to reuse
    "collectives": {"all-reduce": 0, "all-gather": 0, "reduce-scatter": 0,
                    "collective-permute": 0, "all-to-all": 0,
                    "ragged-all-to-all": 0, "collective-broadcast": 0},
    "int8_ops": {"s8_values": 0, "int_dots": 0},
    "comm_bytes": {'all-reduce': 0, 'all-gather': 0, 'reduce-scatter': 0, 'collective-permute': 0, 'all-to-all': 0, 'ragged-all-to-all': 0, 'collective-broadcast': 0},
}


def decode_lowered():
    """Lower the full generate() program — chunked prefill + 128-tick
    lax.scan with KV cache, bench_generate's exact shape at depth 2.
    Shared by test_decode_invariants and scripts/capture_invariants.py
    (the recapture ritual covers "decode" by name)."""
    import dataclasses

    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.inference import generate_jit
    from pytorchdistributed_tpu.models import GPT2, gpt2_config

    cfg = gpt2_config("small", num_layers=2, scan_layers=False)
    model = GPT2(cfg)
    boxed = jax.eval_shape(model.init, jax.random.key(0),
                           jnp.zeros((1, 8), jnp.int32))
    params_sds = nn.meta.unbox(boxed)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    prompt_sds = jax.ShapeDtypeStruct((4, 512), jnp.int32)
    # the prng key is concrete (tiny); params/prompt stay abstract.
    # generate_jit, not generate: the public name is now a thin wrapper
    # (stop-id normalization + eager validation) around this jit.
    return generate_jit.lower(dm, params_sds, prompt_sds,
                              max_new_tokens=128, temperature=0.8,
                              top_k=40, rng=jax.random.key(1))


def test_decode_invariants():
    """The one-shot decode path's tripwire: the committed decode headline
    (gpt2s_decode_tokens_per_s, bench.py bench_generate) had no
    hardware-independent guard. Decode is single-chip (the bench's
    committed point), so the collective census should stay all-zero;
    temp bytes bound the KV-cache + scan working set."""
    inv = compiled_invariants(decode_lowered().compile())
    _assert_invariants("decode", inv, DECODE_COMMITTED)


# ---------------------------------------------------------------------------
# serving-engine pins (ISSUE 3): the two compiled programs steady-state
# serving dispatches — the slot decode tick and the prefill-into-slot —
# at structural (test) width, 4 slots, the committed candidates=64
# sampler. Collectives must stay all-zero (single-chip serving; an
# accidental collective in the tick would tank per-token latency), the
# int8 census pins the --quant composition (5 weight-matmul sites x 2
# operand converts forward; prefill adds nothing — same sites), and temp
# bytes bound the tick's working set next to the [slots, S, kv, hd]
# donated cache.

SERVING_NAMES = ("serve_tick", "serve_prefill", "serve_tick_int8fwd",
                 "serve_prefill_int8fwd", "serve_tick_paged",
                 "serve_prefill_paged", "serve_spec_tick")


def serving_lowered(name: str):
    """Lower one serving program by pin name (shared with
    scripts/capture_invariants.py — the recapture ritual covers the
    SERVING_NAMES). The ``*_paged`` pair (ISSUE 7) lowers the paged
    engine's steady-state programs — the pool-donated block-table tick
    and the chunked prefill — at block 16 over a same-HBM pool;
    ``serve_spec_tick`` (ISSUE 8) lowers the speculative draft-and-
    verify tick (self-drafted, spec_k=4) over the same pool geometry —
    BOTH pools donated, zero collectives."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving.engine import (
        decode_tick,
        paged_decode_tick,
        paged_prefill_chunk,
        paged_slot_models,
        prefill_into_slot,
        slot_models,
        spec_decode_tick,
    )

    slots, candidates, bucket = 4, 64, 128
    quant = "int8_fwd" if name.endswith("_int8fwd") else "none"
    model = GPT2(gpt2_config("test", quant=quant))
    paged = name.endswith("_paged") or name == "serve_spec_tick"
    if paged:
        block, pages = 16, model.cfg.max_seq_len // 16
        tick_model, chunk_model = paged_slot_models(
            model, slots, block, slots * pages + 1)
    else:
        tick_model, prefill_model = slot_models(model, slots)
    boxed = jax.eval_shape(model.init, jax.random.key(0),
                           jnp.zeros((1, 8), jnp.int32))
    weights_sds = nn.meta.unbox(boxed)["params"]
    cache_sds = jax.eval_shape(lambda: tick_model.init(
        jax.random.key(0), jnp.zeros((slots, 1), jnp.int32))["cache"])
    kd = jax.random.key_data(jax.random.key(0))
    i32, f32 = jnp.int32, jnp.float32

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    if name == "serve_prefill_paged":
        return paged_prefill_chunk.lower(
            chunk_model, weights_sds, cache_sds,
            sds((1, bucket), i32),                       # prompt chunk
            sds((), i32),                                # start
            sds((tick_model.cfg.kv_pages,), i32),        # table row
            sds((), i32),                                # true_len
            sds(kd.shape, kd.dtype), sds((), i32),       # key, count
            sds((), f32), sds((), i32), sds((), f32),    # sampling params
            candidates=candidates)
    if name == "serve_spec_tick":
        # self-drafted: draft model/weights/cache mirror the target's —
        # the pin still covers the two-pool donation + the fused
        # rollout/verify/accept program shape
        return spec_decode_tick.lower(
            tick_model, tick_model, weights_sds, weights_sds,
            cache_sds, cache_sds,
            sds((slots, tick_model.cfg.kv_pages), i32),  # block tables
            sds((slots,), i32),                          # lengths
            sds((slots,), i32),                          # tokens
            sds((slots,) + kd.shape, kd.dtype), sds((slots,), i32),
            sds((slots,), f32), sds((slots,), i32), sds((slots,), f32),
            spec_k=4, candidates=candidates)
    if name == "serve_tick_paged":
        return paged_decode_tick.lower(
            tick_model, weights_sds, cache_sds,
            sds((slots, tick_model.cfg.kv_pages), i32),  # block tables
            sds((slots,), i32),                          # lengths
            sds((slots,), i32),
            sds((slots,) + kd.shape, kd.dtype), sds((slots,), i32),
            sds((slots,), f32), sds((slots,), i32), sds((slots,), f32),
            candidates=candidates)
    if name.startswith("serve_prefill"):
        return prefill_into_slot.lower(
            prefill_model, weights_sds, cache_sds,
            sds((1, bucket), i32),                       # bucketed prompt
            sds((), i32), sds((), i32),                  # true_len, slot
            sds(kd.shape, kd.dtype), sds((), i32),       # key, count
            sds((), f32), sds((), i32), sds((), f32),    # sampling params
            candidates=candidates)
    return decode_tick.lower(
        tick_model, weights_sds, cache_sds, sds((slots,), i32),
        sds((slots,) + kd.shape, kd.dtype), sds((slots,), i32),
        sds((slots,), f32), sds((slots,), i32), sds((slots,), f32),
        candidates=candidates)


# Captured 2026-08-04 on this image (scripts/capture_invariants.py with
# the serving names). What the numbers say: alias_bytes 262192 on every
# entry IS the donated slot cache ([4, 128, 4, 16] K+V bf16 x 2 layers +
# the position counters) — if donation breaks, steady-state serving
# holds two cache copies and this drops to 0; the int8 rows carry the
# same 10-convert / 5-int-dot mix as dp8_int8fwd (identical weight-
# matmul sites, the sampler adds none).
SERVE_COMMITTED: dict[str, dict] = {
    "serve_tick": {
        "flops": 1483049.0,
        "temp_bytes": 946624,
        "arg_bytes": 728224,
        "alias_bytes": 262192,
        "collectives": {"all-reduce": 0, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {"all-reduce": 0, "all-gather": 0,
                       "reduce-scatter": 0, "collective-permute": 0,
                       "all-to-all": 0, "ragged-all-to-all": 0,
                       "collective-broadcast": 0},
    },
    # serve_prefill*: recaptured 2026-08-04 after the resume-from-tokens
    # count argument (ISSUE 9) joined the prefill signature — +4
    # arg_bytes (one i32 scalar), +8 flops (the fold_in reads a dynamic
    # count instead of a folded constant); alias/temp/collectives
    # untouched.
    "serve_prefill": {
        "flops": 22284188.0,
        "temp_bytes": 1253864,
        "arg_bytes": 728656,
        "alias_bytes": 262192,
        "collectives": {"all-reduce": 0, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {"all-reduce": 0, "all-gather": 0,
                       "reduce-scatter": 0, "collective-permute": 0,
                       "all-to-all": 0, "ragged-all-to-all": 0,
                       "collective-broadcast": 0},
    },
    "serve_tick_int8fwd": {
        "flops": 2034929.0,
        "temp_bytes": 947456,
        "arg_bytes": 728224,
        "alias_bytes": 262192,
        "collectives": {"all-reduce": 0, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 10, "int_dots": 5},
        "comm_bytes": {"all-reduce": 0, "all-gather": 0,
                       "reduce-scatter": 0, "collective-permute": 0,
                       "all-to-all": 0, "ragged-all-to-all": 0,
                       "collective-broadcast": 0},
    },
    "serve_prefill_int8fwd": {
        "flops": 23949916.0,
        "temp_bytes": 1257192,
        "arg_bytes": 728656,
        "alias_bytes": 262192,
        "collectives": {"all-reduce": 0, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 10, "int_dots": 5},
        "comm_bytes": {"all-reduce": 0, "all-gather": 0,
                       "reduce-scatter": 0, "collective-permute": 0,
                       "all-to-all": 0, "ragged-all-to-all": 0,
                       "collective-broadcast": 0},
    },
    # Paged engine (ISSUE 7), captured 2026-08-04 on this image:
    # alias_bytes 270336 on the tick IS the donated block POOL
    # ([33 blocks x 16 x 4 kv x 16] K+V bf16 x 2 layers = 270336 — the
    # same-HBM pool at 4 slots x 8 pages + trash) — if it drops,
    # donation broke and every tick copies the whole pool; the prefill
    # chunk additionally aliases the counter/table scratch (270640).
    # Zero collectives: paging is single-chip address arithmetic, a
    # gather/scatter that partitions — an accidental collective in the
    # tick is a per-token latency bug.
    "serve_tick_paged": {
        "flops": 1770077.0,
        "temp_bytes": 969232,
        "arg_bytes": 736512,
        "alias_bytes": 270336,
        "collectives": {"all-reduce": 0, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {"all-reduce": 0, "all-gather": 0,
                       "reduce-scatter": 0, "collective-permute": 0,
                       "all-to-all": 0, "ragged-all-to-all": 0,
                       "collective-broadcast": 0},
    },
    # Speculative tick (ISSUE 8), captured 2026-08-04 on this image:
    # alias_bytes 540672 == 2 x 270336 — BOTH donated pools (target +
    # self-draft twin); if it halves, one cache stopped aliasing and
    # every spec tick copies a whole pool. flops ~3.6x the plain paged
    # tick (5 draft rollout steps + the k+1-wide verify vs one s=1
    # apply) for up to spec_k+1=5 tokens emitted. Zero collectives:
    # draft rollout, verify and the rejection kernel are all
    # single-chip; a collective here is a per-token latency bug.
    "serve_spec_tick": {
        "flops": 6330606.0,
        "temp_bytes": 1085760,
        "arg_bytes": 1472768,
        "alias_bytes": 540672,
        "collectives": {"all-reduce": 0, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {"all-reduce": 0, "all-gather": 0,
                       "reduce-scatter": 0, "collective-permute": 0,
                       "all-to-all": 0, "ragged-all-to-all": 0,
                       "collective-broadcast": 0},
    },
    "serve_prefill_paged": {
        "flops": 22510164.0,
        "temp_bytes": 1885952,
        "arg_bytes": 737136,
        "alias_bytes": 270640,
        "collectives": {"all-reduce": 0, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
        "int8_ops": {"s8_values": 0, "int_dots": 0},
        "comm_bytes": {"all-reduce": 0, "all-gather": 0,
                       "reduce-scatter": 0, "collective-permute": 0,
                       "all-to-all": 0, "ragged-all-to-all": 0,
                       "collective-broadcast": 0},
    },
}


@pytest.mark.parametrize("name", SERVING_NAMES)
def test_serving_invariants(name):
    inv = compiled_invariants(serving_lowered(name).compile())
    _assert_invariants(name, inv, SERVE_COMMITTED[name])


# comm_stall_frac (telemetry/accounting.py, ISSUE 5c) computed from the
# compiled artifact alone — comm bytes at the nominal ICI table over
# comm + compute at nominal peaks, cpu-sim-nominal denominators on this
# rig — so the estimator itself is pinnable: a change to the ICI table,
# the byte census, or the stall formula moves these numbers. Captured
# 2026-08-04; the ring config's LOWER stall vs its monolithic twin
# (0.683 < 0.7473) is a census-level win of the decomposition before
# any scheduling effect: each ring hop bills one seq chunk where the
# monolithic all-gather/all-to-all billed whole gathered buffers.
STALL_COMMITTED = {
    "dp8": 0.177,
    "fsdp8": 0.8248,
    "tp4_dp2": 0.7473,
    "tp4_dp2_ring": 0.683,
}


@pytest.mark.parametrize("name", sorted(STALL_COMMITTED))
def test_comm_stall_frac_pinned(name):
    """The structural comm-stall estimator, end to end: lower the step,
    build StepAccounting from the executable, assert the zero-overlap
    stall fraction against the committed value. Also pins the measured
    variant's arithmetic (a fixed fake step time) so both denominators
    of comm_stall_frac are covered."""
    from pytorchdistributed_tpu.telemetry import StepAccounting

    trainer, batch = BUILDERS[name]()
    acct = StepAccounting.from_compiled(
        trainer.lower_step(batch).compile(), batch=batch,
        n_devices=trainer.mesh.devices.size)
    assert acct.ici_source == "cpu-sim-nominal"
    assert acct.comm_stall_frac() == STALL_COMMITTED[name]
    # measured-denominator variant: bytes / ici / sec, capped at 1
    sec = 0.010
    want = round(min(1.0, acct.comm_bytes_per_step
                     / acct.ici_bytes_per_s / sec), 4)
    assert acct.comm_stall_frac(sec) == want
    assert acct.comm_stall_frac(0.0) is None


def test_analytic_flops_formula_pinned():
    """The MFU denominators for every headline bench claim (bench.py
    transformer_train_flops_per_token): pin the analytic per-token flops
    of the FULL flagship configs so the formula (or a config default)
    can't drift silently under a reported MFU number."""
    from bench import transformer_train_flops_per_token
    from pytorchdistributed_tpu.models import gpt2_config, llama_config

    full = {
        "gpt2_small": gpt2_config("small"),
        "gpt2_medium": gpt2_config("medium"),
        "llama_1b": llama_config("1b", max_seq_len=1024),
    }
    got = {k: transformer_train_flops_per_token(c) for k, c in full.items()}
    want = {
        "gpt2_small": 797815296.0,
        "gpt2_medium": 2271713280.0,
        "llama_1b": 6433013760.0,
    }
    assert got == want, got
