"""Hardware-independent perf tripwires (VERDICT r4 #2).

Two rounds of TPU-tunnel downtime left every perf claim unverifiable on
hardware; these tests make the *compiled artifact* the guarded surface so
a wedged tunnel can never again blind a whole round. For each committed
config the train step is AOT-lowered from abstract state on the 8-device
CPU sim (`Trainer.lower_step` — no params materialized, nothing executed)
and its executable's invariants are asserted against committed numbers:

  * collective-op census of the optimized HLO (exact — a collective
    appearing, vanishing, or changing kind is always a deliberate event);
  * per-device flops from XLA cost analysis (exact — catches fusion /
    partitioning changes that alter the op mix);
  * arg bytes, exact: params + opt state + batch (r3's regression — BN
    buffers riding the optimizer tree — was exactly this number growing);
  * alias bytes, exact: the DONATION tripwire — if the train step's
    state donation silently breaks (jax only warns), this number drops
    and a model sized near HBM would OOM holding two state copies;
  * peak temp bytes (±2%: buffer assignment may legitimately wiggle with
    compiler-internal ordering; a real activation-footprint regression is
    far larger).

Two tiers: STRUCTURAL configs (test-size widths, every parallelism
strategy — dp / fsdp / tp x dp / 1F1B pipeline / ring / Ulysses) compile
in seconds and run in `-m quick`; FLAGSHIP configs (bench.py's real
widths, depth cut to 2 layers so CPU compile stays in budget — per-layer
structure is what regresses, the committed number absorbs the depth) run
in the full suite.

When a change trips one of these ON PURPOSE (a new collective pattern, a
deliberate memory/flops tradeoff): re-capture with
`python scripts/capture_invariants.py [names...]`, update COMMITTED
below, and record the why in BASELINE.md next to the bench baselines —
same ritual as COMMITTED_BASELINES in bench.py.
"""

from __future__ import annotations

import numpy as np
import pytest

from pytorchdistributed_tpu.utils.hlo import compiled_invariants

# ---------------------------------------------------------------------------
# config builders: name -> (trainer, sample_batch)


def _lm_batch(batch, seq, vocab=128):
    rng = np.random.default_rng(0)
    return {
        "tokens": rng.integers(0, vocab, (batch, seq)).astype(np.int32),
        "targets": rng.integers(0, vocab, (batch, seq)).astype(np.int32),
    }


def _gpt2_trainer(cfg_kw, mesh_kw, strategy, *, opt=None, loss=None):
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    return Trainer(
        GPT2(gpt2_config(**cfg_kw)), opt or optax.adamw(3e-4),
        loss or token_cross_entropy_loss,
        mesh=create_mesh(**mesh_kw), strategy=strategy, log_every=10**9)


def _structural(cfg_kw, mesh_kw, strategy):
    cfg_kw = dict(size="test", **cfg_kw)
    return lambda: (_gpt2_trainer(cfg_kw, mesh_kw, strategy),
                    _lm_batch(32, 64))


def _moe_structural():
    # Switch-MoE over the expert axis: the one strategy signature the
    # other structural configs miss (one-hot dispatch lowering to
    # all_to_all; the aux loss rides the "losses" collection)
    def build():
        from pytorchdistributed_tpu.training import (
            moe_token_cross_entropy_loss,
        )

        return (_gpt2_trainer(dict(size="test", moe_experts=4),
                              dict(data=2, expert=4), "tp",
                              loss=moe_token_cross_entropy_loss),
                _lm_batch(32, 64))

    return build


def _flagship_gpt2(size, mesh_kw=None, strategy="dp", **extra):
    # bench_gpt2's committed config (bench.py) at depth 2: unrolled, no
    # remat, dense attention (the CPU stand-in for the Pallas kernels),
    # adamw, batch 8 x 1024. mesh_kw/strategy/extra let the fsdp variant
    # reuse the same recipe.
    cfg = dict(size=size, num_layers=2, attention="dense", remat=False,
               scan_layers=False)
    cfg.update(extra)
    return lambda: (_gpt2_trainer(cfg, mesh_kw or dict(data=8), strategy),
                    _lm_batch(8, 1024, vocab=50257))


def _flagship_llama():
    # bench_llama1b's committed config at depth 2: adafactor, fused
    # chunked-CE head, dots_all remat, unrolled.
    import optax

    from pytorchdistributed_tpu.models import Llama, llama_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        fused_token_cross_entropy_loss,
    )

    def build():
        cfg = llama_config("1b", num_layers=2, max_seq_len=1024,
                           attention="dense", remat=True,
                           remat_policy="dots_all", scan_layers=False)
        tr = Trainer(Llama(cfg), optax.adafactor(3e-3),
                     fused_token_cross_entropy_loss,
                     mesh=create_mesh(data=8), strategy="dp",
                     log_every=10**9)
        return tr, _lm_batch(8, 1024, vocab=32000)

    return build


def _flagship_resnet():
    # bench_resnet50's committed config (bf16 compute, sync-BN EMA,
    # sgd+momentum) at batch 32 instead of 256: CPU compile budget; the
    # per-image structure (conv fusions, BN stats, the single grad
    # all-reduce) is batch-size independent.
    import optax

    from pytorchdistributed_tpu.models import resnet50
    from pytorchdistributed_tpu.parallel import Policy
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, cross_entropy_loss

    def build():
        tr = Trainer(resnet50(), optax.sgd(0.1, momentum=0.9),
                     cross_entropy_loss, mesh=create_mesh(data=8),
                     strategy="dp", precision=Policy.bf16(),
                     log_every=10**9)
        rng = np.random.default_rng(0)
        batch = {
            "image": rng.standard_normal((32, 224, 224, 3)).astype(
                np.float32),
            "label": rng.integers(0, 1000, (32,)).astype(np.int32),
        }
        return tr, batch

    return build


BUILDERS = {
    # tier 1: structural — every strategy's collective signature (quick)
    "dp8": _structural({}, dict(data=8), "dp"),
    "fsdp8": _structural({}, dict(fsdp=8), "fsdp"),
    "tp4_dp2": _structural({}, dict(data=2, tensor=4), "tp"),
    "pp4_1f1b": _structural(
        dict(num_layers=4, pipeline_stages=4, pipeline_microbatches=8,
             pp_schedule="1f1b"),
        dict(data=2, pipe=4), "dp"),
    "ring_seq2": _structural(dict(attention="ring"),
                             dict(data=4, seq=2), "dp"),
    "ulysses_seq2": _structural(dict(attention="ulysses"),
                                dict(data=4, seq=2), "dp"),
    "moe_ep4": _moe_structural(),
    # tier 2: flagship widths, depth 2 (full suite)
    "gpt2s_2l": _flagship_gpt2("small"),
    "gpt2m_2l": _flagship_gpt2("medium"),
    # BASELINE config[3]'s actual recipe at depth 2: medium + ZeRO-3 +
    # activation checkpointing. The structural fsdp config is test-width,
    # where min_weight_size leaves most params replicated — only real
    # widths exercise the real shard/gather structure (the fused-CE bug
    # was invisible at test width for the same reason).
    "gpt2m_2l_fsdp8": _flagship_gpt2("medium", mesh_kw=dict(fsdp=8),
                                     strategy="fsdp", remat=True),
    # the fused 1F1B schedule at real width (the most intricate step
    # builder): 4 layers over 4 stages, 8 micro-batches, pipe x dp mesh
    "gpt2s_4l_pp4": _flagship_gpt2(
        "small", mesh_kw=dict(data=2, pipe=4), num_layers=4,
        pipeline_stages=4, pipeline_microbatches=8, pp_schedule="1f1b",
        scan_layers=True),  # the 1F1B stage decomposition requires it
    "llama1b_2l": _flagship_llama(),
    "resnet50_b32": _flagship_resnet(),
}

QUICK_NAMES = ("dp8", "fsdp8", "tp4_dp2", "pp4_1f1b", "ring_seq2",
               "ulysses_seq2", "moe_ep4")

# Captured by scripts/capture_invariants.py on the frozen image's
# jax/XLA; deterministic (verified identical across cold and cache-warm
# compiles). Update ritual in the module docstring. Notes on what the
# numbers say: dp is ONE fused grad all-reduce (+1 for the loss mean);
# fsdp's 9 all-gathers are the ZeRO-3 param regathers; the 1F1B pipe's
# collective-permutes are the stage rotations; ring rotates KV 8 times
# where Ulysses all-to-alls heads 8 times (the two CP dialects' signature
# difference, visible right here); resnet50's ~100 all-reduces are
# sync-BN's per-layer batch statistics (53 BNs), the TPU-native
# SyncBatchNorm.
COMMITTED: dict[str, dict] = {
    "dp8": {
        "flops": 131045120.0,
        "temp_bytes": 8681496,
        "arg_bytes": 1399816,
        "alias_bytes": 1397768,
        "collectives": {"all-reduce": 2, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "fsdp8": {
        "flops": 147790336.0,
        "temp_bytes": 14079520,
        "arg_bytes": 186184,
        "alias_bytes": 184136,
        "collectives": {"all-reduce": 11, "all-gather": 9,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "tp4_dp2": {
        "flops": 142376816.0,
        "temp_bytes": 11496920,
        "arg_bytes": 439432,
        "alias_bytes": 431240,
        "collectives": {"all-reduce": 10, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "pp4_1f1b": {
        "flops": 89115424.0,
        "temp_bytes": 2992960,
        "arg_bytes": 806152,
        "alias_bytes": 797960,
        "collectives": {"all-reduce": 3, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 2,
                        "all-to-all": 3, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "ring_seq2": {
        "flops": 118030232.0,
        "temp_bytes": 7425056,
        "arg_bytes": 1399816,
        "alias_bytes": 1397768,
        "collectives": {"all-reduce": 5, "all-gather": 3,
                        "reduce-scatter": 0, "collective-permute": 8,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "ulysses_seq2": {
        "flops": 120004488.0,
        "temp_bytes": 7310272,
        "arg_bytes": 1399816,
        "alias_bytes": 1397768,
        "collectives": {"all-reduce": 5, "all-gather": 3,
                        "reduce-scatter": 0, "collective-permute": 2,
                        "all-to-all": 8, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    # NOTE the zero all-to-all: at these shapes XLA partitions the
    # one-hot dispatch einsums into all-gather + all-reduce rather than a
    # literal all-to-all — the census records what the compiler actually
    # emits, which is exactly why it's worth pinning.
    "moe_ep4": {
        "flops": 851241152.0,
        "temp_bytes": 47304472,
        "arg_bytes": 1399816,
        "alias_bytes": 1391624,
        "collectives": {"all-reduce": 12, "all-gather": 3,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "gpt2s_2l": {
        "flops": 348919955456.0,
        "temp_bytes": 1316690288,
        "arg_bytes": 642741256,
        "alias_bytes": 642733064,
        "collectives": {"all-reduce": 1, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "gpt2m_2l": {
        "flops": 503792271360.0,
        "temp_bytes": 1587454320,
        "arg_bytes": 932483080,
        "alias_bytes": 932474888,
        "collectives": {"all-reduce": 1, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    # Census caveat, verified with a minimal probe: XLA:CPU lowers the
    # canonical grad reduce-scatter pattern (contraction over the sharded
    # batch, output sharded like the param) as all-reduce + slice — it
    # never emits reduce-scatter ops. So fsdp rows legitimately show
    # reduce-scatter 0 here; on TPU the same programs get the
    # ReduceScatterCreator pass. The CPU census is still a valid tripwire
    # (a change in the all-reduce/all-gather counts is a change in the
    # program), just not a bandwidth model of the TPU lowering. The
    # ~6 GB temp here is likewise CPU-inflated: full all-reduced grads
    # live before slicing.
    "gpt2m_2l_fsdp8": {
        "flops": 513154646016.0,
        "temp_bytes": 5980155704,
        "arg_bytes": 116718088,
        "alias_bytes": 116709896,
        "collectives": {"all-reduce": 19, "all-gather": 15,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    # The 27 all-reduces decompose (audited via op_name metadata) into
    # microbatch-shaped activation psums — the masked pipe-axis combine
    # of the lockstep SPMD schedule — plus one per weight-grad dot; the
    # census counts STATIC instructions, and the 1F1B while-loop executes
    # its 2 collective-permutes once per tick.
    "gpt2s_4l_pp4": {
        "flops": 309091106816.0,
        "temp_bytes": 1861801464,
        "arg_bytes": 557711368,
        "alias_bytes": 557678600,
        "collectives": {"all-reduce": 27, "all-gather": 2,
                        "reduce-scatter": 0, "collective-permute": 2,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    # Re-pinned after the r5 fused-CE seq-chunking fix (BASELINE.md): the
    # original capture showed 5 all-gathers + 1.35e12 per-device flops —
    # the batch-axis-sliced CE chunks were making the partitioner gather
    # neighbors' hidden states and redundantly compute their CE rows.
    # Chunking seq instead: zero all-gathers, 30% fewer per-device flops.
    "llama1b_2l": {
        "flops": 947261276160.0,
        "temp_bytes": 2622011976,
        "arg_bytes": 1011542024,
        "alias_bytes": 1011533832,
        "collectives": {"all-reduce": 2, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
    "resnet50_b32": {
        "flops": 105789972480.0,
        "temp_bytes": 499951336,
        "arg_bytes": 207077204,
        "alias_bytes": 204668740,
        "collectives": {"all-reduce": 100, "all-gather": 0,
                        "reduce-scatter": 0, "collective-permute": 0,
                        "all-to-all": 0, "ragged-all-to-all": 0,
                        "collective-broadcast": 0},
    },
}

TEMP_BYTES_RTOL = 0.02


def _assert_invariants(name, inv, want):
    assert inv["collectives"] == want["collectives"], (
        f"{name}: collective census changed — deliberate? "
        f"got {inv['collectives']}, committed {want['collectives']}")
    assert inv["flops"] == want["flops"], (
        f"{name}: per-device flops changed: got {inv['flops']:.6g}, "
        f"committed {want['flops']:.6g}")
    assert inv["arg_bytes"] == want["arg_bytes"], (
        f"{name}: params+opt_state+batch bytes changed: got "
        f"{inv['arg_bytes']}, committed {want['arg_bytes']} (state bloat? "
        f"r3's BN-in-opt-tree bug was this number growing)")
    assert inv["alias_bytes"] == want["alias_bytes"], (
        f"{name}: donated/aliased bytes changed: got "
        f"{inv['alias_bytes']}, committed {want['alias_bytes']} — if it "
        f"DROPPED, state donation broke (jax only warns) and the step now "
        f"holds two copies of params+opt state")
    lo = want["temp_bytes"] * (1 - TEMP_BYTES_RTOL)
    hi = want["temp_bytes"] * (1 + TEMP_BYTES_RTOL)
    assert lo <= inv["temp_bytes"] <= hi, (
        f"{name}: peak temp memory moved >{TEMP_BYTES_RTOL:.0%}: got "
        f"{inv['temp_bytes']}, committed {want['temp_bytes']}")


def _check(name):
    trainer, batch = BUILDERS[name]()
    inv = compiled_invariants(trainer.lower_step(batch).compile())
    _assert_invariants(name, inv, COMMITTED[name])


@pytest.mark.parametrize("name", QUICK_NAMES)
def test_structural_invariants(name):
    _check(name)


@pytest.mark.parametrize(
    "name", [n for n in BUILDERS if n not in QUICK_NAMES])
def test_flagship_invariants(name):
    _check(name)


DECODE_COMMITTED: dict = {
    "flops": 226508308480.0,
    "temp_bytes": 811830472,
    "arg_bytes": 214252552,
    "alias_bytes": 0,  # generate() does not donate — no state to reuse
    "collectives": {"all-reduce": 0, "all-gather": 0, "reduce-scatter": 0,
                    "collective-permute": 0, "all-to-all": 0,
                    "ragged-all-to-all": 0, "collective-broadcast": 0},
}


def decode_lowered():
    """Lower the full generate() program — chunked prefill + 128-tick
    lax.scan with KV cache, bench_generate's exact shape at depth 2.
    Shared by test_decode_invariants and scripts/capture_invariants.py
    (the recapture ritual covers "decode" by name)."""
    import dataclasses

    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.inference import generate
    from pytorchdistributed_tpu.models import GPT2, gpt2_config

    cfg = gpt2_config("small", num_layers=2, scan_layers=False)
    model = GPT2(cfg)
    boxed = jax.eval_shape(model.init, jax.random.key(0),
                           jnp.zeros((1, 8), jnp.int32))
    params_sds = nn.meta.unbox(boxed)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    prompt_sds = jax.ShapeDtypeStruct((4, 512), jnp.int32)
    # the prng key is concrete (tiny); params/prompt stay abstract
    return generate.lower(dm, params_sds, prompt_sds, max_new_tokens=128,
                          temperature=0.8, top_k=40, rng=jax.random.key(1))


def test_decode_invariants():
    """The serving path's tripwire: the committed decode headline
    (gpt2s_decode_tokens_per_s, bench.py bench_generate) had no
    hardware-independent guard. Decode is single-chip (the bench's
    committed point), so the collective census should stay all-zero;
    temp bytes bound the KV-cache + scan working set."""
    inv = compiled_invariants(decode_lowered().compile())
    _assert_invariants("decode", inv, DECODE_COMMITTED)


def test_analytic_flops_formula_pinned():
    """The MFU denominators for every headline bench claim (bench.py
    transformer_train_flops_per_token): pin the analytic per-token flops
    of the FULL flagship configs so the formula (or a config default)
    can't drift silently under a reported MFU number."""
    from bench import transformer_train_flops_per_token
    from pytorchdistributed_tpu.models import gpt2_config, llama_config

    full = {
        "gpt2_small": gpt2_config("small"),
        "gpt2_medium": gpt2_config("medium"),
        "llama_1b": llama_config("1b", max_seq_len=1024),
    }
    got = {k: transformer_train_flops_per_token(c) for k, c in full.items()}
    want = {
        "gpt2_small": 797815296.0,
        "gpt2_medium": 2271713280.0,
        "llama_1b": 6433013760.0,
    }
    assert got == want, got
