"""Guards + meters tests (SURVEY.md §5 sanitizers/metrics)."""

import numpy as np
import pytest

import jax.numpy as jnp
from pytorchdistributed_tpu.utils import (
    NaNWatchdog,
    StepTimer,
    ThroughputMeter,
    assert_finite,
    assert_replicas_consistent,
    scaling_efficiency,
)


def test_assert_finite_names_offending_leaf():
    tree = {"ok": jnp.ones(3), "bad": {"w": jnp.array([1.0, np.nan])}}
    with pytest.raises(FloatingPointError, match="bad.*w"):
        assert_finite(tree, name="params")
    assert_finite({"ok": jnp.ones(3)})  # no raise
    assert_finite({"ints": jnp.arange(3)})  # non-float leaves skipped


def test_nan_watchdog():
    wd = NaNWatchdog()
    wd.check({"loss": 1.0})
    with pytest.raises(FloatingPointError, match="loss"):
        wd.check({"loss": float("inf")})


def test_replicas_consistent_single_process_noop():
    assert_replicas_consistent({"w": jnp.ones(2)})


def test_step_timer_discards_warmup():
    t = StepTimer(warmup=1)
    for _ in range(3):
        with t:
            pass
    assert len(t._times) == 2
    assert np.isfinite(t.mean)


def test_timeit_reference_methodology():
    mean, std = StepTimer.timeit(lambda: None, repeat=5)
    assert mean >= 0 and std >= 0


def test_throughput_meter():
    m = ThroughputMeter(warmup=0)
    import time
    m.update(100)
    time.sleep(0.01)
    m.update(100)
    assert m.rate > 0


def test_scaling_efficiency():
    assert scaling_efficiency(800.0, 100.0, 8) == pytest.approx(1.0)
    assert scaling_efficiency(720.0, 100.0, 8) == pytest.approx(0.9)
    assert np.isnan(scaling_efficiency(1.0, 0.0, 8))


def test_metric_logger_jsonl_sink(tmp_path):
    """metrics_file: per-step metrics land as machine-readable JSONL
    (SURVEY.md §5 'per-step metrics as first-class data')."""
    import json

    from pytorchdistributed_tpu.training.logging import MetricLogger

    path = tmp_path / "metrics.jsonl"
    lg = MetricLogger(name="jsonl-test", jsonl_path=str(path))
    lg.log_step(0, 10, {"loss": 1.5, "accuracy": 0.25})
    lg.log_step(0, 20, {"loss": 1.25})
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(rows) == 2
    assert rows[0]["step"] == 10 and rows[0]["loss"] == 1.5
    assert rows[0]["accuracy"] == 0.25 and "time" in rows[0]
    assert rows[1]["epoch"] == 0 and rows[1]["step"] == 20
