"""Guards + meters tests (SURVEY.md §5 sanitizers/metrics)."""

import numpy as np
import pytest

import jax.numpy as jnp
from pytorchdistributed_tpu.utils import (
    NaNWatchdog,
    StepTimer,
    ThroughputMeter,
    assert_finite,
    assert_replicas_consistent,
    scaling_efficiency,
)


def test_assert_finite_names_offending_leaf():
    tree = {"ok": jnp.ones(3), "bad": {"w": jnp.array([1.0, np.nan])}}
    with pytest.raises(FloatingPointError, match="bad.*w"):
        assert_finite(tree, name="params")
    assert_finite({"ok": jnp.ones(3)})  # no raise
    assert_finite({"ints": jnp.arange(3)})  # non-float leaves skipped


def test_nan_watchdog():
    wd = NaNWatchdog()
    wd.check({"loss": 1.0})
    with pytest.raises(FloatingPointError, match="loss"):
        wd.check({"loss": float("inf")})


def test_replicas_consistent_single_process_noop():
    assert_replicas_consistent({"w": jnp.ones(2)})


def test_step_timer_discards_warmup():
    t = StepTimer(warmup=1)
    for _ in range(3):
        with t:
            pass
    assert len(t._times) == 2
    assert np.isfinite(t.mean)


def test_timeit_reference_methodology():
    mean, std = StepTimer.timeit(lambda: None, repeat=5)
    assert mean >= 0 and std >= 0


def test_throughput_meter():
    m = ThroughputMeter(warmup=0)
    import time
    m.update(100)
    time.sleep(0.01)
    m.update(100)
    assert m.rate > 0


def test_throughput_meter_edge_cases():
    """The deque-window meter's corners: empty window, warmup-only,
    single stamp, and a zero-dt window all answer NaN instead of raising
    or dividing by zero; the window really is bounded (O(1) eviction
    replaced the O(n) list.pop(0))."""
    import collections

    m = ThroughputMeter(window=4, warmup=1)
    assert np.isnan(m.rate)            # empty
    m.update(10)                       # swallowed by warmup
    assert np.isnan(m.rate)
    m.update(10)                       # one stamp: no interval yet
    assert np.isnan(m.rate)
    for _ in range(20):
        m.update(10)
    assert isinstance(m._stamps, collections.deque)
    assert len(m._stamps) == 4         # maxlen eviction, not unbounded
    # zero wall-clock window (identical timestamps) -> NaN, not ZeroDiv
    z = ThroughputMeter(window=4, warmup=0)
    z._stamps.append((1.0, 10))
    z._stamps.append((1.0, 10))
    assert np.isnan(z.rate)


def test_scaling_efficiency():
    assert scaling_efficiency(800.0, 100.0, 8) == pytest.approx(1.0)
    assert scaling_efficiency(720.0, 100.0, 8) == pytest.approx(0.9)
    assert np.isnan(scaling_efficiency(1.0, 0.0, 8))
    assert np.isnan(scaling_efficiency(800.0, 100.0, 0))   # no chips
    assert np.isnan(scaling_efficiency(800.0, 100.0, -1))
    assert np.isnan(scaling_efficiency(800.0, -5.0, 8))    # bad baseline


def test_metric_logger_jsonl_sink(tmp_path):
    """metrics_file: per-step metrics land as machine-readable JSONL
    (SURVEY.md §5 'per-step metrics as first-class data')."""
    import json

    from pytorchdistributed_tpu.training.logging import MetricLogger

    path = tmp_path / "metrics.jsonl"
    lg = MetricLogger(name="jsonl-test", jsonl_path=str(path))
    lg.log_step(0, 10, {"loss": 1.5, "accuracy": 0.25})
    lg.log_step(0, 20, {"loss": 1.25})
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(rows) == 2
    assert rows[0]["step"] == 10 and rows[0]["loss"] == 1.5
    assert rows[0]["accuracy"] == 0.25 and "time" in rows[0]
    assert rows[1]["epoch"] == 0 and rows[1]["step"] == 20


def test_metric_logger_close_reopen_and_context(tmp_path):
    """close() is idempotent and composes with multi-epoch use: the sink
    lazily reopens in append mode on the next log_step, so per-epoch
    teardown close never truncates earlier rows; the context-manager form
    closes on exceptions too."""
    import json

    from pytorchdistributed_tpu.training.logging import MetricLogger

    path = tmp_path / "metrics.jsonl"
    lg = MetricLogger(name="jsonl-close-test", jsonl_path=str(path))
    lg.log_step(0, 1, {"loss": 2.0})
    lg.close()
    lg.close()  # idempotent
    lg.log_step(1, 1, {"loss": 1.0})  # reopens, appends
    lg.close()
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["epoch"] for r in rows] == [0, 1]
    with pytest.raises(RuntimeError):
        with MetricLogger(name="jsonl-ctx-test",
                          jsonl_path=str(path)) as ctx_lg:
            ctx_lg.log_step(2, 1, {"loss": 0.5})
            raise RuntimeError("boom")
    assert ctx_lg._jsonl._f is None  # closed despite the exception
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert rows[-1]["epoch"] == 2  # the pre-exception row is durable


def test_trace_summary(tmp_path):
    """utils.trace summarizes a jax.profiler capture's device time by op
    family (the Trainer's profile_dir consumer)."""
    import gzip
    import json

    from pytorchdistributed_tpu.utils.trace import summarize

    run = tmp_path / "plugins" / "profile" / "2026_01_01"
    run.mkdir(parents=True)
    events = [
        {"ph": "M", "name": "process_name", "pid": 3,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 3, "tid": 1,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "process_name", "pid": 9,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "thread_name", "pid": 9, "tid": 1,
         "args": {"name": "XLA Ops"}},
        # device ops: two fusions of one family, one custom-call
        {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.1", "dur": 3000},
        {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.2", "dur": 1000},
        {"ph": "X", "pid": 3, "tid": 1, "name": "attn.7", "dur": 2000},
        # host op must be ignored
        {"ph": "X", "pid": 9, "tid": 1, "name": "hostwork.1", "dur": 9999},
    ]
    with gzip.open(run / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)

    out = summarize(str(tmp_path), steps=2)
    assert "fusion" in out and "attn" in out
    assert "hostwork" not in out
    # 4000us fusion over 2 steps -> 2.00 ms/step
    assert "2.00" in out and "3.0 ms/step" in out


def test_trace_auto_detects_step_count(tmp_path):
    """--steps defaults to auto-detection from the capture's step
    annotations (the Trainer wraps each profiled dispatch in
    StepTraceAnnotation("train")): a 2-step capture divides by 2 without
    any flag, and a capture with no markers falls back to 1 with a
    warning instead of silently mislabeling."""
    import gzip
    import json

    from pytorchdistributed_tpu.utils.trace import (
        detect_step_count,
        summarize,
    )

    run = tmp_path / "plugins" / "profile" / "2026_01_02"
    run.mkdir(parents=True)
    events = [
        {"ph": "M", "name": "process_name", "pid": 3,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "thread_name", "pid": 3, "tid": 1,
         "args": {"name": "XLA Ops"}},
        {"ph": "M", "name": "thread_name", "pid": 9, "tid": 5,
         "args": {"name": "python"}},
        # two host-side step annotations = a 2-step capture
        {"ph": "X", "pid": 9, "tid": 5, "name": "train", "dur": 5000},
        {"ph": "X", "pid": 9, "tid": 5, "name": "train", "dur": 5000},
        {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.1", "dur": 4000},
    ]
    assert detect_step_count(events) == 2
    with gzip.open(run / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    out = summarize(str(tmp_path))  # no steps arg
    assert "x2 steps auto-detected" in out
    assert "2.00" in out  # 4000us fusion / 2 steps
    # --steps override still wins
    out = summarize(str(tmp_path), steps=4)
    assert "x4 steps" in out and "1.00" in out
    # no annotations anywhere -> fallback 1 + warning
    assert detect_step_count(
        [{"ph": "X", "pid": 3, "tid": 1, "name": "fusion.1",
          "dur": 10}]) is None
    for e in events:
        if e.get("name") == "train":
            e["name"] = "other"
    with gzip.open(run / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": events}, f)
    out = summarize(str(tmp_path))
    assert "NO step annotations" in out


def test_bf16_policy_preserves_batch_stats():
    """Mixed precision must not quantize normalization running statistics:
    the EMA update reads its fp32 master every step, so casting
    batch_stats to bf16 would accumulate per-step quantization noise in
    the eval stats (torch amp's BN rule)."""
    from pytorchdistributed_tpu.parallel import Policy

    params = {
        "params": {"w": jnp.ones((4, 4), jnp.float32)},
        "batch_stats": {"bn": {"mean": jnp.ones((4,), jnp.float32)}},
    }
    cast = Policy.bf16().cast_params_for_compute(params)
    assert cast["params"]["w"].dtype == jnp.bfloat16
    assert cast["batch_stats"]["bn"]["mean"].dtype == jnp.float32
