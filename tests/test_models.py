"""Model-zoo + strategy-matrix tests (runs on the 8-device CPU sim —
conftest.py; SURVEY.md §4 "multi-node without a cluster" gap, closed)."""

import numpy as np
import optax
import pytest

import jax
from pytorchdistributed_tpu.models import (
    BertMLM,
    GPT2,
    ViT,
    bert_config,
    gpt2_config,
    resnet18,
    vit_config,
)
from pytorchdistributed_tpu.runtime.mesh import Axis, create_mesh
from pytorchdistributed_tpu.training import (
    Trainer,
    cross_entropy_loss,
    token_cross_entropy_loss,
)


def _token_batch(rng, batch=8, seq=32, vocab=128):
    return {
        "tokens": rng.integers(0, vocab, (batch, seq)).astype(np.int32),
        "targets": rng.integers(0, vocab, (batch, seq)).astype(np.int32),
    }


def _image_batch(rng, batch=8, size=32, classes=10):
    return {
        "image": rng.standard_normal((batch, size, size, 3)).astype(np.float32),
        "label": rng.integers(0, classes, (batch,)).astype(np.int32),
    }


@pytest.mark.parametrize("strategy,axes", [
    ("dp", dict()),
    ("fsdp", dict(data=2, fsdp=4)),
    ("tp", dict(data=2, tensor=4)),
    ("tp_fsdp", dict(data=2, fsdp=2, tensor=2)),
])
def test_gpt2_strategies_train(strategy, axes):
    rng = np.random.default_rng(0)
    model = GPT2(gpt2_config("test"))
    mesh = create_mesh(**axes)
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=mesh, strategy=strategy)
    batch = _token_batch(rng)
    l0 = float(tr.train_step(batch)["loss"])
    for _ in range(3):
        m = tr.train_step(batch)
    assert float(m["loss"]) < l0  # it learns the repeated batch


def test_tp_actually_shards_params():
    rng = np.random.default_rng(0)
    model = GPT2(gpt2_config("test"))
    mesh = create_mesh(data=2, tensor=4)
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=mesh, strategy="tp")
    tr.init(_token_batch(rng))
    wi = tr.state.params["params"]["h"]["block"]["mlp"]["wi"]["kernel"]
    flat_axes = []
    for entry in tuple(wi.sharding.spec):
        flat_axes.extend(entry if isinstance(entry, tuple) else (entry,))
    assert Axis.TENSOR in flat_axes
    # each shard holds 1/4 of the mlp dim
    shard = wi.addressable_shards[0].data
    assert shard.shape[-1] * 4 == wi.shape[-1]


def _fsdp_equivalence_tol():
    """fp32 bar on current jax; widened on 0.4.x-era images whose SPMD
    partitioner falls back to 'involuntary full rematerialization' on the
    scanned fsdp carries — a different reduction order, measured ~1.4e-3
    on the 3-step curves (environment numerics, not a resharding bug; the
    strict bar re-arms automatically on a capable image)."""
    from pytorchdistributed_tpu._jax_compat import has_native_check_vma

    return 2e-4 if has_native_check_vma() else 2e-3


def test_fsdp_matches_dp_loss():
    """ZeRO resharding must not change the math (SURVEY.md §4
    loss-curve-equivalence requirement)."""
    rng = np.random.default_rng(1)
    batch = _token_batch(rng)
    losses = {}
    for strategy, axes in [("dp", dict()), ("fsdp", dict(data=2, fsdp=4))]:
        model = GPT2(gpt2_config("test", dtype=np.float32))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(**axes), strategy=strategy)
        ls = [float(tr.train_step(batch)["loss"]) for _ in range(3)]
        losses[strategy] = ls
    tol = _fsdp_equivalence_tol()
    np.testing.assert_allclose(losses["dp"], losses["fsdp"],
                               rtol=tol, atol=tol)


def test_bert_mlm_masked_loss():
    rng = np.random.default_rng(0)
    model = BertMLM(bert_config("test"))
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(), strategy="dp")
    batch = _token_batch(rng)
    batch["loss_mask"] = (rng.random((8, 32)) < 0.15)
    m = tr.train_step(batch)
    assert np.isfinite(float(m["loss"]))


def test_vit_trains():
    rng = np.random.default_rng(0)
    model = ViT(vit_config("test", image_size=32, patch_size=8,
                           num_classes=10))
    tr = Trainer(model, optax.adamw(1e-3), cross_entropy_loss,
                 mesh=create_mesh(data=2, fsdp=2, tensor=2),
                 strategy="tp_fsdp")
    batch = _image_batch(rng)
    l0 = float(tr.train_step(batch)["loss"])
    for _ in range(3):
        m = tr.train_step(batch)
    assert float(m["loss"]) < l0


def test_resnet18_cifar_smoke():
    """BASELINE config[0]: ResNet-18/CIFAR-10-shaped DP smoke."""
    rng = np.random.default_rng(0)
    model = resnet18(num_classes=10, cifar_stem=True)
    tr = Trainer(model, optax.sgd(0.05, momentum=0.9), cross_entropy_loss,
                 mesh=create_mesh(), strategy="dp")
    batch = _image_batch(rng)
    l0 = float(tr.train_step(batch)["loss"])
    for _ in range(5):
        m = tr.train_step(batch)
    assert float(m["loss"]) < l0


def test_resnet_eval_uses_ema_stats():
    """Inference-time normalization (VERDICT r2 missing #3): eval must use
    the EMA statistics, so (a) eval output is invariant to how the eval
    set is batched — including batch 1 — and (b) the EMA actually moves
    during training (batch_stats ride TrainState)."""
    rng = np.random.default_rng(5)
    model = resnet18(num_classes=10, cifar_stem=True)
    tr = Trainer(model, optax.sgd(0.05, momentum=0.9), cross_entropy_loss,
                 mesh=create_mesh(), strategy="dp")
    batch = _image_batch(rng)
    stats0 = None
    for _ in range(3):
        tr.train_step(batch)
        if stats0 is None:
            stats0 = jax.tree.map(np.asarray,
                                  tr.state.params["batch_stats"])
    stats1 = tr.state.params["batch_stats"]
    moved = any(
        not np.allclose(a, b) for a, b in
        zip(jax.tree.leaves(stats0), jax.tree.leaves(stats1)))
    assert moved, "EMA batch_stats never updated during training"

    # eval: full batch at once == same images scored one at a time
    images = batch["image"][:4]
    full = model.apply(tr.state.params, images)
    singles = np.concatenate(
        [np.asarray(model.apply(tr.state.params, images[i:i + 1]))
         for i in range(4)])
    np.testing.assert_allclose(full, singles, atol=1e-5)

    # eval_step path (rng=None) must not depend on eval batch composition
    m_all = tr.eval_step({"image": batch["image"],
                          "label": batch["label"]})
    m_half = tr.eval_step({"image": batch["image"][:8],
                           "label": batch["label"][:8]})
    assert np.isfinite(float(m_all["loss"]))
    assert np.isfinite(float(m_half["loss"]))


def test_fused_ce_loss_matches_unfused():
    """The chunked fused-CE head (ops/fused_ce.py via loss_per_position)
    must reproduce the materialized-logits loss AND its gradients — it is a
    memory-layout optimization, not a different objective."""
    from pytorchdistributed_tpu.models import Llama, llama_config
    from pytorchdistributed_tpu.training import fused_token_cross_entropy_loss
    from pytorchdistributed_tpu.training.losses import (
        token_cross_entropy_loss as unfused,
    )

    rng = np.random.default_rng(4)
    batch = _token_batch(rng, batch=2, seq=16)
    for model in (GPT2(gpt2_config("test", dtype=np.float32)),
                  Llama(llama_config("test", dtype=np.float32))):
        params = model.init(jax.random.key(0), batch["tokens"])

        def fused(p):
            return fused_token_cross_entropy_loss(model, p, batch)[0]

        def dense(p):
            return unfused(model, p, batch)[0]

        lf, gf = jax.value_and_grad(fused)(params)
        ld, gd = jax.value_and_grad(dense)(params)
        np.testing.assert_allclose(float(lf), float(ld), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gd)):
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-6)


def test_ce_chunk_config_is_loss_invariant():
    """cfg.ce_chunk (the r5 HBM-vs-throughput knob, bench.py
    PTD_CE_CHUNK) resizes the fused head's logit chunks only — loss and
    gradients must be identical at any chunk size, including one that
    doesn't divide the token count."""
    from pytorchdistributed_tpu.models import Llama, llama_config
    from pytorchdistributed_tpu.training import fused_token_cross_entropy_loss

    rng = np.random.default_rng(9)
    batch = _token_batch(rng, batch=2, seq=16)
    losses, grads = [], []
    for chunk in (4, 12, 1024):
        model = Llama(llama_config("test", dtype=np.float32,
                                   ce_chunk=chunk))
        params = model.init(jax.random.key(0), batch["tokens"])
        l, g = jax.value_and_grad(
            lambda p: fused_token_cross_entropy_loss(model, p, batch)[0]
        )(params)
        losses.append(float(l))
        grads.append(g)
    for l in losses[1:]:
        np.testing.assert_allclose(l, losses[0], rtol=1e-6)
    for g in grads[1:]:
        for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(grads[0])):
            np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-6)


def test_attn_block_config_is_output_invariant():
    """cfg.attn_block (the r5 block-size A/B knob, bench.py
    PTD_ATTN_BLOCK) must thread to the flash kernels without changing the
    math: a pallas model at a non-default block (forcing a multi-block
    grid with a padded tail at seq 24) matches the dense-attention model
    exactly."""
    rng = np.random.default_rng(10)
    batch = _token_batch(rng, batch=2, seq=24)
    out = {}
    for kind, block in (("dense", None), ("pallas", 16)):
        model = GPT2(gpt2_config("test", dtype=np.float32, attention=kind,
                                 attn_block=block))
        params = model.init(jax.random.key(0), batch["tokens"])
        out[kind] = model.apply(params, batch["tokens"])
    np.testing.assert_allclose(out["pallas"], out["dense"], atol=2e-5)


def test_scan_vs_unrolled_same_shape():
    """scan_layers is a compile-time optimization, not a semantic change."""
    rng = np.random.default_rng(0)
    batch = _token_batch(rng, batch=2, seq=16)
    outs = {}
    for scan in (True, False):
        model = GPT2(gpt2_config("test", scan_layers=scan))
        params = model.init(jax.random.key(0), batch["tokens"])
        outs[scan] = model.apply(params, batch["tokens"])
    assert outs[True].shape == outs[False].shape


def test_remat_trains_and_matches():
    """remat=True (activation checkpointing) must not change the math."""
    rng = np.random.default_rng(2)
    batch = _token_batch(rng)
    losses = {}
    for remat in (False, True):
        model = GPT2(gpt2_config("test", remat=remat, dtype=np.float32))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(), strategy="dp")
        losses[remat] = [float(tr.train_step(batch)["loss"]) for _ in range(2)]
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-6)


def test_rank1_batch_leaves_with_seq_mesh():
    """Rank-aware batch shardings: labels (rank 1) and images (rank 4) must
    survive a mesh that has a context-parallel axis."""
    rng = np.random.default_rng(0)
    model = resnet18(num_classes=10, cifar_stem=True)
    tr = Trainer(model, optax.sgd(0.05), cross_entropy_loss,
                 mesh=create_mesh(data=4, seq=2), strategy="dp")
    m = tr.train_step(_image_batch(rng))
    assert np.isfinite(float(m["loss"]))


def test_dropout_fires_in_training_and_not_in_eval():
    """dropout_rate > 0 must actually drop units during training (different
    rng -> different loss on identical params/batch) and stay off at eval
    (rng=None -> bit-identical, and equal to the rate=0 model's loss)."""
    from pytorchdistributed_tpu.training.losses import (
        token_cross_entropy_loss as tl,
    )

    rng = np.random.default_rng(3)
    batch = _token_batch(rng, batch=4, seq=16)
    model = GPT2(gpt2_config("test", dropout_rate=0.2, dtype=np.float32))
    params = model.init(jax.random.key(0), batch["tokens"])
    l1 = float(tl(model, params, batch, jax.random.key(1))[0])
    l2 = float(tl(model, params, batch, jax.random.key(2))[0])
    l1b = float(tl(model, params, batch, jax.random.key(1))[0])
    assert l1 != l2          # dropout is live and rng-driven
    assert l1 == l1b         # and deterministic per key
    le = float(tl(model, params, batch, None)[0])
    base = GPT2(gpt2_config("test", dropout_rate=0.0, dtype=np.float32))
    lb = float(tl(base, params, batch, None)[0])
    assert le == lb          # eval path = no dropout at all


def test_dropout_trains_end_to_end():
    rng = np.random.default_rng(4)
    model = GPT2(gpt2_config("test", dropout_rate=0.1))
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(data=2, fsdp=4), strategy="fsdp")
    batch = _token_batch(rng)
    l0 = float(tr.train_step(batch)["loss"])
    for _ in range(4):
        m = tr.train_step(batch)
    assert float(m["loss"]) < l0
    # eval_step is deterministic with dropout off
    e1 = float(tr.eval_step(batch)["loss"])
    e2 = float(tr.eval_step(batch)["loss"])
    assert e1 == e2
