"""Latency-hiding collective-matmul tests (ops/overlap.py, ISSUE 5).

Three bars on the 8-device CPU sim:

  * ring-primitive numerics — forward AND both gradients of the
    all-gather→matmul and matmul→reduce-scatter rings allclose to the
    monolithic matmul at fp32 tolerance (the column/dw rings never split
    a contraction; the row ring's traveling accumulator stays fp32), and
    the int8 composition reproduces the monolithic quantized dot on the
    gather side (identical per-row scales — the gathered dim is not
    contracted);
  * training parity — overlap="ring" reproduces the overlap="off" loss
    curve through the full Trainer across dp / fsdp / tp meshes (ring
    engages only where a tensor axis exists; elsewhere it must be the
    identity knob), in fp32 exactly and under --quant int8_fwd within
    the established tolerance, with ZERO steady-state recompiles;
  * the HLO overlap census — ppermute count == rings × (tp−1) on the
    compiled ring step, async starts/dones balanced, and the satellite
    units (ring_schedule, all_to_all validation, prefetch depth).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorchdistributed_tpu.ops.collectives import ring_schedule
from pytorchdistributed_tpu.ops.overlap import (
    ring_column_matmul,
    ring_divisibility,
    ring_row_matmul,
)
from pytorchdistributed_tpu.runtime.mesh import create_mesh

# |ring - monolithic| per-element bound at fp32: reduction-order noise
# only — grads included (the acceptance criterion's 1e-5, with headroom
# for the row ring's chunk-sum order against values of O(10)).
FP32_TOL = 1e-4
# bf16 loss-curve tolerance for the Trainer parity runs: same bar as the
# int8 parity suite (test_quant.PARITY_TOL documents the derivation).
CURVE_TOL = 0.25


def _tp_mesh():
    return create_mesh(data=2, tensor=4)


# ---------------------------------------------------------------------------
# ring-primitive numerics
# ---------------------------------------------------------------------------


class TestRingPrimitives:
    def _check(self, ring_fn, ref_fn, x, w):
        mesh = _tp_mesh()

        def ring_loss(x, w):
            return (ring_fn(x, w, mesh) ** 2).sum()

        def ref_loss(x, w):
            return (ref_fn(x, w) ** 2).sum()

        with jax.set_mesh(mesh):
            out = jax.jit(lambda x, w: ring_fn(x, w, mesh))(x, w)
            gx, gw = jax.jit(jax.grad(ring_loss, argnums=(0, 1)))(x, w)
        ref = ref_fn(x, w)
        rgx, rgw = jax.grad(ref_loss, argnums=(0, 1))(x, w)
        scale = float(jnp.abs(ref).max())
        assert float(jnp.abs(out - ref).max()) <= FP32_TOL * scale
        for g, r in ((gx, rgx), (gw, rgw)):
            gs = max(float(jnp.abs(r).max()), 1.0)
            assert float(jnp.abs(g - r).max()) <= FP32_TOL * gs

    def test_column_matches_monolithic(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 16, 8)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((8, 12)), jnp.float32)
        self._check(lambda x, w, m: ring_column_matmul(x, w, mesh=m),
                    lambda x, w: jnp.einsum("bse,ef->bsf", x, w), x, w)

    def test_column_rank3_kernel(self):
        """The fused QKV / SwiGLU kernel shape [e, stack, f]: the ring
        contracts it whole (the stack dim is a free dim)."""
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((4, 16, 8)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((8, 3, 16)), jnp.float32)
        self._check(lambda x, w, m: ring_column_matmul(x, w, mesh=m),
                    lambda x, w: jnp.einsum("bse,ecf->bscf", x, w), x, w)

    def test_row_matches_monolithic(self):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((4, 16, 12)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((12, 8)), jnp.float32)
        self._check(lambda x, w, m: ring_row_matmul(x, w, mesh=m),
                    lambda x, w: jnp.einsum("bsf,fe->bse", x, w), x, w)

    def test_column_int8_matches_monolithic_quant(self):
        """The gather ring pre-quantizes with per-row scales over the
        contraction dim — the same scales the monolithic quantized dot
        computes, so the composition reproduces it to fp32 noise; the
        int8_fwd backward runs full-precision on the saved operands and
        must match the reference VJP."""
        from pytorchdistributed_tpu.ops.quant import quantized_dot_general

        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((4, 16, 8)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((8, 12)), jnp.float32)
        qd = quantized_dot_general("int8_fwd")
        dims = (((2,), (0,)), ((), ()))
        self._check(
            lambda x, w, m: ring_column_matmul(x, w, mesh=m,
                                               quant="int8_fwd"),
            lambda x, w: qd(x, w, dims), x, w)

    def test_row_int8_close_to_monolithic_quant(self):
        """Row rings quantize over the tensor-SHARDED contraction dim, so
        scales are per-shard where the monolithic dot's are global —
        close (int8 noise level), not equal; pinned as a bound so a
        wrong-axis scale (order-of-magnitude error) still fails."""
        from pytorchdistributed_tpu.ops.quant import quantized_dot_general

        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.standard_normal((4, 16, 12)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((12, 8)), jnp.float32)
        mesh = _tp_mesh()
        with jax.set_mesh(mesh):
            out = jax.jit(lambda x, w: ring_row_matmul(
                x, w, mesh=mesh, quant="int8_fwd"))(x, w)
        ref = quantized_dot_general("int8_fwd")(x, w, (((2,), (0,)), ((), ())))
        rel = float(jnp.abs(out - ref).max() / jnp.abs(ref).max())
        assert rel < 0.05, rel

    def test_preferred_element_type(self):
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((4, 16, 8)), jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal((8, 12)), jnp.bfloat16)
        mesh = _tp_mesh()
        with jax.set_mesh(mesh):
            y = jax.jit(lambda x, w: ring_column_matmul(
                x, w, mesh=mesh))(x, w)
            y32 = jax.jit(lambda x, w: ring_column_matmul(
                x, w, mesh=mesh,
                preferred_element_type=jnp.float32))(x, w)
        assert y.dtype == jnp.bfloat16
        assert y32.dtype == jnp.float32


# ---------------------------------------------------------------------------
# the routing drop-in + divisibility fallbacks
# ---------------------------------------------------------------------------


class TestRouting:
    def test_divisibility_gates(self):
        mesh = _tp_mesh()
        ok = ring_divisibility((4, 16, 8), (8, 12), mesh, "tensor",
                               "column")
        assert ok
        # s=1 (decode tick) / non-tiling seq / feature not divisible
        assert not ring_divisibility((4, 1, 8), (8, 12), mesh, "tensor",
                                     "column")
        assert not ring_divisibility((4, 6, 8), (8, 12), mesh, "tensor",
                                     "column")
        assert not ring_divisibility((4, 16, 8), (8, 10), mesh, "tensor",
                                     "column")
        assert not ring_divisibility((4, 16, 10), (10, 8), mesh, "tensor",
                                     "row")
        # no tensor axis → never rings
        assert not ring_divisibility((4, 16, 8), (8, 12),
                                     create_mesh(data=8), "tensor",
                                     "column")

    def test_dot_general_drop_in_falls_back_without_mesh(self):
        """Outside any mesh context the injectable must be exactly the
        plain dot (the knob can never break a meshless call site)."""
        from pytorchdistributed_tpu.parallel.overlap import (
            overlap_dot_general,
        )

        dg = overlap_dot_general("column", "none")
        x = jnp.ones((2, 4, 8))
        w = jnp.ones((8, 6))
        out = dg(x, w, (((2,), (0,)), ((), ())))
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(x @ w), rtol=1e-6)

    def test_dot_general_cached_identity(self):
        from pytorchdistributed_tpu.parallel.overlap import (
            overlap_dot_general,
        )

        assert (overlap_dot_general("column", "none")
                is overlap_dot_general("column", "none"))
        assert (overlap_dot_general("column", "none")
                is not overlap_dot_general("row", "none"))
        with pytest.raises(ValueError):
            overlap_dot_general("diagonal")

    def test_overlap_config_validation(self):
        from pytorchdistributed_tpu.models import gpt2_config

        with pytest.raises(ValueError):
            gpt2_config("test", overlap="rings")
        from pytorchdistributed_tpu.parallel.overlap import validate_overlap

        with pytest.raises(ValueError):
            validate_overlap("on")


# ---------------------------------------------------------------------------
# Trainer-level parity: ring vs monolithic loss curves (dp / fsdp / tp)
# ---------------------------------------------------------------------------


def _train_losses(overlap, axes, strategy, *, quant="none", steps=6,
                  dtype=None):
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    kw = dict(overlap=overlap, quant=quant)
    if dtype is not None:
        kw["dtype"] = dtype
    cfg = gpt2_config("test", **kw)
    tr = Trainer(GPT2(cfg), optax.adamw(1e-2), token_cross_entropy_loss,
                 mesh=create_mesh(**axes), strategy=strategy,
                 log_every=10**9, watchdog=False, overlap=overlap)
    rng = np.random.default_rng(7)
    batch = {
        "tokens": rng.integers(0, 128, (32, 64)).astype(np.int32),
        "targets": rng.integers(0, 128, (32, 64)).astype(np.int32),
    }
    return [float(tr.train_step(batch)["loss"]) for _ in range(steps)], tr


def test_parity_tp_fp32_exact():
    """fp32 model: ring and monolithic curves agree to fp32 noise per
    step — the acceptance criterion's strict half (bf16 runs get the
    curve tolerance)."""
    off, _ = _train_losses("off", dict(data=2, tensor=4), "tp",
                           dtype=jnp.float32)
    ring, _ = _train_losses("ring", dict(data=2, tensor=4), "tp",
                            dtype=jnp.float32)
    for a, b in zip(off, ring):
        assert abs(a - b) < 1e-3, (off, ring)


@pytest.mark.parametrize("axes,strategy", [
    (dict(data=8), "dp"),
    (dict(data=2, fsdp=4), "fsdp"),
    (dict(data=2, tensor=4), "tp"),
])
def test_parity_bf16(axes, strategy):
    off, _ = _train_losses("off", axes, strategy)
    ring, _ = _train_losses("ring", axes, strategy)
    assert ring[-1] < ring[0], f"ring did not learn: {ring}"
    delta = abs(off[-1] - ring[-1])
    assert delta < CURVE_TOL, (off, ring)
    if "tensor" not in axes:
        # no tp axis: the knob must be the identity — same compiled
        # program, bitwise-equal curve
        assert off == ring, (off, ring)


def test_parity_tp_int8():
    """--quant int8_fwd x overlap=ring: the quantized ring step tracks
    the quantized monolithic step (gather-side scales identical; the
    row side's per-shard scales are inside int8 noise)."""
    off, _ = _train_losses("off", dict(data=2, tensor=4), "tp",
                           quant="int8_fwd")
    ring, _ = _train_losses("ring", dict(data=2, tensor=4), "tp",
                            quant="int8_fwd")
    assert ring[-1] < ring[0], f"quantized ring did not learn: {ring}"
    assert abs(off[-1] - ring[-1]) < CURVE_TOL, (off, ring)


def test_zero_steadystate_recompiles():
    """The ring step compiles once: repeated steps hit the same pjit
    cache entry (the serving suite's _cache_size tripwire, applied to
    the ring-routed train step)."""
    losses, tr = _train_losses("ring", dict(data=2, tensor=4), "tp",
                               steps=4)
    assert tr._step_fn._cache_size() == 1
    for _ in range(3):
        tr.train_step({
            "tokens": np.zeros((32, 64), np.int32),
            "targets": np.zeros((32, 64), np.int32),
        })
    assert tr._step_fn._cache_size() == 1


# ---------------------------------------------------------------------------
# the HLO overlap census
# ---------------------------------------------------------------------------


def test_ring_census_ppermute_counts():
    """The compiled ring step's collective-permute count decomposes as
    baseline + rings x (tp-1): 4 projection sites x 3 rings each (fwd,
    bwd-dx, bwd-dw) in the scanned block body, each ring contributing
    exactly tp-1 hops — the acceptance criterion's census assert. The
    async start/done pairing must be balanced (trivially, on the sim's
    synchronous lowering; on TPU the same census counts real pairs)."""
    from pytorchdistributed_tpu.utils.hlo import compiled_invariants

    _, tr_off = _train_losses("off", dict(data=2, tensor=4), "tp", steps=1)
    _, tr_ring = _train_losses("ring", dict(data=2, tensor=4), "tp",
                               steps=1)
    batch = {
        "tokens": np.zeros((32, 64), np.int32),
        "targets": np.zeros((32, 64), np.int32),
    }
    base = compiled_invariants(tr_off.lower_step(batch).compile())
    ring = compiled_invariants(tr_ring.lower_step(batch).compile())
    tp = 4
    n_rings = 4 * 3  # qkv/out/wi/wo x (fwd, bwd-dx, bwd-dw)
    extra = ring["overlap"]["ppermute"] - base["overlap"]["ppermute"]
    assert extra == n_rings * (tp - 1), (base["overlap"], ring["overlap"])
    assert ring["overlap"]["unpaired_starts"] == 0
    # async pairing on the gradient reduce: starts and dones balance
    # (counted pairs are <= the all-reduce census; every start matched)
    for op, n in ring["overlap"]["async_pairs"].items():
        assert n <= ring["collectives"][op]


def test_overlap_census_parses_async_pairs():
    """Unit: the census pairs starts/dones by value name and counts the
    instructions scheduled between them (the hidden window). The text
    uses the REAL operand syntax this image's `compiled.as_text()`
    emits — tuple staging types with internal spaces on the starts
    (every async collective start returns a tuple) and shape-prefixed
    operands on the dones (`all-gather-done((f32[8], f32[16]) %ag.1)`)
    — so a parser that assumed one type token or a bare `%name` operand
    would read 0 pairs on exactly the TPU programs the census exists to
    verify."""
    from pytorchdistributed_tpu.utils.hlo import overlap_census

    hlo = """
HloModule m
ENTRY e {
  %p0 = f32[8]{0} parameter(0)
  %ar = f32[8]{0} all-reduce-start(f32[8]{0} %p0), replica_groups={}
  %mul = f32[8]{0} multiply(f32[8]{0} %p0, f32[8]{0} %p0)
  %add = f32[8]{0} add(f32[8]{0} %mul, f32[8]{0} %mul)
  %d = f32[8]{0} all-reduce-done(f32[8]{0} %ar)
  %ag.1 = (f32[8]{0}, f32[16]{0}) all-gather-start(f32[8]{0} %d), dimensions={0}
  %sub = f32[8]{0} subtract(f32[8]{0} %d, f32[8]{0} %d)
  %g = f32[16]{0} all-gather-done((f32[8]{0}, f32[16]{0}) %ag.1)
  %cp = f32[8]{0} collective-permute(f32[8]{0} %d), source_target_pairs={{0,1}}
  ROOT %out = (f32[8]{0}, f32[16]{0}) tuple(f32[8]{0} %cp, f32[16]{0} %g)
}
"""
    c = overlap_census(hlo)
    assert c["async_pairs"]["all-reduce"] == 1
    assert c["async_pairs"]["all-gather"] == 1
    assert c["unpaired_starts"] == 0
    assert c["overlapped_ops"] == 3      # mul + add, then sub
    assert c["ppermute"] == 1


# ---------------------------------------------------------------------------
# satellite units: ring_schedule / all_to_all validation / prefetch depth
# ---------------------------------------------------------------------------


def test_ring_schedule():
    assert ring_schedule(4, 1) == [(0, 1), (1, 2), (2, 3), (3, 0)]
    assert ring_schedule(4, -1) == [(0, 3), (1, 0), (2, 1), (3, 2)]
    assert ring_schedule(4, 5) == ring_schedule(4, 1)
    assert ring_schedule(3, 0) == [(0, 0), (1, 1), (2, 2)]
    assert ring_schedule(1, 1) == [(0, 0)]
    with pytest.raises(ValueError):
        ring_schedule(0)


def test_all_to_all_validates_axes():
    from pytorchdistributed_tpu.ops.collectives import all_to_all

    x = jnp.ones((4, 8))
    for bad in (dict(split_axis=2, concat_axis=0),
                dict(split_axis=0, concat_axis=-1),
                dict(split_axis="0", concat_axis=1)):
        with pytest.raises(ValueError, match="out of range"):
            all_to_all(x, "data", **bad)


def test_prefetch_depth_zero_is_synchronous():
    """Depth 0 must degrade to synchronous transfer: each batch is
    yielded before the next is pulled from the host iterator (the
    double-buffer default pulls one ahead)."""
    from pytorchdistributed_tpu.data.loader import prefetch_to_device

    mesh = create_mesh()
    from pytorchdistributed_tpu.runtime.mesh import batch_sharding

    sharding = batch_sharding(mesh)
    pulled = []

    def feed(n):
        for i in range(n):
            pulled.append(i)
            yield {"x": np.full((8, 2), i, np.float32)}

    # sync: after pulling k batches the consumer has seen all k
    it = prefetch_to_device(feed(3), sharding, size=0)
    for i in range(3):
        batch = next(it)
        assert int(batch["x"][0, 0]) == i
        assert pulled == list(range(i + 1))
    pulled.clear()
    # depth 2 runs ahead by up to 2 host batches
    it = prefetch_to_device(feed(4), sharding, size=2)
    first = next(it)
    assert int(first["x"][0, 0]) == 0
    assert len(pulled) >= 2
    with pytest.raises(ValueError):
        list(prefetch_to_device(feed(1), sharding, size=-1))


def test_trainer_prefetch_knob(monkeypatch):
    """Trainer(prefetch=...) and the PTD_PREFETCH env contract resolve
    in that order, and invalid depths are rejected eagerly."""
    import optax

    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    def make(**kw):
        return Trainer(MLP(), optax.sgd(0.1), mse_loss,
                       mesh=create_mesh(), watchdog=False, **kw)

    assert make().prefetch == 2
    assert make(prefetch=0).prefetch == 0
    monkeypatch.setenv("PTD_PREFETCH", "5")
    assert make().prefetch == 5
    assert make(prefetch=1).prefetch == 1    # explicit arg wins
    with pytest.raises(ValueError):
        make(prefetch=-1)
    monkeypatch.delenv("PTD_PREFETCH")
    with pytest.raises(ValueError):
        make(overlap="maybe")
