"""Elastic recovery suite: the persistent AOT executable cache
(runtime/compile_cache.py, ISSUE 10) and its integrations.

Correctness bars:

  * a WARM start — engine or trainer — reaches its first token/step
    with ZERO fresh XLA compiles (compile-cache miss/store counters,
    engine TRACE_COUNTS and the jit wrappers' pjit ``_cache_size`` are
    the tripwires) and outputs BITWISE-equal to the uncached path;
  * the cache can never make anything worse: a corrupt payload, a
    tampered manifest, or a version mismatch quarantines the entry and
    falls back to a clean fresh compile (never-fails contract);
  * two engines racing to publish the same entry both succeed and the
    directory verifies clean (atomic tmp+os.replace publish);
  * a replica worker's ``"checkpoint"`` spec key restores verified
    params (falling back to init_seed when absent), and the router's
    auto-respawn brings a DEAD replica back through the
    quarantine → probe → canary path with streams bitwise-preserved.

Engine geometry mirrors tests/test_router.py (gpt2 "test", 2 layers,
max_seq_len 64, slots 3, bucket 16) so the uncached reference engines
ride the suite's shared jit cache.
"""

import dataclasses
import functools
import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.runtime.compile_cache import (
    CompileCache,
    main as cache_cli,
    stats_snapshot,
)
from pytorchdistributed_tpu.serving import ReplicaRouter, ServingEngine
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.engine import (
    decode_tick,
    params_finite,
    prefill_into_slot,
)

CFG = gpt2_config("test", num_layers=2, max_seq_len=64)


@functools.cache
def _setup():
    model = GPT2(CFG)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    return model, params, dm


def _ref(prompt, n):
    _, params, dm = _setup()
    return np.asarray(generate(dm, params, jnp.asarray(prompt)[None],
                               max_new_tokens=n))[0]


def _prompts(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
            for m in (5, 9, 7, 11, 6, 8, 4, 10)[:n]]


def _delta(before):
    return {k: v - before.get(k, 0) for k, v in stats_snapshot().items()
            if v - before.get(k, 0)}


def _engine(cache, **kw):
    model, params, _ = _setup()
    kw.setdefault("num_slots", 3)
    kw.setdefault("prefill_bucket", 16)
    return ServingEngine(model, params, compile_cache=cache, **kw)


# ----------------------------------------------------------------------
# cache-core units


@jax.jit
def _axpy(a, x, y):
    return a * x + y


def _axpy_compile(args):
    return lambda: _axpy.lower(*args).compile()


def test_key_components_all_enter_the_digest(tmp_path):
    """Every advertised key component — name, avals, dtype, statics,
    config hash, donation — must move the digest; identical inputs must
    reproduce it (the cross-process contract)."""
    cache = CompileCache(tmp_path, events=None)
    args = (jnp.float32(2.0), jnp.ones((4,)), jnp.ones((4,)))
    base_kw = dict(statics="s", config_hash="c", donation="d")
    _, base = cache.entry_key("p", args, **base_kw)
    _, again = cache.entry_key("p", args, **base_kw)
    assert base == again
    variants = [
        cache.entry_key("q", args, **base_kw),
        cache.entry_key("p", (jnp.float32(2.0), jnp.ones((8,)),
                              jnp.ones((8,))), **base_kw),
        cache.entry_key("p", (jnp.float32(2.0), jnp.ones((4,), jnp.int32),
                              jnp.ones((4,))), **base_kw),
        cache.entry_key("p", args, statics="t", config_hash="c",
                        donation="d"),
        cache.entry_key("p", args, statics="s", config_hash="x",
                        donation="d"),
        cache.entry_key("p", args, statics="s", config_hash="c",
                        donation="e"),
    ]
    digests = [d for _, d in variants]
    assert base not in digests and len(set(digests)) == len(digests)


def test_roundtrip_miss_then_hit_bitwise(tmp_path):
    """miss → compile + publish; a second cache instance (a 'restarted
    process') hits, deserializes and computes the identical result."""
    args = (jnp.float32(3.0), jnp.arange(4.0), jnp.ones((4,)))
    c1 = CompileCache(tmp_path, events=None)
    before = stats_snapshot()
    compiled, outcome = c1.load_or_compile("axpy", _axpy_compile(args),
                                           args)
    assert outcome == "miss"
    want = np.asarray(compiled(*args))
    c2 = CompileCache(tmp_path, events=None)
    compiled2, outcome2 = c2.load_or_compile(
        "axpy", lambda: pytest.fail("hit must not compile"), args)
    assert outcome2 == "hit"
    np.testing.assert_array_equal(np.asarray(compiled2(*args)), want)
    assert _delta(before) == {"miss": 1, "store": 1, "hit": 1}


def test_corrupt_payload_quarantined_then_clean_recompile(tmp_path):
    """A bit-flipped payload must cost a quarantine + one fresh compile
    — never an exception, never a wrong executable."""
    args = (jnp.float32(1.0), jnp.arange(4.0), jnp.zeros((4,)))
    cache = CompileCache(tmp_path, events=None)
    cache.load_or_compile("axpy", _axpy_compile(args), args)
    (bin_path,) = [p for p in tmp_path.iterdir() if p.suffix == ".bin"]
    blob = bytearray(bin_path.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    bin_path.write_bytes(bytes(blob))
    before = stats_snapshot()
    compiled, outcome = cache.load_or_compile("axpy", _axpy_compile(args),
                                              args)
    assert outcome == "miss"  # the defect fell back to a fresh compile
    np.testing.assert_array_equal(np.asarray(compiled(*args)),
                                  np.arange(4.0))
    d = _delta(before)
    assert d.get("quarantined") == 1 and d.get("store") == 1
    qdir = tmp_path / "quarantine"
    assert qdir.is_dir() and any(qdir.iterdir())
    # the re-published entry is clean: next load is a pure hit
    _, outcome = CompileCache(tmp_path, events=None).load_or_compile(
        "axpy", lambda: pytest.fail("should hit"), args)
    assert outcome == "hit"


def test_version_mismatch_quarantined(tmp_path):
    """A manifest recording a different jaxlib (tampered, or a drifted
    key scheme) must quarantine, not load: an executable serialized by
    another toolchain can crash the process from native code."""
    args = (jnp.float32(1.0), jnp.arange(4.0), jnp.zeros((4,)))
    cache = CompileCache(tmp_path, events=None)
    cache.load_or_compile("axpy", _axpy_compile(args), args)
    (man,) = [p for p in tmp_path.iterdir() if p.suffix == ".json"]
    meta = json.loads(man.read_text())
    meta["jaxlib"] = "0.0.1"
    man.write_text(json.dumps(meta))
    before = stats_snapshot()
    assert cache.load("axpy", args) is None
    assert _delta(before).get("quarantined") == 1


def test_concurrent_publish_race_is_safe(tmp_path):
    """Two engines racing to publish the same entry (the N-replica
    cold start): both must come back with working executables and the
    directory must verify clean — atomic tmp+os.replace, last writer
    wins with identical content."""
    args = (jnp.float32(2.0), jnp.arange(4.0), jnp.ones((4,)))
    results, errors = [], []

    def worker():
        try:
            cache = CompileCache(tmp_path, events=None)
            compiled, _ = cache.load_or_compile("axpy",
                                                _axpy_compile(args), args)
            results.append(np.asarray(compiled(*args)))
        except Exception as e:  # noqa: BLE001 — the test's whole point
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors and len(results) == 4
    for r in results:
        np.testing.assert_array_equal(r, 2.0 * np.arange(4.0) + 1.0)
    verdicts = CompileCache(tmp_path, events=None).verify()
    assert verdicts and all(ok for _, ok, _ in verdicts), verdicts


# ----------------------------------------------------------------------
# engine integration


def test_engine_warm_start_zero_compiles_bitwise(tmp_path):
    """The headline: a restarted engine over a warm cache reaches its
    tokens with ZERO fresh compiles — no traces (TRACE_COUNTS), no jit
    compiles (pjit _cache_size), no cache misses — and every stream is
    bitwise what the uncached engine produces."""
    prompts = _prompts(3)
    cold = _engine(str(tmp_path))
    cold.warmup(prompt_lens=(16,))
    assert set(cold.aot_outcomes.values()) == {"miss"}
    for p in prompts:
        r = cold.submit(p, max_new_tokens=6)
        cold.run_until_idle()
        np.testing.assert_array_equal(r.output_ids, _ref(p, 6))
    cold.close()

    traces = dict(serving_engine.TRACE_COUNTS)
    sizes = (decode_tick._cache_size(), prefill_into_slot._cache_size(),
             params_finite._cache_size())
    before = stats_snapshot()
    warm = _engine(str(tmp_path))
    warm.warmup(prompt_lens=(16,))
    assert set(warm.aot_outcomes.values()) == {"hit"}
    for p in prompts:
        r = warm.submit(p, max_new_tokens=6)
        warm.run_until_idle()
        np.testing.assert_array_equal(r.output_ids, _ref(p, 6))
    warm.close()
    assert dict(serving_engine.TRACE_COUNTS) == traces
    assert (decode_tick._cache_size(), prefill_into_slot._cache_size(),
            params_finite._cache_size()) == sizes
    d = _delta(before)
    assert "miss" not in d and "store" not in d, d
    assert d.get("hit", 0) >= 3


def test_engine_paged_warm_start_bitwise(tmp_path):
    """Paged engine (block pool + radix + chunked prefill) through the
    cache: warm start all-hits, streams bitwise vs generate()."""
    prompts = _prompts(3, seed=3)
    for leg in ("cold", "warm"):
        before = stats_snapshot()
        eng = _engine(str(tmp_path), block_size=8)
        eng.warmup(prompt_lens=(16,))
        want = {"miss"} if leg == "cold" else {"hit"}
        assert set(eng.aot_outcomes.values()) == want, (leg,
                                                        eng.aot_outcomes)
        for p in prompts:
            r = eng.submit(p, max_new_tokens=6)
            eng.run_until_idle()
            np.testing.assert_array_equal(r.output_ids, _ref(p, 6))
        eng.close()
        if leg == "warm":
            assert "miss" not in _delta(before)


def test_warmup_collapses_to_one_round_with_cache(tmp_path):
    """The two-round-per-bucket warmup exists only to absorb the jit
    fresh-vs-committed-cache recompile; AOT dispatch has a fixed
    convention, so warmup must pay exactly one dummy request per
    bucket (TRACE_COUNTS moves once per program on a cold cache via
    lower(), not at all on a warm one)."""
    eng = _engine(str(tmp_path))
    eng.warmup(prompt_lens=(16, 32))
    # one AOT program per prefill bucket + the tick + the probe — the
    # complete program set for this engine shape, resolved in ONE round
    assert set(eng.aot_outcomes) == {"prefill_b16", "prefill_b32",
                                     "decode_tick", "params_finite"}
    eng.close()
    warm_traces = dict(serving_engine.TRACE_COUNTS)
    eng2 = _engine(str(tmp_path))
    eng2.warmup(prompt_lens=(16, 32))
    eng2.close()
    assert dict(serving_engine.TRACE_COUNTS) == warm_traces


def test_cache_failure_falls_back_to_jit_with_full_warmup(tmp_path,
                                                          monkeypatch):
    """The never-fails floor: if the cache layer itself blows up on
    every program, the engine must serve bitwise from the plain jit
    path — and warmup must still run the jit path's SECOND round (the
    fresh-vs-committed recompile absorber), so the first real request
    pays no compile."""
    def boom(self, *a, **k):
        raise RuntimeError("cache exploded")

    monkeypatch.setattr(CompileCache, "load_or_compile", boom)
    eng = _engine(str(tmp_path))
    eng.warmup(prompt_lens=(16,))
    assert eng.aot_outcomes == {}          # nothing resolved AOT
    assert eng._aot_failed                 # everything fell back
    traces = dict(serving_engine.TRACE_COUNTS)
    p = _prompts(1)[0]
    r = eng.submit(p, max_new_tokens=6)
    eng.run_until_idle()
    np.testing.assert_array_equal(r.output_ids, _ref(p, 6))
    assert dict(serving_engine.TRACE_COUNTS) == traces  # no retrace
    eng.close()


# ----------------------------------------------------------------------
# CLI: ls / verify / gc / prewarm


def test_cli_ls_verify_gc(tmp_path, capsys):
    args = (jnp.float32(1.0), jnp.arange(4.0), jnp.zeros((4,)))
    cache = CompileCache(tmp_path, events=None)
    cache.load_or_compile("axpy", _axpy_compile(args), args)
    assert cache_cli(["ls", str(tmp_path)]) == 0
    assert "axpy" in capsys.readouterr().out
    assert cache_cli(["verify", str(tmp_path)]) == 0
    (bin_path,) = [p for p in tmp_path.iterdir() if p.suffix == ".bin"]
    bin_path.write_bytes(b"garbage")
    assert cache_cli(["verify", str(tmp_path)]) == 1
    assert "CORRUPT" in capsys.readouterr().out
    assert cache_cli(["gc", str(tmp_path), "--keep", "0"]) == 0
    assert not [p for p in tmp_path.iterdir() if p.suffix == ".bin"]


def test_cli_prewarm_then_worker_starts_all_hits(tmp_path):
    """Deploy-time prewarm: the CLI compiles + serializes every program
    a replica spec needs; a worker engine built from the SAME spec then
    warms entirely from the cache."""
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1, "warmup_lens": [16],
            "engine": {"num_slots": 3, "prefill_bucket": 16}}
    assert cache_cli(["prewarm", str(tmp_path),
                      "--spec", json.dumps(spec)]) == 0
    from pytorchdistributed_tpu.serving.replica_worker import _build_engine

    before = stats_snapshot()
    spec["compile_cache"] = str(tmp_path)
    eng = _build_engine(spec)
    eng.warmup(prompt_lens=[16])
    assert set(eng.aot_outcomes.values()) == {"hit"}, eng.aot_outcomes
    p = _prompts(1)[0]
    r = eng.submit(p, max_new_tokens=6)
    eng.run_until_idle()
    np.testing.assert_array_equal(r.output_ids, _ref(p, 6))
    eng.close()
    assert "miss" not in _delta(before)


# ----------------------------------------------------------------------
# replica worker: the "checkpoint" spec key


def test_worker_checkpoint_key_restores_verified_params(tmp_path):
    """The replica_worker docstring's promise: a spec "checkpoint"
    loads verified weights (a TrainState-shaped checkpoint yields its
    params subtree); the engine then serves exactly those weights."""
    from pytorchdistributed_tpu.serving.replica_worker import _build_engine
    from pytorchdistributed_tpu.training.checkpoint import (
        CheckpointManager,
    )

    _, params, _ = _setup()
    state = {"step": jnp.int32(7), "params": params,
             "opt_state": {"nu": jnp.zeros(3)}}
    with CheckpointManager(tmp_path / "ckpt") as mgr:
        mgr.save(7, state)
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 999,  # decoy: must NOT be used
            "checkpoint": str(tmp_path / "ckpt"),
            "engine": {"num_slots": 3, "prefill_bucket": 16}}
    eng = _build_engine(spec)
    eng.warmup(prompt_lens=(16,))
    p = _prompts(1)[0]
    r = eng.submit(p, max_new_tokens=6)
    eng.run_until_idle()
    np.testing.assert_array_equal(r.output_ids, _ref(p, 6))
    eng.close()


def test_worker_checkpoint_absent_falls_back_to_seed(tmp_path,
                                                     monkeypatch):
    """An absent/empty checkpoint must not kill the worker (it would
    die again on every respawn): it falls back to init_seed and logs
    the TelemetryEvent."""
    from pytorchdistributed_tpu.serving.replica_worker import _load_params
    from pytorchdistributed_tpu.telemetry.events import (
        EVENT_REPLICA_RESTORE_FALLBACK,
        read_events,
    )

    monkeypatch.setenv("PTD_TELEMETRY_DIR", str(tmp_path / "tele"))
    model, _, _ = _setup()
    spec = {"init_seed": 1, "checkpoint": str(tmp_path / "nope")}
    params = _load_params(spec, model)
    want = jax.jit(model.init)(jax.random.key(1),
                               jnp.zeros((1, 8), jnp.int32))
    np.testing.assert_array_equal(
        np.asarray(jax.tree_util.tree_leaves(params)[0]),
        np.asarray(jax.tree_util.tree_leaves(want)[0]))
    kinds = [e.kind for e in read_events(tmp_path / "tele")]
    assert EVENT_REPLICA_RESTORE_FALLBACK in kinds


# ----------------------------------------------------------------------
# trainer integration


def _trainer(cache, lr=1e-3):
    import optax

    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    return Trainer(MLP(features=(32, 8)), optax.adamw(lr), mse_loss,
                   mesh=create_mesh(), strategy="dp", log_every=10**9,
                   compile_cache=cache)


@functools.cache
def _train_batch():
    from pytorchdistributed_tpu.data import (
        DataLoader,
        SyntheticRegressionDataset,
    )

    ds = SyntheticRegressionDataset(size=64, in_dim=16, out_dim=8, seed=0)
    return next(iter(DataLoader(ds, batch_size=16, num_replicas=1,
                                rank=0)))


def test_trainer_warm_restart_zero_jit_compiles(tmp_path):
    """A relaunched trainer over a warm cache: the step executable
    deserializes, train_step dispatches through it (the jit wrapper's
    pjit cache stays EMPTY — zero XLA compiles), and the loss curve is
    bitwise the uncached one's."""
    batch = _train_batch()

    def losses(t, steps=3):
        return [float(t.train_step(batch)["loss"]) for _ in range(steps)]

    ref = losses(_trainer(None))
    assert losses(_trainer(str(tmp_path))) == ref       # cold: parity
    before = stats_snapshot()
    warm = _trainer(str(tmp_path))
    assert losses(warm) == ref
    assert warm._step_fn._cache_size() == 0             # never jit-compiled
    d = _delta(before)
    assert d.get("hit") == 1 and "miss" not in d, d
    # step_accounting reuses the SAME cached executable: no extra load
    acc = warm.step_accounting(batch)
    assert acc is not None
    assert _delta(before).get("hit") == 1


def test_trainer_cache_keyed_on_lowered_hlo_not_shapes(tmp_path):
    """Two trainers with identical shapes but different optimizer
    hyperparameters lower to different programs — the HLO-hash key must
    MISS, never serve one the other's executable (the silent-wrong-hit
    failure mode a shapes-only key would have)."""
    batch = _train_batch()
    t1 = _trainer(str(tmp_path), lr=1e-3)
    t1.train_step(batch)
    before = stats_snapshot()
    t2 = _trainer(str(tmp_path), lr=3e-3)
    t2.train_step(batch)
    d = _delta(before)
    assert d.get("miss") == 1 and "hit" not in d, d


# ----------------------------------------------------------------------
# router auto-respawn (in-process; the subprocess e2e is full-tier)


def test_router_respawn_rejoins_and_serves(tmp_path):
    """replica_crash → DEAD → auto-respawn (budgeted, backoff) →
    QUARANTINED → clean-probe streak → canary → HEALTHY and serving
    again, with every stream — failed-over and post-respawn — bitwise
    the single-engine reference. A crash is a transient, not a
    permanent capacity loss."""
    from pytorchdistributed_tpu.faults.inject import (
        FaultInjector,
        FaultPlan,
    )
    from pytorchdistributed_tpu.faults.retry import RetryPolicy
    from pytorchdistributed_tpu.serving import HEALTHY
    from pytorchdistributed_tpu.serving.telemetry import RouterTelemetry
    from pytorchdistributed_tpu.telemetry.report import render

    model, params, _ = _setup()
    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=4,replica=0"))
    router = ReplicaRouter(
        model, params, replicas=2,
        engine_kwargs=dict(num_slots=3, prefill_bucket=16),
        warmup_lens=(16, 32), faults=inj,
        respawn_budget=1, rejoin_after=2,
        respawn_policy=RetryPolicy(base_delay_s=0.0, jitter=0.0),
        telemetry=RouterTelemetry(tmp_path))
    router.warmup()
    prompts = _prompts(5)
    reqs = [router.submit(p, max_new_tokens=8) for p in prompts]
    router.run_until_idle()
    for p, r in zip(prompts, reqs):
        np.testing.assert_array_equal(r.output_ids, _ref(p, 8))
    # second wave: the respawn gate has opened by now — replica 0 comes
    # back through quarantine + canary and takes traffic again
    reqs2 = [router.submit(p, max_new_tokens=8) for p in prompts]
    router.run_until_idle()
    for p, r in zip(prompts, reqs2):
        np.testing.assert_array_equal(r.output_ids, _ref(p, 8))
    s = router.summary()
    assert s["respawns"] == 1 and s["rejoins"] == 1, s
    assert router._status[0] == HEALTHY
    reqs3 = [router.submit(p, max_new_tokens=8) for p in prompts]
    router.run_until_idle()
    assert router.summary()["served_by"].get(0, 0) > 0
    router.close()
    report = render(tmp_path)
    assert "respawns 1" in report and "respawn" in report


def test_subprocess_respawn_from_checkpoint_and_cache(monkeypatch,
                                                      tmp_path):
    """The acceptance chaos e2e, multi-process shape: subprocess
    workers restoring weights from a verified checkpoint and
    executables from a prewarmed compile cache; PTD_FAULTS crashes
    worker 0 from inside (os._exit mid-protocol); the router fails its
    streams over (bitwise), auto-RESPAWNS the worker — which rejoins
    through the quarantine probes and serves again with bitwise-equal
    streams — and teardown leaves no orphan. The one-shot fault marker
    persists in PTD_FAULTS_STATE, so the respawned incarnation does not
    crash-loop."""
    import time as _time

    from pytorchdistributed_tpu.faults import inject as faults_inject
    from pytorchdistributed_tpu.faults.retry import RetryPolicy
    from pytorchdistributed_tpu.serving import HEALTHY
    from pytorchdistributed_tpu.training.checkpoint import (
        CheckpointManager,
    )

    _, params, _ = _setup()
    with CheckpointManager(tmp_path / "ckpt") as mgr:
        mgr.save(1, {"step": jnp.int32(1), "params": params,
                     "opt_state": {"nu": jnp.zeros(1)}})
    monkeypatch.setenv("PTD_FAULTS", "replica_crash@tick=5,replica=0")
    monkeypatch.setenv("PTD_FAULTS_STATE", str(tmp_path / "faults"))
    faults_inject.reset_active()
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "checkpoint": str(tmp_path / "ckpt"),
            "compile_cache": str(tmp_path / "cache"),
            "engine": {"num_slots": 2, "prefill_bucket": 16}}
    router = ReplicaRouter(
        workers=[spec, spec], warmup_lens=(16, 32), faults=None,
        respawn_budget=1, rejoin_after=1,
        respawn_policy=RetryPolicy(base_delay_s=0.0, jitter=0.0))
    try:
        router.warmup()
        prompts = _prompts(4)
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_steps=200000)
        assert router.summary()["replicas_lost"] == 1
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(r.output_ids, _ref(p, 6),
                                          err_msg=f"request {r.id}")
        # idle-tick until the respawned worker has warmed (from the
        # entries the first incarnation published) and rejoined
        deadline = _time.time() + 180
        while (_time.time() < deadline
               and (router.summary()["respawns"] < 1
                    or router._status[0] != HEALTHY)):
            router.step()
        assert router.summary()["respawns"] == 1
        assert router._status[0] == HEALTHY
        reqs2 = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_steps=200000)
        for p, r in zip(prompts, reqs2):
            np.testing.assert_array_equal(r.output_ids, _ref(p, 6),
                                          err_msg=f"request {r.id}")
        assert router.summary()["served_by"].get(0, 0) > 0
        procs = [rep.proc for rep in router._replicas]
    finally:
        router.close()
        faults_inject.reset_active()
    deadline = _time.time() + 15
    while (_time.time() < deadline
           and any(p.poll() is None for p in procs)):
        _time.sleep(0.1)
    assert all(p.poll() is not None for p in procs), \
        [p.poll() for p in procs]


def test_respawn_warmup_timeout_declares_wedged_worker_dead():
    """A respawned worker that wedges DURING its async startup must not
    park its slot in QUARANTINED forever: past respawn_warmup_s the
    router declares it hung — spending the next budgeted attempt (or
    finally giving up) instead of silently losing capacity."""
    import time as _time

    from pytorchdistributed_tpu.serving import DEAD, QUARANTINED

    model, params, _ = _setup()
    router = ReplicaRouter(
        model, params, replicas=2,
        engine_kwargs=dict(num_slots=3, prefill_bucket=16),
        warmup_lens=(16,), faults=None, respawn_budget=1,
        respawn_warmup_s=0.01)
    router.warmup()

    class Wedged:  # a respawned subprocess worker stuck in startup
        index = 0
        hang_grace_s = 0.0
        faults_in_worker = True
        alive = True
        _warming = True

        def health(self):
            return {"alive": True, "progress": -1}

        def probe(self, exclusive=False):
            return False

        def drain(self):
            return []

        def close(self):
            pass

    router._replicas[0] = Wedged()
    router._status[0] = QUARANTINED
    router._respawns[0] = 1  # this IS the budgeted respawn, wedged
    router._warming_deadline[0] = _time.perf_counter() - 1.0
    router.step()
    assert router._status[0] == DEAD
    # budget spent: the fleet serves on the survivor, no infinite park
    p = _prompts(1)[0]
    r = router.submit(p, max_new_tokens=6)
    router.run_until_idle()
    np.testing.assert_array_equal(r.output_ids, _ref(p, 6))
    router.close()


def test_router_respawn_budget_exhausts(tmp_path):
    """With the budget spent, a crash-looping replica stays DEAD — the
    pre-ISSUE-10 behavior is the floor, and the fleet keeps serving on
    the survivor."""
    from pytorchdistributed_tpu.faults.inject import (
        FaultInjector,
        FaultPlan,
    )
    from pytorchdistributed_tpu.faults.retry import RetryPolicy
    from pytorchdistributed_tpu.serving import DEAD

    model, params, _ = _setup()
    # every rejoined incarnation of replica 0 is crashed again
    inj = FaultInjector(FaultPlan.parse(
        "replica_crash@tick=3,replica=0; replica_crash@tick=40,replica=0;"
        " replica_crash@tick=80,replica=0"))
    router = ReplicaRouter(
        model, params, replicas=2,
        engine_kwargs=dict(num_slots=3, prefill_bucket=16),
        warmup_lens=(16,), faults=inj, respawn_budget=1, rejoin_after=1,
        respawn_policy=RetryPolicy(base_delay_s=0.0, jitter=0.0))
    router.warmup()
    prompts = _prompts(4)
    for wave in range(3):
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle()
        assert all(r.finish_reason == "length" for r in reqs), wave
        for _ in range(30):  # spin idle ticks so chaos + respawn fire
            router.step()
    s = router.summary()
    assert s["respawns"] == 1  # budget 1: the second death is final
    assert router._status[0] == DEAD
    router.close()
