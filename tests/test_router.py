"""Serving chaos suite: the replica router (serving/router.py, ISSUE 9).

Correctness bar (the acceptance's chaos parity pin): with a replica
killed MID-STREAM, every affected request's greedy token stream must be
BITWISE-identical to the same trace on an uninterrupted single engine —
the router's resume-from-tokens redispatch (submit(generated=...)
re-prefilling prompt+generated) composes with the engine's existing
bitwise-parity guarantees, so failover is invisible in the tokens. On
top: hang detection within the tick-bounded watchdog, NaN quarantine +
warmup rejoin, load shedding under overload with the router queue
bounded throughout, SIGTERM drain finishing resident streams with no
orphan replica, ZERO steady-state recompiles on survivors across a
failover, and seeded-sampling determinism across a failover.

Engine geometry mirrors tests/test_serving.py / test_paging.py (gpt2
"test", 2 layers, max_seq_len 64, slots 3, bucket 16, paged block 8) so
the compiled programs are shared across the suite's jit cache — the
whole file rides a handful of compiles.
"""

import dataclasses
import functools
import json
import os
import signal

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.faults.inject import (
    FaultInjector,
    FaultPlan,
)
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.serving import (
    DEAD,
    HEALTHY,
    QUARANTINED,
    ReplicaRouter,
    SamplingParams,
    ServingEngine,
)
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.engine import (
    decode_tick,
    params_finite,
    prefill_into_slot,
)

CFG = gpt2_config("test", num_layers=2, max_seq_len=64)


@functools.cache
def _setup():
    model = GPT2(CFG)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    return model, params, dm


def _ref(prompt, n):
    _, params, dm = _setup()
    return np.asarray(generate(dm, params, jnp.asarray(prompt)[None],
                               max_new_tokens=n))[0]


def _prompts(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
            for m in (5, 9, 7, 11, 6, 8, 4, 10)[:n]]


def _router(*, replicas=2, faults=None, paged=False, **kw):
    model, params, _ = _setup()
    ek = dict(num_slots=3, prefill_bucket=16)
    if paged:
        ek["block_size"] = 8
    router = ReplicaRouter(model, params, replicas=replicas,
                           engine_kwargs=ek, warmup_lens=(16, 32),
                           faults=faults, **kw)
    router.warmup()
    return router


# ----------------------------------------------------------------------
# fault-spec plumbing (no jax work)

def test_serving_fault_specs_parse_and_fire_once():
    plan = FaultPlan.parse(
        "replica_crash@tick=5,replica=0; replica_hang@tick=9; "
        "replica_nan@tick=3,replica=1")
    assert [s.describe() for s in plan.specs] == [
        "replica_crash@tick=5,replica=0", "replica_hang@tick=9",
        "replica_nan@tick=3,replica=1"]
    inj = FaultInjector(plan)
    assert inj.on_serving_tick(5, 0) == "replica_crash"
    assert inj.on_serving_tick(5, 0) is None        # one-shot
    assert inj.on_serving_tick(9, 2) == "replica_hang"  # any replica
    assert inj.on_serving_tick(3, 0) is None        # wrong replica
    assert inj.on_serving_tick(3, 1) == "replica_nan"
    with pytest.raises(ValueError, match="needs tick="):
        FaultPlan.parse("replica_crash@replica=0")
    with pytest.raises(ValueError, match="only apply to serving"):
        FaultPlan.parse("crash@step=2,tick=3")


# ----------------------------------------------------------------------
# engine satellite: resume-from-tokens

def test_engine_resume_from_tokens_dense_and_paged():
    """submit(generated=...) continues a greedy stream bitwise from any
    split point — the failover primitive, factored from the paged
    preempt-resume path and extended to the dense engine (whose prefill
    now carries the fold_in count as a dynamic arg)."""
    model, params, _ = _setup()
    prompt = _prompts(1)[0]
    full = _ref(prompt, 8)[prompt.size:]
    for paged in (False, True):
        kw = dict(block_size=8) if paged else {}
        engine = ServingEngine(model, params, num_slots=3,
                               prefill_bucket=16, **kw)
        engine.warmup(prompt_lens=(16, 32))
        for cut in (1, 4, 7):
            fresh = []
            r = engine.submit(prompt, max_new_tokens=8,
                              generated=full[:cut],
                              on_token=lambda _, t: fresh.append(t))
            engine.run_until_idle()
            assert r.finish_reason == "length"
            assert r.resumed_from == cut
            np.testing.assert_array_equal(
                r.output_ids, np.concatenate([prompt, full]),
                err_msg=f"paged={paged} cut={cut}")
            # only the continuation is DELIVERED — the client already
            # holds the resumed prefix
            assert fresh == list(full[cut:])
        # stream() honors the same contract: no prefix replay
        r = engine.submit(prompt, max_new_tokens=8, generated=full[:4])
        assert list(engine.stream(r)) == list(full[4:])
        engine.close()


def test_engine_resume_seeded_sampling_continues_stream():
    """A sampled stream resumed from tokens continues its seeded
    fold_in sequence exactly — deterministic-seed redispatch."""
    model, params, _ = _setup()
    prompt = _prompts(1)[0]
    sampling = SamplingParams(temperature=0.8, top_k=10, seed=123)
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16)
    engine.warmup(prompt_lens=(16, 32))
    a = engine.submit(prompt, max_new_tokens=8, sampling=sampling)
    engine.run_until_idle()
    b = engine.submit(prompt, max_new_tokens=8, sampling=sampling,
                      generated=a.new_tokens[:3])
    engine.run_until_idle()
    assert b.new_tokens == a.new_tokens
    with pytest.raises(ValueError, match="nothing left"):
        engine.submit(prompt, max_new_tokens=3, generated=[1, 2, 3])
    engine.close()


def test_engine_health_snapshot_and_finite_probe():
    model, params, _ = _setup()
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16)
    engine.warmup(prompt_lens=(16,))
    h = engine.health()
    assert h["alive"] and not h["sick"] and h["active"] == 0
    assert h["progress"] > 0  # warmup's compiled calls moved it
    p0 = h["progress"]
    engine.submit(_prompts(1)[0], max_new_tokens=3)
    engine.step()
    assert engine.health()["progress"] > p0
    assert engine.check_params_finite()
    good = engine._weights
    engine.set_params(jax.tree_util.tree_map(
        lambda x: (jnp.full_like(x, jnp.nan)
                   if jnp.issubdtype(x.dtype, jnp.inexact) else x), good))
    assert not engine.check_params_finite()
    assert engine.health()["sick"]
    engine.set_params(good)
    assert engine.check_params_finite()
    assert not engine.health()["sick"]
    engine.close()


# ----------------------------------------------------------------------
# chaos: crash mid-stream

def _assert_crash_parity(paged: bool):
    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=4,replica=0"))
    router = _router(faults=inj, paged=paged)
    prompts = _prompts(5)
    reqs = [router.submit(p, max_new_tokens=8) for p in prompts]
    router.run_until_idle()
    s = router.summary()
    assert s["replicas_lost"] == 1 and s["failovers"] == 1
    assert s["redispatched_requests"] >= 1
    assert s["failover_recovery_ticks"] is not None
    for p, r in zip(prompts, reqs):
        assert r.finish_reason == "length"
        np.testing.assert_array_equal(
            r.output_ids,
            np.concatenate([p, _ref(p, 8)[p.size:]]),
            err_msg=f"request {r.id} (replicas {r.replicas})")
    # at least one stream actually moved replicas mid-flight
    assert any(len(r.replicas) > 1 for r in reqs)
    router.close()  # survivors assert their pool-leak invariant


def test_crash_midstream_greedy_bitwise_dense():
    """THE chaos parity pin: kill replica 0 while it streams; every
    affected request is redispatched (prompt + generated re-prefilled on
    a survivor) and the delivered greedy stream is bitwise what an
    uninterrupted single engine produces."""
    _assert_crash_parity(paged=False)


def test_crash_midstream_greedy_bitwise_paged():
    """Same pin on PAGED replicas — failover composes with block-table
    paging, and the surviving engines' close() re-asserts the pool leak
    invariant after absorbing the redispatched load."""
    _assert_crash_parity(paged=True)


def test_retry_budget_exhausted_fails_request():
    """max_retries=0: a crash's victims are FAILED (finish_reason
    "failed", done=True, partial tokens retained) instead of retried —
    the budget bounds how many deaths one request may surf."""
    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=4,replica=0"))
    router = _router(faults=inj, max_retries=0)
    prompts = _prompts(5)
    reqs = [router.submit(p, max_new_tokens=8) for p in prompts]
    router.run_until_idle()
    s = router.summary()
    assert s["failed_requests"] >= 1
    failed = [r for r in reqs if r.finish_reason == "failed"]
    assert failed and all(r.done for r in reqs)
    ok = [r for r in reqs if r.finish_reason == "length"]
    for r in ok:
        np.testing.assert_array_equal(
            r.output_ids,
            np.concatenate([r.prompt, _ref(r.prompt, 8)[r.prompt.size:]]))
    router.close()


# ----------------------------------------------------------------------
# chaos: hang

def test_hang_detected_within_watchdog_bound():
    """A silently frozen replica (progress watermark stops while it
    holds streams) is declared hung within hang_ticks router ticks of
    the freeze, and its streams fail over losslessly."""
    hang_ticks = 4
    inj = FaultInjector(FaultPlan.parse("replica_hang@tick=3,replica=1"))
    router = _router(faults=inj, hang_ticks=hang_ticks)
    prompts = _prompts(5)
    reqs = [router.submit(p, max_new_tokens=8) for p in prompts]
    detected_at = None
    steps = 0
    while router.queue_depth or router.in_flight:
        router.step()
        steps += 1
        if detected_at is None and router._status[1] == DEAD:
            detected_at = router._ticks
        assert steps < 2000
    assert detected_at is not None, "hang never detected"
    assert detected_at <= 3 + hang_ticks + 1, detected_at
    s = router.summary()
    assert s["hangs_detected"] == 1
    for p, r in zip(prompts, reqs):
        np.testing.assert_array_equal(
            r.output_ids, np.concatenate([p, _ref(p, 8)[p.size:]]))
    router.close()


# ----------------------------------------------------------------------
# chaos: NaN quarantine + rejoin

def test_nan_replica_quarantined_then_rejoins_after_warmup():
    """Poisoned params trip the finite probe: the replica is
    quarantined (streams redispatched before any garbage token is
    delivered at health_every=1), probed while parked, and — once
    repaired — rejoined after a clean-probe streak plus a warmup canary
    run end-to-end. Traffic then flows to it again, still bitwise."""
    inj = FaultInjector(FaultPlan.parse("replica_nan@tick=4,replica=0"))
    router = _router(faults=inj, health_every=1, rejoin_after=2)
    prompts = _prompts(4)
    reqs = [router.submit(p, max_new_tokens=8) for p in prompts]
    repaired = False
    steps = 0
    while router.queue_depth or router.in_flight:
        router.step()
        steps += 1
        if not repaired and router._status[0] == QUARANTINED:
            router._replicas[0].restore_params()  # the operator's fix
            repaired = True
        assert steps < 2000
    assert repaired, "quarantine never happened"
    s = router.summary()
    assert s["quarantines"] == 1
    for p, r in zip(prompts, reqs):
        np.testing.assert_array_equal(
            r.output_ids, np.concatenate([p, _ref(p, 8)[p.size:]]),
            err_msg=f"request {r.id}")
    # keep ticking until the rejoin (probe streak + canary)
    for _ in range(50):
        if router._status[0] == HEALTHY:
            break
        router.step()
    assert router._status[0] == HEALTHY
    assert router.summary()["rejoins"] == 1
    again = [router.submit(p, max_new_tokens=4) for p in prompts]
    router.run_until_idle()
    assert 0 in {r._replica for r in again}, "rejoined replica unused"
    for p, r in zip(prompts, again):
        np.testing.assert_array_equal(
            r.output_ids, np.concatenate([p, _ref(p, 4)[p.size:]]))
    router.close()


# ----------------------------------------------------------------------
# load shedding

def test_shed_under_overload_keeps_queue_bounded():
    """A burst beyond capacity: excess submits are refused immediately
    with finish_reason "shed" (no tokens, no prefill paid), the router
    queue NEVER exceeds its bound (that is the p99-TTFT protection —
    admitted requests wait a bounded line, not an unbounded one), and
    every admitted request completes bitwise-correct."""
    router = _router(max_queue=2)
    prompts = _prompts(8, seed=3)
    reqs = []
    for p in prompts + prompts:          # 16 >> 2 replicas x (3+1) + 2
        reqs.append(router.submit(p, max_new_tokens=6))
        assert len(router._queue) <= 2
    shed = [r for r in reqs if r.finish_reason == "shed"]
    assert shed, "overload never shed"
    assert all(r.done and not r.tokens for r in shed)
    while router.queue_depth or router.in_flight:
        router.step()
        assert len(router._queue) <= 2
    s = router.summary()
    assert s["shed_requests"] == len(shed)
    assert s["shed_rate"] == round(len(shed) / len(reqs), 4)
    assert s["ttft_ms_p99"] is not None
    served = [r for r in reqs if r.finish_reason == "length"]
    assert len(served) == len(reqs) - len(shed)
    for r in served:
        np.testing.assert_array_equal(
            r.output_ids,
            np.concatenate([r.prompt, _ref(r.prompt, 6)[r.prompt.size:]]))
    router.close()


# ----------------------------------------------------------------------
# SIGTERM drain

def test_sigterm_drain_finishes_resident_streams_no_orphans():
    """The PR 4 no-orphans assertion pattern, router-shaped: SIGTERM →
    request_drain → the next step drains: resident streams FINISH
    (full budget, bitwise), queued ones are refused as "drained", and
    close() walks every replica's leak invariant — nothing is left
    holding blocks or slots."""
    router = _router()
    prev = signal.signal(signal.SIGTERM, signal.SIG_DFL)
    try:
        router.install_sigterm_drain()
        prompts = _prompts(5, seed=7)
        resident, queued = [], []
        for p in prompts:
            resident.append(router.submit(p, max_new_tokens=6))
        for _ in range(2):
            router.step()   # all five are placed and streaming
        os.kill(os.getpid(), signal.SIGTERM)
        assert router._draining     # handler ran, drain deferred
        queued.append(router.submit(prompts[0], max_new_tokens=6))
        router.step()               # performs the drain
        for r in resident:
            assert r.done and r.finish_reason == "length"
            np.testing.assert_array_equal(
                r.output_ids,
                np.concatenate([r.prompt,
                                _ref(r.prompt, 6)[r.prompt.size:]]))
        assert queued[0].finish_reason == "drained"
        assert router.in_flight == 0 and router.queue_depth == 0
        router.close()  # leak invariant on every replica
    finally:
        signal.signal(signal.SIGTERM, prev)


# ----------------------------------------------------------------------
# zero recompiles + determinism across failover

def test_zero_steadystate_recompiles_across_failover():
    """Surviving replicas absorb the redispatched load with ZERO
    retraces and ZERO recompiles: resume-from-tokens rides the warmed
    prefill buckets and the same tick program, and the health probe is
    compiled at warmup — TRACE_COUNTS and the pjit _cache_size are the
    tripwires, exactly like the engine's own steady-state guarantee."""
    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=4,replica=0"))
    router = _router(faults=inj)
    traces = dict(serving_engine.TRACE_COUNTS)
    sizes = (decode_tick._cache_size(), prefill_into_slot._cache_size(),
             params_finite._cache_size())
    prompts = _prompts(5)
    reqs = [router.submit(p, max_new_tokens=8) for p in prompts]
    router.run_until_idle()
    assert router.summary()["redispatched_requests"] >= 1
    assert all(r.finish_reason == "length" for r in reqs)
    assert dict(serving_engine.TRACE_COUNTS) == traces
    assert (decode_tick._cache_size(), prefill_into_slot._cache_size(),
            params_finite._cache_size()) == sizes
    router.close()


def test_seeded_sampling_determinism_across_failover():
    """Sampled streams are a function of (prompt, params, seed) alone —
    a mid-stream crash and redispatch reproduces the same tokens the
    single uninterrupted engine samples, because the resume prefill
    continues the per-token fold_in count where the victim stopped."""
    model, params, _ = _setup()
    prompts = _prompts(4, seed=5)
    sampling = [SamplingParams(temperature=0.8, top_k=10, seed=100 + i)
                for i in range(4)]
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16)
    engine.warmup(prompt_lens=(16, 32))
    want = []
    for p, s in zip(prompts, sampling):
        r = engine.submit(p, max_new_tokens=8, sampling=s)
        engine.run_until_idle()
        want.append(list(r.new_tokens))
    engine.close()

    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=4,replica=0"))
    router = _router(faults=inj)
    reqs = [router.submit(p, max_new_tokens=8, sampling=s)
            for p, s in zip(prompts, sampling)]
    router.run_until_idle()
    assert router.summary()["redispatched_requests"] >= 1
    assert [r.tokens for r in reqs] == want
    router.close()


# ----------------------------------------------------------------------
# telemetry + report

def test_router_telemetry_rows_and_report_table(tmp_path):
    """The router's JSONL stream carries per-replica rows, lifecycle
    event rows and the close-time summary; the report CLI renders the
    per-replica table with failover counts."""
    from pytorchdistributed_tpu.serving.telemetry import (
        ROUTER_METRICS_FILE,
    )
    from pytorchdistributed_tpu.telemetry.report import render

    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=4,replica=0"))
    router = _router(faults=inj, telemetry_dir=str(tmp_path), max_queue=2)
    prompts = _prompts(6, seed=9)
    for p in prompts + prompts:
        router.submit(p, max_new_tokens=6)
    router.run_until_idle()
    router.close()

    rows = [json.loads(x) for x in
            (tmp_path / ROUTER_METRICS_FILE.format(rank=0))
            .read_text().strip().splitlines()]
    kinds = {r["kind"] for r in rows}
    assert kinds == {"replica", "event", "router"}
    events = {r["event"] for r in rows if r["kind"] == "event"}
    assert {"replica_dead", "redispatch", "shed"} <= events
    summary = [r for r in rows if r["kind"] == "router"][-1]
    assert summary["failovers"] == 1
    assert summary["shed_requests"] >= 1
    assert summary["redispatched_requests"] >= 1
    assert len(summary["replica_occupancy"]) == 2

    report = render(str(tmp_path))
    assert "replica router" in report
    assert "dead" in report and "healthy" in report
    assert "redispatched" in report


# ----------------------------------------------------------------------
# subprocess mode (full tier: spawns real workers that import jax)

def test_subprocess_replicas_crash_failover_no_orphans(monkeypatch,
                                                      tmp_path):
    """The multi-host shape: replicas as run.py-env-contract subprocess
    workers, PTD_FAULTS crashing worker 0 from INSIDE (os._exit
    mid-protocol). The router sees the death, redispatches, the stream
    stays bitwise, and teardown leaves no orphan process."""
    import time

    from pytorchdistributed_tpu.faults import inject as faults_inject

    monkeypatch.setenv("PTD_FAULTS", "replica_crash@tick=4,replica=0")
    monkeypatch.setenv("PTD_FAULTS_STATE", str(tmp_path / "faults"))
    faults_inject.reset_active()
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1,
            "engine": {"num_slots": 2, "prefill_bucket": 16}}
    router = ReplicaRouter(workers=[spec, spec], warmup_lens=(16, 32),
                           faults=None)
    try:
        router.warmup()
        model = GPT2(CFG)
        params = jax.jit(model.init)(jax.random.key(1),
                                     jnp.zeros((1, 8), jnp.int32))
        dm = GPT2(dataclasses.replace(CFG, decode=True))
        prompts = _prompts(4)
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_steps=200000)
        assert router.summary()["replicas_lost"] == 1
        # the run.py liveness contract rode along: the surviving
        # worker's heartbeat file is fresh in the health snapshot
        age = router.health()[1].get("heartbeat_age_s")
        assert age is not None and age < 60.0, age
        for p, r in zip(prompts, reqs):
            ref = np.asarray(generate(dm, params, jnp.asarray(p)[None],
                                      max_new_tokens=6))[0]
            np.testing.assert_array_equal(r.output_ids, ref,
                                          err_msg=f"request {r.id}")
        procs = [rep.proc for rep in router._replicas]
    finally:
        router.close()
        faults_inject.reset_active()
    deadline = time.time() + 15
    while time.time() < deadline and any(p.poll() is None for p in procs):
        time.sleep(0.1)
    assert all(p.poll() is not None for p in procs), \
        [p.poll() for p in procs]
