"""Config/flag-system tests (SURVEY.md §5)."""

import numpy as np
import pytest

from pytorchdistributed_tpu.config import (
    PRESETS,
    ExperimentConfig,
    make_trainer,
    parse_cli,
)


def test_cli_overrides_and_presets():
    cfg = parse_cli(["--preset", "gpt2_medium_fsdp", "--model_size", "test",
                     "--batch_size", "4", "--remat", "false"])
    assert cfg.model == "gpt2"
    assert cfg.strategy == "fsdp"       # from the preset
    assert cfg.model_size == "test"     # flag overrides preset
    assert cfg.batch_size == 4
    assert cfg.remat is False           # bool flag override
    assert cfg.fsdp == -1


def test_defaults_roundtrip():
    cfg = parse_cli([])
    assert cfg == ExperimentConfig()


@pytest.mark.parametrize("preset", sorted(PRESETS))
def test_presets_construct(preset):
    """Every BASELINE preset must at least build (tiny overrides keep the
    CPU sim fast; vit multi-slice needs the hybrid mesh so it only builds
    the config here)."""
    overrides = ["--model_size", "test", "--dataset_size", "64",
                 "--seq_len", "32", "--image_size", "32",
                 "--num_classes", "10", "--batch_size", "8",
                 "--backend", "auto"]
    if preset == "vit_l16_multihost":
        overrides += ["--num_slices", "1"]  # 1 host in the test rig
    if preset == "resnet50_imagenet_dp":
        overrides += ["--model", "resnet18"]  # keep the smoke fast
    cfg = parse_cli(["--preset", preset] + overrides)
    trainer, loader = make_trainer(cfg)
    batch = next(iter(loader))
    m = trainer.train_step(batch)
    assert np.isfinite(float(m["loss"]))


def test_lr_schedules():
    from pytorchdistributed_tpu.config import make_lr_schedule

    # constant without warmup stays a plain float
    assert make_lr_schedule(ExperimentConfig(learning_rate=0.1)) == 0.1
    # warmup ramps 0 -> peak, then holds
    s = make_lr_schedule(ExperimentConfig(
        learning_rate=0.1, warmup_steps=10))
    assert float(s(0)) == 0.0
    assert float(s(10)) == pytest.approx(0.1)
    assert float(s(500)) == pytest.approx(0.1)
    # cosine decays to lr_end at the horizon
    s = make_lr_schedule(ExperimentConfig(
        learning_rate=0.1, lr_schedule="cosine", warmup_steps=10,
        decay_steps=100, lr_end=0.01))
    assert float(s(10)) == pytest.approx(0.1)
    assert float(s(100)) == pytest.approx(0.01)
    # linear hits the midpoint halfway through the decay span
    s = make_lr_schedule(ExperimentConfig(
        learning_rate=0.1, lr_schedule="linear", warmup_steps=10,
        decay_steps=110, lr_end=0.0))
    assert float(s(60)) == pytest.approx(0.05)
    with pytest.raises(ValueError, match="lr_schedule"):
        make_lr_schedule(ExperimentConfig(lr_schedule="exponential"))


def test_grad_clipping_bounds_update():
    import jax.numpy as jnp
    import optax

    from pytorchdistributed_tpu.config import make_optimizer

    opt = make_optimizer(ExperimentConfig(
        optimizer="sgd", learning_rate=1.0, grad_clip_norm=1.0))
    params = {"w": jnp.zeros(4)}
    huge = {"w": jnp.full(4, 1e6)}
    state = opt.init(params)
    updates, _ = opt.update(huge, state, params)
    # sgd(lr=1) with momentum: first update = -clipped grad
    norm = float(optax.global_norm(updates))
    assert norm == pytest.approx(1.0, rel=1e-5)


def test_preset_trains_with_warmup():
    """The GPT-2 preset (warmup-cosine + clip) actually steps: the first
    update is ~zero-LR, later ones move."""
    cfg = parse_cli(["--preset", "gpt2_medium_fsdp", "--model_size", "test",
                     "--dataset_size", "32", "--seq_len", "32",
                     "--batch_size", "8", "--bf16", "false"])
    trainer, loader = make_trainer(cfg)
    batch = next(iter(loader))
    l0 = float(trainer.train_step(batch)["loss"])
    l1 = float(trainer.train_step(batch)["loss"])
    # step 0 ran at lr≈0 (warmup), so the same batch's loss barely moves
    assert abs(l1 - l0) < 1e-3
    assert np.isfinite(l1)


def test_decay_mask_excludes_vectors():
    import jax

    from pytorchdistributed_tpu.config import decay_mask

    params = {"dense": {"kernel": np.zeros((4, 4)), "bias": np.zeros((4,))},
              "ln": {"scale": np.zeros((4,))},
              "embed": {"embedding": np.zeros((10, 4))}}
    mask = decay_mask(params)
    assert mask["dense"]["kernel"] and mask["embed"]["embedding"]
    assert not mask["dense"]["bias"] and not mask["ln"]["scale"]
    assert jax.tree.structure(mask) == jax.tree.structure(params)


def test_adafactor_optimizer_builds_and_steps():
    from pytorchdistributed_tpu.config import ExperimentConfig, make_trainer

    cfg = ExperimentConfig(model="mlp", optimizer="adafactor",
                           learning_rate=1e-3, batch_size=16,
                           dataset_size=64, backend="auto")
    trainer, loader = make_trainer(cfg)
    batch = next(iter(loader))
    assert np.isfinite(float(trainer.train_step(batch)["loss"]))


def test_masked_adamw_trains_via_preset():
    from pytorchdistributed_tpu.config import parse_cli, make_trainer

    cfg = parse_cli(["--model", "gpt2", "--model_size", "test",
                     "--seq_len", "32", "--batch_size", "8",
                     "--weight_decay", "0.1", "--backend", "auto",
                     "--dataset_size", "64"])
    trainer, loader = make_trainer(cfg)
    batch = next(iter(loader))
    l0 = float(trainer.train_step(batch)["loss"])
    for _ in range(2):
        m = trainer.train_step(batch)
    assert np.isfinite(float(m["loss"])) and float(m["loss"]) < l0
