"""Config/flag-system tests (SURVEY.md §5)."""

import numpy as np
import pytest

from pytorchdistributed_tpu.config import (
    PRESETS,
    ExperimentConfig,
    make_trainer,
    parse_cli,
)


def test_cli_overrides_and_presets():
    cfg = parse_cli(["--preset", "gpt2_medium_fsdp", "--model_size", "test",
                     "--batch_size", "4", "--remat", "false"])
    assert cfg.model == "gpt2"
    assert cfg.strategy == "fsdp"       # from the preset
    assert cfg.model_size == "test"     # flag overrides preset
    assert cfg.batch_size == 4
    assert cfg.remat is False           # bool flag override
    assert cfg.fsdp == -1


def test_defaults_roundtrip():
    cfg = parse_cli([])
    assert cfg == ExperimentConfig()


@pytest.mark.parametrize("preset", sorted(PRESETS))
def test_presets_construct(preset):
    """Every BASELINE preset must at least build (tiny overrides keep the
    CPU sim fast; vit multi-slice needs the hybrid mesh so it only builds
    the config here)."""
    overrides = ["--model_size", "test", "--dataset_size", "64",
                 "--seq_len", "32", "--image_size", "32",
                 "--num_classes", "10", "--batch_size", "8",
                 "--backend", "auto"]
    if preset == "vit_l16_multihost":
        overrides += ["--num_slices", "1"]  # 1 host in the test rig
    if preset == "resnet50_imagenet_dp":
        overrides += ["--model", "resnet18"]  # keep the smoke fast
    cfg = parse_cli(["--preset", preset] + overrides)
    trainer, loader = make_trainer(cfg)
    batch = next(iter(loader))
    m = trainer.train_step(batch)
    assert np.isfinite(float(m["loss"]))
