"""Continuous-batching serving engine tests (serving/).

Correctness bar (the ISSUE 3 acceptance): for ANY admission order, greedy
per-request outputs from the slot engine must be BITWISE-equal to
inference.generate()'s — one assertion that covers per-slot cache
indexing, position-counter rewinds after padded prefill, per-row RoPE /
learned-position offsets, GQA slot layout, the per-row attention mask and
the rank-mask sampler's greedy path all at once. On top: retirement /
readmission stress (more requests than slots), seeded-sampling
determinism across admission orders, the zero-recompile steady-state
guarantee, streaming delivery, and the telemetry bridge's file contract.
"""

import dataclasses
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import (
    GPT2,
    Llama,
    gpt2_config,
    llama_config,
)
from pytorchdistributed_tpu.serving import (
    SamplingParams,
    ServingEngine,
)
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.engine import (
    decode_tick,
    prefill_into_slot,
)


def _init(model, seed=1):
    return model.init(jax.random.key(seed), jnp.zeros((1, 4), jnp.int32))


def _mixed_requests(vocab, seed=0, n=5):
    rng = np.random.default_rng(seed)
    lens = [5, 9, 3, 13, 7, 11, 4, 8, 6][:n]
    news = [6, 3, 8, 5, 4, 7, 2, 5, 3][:n]
    prompts = [rng.integers(0, vocab, (m,)).astype(np.int32) for m in lens]
    return prompts, news


def _assert_parity(model_cls, cfg, *, num_slots, n_requests,
                   mesh=None, params=None, ref_params=None):
    """Engine outputs (staggered admissions, mixed lengths/budgets) must
    equal generate() per request, bitwise."""
    model = model_cls(cfg)
    params = params if params is not None else _init(model)
    ref_params = ref_params if ref_params is not None else params
    dm = model_cls(dataclasses.replace(cfg, decode=True))
    prompts, news = _mixed_requests(cfg.vocab_size, n=n_requests)
    engine = ServingEngine(model, params, num_slots=num_slots,
                           prefill_bucket=16, mesh=mesh)
    engine.warmup(prompt_lens=(8, 16))
    reqs = []
    for p, n in zip(prompts, news):
        reqs.append(engine.submit(p, max_new_tokens=n))
        engine.step()  # staggered: arrivals interleave with decoding
    engine.run_until_idle()
    for p, n, r in zip(prompts, news, reqs):
        ref = generate(dm, ref_params, jnp.asarray(p)[None],
                       max_new_tokens=n)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0],
                                      err_msg=f"request {r.id}")


def test_parity_greedy_gpt2():
    """Learned-position offsets + slot cache layout (quick-tier pick)."""
    _assert_parity(GPT2, gpt2_config("test", num_layers=2, max_seq_len=64),
                   num_slots=3, n_requests=5)


def test_parity_greedy_llama():
    """Per-row RoPE offsets + GQA slot cache layout."""
    _assert_parity(Llama, llama_config("test", max_seq_len=64),
                   num_slots=3, n_requests=5)


def test_parity_greedy_unrolled_layers():
    """scan_layers=False: per-layer (unstacked) cache leaves merge the
    same way."""
    _assert_parity(GPT2, gpt2_config("test", num_layers=2, max_seq_len=64,
                                     scan_layers=False),
                   num_slots=2, n_requests=4)


def test_parity_on_dp_mesh():
    """Engine under a data mesh: replicated params, same tokens."""
    from pytorchdistributed_tpu.runtime.mesh import create_mesh

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    _assert_parity(GPT2, cfg, num_slots=3, n_requests=4,
                   mesh=create_mesh(data=8))


def test_parity_on_tp_mesh():
    """Sharding is a deployment choice, not a code path (the serving
    restatement of test_generate_with_tensor_sharded_params): the engine
    with Megatron tensor-sharded params on a dp x tp mesh must emit
    exactly the tokens the unsharded engine/generate() emit."""
    import optax

    from pytorchdistributed_tpu.runtime.mesh import Axis, create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    cfg = llama_config("test", max_seq_len=64)
    model = Llama(cfg)
    params = _init(model)
    tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                 mesh=create_mesh(data=2, tensor=4), strategy="tp")
    big = np.tile(np.arange(8, dtype=np.int32)[None] % cfg.vocab_size,
                  (8, 1))
    tr.init({"tokens": big, "targets": big})
    shardings = jax.tree.map(lambda a: a.sharding, tr.state.params)
    sharded = jax.device_put(params, shardings)
    assert any(Axis.TENSOR in (e if isinstance(e, tuple) else (e,))
               for leaf in jax.tree.leaves(shardings)
               for e in tuple(leaf.spec))
    _assert_parity(Llama, cfg, num_slots=2, n_requests=3, mesh=tr.mesh,
                   params=sharded, ref_params=params)


def test_parity_paged_on_dp_mesh():
    """Sharding composes with paging (ISSUE 7): the PAGED engine under a
    data mesh — replicated params, host-stamped block tables entering
    the compiled tick as dynamic args — emits exactly the dense
    engine's / generate()'s tokens. (The single-host paged parity
    ladder lives in tests/test_paging.py, quick tier.)"""
    from pytorchdistributed_tpu.runtime.mesh import create_mesh

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    prompts, news = _mixed_requests(cfg.vocab_size, n=4)
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16,
                           block_size=8, mesh=create_mesh(data=8))
    engine.warmup(prompt_lens=(8, 16))
    reqs = []
    for p, n in zip(prompts, news):
        reqs.append(engine.submit(p, max_new_tokens=n))
        engine.step()
    engine.run_until_idle()
    for p, n, r in zip(prompts, news, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=n)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0],
                                      err_msg=f"request {r.id}")
    engine.close()


def test_retirement_readmission_stress():
    """More requests than slots: every slot retires and readmits several
    times (fresh prefill must fully overwrite the previous tenant's rows
    and rewind its counters), outputs still bitwise-equal per request."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    _assert_parity(GPT2, cfg, num_slots=2, n_requests=9)


def test_seeded_sampling_determinism():
    """Per-request sampled outputs are a function of (prompt, sampling
    params, seed) alone: resubmitting the same requests in a DIFFERENT
    order (different slots, different neighbors) reproduces each
    request's tokens exactly; a different seed moves them."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    prompts, news = _mixed_requests(cfg.vocab_size, n=4)
    sampling = [SamplingParams(temperature=0.8, top_k=10, seed=100 + i)
                for i in range(4)]

    def run(order):
        engine = ServingEngine(model, params, num_slots=2,
                               prefill_bucket=16)
        engine.warmup(prompt_lens=(16,))
        reqs = {}
        for i in order:
            reqs[i] = engine.submit(prompts[i], max_new_tokens=news[i],
                                    sampling=sampling[i])
            engine.step()
        engine.run_until_idle()
        return {i: list(r.new_tokens) for i, r in reqs.items()}

    a = run([0, 1, 2, 3])
    b = run([3, 1, 0, 2])
    assert a == b
    # a different seed must change the sampled continuation
    engine = ServingEngine(model, params, num_slots=2, prefill_bucket=16)
    engine.warmup(prompt_lens=(16,))
    r = engine.submit(prompts[0], max_new_tokens=news[0],
                      sampling=dataclasses.replace(sampling[0], seed=999))
    engine.run_until_idle()
    assert list(r.new_tokens) != a[0]


def test_zero_recompiles_steady_state():
    """The acceptance guarantee: after warmup, a mixed serving load (any
    prompt length within the bucket set, any sampling mix, retire +
    readmit) triggers ZERO retraces AND zero recompiles — TRACE_COUNTS
    catches retraces, the pjit _cache_size catches sharding-driven
    recompiles that never rerun the python body."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=3,
                           prefill_bucket=16)
    engine.warmup(prompt_lens=(8, 16))
    traces = dict(serving_engine.TRACE_COUNTS)
    sizes = (prefill_into_slot._cache_size(), decode_tick._cache_size())
    rng = np.random.default_rng(3)
    for i in range(8):
        sampling = (SamplingParams() if i % 2 else
                    SamplingParams(temperature=0.7, top_k=5, top_p=0.9,
                                   seed=i))
        engine.submit(rng.integers(0, cfg.vocab_size,
                                   (int(rng.integers(1, 16)),)),
                      max_new_tokens=int(rng.integers(1, 6)),
                      sampling=sampling)
        engine.step()
    engine.run_until_idle()
    assert dict(serving_engine.TRACE_COUNTS) == traces
    assert (prefill_into_slot._cache_size(),
            decode_tick._cache_size()) == sizes


def test_stop_ids_retire_and_stream():
    """A request retires the moment it emits ANY of its stop ids
    (finish_reason "stop", budget unused); streaming sees tokens in
    emission order, via callback and iterator alike."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, cfg.vocab_size, (6,)).astype(np.int32)
    ref = np.asarray(generate(dm, params, jnp.asarray(prompt)[None],
                              max_new_tokens=8))[0, 6:]
    stop = int(ref[3])  # the 4th greedy token doubles as a stop id

    engine = ServingEngine(model, params, num_slots=2, prefill_bucket=16)
    engine.warmup(prompt_lens=(16,))
    seen = []
    r = engine.submit(prompt, max_new_tokens=8, stop_ids=(stop, 10 ** 6),
                      on_token=lambda req, t: seen.append(t))
    engine.run_until_idle()
    assert r.finish_reason == "stop"
    # truncated at the FIRST emission of the stop id (which may precede
    # the position it was sampled from)
    cut = int(np.argmax(ref == stop)) + 1
    np.testing.assert_array_equal(r.new_tokens, ref[:cut])
    assert seen == r.new_tokens
    # iterator streaming drives the engine itself
    r2 = engine.submit(prompt, max_new_tokens=5)
    assert list(engine.stream(r2)) == r2.new_tokens
    assert r2.done and r2.finish_reason == "length"
    assert len(r2.new_tokens) == 5


def test_submit_validations():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=1)
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.submit(np.zeros(4, np.int32), max_new_tokens=0)
    with pytest.raises(ValueError, match="max_seq_len"):
        engine.submit(np.zeros(30, np.int32), max_new_tokens=10)
    with pytest.raises(ValueError, match="prompt"):
        engine.submit(np.zeros(0, np.int32), max_new_tokens=2)
    with pytest.raises(ValueError, match="num_slots"):
        ServingEngine(model, _init(model), num_slots=0)


def test_telemetry_bridge_files(tmp_path):
    """The telemetry bridge writes the serving metric JSONL (tick +
    request rows with TTFT / occupancy / queue depth) and dumps the span
    trace under the shared spans_rank*.trace.json contract on close."""
    from pytorchdistributed_tpu.serving.telemetry import SERVE_METRICS_FILE
    from pytorchdistributed_tpu.telemetry.spans import SPAN_TRACE_FILE

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=2,
                           prefill_bucket=16,
                           telemetry_dir=str(tmp_path))
    engine.warmup(prompt_lens=(16,))
    rng = np.random.default_rng(0)
    for _ in range(3):
        engine.submit(rng.integers(0, cfg.vocab_size, (5,)),
                      max_new_tokens=4)
    engine.run_until_idle()
    engine.close()

    metrics_path = tmp_path / SERVE_METRICS_FILE.format(rank=0)
    rows = [json.loads(x) for x in
            metrics_path.read_text().strip().splitlines()]
    kinds = {r["kind"] for r in rows}
    assert kinds == {"tick", "request"}
    reqs = [r for r in rows if r["kind"] == "request"]
    assert len(reqs) >= 3  # warmup requests logged too
    done = [r for r in reqs if r["new_tokens"] == 4]
    assert len(done) == 3
    assert all(r["ttft_ms"] > 0 for r in done)
    ticks = [r for r in rows if r["kind"] == "tick"]
    assert all(0 <= r["slot_occupancy"] <= 1 for r in ticks)
    assert all("queued" in r and "tick_ms" in r for r in ticks)

    trace = json.loads(
        (tmp_path / SPAN_TRACE_FILE.format(rank=0)).read_text())
    names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"serve/prefill", "serve/decode_tick"} <= names


def test_quantized_engine_matches_quantized_generate():
    """--quant int8_fwd composes: the engine's tick/prefill run the same
    quantized contractions generate() does, so greedy parity holds under
    the int8 policy too (the int8 HLO census is pinned separately in
    test_compiled_invariants)."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64,
                      quant="int8_fwd")
    _assert_parity(GPT2, cfg, num_slots=2, n_requests=3)


def test_deadline_expires_without_disturbing_other_slots(tmp_path):
    """ISSUE 4 satellite: per-request deadline_s. A request dead on the
    queue is shed before wasting a prefill; one expiring mid-decode is
    retired with the distinct "deadline" finish reason, both leave
    telemetry rows, and every OTHER slot keeps serving bitwise-correct
    tokens throughout."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    engine = ServingEngine(model, params, num_slots=2, prefill_bucket=16,
                           telemetry_dir=tmp_path)
    engine.warmup(prompt_lens=(8,))
    rng = np.random.default_rng(3)
    pa = rng.integers(0, cfg.vocab_size, (5,)).astype(np.int32)
    pb = rng.integers(0, cfg.vocab_size, (7,)).astype(np.int32)
    pc = rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)
    a = engine.submit(pa, max_new_tokens=6)
    b = engine.submit(pb, max_new_tokens=40, deadline_s=60.0)
    c = engine.submit(pc, max_new_tokens=4, deadline_s=0.0)  # dead on queue
    stats = engine.step()  # c shed pre-admission; a + b admitted
    assert stats["expired"] == 1
    assert c.done and c.finish_reason == "deadline" and not c.new_tokens
    assert c.slot is None  # never admitted, no prefill paid
    assert b.slot is not None and len(b.new_tokens) >= 1
    # lapse b's budget deterministically (no wall-clock sleep, no flake
    # under CI load): rewind its submission clock past the deadline
    b.submit_time -= 120.0
    engine.run_until_idle()
    assert b.done and b.finish_reason == "deadline"
    assert 0 < len(b.new_tokens) < 40  # delivered tokens stay delivered
    # the co-resident request was never disturbed: full budget, greedy
    # tokens bitwise-equal to generate()
    assert a.done and a.finish_reason == "length" and len(a.new_tokens) == 6
    ref = generate(dm, params, jnp.asarray(pa)[None], max_new_tokens=6)
    np.testing.assert_array_equal(a.output_ids, np.asarray(ref)[0])
    assert engine.summary()["deadline_expired"] == 2
    # the engine keeps admitting after expiries (slots were freed)
    d = engine.submit(pa, max_new_tokens=3)
    engine.run_until_idle()
    assert d.done and d.finish_reason == "length"
    engine.close()
    rows = [json.loads(x) for x in
            (tmp_path / "serve_metrics_rank0.jsonl")
            .read_text().strip().splitlines()]
    reasons = [r["finish_reason"] for r in rows if r["kind"] == "request"]
    assert reasons.count("deadline") == 2, reasons
