"""Telemetry subsystem tests (ISSUE 2): span tracer round-trip, the
StepAccounting join against hand-computed numbers, anomaly tripwires on
injected NaNs, and the run-report CLI end-to-end — all on the CPU sim."""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from pytorchdistributed_tpu._jax_compat import (
    supports_multiprocess_cpu_collectives,
)
from pytorchdistributed_tpu.telemetry import (
    AnomalyDetector,
    EventLog,
    SpanTracer,
    StepAccounting,
    merge_chrome_traces,
    peak_flops_for,
    read_events,
    summarize_new_events,
)
from pytorchdistributed_tpu.telemetry.accounting import (
    CPU_SIM_NOMINAL_PEAK_FLOPS,
)
from pytorchdistributed_tpu.telemetry.report import render
from pytorchdistributed_tpu.utils.hlo import collective_bytes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_needs_multiproc = pytest.mark.skipif(
    not supports_multiprocess_cpu_collectives(),
    reason="multi-process CPU collectives unimplemented in this jaxlib")


# ---------------------------------------------------------------------------
# span tracer


def test_span_tracer_chrome_roundtrip(tmp_path):
    """Spans dump as valid Chrome-trace JSON (X events, µs ts/dur, pid =
    rank) and merge across ranks onto one timeline."""
    for rank in (0, 1):
        tr = SpanTracer(rank=rank)
        with tr.span("data_load"):
            time.sleep(0.001)
        with tr.span("step_dispatch"):
            pass
        tr.dump(tmp_path / f"spans_rank{rank}.trace.json")

    raw = json.loads((tmp_path / "spans_rank0.trace.json").read_text())
    events = raw["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"data_load", "step_dispatch"}
    for e in xs:
        assert e["pid"] == 0 and e["dur"] >= 0 and e["ts"] > 0
    # the 1 ms sleep is visible in µs
    dl = next(e for e in xs if e["name"] == "data_load")
    assert dl["dur"] >= 1000
    # metadata names the rank process
    meta = [e for e in events if e["ph"] == "M"]
    assert any(e["args"]["name"] == "host rank 0" for e in meta)

    merged = merge_chrome_traces(tmp_path)
    assert {e["pid"] for e in merged["traceEvents"]
            if e["ph"] == "X"} == {0, 1}


def test_span_tracer_ring_buffer_bounds_memory():
    tr = SpanTracer(capacity=8, rank=0)
    for i in range(100):
        with tr.span(f"s{i}"):
            pass
    assert len(tr) == 8
    names = {e["name"] for e in tr.to_chrome_trace()["traceEvents"]
             if e["ph"] == "X"}
    assert names == {f"s{i}" for i in range(92, 100)}  # oldest evicted


def test_span_totals():
    tr = SpanTracer(rank=0)
    for _ in range(3):
        with tr.span("a"):
            pass
    totals = tr.totals()
    assert totals["a"][1] == 3 and totals["a"][0] >= 0


def test_span_overhead_under_budget():
    """The <1%-of-step-time acceptance: at log_every=10 the Trainer opens
    ~4 spans/step; even a 5 ms sim step grants 50 µs/step at 1%. Budget
    each span at 10 µs (measured ~1-2 µs here) with generous headroom
    for a loaded CI core."""
    tr = SpanTracer(capacity=4096, rank=0)
    n = 2000
    trials = []
    for _ in range(3):  # best-of-3: a scheduler preemption mid-window on
        t0 = time.perf_counter()  # a loaded CI core must not flake this
        for _ in range(n):
            with tr.span("x"):
                pass
        trials.append((time.perf_counter() - t0) / n)
    per_span = min(trials)
    assert per_span < 10e-6, f"span overhead {per_span * 1e6:.1f} µs"


# ---------------------------------------------------------------------------
# accounting


def test_collective_bytes_parses_shapes():
    hlo = textwrap.dedent("""\
        %all-reduce.1 = f32[16,8]{1,0} all-reduce(f32[16,8]{1,0} %dot.3), channel_id=2
        %all-reduce.2 = f32[] all-reduce(f32[] %reduce), channel_id=3
        %ag = (bf16[4,8]{1,0}, bf16[32,8]{1,0}) all-gather-start(bf16[4,8]{1,0} %p), dimensions={0}
        %agd = bf16[32,8]{1,0} all-gather-done((bf16[4,8]{1,0}, bf16[32,8]{1,0}) %ag)
        %cp = s8[128]{0} collective-permute(s8[128]{0} %x), source_target_pairs={{0,1}}
        %cps = (f32[64]{0}, f32[64]{0}, u32[], u32[]) collective-permute-start(f32[64]{0} %y), source_target_pairs={{0,1}}
        %ars = (f32[10]{0}, f32[20]{0}) all-reduce-start(f32[10]{0} %a, f32[20]{0} %b), channel_id=9
        %agv = ((f32[4]{0}, f32[6]{0}), (f32[16]{0}, f32[24]{0})) all-gather-start(f32[4]{0} %c, f32[6]{0} %d), dimensions={0}
        %fusion.9 = f32[16,8]{1,0} fusion(f32[16,8]{1,0} %p2, f32[16,8]{1,0} %all-reduce.1), kind=kLoop
    """)
    by_op = collective_bytes(hlo)
    # two sync all-reduces + the variadic -start whose tuple IS its
    # result set (both elements count)
    assert by_op["all-reduce"] == 16 * 8 * 4 + 4 + (10 + 20) * 4
    # all-gather-start staging tuples bill element [1] only: the result
    # array for the flat form, the nested result tuple for the variadic
    assert by_op["all-gather"] == 32 * 8 * 2 + (16 + 24) * 4
    # sync permute counts its array; the TPU async form's staging tuple
    # (operand, result, context u32[] tokens) bills element [1] — the
    # result — not the trailing 4-byte context token
    assert by_op["collective-permute"] == 128 + 64 * 4
    assert by_op["all-to-all"] == 0                    # -done never counted


def test_peak_flops_lookup():
    peak, src = peak_flops_for("TPU v5 lite")
    assert peak == 197e12 and src == "TPU v5 lite"
    peak, src = peak_flops_for("cpu", "cpu")
    assert peak == CPU_SIM_NOMINAL_PEAK_FLOPS and src == "cpu-sim-nominal"
    peak, src = peak_flops_for("TPU v99")
    assert peak is None and src.startswith("unknown")


def test_step_accounting_math_roundtrip(tmp_path):
    acct = StepAccounting(
        model_flops_per_step=2e11, comm_bytes_per_step=1024,
        comm_bytes_by_op={"all-reduce": 1024}, tokens_per_step=8192,
        samples_per_step=8, peak_flops_per_device=1e12,
        peak_source="cpu-sim-nominal", n_devices=8)
    # hand-computed: 2e11 flops in 0.5 s on a 1e12 peak = 40% MFU
    assert acct.mfu(0.5) == pytest.approx(0.4)
    assert acct.tokens_per_s(0.5) == pytest.approx(16384.0)
    assert acct.comm_bytes_per_s(0.5) == pytest.approx(2048.0)
    assert acct.mfu(0.0) is None
    acct.save(tmp_path / "accounting.json")
    assert StepAccounting.load(tmp_path / "accounting.json") == acct


def _mlp_trainer(telemetry_dir=None):
    import optax

    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    return Trainer(
        MLP(features=(16, 4)), optax.sgd(0.1), mse_loss,
        mesh=create_mesh(data=8), strategy="dp", log_every=2,
        watchdog=True,
        telemetry_dir=str(telemetry_dir) if telemetry_dir else None)


def _mlp_batch(nan=False):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 8)).astype(np.float32)
    if nan:
        x[0, 0] = np.nan
    return {"x": x, "y": rng.standard_normal((16, 4)).astype(np.float32)}


def test_step_accounting_mlp_hand_computed():
    """The 8-dev DDP MLP is small enough to account for by hand: the dp
    gradient all-reduces must move exactly the parameter bytes (W1 8x16 +
    b1 16 + W2 16x4 + b2 4 = 212 params x 4 B) plus the 4-byte scalar
    loss all-reduce; tokens = samples (no "tokens" leaf); MFU divides the
    cost-analysis flops by the sim's nominal peak."""
    trainer = _mlp_trainer()
    acct = trainer.step_accounting(_mlp_batch())
    param_bytes = (8 * 16 + 16 + 16 * 4 + 4) * 4
    assert acct.comm_bytes_by_op["all-reduce"] == param_bytes + 4
    assert acct.comm_bytes_per_step == param_bytes + 4
    assert acct.peak_source == "cpu-sim-nominal"
    assert acct.n_devices == 8
    assert acct.tokens_per_step == 16 and acct.samples_per_step == 16
    # flops are PER DEVICE (post-partitioning): per-device batch is
    # 16/8 = 2, fwd matmuls 2·b·(8·16+16·4), fwd+bwd ≥ 3x that
    assert acct.model_flops_per_step >= 3 * 2 * (16 // 8) * (8 * 16
                                                             + 16 * 4)
    assert acct.mfu(1.0) == pytest.approx(
        round(acct.model_flops_per_step / CPU_SIM_NOMINAL_PEAK_FLOPS, 4))


def test_step_accounting_counts_lm_tokens():
    from pytorchdistributed_tpu.telemetry.accounting import (
        _batch_tokens_samples,
    )

    tokens, samples = _batch_tokens_samples(
        {"tokens": np.zeros((4, 128), np.int32),
         "targets": np.zeros((4, 128), np.int32)})
    assert tokens == 512 and samples == 4


# ---------------------------------------------------------------------------
# events / tripwires


def test_anomaly_detector_non_finite_and_spike():
    det = AnomalyDetector(warmup=3, z_threshold=6.0)
    # warmup: steady loss, no events
    for step in range(5):
        assert det.check({"loss": 1.0 + 0.01 * step}, step=step) == []
    found = det.check({"loss": 100.0}, step=6)
    assert [k for k, _ in found] == ["loss_spike"]
    assert found[0][1]["z"] > 6.0
    found = det.check({"loss": float("nan"), "grad_norm": float("inf")},
                      step=7)
    kinds = sorted(k for k, _ in found)
    assert kinds == ["non_finite_metric", "non_finite_metric"]
    # a loss DROP is not an anomaly (one-sided tripwire)
    assert det.check({"loss": 0.0}, step=8) == []


def test_event_log_roundtrip_and_agent_summary(tmp_path):
    with EventLog(tmp_path / "events_rank1.jsonl", rank=1) as log:
        log.emit("loss_spike", step=30, z=7.1)
        log.emit("non_finite_metric", step=40, metric="loss", value="nan")
    events = read_events(tmp_path)
    assert [e.kind for e in events] == ["loss_spike", "non_finite_metric"]
    assert events[0].rank == 1 and events[0].step == 30
    assert events[0].data["z"] == 7.1
    offsets: dict = {}
    summary = summarize_new_events(tmp_path, offsets)
    assert "rank 1 loss_spike x1" in summary
    assert "rank 1 non_finite_metric x1" in summary
    # offsets advanced: a second sweep sees nothing new
    assert summarize_new_events(tmp_path, offsets) is None


class _FakeLoader:
    """Minimal loader protocol (set_epoch/len/batch_size/iter) over a
    fixed batch list."""

    def __init__(self, batches):
        self._batches = batches
        self.batch_size = batches[0]["x"].shape[0]

    def set_epoch(self, epoch):
        pass

    def __len__(self):
        return len(self._batches)

    def __iter__(self):
        return iter([dict(b) for b in self._batches])


def test_tripwires_fire_on_injected_nan_loss(tmp_path):
    """NaN batch → at log cadence the tripwire writes a durable
    non_finite_metric event BEFORE the watchdog raises; the report folds
    the event in afterwards (the post-mortem the watchdog alone never
    left behind)."""
    run_dir = tmp_path / "run"
    trainer = _mlp_trainer(run_dir)
    batches = [_mlp_batch(), _mlp_batch(nan=True)]  # log_every=2
    with pytest.raises(FloatingPointError):
        trainer.run_epoch(_FakeLoader(batches), epoch=0)
    events = read_events(run_dir)
    assert any(e.kind == "non_finite_metric" and e.data["metric"] == "loss"
               for e in events)
    # the exception path still dumped spans + flushed sinks (run_epoch
    # teardown): the report renders from a crashed run
    out = render(run_dir)
    assert "non_finite_metric" in out


# ---------------------------------------------------------------------------
# end-to-end: train with telemetry on, then report


def test_telemetry_smoke_end_to_end(tmp_path):
    """The quick-tier smoke: an 8-device DDP MLP run with telemetry on
    leaves a complete run dir — per-rank metrics with step time / MFU /
    comm-bytes, a valid span trace, accounting.json — and the report CLI
    renders all of it."""
    run_dir = tmp_path / "run"
    trainer = _mlp_trainer(run_dir)
    loader = _FakeLoader([_mlp_batch() for _ in range(8)])
    trainer.fit(loader, max_epochs=1)

    rows = [json.loads(line) for line in
            (run_dir / "metrics_rank0.jsonl").read_text().splitlines()]
    assert len(rows) == 4  # 8 steps, log_every=2
    tail = rows[-1]  # first rows may predate the meter warmup
    for key in ("loss", "samples_per_s", "step_time_s", "tokens_per_s",
                "mfu", "comm_bytes_per_step"):
        assert key in tail, (key, tail)
    assert tail["comm_bytes_per_step"] == 852  # MLP hand-computed value

    spans = json.loads(
        (run_dir / "spans_rank0.trace.json").read_text())["traceEvents"]
    names = {e["name"] for e in spans if e["ph"] == "X"}
    assert {"data_load", "h2d_transfer", "compile_and_dispatch",
            "step_dispatch", "metric_sync"} <= names

    assert (run_dir / "accounting.json").exists()
    out = render(run_dir)
    assert "step accounting" in out and "sim fallback" in out
    assert "tokens/s" in out and "mfu" in out and "comm" in out
    assert "tripwire events: none" in out
    assert "host spans" in out and "step_dispatch" in out


def test_report_step_time_fallback_spans_epochs():
    """Without step_time_s rows (no accounting), the report derives step
    time from row timestamps — and step numbers reset per epoch, so a
    2-epoch run must not divide by last-minus-first step."""
    from pytorchdistributed_tpu.telemetry.report import _derive_step_time

    rows = [{"time": 100.0, "epoch": 0, "step": 2},
            {"time": 102.0, "epoch": 0, "step": 4},
            {"time": 104.0, "epoch": 1, "step": 2},
            {"time": 106.0, "epoch": 1, "step": 4}]
    # 6s wall over 2 + 2 + 2 = 6 steps -> 1 s/step (naive s1-s0 would
    # see (4-2)=2 steps and report 3 s/step)
    assert _derive_step_time(rows) == pytest.approx(1.0)
    # a run ending on the same step number it started on still answers
    assert _derive_step_time(rows[1:3]) == pytest.approx(1.0)
    assert _derive_step_time(rows[:1]) is None
    # explicit step_time_s rows win over the derivation
    assert _derive_step_time(
        [dict(r, step_time_s=0.5) for r in rows]) == pytest.approx(0.5)


def test_bench_mfu_refuses_sim_peak():
    """bench.py's unlabeled analytic `mfu` field must mean real hardware:
    on the CPU sim _mfu answers None (the labeled accounting path is the
    sim's only MFU source)."""
    from bench import _mfu

    assert _mfu(1e12, 1.0) is None  # cpu device_kind not in peak table


def test_accounting_built_on_restored_trainer(tmp_path):
    """A trainer whose state arrived via restore() (a relaunched
    incarnation) must still build StepAccounting — the crash-recovery
    runs are exactly the ones telemetry post-mortems."""
    import optax

    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    ckpt = tmp_path / "ckpt"
    loader = _FakeLoader([_mlp_batch() for _ in range(4)])
    first = Trainer(MLP(features=(16, 4)), optax.sgd(0.1), mse_loss,
                    mesh=create_mesh(), checkpoint_dir=str(ckpt),
                    log_every=2, watchdog=False)
    first.fit(loader, max_epochs=1)

    run_dir = tmp_path / "run"
    resumed = Trainer(MLP(features=(16, 4)), optax.sgd(0.1), mse_loss,
                      mesh=create_mesh(), checkpoint_dir=str(ckpt),
                      log_every=2, watchdog=False,
                      telemetry_dir=str(run_dir))
    resumed.restore(_mlp_batch())
    assert resumed.accounting is None  # init() never ran
    resumed.run_epoch(loader, epoch=1)
    assert resumed.accounting is not None
    assert (run_dir / "accounting.json").exists()
    rows = [json.loads(line) for line in
            (run_dir / "metrics_rank0.jsonl").read_text().splitlines()]
    assert "mfu" in rows[-1] and "comm_bytes_per_step" in rows[-1]


def test_report_cli_subcommands(tmp_path):
    """Argument surface of `python -m pytorchdistributed_tpu.telemetry`:
    report renders an empty dir without crashing; merge-trace writes a
    merged chrome trace."""
    from pytorchdistributed_tpu.telemetry.__main__ import main

    tr = SpanTracer(rank=0)
    with tr.span("a"):
        pass
    tr.dump(tmp_path / "spans_rank0.trace.json")
    assert main(["report", str(tmp_path)]) == 0
    assert main(["merge-trace", str(tmp_path)]) == 0
    merged = json.loads((tmp_path / "merged.trace.json").read_text())
    assert any(e.get("name") == "a" for e in merged["traceEvents"])


@_needs_multiproc
def test_report_cli_two_process_run(tmp_path):
    """The acceptance scenario: a REAL 2-process CPU-sim training run
    (launched through the run.py agent with --telemetry-dir) leaves
    per-rank telemetry, and the report CLI prints a merged per-rank
    report with step time, tokens/s, MFU (sim fallback), comm-bytes/step
    and the tripwire section."""
    run_dir = tmp_path / "telemetry"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import optax
        from pytorchdistributed_tpu.data import (
            DataLoader, SyntheticTokenDataset)
        from pytorchdistributed_tpu.models import GPT2, gpt2_config
        from pytorchdistributed_tpu.runtime import dist
        from pytorchdistributed_tpu.runtime.mesh import create_mesh
        from pytorchdistributed_tpu.training import (
            Trainer, token_cross_entropy_loss)

        dist.init_process_group()
        cfg = gpt2_config("test", num_layers=2, max_seq_len=32,
                          vocab_size=128)
        ds = SyntheticTokenDataset(size=64, seq_len=32, vocab_size=128,
                                   seed=0)
        loader = DataLoader(ds, batch_size=8,
                            num_replicas=dist.get_world_size(),
                            rank=dist.get_rank())
        tr = Trainer(GPT2(cfg), optax.adamw(1e-3),
                     token_cross_entropy_loss, mesh=create_mesh(),
                     log_every=2, watchdog=False)
        assert tr.telemetry_dir is not None  # from PTD_TELEMETRY_DIR
        tr.fit(loader, max_epochs=1)
        dist.destroy_process_group()
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "2", "--devices-per-proc", "1",
         "--telemetry-dir", str(run_dir), str(script)],
        cwd=REPO, timeout=600, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr

    report = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.telemetry",
         "report", str(run_dir)],
        cwd=REPO, timeout=120, capture_output=True, text=True)
    assert report.returncode == 0, report.stderr
    out = report.stdout
    assert "ranks: 0, 1" in out
    assert "step time" in out and "tokens/s" in out and "mfu" in out
    assert "comm" in out and "sim fallback" in out
    assert "tripwire events" in out
    # both ranks logged real rows
    for rank in (0, 1):
        rows = (run_dir / f"metrics_rank{rank}.jsonl").read_text()
        assert "tokens_per_s" in rows and "comm_bytes_per_step" in rows


def test_report_merges_two_launched_ranks(tmp_path):
    """Ungated 2-process variant (this jaxlib cannot do cross-process CPU
    collectives, so the gated test above skips): two run.py-launched
    workers each train their own 4-device sim replica with telemetry from
    the env contract — per-rank files must NOT collide (the RANK-env
    fallback) and the report merges both ranks."""
    run_dir = tmp_path / "telemetry"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import optax
        from pytorchdistributed_tpu.models import MLP
        from pytorchdistributed_tpu.runtime.mesh import create_mesh
        from pytorchdistributed_tpu.training import Trainer, mse_loss

        class Loader:
            batch_size = 16
            def set_epoch(self, e): pass
            def __len__(self): return 6
            def __iter__(self):
                rng = np.random.default_rng(0)
                for _ in range(6):
                    yield {{"x": rng.standard_normal((16, 8)).astype(
                               np.float32),
                           "y": rng.standard_normal((16, 4)).astype(
                               np.float32)}}

        tr = Trainer(MLP(features=(16, 4)), optax.sgd(0.1), mse_loss,
                     mesh=create_mesh(), log_every=2, watchdog=False)
        assert tr.telemetry_dir is not None
        tr.fit(Loader(), max_epochs=1)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "2", "--devices-per-proc", "4",
         "--telemetry-dir", str(run_dir), str(script)],
        cwd=REPO, timeout=600, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    for rank in (0, 1):  # distinct per-rank files, no clobbering
        assert (run_dir / f"metrics_rank{rank}.jsonl").exists()
        assert (run_dir / f"spans_rank{rank}.trace.json").exists()
    report = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.telemetry",
         "report", str(run_dir)],
        cwd=REPO, timeout=120, capture_output=True, text=True)
    assert report.returncode == 0, report.stderr
    out = report.stdout
    assert "ranks: 0, 1" in out
    assert "step time" in out and "tokens/s" in out and "mfu" in out
    assert "comm" in out and "sim fallback" in out
    assert "tripwire events" in out


def test_run_agent_aggregates_events(tmp_path):
    """The run.py agent prints a per-incarnation tripwire summary next to
    its restart decisions when --telemetry-dir is set."""
    run_dir = tmp_path / "telemetry"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        from pytorchdistributed_tpu.telemetry import EventLog
        log = EventLog.from_env(rank=int(os.environ["RANK"]))
        assert log is not None, "agent did not export PTD_TELEMETRY_DIR"
        log.emit("loss_spike", step=10, z=8.5)
        log.close()
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "2", "--telemetry-dir", str(run_dir),
         "--monitor-interval", "0.1", str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "[run] telemetry:" in proc.stderr, proc.stderr
    assert "loss_spike x1" in proc.stderr, proc.stderr
