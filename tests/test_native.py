"""Native host-data-path tests (csrc/ptd_host.cc via ctypes)."""

import numpy as np
import pytest

from pytorchdistributed_tpu import _native
from pytorchdistributed_tpu.data import SyntheticImageDataset


def test_gather_matches_numpy():
    rng = np.random.default_rng(0)
    for dtype in (np.float32, np.int32, np.uint8):
        src = (rng.standard_normal((128, 7, 5)) * 100).astype(dtype)
        idx = rng.integers(0, 128, 33)
        np.testing.assert_array_equal(_native.gather(src, idx), src[idx])


def test_gather_bounds_checked():
    if not _native.native_available():
        pytest.skip("native library not built")
    src = np.zeros((4, 3), np.float32)
    with pytest.raises(IndexError):
        _native.gather(src, np.array([4]))
    with pytest.raises(IndexError):
        _native.gather(src, np.array([-1]))


def test_gather_bounds_identical_on_fallback_path():
    """Semantics must not depend on build state: the numpy fallback
    (non-contiguous src) rejects negative/oob indices exactly like the
    native path, instead of numpy's silent negative wrapping."""
    src = np.asfortranarray(np.zeros((4, 3), np.float32))
    assert not src.flags.c_contiguous
    with pytest.raises(IndexError):
        _native.gather(src, np.array([-1]))
    with pytest.raises(IndexError):
        _native.gather(src, np.array([4]))


def test_gather_non_contiguous_falls_back():
    src = np.asfortranarray(np.arange(24, dtype=np.float32).reshape(4, 6))
    idx = np.array([2, 0])
    np.testing.assert_array_equal(_native.gather(src, idx), src[idx])


def test_dataset_batch_uses_gather_path():
    ds = SyntheticImageDataset(size=64, image_size=8, seed=0)
    idx = np.array([5, 1, 63])
    batch = ds[idx]
    np.testing.assert_array_equal(batch["image"], ds.arrays["image"][idx])
    np.testing.assert_array_equal(batch["label"], ds.arrays["label"][idx])
