"""KV-cache generation tests (inference.generate).

Correctness bar: the cached decode path must reproduce the no-cache model
exactly — greedy generation is checked token-by-token against argmax of a
full decode=False forward pass over the generated sequence (this catches
cache indexing, RoPE offsets, learned-position offsets, GQA cache layout,
and mask bugs all at once)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorchdistributed_tpu.inference import (
    TRACE_COUNTS,
    generate,
    generate_bucketed,
)
from pytorchdistributed_tpu.models import (
    GPT2,
    Llama,
    gpt2_config,
    llama_config,
)


def _greedy_consistency(train_model, decode_model, vocab):
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, vocab, (2, 5)), jnp.int32)
    params = train_model.init(jax.random.key(1), prompt)

    out = generate(decode_model, params, prompt, max_new_tokens=8,
                   temperature=0.0)
    assert out.shape == (2, 13)
    np.testing.assert_array_equal(out[:, :5], prompt)

    # dense re-check: feeding the generated sequence through the normal
    # (uncached) model, every generated token must be the argmax of the
    # logits one position earlier
    logits = train_model.apply(params, out)
    want = jnp.argmax(logits[:, 4:-1].astype(jnp.float32), axis=-1)
    np.testing.assert_array_equal(out[:, 5:], want)


def test_gpt2_greedy_matches_dense():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32)
    _greedy_consistency(GPT2(cfg), GPT2(dataclasses.replace(cfg, decode=True)),
                        cfg.vocab_size)


def test_llama_greedy_matches_dense():
    """RoPE offsets + GQA cache layout under decode."""
    cfg = llama_config("test", max_seq_len=32)
    _greedy_consistency(Llama(cfg),
                        Llama(dataclasses.replace(cfg, decode=True)),
                        cfg.vocab_size)


def test_decode_attend_window_bounds_cost_not_output():
    """generate() bounds per-tick attention to the (128-rounded)
    prompt+new total (cfg.decode_attend_len) instead of max_seq_len. At
    max_seq_len=512 with a 13-token sequence the window is 128 — and the
    output must still match the uncached model exactly (RoPE params are
    max_seq_len-independent, so the same check as _greedy_consistency
    covers the windowed path)."""
    cfg = llama_config("test", max_seq_len=512)
    decode_model = Llama(dataclasses.replace(cfg, decode=True))
    _greedy_consistency(Llama(cfg), decode_model, cfg.vocab_size)


def test_decode_non_dense_attention_warns():
    """The training-time attention backend knob does not apply to decode;
    building a decode config with one must say so (ADVICE r2)."""
    with pytest.warns(UserWarning, match="attention"):
        gpt2_config("test", decode=True, attention="pallas")


def test_gpt2_unrolled_layers_decode():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32, scan_layers=False)
    _greedy_consistency(GPT2(cfg), GPT2(dataclasses.replace(cfg, decode=True)),
                        cfg.vocab_size)


def test_sampling_deterministic_and_in_range():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32, decode=True)
    model = GPT2(cfg)
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    params = model.init(jax.random.key(0), prompt[:, :1])
    kw = dict(max_new_tokens=6, temperature=0.8, top_k=10)
    a = generate(model, params, prompt, rng=jax.random.key(7), **kw)
    b = generate(model, params, prompt, rng=jax.random.key(7), **kw)
    c = generate(model, params, prompt, rng=jax.random.key(8), **kw)
    np.testing.assert_array_equal(a, b)
    assert ((a >= 0) & (a < cfg.vocab_size)).all()
    # different keys must change the sampled continuation (fixed seeds —
    # deterministic; a regression that ignores rng would make these equal)
    assert not np.array_equal(a, c)


def test_top_p_sampling():
    """Nucleus sampling: p→0 degenerates to greedy (only the max survives);
    moderate p is deterministic per key and in-vocab."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32, decode=True)
    model = GPT2(cfg)
    rng = np.random.default_rng(4)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    params = model.init(jax.random.key(0), prompt[:, :1])
    greedy = generate(model, params, prompt, max_new_tokens=6,
                      temperature=0.0)
    tiny_p = generate(model, params, prompt, max_new_tokens=6,
                      temperature=0.7, top_p=1e-9, rng=jax.random.key(1))
    np.testing.assert_array_equal(tiny_p, greedy)
    a = generate(model, params, prompt, max_new_tokens=6, temperature=0.9,
                 top_p=0.9, rng=jax.random.key(2))
    b = generate(model, params, prompt, max_new_tokens=6, temperature=0.9,
                 top_p=0.9, rng=jax.random.key(2))
    np.testing.assert_array_equal(a, b)
    assert ((a >= 0) & (a < cfg.vocab_size)).all()


def test_eos_freezes_rows():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32, decode=True)
    model = GPT2(cfg)
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    params = model.init(jax.random.key(0), prompt[:, :1])
    first = generate(model, params, prompt, max_new_tokens=8, temperature=0.0)
    eos = int(first[0, 4])  # whatever greedy emits first becomes "eos"
    out = generate(model, params, prompt, max_new_tokens=8, temperature=0.0,
                   eos_id=eos)
    assert (np.asarray(out[0, 4:]) == eos).all()


def test_eos_in_prompt_is_inert():
    """A prompt that happens to contain eos_id must pass through intact —
    prefill is not sampling, so it can't trip the done latch."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32, decode=True)
    model = GPT2(cfg)
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 6)), jnp.int32)
    params = model.init(jax.random.key(0), prompt[:, :1])
    eos = int(prompt[0, 2])  # mid-prompt token doubles as eos
    out = generate(model, params, prompt, max_new_tokens=4, temperature=0.0,
                   eos_id=eos)
    np.testing.assert_array_equal(out[:, :6], prompt)
    ref = generate(model, params, prompt, max_new_tokens=4, temperature=0.0)
    # generation proceeds identically until (if ever) eos is emitted
    gen, ref_gen = np.asarray(out[0, 6:]), np.asarray(ref[0, 6:])
    stop = np.argmax(ref_gen == eos) if (ref_gen == eos).any() else len(ref_gen)
    np.testing.assert_array_equal(gen[:stop], ref_gen[:stop])


def test_stop_id_sequence():
    """eos_id accepts a SEQUENCE of stop ids (tokenizers commonly have
    several): any of them freezes a row, frozen rows keep emitting the
    first id, and a singleton sequence behaves exactly like the scalar."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32, decode=True)
    model = GPT2(cfg)
    rng = np.random.default_rng(6)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    params = model.init(jax.random.key(0), prompt[:, :1])
    free = np.asarray(generate(model, params, prompt, max_new_tokens=8,
                               temperature=0.0))
    stop_a, stop_b = int(free[0, 5]), int(free[1, 6])  # mid-run tokens
    out = np.asarray(generate(model, params, prompt, max_new_tokens=8,
                              temperature=0.0, eos_id=[stop_a, stop_b]))
    # row 0 froze at its stop and pads with the FIRST id of the set
    cut0 = int(np.argmax(free[0, 4:] == stop_a))
    np.testing.assert_array_equal(out[0, 4:4 + cut0 + 1],
                                  free[0, 4:4 + cut0 + 1])
    assert (out[0, 4 + cut0:] == stop_a).all()
    # row 1 froze on the OTHER id of the set
    cut1 = int(np.argmax(free[1, 4:] == stop_b))
    assert out[1, 4 + cut1] == stop_b
    assert (out[1, 5 + cut1:] == stop_a).all()
    # singleton sequence == scalar (same compiled program key)
    one = generate(model, params, prompt, max_new_tokens=8,
                   temperature=0.0, eos_id=stop_a)
    seq = generate(model, params, prompt, max_new_tokens=8,
                   temperature=0.0, eos_id=(stop_a,))
    np.testing.assert_array_equal(np.asarray(one), np.asarray(seq))


def test_bucketed_matches_generate_bitwise():
    """generate_bucketed pads prompt AND rounds max_new_tokens up to the
    bucket, yet the returned tokens are bitwise-equal to exact-shape
    generate() — greedy and seeded-sampling alike (pad rows sit beyond
    the position mask until decode overwrites them; masked attention
    contributes exact zeros)."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=512)
    model = GPT2(cfg)
    rng = np.random.default_rng(7)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    for L, n in [(5, 8), (17, 3), (33, 40)]:
        p = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, L)), jnp.int32)
        ref = generate(dm, params, p, max_new_tokens=n)
        got = generate_bucketed(dm, params, p, max_new_tokens=n, bucket=64)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    p = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 9)), jnp.int32)
    kw = dict(max_new_tokens=6, temperature=0.8, top_k=10,
              rng=jax.random.key(3))
    ref = generate(dm, params, p, **kw)
    got = generate_bucketed(dm, params, p, bucket=64, **kw)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_bucketed_trace_count_regression():
    """The retrace tripwire: many distinct (prompt_len, max_new_tokens)
    pairs inside one bucket pair must compile exactly ONE padded program
    (generate() would have compiled one per pair), and repeat calls
    compile nothing. max_seq_len 384 is unique to this test on purpose:
    jit caches by config, so sharing another test's config would let ITS
    compiles absorb ours and zero the delta."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=384)
    model = GPT2(cfg)
    rng = np.random.default_rng(8)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    before = TRACE_COUNTS["generate_padded"]
    for L, n in [(3, 2), (11, 7), (29, 13), (64, 64), (40, 1)]:
        p = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, L)), jnp.int32)
        out = generate_bucketed(dm, params, p, max_new_tokens=n, bucket=64)
        assert out.shape == (2, L + n)
    assert TRACE_COUNTS["generate_padded"] - before == 1
    # a second bucket pair is a second (and final) program
    p = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 70)), jnp.int32)
    generate_bucketed(dm, params, p, max_new_tokens=80, bucket=64)
    generate_bucketed(dm, params, p[:, :65], max_new_tokens=66, bucket=64)
    assert TRACE_COUNTS["generate_padded"] - before == 2


def test_bucketed_fallback_when_bucket_overflows_context():
    """When the rounded shapes cannot fit max_seq_len the wrapper falls
    back to the exact-shape program (correctness over retrace thrift)."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=16, decode=True)
    model = GPT2(cfg)
    rng = np.random.default_rng(9)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 10)), jnp.int32)
    params = model.init(jax.random.key(0), prompt[:, :1])
    ref = generate(model, params, prompt, max_new_tokens=6)
    got = generate_bucketed(model, params, prompt, max_new_tokens=6,
                            bucket=128)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_generate_with_tensor_sharded_params():
    """Sharding is a deployment choice, not a code path: generate() with
    Megatron tensor-sharded params on a dp x tp mesh must emit exactly the
    tokens the unsharded model emits (the decode einsums partition under
    the same logical rules the training step uses)."""
    import optax

    from pytorchdistributed_tpu.runtime.mesh import Axis, create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    cfg = llama_config("test", max_seq_len=32)
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(1, cfg.vocab_size, (2, 6)), jnp.int32)
    model = Llama(cfg)
    params = model.init(jax.random.key(1), prompt)
    dm = Llama(dataclasses.replace(cfg, decode=True))
    ref = generate(dm, params, prompt, max_new_tokens=5, temperature=0.0)

    tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                 mesh=create_mesh(data=2, tensor=4), strategy="tp")
    big = np.tile(np.asarray(prompt), (4, 1))
    tr.init({"tokens": big, "targets": big})
    shardings = jax.tree.map(lambda a: a.sharding, tr.state.params)
    sharded = jax.device_put(params, shardings)
    spec = tuple(jax.tree.leaves(shardings)[0].spec)  # proves it's sharded
    assert any(Axis.TENSOR in (e if isinstance(e, tuple) else (e,))
               for leaf in jax.tree.leaves(shardings)
               for e in tuple(leaf.spec)), spec
    with jax.set_mesh(tr.mesh):
        out = generate(dm, sharded, prompt, max_new_tokens=5, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_generate_validations():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=8)
    model = GPT2(cfg)
    prompt = jnp.zeros((1, 4), jnp.int32)
    params = model.init(jax.random.key(0), prompt)
    with pytest.raises(ValueError, match="decode"):
        generate(model, params, prompt, max_new_tokens=2)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(dm, params, prompt, max_new_tokens=100)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(dm, params, prompt, max_new_tokens=0)
    with pytest.raises(ValueError, match="pipeline"):
        gpt2_config("test", decode=True, pipeline_stages=2)


def test_generate_exactly_fills_max_seq_len():
    """prompt_len + max_new_tokens == max_seq_len is legal: the last cache
    write lands on the final slot, one token past raises."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=16, decode=True)
    model = GPT2(cfg)
    prompt = jnp.asarray(np.arange(8)[None] % cfg.vocab_size, jnp.int32)
    params = model.init(jax.random.key(0), prompt[:, :1])
    out = generate(model, params, prompt, max_new_tokens=8, temperature=0.0)
    assert out.shape == (1, 16)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, max_new_tokens=9)
