"""Int8 quantized-training tests (ops/quant.py, ISSUE 1).

Two bars, mirroring the suite's loss-curve-equivalence discipline:

  * unit numerics — the quantized ``dot_general`` is EXACT for
    power-of-two-scaled inputs (per-channel scales hit representable
    grids), the ``int8_fwd`` backward is bit-identical to the reference
    dot's VJP (it runs on the saved full-precision operands), stochastic
    rounding is unbiased;
  * training parity — ``--quant int8_fwd`` reproduces the bf16 loss curve
    on the small GPT-2/MLP configs across dp, fsdp and tp on the 8-device
    CPU sim within ``PARITY_TOL`` nats (the documented tolerance for the
    acceptance criterion: same data, same init, 8 steps at lr 1e-2 —
    measured deltas sit at 0.003-0.11, the bound leaves ~2x headroom while
    still catching a wrong-scale or wrong-transpose bug, which blows the
    curve apart immediately).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

from pytorchdistributed_tpu.ops.quant import (
    absmax_scale,
    dot_general_for,
    quantized_dot_general,
    stochastic_quantize,
)

# documented acceptance tolerance: |final bf16 loss - final int8_fwd loss|
# after 8 steps on the test-width configs (see module docstring)
PARITY_TOL = 0.25

_2D = (((1,), (0,)), ((), ()))


class TestQuantDot:
    def test_power_of_two_exact(self):
        """Per-channel scales make the int8 dot EXACT when every channel
        is integers in [-127, 127] times a power-of-two scale: absmax/127
        is then itself a power of two, quantization is lossless, the int32
        contraction is exact, and the fp32 rescale multiplies by exact
        powers of two (ISSUE 1 satellite)."""
        rng = np.random.default_rng(0)
        kx = rng.integers(-3, 4, (16, 1)).astype(np.float32)
        xv = rng.integers(-127, 128, (16, 64)).astype(np.float32)
        xv[:, 0] = 127  # pin each row's absmax to the full code range
        x = jnp.asarray(xv * 2.0 ** kx)
        kw = rng.integers(-3, 4, (1, 8)).astype(np.float32)
        wv = rng.integers(-127, 128, (64, 8)).astype(np.float32)
        wv[0, :] = 127
        w = jnp.asarray(wv * 2.0 ** kw)
        out = quantized_dot_general("int8_fwd")(x, w, _2D)
        ref = lax.dot_general(x, w, _2D)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_close_to_fp_reference(self):
        """Random gaussians: int8 with per-channel scales lands within ~2%
        relative error of the fp32 dot (the expected quantization noise
        level — a wrong scale axis is an order of magnitude off)."""
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((128, 32)), jnp.float32)
        out = quantized_dot_general("int8_fwd")(x, w, _2D)
        ref = lax.dot_general(x, w, _2D)
        rel = float(jnp.abs(out - ref).max() / jnp.abs(ref).max())
        assert rel < 0.02, rel

    def test_int8_fwd_backward_is_reference_vjp(self):
        """mode="int8_fwd" saves the UNquantized operands and runs the
        ordinary dot VJP on them — gradients must equal the reference
        dot's exactly (bit-for-bit, same dtypes)."""
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((16, 3, 24)), jnp.bfloat16)
        dg = quantized_dot_general("int8_fwd")

        def loss(dot):
            return lambda x, k: jnp.einsum(
                "bse,ecf->bscf", x, k, _dot_general=dot
            ).astype(jnp.float32).sum()

        gx, gk = jax.grad(loss(dg), argnums=(0, 1))(x, k)
        rx, rk = jax.grad(loss(lax.dot_general), argnums=(0, 1))(x, k)
        np.testing.assert_array_equal(np.asarray(gx), np.asarray(rx))
        np.testing.assert_array_equal(np.asarray(gk), np.asarray(rk))

    def test_int8_backward_close(self):
        """mode="int8" quantizes both grad contractions (stochastic
        rounding on the cotangent): grads land within int8 noise of the
        reference — and the transpose bookkeeping (_grad_dims) is
        exercised on a non-identity permutation (contraction over lhs
        dim 0)."""
        rng = np.random.default_rng(3)
        for dims, xs, ws in [
            (_2D, (16, 32), (32, 8)),
            ((((0,), (0,)), ((), ())), (32, 16), (32, 8)),
        ]:
            x = jnp.asarray(rng.standard_normal(xs), jnp.float32)
            w = jnp.asarray(rng.standard_normal(ws), jnp.float32)
            g8 = jax.grad(
                lambda x, w: quantized_dot_general("int8")(
                    x, w, dims).sum(), argnums=(0, 1))(x, w)
            gr = jax.grad(
                lambda x, w: lax.dot_general(x, w, dims).sum(),
                argnums=(0, 1))(x, w)
            for a, b in zip(g8, gr):
                rel = float(jnp.abs(a - b).max()
                            / jnp.maximum(jnp.abs(b).max(), 1e-6))
                assert rel < 0.05, (dims, rel)

    def test_stochastic_rounding_unbiased(self):
        """E[dequantize(sr_quantize(x))] = x: over a dense value sweep the
        mean rounding error stays < 1e-3 of one quantum — ~5 standard
        errors at N=2e6 (SE = sqrt(1/12)/sqrt(N) ≈ 2e-4) plus the hash
        mixer's measured ~3e-4 residual non-ideality. Round-to-nearest
        has no such bound at ±0.5 fractional offsets — the systematic
        bias SR exists to kill is O(0.5) there."""
        rng = np.random.default_rng(4)
        y = jnp.asarray(rng.uniform(0, 100, (2_000_000,)), jnp.float32)
        scale = jnp.float32(100.0 / 127.0)
        deq = stochastic_quantize(y, scale).astype(jnp.float32) * scale
        bias = float((deq - y).mean()) / float(scale)
        assert abs(bias) < 1e-3, bias

    def test_scale_shapes_per_channel(self):
        x = jnp.ones((4, 8, 16))
        assert absmax_scale(x, (2,)).shape == (4, 8, 1)
        assert absmax_scale(x, (0, 1)).shape == (1, 1, 16)

    def test_preferred_element_type_and_promotion(self):
        x = jnp.ones((4, 8), jnp.bfloat16)
        w = jnp.ones((8, 2), jnp.bfloat16)
        dg = quantized_dot_general("int8_fwd")
        assert dg(x, w, _2D).dtype == jnp.bfloat16
        assert dg(x, w, _2D,
                  preferred_element_type=jnp.float32).dtype == jnp.float32

    def test_batch_dims_rejected(self):
        x = jnp.ones((2, 4, 8))
        w = jnp.ones((2, 8, 3))
        with pytest.raises(NotImplementedError):
            quantized_dot_general("int8")(
                x, w, (((2,), (1,)), ((0,), (0,))))

    def test_mode_validation(self):
        with pytest.raises(ValueError):
            quantized_dot_general("int4")
        assert dot_general_for("none") is None
        assert dot_general_for(None) is None
        # cached: every call site shares one callable per mode (jit/flax
        # caches key on identity)
        assert (quantized_dot_general("int8_fwd")
                is quantized_dot_general("int8_fwd"))


# ---------------------------------------------------------------------------
# training parity (the ISSUE 1 acceptance criterion)
# ---------------------------------------------------------------------------


def _train_losses(strategy, axes, quant, steps=8):
    """8 steps on one repeated batch through config.make_trainer — the
    full --quant flag wiring (ExperimentConfig → TransformerConfig.quant +
    Policy.int8_fwd) is what's under test, not a hand-built Trainer."""
    from pytorchdistributed_tpu.config import ExperimentConfig, make_trainer

    cfg = ExperimentConfig(
        model="gpt2", model_size="test", strategy=strategy, quant=quant,
        seq_len=32, batch_size=8, dataset_size=64, learning_rate=1e-2,
        seed=0, watchdog=False, **axes)
    trainer, loader = make_trainer(cfg)
    batch = next(iter(loader))
    return [float(trainer.train_step(batch)["loss"]) for _ in range(steps)]


def _assert_parity(strategy, axes):
    bf16 = _train_losses(strategy, axes, "none")
    int8 = _train_losses(strategy, axes, "int8_fwd")
    assert int8[-1] < int8[0], f"{strategy}: int8_fwd did not learn {int8}"
    assert bf16[-1] < bf16[0], f"{strategy}: bf16 did not learn {bf16}"
    delta = abs(bf16[-1] - int8[-1])
    assert delta < PARITY_TOL, (
        f"{strategy}: |bf16 - int8_fwd| final-loss delta {delta:.4f} "
        f"exceeds the documented tolerance {PARITY_TOL} "
        f"(bf16 {bf16}, int8_fwd {int8})")


def test_parity_dp():
    _assert_parity("dp", {})


def test_parity_fsdp():
    _assert_parity("fsdp", dict(data=2, fsdp=4))


def test_parity_tp():
    _assert_parity("tp", dict(data=2, tensor=4))


def test_mlp_parity_dp():
    """The MLP toy through Policy.dot_general() (the non-transformer
    injection path): quantized regression training tracks bf16."""
    import optax

    from pytorchdistributed_tpu.data import SyntheticRegressionDataset
    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.parallel import Policy
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    ds = SyntheticRegressionDataset(64, seed=0)
    batch = ds[np.arange(32)]

    def run(policy):
        model = MLP(dot_general=policy.dot_general())
        tr = Trainer(model, optax.adamw(1e-2), mse_loss,
                     mesh=create_mesh(), strategy="dp", watchdog=False)
        return [float(tr.train_step(batch)["loss"]) for _ in range(8)]

    bf16 = run(Policy.bf16())
    int8 = run(Policy.int8_fwd())
    assert int8[-1] < int8[0]
    assert abs(bf16[-1] - int8[-1]) < PARITY_TOL, (bf16, int8)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_parity_pipeline(schedule):
    """Quant x pipeline parallelism: the README claims every strategy picks
    the int8 operands up unmodified, so the pipeline schedules need the
    same parity evidence as dp/fsdp/tp. Gated like the rest of the
    pipeline suite (partial-auto shard_map)."""
    from pytorchdistributed_tpu._jax_compat import (
        supports_partial_auto_shard_map,
    )

    if not supports_partial_auto_shard_map():
        pytest.skip("pipeline schedules need partial-auto shard_map "
                    "(axis_names ⊂ mesh axes), unsupported by this jax")
    import dataclasses

    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    rng = np.random.default_rng(9)
    batch = {
        "tokens": rng.integers(0, 128, (16, 32)).astype(np.int32),
        "targets": rng.integers(0, 128, (16, 32)).astype(np.int32),
    }
    cfg = gpt2_config("test", num_layers=4, pipeline_stages=4,
                      pipeline_microbatches=4, pp_schedule=schedule)

    def run(quant):
        model = GPT2(dataclasses.replace(cfg, quant=quant))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(data=2, pipe=4), strategy="dp",
                     watchdog=False)
        return [float(tr.train_step(batch)["loss"]) for _ in range(8)]

    bf16, int8 = run("none"), run("int8_fwd")
    assert int8[-1] < int8[0], int8
    assert abs(bf16[-1] - int8[-1]) < PARITY_TOL, (bf16, int8)


def test_bert_vit_quant_configs_train():
    """The other two transformer families (bench.py now honors PTD_QUANT
    for them too): one quantized step each, finite and learning-shaped."""
    import optax

    from pytorchdistributed_tpu.data import MLMDataset, SyntheticTokenDataset
    from pytorchdistributed_tpu.models import (
        BertMLM,
        ViT,
        bert_config,
        vit_config,
    )
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        cross_entropy_loss,
        token_cross_entropy_loss,
    )

    rng = np.random.default_rng(3)
    bcfg = bert_config("test", quant="int8_fwd")
    ds = MLMDataset(SyntheticTokenDataset(16, 32, bcfg.vocab_size, 0),
                    bcfg.vocab_size, seed=0)
    tr = Trainer(BertMLM(bcfg), optax.adamw(1e-3),
                 token_cross_entropy_loss, mesh=create_mesh(),
                 strategy="dp", watchdog=False)
    losses = [float(tr.train_step(ds[np.arange(16)])["loss"])
              for _ in range(3)]
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses

    vcfg = vit_config("test", image_size=32, num_classes=10,
                      quant="int8_fwd")
    tr = Trainer(ViT(vcfg), optax.adamw(1e-3), cross_entropy_loss,
                 mesh=create_mesh(), strategy="dp", watchdog=False)
    batch = {
        "image": rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
        "label": rng.integers(0, 10, (16,)).astype(np.int32),
    }
    losses = [float(tr.train_step(batch)["loss"]) for _ in range(3)]
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses


def test_int8_full_mode_trains():
    """mode="int8" (quantized backward + stochastic rounding): the loss
    still decreases and stays finite — the convergence smoke for the
    aggressive mode (parity vs bf16 is only claimed for int8_fwd)."""
    losses = _train_losses("dp", {}, "int8", steps=10)
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses


def test_quant_flag_validation():
    from pytorchdistributed_tpu.config import ExperimentConfig, _build_model
    from pytorchdistributed_tpu.models import gpt2_config

    with pytest.raises(ValueError, match="quant"):
        _build_model(ExperimentConfig(model="gpt2", model_size="test",
                                      quant="int7"))
    with pytest.raises(ValueError, match="quant"):
        gpt2_config("test", quant="fp8")


def test_quant_preserves_tp_sharding():
    """Sharding annotations survive quantization: under TP the quantized
    model's MLP kernel still splits over the tensor axis (the int8
    converts are elementwise — the partitioner shards them like the bf16
    operands they replace)."""
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import Axis, create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    rng = np.random.default_rng(0)
    model = GPT2(gpt2_config("test", quant="int8_fwd"))
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(data=2, tensor=4), strategy="tp",
                 watchdog=False)
    batch = {
        "tokens": rng.integers(0, 128, (8, 32)).astype(np.int32),
        "targets": rng.integers(0, 128, (8, 32)).astype(np.int32),
    }
    tr.init(batch)
    wi = tr.state.params["params"]["h"]["block"]["mlp"]["wi"]["kernel"]
    flat = []
    for entry in tuple(wi.sharding.spec):
        flat.extend(entry if isinstance(entry, tuple) else (entry,))
    assert Axis.TENSOR in flat
    assert wi.addressable_shards[0].data.shape[-1] * 4 == wi.shape[-1]
