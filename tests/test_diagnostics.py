"""In-graph training diagnostics (ISSUE 6, telemetry/diagnostics.py).

Covers the whole chain: the per-layer stats the transformer blocks sow,
the grad/update health the train step folds into its metrics pytree, the
NaN-provenance scalar and its end-to-end ride — a PTD_FAULTS
``nan@step=S,layer=L`` injection must produce anomaly events naming
exactly layer L — plus the zero-overhead disciplines: diagnostics (any
cadence) add zero steady-state recompiles, and with diagnostics off not
one metric key or JSONL file appears (the byte-identical-HLO half lives
in test_compiled_invariants.py::test_diag_off_hlo_byte_identical).
"""

from __future__ import annotations

import glob
import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.ops.quant import (
    int8_dot_stats,
    saturation_fraction,
)
from pytorchdistributed_tpu.runtime.mesh import create_mesh
from pytorchdistributed_tpu.telemetry.diagnostics import (
    DiagnosticsConfig,
    activation_stat_vec,
    collect_activation_tables,
    first_bad_layer,
)
from pytorchdistributed_tpu.telemetry.events import AnomalyDetector
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss

NUM_LAYERS = 4


def _batch(seed=0, batch=32, seq=64):
    rng = np.random.default_rng(seed)
    return {
        "tokens": rng.integers(0, 128, (batch, seq)).astype(np.int32),
        "targets": rng.integers(0, 128, (batch, seq)).astype(np.int32),
    }


def _trainer(diagnostics=None, *, telemetry_dir=None, log_every=1,
             cfg_kw=None, **kw):
    model = GPT2(gpt2_config("test",
                             **{"num_layers": NUM_LAYERS, **(cfg_kw or {})}))
    return Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                   mesh=create_mesh(data=8), strategy="dp",
                   log_every=log_every, diagnostics=diagnostics,
                   telemetry_dir=(str(telemetry_dir) if telemetry_dir
                                  else None), **kw)


class _FakeLoader:
    batch_size = 32

    def __init__(self, n=4, seed=0):
        self._batches = [_batch(seed + i) for i in range(n)]

    def set_epoch(self, epoch):
        pass

    def __len__(self):
        return len(self._batches)

    def __iter__(self):
        return iter([dict(b) for b in self._batches])


# ---------------------------------------------------------------------------
# units
# ---------------------------------------------------------------------------


class TestDiagnosticsConfig:
    def test_parse_modes(self):
        assert DiagnosticsConfig.parse("off") is None
        assert DiagnosticsConfig.parse("") is None
        assert DiagnosticsConfig.parse("scalars") == DiagnosticsConfig(0)
        assert DiagnosticsConfig.parse("full") == DiagnosticsConfig(50)
        assert DiagnosticsConfig.parse("full:7") == DiagnosticsConfig(7)
        assert DiagnosticsConfig.parse("FULL:7").table_every == 7

    def test_parse_rejects_garbage(self):
        with pytest.raises(ValueError, match="diagnostics mode"):
            DiagnosticsConfig.parse("verbose")
        with pytest.raises(ValueError):
            DiagnosticsConfig.parse("full:0")

    def test_resolve_env_and_explicit(self, monkeypatch):
        monkeypatch.delenv("PTD_DIAGNOSTICS", raising=False)
        assert DiagnosticsConfig.resolve(None) is None
        monkeypatch.setenv("PTD_DIAGNOSTICS", "full:9")
        assert DiagnosticsConfig.resolve(None) == DiagnosticsConfig(9)
        # explicit arg wins over env — including explicit "off"
        assert DiagnosticsConfig.resolve("off") is None
        assert DiagnosticsConfig.resolve("scalars") == DiagnosticsConfig(0)
        assert DiagnosticsConfig.resolve(
            DiagnosticsConfig(3)) == DiagnosticsConfig(3)


def test_activation_stat_vec_units():
    x = jnp.array([[3.0, -4.0], [0.0, 0.0]])
    rms, absmax, nonfinite = np.asarray(activation_stat_vec(x))
    assert absmax == 4.0 and nonfinite == 0.0
    assert rms == pytest.approx(np.sqrt(25.0 / 4.0))
    # non-finite elements are COUNTED but excluded from the moments —
    # rms/absmax stay readable through a blowup
    x = jnp.array([[jnp.nan, jnp.inf], [3.0, -4.0]])
    rms, absmax, nonfinite = np.asarray(activation_stat_vec(x))
    assert nonfinite == 2.0 and absmax == 4.0
    assert rms == pytest.approx(np.sqrt(25.0 / 2.0))


def test_first_bad_layer_unit():
    assert float(first_bad_layer(jnp.array([0.0, 0.0, 0.0]))) == -1.0
    assert float(first_bad_layer(jnp.array([0.0, 2.0, 5.0]))) == 1.0
    # micro-batch-averaged counts (fractional) still resolve
    assert float(first_bad_layer(jnp.array([0.0, 0.0, 0.5]))) == 2.0


def test_saturation_fraction_units():
    # every element equals the channel absmax -> all on the clip boundary
    assert float(saturation_fraction(jnp.ones((4, 8)))) == pytest.approx(1.0)
    # one dominant outlier per row -> only it reaches |q| == 127
    x = jnp.concatenate([jnp.full((4, 1), 1000.0), jnp.ones((4, 7))], -1)
    assert float(saturation_fraction(x)) == pytest.approx(1 / 8)


def test_int8_dot_stats_matches_saturation():
    rng = np.random.default_rng(0)
    lhs = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    rhs = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
    stats = int8_dot_stats(lhs, rhs, (((1,), (0,)), ((), ())))
    assert set(stats) == {"lhs_sat_frac", "rhs_sat_frac"}
    assert float(stats["lhs_sat_frac"]) == pytest.approx(
        float(saturation_fraction(lhs, axis=1)))
    for v in stats.values():
        assert 0.0 < float(v) <= 1.0
    with pytest.raises(NotImplementedError):
        int8_dot_stats(lhs[None], rhs[None],
                       (((2,), (1,)), ((0,), (0,))))


# ---------------------------------------------------------------------------
# the model-side sow sites
# ---------------------------------------------------------------------------


def _sown_tables(cfg_kw=None):
    model = GPT2(gpt2_config("test",
                             **{"num_layers": NUM_LAYERS, **(cfg_kw or {})}))
    tokens = jnp.asarray(_batch()["tokens"][:4])
    params = model.init(jax.random.key(0), tokens[:, :8])
    _, mods = model.apply(params, tokens, mutable=["diagnostics"])
    return collect_activation_tables(mods["diagnostics"])


def test_blocks_sow_per_layer_tables():
    tables = _sown_tables()
    assert set(tables) == {"act_rms", "act_absmax", "act_nonfinite"}
    for name, tbl in tables.items():
        assert tbl.shape == (NUM_LAYERS,), name
    assert np.all(np.asarray(tables["act_rms"]) > 0)
    assert np.all(np.asarray(tables["act_nonfinite"]) == 0)


def test_unrolled_stack_sows_in_layer_order():
    # scan_layers=False names blocks block_0..block_N — the collector must
    # reassemble them in NATURAL order (block_2 before block_10)
    tables = _sown_tables(dict(scan_layers=False, num_layers=12))
    assert tables["act_rms"].shape == (12,)


def test_quant_blocks_sow_int8_saturation():
    tables = _sown_tables(dict(quant="int8_fwd"))
    assert tables["int8_sat"].shape == (NUM_LAYERS,)
    sat = np.asarray(tables["int8_sat"])
    assert np.all((sat > 0) & (sat <= 1.0))


def test_no_mutable_collection_sows_nothing():
    model = GPT2(gpt2_config("test", num_layers=NUM_LAYERS))
    tokens = jnp.asarray(_batch()["tokens"][:4])
    variables = model.init(jax.random.key(0), tokens[:, :8])
    # init must not have created the diagnostics collection (it is
    # per-batch output, not state)
    assert set(variables) == {"params"}
    out = model.apply(variables, tokens)  # plain apply: no tuple, no sow
    assert out.shape == (4, tokens.shape[1], 128)


# ---------------------------------------------------------------------------
# AnomalyDetector: per-key EMAs, env knobs, provenance (satellite 6a)
# ---------------------------------------------------------------------------


def test_anomaly_detector_watches_grad_norm_and_diag():
    det = AnomalyDetector(warmup=3, z_threshold=6.0)
    for step in range(6):
        assert det.check({"loss": 1.0, "grad_norm": 2.0,
                          "diag/update_ratio": 1e-3}, step=step) == []
    found = det.check({"loss": 1.0, "grad_norm": 500.0,
                       "diag/update_ratio": 1e-3}, step=6)
    assert [k for k, _ in found] == ["metric_spike"]
    assert found[0][1]["metric"] == "grad_norm"
    assert found[0][1]["z"] > 6.0
    # the loss key keeps its ORIGINAL event kind and payload shape
    found = det.check({"loss": 900.0, "grad_norm": 2.0}, step=7)
    assert [k for k, _ in found] == ["loss_spike"]
    assert set(found[0][1]) == {"value", "ema_mean", "ema_std", "z"}


def test_anomaly_detector_env_knobs(monkeypatch):
    monkeypatch.setenv("PTD_ANOMALY_Z", "2.0")
    monkeypatch.setenv("PTD_ANOMALY_KEYS", "mfu")
    det = AnomalyDetector(warmup=2)
    assert det.z_threshold == 2.0
    for step in range(4):
        det.check({"mfu": 0.5, "grad_norm": 1.0}, step=step)
    # grad_norm is NOT watched (keys pinned to mfu); a mild mfu rise
    # trips at the lowered threshold
    found = det.check({"mfu": 0.9, "grad_norm": 1e6}, step=5)
    assert [(k, p["metric"]) for k, p in found] == [("metric_spike", "mfu")]


def test_nonfinite_event_carries_provenance():
    det = AnomalyDetector()
    found = det.check({"loss": float("nan"),
                       "diag/first_bad_layer": 2.0}, step=3)
    nf = [p for k, p in found if k == "non_finite_metric"]
    assert nf and all(p["first_bad_layer"] == 2 for p in nf)
    # no provenance scalar (diagnostics off) -> original payload shape
    found = det.check({"loss": float("nan")}, step=4)
    payload = dict(found[0][1])
    assert set(payload) == {"metric", "value"}
    # clean provenance (-1) is not attached
    found = det.check({"loss": float("inf"),
                       "diag/first_bad_layer": -1.0}, step=5)
    assert "first_bad_layer" not in found[0][1]


# ---------------------------------------------------------------------------
# trainer integration
# ---------------------------------------------------------------------------


def test_trainer_diag_metrics_and_jsonl_stream(tmp_path):
    tr = _trainer("full:2", telemetry_dir=tmp_path)
    metrics = tr.run_epoch(_FakeLoader(4), epoch=0)
    for key in ("diag/grad_norm", "diag/update_ratio", "diag/act_rms_mean",
                "diag/act_absmax", "diag/first_bad_layer"):
        assert key in metrics, sorted(metrics)
    assert metrics["diag/first_bad_layer"] == -1.0
    assert metrics["diag/grad_norm"] > 0
    # per-layer tables never leak into the scalar metric stream
    assert not any(k.startswith("diag_tbl/") for k in metrics)
    [path] = glob.glob(str(tmp_path / "diagnostics_rank0.jsonl"))
    rows = [json.loads(l) for l in open(path) if l.strip()]
    assert len(rows) == 4  # scalar row per log sync (log_every=1)
    table_rows = [r for r in rows if "layers" in r]
    assert table_rows, "full:2 over 4 steps must write layer tables"
    layers = table_rows[-1]["layers"]
    assert set(layers) >= {"act_rms", "act_absmax", "act_nonfinite",
                           "gnorm_h"}
    assert all(len(v) == NUM_LAYERS for v in layers.values())
    # the primary telemetry metric rows stay diag-free (separate streams)
    mrows = [json.loads(l)
             for l in open(tmp_path / "metrics_rank0.jsonl") if l.strip()]
    assert mrows and not any(k.startswith("diag")
                             for r in mrows for k in r)


def test_diag_off_adds_no_keys_and_no_stream(tmp_path):
    tr = _trainer(None, telemetry_dir=tmp_path)
    metrics = tr.run_epoch(_FakeLoader(2), epoch=0)
    assert not any(k.startswith("diag") for k in metrics)
    assert not glob.glob(str(tmp_path / "diagnostics_rank*.jsonl"))


def test_diag_composes_with_accum_and_remat(tmp_path):
    tr = _trainer("scalars", telemetry_dir=tmp_path, accum_steps=2,
                  remat=True, cfg_kw=dict(remat=True))
    m = tr.train_step(_batch())
    m = tr.train_step(_batch(1))
    assert float(m["diag/grad_norm"]) > 0
    assert float(m["diag/first_bad_layer"]) == -1.0
    assert np.isfinite(float(m["diag/update_ratio"]))


def test_diag_int8_saturation_rides_quant_step():
    tr = _trainer("scalars", cfg_kw=dict(quant="int8_fwd"))
    m = tr.train_step(_batch())
    assert 0.0 < float(m["diag/int8_sat"]) <= 1.0


def test_zero_steadystate_recompiles_with_diagnostics():
    """Any diagnostics cadence rides ONE compiled step: the cadence is a
    host-emission knob, never a second program (the pjit _cache_size
    tripwire, as in test_overlap/test_serving)."""
    tr = _trainer("full:2")
    for i in range(5):
        tr.train_step(_batch(i))
    assert tr._step_fn._cache_size() == 1


def test_nan_provenance_end_to_end(tmp_path, monkeypatch):
    """ISSUE 6 acceptance: the PR 4 nan fault at a chosen layer produces
    anomaly events identifying that layer. The injection poisons layer
    2's params BEFORE the step, the blowup flows through the real
    compiled model, the in-graph provenance pins it, the tripwire writes
    it durably, then the watchdog raises."""
    from pytorchdistributed_tpu.faults.inject import reset_active
    from pytorchdistributed_tpu.telemetry import read_events

    target = 2
    run_dir = tmp_path / "run"
    monkeypatch.setenv("PTD_FAULTS", f"nan@step=3,layer={target}")
    monkeypatch.setenv("PTD_FAULTS_STATE", str(tmp_path / "state"))
    monkeypatch.setenv("PTD_TELEMETRY_DIR", str(run_dir))
    reset_active()
    try:
        tr = _trainer("scalars", telemetry_dir=run_dir)
        with pytest.raises(FloatingPointError):
            tr.run_epoch(_FakeLoader(5), epoch=0)
    finally:
        reset_active()
    events = read_events(run_dir)
    fault = [e for e in events if e.kind == "fault_injected"]
    assert fault and fault[0].data["layer"] == target
    nonfinite = [e for e in events if e.kind == "non_finite_metric"
                 and e.data["metric"] == "loss"]
    assert nonfinite, [e.kind for e in events]
    assert nonfinite[0].data["first_bad_layer"] == target
    assert nonfinite[0].step == 3


def test_nan_layer_fault_spec_validation():
    from pytorchdistributed_tpu.faults.inject import FaultPlan

    plan = FaultPlan.parse("nan@step=4,layer=3")
    assert plan.specs[0].layer == 3
    assert "layer=3" in plan.specs[0].describe()
    with pytest.raises(ValueError, match="layer="):
        FaultPlan.parse("crash@step=4,layer=3")


def test_poison_layer_rejects_out_of_range():
    tr = _trainer("scalars")
    tr.init(_batch())
    with pytest.raises(ValueError, match="out of range"):
        tr._poison_layer_params(NUM_LAYERS)


def test_poison_layer_targets_right_block_when_unrolled():
    """Regression (review finding): at num_layers=3 an unrolled block's
    OWN fused-qkv bias has leading dim 3 == num_layers — shape sniffing
    would poison block_0's bias at row `layer` instead of block_2. The
    layout decision must come from cfg.scan_layers."""
    target = 2
    tr = _trainer("scalars", cfg_kw=dict(scan_layers=False, num_layers=3))
    tr.init(_batch())
    tr._poison_layer_params(target)
    blocks = tr.state.params["params"]["h"]
    for i in range(3):
        leaves = [np.asarray(l) for l in
                  jax.tree.leaves(blocks[f"block_{i}"])]
        has_nan = any(np.isnan(l).any() for l in leaves)
        assert has_nan == (i == target), (i, has_nan)
    # and the provenance scalar agrees end to end
    m = tr.train_step(_batch())
    assert float(m["diag/first_bad_layer"]) == target


def test_custom_loss_without_kwarg_still_gets_grad_health():
    """A loss that doesn't advertise diagnostics= keeps working: no
    activation stats, but grad/update health still reports."""
    def plain_loss(model, params, batch, rng=None):
        logits = model.apply(params, batch["tokens"])
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), batch["targets"])
        return ce.mean(), {"loss": ce.mean()}

    model = GPT2(gpt2_config("test", num_layers=NUM_LAYERS))
    tr = Trainer(model, optax.adamw(1e-3), plain_loss,
                 mesh=create_mesh(data=8), strategy="dp",
                 log_every=10**9, diagnostics="scalars")
    m = tr.train_step(_batch())
    assert float(m["diag/grad_norm"]) > 0
    assert "diag/act_rms_mean" not in m


def test_report_renders_layer_health(tmp_path):
    from pytorchdistributed_tpu.telemetry.report import render

    rows = [
        {"time": 1.0, "epoch": 0, "step": 2, "rank": 0,
         "diag/grad_norm": 0.5, "diag/first_bad_layer": -1.0},
        {"time": 2.0, "epoch": 0, "step": 4, "rank": 0,
         "diag/grad_norm": 0.7, "diag/first_bad_layer": 1.0,
         "layers": {"act_rms": [1.0, 2.0], "act_absmax": [3.0, 9.0],
                    "act_nonfinite": [0.0, 8.0]}},
    ]
    with open(tmp_path / "diagnostics_rank0.jsonl", "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    out = render(tmp_path)
    assert "layer health" in out
    assert "act_rms" in out
    assert "<- non-finite" in out  # layer 1's nonzero count is flagged
    # empty run dirs say how to turn the stream on
    assert "PTD_DIAGNOSTICS" in render(tmp_path / "nothing_here")
