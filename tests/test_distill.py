"""Draft distillation tests (ISSUE 16): the distill→swap→measure loop.

Layers under test:
  * distill_loss / distill_corpus / DistillTrainer — KL falls under
    training, the TARGET stays frozen (read-only by construction), and
    the trained student round-trips through the Trainer's checkpoint
    into the exact tree the serving engine's hot-swap accepts;
  * the fleet broadcast — ReplicaRouter.set_draft_params swaps every
    replica mid-stream with streams bitwise-equal to generate()
    (losslessness is independent of draft quality), the per-replica
    draft identity (params fingerprint + swap count) lands in
    summary()/telemetry/report, and — full tier — the same loop over
    SUBPROCESS workers via the checkpoint-path wire op, leaving no
    orphan processes behind.
"""

import dataclasses
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import generate, generate_speculative
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.serving import ServingEngine
from pytorchdistributed_tpu.serving.router import ReplicaRouter
from pytorchdistributed_tpu.training import (
    DistillTrainer,
    distill_corpus,
)

CFG = gpt2_config("test", num_layers=2, max_seq_len=64)


def _target(seed=1):
    model = GPT2(CFG)
    params = model.init(jax.random.key(seed), jnp.zeros((1, 4), jnp.int32))
    return model, params


def _trained_draft(model, params, *, steps=2, checkpoint_dir=None,
                   spec_heads=3):
    corpus = distill_corpus(model, params, seed=0, num_batches=1,
                            batch_size=8, seq_len=48, max_new_tokens=8)
    dt = DistillTrainer(model, params, num_layers=1,
                        spec_heads=spec_heads,
                        checkpoint_dir=checkpoint_dir)
    dt.init(corpus[0])
    metrics = [dt.train_step(corpus[0]) for _ in range(steps)]
    return dt, metrics


def test_distill_loss_falls_and_target_stays_frozen():
    """KL(teacher || student) falls under training, per-offset metrics
    surface, and the CALLER's target tree is bitwise-untouched — the
    teacher is frozen by construction, not by optimizer masking."""
    model, params = _target()
    before = jax.tree.map(lambda x: np.asarray(x).copy(), params)
    corpus = distill_corpus(model, params, seed=0, num_batches=2,
                            batch_size=8, seq_len=48, max_new_tokens=8)
    dt = DistillTrainer(model, params, num_layers=1, spec_heads=2)
    dt.init(corpus[0])
    first = last = None
    for _ in range(8):
        for b in corpus:
            m = dt.train_step(b)
            if first is None:
                first = float(m["loss"])
    last = float(m["loss"])
    assert last < first, (first, last)
    assert "kl_base" in m and "kl_head1" in m and "kl_head2" in m
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(before),
            jax.tree_util.tree_leaves_with_path(params)):
        np.testing.assert_array_equal(
            a, np.asarray(b), err_msg=jax.tree_util.keystr(ka))


def test_distill_corpus_deterministic_and_validated():
    model, params = _target()
    a = distill_corpus(model, params, seed=3, num_batches=1, batch_size=2,
                       seq_len=32, max_new_tokens=4)
    b = distill_corpus(model, params, seed=3, num_batches=1, batch_size=2,
                       seq_len=32, max_new_tokens=4)
    np.testing.assert_array_equal(a[0]["tokens"], b[0]["tokens"])
    np.testing.assert_array_equal(a[0]["target_logprobs"],
                                  b[0]["target_logprobs"])
    with pytest.raises(ValueError, match="max_seq_len"):
        distill_corpus(model, params, seq_len=128)
    with pytest.raises(ValueError, match="prompt_cap"):
        distill_corpus(model, params, seq_len=32, max_new_tokens=16,
                       prompt_cap=30)


def test_distilled_draft_offline_bitwise():
    """Losslessness survives a TRAINED draft: generate_speculative with
    the distilled student (heads on) is bitwise-equal to generate()."""
    model, params = _target()
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    dt, _ = _trained_draft(model, params)
    dcfg, dparams = dt.draft()
    draft = GPT2(dcfg)
    rng = np.random.default_rng(5)
    prompt = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 9)),
                         jnp.int32)
    ref = generate(dm, params, prompt, max_new_tokens=12)
    out = generate_speculative(dm, params, prompt, max_new_tokens=12,
                               spec_k=4, draft_model=draft,
                               draft_params=dparams)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_distill_checkpoint_roundtrip_feeds_hot_swap(tmp_path):
    """The Trainer checkpoint the distiller writes restores into the
    exact tree the engine hot-swap accepts — the wire contract of the
    router's checkpoint-path broadcast."""
    from pytorchdistributed_tpu.serving.replica_worker import (
        _restore_draft_params,
    )

    model, params = _target()
    dt, _ = _trained_draft(model, params,
                           checkpoint_dir=str(tmp_path / "draft"))
    dt.checkpoint.save(int(dt.state.step), dt.state, force=True)
    dt.checkpoint.wait()
    restored, step = _restore_draft_params(str(tmp_path / "draft"))
    assert step == int(dt.state.step)
    dcfg, dparams = dt.draft()
    engine = ServingEngine(model, params, num_slots=2, prefill_bucket=16,
                           block_size=8, spec_k=4, draft_config=dcfg,
                           draft_params=dparams)
    hash_live = engine.draft_params_hash()
    engine.set_draft_params(restored)
    assert engine.draft_swaps == 1
    # the restored tree IS the live tree — same fingerprint
    assert engine.draft_params_hash() == hash_live
    engine.close()


def test_router_inprocess_fleet_swap_midstream_bitwise(tmp_path):
    """One router call swaps EVERY replica's draft mid-stream: resident
    streams finish bitwise vs generate(), both replicas report the same
    new fingerprint, and the draft identity lands in the summary map,
    the telemetry events, and the report CLI's replica table.

    Tier-1 anchor: the swapped tree is a same-structure perturbation —
    it exercises the broadcast/identity/bitwise contract without paying
    distill_corpus's teacher-generate compile; the DistillTrainer-
    produced tree drives the same swap in the full-tier checkpoint
    round-trip and subprocess e2e tests."""
    from pytorchdistributed_tpu.inference import make_draft
    from pytorchdistributed_tpu.telemetry.report import render

    model, params = _target()
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    draft, dp = make_draft(dm, params, num_layers=1, spec_heads=3)
    dparams = jax.tree.map(lambda x: x * 0.5, dp)
    router = ReplicaRouter(
        workers=None, replicas=2, model=model, params=params,
        engine_kwargs=dict(num_slots=2, prefill_bucket=16, block_size=8,
                           spec_k=4, draft_config=draft.cfg,
                           draft_params=dp, adaptive_k=True),
        telemetry_dir=str(tmp_path))
    rng = np.random.default_rng(9)
    lens, news = (7, 11, 5, 9), (12, 9, 14, 10)
    prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
               for m in lens]
    reqs = [router.submit(p, max_new_tokens=n)
            for p, n in zip(prompts, news)]
    for _ in range(2):
        router.step()
    info = router.set_draft_params(params=dparams)
    assert set(info) == {0, 1}
    assert len({v["draft_hash"] for v in info.values()}) == 1
    assert all(v["draft_swaps"] == 1 for v in info.values())
    router.run_until_idle()
    for p, n, r in zip(prompts, news, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=n)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0],
                                      err_msg=f"request {r.id}")
    s = router.summary()
    assert s["draft_swaps"] == 2
    assert set(s["draft"]) == {0, 1}
    the_hash = s["draft"][0]["draft_hash"]
    router.close()
    report = render(str(tmp_path))
    assert the_hash in report
    assert "draft_swaps 2" in report


def test_router_refuses_mismatched_draft_fleet_wide():
    """A wrong-architecture broadcast is refused by EVERY replica and
    the fleet keeps serving on its old draft."""
    from pytorchdistributed_tpu.inference import make_draft

    model, params = _target()
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    draft, dp = make_draft(dm, params, num_layers=1, spec_heads=3)
    _, wrong = make_draft(dm, params, num_layers=1, spec_heads=1)
    router = ReplicaRouter(
        workers=None, replicas=2, model=model, params=params,
        engine_kwargs=dict(num_slots=2, prefill_bucket=16, block_size=8,
                           spec_k=4, draft_config=draft.cfg,
                           draft_params=dp))
    with pytest.raises(ValueError, match="structure"):
        router.set_draft_params(params=wrong)
    assert router.summary()["draft_swaps"] == 0
    router.close()


# ---------------------------------------------------------------------------
# full tier: subprocess fleet + the example (spawn jax-importing workers)


def _worker_spec(tmp_path, model, params, draft_ckpt=None):
    from pytorchdistributed_tpu.training.checkpoint import (
        CheckpointManager,
    )

    tgt = str(tmp_path / "target")
    with CheckpointManager(tgt) as mgr:
        mgr.save(7, {"step": jnp.int32(7), "params": params,
                     "opt_state": {"nu": jnp.zeros(3)}})
    return {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "checkpoint": tgt,
            "engine": {"num_slots": 3, "prefill_bucket": 16,
                       "block_size": 8, "spec_k": 4, "adaptive_k": True,
                       "draft": {"num_layers": 1, "spec_heads": 3}}}


def test_router_subprocess_hot_swap_checkpoint_no_orphans(tmp_path):
    """The wire op end-to-end: a 2-subprocess fleet swaps to a distilled
    checkpoint mid-stream without dropping or retracing a stream
    (replicas_lost must stay 0 — a post-swap retrace would stall a
    worker into the hang watchdog), streams stay bitwise, params-only
    broadcasts are refused (trees never ship over the wire), and close()
    leaves no orphan worker processes."""
    model, params = _target()
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    spec = _worker_spec(tmp_path, model, params)
    dt, _ = _trained_draft(model, params,
                           checkpoint_dir=str(tmp_path / "draft"))
    dt.checkpoint.save(int(dt.state.step), dt.state, force=True)
    dt.checkpoint.wait()

    router = ReplicaRouter(workers=[spec, spec], warmup_lens=(16,),
                           faults=None, telemetry_dir=str(tmp_path))
    procs = [rep.proc for rep in router._replicas]
    try:
        rng = np.random.default_rng(9)
        lens, news = (7, 11, 5, 9), (20, 18, 22, 16)
        prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
                   for m in lens]
        reqs = [router.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]
        for _ in range(4):
            router.step()
        with pytest.raises(ValueError, match="checkpoint"):
            router.set_draft_params(params=dt.draft()[1])
        info = router.set_draft_params(
            checkpoint=str(tmp_path / "draft"))
        assert set(info) == {0, 1}
        assert len({v["draft_hash"] for v in info.values()}) == 1
        router.run_until_idle()
        for p, n, r in zip(prompts, news, reqs):
            ref = generate(dm, params, jnp.asarray(p)[None],
                           max_new_tokens=n)
            np.testing.assert_array_equal(
                r.output_ids, np.asarray(ref)[0],
                err_msg=f"request {r.id}")
        s = router.summary()
        assert s["replicas_lost"] == 0 and s["failovers"] == 0
        assert s["draft_swaps"] == 2
    finally:
        router.close()
    for p in procs:
        assert p.poll() is not None, "orphan worker process after close"


def test_example_distill_draft_runs():
    """The end-to-end demo (train target → distill → serve → hot-swap)
    runs clean and prints its acceptance A/B."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=root + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "examples",
                                      "distill_draft.py")],
        capture_output=True, text=True, timeout=560, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "acceptance" in out.stdout
    assert "hot-swap" in out.stdout
