"""Torch→TPU weight import parity: a randomly-initialized HF torch model's
logits must match our model's bit-for-architecture (fp32, dense attention)
after models/torch_import.py relayout. Hermetic — HF configs construct
random weights locally, nothing is downloaded."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from pytorchdistributed_tpu.models import (  # noqa: E402
    GPT2,
    Llama,
    gpt2_config,
    llama_config,
)
from pytorchdistributed_tpu.models.torch_import import (  # noqa: E402
    gpt2_params_from_torch,
    llama_params_from_torch,
)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_gpt2_import_matches_torch_logits(scan_layers):
    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0)
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()

    cfg = gpt2_config("test", dtype=jnp.float32, attention="dense",
                      scan_layers=scan_layers)
    params = gpt2_params_from_torch(hf.state_dict(), cfg)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(torch.asarray(tokens)).logits.numpy()
    got = GPT2(cfg).apply(params, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_generate_on_imported_weights_matches_torch_greedy():
    """Serving path on imported weights: our KV-cache generate() produces
    the same greedy continuation as HF's generate for the same torch
    checkpoint."""
    import dataclasses

    from pytorchdistributed_tpu.inference import generate

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0)
    torch.manual_seed(3)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = gpt2_config("test", dtype=jnp.float32, attention="dense",
                      scan_layers=False)
    params = gpt2_params_from_torch(hf.state_dict(), cfg)
    prompt = np.random.default_rng(3).integers(0, 128, (2, 8))
    with torch.no_grad():
        want = hf.generate(torch.asarray(prompt), max_new_tokens=8,
                           do_sample=False, pad_token_id=0).numpy()
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    got = np.asarray(generate(dm, params, jnp.asarray(prompt, jnp.int32),
                              max_new_tokens=8, temperature=0.0))
    np.testing.assert_array_equal(got[:, 8:], want[:, 8:])


@pytest.mark.parametrize("scan_layers,kv_heads", [
    (False, 2), (True, 2),   # GQA layout (1b/8b/70b-style): q + fused kv
    (False, 4),              # MHA layout (7b/13b-style): single fused qkv
])
def test_llama_import_matches_torch_logits(scan_layers, kv_heads):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=kv_heads,
        max_position_embeddings=128, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, tie_word_embeddings=False)
    torch.manual_seed(1)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()

    cfg = llama_config("test", dtype=jnp.float32, attention="dense",
                       scan_layers=scan_layers, num_kv_heads=kv_heads)
    params = llama_params_from_torch(hf.state_dict(), cfg,
                                     rms_norm_eps=hf_cfg.rms_norm_eps)

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(torch.asarray(tokens)).logits.numpy()
    got = Llama(cfg).apply(params, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_bert_import_matches_torch_logits(scan_layers):
    from pytorchdistributed_tpu.models import BertMLM, bert_config
    from pytorchdistributed_tpu.models.torch_import import (
        bert_params_from_torch,
    )

    hf_cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=256,
        max_position_embeddings=128, hidden_act="gelu",
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        layer_norm_eps=1e-12)
    torch.manual_seed(2)
    hf = transformers.BertForMaskedLM(hf_cfg).eval()

    cfg = bert_config("test", dtype=jnp.float32, attention="dense",
                      scan_layers=scan_layers)
    params = bert_params_from_torch(hf.state_dict(), cfg)

    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(torch.asarray(tokens)).logits.numpy()
    got = BertMLM(cfg).apply(params, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_vit_import_matches_torch_logits(scan_layers):
    from pytorchdistributed_tpu.models import ViT, vit_config
    from pytorchdistributed_tpu.models.torch_import import (
        vit_params_from_torch,
    )

    hf_cfg = transformers.ViTConfig(
        hidden_size=64, num_hidden_layers=2, num_attention_heads=4,
        intermediate_size=256, image_size=16, patch_size=8,
        num_channels=3, hidden_act="gelu", layer_norm_eps=1e-12,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
    torch.manual_seed(4)
    hf = transformers.ViTForImageClassification(hf_cfg).eval()
    # HF default num_labels=2

    cfg = vit_config("test", image_size=16, patch_size=8, num_classes=2,
                     dtype=jnp.float32, attention="dense",
                     scan_layers=scan_layers)
    params = vit_params_from_torch(hf.state_dict(), cfg)

    rng = np.random.default_rng(4)
    images = rng.standard_normal((2, 16, 16, 3)).astype(np.float32)
    with torch.no_grad():  # torch wants NCHW
        want = hf(torch.asarray(images.transpose(0, 3, 1, 2))).logits.numpy()
    got = ViT(cfg).apply(params, jnp.asarray(images))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_imported_weights_survive_checkpoint_roundtrip(tmp_path):
    """Interop with the checkpoint system: imported torch weights saved via
    the sharded CheckpointManager and restored into a fresh Trainer still
    reproduce the torch logits — the full migration path (torch ->
    import -> orbax -> serve)."""
    import optax

    from pytorchdistributed_tpu.runtime.mesh import local_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )
    from pytorchdistributed_tpu.training.trainer import TrainState

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=128, n_embd=64, n_layer=2, n_head=4,
        activation_function="gelu_new", resid_pdrop=0.0, embd_pdrop=0.0,
        attn_pdrop=0.0)
    torch.manual_seed(5)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = gpt2_config("test", dtype=jnp.float32, attention="dense",
                      scan_layers=False)
    params = gpt2_params_from_torch(hf.state_dict(), cfg)

    batch = {"tokens": np.zeros((2, 16), np.int32),
             "targets": np.zeros((2, 16), np.int32)}
    opt = optax.sgd(1e-2)
    tr = Trainer(GPT2(cfg), opt, token_cross_entropy_loss,
                 mesh=local_mesh(1), checkpoint_dir=str(tmp_path),
                 log_every=10**9)
    tr.init(batch)
    # adopt the imported weights, save at step 0
    tr.state = TrainState(step=tr.state.step,
                          params=jax.device_put(params),
                          opt_state=tr.state.opt_state)
    tr._save_checkpoint(force=True)
    tr.checkpoint.wait()

    tr2 = Trainer(GPT2(cfg), opt, token_cross_entropy_loss,
                  mesh=local_mesh(1), checkpoint_dir=str(tmp_path))
    tr2.restore(batch)
    tokens = np.random.default_rng(5).integers(0, 128, (2, 16))
    with torch.no_grad():
        want = hf(torch.asarray(tokens)).logits.numpy()
    got = GPT2(cfg).apply(tr2.state.params, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_llama_import_rejects_tied_embeddings():
    with pytest.raises(ValueError, match="tie_embeddings"):
        llama_params_from_torch(
            {}, llama_config("test", tie_embeddings=True))


def _torch_resnet50(num_classes: int = 10):
    """A torch ResNet-50 with torchvision's exact module naming, so its
    state_dict carries the torchvision key schema (conv1/bn1/layer{1-4}.
    {b}.conv{1-3}/bn{1-3}/downsample.{0,1}/fc) — torchvision itself is not
    installed in the test image. Architecture per the reference's own
    model (ModelParallelResNet50 wraps torchvision resnet50,
    03_model_parallel.ipynb:325-349)."""
    tnn = torch.nn

    class Bottleneck(tnn.Module):
        def __init__(self, inplanes, planes, stride=1, downsample=None):
            super().__init__()
            self.conv1 = tnn.Conv2d(inplanes, planes, 1, bias=False)
            self.bn1 = tnn.BatchNorm2d(planes)
            self.conv2 = tnn.Conv2d(planes, planes, 3, stride, 1,
                                    bias=False)
            self.bn2 = tnn.BatchNorm2d(planes)
            self.conv3 = tnn.Conv2d(planes, planes * 4, 1, bias=False)
            self.bn3 = tnn.BatchNorm2d(planes * 4)
            self.relu = tnn.ReLU()
            self.downsample = downsample

        def forward(self, x):
            r = self.relu(self.bn1(self.conv1(x)))
            r = self.relu(self.bn2(self.conv2(r)))
            r = self.bn3(self.conv3(r))
            if self.downsample is not None:
                x = self.downsample(x)
            return self.relu(x + r)

    class ResNet50(tnn.Module):
        def __init__(self):
            super().__init__()
            self.inplanes = 64
            self.conv1 = tnn.Conv2d(3, 64, 7, 2, 3, bias=False)
            self.bn1 = tnn.BatchNorm2d(64)
            self.relu = tnn.ReLU()
            self.maxpool = tnn.MaxPool2d(3, 2, 1)
            self.layer1 = self._make_layer(64, 3, 1)
            self.layer2 = self._make_layer(128, 4, 2)
            self.layer3 = self._make_layer(256, 6, 2)
            self.layer4 = self._make_layer(512, 3, 2)
            self.fc = tnn.Linear(2048, num_classes)

        def _make_layer(self, planes, blocks, stride):
            down = None
            if stride != 1 or self.inplanes != planes * 4:
                down = tnn.Sequential(
                    tnn.Conv2d(self.inplanes, planes * 4, 1, stride,
                               bias=False),
                    tnn.BatchNorm2d(planes * 4))
            layers = [Bottleneck(self.inplanes, planes, stride, down)]
            self.inplanes = planes * 4
            layers += [Bottleneck(self.inplanes, planes)
                       for _ in range(1, blocks)]
            return tnn.Sequential(*layers)

        def forward(self, x):
            x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
            x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
            # torchvision's AdaptiveAvgPool2d(1) == global spatial mean
            return self.fc(x.mean(dim=(2, 3)))

    return ResNet50()


def _warmed_torch_resnet(seed: int = 6, num_classes: int = 10):
    """Random-init torch ResNet-50 with POPULATED BN running stats (a few
    train-mode forwards): import must carry real running_mean/var, not the
    0/1 init that would hide a stats-mapping bug."""
    torch.manual_seed(seed)
    m = _torch_resnet50(num_classes)
    m.train()
    with torch.no_grad():
        for _ in range(2):
            m(torch.randn(4, 3, 64, 64))
    return m.eval()


def test_resnet50_import_matches_torch_logits():
    from pytorchdistributed_tpu.models import resnet50
    from pytorchdistributed_tpu.models.torch_import import (
        resnet50_params_from_torch,
    )

    hf = _warmed_torch_resnet()
    model = resnet50(num_classes=10, dtype=jnp.float32, torch_padding=True)
    variables = resnet50_params_from_torch(hf.state_dict(), model.cfg)

    rng = np.random.default_rng(6)
    images = rng.standard_normal((2, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():  # torch wants NCHW
        want = hf(torch.asarray(images.transpose(0, 3, 1, 2))).numpy()
    got = model.apply(jax.tree.map(jnp.asarray, variables),
                      jnp.asarray(images), deterministic=True)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_resnet50_import_rejects_same_padding_config():
    """SAME-padding models must not accept torch weights: stride-2 convs
    pad differently, so the logits would be silently wrong."""
    from pytorchdistributed_tpu.models import resnet50
    from pytorchdistributed_tpu.models.torch_import import (
        resnet50_params_from_torch,
    )

    with pytest.raises(ValueError, match="torch_padding"):
        resnet50_params_from_torch({}, resnet50(num_classes=10).cfg)


def test_resnet50_import_rejects_class_mismatch():
    from pytorchdistributed_tpu.models import resnet50
    from pytorchdistributed_tpu.models.torch_import import (
        resnet50_params_from_torch,
    )

    hf = _torch_resnet50(num_classes=10)
    cfg = resnet50(num_classes=1000, torch_padding=True).cfg
    with pytest.raises(ValueError, match="classes"):
        resnet50_params_from_torch(hf.state_dict(), cfg)


def test_resnet50_imported_weights_evaluate_smoke():
    """The migration target workload: imported torch weights riding the
    Trainer's pad-aware evaluate(), metrics agreeing with a direct forward
    computation of the same mean CE."""
    import optax

    from pytorchdistributed_tpu.data import DataLoader, SyntheticImageDataset
    from pytorchdistributed_tpu.models import resnet50
    from pytorchdistributed_tpu.models.torch_import import (
        resnet50_params_from_torch,
    )
    from pytorchdistributed_tpu.runtime.mesh import local_mesh
    from pytorchdistributed_tpu.training import Trainer, cross_entropy_loss
    from pytorchdistributed_tpu.training.trainer import TrainState

    hf = _warmed_torch_resnet()
    model = resnet50(num_classes=10, dtype=jnp.float32, torch_padding=True)
    variables = resnet50_params_from_torch(hf.state_dict(), model.cfg)

    ds = SyntheticImageDataset(size=16, image_size=64, num_classes=10,
                               seed=7)
    loader = DataLoader(ds, batch_size=8, num_replicas=1, rank=0,
                        shuffle=False)
    batch = next(iter(loader))
    tr = Trainer(model, optax.sgd(1e-2), cross_entropy_loss,
                 mesh=local_mesh(1), log_every=10**9)
    tr.init(batch)
    tr.state = TrainState(step=tr.state.step,
                          params=jax.device_put(variables),
                          opt_state=tr.state.opt_state)
    metrics = tr.evaluate(loader)

    logits = model.apply(jax.tree.map(jnp.asarray, variables),
                         jnp.asarray(ds.arrays["image"]),
                         deterministic=True)
    want_loss = float(optax.softmax_cross_entropy_with_integer_labels(
        logits, jnp.asarray(ds.arrays["label"])).mean())
    assert abs(metrics["loss"] - want_loss) < 1e-4
    assert 0.0 <= metrics["accuracy"] <= 1.0


def test_llama_import_rejects_eps_mismatch():
    """A Llama-1-style checkpoint (rms_norm_eps=1e-6) must not silently
    import under the preset's 1e-5 — epsilon lives in the HF config, not
    the state_dict, so the importer validates it when given."""
    with pytest.raises(ValueError, match="rms_norm_eps"):
        llama_params_from_torch(
            {}, llama_config("test"), rms_norm_eps=1e-6)
