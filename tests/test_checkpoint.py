"""Checkpoint/resume tests (SURVEY.md §5: sharded save, async, resume with
re-sharding)."""

import numpy as np
import optax
import pytest

import jax
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.runtime.mesh import create_mesh
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss
from pytorchdistributed_tpu.training.checkpoint import (
    CheckpointManager,
    abstract_state_like,
)


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "tokens": rng.integers(0, 128, (8, 32)).astype(np.int32),
        "targets": rng.integers(0, 128, (8, 32)).astype(np.int32),
    }


def _trainer(strategy="dp", axes=None, **kw):
    model = GPT2(gpt2_config("test", dtype=np.float32))
    return Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                   mesh=create_mesh(**(axes or {})), strategy=strategy, **kw)


def test_save_restore_roundtrip(tmp_path):
    tr = _trainer()
    batch = _batch()
    tr.train_step(batch)
    with CheckpointManager(tmp_path / "ckpt") as mgr:
        mgr.save(int(tr.state.step), tr.state, force=True)
        mgr.wait()
        assert mgr.latest_step() == 1
        restored = mgr.restore(
            abstract_state_like(tr.state, tr.state_shardings))
    for a, b in zip(jax.tree.leaves(tr.state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_onto_different_mesh(tmp_path):
    """A DP-saved checkpoint restores onto an FSDP mesh (re-sharding on
    load) and keeps training with the same loss."""
    batch = _batch()
    tr_dp = _trainer("dp")
    tr_dp.train_step(batch)
    with CheckpointManager(tmp_path / "ckpt") as mgr:
        mgr.save(1, tr_dp.state, force=True)
        mgr.wait()
        loss_dp = float(tr_dp.train_step(batch)["loss"])
        tr_fsdp = _trainer("fsdp", axes=dict(data=2, fsdp=4))
        tr_fsdp.init(batch)
        tr_fsdp.state = mgr.restore(
            abstract_state_like(tr_fsdp.state, tr_fsdp.state_shardings))
    loss_fsdp = float(tr_fsdp.train_step(batch)["loss"])
    np.testing.assert_allclose(loss_fsdp, loss_dp, rtol=1e-5)


def test_fit_resume_continues_curve(tmp_path):
    """1 epoch + resume + 1 epoch == 2 epochs straight (loss equality)."""
    from pytorchdistributed_tpu.data import (
        DataLoader,
        SyntheticTokenDataset,
    )

    ds = SyntheticTokenDataset(size=64, seq_len=32, vocab_size=128, seed=0)
    loader = DataLoader(ds, batch_size=8, num_replicas=1, rank=0, seed=0)

    straight = _trainer()
    m_straight = straight.fit(loader, 2)

    resumed = _trainer(checkpoint_dir=str(tmp_path / "ck"))
    resumed.fit(loader, 1)
    resumed2 = _trainer(checkpoint_dir=str(tmp_path / "ck"))
    m_resumed = resumed2.fit(loader, 2, resume=True)
    assert int(resumed2.state.step) == int(straight.state.step)
    np.testing.assert_allclose(m_resumed["loss"], m_straight["loss"],
                               rtol=1e-5)


def test_epoch_end_save_collides_with_interval_save(tmp_path):
    """Regression: when checkpoint_every_steps divides steps-per-epoch, the
    epoch-end save lands on an already-saved step and must be a no-op, not
    a StepAlreadyExistsError crash."""
    from pytorchdistributed_tpu.data import DataLoader, SyntheticTokenDataset

    ds = SyntheticTokenDataset(size=32, seq_len=32, vocab_size=128, seed=0)
    loader = DataLoader(ds, batch_size=8, num_replicas=1, rank=0, seed=0)
    assert len(loader) == 4
    tr = _trainer(checkpoint_dir=str(tmp_path / "ck"),
                  checkpoint_every_steps=2)
    tr.fit(loader, 1)  # interval saves at 2,4; epoch-end save also step 4
    assert tr.checkpoint.latest_step() == 4


def test_resume_without_checkpoint_dir_raises():
    from pytorchdistributed_tpu.data import DataLoader, SyntheticTokenDataset

    ds = SyntheticTokenDataset(size=16, seq_len=32, vocab_size=128, seed=0)
    loader = DataLoader(ds, batch_size=8, num_replicas=1, rank=0, seed=0)
    tr = _trainer()  # no checkpoint_dir
    with pytest.raises(ValueError, match="resume"):
        tr.fit(loader, 1, resume=True)


def test_resume_with_changed_loader_geometry_raises(tmp_path):
    """Resuming with a different batch size than the saving run must fail
    loudly: (epoch, skip) is derived from steps-per-epoch, so a silent
    mismatch would skip the wrong batches or retrain duplicates."""
    from pytorchdistributed_tpu.data import DataLoader, SyntheticTokenDataset

    ds = SyntheticTokenDataset(size=64, seq_len=32, vocab_size=128, seed=0)
    loader = DataLoader(ds, batch_size=8, num_replicas=1, rank=0, seed=0)
    tr = _trainer(checkpoint_dir=str(tmp_path / "ck"))
    tr.fit(loader, 1)

    other = DataLoader(ds, batch_size=16, num_replicas=1, rank=0, seed=0)
    resumed = _trainer(checkpoint_dir=str(tmp_path / "ck"))
    with pytest.raises(ValueError, match="steps_per_epoch"):
        resumed.fit(other, 2, resume=True)


def test_mid_epoch_resume_no_duplicate_batches(tmp_path):
    """Regression: resuming from a mid-epoch checkpoint must skip the
    already-trained prefix of that epoch (same final step and loss as an
    uninterrupted run)."""
    from pytorchdistributed_tpu.data import DataLoader, SyntheticTokenDataset

    ds = SyntheticTokenDataset(size=64, seq_len=32, vocab_size=128, seed=0)
    loader = DataLoader(ds, batch_size=8, num_replicas=1, rank=0, seed=0)
    steps_per_epoch = len(loader)  # 8

    straight = _trainer()
    m_straight = straight.fit(loader, 2)

    # train 5 steps of epoch 0, checkpoint, "crash"
    crashed = _trainer(checkpoint_dir=str(tmp_path / "ck"))
    loader.set_epoch(0)
    for i, batch in enumerate(iter(loader)):
        crashed.train_step(batch)
        if i == 4:
            break
    crashed._save_checkpoint(force=True)
    crashed.checkpoint.wait()

    resumed = _trainer(checkpoint_dir=str(tmp_path / "ck"))
    m_resumed = resumed.fit(loader, 2, resume=True)
    assert int(resumed.state.step) == int(straight.state.step) \
        == 2 * steps_per_epoch
    np.testing.assert_allclose(m_resumed["loss"], m_straight["loss"],
                               rtol=1e-5)


def test_public_restore_for_inference(tmp_path):
    """Trainer.restore: load a checkpoint with no fit loop, then generate
    with the restored params — the load_state_dict-for-eval path."""
    import dataclasses

    import optax

    from pytorchdistributed_tpu.inference import generate
    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    rng = np.random.default_rng(0)
    cfg = gpt2_config("test", max_seq_len=32)
    batch = {
        "tokens": rng.integers(0, 128, (8, 32)).astype(np.int32),
        "targets": rng.integers(0, 128, (8, 32)).astype(np.int32),
    }
    save = Trainer(GPT2(cfg), optax.sgd(1e-2), token_cross_entropy_loss,
                   mesh=create_mesh(), checkpoint_dir=str(tmp_path))
    save.train_step(batch)
    save._save_checkpoint(force=True)
    save.checkpoint.wait()

    # fresh Trainer on a DIFFERENT sharding strategy restores and serves
    load = Trainer(GPT2(cfg), optax.sgd(1e-2), token_cross_entropy_loss,
                   mesh=create_mesh(data=2, fsdp=4), strategy="fsdp",
                   checkpoint_dir=str(tmp_path))
    state = load.restore(batch)
    assert int(state.step) == 1
    a = np.asarray(jax.device_get(
        jax.tree.leaves(save.state.params)[0])).ravel()
    b = np.asarray(jax.device_get(
        jax.tree.leaves(load.state.params)[0])).ravel()
    np.testing.assert_allclose(a, b, rtol=1e-6)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    with jax.set_mesh(load.mesh):
        out = generate(dm, load.state.params, batch["tokens"][:2, :4],
                       max_new_tokens=4, temperature=0.0)
    assert out.shape == (2, 8)

    # errors are loud: empty dir and missing checkpoint_dir
    with pytest.raises(ValueError, match="no checkpoint"):
        Trainer(GPT2(cfg), optax.sgd(1e-2), token_cross_entropy_loss,
                mesh=create_mesh(),
                checkpoint_dir=str(tmp_path / "empty")).restore(batch)
    with pytest.raises(ValueError, match="checkpoint_dir"):
        Trainer(GPT2(cfg), optax.sgd(1e-2), token_cross_entropy_loss,
                mesh=create_mesh()).restore(batch)


def test_batch_stats_survive_checkpoint_roundtrip(tmp_path):
    """The servable-model contract (VERDICT r2 missing #3): ResNet's EMA
    normalization statistics ride TrainState, so a restored model's eval
    output (which normalizes with them) must match the saving run's
    exactly."""
    import optax

    from pytorchdistributed_tpu.models import resnet18
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, cross_entropy_loss

    rng = np.random.default_rng(8)
    batch = {
        "image": rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
        "label": rng.integers(0, 10, (16,)).astype(np.int32),
    }

    def trainer():
        return Trainer(resnet18(num_classes=10, cifar_stem=True),
                       optax.sgd(0.05, momentum=0.9), cross_entropy_loss,
                       mesh=create_mesh(), strategy="dp",
                       checkpoint_dir=str(tmp_path))

    tr = trainer()
    for _ in range(3):
        tr.train_step(batch)
    tr._save_checkpoint(force=True)
    tr.checkpoint.wait()
    saved_stats = jax.tree.map(np.asarray, tr.state.params["batch_stats"])
    saved_eval = np.asarray(
        tr.model.apply(tr.state.params, batch["image"][:2]))

    tr2 = trainer()
    tr2.restore(batch)
    for a, b in zip(jax.tree.leaves(saved_stats),
                    jax.tree.leaves(tr2.state.params["batch_stats"])):
        np.testing.assert_array_equal(a, np.asarray(b))
    got = np.asarray(tr2.model.apply(tr2.state.params, batch["image"][:2]))
    np.testing.assert_allclose(got, saved_eval, atol=1e-6)
