"""Llama-family tests — the working replacement for the reference's failed
llama-7b `device_map="auto"` cell (03_model_parallel.ipynb:86-89). Bar:
the Llama dialect (RMSNorm/SwiGLU/RoPE/GQA/no-bias) must train under every
strategy of the shared core, with loss equivalence across reshardings."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu._jax_compat import (
    supports_partial_auto_shard_map,
)
from pytorchdistributed_tpu.models import Llama, llama_config
from pytorchdistributed_tpu.models.transformer import apply_rope, rope_tables
from pytorchdistributed_tpu.runtime.mesh import Axis, create_mesh
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss


def _token_batch(rng, batch=8, seq=32, vocab=128):
    return {
        "tokens": rng.integers(0, vocab, (batch, seq)).astype(np.int32),
        "targets": rng.integers(0, vocab, (batch, seq)).astype(np.int32),
    }


def test_rope_rotation_properties():
    """RoPE is a pure rotation: it preserves norms, and q·k scores depend
    only on the relative position (the property that makes it a position
    encoding at all)."""
    rng = np.random.default_rng(0)
    s, d = 16, 8
    q = jnp.asarray(rng.standard_normal((1, s, 1, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, s, 1, d)), jnp.float32)
    cos, sin = rope_tables(s, d, 10000.0)
    qr, kr = apply_rope(q, cos, sin), apply_rope(k, cos, sin)
    np.testing.assert_allclose(
        jnp.linalg.norm(qr, axis=-1), jnp.linalg.norm(q, axis=-1), rtol=1e-5)
    # score(i, j) for fixed content must equal score(i+Δ, j+Δ): plant the
    # same q/k content at two absolute offsets and compare the dot products.
    qc = jnp.asarray(rng.standard_normal((d,)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((d,)), jnp.float32)

    def score(i, j):
        qi = apply_rope(jnp.broadcast_to(qc, (1, s, 1, d)), cos, sin)[0, i, 0]
        kj = apply_rope(jnp.broadcast_to(kc, (1, s, 1, d)), cos, sin)[0, j, 0]
        return float(qi @ kj)

    assert score(2, 5) == pytest.approx(score(9, 12), rel=1e-4)
    assert score(5, 2) == pytest.approx(score(12, 9), rel=1e-4)


@pytest.mark.parametrize("strategy,axes", [
    ("dp", dict()),
    ("tp_fsdp", dict(data=2, fsdp=2, tensor=2)),
])
def test_llama_strategies_train(strategy, axes):
    rng = np.random.default_rng(0)
    model = Llama(llama_config("test"))
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(**axes), strategy=strategy)
    batch = _token_batch(rng)
    l0 = float(tr.train_step(batch)["loss"])
    for _ in range(3):
        m = tr.train_step(batch)
    assert float(m["loss"]) < l0


def test_llama_gqa_params_no_bias():
    """GQA splits the projection into q + fused kv kernels (both head-dim
    sharded under TP), and use_bias=False leaves no bias anywhere."""
    rng = np.random.default_rng(0)
    cfg = llama_config("test")  # 4 heads, 2 kv heads
    model = Llama(cfg)
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(data=2, tensor=4), strategy="tp")
    tr.init(_token_batch(rng))
    attn = tr.state.params["params"]["h"]["block"]["attn"]
    assert attn["q_kernel"].shape[1:] == (
        cfg.embed_dim, cfg.num_heads * cfg.head_dim)
    assert attn["kv_kernel"].shape[1:] == (
        cfg.embed_dim, 2, cfg.kv_heads * cfg.head_dim)
    flat = jax.tree_util.tree_leaves_with_path(tr.state.params)
    names = ["/".join(str(k) for k in path) for path, _ in flat]
    assert not any("bias" in n for n in names)
    spec = []
    for entry in tuple(attn["q_kernel"].sharding.spec):
        spec.extend(entry if isinstance(entry, tuple) else (entry,))
    assert Axis.TENSOR in spec


def test_llama_fsdp_matches_dp_loss():
    rng = np.random.default_rng(1)
    batch = _token_batch(rng)
    losses = {}
    for strategy, axes in [("dp", dict()), ("fsdp", dict(data=2, fsdp=4))]:
        model = Llama(llama_config("test", dtype=np.float32))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(**axes), strategy=strategy)
        losses[strategy] = [float(tr.train_step(batch)["loss"])
                            for _ in range(3)]
    from tests.test_models import _fsdp_equivalence_tol

    tol = _fsdp_equivalence_tol()
    np.testing.assert_allclose(losses["dp"], losses["fsdp"],
                               rtol=tol, atol=tol)


@pytest.mark.skipif(
    not supports_partial_auto_shard_map(),
    reason="pipeline schedules need partial-auto shard_map "
           "(axis_names ⊂ mesh axes), unsupported by this jax")
@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_llama_pipeline_loss_equivalence(schedule):
    rng = np.random.default_rng(7)
    batch = _token_batch(rng, batch=16)

    def run(cfg_kw, axes):
        model = Llama(llama_config("test", num_layers=4, dtype=jnp.float32,
                                   **cfg_kw))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(**axes), strategy="dp")
        return [float(tr.train_step(batch)["loss"]) for _ in range(3)]

    seq = run(dict(), dict())
    pp = run(dict(pipeline_stages=4, pipeline_microbatches=4,
                  pp_schedule=schedule), dict(data=2, pipe=4))
    np.testing.assert_allclose(pp, seq, atol=2e-5)
