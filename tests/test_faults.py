"""Chaos suite (SURVEY.md §5 completion): deterministic fault injection,
retry/backoff, checkpoint integrity + fallback, preemption.

Tiering: the spec/retry/injector units and the two single-process
injection tests (nan trip, corrupt-latest fallback) ride the quick tier
(conftest._QUICK); everything driving real ``run.py`` multi-process runs
— crash→resume loss continuity, hang→heartbeat relaunch, repeated
crash→elastic shrink, preemption→uncharged restart, corrupt
latest→fallback resume, SIGTERM forwarding — stays full-suite-only.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import optax
import pytest

from pytorchdistributed_tpu.faults import (
    FaultInjector,
    FaultPlan,
    RetryPolicy,
    retry,
)
from pytorchdistributed_tpu.faults import inject as finject
from pytorchdistributed_tpu.telemetry.events import (
    EVENT_FAULT,
    EVENT_RETRY,
    EventLog,
    read_events,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def faults_env(monkeypatch):
    """Set a PTD_FAULTS plan for the duration of one test and re-resolve
    the process-global injector; everything is undone at teardown so the
    rest of the suite sees no plan."""

    def activate(spec, state_dir=None):
        monkeypatch.setenv(finject.FAULTS_ENV, spec)
        if state_dir is not None:
            monkeypatch.setenv(finject.FAULTS_STATE_ENV, str(state_dir))
        finject.reset_active()
        return finject.active()

    yield activate
    finject.reset_active()


# ---------------------------------------------------------------------------
# spec parsing


class TestFaultPlan:
    def test_parses_full_issue_spec(self):
        plan = FaultPlan.parse(
            "crash@step=7,rank=1; hang@step=12,rank=0; nan@step=9; "
            "preempt@step=15; ckpt_corrupt@step=20; slow_io@p=0.3,ms=200; "
            "io_err@p=1,n=2")
        kinds = [s.kind for s in plan.specs]
        assert kinds == ["crash", "hang", "nan", "preempt", "ckpt_corrupt",
                         "slow_io", "io_err"]
        crash = plan.specs[0]
        assert (crash.step, crash.rank) == (7, 1)
        slow = plan.specs[5]
        assert (slow.p, slow.ms) == (0.3, 200.0)
        assert plan.specs[6].n == 2

    def test_empty_entries_and_whitespace_tolerated(self):
        assert len(FaultPlan.parse(" crash@step=1 ; ; nan@step=2;")) == 2

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            FaultPlan.parse("explode@step=3")

    def test_step_kind_without_step_raises(self):
        with pytest.raises(ValueError, match="needs step="):
            FaultPlan.parse("crash@rank=1")

    def test_bad_param_raises(self):
        with pytest.raises(ValueError, match="bad fault param"):
            FaultPlan.parse("crash@step=banana")
        with pytest.raises(ValueError, match="bad fault param"):
            FaultPlan.parse("slow_io@volume=11")

    def test_probability_bounds(self):
        with pytest.raises(ValueError, match="p must be"):
            FaultPlan.parse("io_err@p=1.5")

    def test_from_env(self, faults_env):
        inj = faults_env("nan@step=4")
        assert inj is not None and inj.plan.specs[0].kind == "nan"
        finject.reset_active()
        os.environ.pop(finject.FAULTS_ENV, None)
        assert FaultPlan.from_env() is None


# ---------------------------------------------------------------------------
# retry/backoff


class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls, sleeps = [], []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = retry(flaky, policy=RetryPolicy(max_attempts=4,
                                              base_delay_s=0.01),
                    sleep=sleeps.append)
        assert out == "ok" and len(calls) == 3 and len(sleeps) == 2

    def test_backoff_grows_exponentially_with_bounded_jitter(self):
        import random

        policy = RetryPolicy(max_attempts=5, base_delay_s=0.1, backoff=2.0,
                             max_delay_s=0.3, jitter=0.25)
        rng = random.Random(0)
        d1, d2, d3, d4 = (policy.delay(k, rng) for k in (1, 2, 3, 4))
        assert 0.1 <= d1 <= 0.125
        assert 0.2 <= d2 <= 0.25
        assert 0.3 <= d3 <= 0.375  # capped at max_delay_s pre-jitter
        assert 0.3 <= d4 <= 0.375

    def test_exhausted_attempts_raise_original(self):
        def always():
            raise OSError("permanent-ish")

        with pytest.raises(OSError, match="permanent-ish"):
            retry(always, policy=RetryPolicy(max_attempts=3,
                                             base_delay_s=0.001),
                  sleep=lambda s: None)

    def test_non_retryable_raises_immediately(self):
        calls = []

        def wrong():
            calls.append(1)
            raise ValueError("caller bug")

        with pytest.raises(ValueError):
            retry(wrong, sleep=lambda s: None)
        assert len(calls) == 1

    def test_each_retry_is_a_durable_event(self, tmp_path):
        events = EventLog(tmp_path / "events_rank0.jsonl", rank=0)
        state = {"n": 0}

        def flaky():
            state["n"] += 1
            if state["n"] < 3:
                raise OSError("blip")
            return 1

        retry(flaky, policy=RetryPolicy(max_attempts=4, base_delay_s=0.001),
              describe="unit read", events=events, sleep=lambda s: None)
        events.close()
        evs = read_events(tmp_path)
        assert [e.kind for e in evs] == [EVENT_RETRY, EVENT_RETRY]
        assert evs[0].data["op"] == "unit read"
        assert evs[0].data["attempt"] == 1 and evs[1].data["attempt"] == 2


# ---------------------------------------------------------------------------
# injector mechanics


class TestInjector:
    def test_rank_filter(self):
        plan = FaultPlan.parse("io_err@p=1,rank=1")
        FaultInjector(plan, rank=0).on_io("x")  # not my rank: no raise
        with pytest.raises(OSError, match="injected io_err"):
            FaultInjector(plan, rank=1).on_io("x")

    def test_io_err_count_cap(self):
        inj = FaultInjector(FaultPlan.parse("io_err@p=1,n=2"), rank=0)
        for _ in range(2):
            with pytest.raises(OSError):
                inj.on_io("read")
        inj.on_io("read")  # cap reached: clean from here on

    def test_slow_io_sleeps(self, monkeypatch):
        slept = []
        monkeypatch.setattr(finject.time, "sleep", slept.append)
        inj = FaultInjector(FaultPlan.parse("slow_io@p=1,ms=123"), rank=0)
        inj.on_io("read")
        assert slept == [pytest.approx(0.123)]

    def test_slow_io_probability_deterministic_per_rank(self):
        plan = FaultPlan.parse("io_err@p=0.5,n=0")

        def failures(rank):
            inj = FaultInjector(plan, rank=rank)
            n = 0
            for _ in range(64):
                try:
                    inj.on_io("read")
                except OSError:
                    n += 1
            return n

        a, b = failures(0), failures(0)
        assert a == b  # same seed, same draw sequence
        assert 8 < a < 56  # and it actually mixes

    def test_poison_nan_is_one_shot(self):
        inj = FaultInjector(FaultPlan.parse("nan@step=3"), rank=0)
        assert not inj.poison_nan(2)
        assert inj.poison_nan(3)
        assert not inj.poison_nan(3)  # marker consumed

    def test_one_shot_markers_survive_reincarnation(self, tmp_path):
        """Two injector instances over the same state dir model two
        incarnations of a relaunched worker: the second must NOT re-fire
        a step fault the first already fired (the infinite-crash-loop
        guard)."""
        plan = FaultPlan.parse("nan@step=5")
        first = FaultInjector(plan, rank=0, state_dir=str(tmp_path))
        assert first.poison_nan(5)
        second = FaultInjector(plan, rank=0, state_dir=str(tmp_path))
        assert not second.poison_nan(5)

    def test_injections_emit_events(self, tmp_path):
        events = EventLog(tmp_path / "events_rank0.jsonl", rank=0)
        inj = FaultInjector(FaultPlan.parse("nan@step=1; io_err@p=1,n=1"),
                            rank=0, events=events)
        assert inj.poison_nan(1)
        with pytest.raises(OSError):
            inj.on_io("read")
        evs = read_events(tmp_path)
        assert [e.kind for e in evs] == [EVENT_FAULT, EVENT_FAULT]
        assert {e.data["fault"] for e in evs} == {"nan", "io_err"}


# ---------------------------------------------------------------------------
# single-process injection through the Trainer / data / checkpoint layers
# (the quick-tier representatives)


def _reg_trainer(tmp_path=None, **kw):
    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    return Trainer(MLP(features=(16, 1)), optax.sgd(0.05), mse_loss,
                   mesh=create_mesh(), **kw)


def _reg_loader(size=32, batch=8):
    from pytorchdistributed_tpu.data import (
        DataLoader,
        SyntheticRegressionDataset,
    )

    ds = SyntheticRegressionDataset(size=size, in_dim=8, out_dim=1, seed=0)
    return DataLoader(ds, batch_size=batch, num_replicas=1, rank=0, seed=0)


def test_nan_injection_trips_watchdog(tmp_path, faults_env, monkeypatch):
    """nan@step poisons the loss; the tripwire records a durable event
    BEFORE the watchdog raises — post-mortem first, halt second."""
    monkeypatch.setenv("PTD_TELEMETRY_DIR", str(tmp_path / "tele"))
    faults_env("nan@step=3")
    tr = _reg_trainer(telemetry_dir=str(tmp_path / "tele"), log_every=1)
    with pytest.raises(FloatingPointError, match="loss"):
        tr.fit(_reg_loader(), max_epochs=1)
    kinds = [e.kind for e in read_events(tmp_path / "tele")]
    assert EVENT_FAULT in kinds, kinds
    assert "non_finite_metric" in kinds, kinds
    # the injection fired at exactly the configured step
    ev = next(e for e in read_events(tmp_path / "tele")
              if e.kind == EVENT_FAULT)
    assert ev.step == 3 and ev.data["fault"] == "nan"


def test_corrupt_latest_checkpoint_falls_back(tmp_path):
    """Integrity chain end to end, single process: corrupt the newest
    step's payload → offline verify flags it, a pinned restore refuses
    it, and the default restore quarantines it and loads the previous
    verified step."""
    from pytorchdistributed_tpu.training import checkpoint as ckpt_mod
    from pytorchdistributed_tpu.training.checkpoint import (
        CheckpointIntegrityError,
    )

    loader = _reg_loader()
    tr = _reg_trainer(checkpoint_dir=str(tmp_path / "ck"))
    loader.set_epoch(0)
    for i, batch in enumerate(iter(loader)):
        tr.train_step(batch)
        if i in (1, 3):
            tr._save_checkpoint(force=True)
    tr.checkpoint.wait()  # durable + manifests written
    assert tr.checkpoint.all_steps() == [2, 4]
    for step in (2, 4):
        v = tr.checkpoint.verify_step(step)
        assert v.ok and v.verified, v

    # flip bytes in step 4's largest payload file (manifest untouched)
    sdir = tmp_path / "ck" / "4"
    target = max((p for p in sdir.rglob("*")
                  if p.is_file() and "manifest" not in p.name.lower()),
                 key=lambda p: p.stat().st_size)
    data = bytearray(target.read_bytes())
    for j in range(min(64, len(data))):
        data[j] ^= 0xFF
    target.write_bytes(bytes(data))

    # offline CLI: reports the corruption, exit 1
    assert ckpt_mod.main(["verify", str(tmp_path / "ck")]) == 1
    v = tr.checkpoint.verify_step(4)
    assert not v.ok and v.verified and "mismatch" in v.detail

    # pinned restore is strict
    fresh = _reg_trainer(checkpoint_dir=str(tmp_path / "ck"))
    loader.set_epoch(0)
    batch = next(iter(loader))
    with pytest.raises(CheckpointIntegrityError, match="step 4"):
        fresh.restore(batch, step=4)

    # default restore falls back to the last verified step + quarantines
    state = fresh.restore(batch)
    assert int(state.step) == 2
    assert fresh.checkpoint.all_steps() == [2]
    assert (tmp_path / "ck" / "quarantine" / "4").is_dir()
    # and the post-quarantine directory verifies clean
    assert ckpt_mod.main(["verify", str(tmp_path / "ck")]) == 0


def test_ckpt_corrupt_injection_and_fallback(tmp_path, faults_env):
    """The ckpt_corrupt injection hook: fires once when the matching
    step's save commits + manifest lands, and the fallback walk then
    restores the previous step."""
    import jax

    from pytorchdistributed_tpu.training.checkpoint import (
        CheckpointManager,
        abstract_state_like,
    )

    faults_env("ckpt_corrupt@step=2", state_dir=tmp_path / "state")
    tr = _reg_trainer()
    loader = _reg_loader()
    loader.set_epoch(0)
    it = iter(loader)
    tr.train_step(next(it))
    with CheckpointManager(tmp_path / "ck") as mgr:
        mgr.save(1, tr.state, force=True)
        tr.train_step(next(it))
        mgr.save(2, tr.state, force=True)
        mgr.wait()  # manifests flush; the injection corrupts step 2
        v = mgr.verify_step(2)
        assert not v.ok, v
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tr.state)
        state, step = mgr.restore_verified(
            abstract_state_like(abstract, tr.state_shardings))
        assert step == 1 and int(state.step) == 1
        assert (tmp_path / "ck" / "quarantine" / "2").is_dir()


def test_trainer_meta_is_atomic_and_resume_tolerates_torn_meta(tmp_path):
    """Satellite: the steps_per_epoch sidecar is written via temp +
    os.replace (no .tmp residue, valid JSON), and a torn/missing sidecar
    downgrades the geometry check to a warning instead of bricking
    resume."""
    loader = _reg_loader()
    tr = _reg_trainer(checkpoint_dir=str(tmp_path / "ck"))
    tr.fit(loader, 1)
    step = tr.checkpoint.latest_step()
    meta = tmp_path / "ck" / f"trainer_meta_{step}.json"
    assert meta.exists()
    assert json.loads(meta.read_text())["steps_per_epoch"] == len(loader)
    assert not list((tmp_path / "ck").glob("*.tmp"))

    # torn meta: truncated JSON must warn, not crash — and training
    # continues to the same final step an uninterrupted run reaches
    meta.write_text('{"steps_per_epo')
    resumed = _reg_trainer(checkpoint_dir=str(tmp_path / "ck"))
    resumed.fit(loader, 2, resume=True)
    assert int(resumed.state.step) == 2 * len(loader)

    # missing meta: same tolerance
    tr2 = _reg_trainer(checkpoint_dir=str(tmp_path / "ck2"))
    tr2.fit(loader, 1)
    (tmp_path / "ck2"
     / f"trainer_meta_{tr2.checkpoint.latest_step()}.json").unlink()
    resumed2 = _reg_trainer(checkpoint_dir=str(tmp_path / "ck2"))
    resumed2.fit(loader, 2, resume=True)
    assert int(resumed2.state.step) == 2 * len(loader)


def test_flaky_reader_retried_in_files(tmp_path, faults_env):
    """Satellite: data/files.py reads ride faults/retry — two injected
    transient failures are absorbed; a persistently failing read still
    raises after the policy's attempts."""
    from pytorchdistributed_tpu.data.files import MappedImageDataset

    rng = np.random.default_rng(0)
    np.save(tmp_path / "train_images.npy",
            rng.integers(0, 255, (8, 4, 4, 3)).astype(np.uint8))
    np.save(tmp_path / "train_labels.npy",
            rng.integers(0, 10, (8,)).astype(np.int32))

    faults_env("io_err@p=1,n=2")
    ds = MappedImageDataset(tmp_path)  # 2 failures < 4 attempts: loads
    assert len(ds) == 8
    batch = ds[np.arange(4)]
    assert batch["image"].shape == (4, 4, 4, 3)

    faults_env("io_err@p=1,n=50")
    with pytest.raises(OSError, match="injected io_err"):
        MappedImageDataset(tmp_path)


def test_loader_slow_io_injection(faults_env, monkeypatch):
    """The DataLoader's per-batch hook: slow_io stretches batch assembly
    (observed via a recording sleep), io_err crashes the fetch."""
    slept = []
    monkeypatch.setattr(finject.time, "sleep", slept.append)
    faults_env("slow_io@p=1,ms=50")
    loader = _reg_loader(size=16, batch=8)
    assert len(list(iter(loader))) == 2
    assert slept == [pytest.approx(0.05)] * 2

    faults_env("io_err@p=1,n=1")
    with pytest.raises(OSError, match="injected io_err"):
        list(iter(loader))


# ---------------------------------------------------------------------------
# multi-process chaos through run.py (slow tier)


_CHAOS_WORKER = """
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import optax
from pytorchdistributed_tpu.data import DataLoader, SyntheticRegressionDataset
from pytorchdistributed_tpu.models import MLP
from pytorchdistributed_tpu.runtime.mesh import create_mesh
from pytorchdistributed_tpu.training import Trainer, mse_loss

ds = SyntheticRegressionDataset(size=64, in_dim=8, out_dim=1, seed=0)
loader = DataLoader(ds, batch_size=8, num_replicas=1, rank=0, seed=0)
tr = Trainer(MLP(features=(16, 1)), optax.sgd(0.05), mse_loss,
             mesh=create_mesh(),
             checkpoint_dir=os.environ["PTD_TEST_CKPT"],
             checkpoint_every_steps=2, log_every=1, watchdog=False)
metrics = tr.fit(loader, max_epochs=int(os.environ.get("PTD_TEST_EPOCHS",
                                                       "2")),
                 resume=True)
with open(os.environ["PTD_TEST_OUT"], "w") as f:
    json.dump(metrics, f)
"""


def _run_agent(script, tmp_path, tag, *run_args, env_extra=None,
               epochs="2", timeout=600):
    out = tmp_path / f"{tag}.json"
    env = dict(os.environ,
               PTD_TEST_CKPT=str(tmp_path / f"ckpt_{tag}"),
               PTD_TEST_OUT=str(out), PTD_TEST_EPOCHS=epochs)
    env.pop("PTD_FAULTS", None)
    env.pop("PTD_FAULTS_STATE", None)
    if env_extra:
        env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "1", "--devices-per-proc", "1",
         "--monitor-interval", "0.1", *run_args, str(script)],
        cwd=REPO, timeout=timeout, capture_output=True, text=True, env=env)
    return proc, out


@pytest.fixture(scope="module")
def chaos_script(tmp_path_factory):
    script = tmp_path_factory.mktemp("chaos") / "worker.py"
    script.write_text(textwrap.dedent(_CHAOS_WORKER.format(repo=REPO)))
    return script


@pytest.fixture(scope="module")
def clean_chaos_loss(chaos_script, tmp_path_factory):
    """Final loss of an UNINTERRUPTED 2-epoch run of the chaos worker —
    the continuity baseline every recovery scenario must match."""
    tmp = tmp_path_factory.mktemp("chaos_baseline")
    proc, out = _run_agent(chaos_script, tmp, "clean")
    assert proc.returncode == 0, proc.stderr
    return json.loads(out.read_text())["loss"]


def test_chaos_crash_restart_resumes_loss_continuity(
        chaos_script, tmp_path, clean_chaos_loss):
    """Acceptance anchor: an injected single-rank crash mid-epoch,
    relaunched by --max-restarts, resumes from checkpoint and lands on
    the uninterrupted run's final loss exactly (same data order via
    set_epoch + skip_steps, same per-step rng folded from state.step)."""
    proc, out = _run_agent(chaos_script, tmp_path, "crashed",
                           "--max-restarts", "1",
                           "--faults", "crash@step=6,rank=0")
    assert proc.returncode == 0, proc.stderr
    assert "injected crash at step 6" in proc.stderr, proc.stderr
    assert "restart 1/1" in proc.stderr, proc.stderr
    assert "resumed from step" in proc.stdout, (proc.stdout, proc.stderr)
    assert json.loads(out.read_text())["loss"] == pytest.approx(
        clean_chaos_loss, rel=1e-6)


def test_chaos_hang_heartbeat_relaunch(chaos_script, tmp_path,
                                       clean_chaos_loss):
    """An injected SIGSTOP hang is invisible to exit-watching; the
    heartbeat watchdog must flag it, relaunch, and the resumed
    incarnation must finish with the continuity loss."""
    proc, out = _run_agent(
        chaos_script, tmp_path, "hung",
        "--max-restarts", "1", "--heartbeat-timeout", "3.0",
        "--heartbeat-grace", "120.0",
        "--faults", "hang@step=4,rank=0")
    assert proc.returncode == 0, proc.stderr
    assert "injected hang at step 4" in proc.stderr, proc.stderr
    assert "hung (heartbeat stale)" in proc.stderr, proc.stderr
    assert json.loads(out.read_text())["loss"] == pytest.approx(
        clean_chaos_loss, rel=1e-6)


def test_chaos_preemption_durable_verified_uncharged(
        chaos_script, tmp_path, clean_chaos_loss):
    """SIGTERM preemption contract end to end: the injected preemption
    finishes its step, drains a DURABLE VERIFIED checkpoint, exits with
    the distinct code; the agent restarts it as 'preempted' (never
    attributed to the rank) and the resumed run matches the continuity
    loss. The checkpoint directory passes the offline verify CLI."""
    from pytorchdistributed_tpu.training import checkpoint as ckpt_mod

    proc, out = _run_agent(chaos_script, tmp_path, "preempted",
                           "--max-restarts", "1",
                           "--faults", "preempt@step=3,rank=0")
    assert proc.returncode == 0, proc.stderr
    assert "injected preemption at step 3" in proc.stderr, proc.stderr
    assert "preempted (graceful, checkpoint drained)" in proc.stderr, \
        proc.stderr
    assert "restart 1/1" in proc.stderr, proc.stderr
    # step 3 was forced durable by the handler (not an interval step),
    # resumed from, and the whole surviving directory verifies clean
    assert "resumed from step 3" in proc.stdout, proc.stdout
    assert ckpt_mod.main(["verify", str(tmp_path / "ckpt_preempted")]) == 0
    assert json.loads(out.read_text())["loss"] == pytest.approx(
        clean_chaos_loss, rel=1e-6)


def test_chaos_corrupt_latest_fallback_resume(chaos_script, tmp_path,
                                              clean_chaos_loss):
    """The acceptance scenario through run.py: epoch 1's final checkpoint
    is corrupted on disk between incarnations; the resumed run must
    quarantine it, fall back to the previous verified step, retrain the
    gap, and still land on the continuity loss."""
    proc, _ = _run_agent(chaos_script, tmp_path, "fallback", epochs="1")
    assert proc.returncode == 0, proc.stderr
    ckpt = tmp_path / "ckpt_fallback"
    latest = max(int(p.name) for p in ckpt.iterdir() if p.name.isdigit())
    assert latest == 8
    target = max((p for p in (ckpt / "8").rglob("*")
                  if p.is_file() and "manifest" not in p.name.lower()),
                 key=lambda p: p.stat().st_size)
    data = bytearray(target.read_bytes())
    for j in range(min(64, len(data))):
        data[j] ^= 0xFF
    target.write_bytes(bytes(data))

    proc, out = _run_agent(chaos_script, tmp_path, "fallback")
    assert proc.returncode == 0, proc.stderr
    assert "fell back to step 6" in proc.stdout, (proc.stdout, proc.stderr)
    assert (ckpt / "quarantine" / "8").is_dir()
    assert json.loads(out.read_text())["loss"] == pytest.approx(
        clean_chaos_loss, rel=1e-6)


def test_chaos_repeated_crash_shrinks_but_preemption_never_does(tmp_path):
    """The shrink-tracker attribution rule, same scenario twice: rank 2
    failing twice in a row shrinks the group; rank 2 PREEMPTING twice in
    a row must not — reclaimed capacity is not a bad slot. Synthetic
    steppers (no jax) keep it fast."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, signal, sys, time
        sys.path.insert(0, {REPO!r})
        from pytorchdistributed_tpu.faults import (
            EXIT_PREEMPTED, FaultInjector)
        signal.signal(signal.SIGTERM,
                      lambda s, f: sys.exit(EXIT_PREEMPTED))
        inj = FaultInjector.from_env()
        for s in range(1, 9):
            if inj is not None:
                inj.on_step(s)
            time.sleep(0.05)
    """))

    def run(spec, max_restarts):
        return subprocess.run(
            [sys.executable, "-m", "pytorchdistributed_tpu.run",
             "--nproc-per-node", "3", "--max-restarts", str(max_restarts),
             "--elastic-min-nproc", "2", "--monitor-interval", "0.1",
             "--faults", spec, str(script)],
            cwd=REPO, timeout=180, capture_output=True, text=True,
            env={k: v for k, v in os.environ.items()
                 if k not in ("PTD_FAULTS", "PTD_FAULTS_STATE")})

    # crashes: same rank twice in a row -> elastic shrink (uncharged)
    proc = run("crash@step=2,rank=2; crash@step=3,rank=2", 1)
    assert proc.returncode == 0, proc.stderr
    assert "resizing group to 2 (elastic)" in proc.stderr, proc.stderr

    # preemptions: same rank twice -> two charged restarts, NO shrink
    proc = run("preempt@step=2,rank=2; preempt@step=3,rank=2", 2)
    assert proc.returncode == 0, proc.stderr
    assert "preempted (graceful" in proc.stderr, proc.stderr
    assert "restart 2/2" in proc.stderr, proc.stderr
    assert "resizing" not in proc.stderr, proc.stderr


def test_agent_forwards_signals_to_workers(tmp_path):
    """Satellite: SIGTERM to the AGENT reaches every worker (graceful
    teardown, no orphans) and the agent reports the forwarding."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, signal, sys, time
        tmp = {str(tmp_path)!r}
        rank = os.environ["RANK"]
        def bye(signum, frame):
            open(os.path.join(tmp, "sigterm" + rank), "w").close()
            sys.exit(0)
        signal.signal(signal.SIGTERM, bye)
        open(os.path.join(tmp, "started" + rank), "w").close()
        for _ in range(600):
            time.sleep(0.1)
        sys.exit(3)  # never reached when forwarding works
    """))
    agent = subprocess.Popen(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "2", "--monitor-interval", "0.1",
         "--preempt-grace", "10.0", str(script)],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 60
        while time.time() < deadline and not (
                os.path.exists(tmp_path / "started0")
                and os.path.exists(tmp_path / "started1")):
            time.sleep(0.1)
        assert os.path.exists(tmp_path / "started1"), "workers never started"
        agent.send_signal(signal.SIGTERM)
        stdout, stderr = agent.communicate(timeout=60)
    finally:
        if agent.poll() is None:
            agent.kill()
    assert agent.returncode == 0, stderr  # workers drained with exit 0
    assert "forwarding to workers" in stderr, stderr
    assert os.path.exists(tmp_path / "sigterm0"), stderr
    assert os.path.exists(tmp_path / "sigterm1"), stderr
