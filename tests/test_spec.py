"""Speculative decoding tests (ISSUE 8): draft-and-verify with lossless
rejection sampling, offline (inference.generate_speculative) and inside
the serving engine's compiled tick (serving.engine.spec_decode_tick).

Correctness bar, in three layers:
  * the rejection KERNEL in isolation — greedy accept/cutoff cases,
    accept-0 / accept-all-k edges, and a seeded chi-squared check that
    the emitted token's marginal distribution matches naive target
    sampling (the losslessness theorem, measured);
  * offline generate_speculative — greedy output BITWISE-equal to
    generate() whatever the draft (self-draft, truncated draft, int8,
    GQA/RoPE, unrolled, stop ids);
  * the serving engine at spec_k > 0 — greedy parity vs generate() for
    staggered admissions (incl. prefix-cache hits, preempt-requeue and
    int8), seeded-sampling determinism across admission orders, zero
    steady-state recompiles (TRACE_COUNTS + pjit _cache_size), and the
    acceptance telemetry surfacing in summary() / the JSONL bridge.
"""

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import (
    generate,
    generate_speculative,
    slot_filtered_probs,
    speculative_accept,
    truncated_draft,
)
from pytorchdistributed_tpu.models import (
    GPT2,
    Llama,
    gpt2_config,
    llama_config,
)
from pytorchdistributed_tpu.serving import SamplingParams, ServingEngine
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.engine import (
    paged_prefill_chunk,
    spec_decode_tick,
)


def _init(model, seed=1):
    return model.init(jax.random.key(seed), jnp.zeros((1, 4), jnp.int32))


# ---------------------------------------------------------------------------
# the rejection kernel in isolation


def _onehot(i, v):
    return jnp.zeros((v,), jnp.float32).at[i].set(1.0)


class TestSpeculativeAccept:
    V = 8

    def _run(self, drafts, q, p, unif=None, greedy=True, seed=0):
        drafts = jnp.asarray(drafts, jnp.int32)[None]
        n, k = drafts.shape
        q = jnp.stack(q)[None]
        p = jnp.stack(p)[None]
        u = (jnp.full((1, k), 0.5) if unif is None
             else jnp.asarray(unif, jnp.float32)[None])
        toks, acc = speculative_accept(
            drafts, q, p, u, jax.random.split(jax.random.key(seed), 1),
            jnp.asarray([greedy]))
        return np.asarray(toks)[0], int(acc[0])

    def test_greedy_accept_all_k_plus_bonus(self):
        """All proposals match the target argmax: accept k and emit the
        bonus token from the k+1th target distribution."""
        v = self.V
        q = [_onehot(3, v), _onehot(5, v)]
        p = [_onehot(3, v), _onehot(5, v), _onehot(1, v)]
        toks, acc = self._run([3, 5], q, p)
        assert acc == 2
        assert list(toks) == [3, 5, 1]

    def test_greedy_cutoff_resamples_target_argmax(self):
        """First mismatch at position i: accept i, emit the target's
        token there, ignore the rest of the draft."""
        v = self.V
        q = [_onehot(3, v), _onehot(5, v)]
        p = [_onehot(3, v), _onehot(6, v), _onehot(1, v)]
        toks, acc = self._run([3, 5], q, p)
        assert acc == 1
        assert toks[0] == 3 and toks[1] == 6

    def test_greedy_accept_zero(self):
        """Immediate mismatch: zero proposals kept, one target token."""
        v = self.V
        q = [_onehot(3, v), _onehot(5, v)]
        p = [_onehot(7, v), _onehot(6, v), _onehot(1, v)]
        toks, acc = self._run([3, 5], q, p)
        assert acc == 0
        assert toks[0] == 7

    def test_accept_prob_is_min_p_over_q(self):
        """Sampled rows accept proposal x iff u < p(x)/q(x): a draft
        token twice as likely under the target always survives, one half
        as likely survives exactly when the coin is under 1/2."""
        v = self.V
        q = jnp.full((v,), 1.0 / v)
        # p(0) = 2/v, p(1) = 0.5/v, remainder spread uniformly
        rest = (1.0 - 2.0 / v - 0.5 / v) / (v - 2)
        p = jnp.full((v,), rest).at[0].set(2.0 / v).at[1].set(0.5 / v)
        bonus = jnp.full((v,), 1.0 / v)
        # token 0 (ratio 2): accepted at u=0.99
        _, acc = self._run([0], [q], [p, bonus], unif=[0.99], greedy=False)
        assert acc == 1
        # token 1 (ratio 0.5): rejected at u=0.6, accepted at u=0.4
        _, acc = self._run([1], [q], [p, bonus], unif=[0.6], greedy=False)
        assert acc == 0
        _, acc = self._run([1], [q], [p, bonus], unif=[0.4], greedy=False)
        assert acc == 1

    def test_chi_squared_first_token_matches_target(self):
        """The losslessness theorem, measured: whatever q proposes, the
        FIRST emitted token is distributed exactly as p_1. Run the kernel
        over many independent rows (a deliberately skewed q vs a
        different p) and chi-squared the first-token histogram against
        p_1 — and, as the power check, against q (which must be
        rejected)."""
        v, n, k = 8, 20000, 2
        key = jax.random.key(42)
        kq, ku, kr = jax.random.split(key, 3)
        q1 = jnp.asarray([0.4, 0.3, 0.1, 0.05, 0.05, 0.05, 0.03, 0.02])
        p1 = jnp.asarray([0.1, 0.1, 0.3, 0.2, 0.1, 0.1, 0.05, 0.05])
        flat = jnp.asarray([1 / v] * v)
        drafts = jax.random.categorical(
            kq, jnp.log(q1)[None].repeat(n * k, 0)).reshape(n, k)
        q = jnp.broadcast_to(q1, (n, k, v))
        p = jnp.broadcast_to(
            jnp.stack([p1, flat, flat]), (n, k + 1, v))
        unif = jax.random.uniform(ku, (n, k))
        toks, _ = speculative_accept(
            drafts.astype(jnp.int32), q, p, unif,
            jax.random.split(kr, n), jnp.zeros((n,), bool))
        first = np.asarray(toks)[:, 0]
        counts = np.bincount(first, minlength=v).astype(np.float64)

        def chi2(expected):
            e = np.asarray(expected, np.float64) * n
            return float(((counts - e) ** 2 / e).sum())

        # 7 dof: 0.1% critical value 24.3 — the match must clear it and
        # the wrong distribution must blow far past it
        assert chi2(p1) < 24.3, (chi2(p1), counts / n)
        assert chi2(q1) > 200.0, (chi2(q1), counts / n)

    def test_vectorized_rows_independent(self):
        """Per-row greedy/sampled mix in one call: with one-hot p/q both
        row kinds resolve deterministically to the same accept + bonus
        (the sampled row's categorical over a one-hot has one outcome) —
        rows never leak into each other."""
        v = self.V
        drafts = jnp.asarray([[3], [3]], jnp.int32)
        q = jnp.broadcast_to(_onehot(3, v), (2, 1, v))
        p = jnp.broadcast_to(
            jnp.stack([_onehot(3, v), _onehot(5, v)]), (2, 2, v))
        toks, acc = speculative_accept(
            drafts, q, p, jnp.full((2, 1), 0.5),
            jax.random.split(jax.random.key(0), 2),
            jnp.asarray([True, False]))
        assert list(np.asarray(acc)) == [1, 1]
        assert list(np.asarray(toks)[:, 1]) == [5, 5]


def test_slot_filtered_probs_matches_sampler_distribution():
    """slot_filtered_probs must be the EXACT distribution sample_slots
    draws from: empirical frequencies of the sampler converge on the
    probability vector (same candidate filter by construction — this
    pins the refactor's coupling), and greedy rows are exact one-hots."""
    from pytorchdistributed_tpu.inference import sample_slots

    v, n = 16, 4000
    logits = jax.random.normal(jax.random.key(0), (1, v)) * 2.0
    temps = jnp.asarray([0.9])
    tks = jnp.asarray([5], jnp.int32)
    tps = jnp.asarray([0.95])
    probs = np.asarray(slot_filtered_probs(logits, temps, tks, tps,
                                           candidates=8))[0]
    assert abs(probs.sum() - 1.0) < 1e-5
    assert (probs > 0).sum() <= 5  # top_k respected
    reps = jnp.broadcast_to(logits, (n, v))
    toks = sample_slots(reps, jax.random.split(jax.random.key(1), n),
                        jnp.full((n,), 0.9), jnp.full((n,), 5, jnp.int32),
                        jnp.full((n,), 0.95), candidates=8)
    freq = np.bincount(np.asarray(toks), minlength=v) / n
    np.testing.assert_allclose(freq, probs, atol=0.03)
    greedy = np.asarray(slot_filtered_probs(
        logits, jnp.asarray([0.0]), tks, tps, candidates=8))[0]
    assert greedy[int(np.asarray(logits).argmax())] == 1.0
    assert greedy.sum() == 1.0


# ---------------------------------------------------------------------------
# offline generate_speculative


def _greedy_parity(model_cls, cfg, *, spec_k=4, draft=None, eos_id=None,
                   max_new=12):
    model = model_cls(cfg)
    params = _init(model)
    dm = model_cls(dataclasses.replace(cfg, decode=True))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 7)), jnp.int32)
    kw = {}
    if draft is not None:
        d, dp = truncated_draft(dm, params, draft)
        kw = dict(draft_model=d, draft_params=dp)
    ref = generate(dm, params, prompt, max_new_tokens=max_new,
                   eos_id=eos_id)
    out = generate_speculative(dm, params, prompt, max_new_tokens=max_new,
                               spec_k=spec_k, eos_id=eos_id, **kw)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    return params, dm, prompt


def test_offline_greedy_bitwise_gpt2():
    _greedy_parity(GPT2, gpt2_config("test", num_layers=2, max_seq_len=64))


def test_offline_greedy_bitwise_llama_gqa_rope():
    _greedy_parity(Llama, llama_config("test", max_seq_len=64))


def test_offline_greedy_bitwise_int8fwd():
    _greedy_parity(GPT2, gpt2_config("test", num_layers=2, max_seq_len=64,
                                     quant="int8_fwd"))


def test_offline_greedy_bitwise_truncated_draft():
    """Losslessness does not depend on draft quality: a 1-layer
    truncation of a 2-layer target still yields bitwise generate()
    output (only the acceptance rate may drop)."""
    _greedy_parity(GPT2, gpt2_config("test", num_layers=2, max_seq_len=64),
                   spec_k=3, draft=1)
    _greedy_parity(GPT2, gpt2_config("test", num_layers=2, max_seq_len=64,
                                     scan_layers=False), spec_k=3, draft=1)


def test_offline_greedy_bitwise_stop_ids():
    """A stop id emitted mid-round freezes the row exactly like
    generate(): the remainder pads with the first stop id."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 7)), jnp.int32)
    chain = np.asarray(generate(dm, params, prompt, max_new_tokens=12))
    stop = int(chain[0, 7 + 3])  # mid-chain token doubles as the stop id
    ref = generate(dm, params, prompt, max_new_tokens=12, eos_id=stop)
    out = generate_speculative(dm, params, prompt, max_new_tokens=12,
                               spec_k=4, eos_id=stop)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_offline_falls_back_when_context_tight():
    """No room for the verify overshoot (prompt + new + k > max_seq_len)
    → silently the plain generate() path, same output."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=32)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    prompt = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (1, 20)),
        jnp.int32)
    ref = generate(dm, params, prompt, max_new_tokens=12)
    out = generate_speculative(dm, params, prompt, max_new_tokens=12,
                               spec_k=4)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_truncated_draft_validations():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(dataclasses.replace(cfg, decode=True))
    params = _init(GPT2(cfg))
    with pytest.raises(ValueError, match="num_layers"):
        truncated_draft(model, params, 0)
    with pytest.raises(ValueError, match="num_layers"):
        truncated_draft(model, params, 2)
    draft, dp = truncated_draft(model, params, 1)
    assert draft.cfg.num_layers == 1
    stacked = jax.tree.leaves(dp["params"]["h"]["block"])
    assert all(leaf.shape[0] == 1 for leaf in stacked)


# ---------------------------------------------------------------------------
# the serving engine at spec_k > 0


def _spec_engine_parity(cfg, engine_kw, n_requests=5, model_cls=GPT2,
                        max_steps=1_000_000):
    model = model_cls(cfg)
    params = _init(model)
    dm = model_cls(dataclasses.replace(cfg, decode=True))
    rng = np.random.default_rng(0)
    lens = [5, 9, 3, 13, 7, 11, 4, 8, 6][:n_requests]
    news = [6, 3, 8, 5, 4, 7, 2, 5, 3][:n_requests]
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in lens]
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16,
                           block_size=8, **engine_kw)
    engine.warmup(prompt_lens=(8, 16))
    reqs = []
    for p, n in zip(prompts, news):
        reqs.append(engine.submit(p, max_new_tokens=n))
        engine.step()
    engine.run_until_idle(max_steps)
    for p, n, r in zip(prompts, news, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=n)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0],
                                      err_msg=f"request {r.id}")
    return engine, reqs


def test_engine_spec_parity_greedy():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    engine, _ = _spec_engine_parity(cfg, dict(spec_k=4))
    s = engine.summary()
    assert s["spec_k"] == 4
    assert s["acceptance_rate"] == 1.0  # self-draft: every proposal kept
    assert s["tokens_per_target_forward"] > 1.0
    engine.close()


def test_engine_spec_parity_llama_and_int8():
    _spec_engine_parity(llama_config("test", max_seq_len=64),
                        dict(spec_k=3), model_cls=Llama)[0].close()
    _spec_engine_parity(
        gpt2_config("test", num_layers=2, max_seq_len=64,
                    quant="int8_fwd"), dict(spec_k=3))[0].close()


def test_engine_spec_parity_truncated_draft():
    """The serving restatement of losslessness-vs-draft-quality: a
    1-layer truncated draft serving a 2-layer target stays bitwise."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    draft, dp = truncated_draft(
        GPT2(dataclasses.replace(cfg, decode=True)), params, 1)
    engine, _ = _spec_engine_parity(
        cfg, dict(spec_k=3, draft_config=draft.cfg, draft_params=dp))
    assert engine.draft_kv_hbm_bytes < engine.kv_hbm_bytes
    engine.close()


def test_engine_spec_prefix_hits_stay_bitwise():
    """Radix prefix reuse composes: target K/V admits by block
    reference while the draft re-prefills the whole prompt into the
    SAME blocks of its own pool — shared-prefix traffic stays bitwise
    and still records cache hits."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    rng = np.random.default_rng(3)
    system = rng.integers(0, cfg.vocab_size, (24,)).astype(np.int32)
    prompts = [np.concatenate([
        system, rng.integers(0, cfg.vocab_size, (4,)).astype(np.int32)])
        for _ in range(4)]
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16,
                           block_size=8, prefill_chunk=16, spec_k=3)
    engine.warmup(prompt_lens=(16,))
    reqs = []
    for p in prompts:
        reqs.append(engine.submit(p, max_new_tokens=6))
        engine.step()
    engine.run_until_idle()
    for p, r in zip(prompts, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=6)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0])
    s = engine.summary()
    assert s["prefix_hit_rate"] > 0
    # the draft prefill also starts at the hit offset (cached blocks keep
    # their draft K/V): a stale reused block would surface here as
    # self-draft acceptance dropping below 1 — losslessness hides it from
    # the bitwise check above, so pin the acceptance side too
    assert s["acceptance_rate"] == 1.0
    engine.close()


def test_engine_spec_preemption_stays_bitwise():
    """Pool pressure under spec: growth must back the whole verify span
    (len..len+k), preempted requests resume by re-prefilling BOTH caches
    — streams bitwise-unchanged."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    rng = np.random.default_rng(0)
    pages = cfg.max_seq_len // 8
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16,
                           block_size=8, num_blocks=pages + 2, spec_k=3,
                           prefix_cache=False)
    engine.warmup(prompt_lens=(8,))
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in (14, 15, 13)]
    reqs = [engine.submit(p, max_new_tokens=24) for p in prompts]
    for _ in range(3):
        engine.step()
    engine.run_until_idle()
    assert sum(r.preemptions for r in reqs) >= 1, \
        "pool pressure never preempted — shrink num_blocks"
    for p, r in zip(prompts, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=24)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0],
                                      err_msg=f"request {r.id}")
    engine.close()


def test_engine_spec_zero_recompiles_and_determinism():
    """Steady-state spec serving performs ZERO retraces and zero
    recompiles after warmup, and seeded sampled outputs are a function
    of (prompt, sampling, seed) alone — admission order moves nothing."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in (5, 9, 3, 7)]
    news = [6, 3, 8, 5]
    sampling = [SamplingParams(temperature=0.8, top_k=10, seed=100 + i)
                for i in range(4)]

    def run(order):
        engine = ServingEngine(model, params, num_slots=2,
                               prefill_bucket=16, block_size=8, spec_k=3)
        engine.warmup(prompt_lens=(8, 16))
        traces = dict(serving_engine.TRACE_COUNTS)
        sizes = (spec_decode_tick._cache_size(),
                 paged_prefill_chunk._cache_size())
        reqs = {}
        for i in order:
            reqs[i] = engine.submit(prompts[i], max_new_tokens=news[i],
                                    sampling=sampling[i])
            engine.step()
        engine.run_until_idle()
        assert dict(serving_engine.TRACE_COUNTS) == traces
        assert (spec_decode_tick._cache_size(),
                paged_prefill_chunk._cache_size()) == sizes
        engine.close()
        return {i: list(r.new_tokens) for i, r in reqs.items()}

    assert run([0, 1, 2, 3]) == run([3, 1, 0, 2])


def test_engine_spec_requires_paged():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(model, _init(model), num_slots=2, spec_k=2)
    with pytest.raises(ValueError, match="draft_params"):
        ServingEngine(model, _init(model), num_slots=2, block_size=8,
                      spec_k=2, draft_config=cfg)


def test_engine_spec_telemetry_rows(tmp_path):
    """The JSONL bridge carries the speculation health columns: request
    rows grow draft/accepted counts, the pool row stamps the aggregate
    acceptance_rate, and the report CLI renders the acceptance column."""
    from pytorchdistributed_tpu.telemetry.report import render

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=2,
                           prefill_bucket=16, block_size=8, spec_k=3,
                           telemetry_dir=str(tmp_path))
    engine.warmup(prompt_lens=(16,))
    rng = np.random.default_rng(0)
    for _ in range(3):
        engine.submit(rng.integers(0, cfg.vocab_size, (5,)),
                      max_new_tokens=4)
    engine.run_until_idle()
    engine.close()
    rows = [json.loads(x) for x in
            (tmp_path / "serve_metrics_rank0.jsonl")
            .read_text().strip().splitlines()]
    done = [r for r in rows if r["kind"] == "request"
            and r["new_tokens"] == 4]
    assert len(done) == 3
    assert all(r["draft_tokens"] > 0 for r in done)
    assert all(0 <= r["accepted_tokens"] <= r["draft_tokens"]
               for r in done)
    pool = next(r for r in reversed(rows) if r["kind"] == "pool")
    assert pool["spec_k"] == 3
    assert pool["acceptance_rate"] == 1.0  # self-draft
    ticks = [r for r in rows if r["kind"] == "tick"]
    assert any("accepted_tokens" in r for r in ticks)
    report = render(str(tmp_path))
    assert "acc rate" in report and "100.00%" in report


# ---------------------------------------------------------------------------
# learned drafting (ISSUE 16): proposal heads, adaptive k, draft hot-swap


def test_offline_greedy_bitwise_proposal_heads():
    """Medusa-style proposal heads: ONE draft forward proposes the whole
    k-token window, and rejection keeps the stream bitwise-equal to
    generate() — losslessness is independent of what the heads emit."""
    from pytorchdistributed_tpu.inference import make_draft

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    draft, dp = make_draft(dm, params, num_layers=1, spec_heads=3)
    assert draft.cfg.spec_heads == 3
    rng = np.random.default_rng(2)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 9)),
                         jnp.int32)
    ref = generate(dm, params, prompt, max_new_tokens=12)
    out = generate_speculative(dm, params, prompt, max_new_tokens=12,
                               spec_k=4, draft_model=draft,
                               draft_params=dp)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_make_draft_validations():
    from pytorchdistributed_tpu.inference import make_draft

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(dataclasses.replace(cfg, decode=True))
    params = _init(GPT2(cfg))
    with pytest.raises(ValueError, match="spec_heads"):
        make_draft(model, params, spec_heads=-1)
    # num_layers None / equal keeps the full stack (self-draft-sized)
    d, _ = make_draft(model, params, num_layers=None, spec_heads=2)
    assert d.cfg.num_layers == 2 and d.cfg.spec_heads == 2


def test_engine_adaptive_k_varies_without_retrace():
    """Per-slot adaptive proposal depth: with a lossy (truncated+heads)
    draft the acceptance EMA moves k_eff off its ceiling, streams stay
    bitwise vs generate(), and the steady state performs ZERO fresh
    traces and zero pjit cache growth while k varies."""
    from pytorchdistributed_tpu.inference import make_draft
    from pytorchdistributed_tpu.serving.engine import (
        spec_decode_tick_heads,
    )

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    draft, dp = make_draft(dm, params, num_layers=1, spec_heads=3)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in (5, 9, 3, 13, 7)]
    news = [8, 5, 9, 6, 7]
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16,
                           block_size=8, spec_k=4, draft_config=draft.cfg,
                           draft_params=dp, adaptive_k=True)
    engine.warmup(prompt_lens=(8, 16))
    traces = dict(serving_engine.TRACE_COUNTS)
    size0 = spec_decode_tick_heads._cache_size()
    reqs, seen_k = [], set()
    for p, n in zip(prompts, news):
        reqs.append(engine.submit(p, max_new_tokens=n))
        engine.step()
        seen_k.update(np.asarray(engine._k_eff).tolist())
    engine.run_until_idle()
    assert dict(serving_engine.TRACE_COUNTS) == traces
    assert spec_decode_tick_heads._cache_size() == size0
    assert len(seen_k) > 1, \
        "adaptive k never moved — the truncated draft accepted everything"
    for p, n, r in zip(prompts, news, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=n)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0])
    s = engine.summary()
    assert s["adaptive_k"] is True
    assert 0.0 < s["accept_ema"] <= 1.0
    assert 1.0 <= s["effective_k"] <= 4.0
    engine.close()


def test_engine_draft_hot_swap_midstream_bitwise():
    """set_draft_params mid-stream: resident streams keep ticking and
    stay bitwise vs generate() across the swap (draft values move
    acceptance only — the rejection kernel is lossless either way), the
    swap counter and params fingerprint update, and — the committedness
    regression — swapping COMMITTED device_put leaves over the boot
    tree's uncommitted ones must not grow the pjit cache."""
    from pytorchdistributed_tpu.inference import make_draft
    from pytorchdistributed_tpu.serving.engine import (
        spec_decode_tick_heads,
    )

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    draft, dp = make_draft(dm, params, num_layers=1, spec_heads=2)
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in (6, 11, 4)]
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16,
                           block_size=8, spec_k=3, draft_config=draft.cfg,
                           draft_params=dp)
    engine.warmup(prompt_lens=(8, 16))
    reqs = [engine.submit(p, max_new_tokens=16) for p in prompts]
    engine.step()
    hash0 = engine.draft_params_hash()
    size0 = spec_decode_tick_heads._cache_size()
    traces = dict(serving_engine.TRACE_COUNTS)
    # a genuinely different draft, shipped as COMMITTED arrays (the
    # checkpoint-restore shape): same treedef/shapes/dtypes, new values
    perturbed = jax.tree.map(
        lambda x: jax.device_put(np.asarray(x) * np.float32(0.5),
                                 jax.devices()[0]),
        dp["params"])
    engine.set_draft_params({"params": perturbed})
    assert engine.draft_swaps == 1
    assert engine.draft_params_hash() != hash0
    engine.run_until_idle()
    assert spec_decode_tick_heads._cache_size() == size0
    assert dict(serving_engine.TRACE_COUNTS) == traces
    for p, r in zip(prompts, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=16)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0],
                                      err_msg=f"request {r.id}")
    assert engine.summary()["draft_swaps"] == 1
    engine.close()


def test_engine_draft_hot_swap_refusals():
    """A hot-swap may only replace VALUES: architecture (treedef),
    shape, and dtype changes are refused loudly, and spec-off engines
    have no draft to swap."""
    from pytorchdistributed_tpu.inference import make_draft

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    draft, dp = make_draft(dm, params, num_layers=1, spec_heads=2)
    engine = ServingEngine(model, params, num_slots=2, prefill_bucket=16,
                           block_size=8, spec_k=3, draft_config=draft.cfg,
                           draft_params=dp)
    other, odp = make_draft(dm, params, num_layers=1, spec_heads=1)
    with pytest.raises(ValueError, match="structure"):
        engine.set_draft_params(odp)
    with pytest.raises(ValueError, match="dtype"):
        engine.set_draft_params(jax.tree.map(
            lambda x: jnp.asarray(x, jnp.bfloat16), dp["params"]))
    engine.close()
    plain = ServingEngine(model, params, num_slots=2, prefill_bucket=16,
                          block_size=8)
    with pytest.raises(ValueError, match="spec_k"):
        plain.set_draft_params(dp)
    plain.close()
