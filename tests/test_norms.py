"""Fused-norm equivalence: the custom_vjp norms (ops/norms.py) must match
flax's nn.RMSNorm / nn.LayerNorm — values AND gradients — since every model
routes through them (models/transformer.py _layer_norm)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorchdistributed_tpu.ops.norms import (
    FusedLayerNorm,
    FusedRMSNorm,
    layernorm,
    rmsnorm,
)


def _grads(fn, *args):
    return jax.grad(lambda *a: jnp.sum(fn(*a) ** 2), argnums=range(len(args))
                    )(*args)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_rmsnorm_matches_flax(dtype):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 16, 32)) * 3, dtype)
    scale = jnp.asarray(rng.standard_normal(32) * 0.5 + 1.0, jnp.float32)
    ref_mod = nn.RMSNorm(dtype=jnp.float32, use_scale=True)
    ref = lambda x, s: ref_mod.apply({"params": {"scale": s}}, x)
    got = lambda x, s: rmsnorm(x, s, 1e-6)
    # forward
    np.testing.assert_allclose(got(x, scale), ref(x, scale),
                               rtol=1e-5, atol=1e-5)
    # grads — bf16 inputs quantize dx to bf16, hence the looser tolerance
    tol = dict(rtol=1e-4, atol=1e-4) if dtype == jnp.float32 else \
        dict(rtol=2e-2, atol=2e-2)
    for a, b in zip(_grads(got, x, scale), _grads(ref, x, scale)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **tol)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_layernorm_matches_flax(dtype):
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 16, 32)) * 3 + 1, dtype)
    scale = jnp.asarray(rng.standard_normal(32) * 0.5 + 1.0, jnp.float32)
    bias = jnp.asarray(rng.standard_normal(32) * 0.1, jnp.float32)
    ref_mod = nn.LayerNorm(dtype=jnp.float32)
    ref = lambda x, s, b: ref_mod.apply({"params": {"scale": s, "bias": b}}, x)
    got = lambda x, s, b: layernorm(x, s, b, 1e-6)
    np.testing.assert_allclose(got(x, scale, bias), ref(x, scale, bias),
                               rtol=1e-5, atol=1e-5)
    tol = dict(rtol=1e-4, atol=1e-4) if dtype == jnp.float32 else \
        dict(rtol=2e-2, atol=2e-2)
    for a, b in zip(_grads(got, x, scale, bias),
                    _grads(ref, x, scale, bias)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), **tol)


def test_fused_norms_config_matches_default():
    """cfg.fused_norms=True is a drop-in: identical param trees (same init)
    and a training loss curve matching the flax-norm path to fp32
    tolerance, for both norm dialects."""
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import local_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    rng = np.random.default_rng(11)
    batch = {
        "tokens": rng.integers(0, 128, (8, 16)).astype(np.int32),
        "targets": rng.integers(0, 128, (8, 16)).astype(np.int32),
    }
    for norm in ("layernorm", "rmsnorm"):
        losses = {}
        for fused in (False, True):
            model = GPT2(gpt2_config("test", dtype=np.float32, norm=norm,
                                     fused_norms=fused))
            tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                         mesh=local_mesh(1), log_every=10**9)
            losses[fused] = [float(tr.train_step(batch)["loss"])
                             for _ in range(3)]
        np.testing.assert_allclose(losses[True], losses[False],
                                   rtol=2e-5, atol=1e-6)


def test_fused_norms_compose_with_remat_and_fsdp():
    """The bench configs that would flip fused_norms on run remat
    (Llama: dots_all) and sharded params — the custom_vjp must hold its
    equivalence under jax.checkpoint recompute and a ZeRO-3 sharded scale
    param."""
    import optax

    from pytorchdistributed_tpu.models import Llama, llama_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    rng = np.random.default_rng(12)
    batch = {
        "tokens": rng.integers(0, 128, (16, 16)).astype(np.int32),
        "targets": rng.integers(0, 128, (16, 16)).astype(np.int32),
    }
    losses = {}
    for fused in (False, True):
        cfg = llama_config("test", dtype=np.float32, fused_norms=fused,
                           remat=True, remat_policy="dots_all")
        tr = Trainer(Llama(cfg), optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(data=2, fsdp=4), strategy="fsdp",
                     remat=True, log_every=10**9)
        losses[fused] = [float(tr.train_step(batch)["loss"])
                         for _ in range(3)]
    np.testing.assert_allclose(losses[True], losses[False],
                               rtol=2e-5, atol=1e-6)


def test_fused_modules_param_trees_match_flax():
    """Checkpoint compatibility: same param names/shapes as the flax
    modules they replace."""
    x = jnp.ones((2, 8))
    fused = FusedRMSNorm().init(jax.random.key(0), x)
    flax_ = nn.RMSNorm().init(jax.random.key(0), x)
    assert jax.tree.structure(fused) == jax.tree.structure(flax_)
    fused = FusedLayerNorm().init(jax.random.key(0), x)
    flax_ = nn.LayerNorm().init(jax.random.key(0), x)
    assert jax.tree.structure(fused) == jax.tree.structure(flax_)
