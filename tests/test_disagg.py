"""Prefill/decode disaggregation (ISSUE 12: KV block streaming + the
fleet-wide radix prefix cache).

Correctness bar, inherited from the paged engine and the router chaos
suite: a stream that prefills on one engine and decodes on another —
through ``export_kv_blocks``/``import_kv_blocks`` in-process, or over
the subprocess wire — must be BITWISE-identical (greedy AND seeded) to
the same request served colocated, because the payload carries the
exact K/V of [0, true_len) plus the per-token fold_in count. On top:
the FleetPrefixIndex/radix local-remote split units, the wire codec
round-trip, import validation walls, lossless failover when either
role dies mid-handoff, deterministic fleet prefix steering + block
shipping, and the zero-recompile guarantee across a steady-state
handoff.

Engine geometry mirrors tests/test_router.py (gpt2 "test", 2 layers,
max_seq_len 64, slots 3, bucket 16, paged block 8) so the compiled
programs are shared across the suite's jit cache.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.faults.inject import (
    FaultInjector,
    FaultPlan,
)
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.serving import (
    ROLE_DECODE,
    ROLE_PREFILL,
    BlockAllocator,
    FleetPrefixIndex,
    KVBlockPayload,
    RadixPrefixCache,
    ReplicaRouter,
    SamplingParams,
    ServingEngine,
    block_hashes,
    kv_payload_from_wire,
    kv_payload_to_wire,
)
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.engine import (
    kv_block_gather,
    kv_block_scatter,
    paged_decode_tick,
    paged_prefill_chunk,
)

CFG = gpt2_config("test", num_layers=2, max_seq_len=64)


@functools.cache
def _setup():
    model = GPT2(CFG)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    return model, params, dm


def _ref(prompt, n):
    _, params, dm = _setup()
    return np.asarray(generate(dm, params, jnp.asarray(prompt)[None],
                               max_new_tokens=n))[0]


def _engine(**kw):
    model, params, _ = _setup()
    ek = dict(num_slots=3, prefill_bucket=16, block_size=8)
    ek.update(kw)
    engine = ServingEngine(model, params, **ek)
    engine.warmup(prompt_lens=(16, 32))
    engine.warmup_kv_stream()
    return engine


def _router(roles, *, faults=None, **kw):
    model, params, _ = _setup()
    router = ReplicaRouter(
        model, params, replicas=len(roles), roles=roles,
        engine_kwargs=dict(num_slots=3, prefill_bucket=16, block_size=8),
        warmup_lens=(16, 32), faults=faults, **kw)
    router.warmup()
    return router


# ----------------------------------------------------------------------
# host units (no jax work)


def test_fleet_prefix_index_units():
    idx = FleetPrefixIndex()
    chain = ["a", "ab", "abc", "abcd"]
    assert idx.best_match(chain) == (None, 0)
    idx.update(0, ["a", "ab"])
    idx.update(1, ["a", "ab", "abc"])
    assert idx.match_depth(0, chain) == 2
    assert idx.match_depth(1, chain) == 3
    assert idx.match_depth(2, chain) == 0
    assert idx.best_match(chain) == (1, 3)
    # eligibility restricts candidates (quarantined/dead replicas)
    assert idx.best_match(chain, eligible={0}) == (0, 2)
    assert idx.best_match(chain, eligible=set()) == (None, 0)
    # chained digests: membership is prefix-positional, a hole ends it
    idx.update(2, ["abc"])  # holds block 3's digest but not 1/2
    assert idx.match_depth(2, chain) == 0
    # ties break to the lowest index (deterministic steering)
    idx.update(3, ["a", "ab", "abc"])
    assert idx.best_match(chain) == (1, 3)
    # optimistic add extends; the next snapshot REPLACES (evictions and
    # frontier churn age out, nothing accumulates forever)
    idx.add(0, ["abc", "abcd"])
    assert idx.match_depth(0, chain) == 4
    idx.update(0, ["a"])
    assert idx.match_depth(0, chain) == 1
    idx.remove(1)
    assert idx.best_match(chain) == (3, 3)
    assert idx.replicas() == [0, 2, 3]


def test_radix_remote_split_and_frontier():
    """Fleet-shipped (remote) prefix blocks count as STEERED hits,
    split out of the local hit_rate; frontier() publishes the chained
    digests best_match consumes."""
    alloc = BlockAllocator(16, 4)
    cache = RadixPrefixCache(alloc)
    toks = np.arange(12, dtype=np.int32)
    blocks = alloc.alloc(3)
    assert cache.insert(toks, blocks, remote=True) == 3
    # the published frontier IS the block_hashes chain of the insert
    assert set(cache.frontier()) == set(block_hashes(toks, 4))
    assert cache.match(toks) == blocks
    remote = sum(1 for n in cache.match_nodes(toks) if n.remote)
    assert remote == 3
    cache.record_admission(3, 12, remote_blocks=3)
    st = cache.stats()
    assert st["hits"] == 0 and st["hit_tokens"] == 0
    assert st["remote_hits"] == 1 and st["remote_hit_tokens"] == 12
    assert st["remote_token_hit_rate"] == 1.0
    # a later LOCAL admission through the same nodes counts locally
    cache.record_admission(2, 12)
    st = cache.stats()
    assert st["hits"] == 1 and st["hit_tokens"] == 8
    assert st["remote_hits"] == 1


def test_kv_payload_wire_roundtrip():
    """The subprocess handoff codec is lossless for every field —
    including non-native dtypes (bf16 pools) via the ml_dtypes name
    path — so a wire hop cannot perturb the bitwise guarantee."""
    import ml_dtypes

    rng = np.random.default_rng(0)
    leaves = [
        ("layer/cached_key", rng.standard_normal(
            (2, 3, 8, 2, 4)).astype(np.float32)),
        ("layer/cached_value", rng.standard_normal(
            (2, 3, 8, 2, 4)).astype(ml_dtypes.bfloat16)),
    ]
    payload = KVBlockPayload(
        prompt=np.arange(17, dtype=np.int32), generated=[5, 9],
        true_len=18, block_size=8, max_new_tokens=6,
        sampling=SamplingParams(temperature=0.7, top_k=8, seed=3),
        stop_ids=(2, 4), leaves=leaves)
    back = kv_payload_from_wire(kv_payload_to_wire(payload))
    assert back.generated == [5, 9] and back.true_len == 18
    assert back.block_size == 8 and back.max_new_tokens == 6
    assert back.stop_ids == (2, 4)
    assert (back.sampling.temperature, back.sampling.top_k,
            back.sampling.seed) == (0.7, 8, 3)
    np.testing.assert_array_equal(back.prompt, payload.prompt)
    for (n0, a0), (n1, a1) in zip(leaves, back.leaves):
        assert n0 == n1 and a0.dtype == a1.dtype
        np.testing.assert_array_equal(
            a0.view(np.uint8), a1.view(np.uint8))  # bit-exact
    assert back.num_blocks == 3 and back.nbytes == payload.nbytes


# ----------------------------------------------------------------------
# engine-level KV stream


def _handoff_all(src, dst, handles):
    """Drive ``src`` until every prefill_only handle parks + exports,
    importing each into ``dst`` as it lands; returns {id: imported}."""
    moved, pending = {}, []
    for _ in range(500):
        if len(moved) == len(handles) and not pending:
            return moved
        src.step()
        for req in list(src.parked_requests):
            pending.append((req, src.export_kv_blocks(req)))
        still = []
        for req, payload in pending:
            out = dst.import_kv_blocks(payload)
            if out is None:        # importer full: the payload is
                still.append((req, payload))  # self-contained, retry
            else:
                moved[req.id] = (req, out)
        pending = still
        dst.step()  # imports decode while later prefills still chunk
    raise AssertionError(f"only {len(moved)}/{len(handles)} landed")


def test_kv_roundtrip_bitwise_ragged_lengths():
    """The acceptance anchor: prompts straddling the block grid
    (k*bs - 1, k*bs, k*bs + 1 at bs=8) prefill on engine A, hand their
    KV blocks to engine B, and the merged stream is bitwise-equal to
    generate() — the partial-tail-block and exact-boundary export
    paths both survive the gather→host→scatter trip."""
    src, dst = _engine(), _engine()
    rng = np.random.default_rng(7)
    lens, news = [7, 8, 9, 16, 17], [9, 8, 7, 6, 5]
    prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
               for m in lens]
    handles = [src.submit(p, max_new_tokens=n, prefill_only=True)
               for p, n in zip(prompts, news)]
    moved = _handoff_all(src, dst, handles)
    # after export the prefill engine holds NOTHING for the streams
    assert not src.parked_requests
    assert all(h.slot is None for h in handles)
    dst.run_until_idle()
    for h, p, n in zip(handles, prompts, news):
        _, out = moved[h.id]
        assert out.finish_reason == "length"
        # the exporter delivered exactly the first token; the importer's
        # resume guard means it never re-delivers it
        assert h.new_tokens == out.new_tokens[:1]
        assert out.resumed_from == 1
        np.testing.assert_array_equal(out.output_ids, _ref(p, n))
    st = src.summary()
    assert st["kv_exports"] == 5 and st["kv_stream_bytes"] > 0
    assert dst.summary()["kv_imports"] == 5
    src.close()  # block-leak invariant on both halves
    dst.close()


def test_kv_roundtrip_bitwise_seeded_sampling():
    """Seeded sampling across a handoff: the importer continues the
    per-token fold_in count at len(generated), so the sampled stream is
    the one an uninterrupted colocated engine draws."""
    sampling = SamplingParams(temperature=0.8, top_k=10, seed=123)
    rng = np.random.default_rng(11)
    p = rng.integers(0, CFG.vocab_size, (13,)).astype(np.int32)
    colo = _engine()
    want = colo.submit(p, max_new_tokens=8, sampling=sampling)
    colo.run_until_idle()
    colo.close()
    src, dst = _engine(), _engine()
    h = src.submit(p, max_new_tokens=8, sampling=sampling,
                   prefill_only=True)
    moved = _handoff_all(src, dst, [h])
    dst.run_until_idle()
    _, out = moved[h.id]
    assert out.new_tokens == want.new_tokens
    src.close()
    dst.close()


def test_kv_export_after_prefix_hit_bitwise():
    """A prefill-role admission that lands on cached prefix blocks
    (radix hit) exports a payload whose leading blocks are the SHARED
    ones — the importer's stream must still be bitwise, and the
    exporter's radix reference must survive the export (the next
    sibling still hits)."""
    src, dst = _engine(), _engine()
    rng = np.random.default_rng(3)
    system = rng.integers(0, CFG.vocab_size, (24,)).astype(np.int32)
    # warm the radix: one colocated stream through the shared prefix
    warm = src.submit(system, max_new_tokens=4)
    src.run_until_idle()
    np.testing.assert_array_equal(warm.output_ids, _ref(system, 4))
    tail = rng.integers(0, CFG.vocab_size, (5,)).astype(np.int32)
    p = np.concatenate([system, tail])
    h = src.submit(p, max_new_tokens=6, prefill_only=True)
    moved = _handoff_all(src, dst, [h])
    assert h.prefix_hit_tokens >= 16  # admitted through cached blocks
    dst.run_until_idle()
    _, out = moved[h.id]
    np.testing.assert_array_equal(out.output_ids, _ref(p, 6))
    # the cache kept its reference through the export: a sibling hits
    sib = src.submit(np.concatenate([system, tail[:2]]),
                     max_new_tokens=4)
    src.run_until_idle()
    assert sib.prefix_hit_tokens >= 16
    src.close()
    dst.close()


def test_import_validation_walls():
    """Geometry/model mismatches must raise, not serve garbage; a
    resource shortfall returns None (the router's lossless
    resume-from-tokens fallback)."""
    model, params, _ = _setup()
    src = _engine()
    rng = np.random.default_rng(5)
    p = rng.integers(0, CFG.vocab_size, (9,)).astype(np.int32)
    h = src.submit(p, max_new_tokens=5, prefill_only=True)
    for _ in range(100):
        src.step()
        if src.parked_requests:
            break
    payload = src.export_kv_blocks(src.parked_requests[0])
    # exporting twice is a caller bug, loudly
    with pytest.raises(ValueError, match="not parked"):
        src.export_kv_blocks(h)
    dense = ServingEngine(model, params, num_slots=2, prefill_bucket=16)
    with pytest.raises(ValueError, match="paged engine"):
        dense.submit(p, max_new_tokens=4, prefill_only=True)
    with pytest.raises(ValueError, match="paged engine"):
        dense.import_kv_blocks(payload)
    dense.close()
    dst = _engine()
    with pytest.raises(ValueError, match="block_size"):
        dst.import_kv_blocks(dataclasses.replace(payload, block_size=16))
    with pytest.raises(ValueError, match="generated"):
        dst.import_kv_blocks(dataclasses.replace(payload, generated=[]))
    with pytest.raises(ValueError, match="true_len"):
        dst.import_kv_blocks(
            dataclasses.replace(payload, true_len=payload.true_len + 1))
    with pytest.raises(ValueError, match="pool leaves"):
        dst.import_kv_blocks(dataclasses.replace(
            payload, leaves=[("bogus", a) for _, a in payload.leaves]))
    # the untampered payload still lands and finishes bitwise
    out = dst.import_kv_blocks(payload)
    assert out is not None
    dst.run_until_idle()
    np.testing.assert_array_equal(out.output_ids, _ref(p, 5))
    src.close()
    dst.close()


# ----------------------------------------------------------------------
# compressed (int8) pools over the KV stream (ISSUE 13)


def _colocated_int8_want(prompts, news, samplings=None):
    """Reference streams from an uninterrupted colocated int8 engine —
    the int8 handoff's bitwise anchor (generate() is the bf16 oracle;
    a quantized pool is its own exactness contract)."""
    samplings = samplings or [None] * len(prompts)
    colo = _engine(kv_dtype="int8")
    want = []
    for p, n, s in zip(prompts, news, samplings):
        r = colo.submit(p, max_new_tokens=n,
                        sampling=s or SamplingParams())
        colo.run_until_idle()
        want.append(list(r.new_tokens))
    colo.close()
    return want


def test_kv_roundtrip_int8_compressed_blocks():
    """ISSUE 13 acceptance: the handoff round-trips COMPRESSED blocks
    exactly — int8 codes and their fp32 scale planes ride the same
    pool-leaf path — so the importer's stream is bitwise-equal to an
    uninterrupted colocated int8 engine's, greedy AND seeded, at
    block-grid-straddling prompt lengths; the payload advertises its
    dtype and wire version and carries the scale leaves."""
    rng = np.random.default_rng(33)
    lens, news = [7, 8, 9, 17], [6, 6, 6, 6]
    prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
               for m in lens]
    samplings = [None, SamplingParams(temperature=0.8, top_k=10, seed=5),
                 None, SamplingParams(temperature=0.7, top_k=8, seed=9)]
    want = _colocated_int8_want(prompts, news, samplings)
    src, dst = _engine(kv_dtype="int8"), _engine(kv_dtype="int8")
    handles = [src.submit(p, max_new_tokens=n,
                          sampling=s or SamplingParams(),
                          prefill_only=True)
               for p, n, s in zip(prompts, news, samplings)]
    # peek at one payload before the batch drive: the self-description
    # a mismatched receiver rejects on, plus the scale planes
    for _ in range(100):
        src.step()
        if src.parked_requests:
            break
    req0 = src.parked_requests[0]
    peek = src.export_kv_blocks(req0)
    assert peek.kv_dtype == "int8"
    assert peek.wire_version == serving_engine.KV_WIRE_VERSION
    names = [n.rsplit("/", 1)[-1] for n, _ in peek.leaves]
    assert "cached_key_scale" in names and "cached_value_scale" in names
    codes = dict(zip(names, (a for _, a in peek.leaves)))
    assert codes["cached_key"].dtype == np.int8
    assert codes["cached_key_scale"].dtype == np.float32
    # the wire codec keeps all of it bit-exact
    back = kv_payload_from_wire(kv_payload_to_wire(peek))
    assert back.kv_dtype == "int8"
    assert back.wire_version == peek.wire_version
    out0 = dst.import_kv_blocks(back)
    assert out0 is not None
    rest = [h for h in handles if h.id != req0.id]
    moved = _handoff_all(src, dst, rest)
    dst.run_until_idle()
    outs = {req0.id: out0, **{i: o for i, (_, o) in moved.items()}}
    for h, w in zip(handles, want):
        out = outs[h.id]
        assert out.finish_reason == "length"
        assert list(out.new_tokens) == w, f"request {h.id}"
    src.close()
    dst.close()


def test_import_rejects_dtype_and_version_mismatch():
    """A bf16 replica must REFUSE an int8 payload (scattering codes
    into a bf16 pool would serve garbage) with a clear error naming
    both dtypes, and any engine refuses a stale wire version; the
    best-effort prefix-ship path declines (0 blocks) instead of
    raising."""
    src = _engine(kv_dtype="int8")
    rng = np.random.default_rng(35)
    p = rng.integers(0, CFG.vocab_size, (9,)).astype(np.int32)
    src.submit(p, max_new_tokens=5, prefill_only=True)
    for _ in range(100):
        src.step()
        if src.parked_requests:
            break
    payload = src.export_kv_blocks(src.parked_requests[0])
    bf16 = _engine()
    with pytest.raises(ValueError, match="kv_dtype 'int8'"):
        bf16.import_kv_blocks(payload)
    dst8 = _engine(kv_dtype="int8")
    with pytest.raises(ValueError, match="wire_version"):
        dst8.import_kv_blocks(
            dataclasses.replace(payload, wire_version=1))
    # prefix shipping is best-effort: mismatches decline, never raise
    ship = src.export_prefix_blocks(p)
    assert ship is not None and ship.kv_dtype == "int8"
    assert bf16.import_prefix_blocks(ship) == 0
    assert dst8.import_prefix_blocks(
        dataclasses.replace(ship, wire_version=1)) == 0
    # the untampered payload still lands on the matching pool
    out = dst8.import_kv_blocks(payload)
    assert out is not None
    dst8.run_until_idle()
    assert out.finish_reason == "length"
    src.close()
    bf16.close()
    dst8.close()


def test_fleet_prefix_ships_int8_blocks():
    """Fleet prefix steering over COMPRESSED pools: an int8 fleet ships
    int8 blocks + scales to the overflow sibling, which admits through
    them as remote hits — every stream bitwise-equal to the colocated
    int8 engine."""
    rng = np.random.default_rng(37)
    system = rng.integers(0, CFG.vocab_size, (16,)).astype(np.int32)
    tails = [rng.integers(0, CFG.vocab_size, (3 + i,)).astype(np.int32)
             for i in range(5)]
    prompts = [system] + [np.concatenate([system, t]) for t in tails]
    want = _colocated_int8_want(prompts, [4] * len(prompts))
    model, params, _ = _setup()
    router = ReplicaRouter(
        model, params, replicas=2, roles=["both", "both"],
        engine_kwargs=dict(num_slots=3, prefill_bucket=16, block_size=8,
                           kv_dtype="int8"),
        warmup_lens=(16, 32))
    router.warmup()
    leader = router.submit(prompts[0], max_new_tokens=4)
    router.run_until_idle()
    sibs = [router.submit(p, max_new_tokens=4) for p in prompts[1:]]
    router.run_until_idle()
    s = router.summary()
    assert s["prefix_ships"] >= 1
    assert s["cross_replica_hit_rate"] > 0
    for r, w in zip([leader] + sibs, want):
        assert list(r.tokens) == w, f"request {r.id} (hops {r.replicas})"
    router.close()


# ----------------------------------------------------------------------
# router-level disaggregation


def test_disagg_router_bitwise_and_handoffs():
    """The tentpole anchor: a prefill-role + decode-role fleet serves
    every stream bitwise-equal to the colocated engine — greedy AND
    seeded — with one handoff per request and zero failures."""
    model, params, _ = _setup()
    rng = np.random.default_rng(9)
    prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
               for m in (5, 9, 7, 11)]
    samplings = [None, SamplingParams(temperature=0.7, top_k=8, seed=4),
                 None, SamplingParams(temperature=0.9, top_k=6, seed=8)]
    colo = _engine()
    want = []
    for p, s in zip(prompts, samplings):
        r = colo.submit(p, max_new_tokens=6,
                        sampling=s or SamplingParams())
        colo.run_until_idle()
        want.append(list(r.new_tokens))
    colo.close()
    router = _router([ROLE_PREFILL, ROLE_DECODE])
    reqs = [router.submit(p, max_new_tokens=6, sampling=s)
            for p, s in zip(prompts, samplings)]
    router.run_until_idle()
    for r, w in zip(reqs, want):
        assert r.finish_reason == "length"
        assert r.tokens == w, f"request {r.id}"
        assert r.replicas == [0, 1]  # prefilled on 0, decoded on 1
        assert r.retries == 0
    s = router.summary()
    assert s["roles"] == [ROLE_PREFILL, ROLE_DECODE]
    assert s["handoffs"] == 4 and s["handoff_failures"] == 0
    assert s["kv_stream_bytes"] > 0
    assert s["served_by"] == {1: 4}
    router.close()


def test_disagg_decode_death_after_import_is_lossless():
    """A decode-role replica dying AFTER imports landed loses no
    stream: the router's failover requeues its residents and the
    resume-from-tokens path re-prefills prompt+generated elsewhere —
    tokens identical to the uninterrupted run."""
    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=6,replica=1"))
    router = _router([ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE],
                     faults=inj)
    rng = np.random.default_rng(13)
    prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
               for m in (6, 10, 8, 5)]
    reqs = [router.submit(p, max_new_tokens=8) for p in prompts]
    router.run_until_idle()
    s = router.summary()
    assert s["replicas_lost"] == 1
    assert s["handoffs"] >= 1
    for r, p in zip(reqs, prompts):
        assert r.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(r.tokens), _ref(p, 8)[p.size:],
            err_msg=f"request {r.id} (hops {r.replicas})")
    router.close()


def test_disagg_prefill_death_with_parked_streams_is_lossless():
    """The other half of the chaos acceptance: the prefill-role
    replica dying while streams are parked (KV not yet exported) must
    not lose them — failover re-prefills them on a survivor."""
    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=3,replica=0"))
    router = _router([ROLE_PREFILL, ROLE_PREFILL, ROLE_DECODE],
                     faults=inj)
    rng = np.random.default_rng(17)
    prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
               for m in (7, 11, 6, 9)]
    reqs = [router.submit(p, max_new_tokens=8) for p in prompts]
    router.run_until_idle()
    s = router.summary()
    assert s["replicas_lost"] == 1
    for r, p in zip(reqs, prompts):
        assert r.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(r.tokens), _ref(p, 8)[p.size:],
            err_msg=f"request {r.id} (hops {r.replicas})")
    router.close()


def test_fleet_prefix_steering_ships_blocks():
    """The fleet-radix anchor, deterministic: same-prefix siblings are
    steered to the replica that published the prefix until it
    saturates; the overflow sibling's target ADOPTS the blocks over
    the KV stream (prefix_ships), admits through them as remote hits
    (cross_replica_hit_rate > 0), and every stream stays bitwise."""
    router = _router(["both", "both"])
    rng = np.random.default_rng(21)
    system = rng.integers(0, CFG.vocab_size, (16,)).astype(np.int32)
    leader = router.submit(system, max_new_tokens=4)
    router.run_until_idle()  # replica 0 serves + publishes its frontier
    np.testing.assert_array_equal(
        np.asarray(leader.tokens), _ref(system, 4)[system.size:])
    assert leader.replicas == [0]
    sibs, prompts = [], []
    for i in range(5):
        tail = rng.integers(0, CFG.vocab_size, (3 + i,)).astype(np.int32)
        p = np.concatenate([system, tail])
        prompts.append(p)
        # no stepping between submits: the first four pile onto the
        # prefix owner (depth dominates the dispatch key) until its
        # load cap excludes it; the fifth lands on replica 1 + ships
        sibs.append(router.submit(p, max_new_tokens=4))
    router.run_until_idle()
    s = router.summary()
    assert s["prefix_ships"] >= 1
    assert s["cross_replica_hit_rate"] > 0
    assert s["kv_stream_bytes"] > 0
    assert 1 in s["served_by"]  # the overflow sibling really moved
    remote = sum(h.get("remote_hit_tokens", 0) for h in router.health())
    assert remote > 0
    for r, p in zip(sibs, prompts):
        np.testing.assert_array_equal(
            np.asarray(r.tokens), _ref(p, 4)[p.size:],
            err_msg=f"request {r.id} (hops {r.replicas})")
    router.close()


def test_zero_recompiles_steady_state_disagg():
    """warmup_kv_stream pre-compiles the gather/scatter pair, so a
    steady-state disaggregated trace — chunked prefill, park, export,
    import, mid-flight activation, fleet prefix ship — performs ZERO
    retraces and zero recompiles (the disagg A/B's tripwire)."""
    router = _router([ROLE_PREFILL, ROLE_DECODE])
    traces = dict(serving_engine.TRACE_COUNTS)
    sizes = (paged_prefill_chunk._cache_size(),
             paged_decode_tick._cache_size(),
             kv_block_gather._cache_size(),
             kv_block_scatter._cache_size())
    rng = np.random.default_rng(25)
    shared = rng.integers(0, CFG.vocab_size, (16,)).astype(np.int32)
    reqs = []
    for i in range(6):
        if i % 2:
            p = np.concatenate([shared, rng.integers(
                0, CFG.vocab_size, (1 + i,)).astype(np.int32)])
        else:
            p = rng.integers(0, CFG.vocab_size,
                             (5 + i,)).astype(np.int32)
        reqs.append(router.submit(p, max_new_tokens=5))
        router.step()
    router.run_until_idle()
    assert router.summary()["handoffs"] == 6
    assert all(r.finish_reason == "length" for r in reqs)
    assert dict(serving_engine.TRACE_COUNTS) == traces
    assert (paged_prefill_chunk._cache_size(),
            paged_decode_tick._cache_size(),
            kv_block_gather._cache_size(),
            kv_block_scatter._cache_size()) == sizes
    router.close()


def test_report_cli_renders_disagg_columns(tmp_path):
    """The report CLI's router section grows the role column and the
    handoff/KV-stream summary line (ISSUE 12 satellite)."""
    from pytorchdistributed_tpu.telemetry.report import render

    router = _router([ROLE_PREFILL, ROLE_DECODE],
                     telemetry_dir=str(tmp_path))
    rng = np.random.default_rng(29)
    reqs = [router.submit(
        rng.integers(0, CFG.vocab_size, (6 + i,)).astype(np.int32),
        max_new_tokens=4) for i in range(3)]
    router.run_until_idle()
    assert all(r.finish_reason == "length" for r in reqs)
    router.close()
    out = render(tmp_path)
    assert "replica router" in out
    assert "handoffs 3" in out
    assert "kv_stream" in out
    assert "prefill" in out and "decode" in out  # per-replica roles


# ----------------------------------------------------------------------
# subprocess wire (full-suite-only: spawns jax-importing workers)


def test_subprocess_disagg_e2e():
    """The multi-host shape: prefill and decode roles as subprocess
    workers, the KV payload serialized over the line-JSON wire — the
    handed-off streams stay bitwise-equal to generate()."""
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1,
            "engine": {"num_slots": 3, "prefill_bucket": 16,
                       "block_size": 8}}
    router = ReplicaRouter(workers=[spec, spec],
                           roles=[ROLE_PREFILL, ROLE_DECODE],
                           warmup_lens=(16, 32), faults=None)
    try:
        router.warmup()
        rng = np.random.default_rng(31)
        prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
                   for m in (5, 9, 12)]
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_steps=200000)
        s = router.summary()
        assert s["handoffs"] == 3 and s["handoff_failures"] == 0
        for p, r in zip(prompts, reqs):
            assert r.finish_reason == "length"
            assert r.replicas == [0, 1]
            np.testing.assert_array_equal(
                np.asarray(r.tokens), _ref(p, 6)[p.size:],
                err_msg=f"request {r.id}")
    finally:
        router.close()


def test_subprocess_disagg_int8_e2e():
    """ISSUE 13 over the real wire: an int8-pool prefill worker hands
    compressed blocks (codes + scale planes, wire_version 2) to an
    int8-pool decode worker over the line-JSON subprocess transport —
    streams bitwise-equal to the colocated int8 engine's."""
    rng = np.random.default_rng(41)
    prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
               for m in (5, 9, 12)]
    want = _colocated_int8_want(prompts, [6] * 3)
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1,
            "engine": {"num_slots": 3, "prefill_bucket": 16,
                       "block_size": 8, "kv_dtype": "int8"}}
    router = ReplicaRouter(workers=[spec, spec],
                           roles=[ROLE_PREFILL, ROLE_DECODE],
                           warmup_lens=(16, 32), faults=None)
    try:
        router.warmup()
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_steps=200000)
        s = router.summary()
        assert s["handoffs"] == 3 and s["handoff_failures"] == 0
        for r, w in zip(reqs, want):
            assert r.finish_reason == "length"
            assert r.replicas == [0, 1]
            assert list(r.tokens) == w, f"request {r.id}"
    finally:
        router.close()
