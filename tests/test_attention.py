"""Attention-variant equivalence tests (SURVEY.md §7 hard part (d): ring
attention correctness vs the dense reference).

All parallel variants — ring (ppermute KV rotation), Ulysses (all-to-all
head redistribution), Pallas flash (fused online-softmax kernel, interpret
mode on the CPU sim) — must reproduce ops.attention.dense_attention values
AND gradients to float32 tolerance, causal and bidirectional.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.ops.attention import dense_attention
from pytorchdistributed_tpu.ops.pallas_attention import flash_attention
from pytorchdistributed_tpu.ops.ring_attention import ring_attention_sharded
from pytorchdistributed_tpu.ops.ulysses import ulysses_attention
from pytorchdistributed_tpu.runtime.mesh import create_mesh
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss

B, S, H, D = 2, 64, 8, 32


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    return tuple(
        jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(qkv, causal):
    q, k, v = qkv
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(out, ref, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match(qkv, causal):
    q, k, v = qkv

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=16,
                               block_k=16).sum()

    def loss_dense(q, k, v):
        return dense_attention(q, k, v, causal=causal).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sequence_parallel_matches_dense(qkv, causal, impl):
    q, k, v = qkv
    fn = ring_attention_sharded if impl == "ring" else ulysses_attention
    mesh = create_mesh(data=2, seq=4)
    ref = dense_attention(q, k, v, causal=causal)
    with jax.set_mesh(mesh):
        out = fn(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)
        g1 = jax.grad(lambda q: fn(q, k, v, causal=causal).sum())(q)
    g2 = jax.grad(lambda q: dense_attention(q, k, v, causal=causal).sum())(q)
    np.testing.assert_allclose(g1, g2, atol=2e-5)


def test_ring_with_tensor_parallel_heads(qkv):
    """Ring attention composes with TP: heads sharded over "tensor" while
    seq rotates over "seq"."""
    q, k, v = qkv
    mesh = create_mesh(data=1, seq=4, tensor=2)
    ref = dense_attention(q, k, v, causal=True)
    with jax.set_mesh(mesh):
        out = ring_attention_sharded(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5)


@pytest.mark.parametrize("attn,axes", [
    ("ring", dict(data=2, seq=4)),
    ("ulysses", dict(data=4, seq=2)),
])
def test_gpt2_sequence_parallel_loss_equivalence(attn, axes):
    """Full train loop under context parallelism must track the dense DP
    loss curve (the north-star 'identical loss curves' requirement)."""
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 128, (8, 64)).astype(np.int32),
        "targets": rng.integers(0, 128, (8, 64)).astype(np.int32),
    }

    def run(attention, axes):
        model = GPT2(gpt2_config("test", attention=attention,
                                 dtype=jnp.float32))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(**axes), strategy="dp")
        return [float(tr.train_step(batch)["loss"]) for _ in range(3)]

    ref = run("dense", dict())
    got = run(attn, axes)
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_flash_tpu_lowering_smoke():
    """Mosaic-lowering check on real hardware: the suite normally runs
    under the forced CPU sim (conftest.py) where interpret mode hides TPU
    tiling constraints, so compile the small-block config for TPU when one
    is attached (run tests without the conftest env override to exercise)."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU (suite runs on the CPU sim)")
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 24, 4, 16)), jnp.float32)
               for _ in range(3))
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                          interpret=False)
    g = jax.grad(lambda q: flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16,
        interpret=False).sum())(q)
    assert np.isfinite(np.asarray(out)).all() and np.isfinite(
        np.asarray(g)).all()


def test_flash_non_divisible_seq_len():
    """Padded Q/K tail blocks must be masked (S % block != 0), in the
    forward and in both backward kernels (dq and dkv accumulate across the
    padded tails)."""
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 24, 4, 16)), jnp.float32)
               for _ in range(3))
    for causal in (False, True):
        ref = dense_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        np.testing.assert_allclose(out, ref, atol=2e-5)
        g1 = jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, causal=causal, block_q=16, block_k=16).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: dense_attention(q, k, v, causal=causal).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=2e-5)
