"""Attention-variant equivalence tests (SURVEY.md §7 hard part (d): ring
attention correctness vs the dense reference).

All parallel variants — ring (ppermute KV rotation), Ulysses (all-to-all
head redistribution), Pallas flash (fused online-softmax kernel, interpret
mode on the CPU sim) — must reproduce ops.attention.dense_attention values
AND gradients to float32 tolerance, causal and bidirectional.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu._jax_compat import has_native_check_vma
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.ops.attention import dense_attention
from pytorchdistributed_tpu.ops.pallas_attention import flash_attention
from pytorchdistributed_tpu.ops.ring_attention import ring_attention_sharded
from pytorchdistributed_tpu.ops.ulysses import ulysses_attention
from pytorchdistributed_tpu.runtime.mesh import create_mesh
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss

B, S, H, D = 2, 64, 8, 32


@pytest.fixture(scope="module")
def qkv():
    rng = np.random.default_rng(0)
    return tuple(
        jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(qkv, causal):
    q, k, v = qkv
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(out, ref, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match(qkv, causal):
    q, k, v = qkv

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=16,
                               block_k=16).sum()

    def loss_dense(q, k, v):
        return dense_attention(q, k, v, causal=causal).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grouped_query_matches_repeated_dense(causal):
    """GQA-native kernels: k/v with fewer heads must equal dense attention
    over explicitly repeated K/V — values and all three grads (the dk/dv
    group reduction runs inside the kernel accumulator across the 4D
    grid's group dim)."""
    b, s, h, hk, d = 2, 64, 8, 2, 32
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, hk, d)), jnp.float32)
    rep = h // hk

    def dense_ref(q, k, v):
        return dense_attention(q, jnp.repeat(k, rep, 2),
                               jnp.repeat(v, rep, 2), causal=causal)

    ref = dense_ref(q, k, v)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(out, ref, atol=2e-5)
    g1 = jax.grad(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: dense_ref(q, k, v).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(a, b_, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_sequence_parallel_matches_dense(qkv, causal, impl):
    q, k, v = qkv
    fn = ring_attention_sharded if impl == "ring" else ulysses_attention
    mesh = create_mesh(data=2, seq=4)
    ref = dense_attention(q, k, v, causal=causal)
    with jax.set_mesh(mesh):
        out = fn(q, k, v, causal=causal)
        np.testing.assert_allclose(out, ref, atol=2e-5)
        g1 = jax.grad(lambda q, k, v: fn(q, k, v, causal=causal).sum(),
                      argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: dense_attention(q, k, v,
                                                  causal=causal).sum(),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):  # dq; dk/dv ride the reverse ring's
        np.testing.assert_allclose(a, b, atol=2e-5)  # co-travelling accums


@pytest.mark.parametrize("causal", [False, True])
def test_ring_small_blocks_padded_tail(qkv, causal):
    """Multi-block ring kernels with a padded tail: block 12 against
    S_local=16 gives nq=nk=2 with a 4-row pad, exercising the seq_len
    masks and _zero_pad_rows guards in all three carry=True kernels (the default
    block size min()-clamps to S_local, so the other ring tests never
    leave the single-block case)."""
    q, k, v = qkv
    mesh = create_mesh(data=2, seq=4)
    ref = dense_attention(q, k, v, causal=causal)
    kw = dict(causal=causal, block_q=12, block_k=12)
    with jax.set_mesh(mesh):
        out = ring_attention_sharded(q, k, v, **kw)
        np.testing.assert_allclose(out, ref, atol=2e-5)
        g1 = jax.grad(lambda q, k, v: ring_attention_sharded(
            q, k, v, **kw).sum(), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: dense_attention(
        q, k, v, causal=causal).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_xla_impl_matches_dense(qkv, causal):
    """The plain-einsum reference path (impl="xla") must agree too — it is
    the debugging baseline for the Pallas block kernels."""
    q, k, v = qkv
    mesh = create_mesh(seq=4)
    ref = dense_attention(q, k, v, causal=causal)
    with jax.set_mesh(mesh):
        out = ring_attention_sharded(q, k, v, causal=causal, impl="xla")
        np.testing.assert_allclose(out, ref, atol=2e-5)
        g1 = jax.grad(lambda q, k, v: ring_attention_sharded(
            q, k, v, causal=causal, impl="xla").sum(),
            argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda q, k, v: dense_attention(
        q, k, v, causal=causal).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=2e-5)


def _collect_avals(jaxpr, out):
    """All intermediate avals of ``jaxpr`` and its sub-jaxprs."""
    from jax.extend import core as jex_core

    jaxpr_types = (jex_core.Jaxpr, jex_core.ClosedJaxpr)
    for eqn in jaxpr.eqns:
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if hasattr(aval, "shape"):
                out.append(aval)
        for p in eqn.params.values():
            for sub in jax.tree.leaves(
                    p, is_leaf=lambda x: isinstance(x, jaxpr_types)):
                if isinstance(sub, jex_core.ClosedJaxpr):
                    _collect_avals(sub.jaxpr, out)
                elif isinstance(sub, jex_core.Jaxpr):
                    _collect_avals(sub, out)


def test_ring_grad_residuals_stay_local():
    """The memory claim under AD (VERDICT r2 missing #2): the backward must
    NOT have saved the rotated (k, v) scan carry per ring step — that is
    O(S_full) residuals per device, exactly what ring attention exists to
    avoid. With the custom_vjp reverse ring, every array inside the
    shard_map body stays O(S_local): a stacked residual would show up as an
    [n_steps, ...] aval of full-sequence size."""
    b, s, h, d = 2, 256, 2, 32
    n_shards = 4
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
               for _ in range(3))
    mesh = create_mesh(seq=n_shards)
    with jax.set_mesh(mesh):
        jaxpr = jax.make_jaxpr(jax.grad(
            lambda q, k, v: ring_attention_sharded(q, k, v, causal=True)
            .astype(jnp.float32).sum(), argnums=(0, 1, 2)))(q, k, v)
    # walk only the shard_map bodies: everything inside runs on local shards
    inner: list = []
    found = False
    for eqn in jaxpr.jaxpr.eqns:
        if "shard_map" in eqn.primitive.name:
            found = True
            _collect_avals(eqn.params["jaxpr"].jaxpr if hasattr(
                eqn.params["jaxpr"], "jaxpr") else eqn.params["jaxpr"], inner)
    assert found, "expected a shard_map eqn in the ring grad jaxpr"
    local_kv_elems = b * (s // n_shards) * h * d
    worst = max(int(np.prod(a.shape)) for a in inner)
    # the old scan-AD residual was [n_shards, ...] x local kv = full size;
    # allow 2x local (fp32 accumulators) but nothing near full
    assert worst < n_shards * local_kv_elems, (
        f"O(S_full) intermediate inside the ring grad: {worst} elems vs "
        f"local kv {local_kv_elems}")


def test_ring_with_tensor_parallel_heads(qkv):
    """Ring attention composes with TP: heads sharded over "tensor" while
    seq rotates over "seq"."""
    q, k, v = qkv
    mesh = create_mesh(data=1, seq=4, tensor=2)
    ref = dense_attention(q, k, v, causal=True)
    with jax.set_mesh(mesh):
        out = ring_attention_sharded(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5)


@pytest.mark.parametrize("attn,axes", [
    ("ring", dict(data=2, seq=4)),
    ("ulysses", dict(data=4, seq=2)),
])
def test_gpt2_sequence_parallel_loss_equivalence(attn, axes):
    """Full train loop under context parallelism must track the dense DP
    loss curve (the north-star 'identical loss curves' requirement)."""
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 128, (8, 64)).astype(np.int32),
        "targets": rng.integers(0, 128, (8, 64)).astype(np.int32),
    }

    def run(attention, axes):
        model = GPT2(gpt2_config("test", attention=attention,
                                 dtype=jnp.float32))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(**axes), strategy="dp")
        return [float(tr.train_step(batch)["loss"]) for _ in range(3)]

    ref = run("dense", dict())
    got = run(attn, axes)
    np.testing.assert_allclose(got, ref, atol=2e-5)


def test_flash_tpu_lowering_smoke():
    """Mosaic-lowering check on real hardware: the suite normally runs
    under the forced CPU sim (conftest.py) where interpret mode hides TPU
    tiling constraints, so compile the small-block config for TPU when one
    is attached (run tests without the conftest env override to exercise)."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU (suite runs on the CPU sim)")
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 24, 4, 16)), jnp.float32)
               for _ in range(3))
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16,
                          interpret=False)
    g = jax.grad(lambda q: flash_attention(
        q, k, v, causal=True, block_q=16, block_k=16,
        interpret=False).sum())(q)
    assert np.isfinite(np.asarray(out)).all() and np.isfinite(
        np.asarray(g)).all()


def test_ulysses_xla_impl_checked_sim():
    """ADVICE r5: check_vma defaults ON for ANY compiled run, including
    the impl='xla' debug path, but only the pallas impl had checker
    evidence. The checker is a trace-time property (axis names, not
    sizes), so the xla path's acceptance is testable on the CPU sim with
    check_vma forced ON — no hardware needed. Ulysses' xla impl carries
    no named residuals, so this runs even under the legacy check_rep
    emulation (older jax)."""
    mesh = create_mesh(data=4, seq=2)
    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.standard_normal((4, 64, 4, 16)),
                           jnp.float32) for _ in range(3))
    kw = dict(causal=True, impl="xla", check_vma=True)
    with jax.set_mesh(mesh), mesh:
        out = ulysses_attention(q, k, v, **kw)
        g = jax.grad(lambda q: ulysses_attention(q, k, v, **kw).sum())(q)
        ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.skipif(
    not has_native_check_vma(),
    reason="ring's checked xla path needs the vma checker; the legacy "
           "check_rep emulation has no rule for checkpoint_name's "
           "primitive inside the ring's custom_vjp")
def test_ring_xla_impl_checked_sim():
    """The ring analog of test_ulysses_xla_impl_checked_sim: one checked
    fwd+bwd impl='xla' ring step on the sim, pinning the xla debug path's
    checker acceptance that the checked-by-default rule now relies on."""
    mesh = create_mesh(data=4, seq=2)
    rng = np.random.default_rng(12)
    q, k, v = (jnp.asarray(rng.standard_normal((4, 64, 4, 16)),
                           jnp.float32) for _ in range(3))
    kw = dict(causal=True, impl="xla", check_vma=True)
    with jax.set_mesh(mesh), mesh:
        out = ring_attention_sharded(q, k, v, **kw)
        g = jax.grad(lambda q: ring_attention_sharded(
            q, k, v, **kw).sum())(q)
        ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    assert np.isfinite(np.asarray(g)).all()


def test_ring_check_vma_tpu():
    """shard_map's one static safety check, ON, for the framework's most
    intricate collective (VERDICT r4 #8). Since r5 this guards the
    PRODUCTION DEFAULT: ring_attention_sharded runs check_vma=True
    whenever the kernels compile for real hardware, opting out only under
    Pallas interpret mode (CPU sim), whose internal evaluation
    false-positives the checker. When hardware is attached, run a checked
    fwd+bwd ring step compiled (interpret=False) and require the checker
    to accept it — the explicit check_vma=True below pins the checked
    path even if the default ever regresses.
    A single chip gives a size-1 seq axis — the vma check is a trace-time
    property of the collective program (axis names, not sizes), so the
    evidence transfers; a multi-chip run would use the same call."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU (suite runs on the CPU sim)")
    n = len(jax.devices())
    seq = 2 if n % 2 == 0 else 1
    data = n // seq if seq > 1 else n
    mesh = create_mesh(data=data, seq=seq)
    rng = np.random.default_rng(5)
    # batch = the data-axis size so the shard_map divides on any host
    # (1-chip bench rig through v4-8/v5e-8 pods)
    q, k, v = (jnp.asarray(rng.standard_normal((max(data, 2), 256, 4, 64)),
                           jnp.float32) for _ in range(3))
    kw = dict(causal=True, interpret=False, check_vma=True)
    with jax.set_mesh(mesh):
        out = ring_attention_sharded(q, k, v, **kw)
        g = jax.grad(lambda q: ring_attention_sharded(
            q, k, v, **kw).sum())(q)
        # one checked impl='xla' step too (ADVICE r5): the checked-by-
        # default rule covers the xla debug path as well, so its checker
        # acceptance needs the same hardware evidence as pallas'
        out_x = ring_attention_sharded(q, k, v, impl="xla", causal=True,
                                       check_vma=True)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(g)).all()
    assert np.isfinite(np.asarray(out_x)).all()


def test_ulysses_check_vma_tpu():
    """Ulysses rides the same checked-by-default contract as the ring
    (check_vma = not interpret): run a checked fwd+bwd all-to-all step
    compiled on real hardware and require the checker to accept it."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU (suite runs on the CPU sim)")
    n = len(jax.devices())
    seq = 2 if n % 2 == 0 else 1
    data = n // seq if seq > 1 else n
    mesh = create_mesh(data=data, seq=seq)
    rng = np.random.default_rng(6)
    # heads must divide the seq axis for the all-to-all redistribution
    q, k, v = (jnp.asarray(rng.standard_normal((max(data, 2), 256, 4, 64)),
                           jnp.float32) for _ in range(3))
    kw = dict(causal=True, interpret=False, check_vma=True)
    with jax.set_mesh(mesh):
        out = ulysses_attention(q, k, v, **kw)
        g = jax.grad(lambda q: ulysses_attention(
            q, k, v, **kw).sum())(q)
        out_x = ulysses_attention(q, k, v, impl="xla", causal=True,
                                  check_vma=True)  # ADVICE r5, see ring
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(g)).all()
    assert np.isfinite(np.asarray(out_x)).all()


def test_ring_kernels_tpu_lowering_smoke():
    """Mosaic-lowering check for the ring-attention block kernels (the
    suite's CPU sim runs them in interpret mode, which hides TPU tiling
    constraints): compile and run the fwd carry-update and bwd dq/dkv
    kernels directly on hardware when attached."""
    if jax.default_backend() != "tpu":
        pytest.skip("needs a real TPU (suite runs on the CPU sim)")
    from pytorchdistributed_tpu.ops.ring_attention import (
        _RingSpec,
        _pallas_bwd_update,
        _pallas_fwd_update,
    )

    bh, s, d = 4, 256, 64
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((bh, s, d)), jnp.bfloat16)
               for _ in range(3))
    spec = _RingSpec(axis_name="seq", causal=True, scale=d**-0.5,
                     impl="pallas", block_q=128, block_k=128,
                     interpret=False)
    acc = jnp.zeros((bh, s, d), jnp.float32)
    m = jnp.full((bh, s, 1), -1e30, jnp.float32)
    l = jnp.zeros((bh, s, 1), jnp.float32)
    for causal in (False, True):
        acc2, m2, l2 = jax.jit(
            lambda q, k, v, acc, m, l, c=causal: _pallas_fwd_update(
                q, k, v, acc, m, l, causal=c, spec=spec))(q, k, v, acc, m, l)
        assert np.isfinite(np.asarray(acc2)).all()
        lse = m2 + jnp.log(jnp.maximum(l2, 1e-30))
        do = jnp.ones((bh, s, d), jnp.bfloat16)
        delta = jnp.sum(do.astype(jnp.float32) * acc2, -1, keepdims=True)
        z = jnp.zeros((bh, s, d), jnp.float32)
        dq, dk, dv = jax.jit(
            lambda *a, c=causal: _pallas_bwd_update(*a, causal=c,
                                                    spec=spec))(
            q, k, v, do, lse, delta, z, z, z)
        for t in (dq, dk, dv):
            assert np.isfinite(np.asarray(t)).all()


def test_flash_non_divisible_seq_len():
    """Padded Q/K tail blocks must be masked (S % block != 0), in the
    forward and in both backward kernels (dq and dkv accumulate across the
    padded tails)."""
    rng = np.random.default_rng(3)
    q, k, v = (jnp.asarray(rng.standard_normal((2, 24, 4, 16)), jnp.float32)
               for _ in range(3))
    for causal in (False, True):
        ref = dense_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        np.testing.assert_allclose(out, ref, atol=2e-5)
        g1 = jax.grad(
            lambda q, k, v: flash_attention(
                q, k, v, causal=causal, block_q=16, block_k=16).sum(),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: dense_attention(q, k, v, causal=causal).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(a, b, atol=2e-5)
