"""Paged KV cache tests (ISSUE 7: serving/paging.py + the paged engine).

Correctness bar, same as the dense engine's (test_serving.py): for ANY
admission order — now including prefix-cache hits, chunked prefills,
block growth and preempt-requeue round-trips — greedy per-request
outputs must be BITWISE-equal to inference.generate()'s. On top: the
paged-attention kernel parity ladder (reference gather vs dense cache
math at ragged/block-boundary lengths, fp32; the Pallas pool-native twin
to online-softmax tolerance), the block allocator / radix-cache units,
the every-exit-path block-leak invariant, and the zero-recompile
steady-state guarantee over the paged program pair.
"""

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import GPT2, Llama, gpt2_config
from pytorchdistributed_tpu.models import llama_config
from pytorchdistributed_tpu.ops.attention import paged_attention
from pytorchdistributed_tpu.serving import (
    BlockAllocator,
    RadixPrefixCache,
    ServingEngine,
)
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.engine import (
    paged_decode_tick,
    paged_prefill_chunk,
)


def _init(model, seed=1):
    return model.init(jax.random.key(seed), jnp.zeros((1, 4), jnp.int32))


# ---------------------------------------------------------------------------
# host bookkeeping units


class TestBlockAllocator:
    def test_alloc_free_refcount(self):
        a = BlockAllocator(8, 4)
        assert a.usable == 7 and a.free_count == 7
        blocks = a.alloc(3)
        assert blocks is not None and 0 not in blocks
        assert a.free_count == 4 and a.resident == 3
        a.incref(blocks[0])
        assert not a.decref(blocks[0])  # still shared
        assert a.decref(blocks[0])      # now freed
        assert a.free_count == 5
        assert a.alloc(6) is None       # over-ask leaves state untouched
        assert a.free_count == 5
        for b in blocks[1:]:
            a.decref(b)
        a.check_leaks(0)

    def test_trash_block_reserved(self):
        a = BlockAllocator(4, 2)
        got = set(a.alloc(3))
        assert 0 not in got
        with pytest.raises(ValueError):
            a.incref(0)

    def test_leak_check_raises(self):
        a = BlockAllocator(4, 2)
        a.alloc(1)
        with pytest.raises(AssertionError, match="leak"):
            a.check_leaks(0)


class TestRadixPrefixCache:
    def test_match_insert_block_granularity(self):
        a = BlockAllocator(16, 4)
        r = RadixPrefixCache(a)
        toks = np.arange(10, dtype=np.int32)  # 2 full blocks + tail
        blocks = a.alloc(3)
        assert r.match(toks) == []
        r.insert(toks[:8], blocks[:2])        # only full blocks cached
        assert r.block_count == 2
        assert [a.refcount(b) for b in blocks[:2]] == [2, 2]
        assert r.match(toks) == blocks[:2]
        # divergence INSIDE the second block misses it (copy-on-write by
        # construction: the divergent request prefills a private copy)
        other = toks.copy()
        other[5] = 99
        assert r.match(other) == blocks[:1]

    def test_reclaim_lru_sole_owner_only(self):
        a = BlockAllocator(16, 4)
        r = RadixPrefixCache(a)
        b1 = a.alloc(2)
        b2 = a.alloc(2)
        r.insert(np.arange(8, dtype=np.int32), b1)
        r.insert(np.arange(100, 108, dtype=np.int32), b2)
        for b in b1 + b2:  # the admitting slots release their refs
            a.decref(b)
        # touch chain 1 -> chain 2's tail is the LRU evictable leaf
        r.match(np.arange(8, dtype=np.int32))
        free0 = a.free_count
        assert r.reclaim(1) == 1
        assert a.free_count == free0 + 1
        assert r.match(np.arange(100, 108, dtype=np.int32)) == b2[:1]
        # a block an active slot still holds is never reaped
        a.incref(b1[1])
        assert r.reclaim(10) >= 1  # everything sole-owner goes
        assert a.refcount(b1[1]) >= 1
        a.decref(b1[1])
        r.clear()
        a.check_leaks(0)


# ---------------------------------------------------------------------------
# paged attention parity ladder


def _dense_decode_oracle(q, k_rows, v_rows, lengths):
    """The dense cache-masked decode math, verbatim from the model's
    dense branch (fp32 softmax, /sqrt(d) spelling)."""
    attend = k_rows.shape[1]
    pos = lengths[:, None] + jnp.arange(q.shape[1])
    valid = jnp.arange(attend) <= pos[..., None]
    scores = jnp.einsum("bihd,bjhd->bhij", q, k_rows,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    scores = jnp.where(valid[:, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhij,bjhd->bihd", probs.astype(v_rows.dtype), v_rows,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _paged_fixture(lengths, *, bs=8, heads=4, kvh=2, d=16, seed=0):
    """Build a pool + tables whose gathered content equals dense rows
    holding the same K/V — the two layouts of one logical cache."""
    rng = np.random.default_rng(seed)
    slots = len(lengths)
    mb = 8
    attend = mb * bs
    k_rows = rng.normal(size=(slots, attend, kvh, d)).astype(np.float32)
    v_rows = rng.normal(size=(slots, attend, kvh, d)).astype(np.float32)
    pool_k = np.zeros((slots * mb + 1, bs, kvh, d), np.float32)
    pool_v = np.zeros_like(pool_k)
    tables = np.zeros((slots, mb), np.int32)
    nxt = 1
    for s in range(slots):
        for j in range(mb):
            pool_k[nxt] = k_rows[s, j * bs:(j + 1) * bs]
            pool_v[nxt] = v_rows[s, j * bs:(j + 1) * bs]
            tables[s, j] = nxt
            nxt += 1
    q = rng.normal(size=(slots, 1, heads, d)).astype(np.float32)
    rep = heads // kvh
    k_full = np.repeat(k_rows, rep, axis=2)
    v_full = np.repeat(v_rows, rep, axis=2)
    return (jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(tables), jnp.asarray(np.asarray(lengths, np.int32)),
            jnp.asarray(k_full), jnp.asarray(v_full))


@pytest.mark.parametrize("lengths", [
    (5, 17, 40),          # ragged
    (16, 15, 17),         # block boundary: k*bs, k*bs - 1, k*bs + 1
    (0, 63, 32),          # empty slot, last row, boundary
])
def test_paged_attention_bitwise_vs_dense(lengths):
    """The gather layout is invisible to the math: paged attention over
    a block pool is BITWISE-equal (fp32) to the dense cache path for
    ragged and block-boundary (len == k*bs +/- 1) slot lengths."""
    q, pk, pv, tbl, lens, kf, vf = _paged_fixture(lengths)
    ref = _dense_decode_oracle(q, kf, vf, lens)
    got = paged_attention(q, pk, pv, tbl, lens)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_paged_attention_trash_garbage_is_masked():
    """Table entries past the live window point at the trash block; its
    content must not perturb outputs (0-prob x finite garbage == 0)."""
    q, pk, pv, tbl, lens, kf, vf = _paged_fixture((5, 9, 2))
    ref = paged_attention(q, pk, pv, tbl, lens)
    # poison the trash block and every block past each slot's window
    pk = pk.at[0].set(1e6)
    pv = pv.at[0].set(-1e6)
    bs = pk.shape[1]
    tbl_np = np.asarray(tbl).copy()
    for s, n in enumerate((5, 9, 2)):
        tbl_np[s, (n // bs) + 1:] = 0
    got = paged_attention(q, pk, pv, jnp.asarray(tbl_np), lens)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("lengths", [
    (5, 17, 40, 64),       # the original mixed-ragged set
    (1, 7, 9, 23),         # every length off the block grid (bs=8),
                           # final block 1..7 rows full
    (8, 15, 16, 63),       # exact boundary, last-row-of-block, and the
                           # last row of the final block
])
def test_paged_flash_matches_reference(lengths):
    """The Pallas pool-native twin (scalar-prefetched block tables, no
    gathered HBM copy) matches the reference gather to online-softmax
    tolerance, GQA included — including lengths NOT multiples of
    block_size, where the final block is only partially filled and the
    kernel's in-block masking does the cut."""
    from pytorchdistributed_tpu.ops.pallas_attention import (
        paged_flash_attention,
    )

    q, pk, pv, tbl, lens, _, _ = _paged_fixture(lengths, kvh=2)
    ref = paged_attention(q, pk, pv, tbl, lens)
    got = paged_flash_attention(q[:, 0], pk, pv, tbl, lens)
    np.testing.assert_allclose(np.asarray(ref[:, 0]), np.asarray(got),
                               atol=2e-5, rtol=2e-5)


def _quantize_fixture_pool(pk, pv):
    from pytorchdistributed_tpu.ops.quant import kv_quantize

    kc, ks = kv_quantize(pk)
    vc, vs = kv_quantize(pv)
    return kc, ks, vc, vs


@pytest.mark.parametrize("lengths", [(5, 17, 40, 64), (1, 9, 23, 63)])
def test_paged_flash_int8_matches_reference(lengths):
    """The ISSUE 13 compressed hot path: the Pallas kernel reading the
    int8 pool + fp32 scale planes matches the reference gather running
    the SAME canonical dequant (ops.quant.kv_dequantize) to
    online-softmax tolerance — the tolerance-pinned int8 twin."""
    from pytorchdistributed_tpu.ops.pallas_attention import (
        paged_flash_attention,
    )

    q, pk, pv, tbl, lens, _, _ = _paged_fixture(lengths, kvh=2)
    kc, ks, vc, vs = _quantize_fixture_pool(pk, pv)
    ref = paged_attention(q, kc, vc, tbl, lens, k_scale=ks, v_scale=vs)
    got = paged_flash_attention(q[:, 0], kc, vc, tbl, lens,
                                k_scale=ks, v_scale=vs)
    np.testing.assert_allclose(np.asarray(ref[:, 0]), np.asarray(got),
                               atol=2e-5, rtol=2e-5)
    # and the quantization error itself is bounded: int8 per-(token,
    # head) absmax scaling stays close to the fp32 oracle
    full = paged_attention(q, pk, pv, tbl, lens)
    np.testing.assert_allclose(np.asarray(full[:, 0]), np.asarray(got),
                               atol=0.05, rtol=0.05)


def test_paged_flash_sink_window_matches_reference():
    """Sink + sliding-window masking agrees between the kernel and the
    reference gather (fp32 and int8 pools): only the first sink_tokens
    and the trailing window_tokens positions contribute, and a
    fully-dead middle block's content is irrelevant (the kernel skips
    its DMA; the engine retires it back to the allocator)."""
    from pytorchdistributed_tpu.ops.pallas_attention import (
        paged_flash_attention,
    )

    lengths = (40, 64, 23)
    q, pk, pv, tbl, lens, _, _ = _paged_fixture(lengths, kvh=2)
    kw = dict(sink_tokens=8, window_tokens=16)
    ref = paged_attention(q, pk, pv, tbl, lens, **kw)
    got = paged_flash_attention(q[:, 0], pk, pv, tbl, lens, **kw)
    np.testing.assert_allclose(np.asarray(ref[:, 0]), np.asarray(got),
                               atol=2e-5, rtol=2e-5)
    # windowing changed the answer (the mask is real)
    full = paged_attention(q, pk, pv, tbl, lens)
    assert not np.allclose(np.asarray(full[:, 0]), np.asarray(got),
                           atol=1e-3)
    # dead middle blocks are never read: poison them, nothing moves
    bs = pk.shape[1]
    tbl_np = np.asarray(tbl).copy()
    for s, n in enumerate(lengths):
        for bi in range(tbl_np.shape[1]):
            if bi * bs >= 8 and (bi + 1) * bs <= n - 16 + 1:
                tbl_np[s, bi] = 0  # retire: point at trash
    pk = pk.at[0].set(1e6)
    pv = pv.at[0].set(-1e6)
    got2 = paged_flash_attention(q[:, 0], pk, pv, jnp.asarray(tbl_np),
                                 lens, **kw)
    np.testing.assert_allclose(np.asarray(got), np.asarray(got2),
                               atol=2e-5, rtol=2e-5)
    # int8 pool through the same mask
    kc, ks, vc, vs = _quantize_fixture_pool(pk, pv)
    refq = paged_attention(q, kc, vc, tbl, lens, k_scale=ks, v_scale=vs,
                           **kw)
    gotq = paged_flash_attention(q[:, 0], kc, vc, tbl, lens,
                                 k_scale=ks, v_scale=vs, **kw)
    np.testing.assert_allclose(np.asarray(refq[:, 0]), np.asarray(gotq),
                               atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# the paged engine: parity, reuse, chunking, preemption, leaks


def _mixed_requests(vocab, seed=0, n=5, lens=None, news=None):
    rng = np.random.default_rng(seed)
    lens = lens or [5, 9, 3, 13, 7, 11, 4, 8, 6][:n]
    news = news or [6, 3, 8, 5, 4, 7, 2, 5, 3][:n]
    prompts = [rng.integers(0, vocab, (m,)).astype(np.int32) for m in lens]
    return prompts, news


def _assert_paged_parity(model_cls, cfg, *, num_slots, lens=None,
                         news=None, n=5, **engine_kw):
    model = model_cls(cfg)
    params = _init(model)
    dm = model_cls(dataclasses.replace(cfg, decode=True))
    prompts, news = _mixed_requests(cfg.vocab_size, n=n, lens=lens,
                                    news=news)
    engine = ServingEngine(model, params, num_slots=num_slots,
                           prefill_bucket=16, block_size=8, **engine_kw)
    assert engine.paged
    engine.warmup(prompt_lens=(8, 16))
    reqs = []
    for p, n_new in zip(prompts, news):
        reqs.append(engine.submit(p, max_new_tokens=n_new))
        engine.step()  # staggered arrivals interleave with decoding
    engine.run_until_idle()
    for p, n_new, r in zip(prompts, news, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None],
                       max_new_tokens=n_new)
        np.testing.assert_array_equal(
            r.output_ids, np.asarray(ref)[0],
            err_msg=f"request {r.id} (preemptions={r.preemptions})")
    engine.close()  # the leak invariant runs on every parity drive
    return reqs


def test_parity_paged_engine():
    """The ISSUE 7 acceptance anchor: greedy paged-engine outputs are
    bitwise-equal to generate() for a staggered mixed-length admission
    order (chunked prefill + block growth on every request)."""
    _assert_paged_parity(GPT2, gpt2_config("test", num_layers=2,
                                           max_seq_len=64),
                         num_slots=3, n=5)


def test_parity_paged_block_boundary_lengths():
    """Prompt lengths straddling the block grid (k*bs - 1, k*bs,
    k*bs + 1 at bs=8) — the partial-tail-block and exact-boundary write
    paths — plus generations that cross block boundaries mid-decode."""
    _assert_paged_parity(GPT2, gpt2_config("test", num_layers=2,
                                           max_seq_len=64),
                         num_slots=3, lens=[7, 8, 9, 16, 17],
                         news=[9, 8, 7, 6, 5], n=5)


def test_parity_paged_llama_gqa():
    """Per-row RoPE offsets + grouped-query heads through the pool
    scatter/gather layout."""
    _assert_paged_parity(Llama, llama_config("test", max_seq_len=64),
                         num_slots=2, n=4)


def test_parity_paged_int8():
    """--quant int8_fwd composes with paging: the chunk/tick run the
    same quantized projections, outputs bitwise-equal to quantized
    generate()."""
    _assert_paged_parity(GPT2, gpt2_config("test", num_layers=2,
                                           max_seq_len=64,
                                           quant="int8_fwd"),
                         num_slots=2, n=3)


def test_parity_paged_unrolled_layers():
    """scan_layers=False: per-layer (unstacked) pool/table leaves ride
    the same name-based override plumbing."""
    _assert_paged_parity(GPT2, gpt2_config("test", num_layers=2,
                                           max_seq_len=64,
                                           scan_layers=False),
                         num_slots=2, n=3)


def test_prefix_reuse_hits_and_parity():
    """Shared-system-prompt admissions reuse cached blocks (hit tokens
    > 0, fewer prefill chunks) and stay bitwise-equal: reused K/V is
    bit-identical to recomputed K/V."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    rng = np.random.default_rng(2)
    system = rng.integers(0, cfg.vocab_size, (40,)).astype(np.int32)
    engine = ServingEngine(model, params, num_slots=2, prefill_bucket=16,
                           block_size=8, prefill_chunk=16)
    engine.warmup(prompt_lens=(16, 48))
    reqs = []
    for i in range(3):
        tail = rng.integers(0, cfg.vocab_size, (5 + i,)).astype(np.int32)
        p = np.concatenate([system, tail])
        reqs.append((p, engine.submit(p, max_new_tokens=5)))
        engine.run_until_idle()  # serialize so each later one can hit
    first, later = reqs[0][1], [r for _, r in reqs[1:]]
    assert first.prefix_hit_tokens == 0
    assert all(r.prefix_hit_tokens >= 40 - 8 for r in later)
    assert all(r.prefill_chunks < first.prefill_chunks for r in later)
    for p, r in reqs:
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=5)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0])
    s = engine.summary()
    assert s["prefix_hit_rate"] > 0
    assert s["prefix_cache"]["hits"] == 2
    engine.close()


def test_chunked_prefill_interleaves_with_decode():
    """A long admission must not head-of-line-block resident streams:
    while request B's prompt prefills chunk by chunk, resident request A
    keeps receiving one token per step."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=2,
                           prefill_bucket=16, block_size=8,
                           prefill_chunk=16)
    engine.warmup(prompt_lens=(16, 64))
    rng = np.random.default_rng(4)
    a = engine.submit(rng.integers(0, cfg.vocab_size, (5,)),
                      max_new_tokens=20)
    engine.step()
    assert len(a.new_tokens) >= 1
    # 60-token prompt = 4 chunks of 16: admission spans multiple steps
    b = engine.submit(rng.integers(0, cfg.vocab_size, (60,)),
                      max_new_tokens=4)
    deliveries = []
    while b.slot is None and not b.done:
        before = len(a.new_tokens)
        engine.step()
        deliveries.append(len(a.new_tokens) - before)
    assert len(deliveries) >= 3  # the admission really was chunked
    assert all(d == 1 for d in deliveries[:-1]), (
        f"resident stream starved during chunked prefill: {deliveries}")
    engine.run_until_idle()
    assert a.finish_reason == "length" and b.finish_reason == "length"
    engine.close()


def test_run_until_idle_finishes_stranded_prefill():
    """Regression: a resident stream retiring on the very step a
    neighbor's chunked prefill is mid-flight used to leave queue and
    slots empty with the admission stranded — run_until_idle must keep
    stepping until the in-flight prefill completes too."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=2,
                           prefill_bucket=16, block_size=8,
                           prefill_chunk=16)
    engine.warmup(prompt_lens=(16, 64))
    rng = np.random.default_rng(6)
    a = engine.submit(rng.integers(0, cfg.vocab_size, (5,)),
                      max_new_tokens=3)
    engine.step()  # admission + tick deliver 2: one-token budget left
    b = engine.submit(rng.integers(0, cfg.vocab_size, (60,)),
                      max_new_tokens=3)
    engine.step()  # chunk 1 of b + a's final token: a retires here
    assert a.done and not b.done and engine.prefilling_count == 1
    assert engine.active_count == 0 and engine.queue_depth == 0
    engine.run_until_idle()
    assert b.done and b.finish_reason == "length"
    assert len(b.new_tokens) == 3
    engine.close()


def test_preemption_requeues_and_stays_bitwise():
    """A pool too small for the offered load preempts the youngest
    resident (blocks freed, request requeued); its continuation resumes
    by re-prefilling prompt + generated — every request's final output
    stays bitwise-equal to generate(), and nothing retraces."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128)
    model = GPT2(cfg)
    params = _init(model)
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    engine = ServingEngine(model, params, num_slots=3, prefill_bucket=16,
                           block_size=8, num_blocks=21, prefill_chunk=16)
    engine.warmup(prompt_lens=(16,))
    traces0 = dict(serving_engine.TRACE_COUNTS)
    rng = np.random.default_rng(0)
    ps, rs = [], []
    for i in range(4):
        p = rng.integers(0, cfg.vocab_size, (20 + 7 * i,)).astype(np.int32)
        ps.append(p)
        rs.append(engine.submit(p, max_new_tokens=30))
        engine.step()
    engine.run_until_idle()
    assert sum(r.preemptions for r in rs) >= 1, "pool never pressured"
    assert engine.summary()["preemptions"] >= 1
    for p, r in zip(ps, rs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=30)
        np.testing.assert_array_equal(
            r.output_ids, np.asarray(ref)[0],
            err_msg=f"request {r.id} (preemptions={r.preemptions})")
    assert dict(serving_engine.TRACE_COUNTS) == traces0
    engine.close()


def test_blocks_freed_on_every_exit_path(tmp_path):
    """The ISSUE 7 leak satellite: stop-id retirement, budget
    retirement, deadline expiry (queued / resident / MID-PREFILL) and
    the SIGTERM drain all return their blocks — the pool invariant
    (free + resident == usable) holds mid-run and close()'s teardown
    assertion passes with only radix-cached blocks resident."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=2,
                           prefill_bucket=16, block_size=8,
                           prefill_chunk=16, telemetry_dir=str(tmp_path))
    engine.warmup(prompt_lens=(16, 64))
    rng = np.random.default_rng(1)

    def pool_consistent():
        a = engine._alloc
        assert a.free_count + a.resident == a.usable

    # budget ("length") + stop-id retirement
    r1 = engine.submit(rng.integers(0, cfg.vocab_size, (5,)),
                       max_new_tokens=3)
    engine.run_until_idle()
    stop = r1.new_tokens[0]
    r2 = engine.submit(rng.integers(0, cfg.vocab_size, (5,)),
                       max_new_tokens=50, stop_ids=(stop, 10 ** 6))
    engine.run_until_idle()
    pool_consistent()
    # deadline on queue (no blocks ever allocated) and mid-decode
    r3 = engine.submit(rng.integers(0, cfg.vocab_size, (6,)),
                       max_new_tokens=40, deadline_s=60.0)
    engine.step()
    assert r3.slot is not None
    r3.submit_time -= 120.0
    engine.step()
    assert r3.finish_reason == "deadline"
    pool_consistent()
    # deadline mid-chunked-prefill: blocks allocated, never decoded (a
    # resident stream keeps the admission chunked across steps)
    r5 = engine.submit(rng.integers(0, cfg.vocab_size, (5,)),
                       max_new_tokens=40)
    engine.step()
    r4 = engine.submit(rng.integers(0, cfg.vocab_size, (60,)),
                       max_new_tokens=4, deadline_s=60.0)
    engine.step()
    assert r4.slot is None and engine._prefilling is not None
    r4.submit_time -= 120.0
    engine.step()
    assert r4.finish_reason == "deadline" and engine._prefilling is None
    pool_consistent()
    # SIGTERM drain: the mid-stream resident + a queued request both shed
    r6 = engine.submit(rng.integers(0, cfg.vocab_size, (90,)),
                       max_new_tokens=4)
    engine.request_drain()
    engine.step()
    assert r5.finish_reason == "drained" and r6.finish_reason == "drained"
    assert 0 < len(r5.new_tokens) < 40
    pool_consistent()
    engine.close()  # asserts free + resident == pool, radix-only residue
    rows = [json.loads(x) for x in
            (tmp_path / "serve_metrics_rank0.jsonl")
            .read_text().strip().splitlines()]
    reasons = [r["finish_reason"] for r in rows if r["kind"] == "request"]
    assert reasons.count("deadline") == 2
    assert reasons.count("drained") == 2
    assert any(r["kind"] == "pool" for r in rows)


def test_zero_recompiles_steady_state_paged():
    """After warmup, a mixed paged load — any in-bucket prompt length,
    prefix hits AND misses, block growth, retire + readmit — triggers
    ZERO retraces and zero recompiles of the paged tick/chunk pair."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=3,
                           prefill_bucket=16, block_size=8)
    engine.warmup(prompt_lens=(8, 16))
    traces = dict(serving_engine.TRACE_COUNTS)
    sizes = (paged_prefill_chunk._cache_size(),
             paged_decode_tick._cache_size())
    rng = np.random.default_rng(3)
    shared = rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
    for i in range(8):
        if i % 3 == 0:  # prefix-cache hits exercise the reuse path
            p = np.concatenate([shared, rng.integers(
                0, cfg.vocab_size, (int(rng.integers(1, 8)),))]).astype(
                    np.int32)
        else:
            p = rng.integers(0, cfg.vocab_size,
                             (int(rng.integers(1, 16)),)).astype(np.int32)
        engine.submit(p, max_new_tokens=int(rng.integers(1, 6)))
        engine.step()
    engine.run_until_idle()
    assert dict(serving_engine.TRACE_COUNTS) == traces
    assert (paged_prefill_chunk._cache_size(),
            paged_decode_tick._cache_size()) == sizes
    engine.close()


def test_report_cli_renders_serving_table(tmp_path):
    """The telemetry report CLI grows a serving / prefix-cache section
    from the serve_metrics JSONL (ISSUE 7 satellite)."""
    from pytorchdistributed_tpu.telemetry.report import render

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=2,
                           prefill_bucket=16, block_size=8,
                           telemetry_dir=str(tmp_path))
    engine.warmup(prompt_lens=(16,))
    rng = np.random.default_rng(0)
    p = rng.integers(0, cfg.vocab_size, (20,)).astype(np.int32)
    engine.submit(p, max_new_tokens=4)
    engine.run_until_idle()
    engine.submit(p, max_new_tokens=4)  # guaranteed prefix hit
    engine.run_until_idle()
    engine.close()
    out = render(tmp_path)
    assert "serving (per rank" in out
    assert "prefix cache" in out
    assert "-token blocks" in out
    # KV compression columns (ISSUE 13): high-water resident bytes and
    # the pool's effective capacity at its storage dtype
    assert "kv resident" in out
    assert "tokens @ bf16" in out
    # the hit tokens column is non-zero: reuse reached the report
    import re
    m = re.search(r"^\s+0\s+\d+\s+\S+ ms\s+(\d+)", out, re.M)
    assert m and int(m.group(1)) > 0, out


# ---------------------------------------------------------------------------
# KV compression (ISSUE 13): int8 pool, window retirement, Pallas default


def test_allocator_midstream_decref_recycles():
    """ISSUE 13 regression: blocks decref'd MID-STREAM (window
    retirement) go straight back onto the free list and are handed out
    again while the retiring owner still holds its other blocks —
    and once everyone exits, check_leaks is clean."""
    a = BlockAllocator(8, 4)
    mine = a.alloc(5)
    retired = mine[1:3]
    for b in retired:
        assert a.decref(b)          # mid-stream retirement frees NOW
    assert a.free_count == 4
    theirs = a.alloc(4)             # a newcomer is backed by them
    assert theirs is not None and set(retired) <= set(theirs)
    for b in [mine[0], *mine[3:], *theirs]:
        a.decref(b)
    a.check_leaks()                 # stream finish leaves no residue


def test_parity_paged_engine_pallas():
    """The Pallas decode tick forced on CPU (interpret=True): greedy
    token streams match the gather engine's exactly on this seeded
    mixed workload. (Flash reassociates the softmax, so the pinned
    cross-engine contract is token equality on a deterministic
    backend; the BITWISE-vs-generate() contract stays on gather.)"""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    outs = {}
    for mode in ("gather", "pallas"):
        engine = ServingEngine(model, params, num_slots=3,
                               prefill_bucket=16, block_size=8,
                               paged_attn=mode)
        assert engine.paged_attn == mode
        assert engine.summary()["paged_attn"] == mode
        engine.warmup(prompt_lens=(8, 16))
        prompts, news = _mixed_requests(cfg.vocab_size, n=4)
        rs = []
        for p, n in zip(prompts, news):
            rs.append(engine.submit(p, max_new_tokens=n))
            engine.step()
        engine.run_until_idle()
        outs[mode] = [list(r.new_tokens) for r in rs]
        engine.close()
    assert outs["pallas"] == outs["gather"]


def test_parity_paged_engine_int8_readers_agree():
    """kv_dtype="int8" end-to-end: blocks are quantized at write time
    and both pool readers — the reference gather and the Pallas kernel
    — decode the SAME greedy streams from the same compressed pool
    (one canonical dequant, ops.quant.kv_dequantize, pinned across
    readers). The int8 pool is smaller than bf16's at equal blocks."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    outs, hbm = {}, {}
    for mode in ("gather", "pallas"):
        engine = ServingEngine(model, params, num_slots=2,
                               prefill_bucket=16, block_size=8,
                               kv_dtype="int8", paged_attn=mode)
        assert engine.summary()["kv_dtype"] == "int8"
        engine.warmup(prompt_lens=(8, 16))
        prompts, news = _mixed_requests(cfg.vocab_size, seed=5, n=3)
        rs = []
        for p, n in zip(prompts, news):
            rs.append(engine.submit(p, max_new_tokens=n))
            engine.step()
        engine.run_until_idle()
        assert all(r.finish_reason == "length" for r in rs)
        outs[mode] = [list(r.new_tokens) for r in rs]
        hbm[mode] = engine.kv_hbm_bytes
        engine.close()
    assert outs["pallas"] == outs["gather"]
    bf16 = ServingEngine(model, params, num_slots=2, prefill_bucket=16,
                         block_size=8)
    # int8 codes + fp32 scale planes vs bf16: (d + 4) / 2d bytes per
    # token-head — a real shrink at any head_dim > 4
    assert hbm["gather"] < bf16.kv_hbm_bytes
    bf16.close()


def test_window_retirement_recycles_blocks_midstream():
    """Sink+window streams hand their fully-dead middle blocks back to
    the pool WHILE STILL DECODING: two long streams that would
    overflow the pool at full attention (and preempt) instead run to
    completion preemption-free on the blocks retirement recycles —
    and close()'s leak invariant still passes."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128)
    model = GPT2(cfg)
    params = _init(model)
    rng = np.random.default_rng(21)
    prompts = [rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)
               for _ in range(2)]

    def run(**kw):
        engine = ServingEngine(model, params, num_slots=2,
                               prefill_bucket=16, block_size=8,
                               num_blocks=21, **kw)
        engine.warmup(prompt_lens=(16,))
        rs = [engine.submit(p, max_new_tokens=80) for p in prompts]
        engine.run_until_idle()
        assert all(r.finish_reason == "length" for r in rs)
        assert all(len(r.new_tokens) == 80 for r in rs)
        s = engine.summary()
        engine.close()
        return s

    full = run()
    win = run(kv_sink_tokens=8, kv_window_tokens=32)
    # full attention can't hold 2 x 96 tokens in 20 usable blocks
    assert full["preemptions"] >= 1
    # windowed: middle blocks retire back mid-stream, nobody preempts
    assert win["preemptions"] == 0
    assert win["retired_blocks"] > 0
    assert win["peak_blocks_used"] < full["peak_blocks_used"]
    assert win["kv_window_tokens"] == 32 and win["kv_sink_tokens"] == 8


def test_zero_recompiles_compressed_path():
    """The ISSUE 13 tripwire: steady-state decode on the int8 +
    windowed engine — block growth, MID-STREAM window retirement,
    retire + readmit — triggers ZERO retraces and zero recompiles
    after warmup (scale planes and the static window mask are baked
    into the compiled pair, never re-traced per step)."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    engine = ServingEngine(model, _init(model), num_slots=2,
                           prefill_bucket=16, block_size=8,
                           kv_dtype="int8", kv_sink_tokens=8,
                           kv_window_tokens=16)
    engine.warmup(prompt_lens=(8, 16))
    traces = dict(serving_engine.TRACE_COUNTS)
    sizes = (paged_prefill_chunk._cache_size(),
             paged_decode_tick._cache_size())
    rng = np.random.default_rng(13)
    for i in range(6):
        p = rng.integers(0, cfg.vocab_size,
                         (int(rng.integers(1, 16)),)).astype(np.int32)
        engine.submit(p, max_new_tokens=int(rng.integers(25, 40)))
        engine.step()
    engine.run_until_idle()
    s = engine.summary()
    assert s["retired_blocks"] > 0, "retirement never exercised"
    assert dict(serving_engine.TRACE_COUNTS) == traces
    assert (paged_prefill_chunk._cache_size(),
            paged_decode_tick._cache_size()) == sizes
    engine.close()


def test_paged_attn_env_and_auto_resolution(monkeypatch):
    """PTD_PAGED_ATTN seeds the default; "auto" resolves per backend
    (pallas on TPU, gather elsewhere — this suite runs on CPU); an
    explicit constructor arg beats the env."""
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)

    def attn(**kw):
        e = ServingEngine(model, params, num_slots=2, block_size=8, **kw)
        mode = e.paged_attn
        e.close()
        return mode

    monkeypatch.delenv("PTD_PAGED_ATTN", raising=False)
    assert attn() == "gather"                      # auto on CPU
    monkeypatch.setenv("PTD_PAGED_ATTN", "pallas")
    assert attn() == "pallas"                      # env seeds default
    assert attn(paged_attn="gather") == "gather"   # arg beats env
    monkeypatch.setenv("PTD_PAGED_ATTN", "auto")
    assert attn() == "gather"


def test_kv_compression_validations():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    with pytest.raises(ValueError, match="paged-engine knobs"):
        ServingEngine(model, params, num_slots=2, kv_dtype="int8")
    with pytest.raises(ValueError, match="paged_attn"):
        ServingEngine(model, params, num_slots=2, block_size=8,
                      paged_attn="bogus")
    with pytest.raises(ValueError, match="kv_dtype"):
        ServingEngine(model, params, num_slots=2, block_size=8,
                      kv_dtype="fp8")
    with pytest.raises(ValueError, match="multiple"):
        ServingEngine(model, params, num_slots=2, block_size=8,
                      kv_window_tokens=12)
    with pytest.raises(ValueError, match="kv_window_tokens"):
        ServingEngine(model, params, num_slots=2, block_size=8,
                      kv_sink_tokens=8)


def test_paged_validations():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=64)
    model = GPT2(cfg)
    params = _init(model)
    with pytest.raises(ValueError, match="divide"):
        ServingEngine(model, params, num_slots=2, block_size=7)
    with pytest.raises(ValueError, match="full-context"):
        ServingEngine(model, params, num_slots=2, block_size=8,
                      num_blocks=4)
    from pytorchdistributed_tpu.models.transformer import TransformerConfig
    with pytest.raises(ValueError, match="decode"):
        TransformerConfig(kv_block_size=8, kv_blocks=4)
    with pytest.raises(ValueError, match="multiple"):
        TransformerConfig(decode=True, decode_slots=2, kv_block_size=7,
                          kv_blocks=4, max_seq_len=64)
