"""Auto-placement planner tests (SURVEY.md §2c last row: the
device_map="auto" analog — reference 03_model_parallel.ipynb:86-89 (cell 1)).

The planner must climb the sharding ladder (replicate → fsdp → +tensor →
+pipe) exactly as far as the memory budget forces, computing per-device
state from the same logical-axis rules the Trainer shards with.
"""

import numpy as np
import pytest

from pytorchdistributed_tpu.config import ExperimentConfig, make_trainer
from pytorchdistributed_tpu.parallel.auto import (
    Leaf,
    auto_shard,
    plan_auto_shard,
)
from pytorchdistributed_tpu.parallel.tp import Logical

MB = 2**20

# A transformer-ish synthetic model: 8 stacked layers of [embed=1024,
# mlp=4096] kernels (stage-stacked, so pipe applies) + a [vocab=4096,
# embed=1024] embedding. ~46M params → ~738MB of adamw state replicated.
LEAVES = [
    Leaf((8, 1024, 4096), (Logical.STAGE, Logical.EMBED, Logical.MLP)),
    Leaf((8, 4096, 1024), (Logical.STAGE, Logical.MLP, Logical.EMBED)),
    Leaf((4096, 1024), (Logical.VOCAB, Logical.EMBED)),
]
TOTAL = sum(l.size for l in LEAVES) * 16  # adamw: 16 B/param


def _plan(budget_bytes, n=8, leaves=LEAVES):
    return plan_auto_shard(leaves, n, budget_bytes / 0.65, optimizer="adamw")
    # (/0.65 cancels the planner's 35% activation headroom so tests can
    # reason in exact state bytes)


def test_fits_replicated_stays_dp():
    plan = _plan(TOTAL * 1.01)
    assert plan.strategy == "dp"
    assert (plan.mesh.fsdp, plan.mesh.tensor, plan.mesh.pipe) == (1, 1, 1)


def test_grows_fsdp_minimally():
    # needs a factor of 2 → fsdp=2, not more
    plan = _plan(TOTAL / 2 * 1.01)
    assert plan.strategy == "fsdp" and plan.mesh.fsdp == 2
    # needs a factor of 8 → fsdp=8
    plan = _plan(TOTAL / 8 * 1.01)
    assert plan.strategy == "fsdp" and plan.mesh.fsdp == 8


def test_divisibility_caps_fsdp_then_tensor_takes_over():
    # embed=12 can only split 2 or 4 ways; mlp=4096 takes the rest
    leaves = [Leaf((8, 12, 4096), (Logical.STAGE, Logical.EMBED, Logical.MLP))]
    total = leaves[0].size * 16
    plan = _plan(total / 8 * 1.01, leaves=leaves)
    assert plan.strategy == "tp_fsdp"
    assert plan.mesh.fsdp * plan.mesh.tensor == 8


def test_pipe_when_only_stages_divide():
    # odd embed/mlp dims: fsdp and tensor can't split anything — only the
    # stage axis divides, so the ladder must reach for pipe
    leaves = [Leaf((8, 999, 999), (Logical.STAGE, Logical.EMBED, Logical.MLP))]
    total = leaves[0].size * 16
    plan = _plan(total / 4 * 1.01, leaves=leaves)
    assert plan.mesh.pipe >= 4


def test_impossible_budget_raises():
    with pytest.raises(ValueError, match="does not fit"):
        _plan(TOTAL / 64)


def test_auto_shard_on_real_gpt2():
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config

    model = GPT2(gpt2_config("test", dtype=jnp.float32))
    tokens = np.zeros((2, 32), np.int32)
    generous = auto_shard(model, (tokens,), n_devices=8,
                          device_memory_bytes=8 * 2**30)
    assert generous.strategy == "dp"
    tight = auto_shard(
        model, (tokens,), n_devices=8,
        device_memory_bytes=generous.total_state_bytes / 4)
    assert tight.strategy in ("fsdp", "tp_fsdp")
    assert tight.per_device_state_bytes < generous.per_device_state_bytes


def test_strategy_auto_end_to_end():
    """--strategy auto trains: the planner picks fsdp under a squeezed
    budget and the resulting Trainer takes a real step."""
    cfg = ExperimentConfig(
        model="gpt2", model_size="test", strategy="auto", seq_len=32,
        dataset_size=32, batch_size=8, bf16=False,
        device_memory_gb=0.002)  # ~2MB: forces sharding for the test model
    trainer, loader = make_trainer(cfg)
    assert trainer.strategy in ("fsdp", "tp_fsdp")
    batch = next(iter(loader))
    assert np.isfinite(float(trainer.train_step(batch)["loss"]))


def test_validate_plan_compiler_verified_fit():
    """validate_plan closes the planner's loop with XLA's own memory
    analysis of the actual compiled step: a test model fits a generous
    budget and fails an absurdly small one, with the reported need
    covering at least the training state the planner counted."""
    import jax.numpy as jnp
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.parallel.auto import validate_plan
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    model = GPT2(gpt2_config("test", dtype=jnp.float32))
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(data=8), strategy="dp", log_every=10**9)
    rng = np.random.default_rng(0)
    batch = {"tokens": rng.integers(0, 128, (16, 32)).astype(np.int32),
             "targets": rng.integers(0, 128, (16, 32)).astype(np.int32)}
    ok = validate_plan(tr, batch, device_memory_bytes=2 * 2**30)
    assert ok["fits"], ok
    assert ok["need_bytes"] >= ok["aliased_bytes"] > 0
    assert ok["need_bytes"] == (ok["arg_bytes"] + ok["out_bytes"]
                                - ok["aliased_bytes"] + ok["temp_bytes"])
    tight = validate_plan(tr, batch, device_memory_bytes=2**20)
    assert not tight["fits"], tight
