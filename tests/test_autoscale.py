"""SLO-aware autoscaling + multi-tenant admission (ISSUE 15).

Three layers, cheapest first:

  * PURE HOST — the traffic generators (seeded determinism tripwire,
    shape/tenant-mix properties), the AdmissionController's WDRR
    fairness (the acceptance pin: a hot tenant at 10x its budget CANNOT
    push a compliant tenant's shed count above zero), priority tiers,
    rate buckets on a FakeClock, pressure->window clamping, and the
    Autoscaler decision machine against a stub router (hysteresis,
    cooldowns, bounds, role-aware disagg pools) — no jax anywhere.
  * IN-PROCESS JAX — elastic add/remove on a live router (tombstone
    history surviving removal), the closed-loop flash-crowd demo
    (seeded trace -> queue growth -> warm scale-up with ZERO fresh
    compiles on the joiner -> drain back to baseline, compliant tenant
    shed == 0 throughout), router-level lossless preemption under
    tenant pressure, per-request KV window overrides (bitwise vs a
    natively tighter pool) and the loud rejection walls. Engine
    geometry mirrors tests/test_router.py / test_paging.py so the
    compiled programs ride the suite's shared jit cache.
  * SUBPROCESS (full tier only) — the autoscale e2e over real
    run.py-env-contract workers: flash crowd, async warm join through
    quarantine, graceful drain-down, zero orphan processes.

No wall-clock sleeps in the quick tier: every clock is a FakeClock.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.serving import (
    AdmissionController,
    Autoscaler,
    FakeClock,
    ReplicaRouter,
    RouterTelemetry,
    ServingEngine,
    SignalRing,
    SLOConfig,
    TenantConfig,
    TenantTraffic,
    make_trace,
    replay,
)
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.engine import (
    decode_tick,
    prefill_into_slot,
)

CFG = gpt2_config("test", num_layers=2, max_seq_len=64)


@functools.cache
def _setup():
    model = GPT2(CFG)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    return model, params, dm


def _ref(prompt, n):
    _, params, dm = _setup()
    return np.asarray(generate(dm, params, jnp.asarray(prompt)[None],
                               max_new_tokens=n))[0]


class _Req:
    """The slice of RouterRequest the admission controller reads."""

    _ids = iter(range(1, 10**9))

    def __init__(self, tenant, cost=10, priority=0, kv_window=None):
        self.id = next(self._ids)
        self.tenant = tenant
        self.priority = priority
        self.prompt = np.zeros(cost // 2, np.int32)
        self.max_new_tokens = cost - cost // 2
        self.kv_window = kv_window


# ----------------------------------------------------------------------
# traffic generators (pure host)

def test_traffic_determinism_and_validation():
    """The determinism tripwire: same seed -> byte-identical trace,
    prompts included. Plus the validation walls and FakeClock basics."""
    tens = (TenantTraffic("a", share=3, prefix_len=6, prefix_frac=0.5),
            TenantTraffic("b", share=1, priority=1))
    kw = dict(seed=11, duration_s=20.0, base_qps=4.0, shape="flash",
              peak_mult=5.0, tenants=tens)
    t1, t2 = make_trace(**kw), make_trace(**kw)
    assert len(t1) == len(t2) > 20
    for a, b in zip(t1, t2):
        assert (a.at_s, a.tenant, a.priority, a.max_new_tokens) == \
            (b.at_s, b.tenant, b.priority, b.max_new_tokens)
        np.testing.assert_array_equal(a.prompt, b.prompt)
    t3 = make_trace(**{**kw, "seed": 12})
    assert [r.at_s for r in t3] != [r.at_s for r in t1]
    with pytest.raises(ValueError, match="unknown traffic shape"):
        make_trace(seed=0, duration_s=1, base_qps=1, shape="bursty")
    with pytest.raises(ValueError, match="must be > 0"):
        make_trace(seed=0, duration_s=0, base_qps=1)
    clk = FakeClock(5.0)
    clk.advance(2.5)
    assert clk() == clk.now() == 7.5
    with pytest.raises(ValueError, match="forward"):
        clk.advance(-1)


def test_traffic_shapes_tenant_mix_and_prefixes():
    """Flash window runs ~peak_mult x the background rate; tenant
    shares land near the mix; a prefix_frac=1 tenant always opens with
    its fixed prefix; lengths respect the caps."""
    tens = (TenantTraffic("hot", share=3, prefix_len=8, prefix_frac=1.0),
            TenantTraffic("cold", share=1, priority=2))
    trace = make_trace(seed=3, duration_s=60.0, base_qps=6.0,
                       shape="flash", peak_mult=4.0, flash_at_s=20.0,
                       flash_len_s=10.0, tenants=tens, prompt_cap=24,
                       new_cap=12)
    in_flash = [r for r in trace if 20.0 <= r.at_s < 30.0]
    outside = [r for r in trace if not 20.0 <= r.at_s < 30.0]
    flash_qps = len(in_flash) / 10.0
    base_qps = len(outside) / 50.0
    assert flash_qps > 2.5 * base_qps, (flash_qps, base_qps)
    hot = [r for r in trace if r.tenant == "hot"]
    cold = [r for r in trace if r.tenant == "cold"]
    assert len(hot) + len(cold) == len(trace)
    assert 1.8 < len(hot) / max(1, len(cold)) < 5.0
    pre = hot[0].prompt[:8]
    for r in hot:
        np.testing.assert_array_equal(r.prompt[:8], pre)
        assert r.priority == 0
    for r in cold:
        assert r.priority == 2
    for r in trace:
        assert 1 <= r.prompt.size <= 24 and 1 <= r.max_new_tokens <= 12
    steady = make_trace(seed=3, duration_s=60.0, base_qps=6.0)
    assert all(r.tenant == "default" for r in steady)


# ----------------------------------------------------------------------
# admission control (pure host)

def test_wdrr_weighted_token_fairness_and_priority_tiers():
    """Served token cost tracks WDRR weights (3:1), not request counts;
    a lower priority tier is never popped while a higher one queues."""
    ac = AdmissionController({"big": TenantConfig(weight=3.0),
                              "small": TenantConfig(weight=1.0)})
    for _ in range(60):
        assert ac.offer(_Req("big", cost=20)) is None
        assert ac.offer(_Req("small", cost=20)) is None
    served = {"big": 0.0, "small": 0.0}
    for _ in range(80):
        rr = ac.popleft()
        served[rr.tenant] += rr.prompt.size + rr.max_new_tokens
    ratio = served["big"] / served["small"]
    assert 2.0 < ratio < 4.5, served
    # strict priority tiers above fairness
    ac2 = AdmissionController({"fg": TenantConfig(), "bg": TenantConfig()})
    for _ in range(5):
        ac2.offer(_Req("bg", priority=1))
    for _ in range(3):
        ac2.offer(_Req("fg", priority=0))
    order = [ac2.popleft().tenant for _ in range(8)]
    assert order[:3] == ["fg"] * 3 and order[3:] == ["bg"] * 5
    with pytest.raises(IndexError):
        ac2.popleft()


def test_admission_per_tenant_caps_and_rate_bucket():
    """max_queued sheds the arrival itself; the token rate bucket
    refills on the injected clock — no wall-clock anywhere."""
    clk = FakeClock()
    ac = AdmissionController(
        {"capped": TenantConfig(max_queued=2),
         "metered": TenantConfig(rate_tokens_per_s=10.0, burst_s=1.0)},
        clock=clk)
    a, b, c = _Req("capped"), _Req("capped"), _Req("capped")
    assert ac.offer(a) is None and ac.offer(b) is None
    assert ac.offer(c) is c          # over the per-tenant cap
    # bucket starts at rate*burst = 10 tokens: one cost-10 fits
    m1, m2 = _Req("metered", cost=10), _Req("metered", cost=10)
    assert ac.offer(m1) is None
    assert ac.offer(m2) is m2        # bucket empty, clock frozen
    clk.advance(1.0)                 # +10 tokens
    assert ac.offer(_Req("metered", cost=10)) is None
    with pytest.raises(ValueError, match="weight"):
        TenantConfig(weight=0)
    with pytest.raises(ValueError, match="max_queued"):
        TenantConfig(max_queued=0)
    with pytest.raises(ValueError, match="rate_tokens_per_s"):
        TenantConfig(rate_tokens_per_s=-1)


def test_hot_tenant_at_10x_cannot_shed_compliant_tenant():
    """THE fairness acceptance pin: with the global queue capped and a
    hot tenant flooding at 10x a compliant neighbour's volume, every
    shed lands on the hot tenant — the compliant tenant's shed count is
    exactly zero, and it keeps being served."""
    ac = AdmissionController({"hot": TenantConfig(weight=1.0),
                              "calm": TenantConfig(weight=1.0)},
                             max_queue=8)
    shed = {"hot": 0, "calm": 0}
    calm_admitted = 0
    for i in range(200):
        victim = ac.offer(_Req("hot", cost=20))
        if victim is not None:
            shed[victim.tenant] += 1
        if i % 10 == 0:
            rr = _Req("calm", cost=20)
            victim = ac.offer(rr)
            if victim is not None:   # a hot eviction = calm admitted
                shed[victim.tenant] += 1
            if victim is not rr:
                calm_admitted += 1
        if i % 4 == 0 and len(ac):
            ac.popleft()
    assert shed["calm"] == 0, shed
    assert shed["hot"] > 0, shed
    assert calm_admitted == 20
    stats = ac.tenant_stats()
    assert stats["hot"]["overage"] > 0 >= stats["calm"]["overage"]
    # starved_head surfaces the compliant head, never the hot one
    ac.offer(_Req("calm", cost=20))
    head = ac.starved_head()
    assert head is not None and head.tenant == "calm"


def test_pressure_clamps_kv_windows_by_priority():
    """Past pressure_depth, an admitted request's kv_window is clamped
    to its priority class budget — tighten-only, best tier untouched."""
    ac = AdmissionController(max_queue=None, pressure_depth=2,
                             priority_windows={1: 8, 2: 4})
    r0 = _Req("t", priority=1)
    assert ac.offer(r0) is None and r0.kv_window is None  # no pressure
    ac.offer(_Req("t"))
    hi = _Req("t", priority=0)
    lo = _Req("t", priority=1)
    bg = _Req("t", priority=2, kv_window=2)
    for rr in (hi, lo, bg):
        assert ac.offer(rr) is None
    assert hi.kv_window is None       # priority 0 has no budget entry
    assert lo.kv_window == 8
    assert bg.kv_window == 2          # already tighter: not loosened


def test_admission_deque_protocol_roundtrip():
    """append/appendleft/remove/len/iter keep the router's existing
    queue idioms working; appendleft (requeue) never re-charges."""
    ac = AdmissionController({"t": TenantConfig()})
    rs = [_Req("t") for _ in range(3)]
    for rr in rs:
        assert ac.offer(rr) is None
    charged = ac.tenant_stats()["t"]["charged_tokens"]
    head = ac.popleft()
    ac.appendleft(head)               # failover-style requeue
    assert ac.tenant_stats()["t"]["charged_tokens"] == charged
    assert len(ac) == 3 and bool(ac)
    assert ac.popleft() is head       # back at the front
    ac.remove(rs[2])
    assert [r.id for r in ac] == [rs[1].id]
    with pytest.raises(ValueError, match="not queued"):
        ac.remove(rs[2])


# ----------------------------------------------------------------------
# the autoscaler decision machine (pure host, stub router)

class _StubRouter:
    """The narrow surface Autoscaler consumes, scriptable per tick."""

    def __init__(self, pools=("fleet",), healthy=1):
        self.telemetry = RouterTelemetry(None)
        self.pools = {p: dict(replicas=healthy, healthy=healthy,
                              draining=0, quarantined=0, dead=0,
                              removed=0, occupancy=0.1, free_slots=3,
                              queued=0, prefilling=0, parked=0)
                      for p in pools}
        self.added: list[str] = []
        self.removed: list[str | None] = []
        self.veto_remove = False
        self.first_token_times: dict[int, float] = {}
        self._next = healthy * len(self.pools)

    def pool_state(self):
        return {p: dict(st) for p, st in self.pools.items()}

    def _pool_of(self, role):
        if "fleet" in self.pools:
            return "fleet"
        return "decode" if role in ("decode", "both") else "prefill"

    def add_replica(self, role="both"):
        self.added.append(role)
        self.pools[self._pool_of(role)]["healthy"] += 1
        self._next += 1
        return self._next - 1

    def remove_replica(self, index=None, role=None):
        if self.veto_remove:
            return None
        self.removed.append(role)
        pool = self._pool_of(role or "both")
        self.pools[pool]["healthy"] -= 1
        return 0


def test_autoscaler_hysteresis_cooldown_and_bounds():
    clk = FakeClock()
    stub = _StubRouter()
    asc = Autoscaler(stub, SLOConfig(queue_high=4.0), min_replicas=1,
                     max_replicas=3, breach_ticks=2, clear_ticks=3,
                     up_cooldown_s=1.0, down_cooldown_s=1.0, clock=clk)
    stub.telemetry.signal(queue_depth=20, submitted=5, shed=0)
    assert asc.step() == []                   # breach 1 < breach_ticks
    made = asc.step()                         # breach 2 -> scale up
    assert [d["action"] for d in made] == ["scale_up"]
    assert made[0]["why"] == ["queue_depth"]
    assert made[0]["m_queue_depth"] > 4.0     # the justifying snapshot
    assert stub.added == ["both"]
    for _ in range(4):                        # still breaching, cooling
        assert asc.step() == []
    clk.advance(1.5)
    asc.step(), asc.step()
    assert len(stub.added) == 2 and stub.pools["fleet"]["healthy"] == 3
    clk.advance(1.5)
    for _ in range(5):                        # at max_replicas: capped
        asc.step()
    assert len(stub.added) == 2
    # quarantined joiners count toward the bound
    stub.pools["fleet"]["healthy"], stub.pools["fleet"]["quarantined"] = 2, 1
    clk.advance(1.5)
    for _ in range(3):
        assert asc.step() == []
    stub.pools["fleet"]["quarantined"] = 0
    stub.pools["fleet"]["healthy"] = 3
    # idle -> clear_ticks -> one graceful scale-down at a time
    stub.telemetry.signal(queue_depth=0, submitted=0, shed=0)
    stub.pools["fleet"]["occupancy"] = 0.05
    for _ in range(40):                       # drain the queue EMA
        stub.telemetry.signal(queue_depth=0, submitted=0, shed=0)
    clk.advance(5.0)
    downs = []
    for _ in range(3):
        downs += asc.step()
    assert [d["action"] for d in downs] == ["scale_down"]
    assert downs[0]["why"] == ["idle"]
    # a draining pool blocks further shrink; a vetoed remove is no-op
    stub.pools["fleet"]["draining"] = 1
    clk.advance(5.0)
    for _ in range(5):
        assert asc.step() == []
    stub.pools["fleet"]["draining"] = 0
    stub.veto_remove = True
    for _ in range(5):
        assert asc.step() == []
    assert stub.pools["fleet"]["healthy"] == 2
    s = asc.summary()
    assert s["scale_ups"] == 2 and s["scale_downs"] == 1
    with pytest.raises(ValueError, match="min_replicas"):
        Autoscaler(stub, min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        Autoscaler(stub, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="occupancy_low"):
        SLOConfig(occupancy_low=0.9, occupancy_high=0.5)


def test_autoscaler_role_aware_disagg_pools():
    """In a disaggregated fleet the pools scale INDEPENDENTLY: prefill
    backlog grows only the prefill pool, decode occupancy only the
    decode pool, each within its own pool_bounds."""
    clk = FakeClock()
    stub = _StubRouter(pools=("prefill", "decode"), healthy=1)
    asc = Autoscaler(stub, SLOConfig(prefill_backlog_high=4.0,
                                     occupancy_high=0.8, queue_high=50.0),
                     pool_bounds={"prefill": (1, 2), "decode": (1, 3)},
                     breach_ticks=2, clear_ticks=100,
                     up_cooldown_s=0.0, clock=clk)
    stub.telemetry.signal(prefill_backlog=10, queue_depth=0,
                          submitted=4, shed=0)
    asc.step()
    made = asc.step()
    assert [(d["action"], d["pool"]) for d in made] == \
        [("scale_up", "prefill")]
    assert made[0]["why"] == ["prefill_backlog"]
    assert stub.added == ["prefill"]
    # decode pressure scales decode only; prefill is now at its cap
    stub.pools["decode"]["occupancy"] = 0.95
    clk.advance(1.0)
    for _ in range(3):
        made += asc.step()
    assert stub.added == ["prefill", "decode"]
    ups = [(d["pool"], d["why"]) for d in made if d["action"] == "scale_up"]
    assert ("decode", ["occupancy"]) in ups
    # reaction_times joins decisions against first_token_times
    up = [d for d in made if d["pool"] == "decode"][0]
    stub.first_token_times[up["replica"]] = up["wall_t"] + 0.25
    reacts = {r["replica"]: r["reaction_s"] for r in asc.reaction_times()}
    assert abs(reacts[up["replica"]] - 0.25) < 1e-6


def test_signal_ring_bounded_stats_and_snapshot():
    ring = SignalRing(maxlen=4, alpha=0.5)
    for v in range(10):
        ring.push(float(v))
    st = ring.stats()
    assert st["n"] == 4 and st["last"] == 9.0 and st["max"] == 9.0
    assert st["sum"] == 6.0 + 7 + 8 + 9 and st["mean"] == 7.5
    assert 0 < st["ema"] < 9.0
    tel = RouterTelemetry(None)           # ring-only mode: no files
    tel.signal(queue_depth=3, shed=1, skipped=None)
    tel.signal(queue_depth=5, shed=0)
    snap = tel.snapshot()
    assert set(snap) == {"queue_depth", "shed"}
    assert snap["queue_depth"]["last"] == 5.0
    assert snap["shed"]["sum"] == 1.0
    tel.event("autoscale_up", pool="fleet")
    assert tel.recent_events[-1]["event"] == "autoscale_up"


# ----------------------------------------------------------------------
# in-process jax: elastic scaling on a live router

def _router(*, replicas=1, **kw):
    model, params, _ = _setup()
    router = ReplicaRouter(model, params, replicas=replicas,
                           engine_kwargs=dict(num_slots=3,
                                              prefill_bucket=16),
                           warmup_lens=(16, 32), **kw)
    router.warmup()
    return router


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
            for m in (5, 9, 7, 11, 6, 8, 4, 10)[:n]]


def test_router_add_remove_replica_tombstone_history():
    """add_replica warm-joins at a NEW index (in-process: shares the
    jit cache, HEALTHY immediately); remove_replica drains gracefully
    to a REMOVED tombstone that is never renumbered — counters, roles
    and served_by history survive the removal in summary()."""
    router = _router(replicas=1)
    try:
        prompts = _prompts(4)
        for p in prompts[:2]:
            router.submit(p, max_new_tokens=5)
        router.run_until_idle()
        j = router.add_replica()
        assert j == 1
        st = router.pool_state()["fleet"]
        assert st["healthy"] == 2 and st["draining"] == 0
        # steer work onto the joiner so its history is non-trivial
        reqs = [router.submit(p, max_new_tokens=5) for p in prompts]
        router.run_until_idle()
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(
                r.output_ids, _ref(p, 5), err_msg=f"request {r.id}")
        assert router.remove_replica(index=j) == j
        for _ in range(50):
            router.step()
            if router.summary()["statuses"][j] == "removed":
                break
        s = router.summary()
        assert s["statuses"] == ["healthy", "removed"]
        assert s["scale_ups"] == 1 and s["scale_downs"] == 1
        assert s["replicas"] == 2 and s["healthy_replicas"] == 1
        # history survives the tombstone: the removed replica's serves
        # stay in served_by, and the remove is vetoed at min fleet
        assert sum(s["served_by"].values()) == s["completed"]
        assert router.remove_replica() is None
        evs = [e["event"] for e in router.telemetry.recent_events]
        assert {"scale_up", "scale_down", "replica_removed"} <= set(evs)
        # post-removal service still works on the survivor
        r = router.submit(prompts[0], max_new_tokens=5)
        router.run_until_idle()
        np.testing.assert_array_equal(r.output_ids, _ref(prompts[0], 5))
    finally:
        router.close()


def test_flash_crowd_autoscales_warm_and_drains_back():
    """The closed-loop acceptance demo: a seeded flash crowd over a
    hot(10x)/calm tenant mix on a 1-replica fleet. The autoscaler must
    scale up on the breach WITHOUT a single fresh XLA trace (the warm
    join shares the jit cache), the compliant tenant must shed exactly
    zero while the queue cap sheds the hot tenant, and after the crowd
    passes the fleet must drain back to baseline tombstones. Fully
    deterministic arrivals (seeded trace + FakeClock), no sleeps."""
    trace = make_trace(
        seed=7, duration_s=4.0, base_qps=5.0, shape="flash",
        peak_mult=30.0, flash_at_s=1.0, flash_len_s=1.5,
        tenants=(TenantTraffic("hot", share=10.0),
                 TenantTraffic("calm", share=1.0)),
        vocab_size=CFG.vocab_size, prompt_cap=24, new_cap=8)
    assert {r.tenant for r in trace} == {"hot", "calm"}
    router = _router(
        replicas=1, max_queue=8,
        tenants={"hot": TenantConfig(weight=1.0),
                 "calm": TenantConfig(weight=1.0)})
    clk = FakeClock()
    # ttft_target is wall-clock — neutralized here (CPU step timing is
    # not a test input); queue depth is the deterministic breach signal
    asc = Autoscaler(router, SLOConfig(queue_high=3.0,
                                       occupancy_high=0.9,
                                       occupancy_low=0.5,
                                       shed_rate_max=1.0,
                                       ttft_target_ms=1e9),
                     min_replicas=1, max_replicas=3, breach_ticks=2,
                     clear_ticks=25, up_cooldown_s=0.3,
                     down_cooldown_s=0.2, clock=clk)
    try:
        traces = dict(serving_engine.TRACE_COUNTS)
        sizes = (decode_tick._cache_size(),
                 prefill_into_slot._cache_size())
        replay(router, trace, clock=clk, tick_s=0.02, autoscaler=asc)
        # scaled up during the crowd...
        s = router.summary()
        assert s["scale_ups"] >= 1, s
        # ...with ZERO fresh compiles anywhere (warm join = shared cache)
        assert dict(serving_engine.TRACE_COUNTS) == traces
        assert (decode_tick._cache_size(),
                prefill_into_slot._cache_size()) == sizes
        # fairness held under the cap: the hot tenant shed, calm did not
        tens = s["tenants"]
        assert s["shed_requests"] > 0, s
        assert tens["calm"]["shed"] == 0, tens
        assert tens["hot"]["shed"] == s["shed_requests"], tens
        assert tens["hot"]["submitted"] > 4 * tens["calm"]["submitted"]
        assert tens["calm"]["completed"] == tens["calm"]["submitted"]
        assert s["completed"] == s["submitted"] - s["shed_requests"]
        # keep ticking the idle fleet: it must drain back to baseline
        for _ in range(3000):
            router.step()
            asc.step()
            clk.advance(0.02)
            if router.pool_state()["fleet"]["healthy"] == 1 \
                    and router.pool_state()["fleet"]["draining"] == 0:
                break
        s = router.summary()
        assert s["healthy_replicas"] == 1, s
        assert s["scale_downs"] >= 1, s
        assert all(st in ("healthy", "removed") for st in s["statuses"])
        # every decision carries its justifying metric snapshot
        for d in asc.decisions:
            assert d["why"] and "m_queue_depth" in d
        up_events = [e for e in router.telemetry.recent_events
                     if e["event"] == "autoscale_up"]
        assert up_events and "why" in up_events[0]
        # the joiners actually served: reaction times are measurable
        reacts = [r for r in asc.reaction_times()
                  if r["reaction_s"] is not None]
        assert reacts, asc.reaction_times()
    finally:
        router.close()


def test_router_preempts_over_budget_tenant_losslessly():
    """Admission-pressure preemption: a hot tenant saturating the only
    replica gets one stream preempted (requeued, NOT dropped) when a
    compliant tenant's request starves at the head — and every stream,
    preempted included, still finishes bitwise-identical to the
    uncontended reference."""
    router = _router(replicas=1, preempt_every=2,
                     tenants={"hot": TenantConfig(weight=1.0),
                              "calm": TenantConfig(weight=1.0)})
    try:
        prompts = _prompts(7, seed=5)
        hot = [router.submit(p, max_new_tokens=10, tenant="hot")
               for p in prompts[:6]]
        for _ in range(3):             # saturate: 3 slots + 1 pending
            router.step()
        calm = router.submit(prompts[6], max_new_tokens=10, tenant="calm")
        router.run_until_idle()
        s = router.summary()
        assert s["preemptions"] >= 1, s
        assert s["preempted_requeues"] == s["preemptions"]
        assert s["completed"] == 7 and s["shed_requests"] == 0
        for p, r in zip(prompts, hot + [calm]):
            np.testing.assert_array_equal(
                r.output_ids, _ref(p, 10), err_msg=f"request {r.id}")
        evs = [e["event"] for e in router.telemetry.recent_events]
        assert "preempt" in evs and "preempt_requeue" in evs
    finally:
        router.close()


def test_router_rejects_incompatible_kv_override_loudly():
    """A per-request window override on a pool that can't honor it
    (dense engine) fails THAT request loudly — finish_reason "failed"
    plus a "rejected" telemetry event — and never poisons the fleet."""
    router = _router(replicas=1)
    try:
        with pytest.raises(ValueError, match="kv_window"):
            router.submit(_prompts(1)[0], max_new_tokens=4, kv_window=0)
        bad = router.submit(_prompts(1)[0], max_new_tokens=4, kv_window=16)
        ok = router.submit(_prompts(1)[0], max_new_tokens=4)
        router.run_until_idle()
        assert bad.finish_reason == "failed"
        assert ok.finish_reason == "length"
        s = router.summary()
        assert s["failed_requests"] == 1 and s["healthy_replicas"] == 1
        rej = [e for e in router.telemetry.recent_events
               if e["event"] == "rejected"]
        assert rej and "kv_window" in rej[0]["error"]
    finally:
        router.close()


# ----------------------------------------------------------------------
# in-process jax: per-request KV windows + engine preemption

@functools.cache
def _setup_win():
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128)
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    return model, params


def _win_engine(window, **kw):
    model, params = _setup_win()
    return ServingEngine(model, params, num_slots=2, prefill_bucket=16,
                         block_size=8, num_blocks=64, kv_sink_tokens=8,
                         kv_window_tokens=window, **kw)


def test_per_request_window_override_bitwise():
    """submit(kv_window=W) on a window-2W pool decodes BITWISE like a
    pool natively configured at W (prompt shorter than W: prefill masks
    under the pool config, the override owns every decoded token) —
    while a no-op override and the untouched default stay bitwise with
    the wide pool. Overrides only tighten: a wider ask clamps to the
    pool; both round up to whole blocks."""
    def run(window, **skw):
        eng = _win_engine(window)
        eng.warmup(prompt_lens=(16,))
        req = eng.submit(np.arange(1, 11, dtype=np.int32),
                         max_new_tokens=48, **skw)
        eng.run_until_idle()
        toks = list(req.new_tokens)
        assert req.finish_reason == "length"
        eng.close()
        return toks, req

    tight, _ = run(16)
    overridden, req = run(32, kv_window=16, kv_sink=8)
    assert req.kv_window == 16 and req.kv_sink == 8
    assert overridden == tight
    wide, _ = run(32)
    noop, _ = run(32, kv_window=32)
    assert noop == wide
    assert overridden != wide          # the window actually bites
    # tighten-only + block rounding
    eng = _win_engine(32)
    r = eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                   kv_window=1000, kv_sink=3)
    assert r.kv_window == 32 and r.kv_sink == 8   # clamped to the pool
    r2 = eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                    kv_window=9)
    assert r2.kv_window == 16          # rounded UP to whole blocks
    eng.close()


def test_kv_override_rejection_walls():
    """Incompatible pools reject the override at submit() with a
    loud ValueError: dense, windowless-paged, and pallas decode.
    prefill_only + override is ACCEPTED — the tightened limit rides
    the handoff wire (test_sessions covers the import side)."""
    model, params = _setup_win()
    dense = ServingEngine(model, params, num_slots=2, prefill_bucket=16)
    with pytest.raises(ValueError, match="paged engine"):
        dense.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                     kv_window=16)
    dense.close()
    windowless = ServingEngine(model, params, num_slots=2,
                               prefill_bucket=16, block_size=8)
    with pytest.raises(ValueError, match="windowed pool"):
        windowless.submit(np.arange(1, 5, dtype=np.int32),
                          max_new_tokens=4, kv_window=16)
    windowless.close()
    pal = _win_engine(32, paged_attn="pallas")
    assert not pal.per_slot_limits
    with pytest.raises(ValueError, match="Pallas"):
        pal.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                   kv_window=16)
    pal.close()
    eng = _win_engine(32)
    with pytest.raises(ValueError, match="kv_window must be >= 1"):
        eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                   kv_window=0)
    with pytest.raises(ValueError, match="kv_sink must be >= 0"):
        eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                   kv_sink=-1)
    h = eng.submit(np.arange(1, 5, dtype=np.int32), max_new_tokens=4,
                   kv_window=16, prefill_only=True)
    while not h.parked:
        eng.step()
    assert h.kv_window == 16
    eng.close()


def test_engine_preempt_request_lossless_and_states():
    """preempt_request frees the slot NOW and keeps every delivered
    token; submit(generated=...) resumes the stream bitwise. Queued
    requests just leave the queue; mid-prefill and foreign requests
    are refused (False), never half-torn."""
    model, params = _setup_win()
    eng = ServingEngine(model, params, num_slots=2, prefill_bucket=16,
                        block_size=8, num_blocks=64)
    eng.warmup(prompt_lens=(16,))
    p = np.arange(1, 9, dtype=np.int32)
    ref = eng.submit(np.array(p), max_new_tokens=12)
    eng.run_until_idle()
    want = list(ref.new_tokens)
    r2 = eng.submit(np.array(p), max_new_tokens=12)
    for _ in range(4):
        eng.step()
    got = list(r2.new_tokens)
    assert 0 < len(got) < 12
    assert eng.preempt_request(r2)
    assert r2.done and r2.finish_reason == "preempted"
    assert not eng.preempt_request(r2)          # already retired
    r3 = eng.submit(np.array(p), max_new_tokens=12 - len(got),
                    generated=got)
    eng.run_until_idle()
    assert got + list(r3.new_tokens) == want
    # queued preemption: never activated, just leaves the queue
    stuck = [eng.submit(np.array(p), max_new_tokens=4)
             for _ in range(4)]
    assert eng.preempt_request(stuck[-1])
    eng.run_until_idle()
    assert stuck[-1].finish_reason == "preempted"
    assert all(r.finish_reason == "length" for r in stuck[:-1])
    assert eng.summary()["preempted_requests"] == 2
    eng.close()


# ----------------------------------------------------------------------
# subprocess mode (full tier: spawns real workers that import jax)

def test_subprocess_autoscale_e2e_no_orphans():
    """The e2e: subprocess workers under a flash crowd — the autoscaler
    spawns a joiner from the base spec (async warm through QUARANTINE,
    run.py env contract), the joiner rejoins and the fleet serves, then
    a graceful remove drains it to a tombstone whose process EXITS; at
    close, zero orphan processes fleet-wide."""
    import time

    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1,
            "engine": {"num_slots": 2, "prefill_bucket": 16}}
    router = ReplicaRouter(workers=[spec], warmup_lens=(16, 32))
    procs = []
    try:
        router.warmup()
        j = router.add_replica()
        procs = [rep.proc for rep in router._replicas]
        assert router._status[j] == "quarantined"   # warming async
        deadline = time.time() + 300
        prompts = _prompts(4)
        while time.time() < deadline and router._status[j] != "healthy":
            router.step()
            time.sleep(0.01)
        assert router._status[j] == "healthy", router._status
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_steps=200000)
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(r.output_ids, _ref(p, 6),
                                          err_msg=f"request {r.id}")
        assert router.remove_replica(index=j) == j
        deadline = time.time() + 60
        while time.time() < deadline \
                and router.summary()["statuses"][j] != "removed":
            router.step()
        s = router.summary()
        assert s["statuses"][j] == "removed"
        assert s["scale_ups"] == 1 and s["scale_downs"] == 1
        # the tombstone's worker process is already gone at removal
        deadline = time.time() + 15
        while time.time() < deadline and procs[j].poll() is None:
            time.sleep(0.1)
        assert procs[j].poll() is not None
        # the survivor still serves
        r = router.submit(prompts[0], max_new_tokens=6)
        router.run_until_idle(max_steps=200000)
        np.testing.assert_array_equal(r.output_ids, _ref(prompts[0], 6))
    finally:
        router.close()
    deadline = time.time() + 15
    while time.time() < deadline and any(p.poll() is None for p in procs):
        time.sleep(0.1)
    assert all(p.poll() is not None for p in procs), \
        [p.poll() for p in procs]
