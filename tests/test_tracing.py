"""Fleet-wide distributed request tracing (telemetry/tracing.py,
ISSUE 17).

Three layers, cheapest first:

  * host units — TraceContext wire round-trip, tracer rows + the
    unix-anchor clock mapping, the critical-path sweep's EXACT-tiling
    invariant on synthetic spans, SLO-debt attribution, the Chrome
    export, and the ``telemetry trace`` CLI;
  * in-process fleet e2e — a disagg fleet (prefill role handing KV to
    decode roles) with an injected mid-stream crash: every completed
    request's spans form ONE connected trace whose per-stage sums tile
    its terminal latency within 1 ms, across handoff AND failover;
  * the off-means-off pin — tracing disabled leaves the router's event
    stream identical (wall-clock stamp aside), writes no trace files,
    and triggers zero fresh XLA traces.

The subprocess wire e2e (workers exporting per-rank trace files joined
across process boundaries) is full-tier only — it spawns jax-importing
workers. Engine geometry mirrors tests/test_disagg.py so the compiled
programs ride the suite's shared jit cache.
"""

import dataclasses
import functools
import json
import os

import numpy as np

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.faults.inject import FaultInjector, FaultPlan
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.serving import (
    ROLE_DECODE,
    ROLE_PREFILL,
    KVBlockPayload,
    ReplicaRouter,
    SamplingParams,
    kv_payload_from_wire,
    kv_payload_to_wire,
)
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.telemetry.tracing import (
    STAGES,
    TRACE_GLOB,
    RequestTracer,
    TraceContext,
    chrome_trace,
    critical_path,
    critical_paths,
    from_unix,
    read_trace,
    render_trace,
    slo_debt,
    to_unix,
)

CFG = gpt2_config("test", num_layers=2, max_seq_len=64)


@functools.cache
def _setup():
    model = GPT2(CFG)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    return model, params, dm


def _ref(prompt, n):
    _, params, dm = _setup()
    return np.asarray(generate(dm, params, jnp.asarray(prompt)[None],
                               max_new_tokens=n))[0]


def _router(roles, run_dir, *, trace=True, faults=None, **kw):
    model, params, _ = _setup()
    router = ReplicaRouter(
        model, params, replicas=len(roles), roles=roles,
        engine_kwargs=dict(num_slots=3, prefill_bucket=16, block_size=8),
        warmup_lens=(16, 32), faults=faults,
        telemetry_dir=str(run_dir), trace=trace, **kw)
    router.warmup()
    return router


# ----------------------------------------------------------------------
# host units (no jax work)


def test_trace_context_wire_roundtrip():
    tracer_free = TraceContext("abcd1234", "abcd1234/0")
    wire = json.loads(json.dumps(tracer_free.to_wire()))
    back = TraceContext.from_wire(wire)
    assert back.trace_id == "abcd1234" and back.root == "abcd1234/0"
    # absent / empty context on the wire -> no context, not a crash
    assert TraceContext.from_wire(None) is None
    assert TraceContext.from_wire({}) is None


def test_tracer_rows_and_clock_anchor(tmp_path, monkeypatch):
    t = RequestTracer(tmp_path, rank=3)
    ctx = t.new_trace()
    assert ctx.root == f"{ctx.trace_id}/0"
    now = 1000.0   # a perf_counter reading
    t.span(ctx, "request", now, now + 0.5, root=True,
           request=7, tenant="a", ttft_s=0.1)
    t.span(ctx, "queue", now, now + 0.1, replica=1)
    t.span(None, "queue", now, now + 0.1)   # no context -> no row
    t.close()
    rows = read_trace(tmp_path)
    assert len(rows) == 2
    root = next(r for r in rows if r["parent"] is None)
    stage = next(r for r in rows if r["parent"] is not None)
    assert root["span"] == ctx.root and stage["parent"] == ctx.root
    assert root["span"] != stage["span"]
    assert stage["rank"] == 3 and stage["replica"] == 1
    assert root["t1_us"] - root["t0_us"] == 500_000.0
    # the anchor maps perf_counter <-> unix exactly (one process)
    assert from_unix(to_unix(now)) == now
    assert abs(root["t0_us"] / 1e6 - to_unix(now)) < 1e-3
    # env-contract constructor: off by default, on with both vars
    monkeypatch.delenv("PTD_TRACE", raising=False)
    assert RequestTracer.from_env() is None
    monkeypatch.setenv("PTD_TRACE", "1")
    monkeypatch.delenv("PTD_TELEMETRY_DIR", raising=False)
    assert RequestTracer.from_env() is None
    monkeypatch.setenv("PTD_TELEMETRY_DIR", str(tmp_path))
    t2 = RequestTracer.from_env(rank=5)
    assert t2 is not None and t2.rank == 5
    t2.close()
    monkeypatch.setenv("PTD_TRACE", "0")
    assert RequestTracer.from_env() is None


def _synthetic_spans():
    """One hand-built trace, times in ms from 0: queue [0,10],
    admission [10,12], prefill [12,30], handoff [28,32] (overlaps the
    prefill tail — the LATER-STARTING span owns the overlap), decode
    [32,90], nothing covers [90,100]. TTFT = 40 ms."""
    def row(span, parent, stage, a_ms, b_ms, **attrs):
        return {"trace": "t1", "span": span, "parent": parent,
                "stage": stage, "rank": 0,
                "t0_us": a_ms * 1e3, "t1_us": b_ms * 1e3, **attrs}

    return [
        row("t1/0", None, "request", 0, 100, request=1, tenant="a",
            ttft_s=0.040, finish_reason="length", retries=1),
        row("r/1", "t1/0", "queue", 0, 10),
        row("r/2", "t1/0", "admission", 10, 12),
        row("0/1", "t1/0", "prefill", 12, 30),
        row("r/3", "t1/0", "handoff", 28, 32),
        row("1/1", "t1/0", "decode", 32, 90, rank=1),
    ]


def test_critical_path_exact_tiling_and_ttft_clip():
    cp = critical_path(_synthetic_spans())
    assert cp["connected"] and cp["spans"] == 6
    assert cp["tenant"] == "a" and cp["retries"] == 1
    want = {"queue": 10, "admission": 2, "prefill": 16, "handoff": 4,
            "decode": 58}
    for st, ms in want.items():
        assert abs(cp[f"{st}_s"] * 1e3 - ms) < 1e-9, st
    assert abs(cp["stall_s"] * 1e3 - 10) < 1e-9
    # the invariant: stage sums + stall TILE the root window exactly
    assert abs(sum(cp[f"{st}_s"] for st in STAGES)
               + cp["stall_s"] - cp["total_s"]) < 1e-12
    # TTFT window [0, 40ms]: decode only owns [32, 40]
    assert abs(cp["ttft_decode_s"] * 1e3 - 8) < 1e-9
    assert abs(cp["ttft_prefill_s"] * 1e3 - 16) < 1e-9
    assert cp["ttft_stall_s"] == 0.0
    # an orphan span breaks connectivity but not the math
    spans = _synthetic_spans()
    spans[1]["parent"] = "someone/else"
    assert critical_path(spans)["connected"] is False
    # no root span -> no path
    assert critical_path(_synthetic_spans()[1:]) is None


def test_slo_debt_attribution_and_tracer_ledger():
    paths = critical_paths(_synthetic_spans())
    assert len(paths) == 1
    # budget above the 40 ms TTFT: no breach, no debt
    clean = slo_debt(paths, slo_ttft_s=0.5)["a"]
    assert clean["breaches"] == 0 and clean["debt_s"] == 0.0
    # budget below it: one breach, debt = ttft - budget, and the
    # breach-window attribution says which stage ate the TTFT
    hot = slo_debt(paths, slo_ttft_s=0.01)["a"]
    assert hot["breaches"] == 1
    assert abs(hot["debt_s"] - 0.030) < 1e-9
    assert abs(hot["ttft_prefill_s"] * 1e3 - 16) < 1e-9
    # the tracer's live ledger (what the autoscaler snapshot reads)
    t = RequestTracer.__new__(RequestTracer)   # no files needed
    t.slo_ttft_s, t.slo_debt = 0.1, {}
    assert t.debt_totals() == {}
    t.note_finish("a", 0.05)
    t.note_finish("a", None)
    t.note_finish("b", 0.4)
    t.note_finish("b", 0.2)
    totals = t.debt_totals()
    assert totals["slo_debt_tenant"] == "b"
    assert abs(totals["slo_debt_s"] - 0.4) < 1e-6
    assert t.slo_debt["a"]["breaches"] == 0
    assert t.slo_debt["b"] == {"requests": 2, "breaches": 2,
                               "debt_s": t.slo_debt["b"]["debt_s"]}


def test_chrome_trace_lanes_and_tid_coercion():
    rows = _synthetic_spans()
    rows[1]["rank"] = "router"     # the router's rank is a string
    ct = chrome_trace(rows)
    json.dumps(ct)                 # must be valid Trace Event JSON
    evs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert len(evs) == 6
    assert all(e["pid"] == 0 for e in evs)       # one lane per trace
    assert {e["tid"] for e in evs} == {-1, 0, 1}  # string rank -> -1
    meta = [e for e in ct["traceEvents"] if e["ph"] == "M"]
    assert meta and "req 1 (a)" in meta[0]["args"]["name"]


def test_kv_payload_wire_carries_origin_and_trace():
    """Satellite 1's wire half: the handoff payload round-trips the
    ORIGIN submit stamp and the TraceContext — and a pre-ISSUE-17 wire
    dict (neither key) still decodes, as None."""
    payload = KVBlockPayload(
        prompt=np.arange(5, dtype=np.int32), generated=[3, 1],
        true_len=5, block_size=8, max_new_tokens=4,
        sampling=SamplingParams(), stop_ids=(),
        leaves=[("k", np.zeros((1, 2), np.float32))],
        origin_t=1234.5, trace={"trace_id": "t", "root": "t/0"})
    d = json.loads(json.dumps(kv_payload_to_wire(payload)))
    back = kv_payload_from_wire(d)
    assert back.origin_t == 1234.5
    assert back.trace == {"trace_id": "t", "root": "t/0"}
    legacy = {k: v for k, v in d.items()
              if k not in ("origin_t", "trace")}
    old = kv_payload_from_wire(legacy)
    assert old.origin_t is None and old.trace is None


def test_trace_cli_and_report_section(tmp_path, capsys):
    from pytorchdistributed_tpu.telemetry.__main__ import main
    from pytorchdistributed_tpu.telemetry.report import render

    # a dir with NO trace files: report stays silent, CLI says so
    empty = tmp_path / "empty"
    empty.mkdir()
    assert "request traces" not in render(empty)
    assert main(["trace", str(empty)]) == 0
    assert "none found" in capsys.readouterr().out
    # two tenants, one slow outlier, written through the real tracer
    t = RequestTracer(tmp_path, rank="router")
    for i, (tenant, total) in enumerate(
            [("hot", 0.9), ("hot", 0.05), ("calm", 0.06)]):
        ctx = t.new_trace()
        t.span(ctx, "request", 0.0, total, root=True, request=i,
               tenant=tenant, ttft_s=total * 0.9,
               finish_reason="length")
        t.span(ctx, "queue", 0.0, total * 0.5, replica=0)
        t.span(ctx, "decode", total * 0.5, total, replica=0)
    t.close()
    out_path = tmp_path / "req.trace.json"
    assert main(["trace", str(tmp_path), "--top", "2",
                 "--slo-ttft-ms", "100",
                 "--chrome", str(out_path)]) == 0
    out = capsys.readouterr().out
    assert "3 requests" in out and "3/3 connected" in out
    assert "hot" in out and "calm" in out
    assert json.load(open(out_path))["traceEvents"]
    # --tenant filters; --stage reranks
    assert main(["trace", str(tmp_path), "--tenant", "calm",
                 "--stage", "queue"]) == 0
    out = capsys.readouterr().out
    assert "1 requests" in out and "slowest by queue" in out
    # the run report grows the same table without breaking its layout
    rep = render(tmp_path)
    assert "request traces" in rep and "SLO debt" in rep
    # fed to the autoscaler's decision snapshot via the same ledger
    assert render_trace(tmp_path).startswith("request traces")


# ----------------------------------------------------------------------
# in-process fleet e2e (shared jit cache with test_disagg geometry)


def test_fleet_trace_connected_across_handoff_and_failover(tmp_path):
    """The acceptance run, in-process: a disagg fleet (1 prefill -> 2
    decode) with replica 1 crashed mid-stream. Every COMPLETED request
    has one connected span chain whose per-stage sums tile its terminal
    latency within 1 ms — handoffs and the failover redispatch
    included — and the handed-off streams' e2e TTFT measures from the
    ORIGIN submit (satellite 1)."""
    inj = FaultInjector(FaultPlan.parse("replica_crash@tick=9,replica=1"))
    router = _router([ROLE_PREFILL, ROLE_DECODE, ROLE_DECODE], tmp_path,
                     faults=inj)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
               for m in (5, 9, 7, 11, 6, 8)]
    reqs = [router.submit(p, max_new_tokens=10, tenant=f"t{i % 2}")
            for i, p in enumerate(prompts)]
    router.run_until_idle()
    s = router.summary()
    router.close()
    assert s["handoffs"] >= 1 and s["failovers"] >= 1
    assert s["redispatched_requests"] >= 1
    rows = read_trace(tmp_path)
    paths = {p["request"]: p for p in critical_paths(rows)}
    done = [r for r in reqs if r.finish_reason in ("length", "stop")]
    assert done and len(paths) == len(reqs)
    for r in done:
        p = paths[r.id]
        assert p["connected"], f"request {r.id} has orphan spans"
        terminal = r.finish_time - r.submit_time
        stage_sum = sum(p[f"{st}_s"] for st in STAGES) + p["stall_s"]
        assert abs(stage_sum - terminal) < 1e-3, \
            f"request {r.id}: {stage_sum} vs {terminal}"
        assert abs(p["total_s"] - terminal) < 1e-3
    # the handoff stage is visible in at least one breakdown, and the
    # failover left a redispatch marker in the raw spans
    assert any(p["handoff_s"] > 0 for p in paths.values())
    assert any(r.get("stage") == "redispatch" for r in rows)
    # satellite 1: a handed-off stream's decode-local TTFT collapses to
    # ~0 at import, but its e2e TTFT (origin router submit -> first
    # token) survives the wire
    serve_rows = []
    for i in range(3):
        f = tmp_path / f"serve_metrics_rank{i}.jsonl"
        if f.exists():
            serve_rows += [json.loads(line) for line in open(f)]
    req_rows = [r for r in serve_rows if r.get("kind") == "request"]
    assert any(r["ttft_e2e_ms"] is not None
               and r["ttft_ms"] is not None
               and r["ttft_e2e_ms"] > r["ttft_ms"] + 0.5
               for r in req_rows), "no row shows e2e > decode-local TTFT"


def test_tracing_off_is_off(tmp_path):
    """Off means OFF: the same deterministic disagg workload run with
    tracing off vs on — the off run writes no trace files, both runs
    trigger ZERO fresh XLA traces, and the router's event rows are
    identical (the wall-clock ``time`` stamp aside)."""
    import glob as _glob

    def run(sub, trace_on):
        d = tmp_path / sub
        router = _router([ROLE_PREFILL, ROLE_DECODE], d, trace=trace_on)
        traces0 = dict(serving_engine.TRACE_COUNTS)
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
                   for m in (5, 9, 12)]
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle()
        recompiles = (sum(serving_engine.TRACE_COUNTS.values())
                      - sum(traces0.values()))
        router.close()
        assert all(r.finish_reason == "length" for r in reqs)
        # request ids are process-global: normalize to submit order so
        # the two runs' event rows compare field-for-field
        id_map = {r.id: i for i, r in enumerate(reqs)}
        events = []
        for line in open(d / "router_metrics_rank0.jsonl"):
            row = json.loads(line)
            if row.get("kind") == "event":
                row.pop("time")
                if "request" in row:
                    row["request"] = id_map.get(row["request"],
                                                row["request"])
                events.append(row)
        return d, recompiles, events

    d_off, rec_off, ev_off = run("off", False)
    d_on, rec_on, ev_on = run("on", True)
    assert rec_off == 0 and rec_on == 0
    assert _glob.glob(str(d_off / TRACE_GLOB)) == []
    assert _glob.glob(str(d_on / TRACE_GLOB)) != []
    assert ev_off == ev_on
    # and the tokens never depend on the tracer either way
    assert len(critical_paths(read_trace(d_on))) == 3


# ----------------------------------------------------------------------
# subprocess wire (full-suite-only: spawns jax-importing workers)


def test_subprocess_trace_connected_over_wire(tmp_path, monkeypatch):
    """The multi-host shape: PTD_TRACE=1 + a telemetry dir makes the
    router AND both subprocess workers write per-rank trace files; the
    TraceContext rides the submit op and the KV handoff payload, so the
    merged trace is connected across real process boundaries."""
    monkeypatch.setenv("PTD_TRACE", "1")
    monkeypatch.setenv("PTD_TELEMETRY_DIR", str(tmp_path))
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1,
            "engine": {"num_slots": 3, "prefill_bucket": 16,
                       "block_size": 8}}
    router = ReplicaRouter(workers=[spec, spec],
                           roles=[ROLE_PREFILL, ROLE_DECODE],
                           warmup_lens=(16, 32), faults=None,
                           telemetry_dir=str(tmp_path))
    try:
        router.warmup()
        rng = np.random.default_rng(31)
        prompts = [rng.integers(0, CFG.vocab_size, (m,)).astype(np.int32)
                   for m in (5, 9, 12)]
        reqs = [router.submit(p, max_new_tokens=6) for p in prompts]
        router.run_until_idle(max_steps=200000)
        s = router.summary()
        assert s["handoffs"] == 3 and s["handoff_failures"] == 0
        for p, r in zip(prompts, reqs):
            assert r.finish_reason == "length"
            np.testing.assert_array_equal(
                np.asarray(r.tokens), _ref(p, 6)[p.size:],
                err_msg=f"request {r.id}")
    finally:
        router.close()
    rows = read_trace(tmp_path)
    ranks = {r["rank"] for r in rows}
    assert "router" in ranks and 0 in ranks and 1 in ranks
    paths = critical_paths(rows)
    assert len(paths) == 3
    for p in paths:
        assert p["connected"], f"request {p['request']}: orphan spans"
        assert p["handoff_s"] > 0
        # engine-side spans from BOTH workers joined the router's trace
        stage_sum = sum(p[f"{st}_s"] for st in STAGES) + p["stall_s"]
        assert abs(stage_sum - p["total_s"]) < 1e-6
