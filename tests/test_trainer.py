"""End-to-end DP slice + the loss-curve equivalence test.

The north star demands "identical loss curves to the NCCL path"
(BASELINE.json); here that becomes: training with the batch sharded over 8
devices produces the same loss curve as the same step run on one device
(SURVEY.md §7 step 3).
"""

import jax
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu.data import DataLoader, SyntheticRegressionDataset
from pytorchdistributed_tpu.models import MLP, LinearRegression
from pytorchdistributed_tpu.parallel import Policy
from pytorchdistributed_tpu.runtime.mesh import MeshConfig, create_mesh, local_mesh
from pytorchdistributed_tpu.training import Trainer, mse_loss


def _make_loader(batch_size=32, **kw):
    ds = SyntheticRegressionDataset(size=256, in_dim=20, out_dim=1)
    return DataLoader(ds, batch_size=batch_size, num_replicas=1, rank=0, **kw)


def _fit_losses(mesh, strategy="dp", epochs=2, precision=None):
    trainer = Trainer(
        LinearRegression(),
        optax.sgd(1e-2),
        mse_loss,
        mesh=mesh,
        strategy=strategy,
        precision=precision,
    )
    loader = _make_loader()
    losses = []
    for epoch in range(epochs):
        loader.set_epoch(epoch)
        for batch in loader:
            losses.append(trainer.train_step(batch)["loss"])
    return np.array([float(l) for l in losses])


def test_reference_training_job_runs():
    """The reference's whole job (ddp_gpus.py: Linear(20,1) + SGD + MSE,
    sharded sampler, epochs) on an 8-device mesh."""
    mesh = create_mesh()
    trainer = Trainer(LinearRegression(), optax.sgd(1e-3), mse_loss, mesh=mesh)
    final = trainer.fit(_make_loader(), max_epochs=2)
    assert np.isfinite(final["loss"])


def test_loss_decreases():
    losses = _fit_losses(create_mesh(), epochs=3)
    assert losses[-1] < losses[0] * 0.9


def test_dp_equivalence_8dev_vs_1dev():
    """Sharded-vs-single-device loss-curve equivalence (north star)."""
    losses_8 = _fit_losses(create_mesh())
    losses_1 = _fit_losses(local_mesh(1))
    np.testing.assert_allclose(losses_8, losses_1, rtol=2e-5, atol=1e-6)


def test_fsdp_matches_dp():
    """ZeRO-3 sharding is a numerics-preserving re-layout."""
    mlp = MLP(features=(64, 64, 1))
    ds = SyntheticRegressionDataset(size=128, in_dim=20, out_dim=1)

    def run(strategy, mesh):
        tr = Trainer(mlp, optax.adam(1e-3), mse_loss, mesh=mesh,
                     strategy=strategy)
        dl = DataLoader(ds, batch_size=32, num_replicas=1, rank=0)
        out = []
        for batch in dl:
            out.append(float(tr.train_step(batch)["loss"]))
        return np.array(out)

    dp = run("dp", create_mesh())
    fsdp = run("fsdp", create_mesh(MeshConfig(data=2, fsdp=4)))
    np.testing.assert_allclose(dp, fsdp, rtol=2e-4, atol=1e-6)


def test_fsdp_actually_shards_params():
    from pytorchdistributed_tpu.runtime.mesh import Axis

    mesh = create_mesh(MeshConfig(data=1, fsdp=8))
    tr = Trainer(MLP(features=(256, 256, 8)), optax.sgd(1e-2), mse_loss,
                 mesh=mesh, strategy="fsdp", )
    ds = SyntheticRegressionDataset(size=64, in_dim=16, out_dim=8)
    batch = ds[np.arange(32)]
    tr.init(batch)
    # Dense_1 (256x256) is above the min-size-to-shard threshold;
    # Dense_0 (16x256) is below it and stays replicated.
    kernel = tr.state.params["params"]["Dense_1"]["kernel"]
    assert Axis.FSDP in jax.tree.leaves(tuple(kernel.sharding.spec))
    small = tr.state.params["params"]["Dense_0"]["kernel"]
    assert small.sharding.spec == ()
    # adam-free sgd: opt state trace mirrors param sharding
    tr.train_step(batch)


def test_bf16_policy_trains():
    losses = _fit_losses(create_mesh(), precision=Policy.bf16(), epochs=1)
    assert np.isfinite(losses).all()


def test_remat_matches_no_remat():
    mesh = create_mesh()
    mlp = MLP(features=(32, 32, 1))
    ds = SyntheticRegressionDataset(size=64, in_dim=8, out_dim=1)
    batch = ds[np.arange(64)]

    def one_step(remat):
        tr = Trainer(mlp, optax.sgd(1e-2), mse_loss, mesh=mesh, remat=remat)
        return float(tr.train_step(batch)["loss"])

    assert one_step(False) == pytest.approx(one_step(True), rel=1e-6)


def test_watchdog_kills_training_on_nan():
    """SURVEY.md §5 wiring: an injected numeric blowup must stop fit() with
    FloatingPointError, not train on garbage for the rest of the job."""
    ds = SyntheticRegressionDataset(size=64, in_dim=20, out_dim=1)
    ds.arrays["x"][7] = np.inf  # poison one sample
    loader = DataLoader(ds, batch_size=32, num_replicas=1, rank=0)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh(), log_every=1)
    with pytest.raises(FloatingPointError, match="loss"):
        tr.fit(loader, max_epochs=1)


def test_watchdog_off_by_flag():
    ds = SyntheticRegressionDataset(size=64, in_dim=20, out_dim=1)
    ds.arrays["x"][7] = np.inf
    loader = DataLoader(ds, batch_size=32, num_replicas=1, rank=0)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh(), log_every=1, watchdog=False)
    metrics = tr.fit(loader, max_epochs=1)  # runs to completion (on garbage)
    assert not np.isfinite(metrics["loss"])


def test_throughput_meter_feeds_logging():
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh(), log_every=2)
    tr.fit(_make_loader(), max_epochs=2)
    assert np.isfinite(tr.throughput) and tr.throughput > 0


def test_batch_adapter_multi_input_model():
    """A model with two positional inputs (values + mask) trains through an
    explicit batch_adapter — the contract key-probing could never express."""
    import flax.linen as nn
    import jax.numpy as jnp

    class MaskedRegressor(nn.Module):
        @nn.compact
        def __call__(self, x, mask):
            return nn.Dense(1)(x * mask[..., None])

    def masked_mse(model, params, batch, rng=None):
        pred = model.apply(params, batch["x"], batch["mask"])
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, {"loss": loss}

    rng = np.random.default_rng(5)
    batch = {
        "x": rng.random((32, 8)).astype(np.float32),
        "mask": np.ones((32,), np.float32),
        "y": rng.random((32, 1)).astype(np.float32),
    }
    tr = Trainer(MaskedRegressor(), optax.sgd(1e-2), masked_mse,
                 mesh=create_mesh(),
                 batch_adapter=lambda b: (b["x"], b["mask"]))
    assert np.isfinite(float(tr.train_step(batch)["loss"]))


def test_unknown_batch_keys_error_mentions_adapter():
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh())
    with pytest.raises(ValueError, match="batch_adapter"):
        tr.train_step({"weird": np.zeros((8, 20), np.float32)})


def test_profile_flag_writes_trace(tmp_path):
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh(), profile_dir=str(tmp_path))
    tr.fit(_make_loader(), max_epochs=1)
    traces = list(tmp_path.rglob("*"))
    assert any(p.is_file() for p in traces), "no trace files captured"


def test_accum_steps_matches_large_batch():
    """Gradient accumulation is a memory layout, not a different optimizer:
    accum_steps=4 on a batch of 32 must reproduce the accum_steps=1 loss
    curve exactly (fp32 grad averaging == the mean-loss gradient)."""
    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.training import token_cross_entropy_loss

    rng = np.random.default_rng(6)
    batch = {
        "tokens": rng.integers(0, 128, (32, 16)).astype(np.int32),
        "targets": rng.integers(0, 128, (32, 16)).astype(np.int32),
    }
    losses = {}
    for accum in (1, 4):
        model = GPT2(gpt2_config("test", dtype=np.float32))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(), strategy="dp", accum_steps=accum)
        losses[accum] = [float(tr.train_step(batch)["loss"])
                         for _ in range(3)]
    np.testing.assert_allclose(losses[1], losses[4], rtol=1e-5, atol=1e-6)


def test_accum_exact_for_masked_loss():
    """Masked-loss accumulation is EXACT (VERDICT r3 #5): each micro-batch's
    grads are weighted by its token count ("_mask_count") and normalized
    once, so accum_steps=4 with RAGGED loss masks reproduces the
    accum_steps=1 full-batch masked mean — the regime where the old
    equal-weight averaging was only approximate."""
    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.training import token_cross_entropy_loss

    rng = np.random.default_rng(7)
    # per-sample keep probabilities ramp 5%→95%, so the four micro-batch
    # slices carry very different mask counts
    mask = rng.random((32, 16)) < np.linspace(0.05, 0.95, 32)[:, None]
    batch = {
        "tokens": rng.integers(0, 128, (32, 16)).astype(np.int32),
        "targets": rng.integers(0, 128, (32, 16)).astype(np.int32),
        "loss_mask": mask,
    }
    losses = {}
    for accum in (1, 4):
        model = GPT2(gpt2_config("test", dtype=np.float32))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(), strategy="dp", accum_steps=accum)
        losses[accum] = [float(tr.train_step(batch)["loss"])
                         for _ in range(3)]
    np.testing.assert_allclose(losses[1], losses[4], rtol=1e-5, atol=1e-6)


def test_multi_replica_eval_ignores_padding():
    """2-replica eval over a ragged val set equals the single-replica mean
    exactly (VERDICT r3 #6): evaluate() zero-weights the wrap-around pad
    duplicates via ShardedSampler.valid_mask, so combining the per-rank
    means by REAL sample counts reproduces the global mean."""
    ds = SyntheticRegressionDataset(size=37, seed=8)
    mesh = local_mesh(1)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=mesh, log_every=10**9)
    single = DataLoader(ds, batch_size=8, num_replicas=1, rank=0,
                        shuffle=False, drop_last=False)
    tr.init(next(iter(single)))
    want = tr.evaluate(single)["loss"]
    parts = []
    for rank in (0, 1):
        loader = DataLoader(ds, batch_size=8, num_replicas=2, rank=rank,
                            shuffle=False, drop_last=False)
        got = tr.evaluate(loader)["loss"]
        nreal = int(loader.sampler.valid_mask().sum())
        parts.append((got, nreal))
    # 37 over 2 replicas: 19 each, one wrap-around pad on the last rank
    assert [n for _, n in parts] == [19, 18]
    combined = (sum(v * n for v, n in parts)
                / sum(n for _, n in parts))
    np.testing.assert_allclose(combined, want, rtol=1e-6)


def test_evaluate_warns_when_custom_loss_ignores_sample_weight():
    """The sample_weight contract guard (VERDICT r4 weak #5): a custom
    loss that ignores the injected pad weights silently reintroduces the
    duplicate-counting skew — evaluate() must detect it (all-ones probe
    on the first pad-carrying batch answers identically) and warn. The
    built-in weight-folding loss on the same padded loader must NOT
    warn."""
    import warnings

    def ignores_weights(model, params, batch, rng=None):
        pred = model.apply(params, batch["x"])
        loss = ((pred - batch["y"]) ** 2).mean()  # no sample_weight fold
        return loss, {"loss": loss}

    ds = SyntheticRegressionDataset(size=37, seed=8)
    # 37 over 2 replicas pads rank 1 with one wrap-around duplicate
    loader = DataLoader(ds, batch_size=8, num_replicas=2, rank=1,
                        shuffle=False, drop_last=False)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), ignores_weights,
                 mesh=local_mesh(1), log_every=10**9)
    tr.init(next(iter(loader)))
    with pytest.warns(UserWarning, match="sample_weight"):
        tr.evaluate(loader)

    tr2 = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                  mesh=local_mesh(1), log_every=10**9)
    tr2.init(next(iter(loader)))
    with warnings.catch_warnings():
        # escalate only the guard's own warning — unrelated library
        # warnings during the eval compile must not fail this test
        warnings.filterwarnings("error", message=".*sample_weight.*")
        tr2.evaluate(loader)


def test_evaluate_asserts_loader_sampler_alignment():
    """ADVICE r4 #2: the padded-weight path maps valid_mask() onto batches
    positionally, so a loader that yields a different sample count than
    its sampler advertises must fail loudly, not mis-weight silently."""

    class MiscountingLoader:
        """Duck-typed loader: claims a padded sampler but re-batches the
        data its own way (drops the final ragged batch)."""

        def __init__(self, loader):
            self._loader = loader
            self.sampler = loader.sampler
            self.batch_size = loader.batch_size

        def set_epoch(self, epoch):
            self._loader.set_epoch(epoch)

        def __len__(self):
            return len(self._loader) - 1

        def __iter__(self):
            for i, b in enumerate(self._loader):
                if i < len(self):
                    yield b

    ds = SyntheticRegressionDataset(size=37, seed=8)
    loader = DataLoader(ds, batch_size=8, num_replicas=2, rank=1,
                        shuffle=False, drop_last=False)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=local_mesh(1), log_every=10**9)
    tr.init(next(iter(loader)))
    with pytest.raises(ValueError, match="samples"):
        tr.evaluate(MiscountingLoader(loader))


def test_evaluate_pad_weights_ignore_claimed_batch_size():
    """The padded-weight path slices valid_mask() by a RUNNING offset of
    actually-yielded samples, not batch_index * loader.batch_size — a
    loader whose batch_size attribute misstates its real batch width must
    still get correctly-aligned weights (code review r5: the b*bs slicing
    would have overlapped slices silently)."""

    class LyingBatchSize:
        def __init__(self, loader):
            self._loader = loader
            self.sampler = loader.sampler
            self.batch_size = 4          # actual batches are 8 wide

        def set_epoch(self, epoch):
            self._loader.set_epoch(epoch)

        def __len__(self):
            return len(self._loader)

        def __iter__(self):
            return iter(self._loader)

    ds = SyntheticRegressionDataset(size=37, seed=8)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=local_mesh(1), log_every=10**9)
    single = DataLoader(ds, batch_size=8, num_replicas=1, rank=0,
                        shuffle=False, drop_last=False)
    tr.init(next(iter(single)))
    want = tr.evaluate(single)["loss"]
    parts = []
    for rank in (0, 1):
        loader = DataLoader(ds, batch_size=8, num_replicas=2, rank=rank,
                            shuffle=False, drop_last=False)
        got = tr.evaluate(LyingBatchSize(loader))["loss"]
        parts.append((got, int(loader.sampler.valid_mask().sum())))
    combined = (sum(v * n for v, n in parts) / sum(n for _, n in parts))
    np.testing.assert_allclose(combined, want, rtol=1e-6)


def test_masked_eval_independent_of_batch_grouping():
    """For masked-token losses, evaluate() weights each batch mean by its
    token count ("_mask_count"), so the result is the global masked-token
    mean — identical across batch sizes, which sample-count weighting of
    ragged masks cannot deliver."""
    from pytorchdistributed_tpu.data import MLMDataset, SyntheticTokenDataset
    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.training import token_cross_entropy_loss

    ds = MLMDataset(SyntheticTokenDataset(size=24, seq_len=16, vocab_size=128,
                                          seed=9), vocab_size=128, seed=9)
    tr = Trainer(GPT2(gpt2_config("test", dtype=np.float32)),
                 optax.sgd(1e-2), token_cross_entropy_loss,
                 mesh=local_mesh(1), log_every=10**9)
    results = []
    for bs in (24, 8, 4):
        loader = DataLoader(ds, batch_size=bs, num_replicas=1, rank=0,
                            shuffle=False, drop_last=False)
        if not results:
            tr.init(next(iter(loader)))
        results.append(tr.evaluate(loader)["loss"])
    np.testing.assert_allclose(results[1:], results[0], rtol=1e-6)


def test_accum_rejects_1f1b():
    """accum_steps must not be silently ignored on the fused-1F1B path."""
    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.training import token_cross_entropy_loss

    model = GPT2(gpt2_config("test", num_layers=4, pipeline_stages=4,
                             pipeline_microbatches=4, pp_schedule="1f1b"))
    tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                 mesh=create_mesh(data=2, pipe=4), accum_steps=2)
    batch = {"tokens": np.zeros((16, 32), np.int32),
             "targets": np.zeros((16, 32), np.int32)}
    with pytest.raises(ValueError, match="pipeline_microbatches"):
        tr.train_step(batch)


def test_accum_steps_validations():
    from pytorchdistributed_tpu.training import mse_loss as _mse

    with pytest.raises(ValueError, match="accum_steps"):
        Trainer(LinearRegression(), optax.sgd(1e-2), _mse,
                mesh=create_mesh(), accum_steps=0)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), _mse,
                 mesh=create_mesh(), accum_steps=3)
    batch = {"x": np.zeros((8, 20), np.float32),
             "y": np.zeros((8, 1), np.float32)}
    with pytest.raises(ValueError, match="divisible"):
        tr.train_step(batch)


def test_compiler_options_merge_over_backend_defaults(monkeypatch):
    """User compiler_options MERGE OVER the backend defaults — a caller
    tuning an unrelated XLA flag must not silently drop the scoped-VMEM
    fix (the r5 longcontext compile abort); overriding a default takes
    setting its key explicitly."""
    import pytorchdistributed_tpu.training.trainer as trainer_mod

    monkeypatch.setattr(trainer_mod, "_default_compiler_options",
                        lambda: {"xla_tpu_scoped_vmem_limit_kib": "24576"})
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh(),
                 compiler_options={"xla_some_other_flag": "1"})
    assert tr._compiler_options == {
        "xla_tpu_scoped_vmem_limit_kib": "24576",
        "xla_some_other_flag": "1",
    }
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh(),
                 compiler_options={"xla_tpu_scoped_vmem_limit_kib": "16384"})
    assert tr._compiler_options == {
        "xla_tpu_scoped_vmem_limit_kib": "16384"}
    # no backend defaults (CPU) and no user options -> None, not {}
    monkeypatch.setattr(trainer_mod, "_default_compiler_options",
                        lambda: None)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh())
    assert tr._compiler_options is None


def test_evaluate_matches_train_loss():
    """eval_step computes the same loss the next train_step reports (before
    its update), and evaluate() sample-weights ragged final batches."""
    ds = SyntheticRegressionDataset(size=40, seed=3)
    loader = DataLoader(ds, batch_size=16, num_replicas=1, rank=0)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh(), log_every=10**9)
    batch = next(iter(loader))
    tr.init(batch)
    ev = float(tr.eval_step(batch)["loss"])
    trn = float(tr.train_step(batch)["loss"])
    np.testing.assert_allclose(ev, trn, rtol=1e-6)
    out = tr.evaluate(loader)
    assert set(out) == {"loss"} and np.isfinite(out["loss"])
    # hand-computed sample-weighted mean over the same batches
    want, n = 0.0, 0
    loader.set_epoch(0)
    for b in loader:
        want += float(tr.eval_step(b)["loss"]) * b["x"].shape[0]
        n += b["x"].shape[0]
    np.testing.assert_allclose(out["loss"], want / n, rtol=1e-6)


def test_fit_with_val_loader_reports_val_metrics():
    ds = SyntheticRegressionDataset(size=64, seed=4)
    val = DataLoader(SyntheticRegressionDataset(size=32, seed=5),
                     batch_size=16, num_replicas=1, rank=0)
    loader = DataLoader(ds, batch_size=16, num_replicas=1, rank=0)
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=create_mesh(), log_every=10**9)
    out = tr.fit(loader, max_epochs=2, val_loader=val)
    assert "val_loss" in out and np.isfinite(out["val_loss"])


def test_accum_composes_with_fsdp():
    """Accumulated grads inherit the ZeRO-3 sharding (the fp32 accumulator
    is zeros_like the sharded params) — loss matches plain fsdp."""
    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.training import token_cross_entropy_loss

    rng = np.random.default_rng(10)
    batch = {
        "tokens": rng.integers(0, 128, (32, 16)).astype(np.int32),
        "targets": rng.integers(0, 128, (32, 16)).astype(np.int32),
    }
    losses = {}
    for accum in (1, 4):
        model = GPT2(gpt2_config("test", dtype=np.float32))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(data=2, fsdp=4), strategy="fsdp",
                     accum_steps=accum)
        losses[accum] = [float(tr.train_step(batch)["loss"])
                         for _ in range(3)]
    np.testing.assert_allclose(losses[1], losses[4], rtol=2e-4, atol=1e-6)


def test_trainer_beats_heartbeat_at_device_sync(tmp_path, monkeypatch):
    """The launcher-side watchdog is only as good as the Trainer's beats:
    with PTD_HEARTBEAT_DIR exported (run.py --heartbeat-timeout), run_epoch
    must stamp this rank's liveness file at its device-sync points."""
    import time as _time

    from pytorchdistributed_tpu.runtime.heartbeat import HEARTBEAT_DIR_ENV

    monkeypatch.setenv(HEARTBEAT_DIR_ENV, str(tmp_path))
    monkeypatch.setenv("RANK", "0")
    tr = Trainer(LinearRegression(), optax.sgd(1e-2), mse_loss,
                 mesh=local_mesh(1), log_every=2, watchdog=False)
    rank_file = tmp_path / "rank0"
    assert not rank_file.exists()  # no beat before real progress (grace
    #                                covers imports + first compile)
    tr.run_epoch(_make_loader(batch_size=16), epoch=0)
    assert rank_file.exists()
    first = rank_file.stat().st_mtime
    _time.sleep(0.05)
    tr.run_epoch(_make_loader(batch_size=16), epoch=1)
    assert rank_file.stat().st_mtime > first  # keeps beating epoch over epoch
