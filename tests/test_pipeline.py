"""GPipe pipeline-parallelism tests (SURVEY.md §7 hard part (a)).

The correctness bar mirrors the reference's lesson: a pipelined model must
compute exactly what the unpipelined one computes (the reference's
PipelineParallelResNet50 returns the same logits as ModelParallelResNet50,
03_model_parallel.ipynb:538-560) — here enforced as loss-curve equality
against the sequential-scan stack.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.parallel.pipeline import gpipe_spmd
from pytorchdistributed_tpu.runtime.mesh import create_mesh
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss


def test_gpipe_spmd_matches_sequential():
    """Functional core: pipelined stage chain == sequential chain."""
    rng = np.random.default_rng(0)
    p, b, d = 4, 16, 32
    params = jnp.asarray(rng.standard_normal((p, d, d)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

    def stage_apply(w, h):
        return jnp.tanh(h @ w[0])

    mesh = create_mesh(data=2, pipe=4)
    with jax.set_mesh(mesh):
        out = gpipe_spmd(
            stage_apply, params.reshape(p, 1, d, d), x, num_microbatches=4)

    ref = x
    for i in range(p):
        ref = jnp.tanh(ref @ params[i])
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_gpipe_gradients_match():
    rng = np.random.default_rng(1)
    p, b, d = 2, 8, 16
    params = jnp.asarray(rng.standard_normal((p, 1, d, d)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

    def stage_apply(w, h):
        return jnp.tanh(h @ w[0])

    def seq_loss(params):
        h = x
        for i in range(p):
            h = jnp.tanh(h @ params[i, 0])
        return (h**2).sum()

    mesh = create_mesh(data=2, pipe=2, tensor=2)
    with jax.set_mesh(mesh):
        def pp_loss(params):
            return (gpipe_spmd(stage_apply, params, x,
                               num_microbatches=4)**2).sum()
        g_pp = jax.grad(pp_loss)(params)
    g_seq = jax.grad(seq_loss)(params)
    np.testing.assert_allclose(g_pp, g_seq, atol=1e-4)


_BATCH_RNG = np.random.default_rng(7)
_BATCH = {
    "tokens": _BATCH_RNG.integers(0, 128, (16, 32)).astype(np.int32),
    "targets": _BATCH_RNG.integers(0, 128, (16, 32)).astype(np.int32),
}


def _run_losses(cfg_kw, axes, strategy="dp", steps=3):
    model = GPT2(gpt2_config("test", num_layers=4, dtype=jnp.float32,
                             **cfg_kw))
    tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                 mesh=create_mesh(**axes), strategy=strategy)
    return [float(tr.train_step(_BATCH)["loss"]) for _ in range(steps)]


@pytest.fixture(scope="module")
def sequential_losses():
    return _run_losses(dict(), dict())


@pytest.mark.parametrize("pp_kw,axes,strategy", [
    (dict(pipeline_stages=4, pipeline_microbatches=4),
     dict(data=2, pipe=4), "dp"),
    (dict(pipeline_stages=2, pipeline_microbatches=8),
     dict(data=2, pipe=2, tensor=2), "tp"),
    (dict(pipeline_stages=2, pipeline_microbatches=2, remat=True),
     dict(data=4, pipe=2), "dp"),
])
def test_gpt2_pipeline_loss_equivalence(sequential_losses, pp_kw, axes,
                                        strategy):
    got = _run_losses(pp_kw, axes, strategy)
    np.testing.assert_allclose(got, sequential_losses, atol=2e-5)


def test_pipeline_validations():
    # micro-batch count must divide the global batch (16)
    with pytest.raises(ValueError, match="divisible"):
        _run_losses(dict(pipeline_stages=2, pipeline_microbatches=3),
                    dict(data=4, pipe=2), steps=1)
    # stage count must match the mesh's pipe axis
    with pytest.raises(ValueError, match="pipe axis"):
        _run_losses(dict(pipeline_stages=2, pipeline_microbatches=2),
                    dict(data=2, pipe=4), steps=1)
