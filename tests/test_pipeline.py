"""GPipe pipeline-parallelism tests (SURVEY.md §7 hard part (a)).

The correctness bar mirrors the reference's lesson: a pipelined model must
compute exactly what the unpipelined one computes (the reference's
PipelineParallelResNet50 returns the same logits as ModelParallelResNet50,
03_model_parallel.ipynb:538-560) — here enforced as loss-curve equality
against the sequential-scan stack.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu._jax_compat import (
    supports_partial_auto_shard_map,
)
from pytorchdistributed_tpu.models import GPT2, gpt2_config

# Both schedules run shard_map with axis_names={"pipe"} (other axes stay
# auto); jax versions whose shard_map was backfilled from the experimental
# module (0.4.x) cannot lower that shape — the SPMD partitioner rejects
# the manual-region PartitionId and CHECK-aborts on the stage ppermute —
# so the whole module skips there (environment limitation, not a bug).
pytestmark = pytest.mark.skipif(
    not supports_partial_auto_shard_map(),
    reason="pipeline schedules need partial-auto shard_map "
           "(axis_names ⊂ mesh axes), unsupported by this jax")
from pytorchdistributed_tpu.parallel.pipeline import gpipe_spmd, one_f_one_b
from pytorchdistributed_tpu.runtime.mesh import create_mesh
from pytorchdistributed_tpu.training import Trainer, token_cross_entropy_loss


def test_gpipe_spmd_matches_sequential():
    """Functional core: pipelined stage chain == sequential chain."""
    rng = np.random.default_rng(0)
    p, b, d = 4, 16, 32
    params = jnp.asarray(rng.standard_normal((p, d, d)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

    def stage_apply(w, h):
        return jnp.tanh(h @ w[0])

    mesh = create_mesh(data=2, pipe=4)
    with jax.set_mesh(mesh):
        out = gpipe_spmd(
            stage_apply, params.reshape(p, 1, d, d), x, num_microbatches=4)

    ref = x
    for i in range(p):
        ref = jnp.tanh(ref @ params[i])
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_gpipe_gradients_match():
    rng = np.random.default_rng(1)
    p, b, d = 2, 8, 16
    params = jnp.asarray(rng.standard_normal((p, 1, d, d)) * 0.1, jnp.float32)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)

    def stage_apply(w, h):
        return jnp.tanh(h @ w[0])

    def seq_loss(params):
        h = x
        for i in range(p):
            h = jnp.tanh(h @ params[i, 0])
        return (h**2).sum()

    mesh = create_mesh(data=2, pipe=2, tensor=2)
    with jax.set_mesh(mesh):
        def pp_loss(params):
            return (gpipe_spmd(stage_apply, params, x,
                               num_microbatches=4)**2).sum()
        g_pp = jax.grad(pp_loss)(params)
    g_seq = jax.grad(seq_loss)(params)
    np.testing.assert_allclose(g_pp, g_seq, atol=1e-4)


_BATCH_RNG = np.random.default_rng(7)
_BATCH = {
    "tokens": _BATCH_RNG.integers(0, 128, (16, 32)).astype(np.int32),
    "targets": _BATCH_RNG.integers(0, 128, (16, 32)).astype(np.int32),
}


def _run_losses(cfg_kw, axes, strategy="dp", steps=3):
    model = GPT2(gpt2_config("test", num_layers=4, dtype=jnp.float32,
                             **cfg_kw))
    tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                 mesh=create_mesh(**axes), strategy=strategy)
    return [float(tr.train_step(_BATCH)["loss"]) for _ in range(steps)]


@pytest.fixture(scope="module")
def sequential_losses():
    return _run_losses(dict(), dict())


@pytest.mark.parametrize("pp_kw,axes,strategy", [
    (dict(pipeline_stages=4, pipeline_microbatches=4),
     dict(data=2, pipe=4), "dp"),
    (dict(pipeline_stages=2, pipeline_microbatches=8),
     dict(data=2, pipe=2, tensor=2), "tp"),
    (dict(pipeline_stages=2, pipeline_microbatches=2, remat=True),
     dict(data=4, pipe=2), "dp"),
    # 1F1B fused-step schedule: same bar — loss curve == sequential — and
    # same strategy composition (pure PP, PP×TP, PP×FSDP).
    (dict(pipeline_stages=4, pipeline_microbatches=4, pp_schedule="1f1b"),
     dict(data=2, pipe=4), "dp"),
    (dict(pipeline_stages=2, pipeline_microbatches=8, pp_schedule="1f1b"),
     dict(data=2, pipe=2, tensor=2), "tp"),
    (dict(pipeline_stages=2, pipeline_microbatches=4, pp_schedule="1f1b"),
     dict(data=2, fsdp=2, pipe=2), "fsdp"),
])
def test_gpt2_pipeline_loss_equivalence(sequential_losses, pp_kw, axes,
                                        strategy):
    got = _run_losses(pp_kw, axes, strategy)
    np.testing.assert_allclose(got, sequential_losses, atol=2e-5)


def test_bert_1f1b_masked_loss_equivalence():
    """BERT MLM under 1F1B: the globally-normalized mask weights must make
    micro-batch losses compose to exactly the full-batch masked mean, no
    matter how unevenly masked tokens fall across micro-batches."""
    from pytorchdistributed_tpu.models import BertMLM, bert_config

    rng = np.random.default_rng(9)
    batch = {
        "tokens": rng.integers(0, 128, (16, 32)).astype(np.int32),
        "targets": rng.integers(0, 128, (16, 32)).astype(np.int32),
        # lopsided mask: rows 0-3 heavily masked, rows 12-15 barely
        "loss_mask": (rng.random((16, 32)) <
                      np.linspace(0.9, 0.05, 16)[:, None]).astype(np.int32),
    }

    def run(cfg_kw, axes, steps=3):
        model = BertMLM(bert_config("test", num_layers=4, dtype=jnp.float32,
                                    **cfg_kw))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(**axes), strategy="dp")
        return [float(tr.train_step(batch)["loss"]) for _ in range(steps)]

    seq = run(dict(), dict())
    f1b = run(dict(pipeline_stages=4, pipeline_microbatches=4,
                   pp_schedule="1f1b"), dict(data=2, pipe=4))
    np.testing.assert_allclose(f1b, seq, atol=2e-5)


def test_one_f_one_b_matches_sequential_grads():
    """Core 1F1B primitive: loss, stage grads, head grads and the input
    cotangent all equal sequential AD (the PipeDream-flush schedule is a
    reordering, not an approximation)."""
    rng = np.random.default_rng(3)
    p, b, d, m = 4, 16, 8, 8
    sp = jnp.asarray(rng.standard_normal((p, d, d)) * 0.3, jnp.float32)
    hw = jnp.asarray(rng.standard_normal((d, 3)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((b, 3)), jnp.float32)

    def stage_apply(w, h):
        return jnp.tanh(h @ w)

    def head_loss(w, h, tt):
        return jnp.mean((h @ w - tt) ** 2)

    mesh = create_mesh(data=2, pipe=4)
    with jax.set_mesh(mesh):
        loss, sg, hg, dx = one_f_one_b(
            stage_apply, sp, head_loss, hw, x, t, num_microbatches=m)

    def ref(sp, hw, xx):
        h = xx
        for i in range(p):
            h = jnp.tanh(h @ sp[i])
        return jnp.mean((h @ hw - t) ** 2)

    rl, (rsg, rhg, rdx) = jax.value_and_grad(ref, argnums=(0, 1, 2))(sp, hw, x)
    np.testing.assert_allclose(float(loss), float(rl), atol=1e-6)
    np.testing.assert_allclose(sg, rsg, atol=1e-5)
    np.testing.assert_allclose(hg, rhg, atol=1e-5)
    np.testing.assert_allclose(dx, rdx, atol=1e-5)


def test_1f1b_bounds_activation_memory():
    """The schedule's point (reference 03_model_parallel.ipynb:668-697):
    in-flight residuals bounded by stage count, not micro-batch count. At
    M=16 >> P=4 the compiled 1F1B step must use measurably less scratch than
    the GPipe step (whose AD keeps one residual set per micro-batch)."""
    rng = np.random.default_rng(11)
    batch = {
        "tokens": rng.integers(0, 128, (32, 64)).astype(np.int32),
        "targets": rng.integers(0, 128, (32, 64)).astype(np.int32),
    }

    def temp_bytes(schedule):
        model = GPT2(gpt2_config(
            "test", num_layers=4, dtype=jnp.float32, pipeline_stages=4,
            pipeline_microbatches=16, pp_schedule=schedule, remat=True,
            remat_policy="full"))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(data=2, pipe=4), strategy="dp")
        tr.init(batch)
        from pytorchdistributed_tpu.data.loader import shard_batch
        with jax.set_mesh(tr.mesh):
            sharded = shard_batch(batch, tr.batch_sharding)
            compiled = tr._step_fn.lower(tr.state, sharded).compile()
        ma = compiled.memory_analysis()
        return getattr(ma, "temp_size_in_bytes", None)

    gpipe, f1b = temp_bytes("gpipe"), temp_bytes("1f1b")
    if gpipe is None or f1b is None:
        pytest.skip("memory_analysis unavailable on this backend")
    assert f1b < 0.8 * gpipe, (
        f"1F1B scratch {f1b} not materially below GPipe's {gpipe}")


def test_1f1b_validations():
    # the fused schedule needs the scanned (stage-stacked) parameter layout
    model = GPT2(gpt2_config("test", num_layers=4, scan_layers=False,
                             pipeline_stages=2, pp_schedule="1f1b"))
    with pytest.raises(ValueError, match="scan_layers"):
        model.pipeline_parts()
    # models without a pipeline decomposition reject the 1f1b step builder
    import dataclasses

    from pytorchdistributed_tpu.models.resnet import ResNet, ResNetConfig
    from pytorchdistributed_tpu.training import cross_entropy_loss

    @dataclasses.dataclass(frozen=True)
    class _PipeResNetConfig(ResNetConfig):
        # pipeline knobs so the Trainer picks the 1f1b builder; ResNet
        # itself has no pipeline_parts() decomposition
        pipeline_stages: int = 2
        pp_schedule: str = "1f1b"
        dropout_rate: float = 0.0

    resnet = ResNet(_PipeResNetConfig(num_classes=10, cifar_stem=True,
                                      stage_sizes=(1, 1), bottleneck=False))
    tr = Trainer(resnet, optax.sgd(1e-2), cross_entropy_loss,
                 mesh=create_mesh(data=4, pipe=2), strategy="dp")
    batch = {"image": np.zeros((8, 32, 32, 3), np.float32),
             "label": np.zeros((8,), np.int32)}
    with pytest.raises(ValueError, match="pipeline_parts"):
        tr.train_step(batch)


def test_vit_1f1b_loss_equivalence():
    """ViT rides the fused 1F1B schedule too (PatchEmbed pre-stage, CLS
    classifier head): pipelined loss curve == sequential."""
    from pytorchdistributed_tpu.models import ViT, vit_config
    from pytorchdistributed_tpu.training import cross_entropy_loss

    rng = np.random.default_rng(12)
    batch = {"image": rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
             "label": rng.integers(0, 10, (16,)).astype(np.int32)}

    def run(cfg_kw, axes):
        model = ViT(vit_config("test", image_size=32, patch_size=8,
                               num_classes=10, num_layers=4,
                               dtype=jnp.float32, **cfg_kw))
        tr = Trainer(model, optax.sgd(1e-2), cross_entropy_loss,
                     mesh=create_mesh(**axes), strategy="dp")
        return [float(tr.train_step(batch)["loss"]) for _ in range(3)]

    seq = run(dict(), dict())
    f1b = run(dict(pipeline_stages=4, pipeline_microbatches=4,
                   pp_schedule="1f1b"), dict(data=2, pipe=4))
    np.testing.assert_allclose(f1b, seq, atol=2e-5)


def test_pipeline_validations():
    # micro-batch count must divide the global batch (16)
    with pytest.raises(ValueError, match="divisible"):
        _run_losses(dict(pipeline_stages=2, pipeline_microbatches=3),
                    dict(data=4, pipe=2), steps=1)
    # stage count must match the mesh's pipe axis
    with pytest.raises(ValueError, match="pipe axis"):
        _run_losses(dict(pipeline_stages=2, pipeline_microbatches=2),
                    dict(data=2, pipe=4), steps=1)


def test_gpipe_dropout_key_routing():
    """Dropout keys must route to the right (stage, micro-batch) pair: the
    pipelined output with a stochastic stage equals a handwritten
    sequential loop using stage_microbatch_key — exact, not statistical."""
    from pytorchdistributed_tpu.parallel.pipeline import stage_microbatch_key

    rng = np.random.default_rng(5)
    p, b, d, m = 2, 8, 16, 4
    params = jnp.asarray(rng.standard_normal((p, d, d)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    base = jax.random.key(42)

    def stage_apply(w, h, key):
        h = jnp.tanh(h @ w)
        keep = jax.random.bernoulli(key, 0.5, h.shape)
        return jnp.where(keep, h / 0.5, 0.0)

    mesh = create_mesh(data=4, pipe=2)
    with jax.set_mesh(mesh):
        out = gpipe_spmd(stage_apply, params, x, num_microbatches=m,
                         remat=False, dropout_rng=base)

    mb = b // m
    chunks = []
    for k in range(m):
        h = x[k * mb:(k + 1) * mb]
        for s in range(p):
            h = stage_apply(params[s], h, stage_microbatch_key(base, s, k))
        chunks.append(h)
    np.testing.assert_allclose(out, jnp.concatenate(chunks), atol=1e-5)


def test_one_f_one_b_dropout_matches_sequential_grads():
    """1F1B with dropout: loss AND grads equal sequential AD with the same
    per-(stage, micro-batch) keys — which also proves the backward slot's
    recompute re-derives the forward's exact dropout masks (mismatched
    masks would corrupt every gradient)."""
    from pytorchdistributed_tpu.parallel.pipeline import stage_microbatch_key

    rng = np.random.default_rng(6)
    p, b, d, m = 2, 8, 8, 4
    sp = jnp.asarray(rng.standard_normal((p, d, d)) * 0.3, jnp.float32)
    hw = jnp.asarray(rng.standard_normal((d, 3)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((b, d)), jnp.float32)
    t = jnp.asarray(rng.standard_normal((b, 3)), jnp.float32)
    base = jax.random.key(13)

    def stage_apply(w, h, key):
        h = jnp.tanh(h @ w)
        keep = jax.random.bernoulli(key, 0.8, h.shape)
        return jnp.where(keep, h / 0.8, 0.0)

    def head_loss(w, h, tt):
        return jnp.mean((h @ w - tt) ** 2)

    mesh = create_mesh(data=4, pipe=2)
    with jax.set_mesh(mesh):
        loss, sg, hg, dx = one_f_one_b(
            stage_apply, sp, head_loss, hw, x, t, num_microbatches=m,
            dropout_rng=base)

    mb = b // m

    def ref(sp, hw, xx):
        tot = 0.0
        for k in range(m):
            h = xx[k * mb:(k + 1) * mb]
            for s in range(p):
                h = stage_apply(sp[s], h, stage_microbatch_key(base, s, k))
            tot = tot + head_loss(hw, h, t[k * mb:(k + 1) * mb])
        return tot / m

    rl, (rsg, rhg, rdx) = jax.value_and_grad(ref, argnums=(0, 1, 2))(sp, hw, x)
    np.testing.assert_allclose(float(loss), float(rl), atol=1e-6)
    np.testing.assert_allclose(sg, rsg, atol=1e-5)
    np.testing.assert_allclose(hg, rhg, atol=1e-5)
    np.testing.assert_allclose(dx, rdx, atol=1e-5)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_gpt2_pipelined_dropout_trains(schedule):
    """Dropout now rides both pipeline schedules (VERDICT r2 next #3): the
    stochastic run is finite and differs from the deterministic one (units
    actually drop), and training still converges stepwise."""
    def run(rate):
        model = GPT2(gpt2_config(
            "test", num_layers=4, dropout_rate=rate, dtype=jnp.float32,
            pipeline_stages=2, pipeline_microbatches=2,
            pp_schedule=schedule))
        tr = Trainer(model, optax.sgd(1e-2), token_cross_entropy_loss,
                     mesh=create_mesh(data=4, pipe=2), strategy="dp")
        return [float(tr.train_step(_BATCH)["loss"]) for _ in range(3)]

    dropped, det = run(0.2), run(0.0)
    assert all(np.isfinite(dropped)), dropped
    assert dropped != det, "dropout_rate=0.2 changed nothing in the pipeline"


def test_moe_pipeline_gpipe_1f1b_equivalence():
    """Switch-MoE rides both schedules (VERDICT r2 next #4) with the same
    objective: ce + aux averaged over micro-batches and layers — so the
    GPipe loss curve (aux collected through the schedule and re-sown) must
    equal the fused 1F1B one (aux seeded in the backward slots)."""
    from pytorchdistributed_tpu.training import moe_token_cross_entropy_loss

    def run(schedule):
        model = GPT2(gpt2_config(
            "test", num_layers=4, dtype=jnp.float32, moe_experts=4,
            moe_capacity_factor=2.0, pipeline_stages=2,
            pipeline_microbatches=2, pp_schedule=schedule))
        tr = Trainer(model, optax.sgd(1e-2), moe_token_cross_entropy_loss,
                     mesh=create_mesh(data=2, expert=2, pipe=2),
                     strategy="tp")
        return [float(tr.train_step(_BATCH)["loss"]) for _ in range(3)]

    np.testing.assert_allclose(run("1f1b"), run("gpipe"), atol=2e-5)


def test_1f1b_custom_loss_raises():
    """A custom loss_fn cannot ride the fused pipeline — must raise, not
    warn-and-train-a-different-objective (VERDICT r2 weak #3)."""
    def my_loss(model, params, batch, rng=None):
        return jnp.float32(0.0), {}

    model = GPT2(gpt2_config("test", num_layers=4, pipeline_stages=2,
                             pipeline_microbatches=2, pp_schedule="1f1b"))
    tr = Trainer(model, optax.sgd(1e-2), my_loss,
                 mesh=create_mesh(data=4, pipe=2), strategy="dp")
    with pytest.raises(ValueError, match="loss"):
        tr.train_step(_BATCH)
