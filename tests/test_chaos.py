"""Chaos-soaked fleet (ISSUE 19): rate-based fault schedules, wire-level
fault injection, and the continuously-checked soak invariants.

Three layers, cheapest first:

  * PURE HOST — the extended fault grammar (rate=/period=/burst= +
    wire kinds) parse/validation walls, ChaosSchedule determinism
    (same seed + same FakeClock drive -> bit-identical firing log),
    targeted-vs-random victim selection, the wire manglers, the
    recovery_table MTTR join and its report rendering, and the
    autoscaler's hold-while-degraded gate against a stub router.
  * FAKE-PIPE WIRE — a SubprocessReplica wired to a real os.pipe (no
    jax worker): a literal torn JSON line is a PROTOCOL FAULT (flag
    set, nothing raises), wire_drop leaves the op pending like real
    message loss, and the per-op timeout ladder (env overrides, soft
    wire_slow deadline, bounded wire_retry, terminal WireFault).
  * IN-PROCESS JAX — the quick-tier mini-soak twin: a seeded diurnal
    trace on a FakeClock over 2 replicas with the autoscaler live and
    a ChaosSchedule firing crash/nan/slow, InvariantChecker strict —
    zero compliant-tenant sheds, every stream terminal, zero fresh XLA
    traces, non-empty recovery table. Engine geometry mirrors
    test_router/test_autoscale so compiles ride the shared jit cache.
  * SUBPROCESS (full tier only) — a short real soak: wire faults over
    live run.py-env-contract workers, quarantine->rejoin round trips,
    zero orphans at close.
"""

import collections
import dataclasses
import functools
import json
import os
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.faults import (
    ChaosSchedule,
    FaultInjector,
    FaultPlan,
    recovery_table,
)
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.serving import (
    Autoscaler,
    FakeClock,
    KVBlockPayload,
    ReplicaRouter,
    RouterTelemetry,
    SamplingParams,
    SessionStore,
    SLOConfig,
    TenantConfig,
    TenantTraffic,
    WallClock,
    WireFault,
    make_trace,
    run_soak,
)
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.router import SubprocessReplica

CFG = gpt2_config("test", num_layers=2, max_seq_len=64)


@functools.cache
def _setup():
    model = GPT2(CFG)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    return model, params


# ----------------------------------------------------------------------
# grammar (pure host)


def test_chaos_grammar_rate_specs_parse_and_walls():
    plan = FaultPlan.parse(
        "replica_crash@rate=0.02;replica_hang@period=2.0,burst=2;"
        "wire_torn@rate=0.1;wire_drop@p=0.01;replica_slow@tick=5,ms=50")
    kinds = [s.kind for s in plan.specs]
    assert kinds == ["replica_crash", "replica_hang", "wire_torn",
                     "wire_drop", "replica_slow"]
    assert plan.specs[0].rate == 0.02
    assert plan.specs[1].period == 2.0 and plan.specs[1].burst == 2
    # describe() (what fault_injected events stamp) names the trigger
    assert plan.specs[0].describe() == "replica_crash@rate=0.02"
    assert plan.specs[1].describe() == "replica_hang@period=2.0,burst=2"
    with pytest.raises(ValueError, match="rate"):
        FaultPlan.parse("replica_crash@rate=-1")
    with pytest.raises(ValueError, match="burst"):
        FaultPlan.parse("replica_crash@rate=0.1,burst=0")
    with pytest.raises(ValueError):
        FaultPlan.parse("io_err@rate=0.1")          # rate is chaos-only
    with pytest.raises(ValueError):
        FaultPlan.parse("replica_crash@p=0.5")      # needs a trigger
    with pytest.raises(ValueError):
        FaultPlan.parse("wire_torn@ms=5")           # needs a trigger


def _drive(sched, *, ticks=60, replicas=3, dt=0.125, clk=None):
    clk = clk or FakeClock()
    for t in range(ticks):
        for r in range(replicas):
            sched.on_serving_tick(t, r)
        clk.advance(dt)
    return sched.injected


def test_chaos_schedule_deterministic_and_targeted():
    def build():
        clk = FakeClock()
        return ChaosSchedule("replica_nan@rate=1.0", seed=3,
                             clock=clk), clk

    s1, c1 = build()
    s2, c2 = build()
    log1 = _drive(s1, clk=c1)
    log2 = _drive(s2, clk=c2)
    assert log1 == log2 and len(log1) > 0    # bit-identical replay
    assert all(e["kind"] == "replica_nan" for e in log1)

    # period math is exact on a binary-friendly dt: epoch anchors at the
    # first consult, then 1.0s / 0.125s = every 8 ticks, burst=2 victims
    sp = ChaosSchedule("replica_crash@period=1.0,burst=2", seed=3,
                       clock=(cp := FakeClock()))
    crashes = _drive(sp, clk=cp)
    by_tick = collections.Counter(e["tick"] for e in crashes)
    assert sorted(by_tick) == [8, 16, 24, 32, 40, 48, 56]
    assert all(n == 2 for n in by_tick.values())
    assert all(len({e["replica"] for e in crashes if e["tick"] == t}) == 2
               for t in by_tick)             # distinct victims per burst

    # targeted spec only ever hits its replica
    st = ChaosSchedule("replica_nan@rate=5.0,replica=1", seed=0,
                       clock=(ck := FakeClock()))
    tlog = _drive(st, clk=ck)
    assert tlog and all(e["replica"] == 1 for e in tlog)


def test_mangle_recv_wire_kinds():
    line = json.dumps({"ok": True, "delivered": [[1, 2]] * 8}) + "\n"
    for kind in ("wire_corrupt", "wire_torn"):
        s = ChaosSchedule(f"{kind}@p=1.0", seed=0)
        out, fault = s.mangle_recv(0, line)
        assert fault == kind and out is not None and out.endswith("\n")
        with pytest.raises(json.JSONDecodeError):
            json.loads(out)
        assert s.injected[-1]["kind"] == kind
    s = ChaosSchedule("wire_drop@p=1.0", seed=0)
    assert s.mangle_recv(0, line) == (None, "wire_drop")
    s = ChaosSchedule("wire_delay@p=1.0,ms=1", seed=0)
    out, fault = s.mangle_recv(0, line)
    assert fault == "wire_delay" and json.loads(out)["ok"] is True
    # a clean schedule passes lines through untouched
    s = ChaosSchedule("wire_torn@p=0.0", seed=0)
    assert s.mangle_recv(0, line) == (line, None)
    # targeted wire fault leaves other replicas' lines alone
    s = ChaosSchedule("wire_drop@p=1.0,replica=1", seed=0)
    assert s.mangle_recv(0, line) == (line, None)
    assert s.mangle_recv(1, line) == (None, "wire_drop")


# ----------------------------------------------------------------------
# the wire, against a real pipe (no jax worker)


def _fake_replica(hang_grace_s=0.2):
    """A SubprocessReplica whose 'worker' is a bare os.pipe — the recv
    path (select + readline + parse) is the real code under test."""
    r = SubprocessReplica.__new__(SubprocessReplica)
    r.index = 0
    r.alive = True
    r.hang_grace_s = hang_grace_s
    r.heartbeat_path = None
    r._mirrors, r._on_token, r._demoted = {}, {}, []
    r._health = {}
    r._pending_op = None
    r._probe_result = None
    r.protocol_faults = 0
    r._protocol_fault = False
    r.wire_stats = collections.Counter()
    rfd, wfd = os.pipe()
    r.proc = types.SimpleNamespace(stdout=os.fdopen(rfd, "r"),
                                   poll=lambda: None, pid=os.getpid())
    return r, os.fdopen(wfd, "w")


def test_torn_wire_line_is_protocol_fault_not_crash():
    """Satellite: garbage on stdout classifies as a replica protocol
    fault — flagged for the health sweep's quarantine — never an
    uncaught JSONDecodeError out of the router tick."""
    r, w = _fake_replica()
    r._pending_op = "step"
    w.write('{"ok": true, "delivered": [[1, 42\n')   # literally torn
    w.flush()
    assert r._try_recv(timeout=1.0) is None          # no raise
    assert r._protocol_fault and r.protocol_faults == 1
    assert r.wire_stats["bad_lines"] == 1
    assert r.alive and r._pending_op is None         # line consumed

    # a blocking waiter surfaces it as WireFault (kind=wire_protocol),
    # which every router call site already catches as a TimeoutError
    r._pending_op = "export_kv"
    w.write("\x00garbage not json at all\n")
    w.flush()
    with pytest.raises(WireFault) as ei:
        r.wait_response(op="export_kv")
    assert ei.value.kind == "wire_protocol"
    assert isinstance(ei.value, TimeoutError)


def test_wire_drop_keeps_op_pending():
    r, w = _fake_replica()
    r.wire_chaos = ChaosSchedule("wire_drop@p=1.0", seed=0)
    events = []
    r.on_wire_event = lambda ev, **row: events.append((ev, row))
    r._pending_op = "step"
    w.write('{"ok": true}\n')
    w.flush()
    assert r._try_recv(timeout=1.0) is None
    # the response is GONE but the op is still pending — exactly what
    # real message loss looks like; the watchdog/timeout owns it now
    assert r._pending_op == "step"
    assert r.wire_stats["wire_drop"] == 1
    assert events == [("wire_fault", {"fault": "wire_drop", "op": "step"})]


def test_wire_timeouts_env_overrides_and_soft_deadline(monkeypatch):
    """Satellite: warmup's hard deadline is policy, not a constant —
    PTD_WIRE_TIMEOUT_S globally, PTD_WIRE_TIMEOUT_<OP>_S per op — and a
    DELAYED op is observable (wire_slow) long before the hard timeout
    kills it (wire_retry -> wire_timeout -> WireFault)."""
    for var in ("PTD_WIRE_TIMEOUT_S", "PTD_WIRE_TIMEOUT_WARMUP_S",
                "PTD_WIRE_SOFT_S"):
        monkeypatch.delenv(var, raising=False)
    r, w = _fake_replica(hang_grace_s=10.0)
    assert r._op_timeout("warmup") == 600.0           # the old constant
    assert r._op_timeout("export_kv") == 30.0         # generic floor
    monkeypatch.setenv("PTD_WIRE_TIMEOUT_S", "3")
    assert r._op_timeout("warmup") == 3.0
    monkeypatch.setenv("PTD_WIRE_TIMEOUT_WARMUP_S", "7.5")
    assert r._op_timeout("warmup") == 7.5             # per-op wins
    assert r._op_timeout("export_kv") == 3.0

    monkeypatch.setenv("PTD_WIRE_TIMEOUT_S", "0.4")
    monkeypatch.setenv("PTD_WIRE_SOFT_S", "0.1")
    events = []
    r.on_wire_event = lambda ev, **row: events.append(ev)
    r._pending_op = "warmup"
    monkeypatch.delenv("PTD_WIRE_TIMEOUT_WARMUP_S")
    with pytest.raises(WireFault) as ei:
        r.wait_response(op="warmup", retries=1)
    assert ei.value.kind == "wire_timeout"
    assert events == ["wire_slow", "wire_retry", "wire_timeout"]
    assert r.wire_stats["retries"] == 1


# ----------------------------------------------------------------------
# session disk tier under injected I/O faults (satellite 2)


def _mk_payload(n=16, bs=8):
    return KVBlockPayload(
        prompt=np.arange(n, dtype=np.int32), generated=[5],
        true_len=n, block_size=bs, max_new_tokens=4,
        sampling=SamplingParams(), stop_ids=(),
        leaves=[("h0/cached_key",
                 np.ones((2, n // bs, bs, 4), np.float32))])


def test_session_store_io_faults_absorbed_and_fallback(tmp_path):
    # spill path: two injected io_errs absorbed (counted, session stays
    # in DRAM), third attempt lands on disk
    st = SessionStore(str(tmp_path / "a"), dram_bytes=1 << 30,
                      faults=FaultInjector(
                          FaultPlan.parse("io_err@p=1.0,n=2")))
    st.put("s1", _mk_payload())
    assert st.flush() == 0 and st.stats()["io_errors"] == 1
    assert st.peek_tier("s1") == "dram"     # nothing lost, nothing torn
    assert st.flush() == 0 and st.stats()["io_errors"] == 2
    assert st.flush() == 1                  # injector exhausted (n=2)
    assert st.peek_tier("s1") == "dram" and "s1" in st._disk

    # load path: a transient read fault is a counted MISS (caller
    # re-prefills), NOT corruption — the disk copy survives and the
    # retry serves it
    st2 = SessionStore(str(tmp_path / "a"), dram_bytes=1 << 30,
                       faults=FaultInjector(
                           FaultPlan.parse("io_err@p=1.0,n=1")))
    assert st2.get("s1") is None
    s = st2.stats()
    assert s["io_errors"] == 1 and s["misses"] == 1
    assert s["quarantined"] == 0            # never evidence of rot
    got = st2.get("s1")
    assert got is not None and got[1] == "disk"

    # demotion under a DEAD disk (disk-full story, p=1.0 forever): the
    # spill fails loudly -> the session drops (counted), never a crash
    pay = _mk_payload()
    st3 = SessionStore(str(tmp_path / "b"),
                       dram_bytes=3 * pay.nbytes // 2,
                       faults=FaultInjector(
                           FaultPlan.parse("io_err@p=1.0")))
    st3.put("x", _mk_payload())
    st3.put("y", _mk_payload())             # pushes "x" out of DRAM
    s3 = st3.stats()
    assert s3["io_errors"] >= 1 and s3["dropped"] == 1
    assert s3["demotes"] == 0 and s3["spilled_bytes"] == 0
    assert st3.peek_tier("x") is None and st3.peek_tier("y") == "dram"


# ----------------------------------------------------------------------
# autoscaler: never scale down a degraded fleet


class _StubRouter:
    def __init__(self, healthy=2):
        self.telemetry = RouterTelemetry(None)
        self.pool = dict(replicas=healthy, healthy=healthy, draining=0,
                         quarantined=0, dead=0, removed=0, occupancy=0.1,
                         free_slots=3, queued=0, prefilling=0, parked=0)
        self.removed = 0
        self.trace = None

    def pool_state(self):
        return {"fleet": dict(self.pool)}

    def add_replica(self, role="both"):
        self.pool["healthy"] += 1
        return self.pool["healthy"] - 1

    def remove_replica(self, role=None):
        self.removed += 1
        self.pool["healthy"] -= 1
        return self.pool["healthy"]


def test_autoscaler_holds_scaledown_while_degraded():
    clk = FakeClock()
    stub = _StubRouter(healthy=3)
    asc = Autoscaler(stub, SLOConfig(queue_high=100.0), min_replicas=1,
                     max_replicas=4, breach_ticks=2, clear_ticks=3,
                     up_cooldown_s=0.1, down_cooldown_s=0.1, clock=clk)
    stub.telemetry.signal(queue_depth=0, submitted=0, shed=0)
    # fleet reads idle, but one replica is quarantined (recovery in
    # flight): the clear streak must never accumulate
    stub.pool["quarantined"] = 1
    for _ in range(10):
        assert asc.step() == []
        clk.advance(0.2)
    assert stub.removed == 0
    stub.pool["quarantined"] = 0            # healed -> downscale resumes
    for _ in range(4):
        asc.step()
        clk.advance(0.2)
    assert stub.removed == 1
    # the knob is opt-out for the pre-chaos behavior
    stub2 = _StubRouter(healthy=3)
    stub2.pool["dead"] = 1
    asc2 = Autoscaler(stub2, SLOConfig(queue_high=100.0), min_replicas=1,
                      max_replicas=4, breach_ticks=2, clear_ticks=3,
                      up_cooldown_s=0.1, down_cooldown_s=0.1,
                      hold_on_degraded=False, clock=clk)
    stub2.telemetry.signal(queue_depth=0, submitted=0, shed=0)
    for _ in range(4):
        asc2.step()
        clk.advance(0.2)
    assert stub2.removed == 1


# ----------------------------------------------------------------------
# MTTR attribution + report rendering


def test_recovery_table_and_report_section(tmp_path):
    events = [
        dict(event="fault_injected", time=100.0, replica=0,
             fault="replica_crash"),
        dict(event="replica_dead", time=100.5, replica=0),
        dict(event="respawn", time=101.0, replica=0),
        dict(event="rejoin", time=103.0, replica=0),
        dict(event="wire_fault", time=104.0, replica=1,
             fault="wire_delay"),
        dict(event="wire_slow", time=104.2, replica=1),
        # injected but never noticed: counted, not credited
        dict(event="fault_injected", time=105.0, replica=1,
             fault="replica_hang"),
        # someone ELSE's rejoin must not credit replica 1
        dict(event="rejoin", time=106.0, replica=0),
    ]
    t = recovery_table(events)
    crash = t["replica_crash"]
    assert (crash["injected"], crash["detected"],
            crash["recovered"]) == (1, 1, 1)
    assert crash["mttr_p50_s"] == 3.0 == crash["mttr_max_s"]
    delay = t["wire_delay"]                 # self-healing class
    assert delay["recovered"] == 1 and delay["mttr_p50_s"] == 0.2
    hang = t["replica_hang"]
    assert (hang["detected"], hang["recovered"]) == (0, 0)
    assert hang["mttr_p50_s"] is None

    # the telemetry report CLI renders the same join from the router's
    # event stream on disk
    from pytorchdistributed_tpu.serving.telemetry import (
        ROUTER_METRICS_FILE,
    )
    from pytorchdistributed_tpu.telemetry.report import _router_section

    with open(tmp_path / ROUTER_METRICS_FILE.format(rank=0), "w") as f:
        for e in events:
            f.write(json.dumps({"kind": "event", **e}) + "\n")
    out = "\n".join(_router_section(str(tmp_path)))
    assert "fault recovery (per class):" in out
    assert "replica_crash" in out and "3.00s" in out
    assert "wire_delay" in out and "0.20s" in out


# ----------------------------------------------------------------------
# the mini-soak twin (quick tier): chaos + autoscaler + invariants


def test_mini_soak_invariants_and_fairness_under_chaos(tmp_path):
    """Satellites 4 + 6: a seeded diurnal trace on a FakeClock over an
    in-process fleet with crash/nan/slow rates firing and the
    autoscaler live. InvariantChecker runs STRICT — a compliant-tenant
    shed, a fresh XLA trace on a survivor, a non-terminal stream or a
    failed close raises right here. Deterministic: seeded trace,
    seeded chaos, fake clock."""
    model, params = _setup()
    clk = FakeClock()
    chaos = ChaosSchedule(
        "replica_crash@rate=0.4;replica_nan@rate=0.4;"
        "replica_slow@rate=0.7,ms=2",
        seed=5, clock=clk)
    trace = make_trace(
        seed=5, duration_s=2.5, base_qps=6.0, shape="diurnal",
        peak_mult=2.5,
        tenants=(TenantTraffic("hot", share=10.0),
                 TenantTraffic("calm", share=1.0)),
        vocab_size=CFG.vocab_size, prompt_cap=24, new_cap=6)
    router = ReplicaRouter(
        model, params, replicas=2,
        engine_kwargs=dict(num_slots=3, prefill_bucket=16),
        warmup_lens=(16, 32), max_queue=10, faults=chaos,
        respawn_budget=2, seed=5,
        tenants={"hot": TenantConfig(weight=1.0),
                 "calm": TenantConfig(weight=1.0)})
    router.warmup()
    traces0 = sum(serving_engine.TRACE_COUNTS.values())
    asc = Autoscaler(router,
                     SLOConfig(queue_high=3.0, occupancy_high=0.9,
                               occupancy_low=0.5, shed_rate_max=1.0,
                               ttft_target_ms=1e9),
                     min_replicas=1, max_replicas=3, breach_ticks=2,
                     clear_ticks=25, up_cooldown_s=0.3,
                     down_cooldown_s=0.2, clock=clk)
    report = run_soak(router, trace, clock=clk, tick_s=0.02,
                      autoscaler=asc, compliant=("calm",),
                      debt_budget_s=1000.0, strict=True, check_every=10)
    inv = report["invariants"]
    assert inv["ok"] and inv["violations"] == []
    assert inv["checks"] > 0
    # chaos actually happened — and the fleet absorbed all of it
    assert report["faults_injected"] >= 3
    assert len(report["injected_by_kind"]) >= 2
    assert report["recovery"], "no fault class made it to the table"
    detected = sum(r["detected"] for r in report["recovery"].values())
    assert detected >= 1
    # every admitted stream terminal; the split accounts for everything
    assert sum(report["finish_reasons"].values()) == report["requests"]
    assert report["finish_reasons"].get("stop", 0) \
        + report["finish_reasons"].get("length", 0) > 0
    # fairness under chaos: the compliant tenant NEVER pays for it
    assert inv["shed_by_tenant"].get("calm", 0) == 0
    assert report["slo_attainment"] is not None
    # zero fresh XLA traces fleet-wide (respawns ride the jit cache)
    assert sum(serving_engine.TRACE_COUNTS.values()) == traces0


# ----------------------------------------------------------------------
# the real thing, shortened (full tier only: spawns jax workers)


def test_subprocess_soak_short_with_wire_faults(tmp_path):
    """A compressed BENCH_soak leg: real workers, real wall clock, wire
    faults on the actual stdout pipes, autoscaler live, strict
    invariants — zero orphans proven by PID sweep after close."""
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1,
            "engine": {"num_slots": 3, "prefill_bucket": 16}}
    clk = WallClock()
    chaos = ChaosSchedule(
        "replica_crash@rate=0.05;replica_slow@rate=0.25,ms=40;"
        "wire_torn@rate=0.25;wire_delay@rate=0.4,ms=30",
        seed=3, clock=clk)
    trace = make_trace(
        seed=3, duration_s=8.0, base_qps=2.0, shape="diurnal",
        peak_mult=2.0,
        tenants=(TenantTraffic("hot", share=4.0),
                 TenantTraffic("calm", share=1.0)),
        vocab_size=50257, prompt_cap=24, new_cap=6)
    router = ReplicaRouter(
        workers=[spec, spec], warmup_lens=(16, 32), max_queue=16,
        faults=chaos, respawn_budget=2, seed=3,
        telemetry_dir=str(tmp_path),
        tenants={"hot": TenantConfig(weight=1.0),
                 "calm": TenantConfig(weight=1.0)})
    router.warmup()
    asc = Autoscaler(router,
                     SLOConfig(queue_high=8.0, occupancy_high=0.95,
                               occupancy_low=0.3, shed_rate_max=1.0,
                               ttft_target_ms=1e9),
                     min_replicas=1, max_replicas=3, breach_ticks=5,
                     clear_ticks=100, up_cooldown_s=5.0,
                     down_cooldown_s=10.0, clock=clk)
    report = run_soak(router, trace, clock=clk, tick_s=0.02,
                      autoscaler=asc, compliant=("calm",), strict=True)
    inv = report["invariants"]
    assert inv["ok"] and inv["violations"] == []
    assert inv["pids_seen"] >= 2            # the orphan sweep saw them
    assert report["faults_injected"] >= 1
    wire = (report["router"]["wire_faults"]
            + sum(n for k, n in report["injected_by_kind"].items()
                  if k.startswith("wire_")))
    assert wire >= 1                        # the wire actually misbehaved
    assert sum(report["finish_reasons"].values()) == report["requests"]
    assert inv["shed_by_tenant"].get("calm", 0) == 0
