"""Launcher tests — both reference entry styles (SURVEY.md §3.1/§3.2) on
real OS processes, each with its own 1-device CPU sim: the "multi-node
without a cluster" rig the reference never had (§4 item 4).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from pytorchdistributed_tpu._jax_compat import (
    supports_multiprocess_cpu_collectives,
)
from pytorchdistributed_tpu.runtime.launch import launch

# Real-process jax.distributed collectives on the CPU backend need a
# jaxlib that implements multi-process CPU computations; the 0.4.x-era
# jaxlib rejects them outright ("Multiprocess computations aren't
# implemented on the CPU backend") — environment gate, same vintage
# marker as the shard_map backfill (see _jax_compat).
_needs_multiproc = pytest.mark.skipif(
    not supports_multiprocess_cpu_collectives(),
    reason="multi-process CPU collectives unimplemented in this jaxlib")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _allgather_worker(rank):
    # runs in a fresh spawned process: set up its own platform
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from pytorchdistributed_tpu.runtime import dist

    dist.init_process_group()
    assert dist.get_rank() == rank
    assert dist.get_world_size() == 2
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    got = multihost_utils.process_allgather(jnp.array([dist.get_rank()]))
    assert got.ravel().tolist() == [0, 1]
    dist.destroy_process_group()


def _failing_worker(rank):
    if rank == 1:
        raise SystemExit(3)


def _hang_or_fail_worker(rank):
    if rank == 1:
        raise SystemExit(5)
    import time
    time.sleep(600)  # rank 0 blocks (e.g. in a collective) forever


@_needs_multiproc
def test_spawn_style_collective():
    """The mp.spawn path (reference ddp_gpus.py:98): 2 processes rendezvous
    via the env contract and complete a cross-process collective."""
    launch(_allgather_worker, 2, devices_per_proc=1, timeout=180)


def test_spawn_style_failure_propagates():
    with pytest.raises(RuntimeError, match="rank 1 failed"):
        launch(_failing_worker, 2, devices_per_proc=1, timeout=60)


def test_spawn_style_fail_fast_with_blocked_earlier_rank():
    """A later rank's crash must tear the group down even while an earlier
    rank is blocked (the sequential-join hang: rank 0 stuck in a collective
    waiting for dead rank 1). Must fail in seconds, not at rank 0's
    600s sleep."""
    import time
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="rank 1 failed"):
        launch(_hang_or_fail_worker, 2, devices_per_proc=1, timeout=None)
    assert time.monotonic() - t0 < 60


def test_sim_device_flags_deduplicated():
    """Inherited XLA_FLAGS with a device count must be replaced, not
    appended (last-flag-wins is brittle)."""
    from pytorchdistributed_tpu.runtime.launch import sim_device_flags
    out = sim_device_flags(
        "--foo=1 --xla_force_host_platform_device_count=8 --bar=2", 4)
    assert out.count("xla_force_host_platform_device_count") == 1
    assert "--xla_force_host_platform_device_count=4" in out
    assert "--foo=1" in out and "--bar=2" in out


@_needs_multiproc
def test_torchrun_style_cli(tmp_path):
    """The torchrun path (reference ddp_gpus_torchrun.py:102): the run CLI
    sets the env contract; the script reads it via init_process_group."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, {REPO!r})
        from pytorchdistributed_tpu.runtime import dist
        dist.init_process_group()
        rank = dist.get_rank()
        assert os.environ["RANK"] == str(rank)
        assert dist.get_world_size() == 2
        dist.barrier("test")
        dist.destroy_process_group()
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "2", "--devices-per-proc", "1", str(script)],
        cwd=REPO, timeout=240, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr


def test_heartbeat_detects_hung_rank(tmp_path):
    """Hung-rank fault injection (VERDICT r2 missing #1): rank 1 wedges
    itself (SIGSTOP — alive, silent, never exits), once before its first
    beat and once after, covering both staleness clocks: the pre-first-beat
    ``grace`` window (nothing is stamped at construction, by design — the
    first XLA compile must not count against ``timeout``) and the
    post-beat ``timeout``. Exit-watching alone would hang forever; the
    watchdog must flag the rank, tear the group down (SIGCONT+TERM wakes
    the frozen worker) and relaunch until the third incarnation completes."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, signal, sys, time
        sys.path.insert(0, {REPO!r})
        from pytorchdistributed_tpu.runtime.heartbeat import Heartbeat

        hb = Heartbeat.from_env()
        assert hb is not None, "launcher did not export PTD_HEARTBEAT_DIR"
        tmp = {str(tmp_path)!r}
        if os.environ["RANK"] == "1":
            if not os.path.exists(os.path.join(tmp, "froze_early")):
                open(os.path.join(tmp, "froze_early"), "w").close()
                os.kill(os.getpid(), signal.SIGSTOP)   # before first beat
            elif not os.path.exists(os.path.join(tmp, "froze_late")):
                open(os.path.join(tmp, "froze_late"), "w").close()
                hb.beat()
                os.kill(os.getpid(), signal.SIGSTOP)   # after first beat
        for _ in range(8):   # healthy ranks keep beating to completion
            hb.beat()
            time.sleep(0.1)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "2", "--max-restarts", "2",
         "--heartbeat-timeout", "2.0", "--heartbeat-grace", "8.0",
         "--monitor-interval", "0.1", str(script)],
        cwd=REPO, timeout=180, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "hung (heartbeat stale)" in proc.stderr, proc.stderr
    assert "restart 1/2" in proc.stderr and "restart 2/2" in proc.stderr


def test_heartbeat_ignores_cleanly_exited_ranks(tmp_path):
    """A rank that finishes early stops beating legitimately; the agent
    must not flag it as hung while the rest of the group keeps working."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        from pytorchdistributed_tpu.runtime.heartbeat import Heartbeat

        hb = Heartbeat.from_env()
        if os.environ["RANK"] == "0":
            sys.exit(0)          # done immediately, no more beats
        for _ in range(30):      # rank 1 outlives the timeout by 2x
            hb.beat()
            time.sleep(0.1)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "2", "--heartbeat-timeout", "1.0",
         "--monitor-interval", "0.1", str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "hung" not in proc.stderr, proc.stderr


def test_torchrun_style_elastic_restart(tmp_path):
    """Fault injection (SURVEY.md §5): rank 0 dies on the first incarnation,
    the agent relaunches the group, second incarnation succeeds."""
    marker = tmp_path / "died_once"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        marker = {str(marker)!r}
        if os.environ["RANK"] == "0" and not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(17)  # simulated failure, pre-rendezvous
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "2", "--max-restarts", "1", str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "restart 1/1" in proc.stderr


@_needs_multiproc
def test_elastic_restart_resumes_real_training(tmp_path):
    """The launcher's restart-resume promise, end to end (VERDICT r4 #5 /
    weak #4 — every other launcher test uses synthetic exit-code workers):
    a REAL 2-process DDP training job checkpoints as it goes, rank 0 kills
    itself mid-epoch-1, the agent relaunches the group, and the second
    incarnation's ``fit(resume=True)`` restores the sharded checkpoint and
    fast-forwards to where it left off. The resumed run's final loss must
    equal an uninterrupted run's exactly (same data order via
    set_epoch+skip_steps, same per-step rng folded from state.step) —
    restart-from-checkpoint semantics, SURVEY.md §5."""
    import json

    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import json, os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        sys.path.insert(0, {REPO!r})
        import optax
        from pytorchdistributed_tpu.data import (
            DataLoader, SyntheticRegressionDataset)
        from pytorchdistributed_tpu.models import MLP
        from pytorchdistributed_tpu.runtime import dist
        from pytorchdistributed_tpu.runtime.mesh import create_mesh
        from pytorchdistributed_tpu.training import Trainer, mse_loss

        dist.init_process_group()
        marker = os.environ["PTD_TEST_MARKER"]  # "" = uninterrupted run

        class KillAfter:
            # mid-epoch fault injection: rank 0 dies right before its
            # (n+1)-th batch, once (the marker survives the relaunch)
            def __init__(self, loader, n):
                self.loader, self.n = loader, n

            def __len__(self):
                return len(self.loader)

            def __getattr__(self, name):
                return getattr(self.loader, name)

            def set_epoch(self, epoch):
                self.loader.set_epoch(epoch)

            def __iter__(self):
                for batch in self.loader:
                    if (marker and dist.get_rank() == 0
                            and not os.path.exists(marker)):
                        if self.n == 0:
                            open(marker, "w").close()
                            os._exit(17)
                        self.n -= 1
                    yield batch

        ds = SyntheticRegressionDataset(size=64, in_dim=8, out_dim=1,
                                        seed=0)
        loader = DataLoader(ds, batch_size=8,
                            num_replicas=dist.get_world_size(),
                            rank=dist.get_rank())
        tr = Trainer(MLP(features=(16, 1)), optax.sgd(0.05), mse_loss,
                     mesh=create_mesh(),
                     checkpoint_dir=os.environ["PTD_TEST_CKPT"],
                     checkpoint_every_steps=2, log_every=10**9,
                     watchdog=False)
        # 4 steps/epoch (64 / (8 x 2 ranks)): die at epoch 1 step 2, past
        # the epoch-0 end save and the step-6 periodic save
        metrics = tr.fit(KillAfter(loader, 6) if marker else loader,
                         max_epochs=2, resume=True)
        if dist.get_rank() == 0:
            with open(os.environ["PTD_TEST_OUT"], "w") as f:
                json.dump(metrics, f)
        dist.destroy_process_group()
    """))

    def run(tag, *, kill):
        out = tmp_path / f"{tag}.json"
        env = dict(
            os.environ,
            PTD_TEST_CKPT=str(tmp_path / f"ckpt_{tag}"),
            PTD_TEST_OUT=str(out),
            PTD_TEST_MARKER=str(tmp_path / "died_once") if kill else "",
        )
        proc = subprocess.run(
            [sys.executable, "-m", "pytorchdistributed_tpu.run",
             "--nproc-per-node", "2", "--devices-per-proc", "1",
             "--max-restarts", "1", "--monitor-interval", "0.1",
             str(script)],
            cwd=REPO, timeout=600, capture_output=True, text=True, env=env,
        )
        assert proc.returncode == 0, proc.stderr
        return proc, json.loads(out.read_text())

    proc, interrupted = run("killed", kill=True)
    assert "restart 1/1" in proc.stderr, proc.stderr
    # resume really ran (trainer logs land on the worker's stdout, which
    # the agent inherits)
    assert "resumed from step" in proc.stdout, (proc.stdout, proc.stderr)
    _, baseline = run("clean", kill=False)
    assert interrupted["loss"] == pytest.approx(baseline["loss"],
                                                rel=1e-6), (
        interrupted, baseline)


def test_elastic_resize_drops_persistently_bad_rank(tmp_path):
    """torchrun --nnodes=min:max resize semantics (--elastic-min-nproc,
    VERDICT r3 missing #3 stretch): the top rank fails whenever the group
    is larger than 2 — a persistently bad slot. After it fails twice in a
    row the agent relaunches the group one smaller instead of burning the
    remaining restarts; the 2-wide incarnation completes."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        world = int(os.environ["WORLD_SIZE"])
        if world > 2 and os.environ["RANK"] == str(world - 1):
            sys.exit(13)
    """))
    # max-restarts 1 also proves the shrink is NOT charged to the restart
    # budget: fail -> restart 1/1 -> fail again -> resize (free) -> done
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "3", "--max-restarts", "1",
         "--elastic-min-nproc", "2", "--monitor-interval", "0.1",
         str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "resizing group to 2 (elastic)" in proc.stderr, proc.stderr
    # with resize disabled, the same failure exhausts the restarts
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "3", "--max-restarts", "2",
         "--monitor-interval", "0.1", str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "no restarts left" in proc.stderr


def test_elastic_shrink_then_regrow(tmp_path):
    """torchrun's max bound is standing, not a ratchet (VERDICT r4 missing
    #3): after a shrink, a charged relaunch boundary whose incarnation
    first ran healthy past --elastic-regrow-after probes one worker
    bigger. Scenario: the top rank fails fast while 3-wide but only twice
    (a transient bad slot) → shrink to 2; the 2-wide group runs stably,
    then rank 0 hits a one-off failure — that restart regrows to 3; the
    now-healthy 3-wide group completes."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys, time
        tmp = {str(tmp_path)!r}
        world = int(os.environ["WORLD_SIZE"])
        # top rank is bad while 3-wide, but only for its first two lives
        # (fails FAST — must not look like a stable group to the probe)
        fails = os.path.join(tmp, "topfails")
        n = (len(open(fails).read().splitlines())
             if os.path.exists(fails) else 0)
        if world > 2 and os.environ["RANK"] == str(world - 1) and n < 2:
            with open(fails, "a") as f:
                f.write("x\\n")
            sys.exit(13)
        # everyone else works for a while (past the regrow-after gate)
        time.sleep(1.5)
        # one transient rank-0 failure at the shrunken size AFTER the
        # stable stretch: the restart it forces carries the regrow probe
        transient = os.path.join(tmp, "transient")
        if (world == 2 and os.environ["RANK"] == "0"
                and not os.path.exists(transient)):
            open(transient, "w").close()
            sys.exit(11)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "3", "--max-restarts", "2",
         "--elastic-min-nproc", "2", "--elastic-regrow-after", "1.0",
         "--monitor-interval", "0.1", str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "resizing group to 2 (elastic)" in proc.stderr, proc.stderr
    assert "regrowing group to 3" in proc.stderr, proc.stderr
    # order: shrink first, then the regrow probe
    assert (proc.stderr.index("resizing group to 2")
            < proc.stderr.index("regrowing group to 3")), proc.stderr


def test_elastic_regrow_gate_lets_shrink_reach_min(tmp_path):
    """The uptime gate that keeps regrow from fighting shrink: a slot
    that's bad whenever the group is wider than 2 fails FAST, so no
    restart ever probes bigger, shrink evidence accumulates undisturbed,
    and a 4-wide job steps 4 → 3 → 2 and completes — sizes below max−1
    must stay reachable (a probe on every restart would reset the
    tracker first and flap 4↔3 until the budget died)."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        world = int(os.environ["WORLD_SIZE"])
        if world > 2 and os.environ["RANK"] == str(world - 1):
            sys.exit(13)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "4", "--max-restarts", "2",
         "--elastic-min-nproc", "2", "--monitor-interval", "0.1",
         str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "resizing group to 3 (elastic)" in proc.stderr, proc.stderr
    assert "resizing group to 2 (elastic)" in proc.stderr, proc.stderr
    assert "regrowing" not in proc.stderr, proc.stderr


def test_elastic_regrow_gate_ignores_hung_detection_latency(tmp_path):
    """A slot that persistently WEDGES (never exits, never beats) must not
    pass the regrow gate on detection latency: heartbeat grace/timeout is
    time spent *discovering* the hang, not healthy runtime, so the gate
    credits a hung cohort only up to its last observed beat (0 here — it
    never beat) and the shrink still reaches the healthy size."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, signal, sys, time
        sys.path.insert(0, {REPO!r})
        from pytorchdistributed_tpu.runtime.heartbeat import Heartbeat
        world = int(os.environ["WORLD_SIZE"])
        if world > 2 and os.environ["RANK"] == str(world - 1):
            os.kill(os.getpid(), signal.SIGSTOP)   # wedge, never beat
        hb = Heartbeat.from_env()
        for _ in range(5):
            hb.beat()
            time.sleep(0.1)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "3", "--max-restarts", "1",
         "--elastic-min-nproc", "2", "--elastic-regrow-after", "1.0",
         # generous grace: healthy ranks must land their FIRST beat
         # inside it even when the whole suite is hammering one core
         # (8.0 flaked there — imports alone can exceed it under load)
         "--heartbeat-timeout", "4.0", "--heartbeat-grace", "20.0",
         "--monitor-interval", "0.1", str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "resizing group to 2 (elastic)" in proc.stderr, proc.stderr
    assert "regrowing" not in proc.stderr, proc.stderr


def test_elastic_resize_ignores_group_wide_failures(tmp_path):
    """A failure that takes out EVERY rank (bad script arg analog) is no
    evidence of one bad slot: the tracker resets, no shrink happens, and
    the restarts budget is what runs out."""
    script = tmp_path / "worker.py"
    script.write_text("import sys; sys.exit(7)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "pytorchdistributed_tpu.run",
         "--nproc-per-node", "3", "--max-restarts", "2",
         "--elastic-min-nproc", "2", "--monitor-interval", "0.1",
         str(script)],
        cwd=REPO, timeout=120, capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "resizing" not in proc.stderr, proc.stderr
    assert "no restarts left" in proc.stderr


def test_stale_ranks_clocks(tmp_path):
    """Unit check of the agent's two staleness clocks: a rank WITH a beat
    file is judged by `timeout` from its mtime; a rank with NO file (still
    importing / compiling) gets the more generous `grace` from spawn."""
    import os

    from pytorchdistributed_tpu.runtime.heartbeat import stale_ranks

    spawn = 1000.0
    (tmp_path / "rank0").touch()
    os.utime(tmp_path / "rank0", times=(spawn + 5, spawn + 5))
    # rank1 never beat (no file)
    kw = dict(timeout=2.0, grace=30.0, baseline=spawn)
    # t=6: rank0 fresh (beat at +5), rank1 inside grace
    assert stale_ranks(tmp_path, 2, now=spawn + 6, **kw) == []
    # t=8: rank0 stale (3s > timeout), rank1 still inside grace
    assert stale_ranks(tmp_path, 2, now=spawn + 8, **kw) == [0]
    # t=31: rank1 exceeded grace too
    assert stale_ranks(tmp_path, 2, now=spawn + 31, **kw) == [0, 1]
    # a fresh incarnation's baseline resets both clocks (stale old mtimes
    # are ignored via max(mtime, baseline))
    assert stale_ranks(tmp_path, 2, timeout=2.0, grace=30.0,
                       now=spawn + 100, baseline=spawn + 99) == []
