"""Persistent sessions + the tiered KV memory hierarchy (ISSUE 18).

Correctness bar: a multi-turn session whose KV parked in HBM, demoted
to the host-DRAM tier, spilled to disk, or reattached on a DIFFERENT
replica must produce streams BITWISE-identical (greedy AND seeded) to
the same turn sequence served by one uninterrupted engine — and a
corrupted/torn/version-skewed stored session must NEVER serve wrong
KV: it quarantines (or misses) and the turn re-prefills losslessly.
On top: SessionStore tier/LRU/tenant-cap units, the manifest restart
survival + offline ls/verify/gc CLI, FleetSessionIndex units, the
engine/router validation walls, the kv_window wire carry (satellite
4), the conversation traffic generator + replay driver, and the
zero-recompile guarantee across park/adopt/demote/reattach.

Engine geometry mirrors tests/test_router.py (gpt2 "test", 2 layers,
max_seq_len 64, slots 3, bucket 16, paged block 8) so the compiled
programs are shared across the suite's jit cache.
"""

import contextlib
import dataclasses
import functools
import io
import json
import pathlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from pytorchdistributed_tpu.inference import generate
from pytorchdistributed_tpu.models import GPT2, gpt2_config
from pytorchdistributed_tpu.serving import (
    FleetSessionIndex,
    KVBlockPayload,
    ReplicaRouter,
    SamplingParams,
    ServingEngine,
    SessionStore,
    kv_payload_from_wire,
    kv_payload_to_wire,
    make_conversations,
    replay_conversations,
    session_id_ok,
)
from pytorchdistributed_tpu.serving import engine as serving_engine
from pytorchdistributed_tpu.serving.admission import TenantConfig
from pytorchdistributed_tpu.serving.engine import (
    paged_decode_tick,
    paged_prefill_chunk,
)
from pytorchdistributed_tpu.serving.sessions import main as sessions_cli
from pytorchdistributed_tpu.serving.traffic import TenantTraffic

CFG = gpt2_config("test", num_layers=2, max_seq_len=64)


@functools.cache
def _setup():
    model = GPT2(CFG)
    params = model.init(jax.random.key(1), jnp.zeros((1, 4), jnp.int32))
    dm = GPT2(dataclasses.replace(CFG, decode=True))
    return model, params, dm


def _ref(prompt, n):
    _, params, dm = _setup()
    return np.asarray(generate(dm, params, jnp.asarray(prompt)[None],
                               max_new_tokens=n))[0]


def _engine(**kw):
    model, params, _ = _setup()
    ek = dict(num_slots=3, prefill_bucket=16, block_size=8)
    ek.update(kw)
    engine = ServingEngine(model, params, **ek)
    engine.warmup(prompt_lens=(16, 32))
    engine.warmup_kv_stream()
    return engine


def _router(n, *, store=None, **kw):
    model, params, _ = _setup()
    ek = dict(num_slots=3, prefill_bucket=16, block_size=8,
              session_hbm_max=2)
    ek.update(kw.pop("engine_kwargs", {}))
    router = ReplicaRouter(
        model, params, replicas=n, engine_kwargs=ek,
        warmup_lens=(16, 32), session_store=store, **kw)
    router.warmup()
    return router


def _run(e, prompt, n, **kw):
    h = e.submit(prompt, max_new_tokens=n, **kw)
    while not h.done:
        e.step()
    return h


def _router_run(router, rrs, max_steps=5000):
    rrs = rrs if isinstance(rrs, list) else [rrs]
    for _ in range(max_steps):
        router.step()
        if all(r.done for r in rrs):
            return
    raise AssertionError(
        f"streams not done: {[r.finish_reason for r in rrs]}")


def _mk_payload(n=16, bs=8, **kw):
    """A synthetic payload for store-tier tests — the store treats the
    leaves as opaque arrays, so numpy stand-ins exercise every tier."""
    fields = dict(
        prompt=np.arange(n, dtype=np.int32), generated=[5],
        true_len=n, block_size=bs, max_new_tokens=4,
        sampling=SamplingParams(), stop_ids=(),
        leaves=[("h0/cached_key",
                 np.ones((2, n // bs, bs, 4), np.float32))])
    fields.update(kw)
    return KVBlockPayload(**fields)


# ----------------------------------------------------------------------
# host units (no jax work)


def test_session_id_validation():
    assert session_id_ok("a")
    assert session_id_ok("tenant-1.conv:42_b")
    assert session_id_ok("A" * 128)
    assert not session_id_ok("")
    assert not session_id_ok("-leading-dash")
    assert not session_id_ok(".hidden")
    assert not session_id_ok("has space")
    assert not session_id_ok("sl/ash")
    assert not session_id_ok("A" * 129)


def test_fleet_session_index_units():
    idx = FleetSessionIndex()
    assert idx.owner("s1") is None
    idx.update(0, ["s1", "s2"])
    idx.update(1, ["s2", "s3"])
    assert idx.owner("s1") == 0
    assert idx.owner("s3") == 1
    # ties break to the lowest index (deterministic steering)
    assert idx.owner("s2") == 0
    assert idx.owner("s2", eligible=[1]) == 1
    assert idx.owner("s2", eligible=[]) is None
    # optimistic add answers before the next snapshot confirms it
    idx.add(1, "s4")
    assert idx.owner("s4") == 1
    # the next snapshot REPLACES — demotions/evictions age out
    idx.update(1, ["s3"])
    assert idx.owner("s4") is None
    idx.discard("s2")
    assert idx.owner("s2") is None
    idx.remove(0)
    assert idx.owner("s1") is None
    assert idx.sessions(1) == {"s3"}


def test_store_lru_demotion_and_tenant_caps(tmp_path):
    # per-tenant session caps (the PR 15 admission vocabulary) evict
    # that tenant's oldest sessions only
    st = SessionStore(str(tmp_path / "caps"), dram_bytes=1 << 30,
                      tenants={"small": TenantConfig(max_sessions=2)})
    for i in range(4):
        st.put(f"small-{i}", _mk_payload(), tenant="small")
    st.put("other-0", _mk_payload(), tenant="other")
    s = st.stats()
    assert s["tenant_evicted"] == 2, s
    assert st.get("small-0") is None and st.get("small-1") is None
    assert st.get("small-2") is not None and st.get("other-0") is not None

    # DRAM pressure demotes in LRU order: touch "a" so "b" spills first
    st2 = SessionStore(str(tmp_path / "lru"),
                       dram_bytes=5 * _mk_payload().nbytes // 2)
    st2.put("a", _mk_payload())
    assert st2.peek_tier("a") == "dram"
    st2.put("b", _mk_payload())
    st2.get("a")  # touch — "b" is now the LRU entry
    st2.put("c", _mk_payload())
    assert st2.peek_tier("b") == "disk", st2.stats()
    assert st2.peek_tier("a") == "dram"
    s2 = st2.stats()
    assert s2["demotes"] >= 1 and s2["spilled_bytes"] > 0
    # a disk hit PROMOTES back up the hierarchy
    got = st2.get("b")
    assert got is not None and got[1] == "disk"
    assert st2.stats()["promotes"] >= 1
    st.close()
    st2.close()


def test_store_restart_corruption_torn_and_version(tmp_path):
    d = str(tmp_path / "store")
    st = SessionStore(d, dram_bytes=1 << 30)
    st.put("alice", _mk_payload())
    st.put("bob", _mk_payload(n=24, bs=8))
    st.flush()
    st.close()

    # restart survival: a fresh store over the same dir serves both
    st2 = SessionStore(d, dram_bytes=1 << 30)
    assert st2.peek_tier("alice") == "disk"
    p, tier = st2.get("bob")
    assert tier == "disk"
    np.testing.assert_array_equal(p.prompt, np.arange(24, dtype=np.int32))
    st2.close()

    # corruption -> quarantine: a torn payload can only MISS, never
    # serve wrong KV; the session dir moves under quarantine/
    sdir = next(x for x in pathlib.Path(d).iterdir()
                if x.is_dir() and x.name.startswith("alice"))
    pj = sdir / "payload.json"
    pj.write_text(pj.read_text()[:-20] + '"corrupted": true}')
    st3 = SessionStore(d, dram_bytes=1 << 30)
    assert st3.get("alice") is None
    assert st3.stats()["quarantined"] == 1
    assert (pathlib.Path(d) / "quarantine").exists()
    st3.close()

    # torn publish (manifest never landed) -> counted miss
    tdir = pathlib.Path(d) / "torn-1"
    tdir.mkdir()
    (tdir / "payload.json").write_text("{}")
    st4 = SessionStore(d, dram_bytes=1 << 30)
    assert st4.get("torn-1") is None
    st4.close()

    # wire-version skew: intact but from another era -> loud decline,
    # never parsed into an engine
    st5 = SessionStore(d, dram_bytes=1 << 30, wire_version=999)
    assert st5.get("bob") is None
    assert st5.stats()["version_declines"] == 1
    st5.close()


def test_store_cli_ls_verify_gc(tmp_path):
    d = str(tmp_path / "store")
    st = SessionStore(d, dram_bytes=1 << 30)
    for i in range(3):
        st.put(f"s-{i}", _mk_payload())
    st.flush()
    st.close()

    def run_cli(args):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = sessions_cli(args)
        return rc, buf.getvalue()

    rc, out = run_cli(["ls", d])
    assert rc == 0 and all(f"s-{i}" in out for i in range(3))
    rc, out = run_cli(["verify", d])
    assert rc == 0

    # verify --strict flags a corrupted session non-zero
    sdir = next(x for x in pathlib.Path(d).iterdir()
                if x.is_dir() and x.name.startswith("s-0"))
    (sdir / "payload.json").write_text("not json")
    rc, out = run_cli(["verify", d, "--strict"])
    assert rc != 0

    # gc: --dry-run touches nothing, then --max-age 0 reaps everything
    rc, out = run_cli(["gc", d, "--max-age", "0", "--dry-run"])
    assert rc == 0
    assert any(pathlib.Path(d).glob("s-*")), "dry-run must not delete"
    rc, out = run_cli(["gc", d, "--max-age", "0"])
    assert rc == 0
    st2 = SessionStore(d, dram_bytes=1 << 30)
    assert all(st2.peek_tier(f"s-{i}") is None for i in range(3))
    st2.close()


def test_conversation_generator_determinism():
    tenants = (TenantTraffic("acme", share=2.0, prefix_len=8,
                             prefix_frac=1.0),
               TenantTraffic("solo", share=1.0))
    a = make_conversations(seed=7, duration_s=20.0, session_rate=0.5,
                           tenants=tenants, vocab_size=CFG.vocab_size)
    b = make_conversations(seed=7, duration_s=20.0, session_rate=0.5,
                           tenants=tenants, vocab_size=CFG.vocab_size)
    assert len(a) == len(b) > 0
    for ca, cb in zip(a, b):
        assert ca.session_id == cb.session_id and session_id_ok(
            ca.session_id)
        assert ca.open_at_s == cb.open_at_s
        assert len(ca.turns) == len(cb.turns) >= 1
        for ta, tb in zip(ca.turns, cb.turns):
            np.testing.assert_array_equal(ta.user_tokens, tb.user_tokens)
            assert ta.max_new_tokens == tb.max_new_tokens
            assert ta.think_gap_s == tb.think_gap_s
        # opening turns release immediately; later turns think first
        assert ca.turns[0].think_gap_s == 0.0
        assert all(t.think_gap_s > 0.0 for t in ca.turns[1:])
    assert [c.open_at_s for c in a] == sorted(c.open_at_s for c in a)
    # a prefix_frac=1.0 tenant opens every session with its shared
    # system-prompt prefix (the shape prefix caching feeds on)
    acme = [c for c in a if c.tenant == "acme"]
    assert acme, "share 2/3 over 20 s at 0.5/s must open acme sessions"
    first = acme[0].turns[0].user_tokens[:8]
    for c in acme[1:]:
        np.testing.assert_array_equal(c.turns[0].user_tokens[:8], first)
    # a different seed moves the mix
    c = make_conversations(seed=8, duration_s=20.0, session_rate=0.5,
                           tenants=tenants, vocab_size=CFG.vocab_size)
    assert [x.open_at_s for x in c] != [x.open_at_s for x in a]


# ----------------------------------------------------------------------
# engine tier: walls, park/adopt/demote, store reattach


def test_engine_and_router_session_walls(tmp_path):
    # dense-refusal wall: sessions need the paged pool
    model, params, _ = _setup()
    dense = ServingEngine(model, params, num_slots=2)
    with pytest.raises(ValueError, match="paged engine"):
        dense.submit(np.arange(4, dtype=np.int32), max_new_tokens=2,
                     session_id="s-1")
    dense.close()

    e = _engine()
    with pytest.raises(ValueError, match="malformed session_id"):
        e.submit(np.arange(4, dtype=np.int32), max_new_tokens=2,
                 session_id="-bad id")

    # seed declines (return 0, loud fallback upstream) — never a crash
    good = _mk_payload()
    assert e.seed_session_blocks(
        _mk_payload(block_size=16)) == 0          # geometry mismatch
    assert e.seed_session_blocks(
        dataclasses.replace(good, kv_window=16)) == 0   # windowed trash
    assert e.seed_session_blocks(
        dataclasses.replace(good, kv_dtype="int8")) == 0  # pool dtype
    assert e.seed_session_blocks(
        dataclasses.replace(good, wire_version=999)) == 0
    e.close()


def test_engine_sessions_park_adopt_store_bitwise(tmp_path):
    """The engine-level session lifecycle, bitwise at every tier: turn
    1 parks in HBM, turn 2 adopts the resident blocks through the
    radix, a second session forces a demote into the store
    (session_hbm_max=1), a FRESH engine sharing the store reattaches
    from the DRAM tier, and finally a corrupted disk session
    quarantines and re-prefills — every turn equal to generate()."""
    store = SessionStore(str(tmp_path / "kv"), dram_bytes=1 << 20)
    e = _engine(session_store=store, session_hbm_max=1)
    traces0 = dict(serving_engine.TRACE_COUNTS)
    prefill_c = paged_prefill_chunk._cache_size()
    decode_c = paged_decode_tick._cache_size()

    rng = np.random.default_rng(0)
    p1 = rng.integers(0, CFG.vocab_size, 20).astype(np.int32)
    h1 = _run(e, p1, 6, session_id="alice-1", tenant="alice")
    np.testing.assert_array_equal(h1.new_tokens, _ref(p1, 6)[len(p1):])
    assert e._stats["session_detaches"] == 1

    # turn 2: full history + fresh user tokens rides the parked blocks
    p2 = np.concatenate([p1, np.asarray(h1.new_tokens, np.int32),
                         rng.integers(0, CFG.vocab_size, 5).astype(
                             np.int32)])
    h2 = _run(e, p2, 6, session_id="alice-1", tenant="alice")
    np.testing.assert_array_equal(h2.new_tokens, _ref(p2, 6)[len(p2):])
    sess = e.summary()["sessions"]
    assert sess["attaches"] == 1
    assert e._stats["prefix_hit_tokens"] > 0, "adoption must ride radix"

    # a second parked session busts session_hbm_max=1 -> demote to DRAM
    p3 = rng.integers(0, CFG.vocab_size, 12).astype(np.int32)
    _run(e, p3, 5, session_id="bob-1", tenant="bob")
    assert e.summary()["sessions"]["demotes"] == 1
    assert store.peek_tier("alice-1") == "dram"

    # fresh engine, same store: turn 3 reattaches from host DRAM
    e.close()
    e2 = _engine(session_store=store, session_hbm_max=2)
    p4 = np.concatenate([p2, np.asarray(h2.new_tokens, np.int32)])
    h4 = _run(e2, p4, 4, session_id="alice-1", tenant="alice")
    np.testing.assert_array_equal(h4.new_tokens, _ref(p4, 4)[len(p4):])
    st2 = e2.summary()["sessions"]
    assert st2["attaches"] == 1 and st2["seed_tokens"] > 0
    assert store.stats()["hits_dram"] >= 1
    e2.close()

    # corrupt the disk copy: the reattach must quarantine + re-prefill
    store.flush()
    store.close()
    root = pathlib.Path(str(tmp_path / "kv"))
    sdir = next(x for x in root.iterdir()
                if x.is_dir() and x.name.startswith("alice-1"))
    pj = sdir / "payload.json"
    pj.write_text(pj.read_text()[:-20] + '"corrupted": true}')
    store2 = SessionStore(str(tmp_path / "kv"), dram_bytes=1 << 20)
    e3 = _engine(session_store=store2, session_hbm_max=2)
    h5 = _run(e3, p4, 4, session_id="alice-1", tenant="alice")
    np.testing.assert_array_equal(h5.new_tokens, _ref(p4, 4)[len(p4):])
    assert store2.stats()["quarantined"] == 1
    assert e3.summary()["sessions"]["seed_tokens"] == 0
    e3.close()
    store2.close()

    # the whole lifecycle compiled NOTHING new after warmup
    assert dict(serving_engine.TRACE_COUNTS) == traces0
    assert paged_prefill_chunk._cache_size() == prefill_c
    assert paged_decode_tick._cache_size() == decode_c


def test_engine_sessions_seeded_and_int8_bitwise(tmp_path):
    """Seeded sampling on an int8 pool: a session demoted through the
    store must resume bitwise-equal to one uninterrupted int8 engine
    serving the same turn sequence (generate() is the bf16 oracle, so
    the uninterrupted engine is the int8 reference)."""
    sp = SamplingParams(temperature=0.9, top_k=8, seed=7)
    rng = np.random.default_rng(3)
    p1 = rng.integers(0, CFG.vocab_size, 14).astype(np.int32)

    colo = _engine(kv_dtype="int8")
    w1 = list(_run(colo, p1, 6, sampling=sp).new_tokens)
    p2 = np.concatenate([p1, np.asarray(w1, np.int32),
                         rng.integers(0, CFG.vocab_size, 4).astype(
                             np.int32)])
    w2 = list(_run(colo, p2, 6, sampling=sp).new_tokens)
    colo.close()

    store = SessionStore(str(tmp_path / "kv8"), dram_bytes=1 << 20)
    a = _engine(kv_dtype="int8", session_store=store, session_hbm_max=1)
    h1 = _run(a, p1, 6, session_id="conv-8", sampling=sp)
    assert list(h1.new_tokens) == w1
    # force the demote, then reattach on a FRESH int8 engine
    _run(a, rng.integers(0, CFG.vocab_size, 10).astype(np.int32), 4,
         session_id="filler", sampling=sp)
    assert store.peek_tier("conv-8") == "dram"
    a.close()
    b = _engine(kv_dtype="int8", session_store=store, session_hbm_max=2)
    h2 = _run(b, p2, 6, session_id="conv-8", sampling=sp)
    assert list(h2.new_tokens) == w2
    assert b.summary()["sessions"]["seed_tokens"] > 0
    b.close()
    store.close()


def test_kv_window_override_rides_wire():
    """Satellite 4 (carried bug): export_kv_blocks on a slot with a
    per-request kv_window override used to DROP the tightened limit on
    the wire — the importer then attended over window-retired trash.
    The override must ride the payload both directions."""
    rng = np.random.default_rng(1)
    p = rng.integers(0, CFG.vocab_size, 20).astype(np.int32)
    a = _engine(kv_window_tokens=32, kv_sink_tokens=8)
    b = _engine(kv_window_tokens=32, kv_sink_tokens=8)
    h = a.submit(p, max_new_tokens=8, prefill_only=True,
                 kv_window=16, kv_sink=8)
    while not h.parked:
        a.step()
    pay = a.export_kv_blocks(h)
    assert pay.kv_window == 16 and pay.kv_sink == 8
    wire = kv_payload_from_wire(kv_payload_to_wire(pay))
    assert wire.kv_window == 16 and wire.kv_sink == 8
    h2 = b.import_kv_blocks(wire)
    assert h2.kv_window == 16 and h2.kv_sink == 8
    assert b._slot_windows[h2.slot] == 16
    assert b._slot_sinks[h2.slot] == 8
    while not h2.done:
        b.step()
    # reference: the same overridden request served colocated
    c = _engine(kv_window_tokens=32, kv_sink_tokens=8)
    h3 = _run(c, p, 8, kv_window=16, kv_sink=8)
    np.testing.assert_array_equal(h2.new_tokens, h3.new_tokens)
    # a windowless engine must REFUSE the windowed payload loudly
    d = _engine()
    with pytest.raises(ValueError, match="kv_window"):
        d.import_kv_blocks(wire)
    for e in (a, b, c, d):
        e.close()


def test_replica_ship_export_seed_bitwise():
    """The cross-replica reattach mechanics in isolation: the owner
    engine pops + gathers the resident session (export_session), the
    target seeds it into its radix (seed_session_blocks remote=True),
    and the next turn on the TARGET stays bitwise with generate()."""
    rng = np.random.default_rng(5)
    p1 = rng.integers(0, CFG.vocab_size, 18).astype(np.int32)
    a, b = _engine(), _engine()
    h1 = _run(a, p1, 6, session_id="ship-1")
    np.testing.assert_array_equal(h1.new_tokens, _ref(p1, 6)[len(p1):])

    pay = a.export_session("ship-1")
    # the final sampled token's KV is never written (it was the output,
    # not an input), so the parked cache covers prompt + 5 of 6 tokens
    assert pay is not None and pay.true_len == len(p1) + 5
    assert a.export_session("ship-1") is None, "export pops the session"
    assert a.summary()["sessions"]["resident"] == 0

    wire = kv_payload_from_wire(kv_payload_to_wire(pay))
    seeded = b.seed_session_blocks(wire, remote=True)
    assert seeded > 0

    p2 = np.concatenate([p1, np.asarray(h1.new_tokens, np.int32),
                         rng.integers(0, CFG.vocab_size, 4).astype(
                             np.int32)])
    h2 = _run(b, p2, 6, session_id="ship-1")
    np.testing.assert_array_equal(h2.new_tokens, _ref(p2, 6)[len(p2):])
    assert b._stats["prefix_hit_tokens"] >= seeded
    a.close()
    b.close()


def test_parked_sessions_never_deadlock_admission(tmp_path):
    """Byte pressure outranks session_hbm_max: when parked sessions
    pin enough of the block pool that a live admission cannot cover
    its allocation, the engine demotes LRU residents down the
    hierarchy instead of spinning forever on pool pressure — and the
    demoted sessions land intact in the store."""
    store = SessionStore(str(tmp_path / "kv"), dram_bytes=1 << 20)
    # pool = num_slots * (max_seq_len / block) = 3 * 8 = 24 blocks;
    # hbm_max=8 lets parked sessions squat nearly all of it
    e = _engine(session_store=store, session_hbm_max=8)
    rng = np.random.default_rng(2)
    prompts = [rng.integers(0, CFG.vocab_size, 40).astype(np.int32)
               for _ in range(4)]
    for i, p in enumerate(prompts):
        _run(e, p, 4, session_id=f"squat-{i}")
    assert len(e._sessions) >= 3, "pressure setup must park sessions"
    # a big sessionless admission needs more blocks than remain free
    big = rng.integers(0, CFG.vocab_size, 48).astype(np.int32)
    h = _run(e, big, 4)
    np.testing.assert_array_equal(h.new_tokens, _ref(big, 4)[len(big):])
    assert e.summary()["sessions"]["demotes"] >= 1
    # every demoted session is still resumable from the store tier
    for i in range(4):
        sid = f"squat-{i}"
        assert (sid in e._sessions) or store.peek_tier(sid) is not None
    e.close()
    store.close()


# ----------------------------------------------------------------------
# router tier: steering, demote sweep, restart, fallback


def test_router_sessions_all_tiers_bitwise(tmp_path):
    """The fleet-wide flow across every tier: turn 2 steered to the
    HBM owner, a seeded session demoted into host DRAM under filler
    pressure and reattached, then a BRAND-NEW router + store over the
    same directory resuming from disk — every resumed stream bitwise
    with one uninterrupted engine, zero recompiles throughout."""
    store = SessionStore(str(tmp_path / "fleet"), dram_bytes=1 << 20)
    r = _router(2, store=store)
    with pytest.raises(ValueError, match="malformed session_id"):
        r.submit(np.arange(4, dtype=np.int32), max_new_tokens=2,
                 session_id="bad/slash")
    traces0 = dict(serving_engine.TRACE_COUNTS)
    prefill_c = paged_prefill_chunk._cache_size()
    decode_c = paged_decode_tick._cache_size()

    rng = np.random.default_rng(0)
    p1 = rng.integers(0, CFG.vocab_size, 12).astype(np.int32)
    rr1 = r.submit(p1, max_new_tokens=8, session_id="conv-a",
                   tenant="t0")
    _router_run(r, rr1)
    t1 = list(rr1.tokens)
    home = rr1.replicas[-1]
    r.step()  # the next health snapshot publishes the parked frontier
    assert r._session_index.owner("conv-a") == home

    ref = _engine()
    assert list(_run(ref, p1, 8).new_tokens) == t1
    p2 = np.concatenate([p1, np.asarray(t1, np.int32),
                         rng.integers(0, CFG.vocab_size, 4).astype(
                             np.int32)])
    ref2 = list(_run(ref, p2, 8).new_tokens)

    # turn 2: steered back to the owner, zero-copy HBM reattach
    rr2 = r.submit(p2, max_new_tokens=8, session_id="conv-a",
                   tenant="t0")
    _router_run(r, rr2)
    assert rr2.replicas[-1] == home
    assert list(rr2.tokens) == ref2
    assert r.summary()["sessions"]["reattach"]["hbm"] == 1

    # seeded session under demote pressure: filler sessions bust
    # session_hbm_max=2, the step sweep persists into the store
    sp = SamplingParams(temperature=0.9, top_k=8, seed=7)
    refs = _engine()
    ps = rng.integers(0, CFG.vocab_size, 10).astype(np.int32)
    s1 = list(_run(refs, ps, 6, sampling=sp).new_tokens)
    psx = np.concatenate([ps, np.asarray(s1, np.int32),
                          rng.integers(0, CFG.vocab_size, 4).astype(
                              np.int32)])
    s2 = list(_run(refs, psx, 6, sampling=sp).new_tokens)

    rs1 = r.submit(ps, max_new_tokens=6, session_id="conv-b",
                   tenant="t0", sampling=sp)
    _router_run(r, rs1)
    assert list(rs1.tokens) == s1
    evs = [r.submit(
        rng.integers(0, CFG.vocab_size, 14).astype(np.int32),
        max_new_tokens=4, session_id=f"filler-{k}", tenant="t0")
        for k in range(5)]
    _router_run(r, evs)
    for _ in range(5):
        r.step()  # demote sweeps drain the workers into the store
    assert r.summary()["sessions"]["demotes"] >= 1

    rs2 = r.submit(psx, max_new_tokens=6, session_id="conv-b",
                   tenant="t0", sampling=sp)
    _router_run(r, rs2)
    assert list(rs2.tokens) == s2
    assert sum(r.summary()["sessions"]["reattach"].values()) >= 2

    r.close()  # persists every resident session, flushes DRAM to disk

    # restart survival: new router + new store over the same directory
    full = np.concatenate([psx, np.asarray(s2, np.int32),
                           rng.integers(0, CFG.vocab_size, 3).astype(
                               np.int32)])
    s3 = list(_run(refs, full, 5, sampling=sp).new_tokens)
    ref.close()
    refs.close()
    store2 = SessionStore(str(tmp_path / "fleet"), dram_bytes=1 << 20)
    r2 = _router(1, store=store2)
    rs3 = r2.submit(full, max_new_tokens=5, session_id="conv-b",
                    tenant="t0", sampling=sp)
    _router_run(r2, rs3)
    assert list(rs3.tokens) == s3
    st2 = r2.summary()["sessions"]
    assert st2["reattach"]["disk"] + st2["reattach"]["dram"] >= 1
    assert st2["fallbacks"] == 0
    r2.close()
    store2.close()

    # park/steer/demote/seed across two routers compiled nothing new
    assert dict(serving_engine.TRACE_COUNTS) == traces0
    assert paged_prefill_chunk._cache_size() == prefill_c
    assert paged_decode_tick._cache_size() == decode_c


def test_router_cross_replica_reattach_when_owner_drains(tmp_path):
    """A reattach that CANNOT land on the owner (it is draining out of
    the dispatch set) still resumes losslessly on another replica —
    shipped from the owner's HBM or pulled from the store tier the
    drain demoted it into; re-prefill stays the loud fallback."""
    store = SessionStore(str(tmp_path / "drain"), dram_bytes=1 << 20)
    r = _router(2, store=store)
    rng = np.random.default_rng(9)
    p1 = rng.integers(0, CFG.vocab_size, 12).astype(np.int32)
    rr1 = r.submit(p1, max_new_tokens=6, session_id="conv-d")
    _router_run(r, rr1)
    home = rr1.replicas[-1]
    r.step()
    assert r._session_index.owner("conv-d") == home

    ref = _engine()
    assert list(_run(ref, p1, 6).new_tokens) == list(rr1.tokens)
    p2 = np.concatenate([p1, np.asarray(rr1.tokens, np.int32),
                         rng.integers(0, CFG.vocab_size, 4).astype(
                             np.int32)])
    ref2 = list(_run(ref, p2, 6).new_tokens)
    ref.close()

    r.remove_replica(home)  # graceful drain: out of dispatch, alive
    rr2 = r.submit(p2, max_new_tokens=6, session_id="conv-d")
    _router_run(r, rr2)
    assert rr2.replicas[-1] != home
    assert list(rr2.tokens) == ref2
    st = r.summary()["sessions"]
    assert sum(st["reattach"].values()) + st["fallbacks"] >= 1
    r.close()
    store.close()


def test_conversation_replay_drives_reattaches(tmp_path):
    """The satellite-1 traffic shape end to end: a seeded multi-turn
    conversation mix replayed through a sessioned router — later turns
    reattach (HBM or store tier) instead of re-prefilling, and every
    multi-turn session's final turn is bitwise with one uninterrupted
    engine serving its full history."""
    convs = make_conversations(seed=11, duration_s=8.0,
                               session_rate=0.6,
                               vocab_size=CFG.vocab_size,
                               turns_cap=3, turn_cap=8, new_cap=6,
                               think_mean_s=0.2)
    assert any(len(c.turns) > 1 for c in convs)
    store = SessionStore(str(tmp_path / "conv"), dram_bytes=1 << 20)
    r = _router(1, store=store)
    out = replay_conversations(r, convs, tick_s=0.05,
                               max_seq_len=CFG.max_seq_len)
    multi = [c for c in convs if len(out[c.session_id]) > 1]
    assert multi, "mix must produce at least one multi-turn replay"
    st = r.summary()["sessions"]
    assert sum(st["reattach"].values()) >= 1
    # full-history replay of one multi-turn session, uninterrupted
    c = multi[0]
    handles = out[c.session_id]
    ref = _engine()
    hist = np.zeros(0, np.int32)
    for i, rr in enumerate(handles):
        assert rr.finish_reason in ("stop", "length")
        prompt = np.concatenate([hist, c.turns[i].user_tokens])
        np.testing.assert_array_equal(rr.prompt, prompt)
        want = _run(ref, prompt, c.turns[i].max_new_tokens).new_tokens
        np.testing.assert_array_equal(rr.tokens, want,
                                      err_msg=f"turn {i}")
        hist = np.concatenate([prompt, np.asarray(want, np.int32)])
    ref.close()
    r.close()
    store.close()


# ----------------------------------------------------------------------
# subprocess wire (full-suite-only: spawns jax-importing workers)


def test_subprocess_sessions_e2e(tmp_path):
    """The multi-host shape: session turns over the line-JSON wire —
    the reattach steers to the subprocess owner (frontier rides
    health), export/seed ship a session between workers, the demote
    sweep drains workers into the router's store on close, and a
    restarted subprocess fleet resumes from disk — all bitwise."""
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1,
            "engine": {"num_slots": 3, "prefill_bucket": 16,
                       "block_size": 8, "session_hbm_max": 2}}
    store = SessionStore(str(tmp_path / "wire"), dram_bytes=1 << 20)
    router = ReplicaRouter(workers=[spec, spec], warmup_lens=(16, 32),
                           session_store=store, faults=None)
    try:
        router.warmup()
        rng = np.random.default_rng(17)
        p1 = rng.integers(0, CFG.vocab_size, 12).astype(np.int32)
        rr1 = router.submit(p1, max_new_tokens=6, session_id="wire-a")
        router.run_until_idle(max_steps=200000)
        np.testing.assert_array_equal(rr1.tokens,
                                      _ref(p1, 6)[p1.size:])
        home = rr1.replicas[-1]
        router.step()
        assert router._session_index.owner("wire-a") == home

        # turn 2 steers to the subprocess owner (HBM reattach)
        p2 = np.concatenate([p1, np.asarray(rr1.tokens, np.int32),
                             rng.integers(0, CFG.vocab_size, 4).astype(
                                 np.int32)])
        rr2 = router.submit(p2, max_new_tokens=6, session_id="wire-a")
        router.run_until_idle(max_steps=200000)
        assert rr2.replicas[-1] == home
        np.testing.assert_array_equal(rr2.tokens,
                                      _ref(p2, 6)[p2.size:])
        assert router.summary()["sessions"]["reattach"]["hbm"] == 1

        # explicit wire ship on a throwaway session: export pops it
        # from the owner worker, seed lands it in the other's radix
        pb = rng.integers(0, CFG.vocab_size, 10).astype(np.int32)
        rrb = router.submit(pb, max_new_tokens=4, session_id="wire-b")
        router.run_until_idle(max_steps=200000)
        router.step()
        bhome = rrb.replicas[-1]
        pay = router._replicas[bhome].export_session("wire-b")
        assert pay is not None
        assert router._replicas[1 - bhome].seed_session(pay) > 0
        assert router._replicas[bhome].export_session("wire-b") is None
    finally:
        router.close()
    assert store.peek_tier("wire-a") is not None, \
        "close must persist the resident session into the store"
    # restart: a fresh subprocess fleet + store over the same dir —
    # the seeded copy (or the close-persisted one) resumes from disk
    store.close()
    store2 = SessionStore(str(tmp_path / "wire"), dram_bytes=1 << 20)
    router2 = ReplicaRouter(workers=[spec], warmup_lens=(16, 32),
                            session_store=store2, faults=None)
    try:
        router2.warmup()
        p3 = np.concatenate([p2, np.asarray(rr2.tokens, np.int32),
                             rng.integers(0, CFG.vocab_size, 3).astype(
                                 np.int32)])
        rr3 = router2.submit(p3, max_new_tokens=5,
                             session_id="wire-a")
        router2.run_until_idle(max_steps=200000)
        np.testing.assert_array_equal(rr3.tokens,
                                      _ref(p3, 5)[p3.size:])
        st = router2.summary()["sessions"]
        assert (st["reattach"]["disk"] + st["reattach"]["dram"]
                + st["fallbacks"]) >= 1
    finally:
        router2.close()
    store2.close()
