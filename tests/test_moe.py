"""Switch-MoE tests (SURVEY.md §2c "EP"). The reference has no MoE, so the
correctness bar is internal: the routed computation must equal a per-token
reference loop, degenerate to the dense MLP at one expert, respect capacity,
and actually shard experts over the "expert" mesh axis under the tp rules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu.models import GPT2, SwitchMoE, gpt2_config
from pytorchdistributed_tpu.models.transformer import TransformerConfig
from pytorchdistributed_tpu.runtime.mesh import Axis, create_mesh
from pytorchdistributed_tpu.training import (
    Trainer,
    moe_token_cross_entropy_loss,
)


def _moe(e, cf=2.0, d=16, f=32):
    cfg = TransformerConfig(
        embed_dim=d, mlp_dim=f, dtype=jnp.float32, moe_experts=e,
        moe_capacity_factor=cf)
    return SwitchMoE(cfg)


def test_single_expert_is_dense_mlp():
    """e=1 degenerates: gate==1, every token kept, output == gelu(xW_i)W_o."""
    moe = _moe(1, cf=1.0)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 8, 16)),
                    jnp.float32)
    params = moe.init(jax.random.key(0), x)
    out = moe.apply(params, x)
    import flax.linen as nn
    p = jax.tree.map(lambda l: l.unbox() if hasattr(l, "unbox") else l,
                     params["params"],
                     is_leaf=lambda l: isinstance(l, nn.Partitioned))
    ref = nn.gelu(x @ p["wi"][0]) @ p["wo"][0]
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_moe_matches_per_token_reference():
    """Dense one-hot dispatch == an explicit per-token route-and-apply loop
    (capacity generous enough that nothing overflows)."""
    moe = _moe(4, cf=4.0)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    params = moe.init(jax.random.key(1), x)
    out = np.asarray(moe.apply(params, x)).reshape(-1, 16)

    import flax.linen as nn
    p = jax.tree.map(lambda l: l.unbox() if hasattr(l, "unbox") else l,
                     params["params"],
                     is_leaf=lambda l: isinstance(l, nn.Partitioned))
    toks = np.asarray(x, np.float32).reshape(-1, 16)
    probs = np.asarray(jax.nn.softmax(
        jnp.asarray(toks) @ p["router"], axis=-1))
    for g in range(toks.shape[0]):
        e = int(probs[g].argmax())
        ref = probs[g, e] * np.asarray(
            nn.gelu(jnp.asarray(toks[g]) @ p["wi"][e]) @ p["wo"][e])
        np.testing.assert_allclose(out[g], ref, atol=1e-4)


def test_capacity_overflow_rides_residual():
    """With capacity 1 slot per expert, at most e tokens get an expert
    output; the rest must be exactly zero (the block's residual carries
    them)."""
    e = 2
    moe = _moe(e, cf=2 / 16)  # 16 tokens → capacity 1
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 8, 16)),
                    jnp.float32)
    params = moe.init(jax.random.key(2), x)
    out = np.asarray(moe.apply(params, x)).reshape(-1, 16)
    nonzero = (np.abs(out).sum(-1) > 1e-9).sum()
    assert nonzero <= e, f"{nonzero} tokens routed with {e} capacity slots"


def test_moe_gpt2_trains_sharded():
    """End to end: GPT-2 with Switch MLP blocks trains under the tp rules on
    an expert-axis mesh; expert kernels are actually split; the aux loss is
    reported and the model still learns (loss falls over steps)."""
    mesh = create_mesh(data=2, expert=4)
    model = GPT2(gpt2_config(
        "test", num_layers=2, dtype=jnp.float32, moe_experts=4,
        moe_capacity_factor=2.0))
    tr = Trainer(model, optax.adamw(1e-2), moe_token_cross_entropy_loss,
                 mesh=mesh, strategy="tp")
    rng = np.random.default_rng(3)
    batch = {"tokens": rng.integers(0, 128, (16, 32)).astype(np.int32),
             "targets": rng.integers(0, 128, (16, 32)).astype(np.int32)}
    losses, metrics = [], None
    for _ in range(5):
        metrics = tr.train_step(batch)
        losses.append(float(metrics["loss"]))
    assert "moe_aux" in metrics and np.isfinite(float(metrics["moe_aux"]))
    assert losses[-1] < losses[0], losses

    wi = tr.state.params["params"]["h"]["block"]["moe"]["wi"]
    spec = wi.sharding.spec
    assert Axis.EXPERT in jax.tree.leaves(tuple(spec)), (
        f"expert kernels not sharded over the expert axis: {spec}")
    # per-device shard holds 1/4 of the experts
    shard = wi.addressable_shards[0].data
    assert shard.shape[1] == wi.shape[1] // 4, (wi.shape, shard.shape)


def test_moe_aux_loss_uniform_at_balance():
    """The Switch aux term is exactly 1 when routing is uniform."""
    e = 4
    probs = jnp.full((64, e), 1 / e)
    onehot = jax.nn.one_hot(jnp.arange(64) % e, e)
    aux = e * jnp.sum(onehot.mean(0) * probs.mean(0))
    assert np.isclose(float(aux), 1.0)


# ---------------------------------------------------------------------------
# expert-parallel a2a dispatch (ISSUE 14)
# ---------------------------------------------------------------------------

def _grouped_cfg(**kw):
    """Grouped-routing config pinned to G=8 — the dp2 x expert4 layout —
    so the single-device dense reference computes the IDENTICAL routing
    function the sharded a2a path runs."""
    base = dict(embed_dim=16, mlp_dim=32, dtype=jnp.float32,
                param_dtype=jnp.float32, moe_experts=4,
                moe_capacity_factor=2.0, moe_groups=8)
    base.update(kw)
    return TransformerConfig(**base)


def _unboxed(params):
    import flax.linen as nn

    return jax.tree.map(lambda l: l.unbox() if hasattr(l, "unbox") else l,
                        params, is_leaf=lambda l: isinstance(l,
                                                             nn.Partitioned))


def test_expert_parallel_a2a_matches_single_device():
    """The tentpole parity pin: the explicit all_to_all dispatch/combine
    (shard_map + custom_vjp, ops/overlap.expert_a2a_ffn) on a
    dp2 x expert4 mesh computes the SAME function as the dense grouped
    einsums on one device — fp32 forward BITWISE, grads to float
    roundoff (the backward reuses both exchange directions, so this also
    pins the hand-written cotangent einsums against autodiff of the
    dense path)."""
    mesh = create_mesh(data=2, expert=4)
    x = jnp.asarray(np.random.default_rng(5).standard_normal((8, 8, 16)),
                    jnp.float32)
    dense = SwitchMoE(_grouped_cfg(moe_dispatch="dense"))
    params = dense.init(jax.random.key(5), x)

    def loss(m):
        return lambda p, v: jnp.sum(m.apply(p, v) ** 2)

    ref = dense.apply(params, x)
    ref_g = jax.grad(loss(dense), argnums=(0, 1))(params, x)

    a2a = SwitchMoE(_grouped_cfg(moe_dispatch="a2a"))
    with jax.set_mesh(mesh):
        out = jax.jit(a2a.apply)(params, x)
        g = jax.jit(jax.grad(loss(a2a), argnums=(0, 1)))(params, x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    for got, want in zip(jax.tree.leaves(_unboxed(g)),
                         jax.tree.leaves(_unboxed(ref_g))):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-7)


def test_expert_parallel_int8_parity():
    """int8 payloads compose with the a2a path (pre-quantized dispatch +
    int8 expert matmuls): outputs track the fp32 path within quantization
    tolerance, and the "int8" backward (stochastic-rounded gradient
    exchanges) still produces finite grads of the right structure."""
    mesh = create_mesh(data=2, expert=4)
    x = jnp.asarray(np.random.default_rng(6).standard_normal((8, 8, 16)),
                    jnp.float32)
    fp = SwitchMoE(_grouped_cfg(moe_dispatch="dense"))
    params = fp.init(jax.random.key(6), x)
    ref = np.asarray(fp.apply(params, x))

    q = SwitchMoE(_grouped_cfg(moe_dispatch="a2a", quant="int8_fwd"))
    with jax.set_mesh(mesh):
        out = np.asarray(jax.jit(q.apply)(params, x))
    np.testing.assert_allclose(out, ref, atol=1e-2)

    sr = SwitchMoE(_grouped_cfg(moe_dispatch="a2a", quant="int8"))
    with jax.set_mesh(mesh):
        g = jax.jit(jax.grad(
            lambda p, v: jnp.sum(sr.apply(p, v) ** 2)))(params, x)
    for leaf in jax.tree.leaves(_unboxed(g)):
        assert np.isfinite(np.asarray(leaf)).all()


def test_moe_chunked_overlap_bitwise():
    """Capacity chunking (the combine-a2a-behind-next-chunk's-matmul
    pipeline) is a pure schedule change: chunks=2 output must be BITWISE
    the chunks=1 output — every einsum contracts within a chunk, so not
    even the reduction order moves."""
    mesh = create_mesh(data=2, expert=4)
    x = jnp.asarray(np.random.default_rng(7).standard_normal((8, 8, 16)),
                    jnp.float32)
    mono = SwitchMoE(_grouped_cfg(moe_dispatch="a2a", moe_chunks=1))
    params = mono.init(jax.random.key(7), x)
    piped = SwitchMoE(_grouped_cfg(moe_dispatch="a2a", moe_chunks=2))
    with jax.set_mesh(mesh):
        a = np.asarray(jax.jit(mono.apply)(params, x))
        b = np.asarray(jax.jit(piped.apply)(params, x))
    np.testing.assert_array_equal(a, b)


def test_top2_matches_per_token_reference():
    """k=2 routing with generous capacity == an explicit per-token
    top-2 loop with renormalized gates."""
    import flax.linen as nn

    moe = SwitchMoE(TransformerConfig(
        embed_dim=16, mlp_dim=32, dtype=jnp.float32, moe_experts=4,
        moe_capacity_factor=8.0, moe_top_k=2))
    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    params = moe.init(jax.random.key(8), x)
    out = np.asarray(moe.apply(params, x)).reshape(-1, 16)
    p = _unboxed(params)["params"]
    toks = np.asarray(x, np.float32).reshape(-1, 16)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(toks) @ p["router"],
                                      axis=-1))
    for t in range(toks.shape[0]):
        top2 = np.argsort(-probs[t])[:2]
        gates = probs[t, top2] / probs[t, top2].sum()
        ref = sum(
            gates[j] * np.asarray(
                nn.gelu(jnp.asarray(toks[t]) @ p["wi"][e]) @ p["wo"][e])
            for j, e in enumerate(top2))
        np.testing.assert_allclose(out[t], ref, atol=1e-4)


def test_top2_first_choices_win_capacity_race():
    """The deterministic k-major priority cumsum: with capacity 1 and
    every token's FIRST choice on expert 0 except token 0 (which first-
    chooses expert 1), the two slots must go to token 0's first choice
    and token 1's first choice — token 0's SECOND choice must NOT steal
    expert 0's slot from token 1 (the interleaved-order bug this
    ordering exists to prevent). Overflow diagnostics count the losers:
    30 of 32 assignments."""
    cfg = TransformerConfig(
        embed_dim=2, mlp_dim=4, dtype=jnp.float32, moe_experts=2,
        moe_capacity_factor=1 / 8, moe_top_k=2)  # 16 tokens -> capacity 1
    moe = SwitchMoE(cfg)
    x = np.zeros((1, 16, 2), np.float32)
    x[0, 0] = [1.0, 0.0]   # token 0 prefers expert 1 (via W below)
    x[0, 1:] = [0.0, 1.0]  # tokens 1.. prefer expert 0
    x = jnp.asarray(x)
    params = moe.init(jax.random.key(9), x)
    router = params["params"]["router"]
    W = jnp.asarray([[0.0, 1.0], [1.0, 0.0]], jnp.float32)
    params = {"params": {**params["params"],
                         "router": (router.replace(value=W)
                                    if hasattr(router, "replace") else W)}}
    out, mods = moe.apply(params, x, mutable=["diagnostics"])
    routed = np.flatnonzero(np.abs(np.asarray(out)[0]).sum(-1) > 1e-9)
    np.testing.assert_array_equal(routed, [0, 1])
    overflow = jax.tree.leaves(mods["diagnostics"])[-1]
    assert np.isclose(float(jnp.asarray(overflow)), 30 / 32)


def test_moe_serving_bitwise_vs_generate_expert_sharded():
    """MoE serves (ISSUE 14): a GPT-2 MoE model with EXPERT-SHARDED
    weights on a dp2 x expert4 mesh, through the stock ServingEngine —
    greedy tokens bitwise-equal to offline generate() on replicated
    params (decode routes per token, so a request's output is
    independent of its batch neighbours), with ZERO steady-state
    retraces/recompiles after warmup."""
    from pytorchdistributed_tpu.inference import generate
    from pytorchdistributed_tpu.serving import ServingEngine
    from pytorchdistributed_tpu.serving import engine as serving_engine
    from pytorchdistributed_tpu.serving.engine import (
        decode_tick,
        prefill_into_slot,
    )

    cfg = gpt2_config("test", num_layers=2, max_seq_len=64,
                      moe_experts=4, moe_capacity_factor=2.0)
    model = GPT2(cfg)
    # plain {"params": ...}: init also returns the sown "losses"
    # collection (the router aux terms), which is not a weight
    params = {"params": _unboxed(model.init(
        jax.random.key(11), jnp.zeros((1, 4), jnp.int32))["params"])}
    mesh = create_mesh(data=2, expert=4)
    tr = Trainer(model, optax.sgd(1e-2), moe_token_cross_entropy_loss,
                 mesh=mesh, strategy="dp")
    big = np.tile(np.arange(8, dtype=np.int32)[None] % cfg.vocab_size,
                  (8, 1))
    tr.init({"tokens": big, "targets": big})
    shardings = jax.tree.map(lambda a: a.sharding, tr.state.params)
    sharded = jax.device_put(params, shardings)
    wi = sharded["params"]["h"]["block"]["moe"]["wi"]
    assert Axis.EXPERT in jax.tree.leaves(tuple(wi.sharding.spec)), (
        f"expert kernels not sharded: {wi.sharding.spec}")

    import dataclasses
    dm = GPT2(dataclasses.replace(cfg, decode=True))
    rng = np.random.default_rng(12)
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in (5, 9, 3, 13)]
    news = [6, 3, 8, 5]
    engine = ServingEngine(model, sharded, num_slots=2, prefill_bucket=16,
                           mesh=mesh)
    engine.warmup(prompt_lens=(8, 16))
    traces = dict(serving_engine.TRACE_COUNTS)
    sizes = (prefill_into_slot._cache_size(), decode_tick._cache_size())
    reqs = []
    for p, n in zip(prompts, news):
        reqs.append(engine.submit(p, max_new_tokens=n))
        engine.step()
    engine.run_until_idle()
    for p, n, r in zip(prompts, news, reqs):
        ref = generate(dm, params, jnp.asarray(p)[None], max_new_tokens=n)
        np.testing.assert_array_equal(r.output_ids, np.asarray(ref)[0],
                                      err_msg=f"request {r.id}")
    assert dict(serving_engine.TRACE_COUNTS) == traces
    assert (prefill_into_slot._cache_size(),
            decode_tick._cache_size()) == sizes
