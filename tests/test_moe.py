"""Switch-MoE tests (SURVEY.md §2c "EP"). The reference has no MoE, so the
correctness bar is internal: the routed computation must equal a per-token
reference loop, degenerate to the dense MLP at one expert, respect capacity,
and actually shard experts over the "expert" mesh axis under the tp rules.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorchdistributed_tpu.models import GPT2, SwitchMoE, gpt2_config
from pytorchdistributed_tpu.models.transformer import TransformerConfig
from pytorchdistributed_tpu.runtime.mesh import Axis, create_mesh
from pytorchdistributed_tpu.training import (
    Trainer,
    moe_token_cross_entropy_loss,
)


def _moe(e, cf=2.0, d=16, f=32):
    cfg = TransformerConfig(
        embed_dim=d, mlp_dim=f, dtype=jnp.float32, moe_experts=e,
        moe_capacity_factor=cf)
    return SwitchMoE(cfg)


def test_single_expert_is_dense_mlp():
    """e=1 degenerates: gate==1, every token kept, output == gelu(xW_i)W_o."""
    moe = _moe(1, cf=1.0)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 8, 16)),
                    jnp.float32)
    params = moe.init(jax.random.key(0), x)
    out = moe.apply(params, x)
    import flax.linen as nn
    p = jax.tree.map(lambda l: l.unbox() if hasattr(l, "unbox") else l,
                     params["params"],
                     is_leaf=lambda l: isinstance(l, nn.Partitioned))
    ref = nn.gelu(x @ p["wi"][0]) @ p["wo"][0]
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_moe_matches_per_token_reference():
    """Dense one-hot dispatch == an explicit per-token route-and-apply loop
    (capacity generous enough that nothing overflows)."""
    moe = _moe(4, cf=4.0)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((2, 8, 16)), jnp.float32)
    params = moe.init(jax.random.key(1), x)
    out = np.asarray(moe.apply(params, x)).reshape(-1, 16)

    import flax.linen as nn
    p = jax.tree.map(lambda l: l.unbox() if hasattr(l, "unbox") else l,
                     params["params"],
                     is_leaf=lambda l: isinstance(l, nn.Partitioned))
    toks = np.asarray(x, np.float32).reshape(-1, 16)
    probs = np.asarray(jax.nn.softmax(
        jnp.asarray(toks) @ p["router"], axis=-1))
    for g in range(toks.shape[0]):
        e = int(probs[g].argmax())
        ref = probs[g, e] * np.asarray(
            nn.gelu(jnp.asarray(toks[g]) @ p["wi"][e]) @ p["wo"][e])
        np.testing.assert_allclose(out[g], ref, atol=1e-4)


def test_capacity_overflow_rides_residual():
    """With capacity 1 slot per expert, at most e tokens get an expert
    output; the rest must be exactly zero (the block's residual carries
    them)."""
    e = 2
    moe = _moe(e, cf=2 / 16)  # 16 tokens → capacity 1
    x = jnp.asarray(np.random.default_rng(2).standard_normal((2, 8, 16)),
                    jnp.float32)
    params = moe.init(jax.random.key(2), x)
    out = np.asarray(moe.apply(params, x)).reshape(-1, 16)
    nonzero = (np.abs(out).sum(-1) > 1e-9).sum()
    assert nonzero <= e, f"{nonzero} tokens routed with {e} capacity slots"


def test_moe_gpt2_trains_sharded():
    """End to end: GPT-2 with Switch MLP blocks trains under the tp rules on
    an expert-axis mesh; expert kernels are actually split; the aux loss is
    reported and the model still learns (loss falls over steps)."""
    mesh = create_mesh(data=2, expert=4)
    model = GPT2(gpt2_config(
        "test", num_layers=2, dtype=jnp.float32, moe_experts=4,
        moe_capacity_factor=2.0))
    tr = Trainer(model, optax.adamw(1e-2), moe_token_cross_entropy_loss,
                 mesh=mesh, strategy="tp")
    rng = np.random.default_rng(3)
    batch = {"tokens": rng.integers(0, 128, (16, 32)).astype(np.int32),
             "targets": rng.integers(0, 128, (16, 32)).astype(np.int32)}
    losses, metrics = [], None
    for _ in range(5):
        metrics = tr.train_step(batch)
        losses.append(float(metrics["loss"]))
    assert "moe_aux" in metrics and np.isfinite(float(metrics["moe_aux"]))
    assert losses[-1] < losses[0], losses

    wi = tr.state.params["params"]["h"]["block"]["moe"]["wi"]
    spec = wi.sharding.spec
    assert Axis.EXPERT in jax.tree.leaves(tuple(spec)), (
        f"expert kernels not sharded over the expert axis: {spec}")
    # per-device shard holds 1/4 of the experts
    shard = wi.addressable_shards[0].data
    assert shard.shape[1] == wi.shape[1] // 4, (wi.shape, shard.shape)


def test_moe_aux_loss_uniform_at_balance():
    """The Switch aux term is exactly 1 when routing is uniform."""
    e = 4
    probs = jnp.full((64, e), 1 / e)
    onehot = jax.nn.one_hot(jnp.arange(64) % e, e)
    aux = e * jnp.sum(onehot.mean(0) * probs.mean(0))
    assert np.isclose(float(aux), 1.0)
