"""Headline benchmark — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Default flagship: GPT-2-small causal-LM training throughput (tokens/s) on
the available chip(s) — bf16 compute on the MXU, Pallas flash attention,
adamw, the jitted Trainer hot loop. Other modes (--bench): "mlp" (the
original smoke), "resnet50" (BASELINE config[1] img/s), "sweep" (the
reference's pipeline split-size sweep shape, 03_model_parallel.ipynb:586-623).

Methodology matches the reference's harness (`timeit.repeat`-style: timed
repeats after a compile warmup, mean reported; 03_model_parallel.ipynb:
403-423). The reference publishes no absolute numbers (BASELINE.md), so
vs_baseline is self-relative: the first recorded run writes
`bench_baseline.json`; later runs report value/baseline.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import time

import numpy as np

_BASELINE_FILE = pathlib.Path(__file__).parent / "bench_baseline.json"


def _vs_baseline(metric: str, value: float) -> float:
    baselines = {}
    if _BASELINE_FILE.exists():
        baselines = json.loads(_BASELINE_FILE.read_text())
    if metric not in baselines:
        baselines[metric] = value
        _BASELINE_FILE.write_text(json.dumps(baselines, indent=1))
    return round(value / baselines[metric], 3)


def _time_steps(trainer, batch, *, warmup: int = 2, steps: int = 20) -> float:
    """Seconds per step, post-compile. Synchronization is by *forcing a
    metric value* (float()), not block_until_ready: through the axon TPU
    tunnel block_until_ready has been observed to return without fencing
    the async dispatch queue, inflating throughput ~100x."""
    from pytorchdistributed_tpu.data.loader import shard_batch

    if trainer.state is None:
        trainer.init(batch)
    batch = shard_batch(batch, trainer.batch_sharding)  # one H2D, not per step
    metrics = None
    for _ in range(warmup):
        metrics = trainer.train_step(batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        metrics = trainer.train_step(batch)
    float(metrics["loss"])  # forces the whole chain
    return (time.perf_counter() - t0) / steps


def bench_gpt2() -> dict:
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    import jax
    batch_size, seq_len = 8, 1024
    attention = "pallas" if jax.default_backend() == "tpu" else "dense"
    # remat: without it the 12-layer scan keeps every layer's activations
    # live and the step thrashes HBM (measured 18x slower on v5e)
    model = GPT2(gpt2_config("small", attention=attention, remat=True))
    trainer = Trainer(model, optax.adamw(3e-4), token_cross_entropy_loss,
                      mesh=create_mesh(), strategy="dp", log_every=10**9)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 50257, (batch_size, seq_len)).astype(
            np.int32),
        "targets": rng.integers(0, 50257, (batch_size, seq_len)).astype(
            np.int32),
    }
    sec = _time_steps(trainer, batch)
    tokens_per_s = batch_size * seq_len / sec
    return {"metric": "gpt2s_train_tokens_per_s",
            "value": round(tokens_per_s, 1), "unit": "tokens/s"}


def bench_resnet50() -> dict:
    import optax

    from pytorchdistributed_tpu.models import resnet50
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, cross_entropy_loss

    batch_size = 64
    trainer = Trainer(resnet50(), optax.sgd(0.1, momentum=0.9),
                      cross_entropy_loss, mesh=create_mesh(),
                      strategy="dp", log_every=10**9)
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.standard_normal(
            (batch_size, 224, 224, 3)).astype(np.float32),
        "label": rng.integers(0, 1000, (batch_size,)).astype(np.int32),
    }
    sec = _time_steps(trainer, batch, steps=10)
    return {"metric": "resnet50_train_img_per_s",
            "value": round(batch_size / sec, 1), "unit": "img/s"}


def bench_mlp() -> dict:
    import optax

    from pytorchdistributed_tpu.data import (
        DataLoader,
        SyntheticRegressionDataset,
    )
    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    batch_size = 8192
    model = MLP(features=(1024, 1024, 256))
    ds = SyntheticRegressionDataset(size=batch_size * 4, in_dim=256,
                                    out_dim=256, seed=0)
    trainer = Trainer(model, optax.adamw(1e-3), mse_loss,
                      mesh=create_mesh(), strategy="dp", log_every=10**9)
    loader = DataLoader(ds, batch_size=batch_size, num_replicas=1, rank=0)
    batch = next(iter(loader))
    sec = _time_steps(trainer, batch)
    return {"metric": "mlp_dp_training_throughput",
            "value": round(batch_size / sec, 1), "unit": "samples/s"}


def bench_sweep() -> dict:
    """The reference's split-size tradeoff sweep
    (03_model_parallel.ipynb:586-623): step time vs pipeline micro-batch
    count for a 2-stage GPT-2 on a 2-way pipe mesh. Always runs on a
    2-device CPU sim (the bench host has one TPU chip; the env override
    must happen before the first backend initialization, so no device
    query can precede it). Reports the best micro-batch count's
    throughput; the full table goes to stderr."""
    import sys

    from pytorchdistributed_tpu.config import select_backend

    select_backend("cpu-sim2")  # env + jax.config, before backend init
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 512, (32, 128)).astype(np.int32),
        "targets": rng.integers(0, 512, (32, 128)).astype(np.int32),
    }
    results = {}
    for m in [1, 2, 4, 8, 16, 32]:
        model = GPT2(gpt2_config(
            "test", num_layers=4, vocab_size=512,
            pipeline_stages=2, pipeline_microbatches=m))
        tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                     mesh=create_mesh(pipe=2), strategy="dp",
                     log_every=10**9)
        results[m] = _time_steps(tr, batch, warmup=1, steps=5)
    best = min(results, key=results.get)
    print(f"sweep step seconds: {results} (best microbatches={best})",
          file=sys.stderr, flush=True)
    return {"metric": "pp_sweep_best_tokens_per_s",
            "value": round(32 * 128 / results[best], 1), "unit": "tokens/s"}


BENCHES = {"gpt2": bench_gpt2, "resnet50": bench_resnet50, "mlp": bench_mlp,
           "sweep": bench_sweep}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--bench", choices=sorted(BENCHES), default="gpt2")
    args = parser.parse_args()
    result = BENCHES[args.bench]()
    result["vs_baseline"] = _vs_baseline(result["metric"], result["value"])
    print(json.dumps(result))


if __name__ == "__main__":
    main()
