"""Headline benchmark — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Current flagship benchmark: MLP data-parallel training throughput on the
available chip(s), methodology matching the reference's harness
(`timeit.repeat(number=1, repeat=N)` mean over identical epochs,
03_model_parallel.ipynb:403-423). The reference publishes no absolute
numbers (BASELINE.md), so vs_baseline is self-relative: the first recorded
run writes `bench_baseline.json` and subsequent runs report value/baseline.
"""

from __future__ import annotations

import json
import pathlib
import time

import numpy as np

_BASELINE_FILE = pathlib.Path(__file__).parent / "bench_baseline.json"


def _vs_baseline(metric: str, value: float) -> float:
    baselines = {}
    if _BASELINE_FILE.exists():
        baselines = json.loads(_BASELINE_FILE.read_text())
    if metric not in baselines:
        baselines[metric] = value
        _BASELINE_FILE.write_text(json.dumps(baselines, indent=1))
    return round(value / baselines[metric], 3)


def main() -> None:
    import jax
    import optax

    from pytorchdistributed_tpu.data import (
        DataLoader,
        SyntheticRegressionDataset,
    )
    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    batch_size = 8192
    model = MLP(features=(1024, 1024, 256))
    ds = SyntheticRegressionDataset(size=batch_size * 4, in_dim=256,
                                    out_dim=256, seed=0)
    mesh = create_mesh()
    trainer = Trainer(model, optax.adamw(1e-3), mse_loss, mesh=mesh,
                      strategy="dp", log_every=10**9)
    loader = DataLoader(ds, batch_size=batch_size, num_replicas=1, rank=0)

    # Warmup (compile).
    batch = next(iter(loader))
    trainer.train_step(batch)
    jax.block_until_ready(trainer.state.params)

    repeats, steps = 5, 8
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for batch in loader:
            trainer.train_step(batch)
        for _ in range(steps - len(loader)):
            trainer.train_step(batch)
        jax.block_until_ready(trainer.state.params)
        times.append(time.perf_counter() - t0)
    mean_t = float(np.mean(times))
    samples_per_s = batch_size * max(len(loader), steps) / mean_t

    metric = "mlp_dp_training_throughput"
    print(json.dumps({
        "metric": metric,
        "value": round(samples_per_s, 1),
        "unit": "samples/s",
        "vs_baseline": _vs_baseline(metric, samples_per_s),
    }))


if __name__ == "__main__":
    main()
