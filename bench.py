"""Headline benchmark — run by the driver on real TPU hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Default flagship: GPT-2-small causal-LM training throughput (tokens/s) on
the available chip(s) — bf16 compute on the MXU, Pallas flash attention,
adamw, the jitted Trainer hot loop. Other modes (--bench): "gpt2medium"
(BASELINE config[3]'s model), "llama1b" (RoPE/SwiGLU/GQA + fused CE),
"resnet50" (BASELINE config[1] img/s), "generate" (KV-cache decode),
"serve" (continuous-batching engine under a Poisson arrival trace —
TTFT + steady-state decode tokens/s; `--mode serve` works too),
"mlp" (the original smoke), "sweep" (the reference's pipeline split-size
sweep shape, 03_model_parallel.ipynb:586-623).

Methodology matches the reference's harness (`timeit.repeat`-style: timed
repeats after a compile warmup, mean reported; 03_model_parallel.ipynb:
403-423). The reference publishes no absolute numbers (BASELINE.md), so
vs_baseline compares against COMMITTED absolute targets (the round-1
measurements recorded in BASELINE.md) — a number this harness can never
quietly move. The GPT-2 bench additionally reports MFU from the analytic
model-FLOPs formula so the utilization claim is checkable, and every
Trainer-based bench stamps ``comm_bytes_per_step`` (and, where no
analytic MFU exists, a cost-analysis ``mfu``) from
telemetry.StepAccounting — the same numbers the telemetry run report
derives (PTD_BENCH_ACCOUNTING=0 skips the extra AOT compile they cost).
"""

from __future__ import annotations

import argparse
import functools
import json
import time

import numpy as np

# Absolute committed baselines (BASELINE.md "Recorded absolute numbers"):
# the previous round's verified results pinned at the FLOOR of their
# same-day run-to-run spread — vs_baseline is the round-over-round
# regression tripwire, and a floor pin means only a real regression trips
# it (a best-of-N pin would flag healthy runs inside the noise band; see
# the r5 note below). Fixed in source on purpose: a file the bench writes
# itself can never look slow.
COMMITTED_BASELINES = {
    # r5 verified capture, 2026-07-31 (BASELINE.md "Round-5 verified
    # capture") — the first driver-reachable chip since r2; every LM/vision
    # number includes the Trainer's scoped-VMEM compile default. Pinned at
    # the FLOOR of the same-day multi-run spread (same discipline as the
    # sim tripwires: a committed value inside the noise band makes healthy
    # runs read as regressions), with the observed spread recorded here
    # and in BASELINE.md.
    "gpt2s_train_tokens_per_s": 120294.0,   # 4 runs 120,294-124,469.7
    #                                         (48.7-50.4% MFU)
    "llama1b_train_tokens_per_s": 18512.9,  # 2 runs 18,512.9-18,979.6
    #                                         (60.5-62.0% MFU)
    "gpt2s_decode_tokens_per_s": 3251.8,    # marginal-rate method, 2 runs
    #                                         3,251.8-3,443.8; r3's 3,833
    #                                         did not reproduce
    "gpt2m_train_tokens_per_s": 46442.3,    # 2 runs 46,442.3-46,674.4
    #                                         (53.6-53.8% MFU)
    # EMA batch_stats era: r4's BN-buffer split + compile headroom claw
    # r3's 2,250 back to ~2,276, but the same-day band is wide
    # (2,196.3-2,276.3); the residual vs the r2-late stat-free 2,307.8 is
    # the accepted cost of servable eval
    "resnet50_train_img_per_s": 2196.3,
    # first-ever rows (r5): committed configs in their bench docstrings
    "bert_base_mlm_samples_per_s": 891.7,   # fused_norms=True config;
    #                                         2 runs 891.7-893.9
    "vit_l16_train_img_per_s": 271.6,       # 2 runs 271.6-275.5
    "llama1b_s4096_train_tokens_per_s": 13901.7,  # 3 runs 13,901.7-13,926.5;
    #                                         was a compile failure before
    #                                         the scoped-VMEM default
    "pp_sweep_best_tokens_per_s": 6025.1,  # re-measured on r5 code (2-dev
    #                                        CPU sim; 2 runs 6,025-6,382)
    # In-process weak scaling, eff(8) = 8·t_1/t_8 (VERDICT r3 #8): r4
    # measured 0.895-0.930 across idle runs (BASELINE.md); committed below
    # the noise floor so only a real collective-overhead regression trips.
    # (r5 idle runs spread 0.81-0.90 — t_1's 3-window wiggle transfers 8x
    # into the ratio; see the r5 BASELINE.md row before reading a sub-1.0
    # vs_baseline here as a code regression.)
    "sim_weak_scaling_eff_8dev": 0.85,
    # 8-dev points for the sharded strategies the DP tripwire was blind to
    # (VERDICT r4 #6), same t_1 denominator. Absolute levels are low by
    # construction — the test model is tiny, so fixed per-collective host
    # costs dominate (fsdp pays per-layer all-gather/reduce-scatter, ~9+11
    # collectives/step vs dp's 1) — but they are stable when idle (r5:
    # fsdp 0.183-0.200, tp_dp 0.387-0.461, pipe_dp 0.459-0.512); committed
    # under the observed floor so only a real regression trips.
    "sim_weak_scaling_eff_8dev_fsdp": 0.15,
    "sim_weak_scaling_eff_8dev_tp_dp": 0.32,
    "sim_weak_scaling_eff_8dev_pipe_dp": 0.38,
}


def _vs_baseline(metric: str, value: float) -> float | None:
    if metric not in COMMITTED_BASELINES:
        return None
    return round(value / COMMITTED_BASELINES[metric], 3)


def _mfu(flops_per_step: float, sec_per_step: float) -> float | None:
    """Analytic MFU against the per-generation peak table (owned by
    telemetry/accounting.py). HARDWARE kinds only: an unlabeled bench
    "mfu" must always mean utilization of a real chip, so the CPU sim's
    NOMINAL fallback peak is refused here — sim runs get their MFU from
    `_accounting_fields`, which stamps the peak source alongside it."""
    import jax

    from pytorchdistributed_tpu.telemetry import PEAK_BF16_FLOPS

    peak = PEAK_BF16_FLOPS.get(jax.devices()[0].device_kind)
    if peak is None:
        return None
    return round(flops_per_step / sec_per_step / peak, 4)


def _accounting_fields(trainer, batch, result: dict, sec: float) -> dict:
    """Stamp StepAccounting-derived fields into a bench record:
    ``comm_bytes_per_step`` always, ``mfu`` only where the bench didn't
    already report the analytic-formula MFU (the two denominators differ
    — cost-analysis flops include remat recompute, the analytic formula
    counts model flops once — and the committed MFU story stays
    analytic). Costs one extra AOT compile of the already-built step
    (cheap under a persistent compile cache); PTD_BENCH_ACCOUNTING=0
    skips it, and any failure degrades to omitting the fields — a
    telemetry quirk must not sink a bench run."""
    import os
    import sys

    if os.environ.get("PTD_BENCH_ACCOUNTING") == "0":
        return result
    try:
        acct = trainer.step_accounting(batch)
    except Exception as e:
        print(f"bench: step accounting skipped ({e})", file=sys.stderr)
        return result
    result["comm_bytes_per_step"] = acct.comm_bytes_per_step
    # estimated comm-stall fraction of the measured step (ISSUE 5c):
    # per-device collective bytes at nominal ICI bandwidth over the real
    # step time — the zero-overlap upper bound; read next to the overlap
    # mode stamped by the bench and the HLO overlap census
    stall = acct.comm_stall_frac(sec)
    if stall is not None:
        result["comm_stall_frac"] = stall
        result["comm_stall_ici"] = acct.ici_source
    if "mfu" not in result:
        mfu = acct.mfu(sec)
        if mfu is not None:
            # labeled on BOTH axes: where the flops came from and which
            # peak divided them — a sim-fallback MFU must never read as a
            # hardware utilization claim
            result["mfu"] = mfu
            result["mfu_source"] = "xla_cost_analysis"
            result["mfu_peak"] = acct.peak_source
    return result


def _diag_ab_fields(result: dict, sec: float, make_trainer, batch) -> dict:
    """Diagnostics on/off A/B (ISSUE 6 acceptance): re-time the SAME
    bench config with in-graph diagnostics at scalar cadence
    (Trainer(diagnostics="scalars")) and stamp the measured step-time
    overhead fraction — the "zero-overhead-when-off / measured-when-on"
    guarantee as a number, not a hope (the committed headline stays the
    diagnostics-off program; the pinned HLO byte-identity test covers
    the off side). PTD_DIAG_AB=0 skips the extra compile+timing; any
    failure degrades to omitting the fields."""
    import os
    import sys

    if os.environ.get("PTD_DIAG_AB", "1") == "0":
        return result
    if os.environ.get("PTD_DIAGNOSTICS"):
        # the headline leg already ran with the env's diagnostics mode
        # (stamped via overrides) — re-timing "scalars" against it would
        # record an on-vs-on ~0% and masquerade as the acceptance number
        print("bench: diagnostics A/B skipped (PTD_DIAGNOSTICS set — the "
              "headline already measures that mode)", file=sys.stderr)
        return result
    try:
        sec_d = _time_steps(make_trainer("scalars"), batch)
    except Exception as e:
        print(f"bench: diagnostics A/B skipped ({e})", file=sys.stderr)
        return result
    result["diag_sec_per_step"] = round(sec_d, 6)
    result["diag_overhead_frac"] = round(sec_d / sec - 1.0, 4)
    return result


def transformer_train_flops_per_token(cfg) -> float:
    """Analytic model FLOPs per trained token (fwd+bwd = 3x fwd):
    6 x matmul-params (q/kv/o + MLP per layer, plus the vocab projection)
    + the attention score/value matmuls 12·L·S·E, halved when causal (the
    flash kernel skips acausal blocks — we count FLOPs actually executed).
    Dialect-aware: GQA shrinks the kv projection, SwiGLU adds a third MLP
    matmul (gate), ffn_dim may differ from 4·embed."""
    e, l, s, v = cfg.embed_dim, cfg.num_layers, cfg.max_seq_len, cfg.vocab_size
    kv_frac = cfg.kv_heads / cfg.num_heads
    mlp_mats = 3 if cfg.activation == "swiglu" else 2
    per_layer = (2 + 2 * kv_frac) * e * e + mlp_mats * e * cfg.ffn_dim
    matmul_params = l * per_layer + e * v
    attn = 12 * l * s * e * (0.5 if cfg.causal else 1.0)
    return 6 * matmul_params + attn


def _time_steps(trainer, batch, *, warmup: int = 2, steps: int = 20) -> float:
    """Seconds per step, post-compile. Synchronization is by *forcing a
    metric value* (float()), not block_until_ready: through the axon TPU
    tunnel block_until_ready has been observed to return without fencing
    the async dispatch queue, inflating throughput ~100x."""
    from pytorchdistributed_tpu.data.loader import shard_batch

    if trainer.state is None:
        trainer.init(batch)
    batch = shard_batch(batch, trainer.batch_sharding)  # one H2D, not per step
    metrics = None
    for _ in range(warmup):
        metrics = trainer.train_step(batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        metrics = trainer.train_step(batch)
    float(metrics["loss"])  # forces the whole chain
    return (time.perf_counter() - t0) / steps


def _fused_norms_override(default: bool = False) -> bool:
    """PTD_FUSED_NORMS=1/0 flips the transformer benches onto/off the
    custom_vjp norm backward (TransformerConfig.fused_norms) for chip
    A/Bs; unset takes the bench's committed default. The r5 A/B (all four
    families, BASELINE.md): fused wins ONLY on BERT (+4.3% — post-LN has
    2x the LayerNorm sites per block); gpt2s is a wash, gpt2m -1.6%,
    vit -2.8%, llama -0.7% — so BERT's bench passes default=True and the
    global TransformerConfig default stays False."""
    import os

    val = os.environ.get("PTD_FUSED_NORMS")
    if val is None:
        return default
    return val == "1"


def _quant_override(default: str = "none") -> str:
    """PTD_QUANT={none,int8_fwd,int8} flips the LM benches onto the int8
    quantized-matmul subsystem (ops/quant.py, TransformerConfig.quant) for
    chip A/Bs without code edits — the standing-target lever aimed at the
    measured bf16 plateau (BASELINE.md r5: the MXU's int8 rate is ~2x
    bf16, so quantizing the weight matmuls attacks the arithmetic ceiling
    the schedule knobs couldn't). Unset takes the bench's committed
    default (bf16 — re-pin baselines only after a verified win)."""
    import os

    val = os.environ.get("PTD_QUANT")
    if val is None:
        return default
    if val not in ("none", "int8_fwd", "int8"):
        raise SystemExit(f"bench: PTD_QUANT={val!r} must be one of "
                         f"none|int8_fwd|int8")
    return val


def _overlap_override(default: str = "xla") -> str:
    """PTD_OVERLAP={ring,xla,off} flips the LM benches' collective-overlap
    mode (TransformerConfig.overlap + the Trainer's latency-hiding
    scheduler flags) for chip A/Bs without code edits. Unset takes the
    committed default ("xla" — the monolithic collectives the committed
    baselines were measured with ride the same compiled program; "off"
    additionally drops the scheduler flags, giving the no-overlap
    baseline the acceptance criterion compares against)."""
    import os

    from pytorchdistributed_tpu.parallel.overlap import OVERLAP_MODES

    val = os.environ.get("PTD_OVERLAP")
    if val is None:
        return default
    if val not in OVERLAP_MODES:
        raise SystemExit(f"bench: PTD_OVERLAP={val!r} must be one of "
                         f"{'|'.join(OVERLAP_MODES)}")
    return val


def _stamp_overrides(result: dict,
                     keys: tuple = ("PTD_FUSED_NORMS",)) -> dict:
    """Stamp the A/B env knobs THIS bench actually reads into the record:
    a number captured under an override must never be mistaken for the
    committed config's. (The r5 capture found bench_gpt2 honoring
    PTD_FUSED_NORMS without stamping it — the fused gpt2m row was
    indistinguishable from a plain re-run.) ``keys`` is per-bench on
    purpose: stamping a knob the bench ignores would taint a
    committed-config record the other way."""
    import os

    overrides = {k: os.environ[k] for k in keys if k in os.environ}
    if overrides:
        result["overrides"] = overrides
    return result


def bench_gpt2(size: str = "small") -> dict:
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    import jax
    batch_size, seq_len = 8, 1024
    attention = "pallas" if jax.default_backend() == "tpu" else "dense"
    # Fastest measured v5e config for both sizes: layers unrolled (the
    # per-layer scan costs ~8% in while-loop scheduling) and no remat —
    # small AND medium at batch 8 fit v5e HBM without recompute (medium:
    # 47.4% MFU, the 1024-wide-matmul shape dividend over small's 45.9%).
    # remat="dots" is the fallback for bigger models/batches (config.py).
    import os
    attn_block = os.environ.get("PTD_ATTN_BLOCK")
    overlap = _overlap_override()
    cfg = gpt2_config(size, attention=attention, remat=False,
                      scan_layers=False,
                      ce_chunk=int(os.environ.get("PTD_CE_CHUNK", 2048)),
                      attn_block=int(attn_block) if attn_block else None,
                      fused_norms=_fused_norms_override(),
                      quant=_quant_override(), overlap=overlap)
    model = GPT2(cfg)
    # r2 measured dense CE faster than the fused chunked head for SMALL at
    # batch 8 (BASELINE.md r2-late note); PTD_FUSED_CE=1 re-opens the A/B
    # (medium's 1.6 GB fp32 logits round-trip is 4x small's relative cost)
    if os.environ.get("PTD_FUSED_CE") == "1":
        from pytorchdistributed_tpu.training import (
            fused_token_cross_entropy_loss as loss_fn,
        )
    else:
        loss_fn = token_cross_entropy_loss
    def make_trainer(diagnostics=None):
        return Trainer(model, optax.adamw(3e-4), loss_fn,
                       mesh=create_mesh(), strategy="dp", log_every=10**9,
                       overlap=overlap, diagnostics=diagnostics)

    trainer = make_trainer()
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 50257, (batch_size, seq_len)).astype(
            np.int32),
        "targets": rng.integers(0, 50257, (batch_size, seq_len)).astype(
            np.int32),
    }
    sec = _time_steps(trainer, batch)
    tokens = batch_size * seq_len
    tag = {"small": "gpt2s", "medium": "gpt2m"}.get(size, f"gpt2_{size}")
    result = {"metric": f"{tag}_train_tokens_per_s",
              "value": round(tokens / sec, 1), "unit": "tokens/s",
              "overlap": overlap}
    # PTD_CE_CHUNK only does anything here under the fused head — stamping
    # it on the dense-CE path would taint a committed-config record
    keys = ("PTD_FUSED_CE", "PTD_ATTN_BLOCK", "PTD_FUSED_NORMS",
            "PTD_QUANT", "PTD_OVERLAP", "PTD_DIAGNOSTICS")
    if os.environ.get("PTD_FUSED_CE") == "1":
        keys += ("PTD_CE_CHUNK",)
    _stamp_overrides(result, keys)
    mfu = _mfu(transformer_train_flops_per_token(cfg) * tokens, sec)
    if mfu is not None:
        result["mfu"] = mfu
    result = _accounting_fields(trainer, batch, result, sec)
    # the diagnostics on/off A/B rides the flagship bench (ISSUE 6
    # acceptance: measured scalar-cadence overhead, target <= 3%)
    return _diag_ab_fields(result, sec, make_trainer, batch)


def bench_llama1b(batch_size: int = 8, seq_len: int = 1024,
                  metric: str = "llama1b_train_tokens_per_s") -> dict:
    """Llama-1B (RMSNorm/SwiGLU/RoPE/GQA) single-chip training. Fastest
    measured v5e fit: adafactor (fp32 adamw state for 1.1B params alone
    exceeds the chip's 16G HBM), fused chunked-CE head, unrolled layers
    (the 16-tick scan costs ~8% in while-loop scheduling), selective remat
    keeping all dot outputs; batch 8 at S=1024 (12+ OOMs; sweep in
    BASELINE.md). MFU here beats the GPT-2 bench's shape ceiling story:
    2048-dim matmuls run the MXU harder than 768-dim ones. The
    "longcontext" bench is the same recipe at (2, 4096) — the same global
    token count, so tokens/s compares the cost of sequence length
    directly; causal flash tiles the longer sequence with the same
    block-1024 grid, and the multi-chip continuation is ring/Ulysses
    sequence parallelism (examples/long_context.py)."""
    import optax

    from pytorchdistributed_tpu.models import Llama, llama_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        fused_token_cross_entropy_loss,
    )

    import jax
    import os
    attention = "pallas" if jax.default_backend() == "tpu" else "dense"
    # capture-time A/B knobs (BASELINE.md runbook): batch size and remat
    # policy sweeps without code edits; the committed config is the
    # measured-fastest and stays the default
    batch_size = int(os.environ.get("PTD_BENCH_BS", batch_size))
    remat_policy = os.environ.get("PTD_REMAT_POLICY", "dots_all")
    ce_chunk = int(os.environ.get("PTD_CE_CHUNK", 2048))
    overlap = _overlap_override()
    cfg = llama_config("1b", max_seq_len=seq_len, attention=attention,
                       remat=True, remat_policy=remat_policy,
                       scan_layers=False, ce_chunk=ce_chunk,
                       fused_norms=_fused_norms_override(),
                       quant=_quant_override(), overlap=overlap)
    trainer = Trainer(Llama(cfg), optax.adafactor(3e-3),
                      fused_token_cross_entropy_loss, mesh=create_mesh(),
                      strategy="dp", log_every=10**9, overlap=overlap)
    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 32000, (batch_size, seq_len)).astype(
            np.int32),
        "targets": rng.integers(0, 32000, (batch_size, seq_len)).astype(
            np.int32),
    }
    sec = _time_steps(trainer, batch, steps=10)
    tokens = batch_size * seq_len
    result = {"metric": metric,
              "value": round(tokens / sec, 1), "unit": "tokens/s",
              "overlap": overlap}
    _stamp_overrides(result, ("PTD_BENCH_BS", "PTD_REMAT_POLICY",
                              "PTD_CE_CHUNK", "PTD_FUSED_NORMS",
                              "PTD_QUANT", "PTD_OVERLAP",
                              "PTD_DIAGNOSTICS"))
    mfu = _mfu(transformer_train_flops_per_token(cfg) * tokens, sec)
    if mfu is not None:
        result["mfu"] = mfu
    return _accounting_fields(trainer, batch, result, sec)


def bench_bert(size: str = "base", batch_size: int = 64,
               seq_len: int = 128) -> dict:
    """BERT-base MLM pretraining throughput (BASELINE config[2]: "BERT-base
    MLM (DDP + amp → bf16)"), single chip: released post-LN/exact-GELU
    architecture (the r4 fidelity pins), dynamic RoBERTa-style masking via
    MLMDataset, bf16 compute, adamw. Samples/s is the BASELINE.json
    headline metric; MFU rides along from the analytic formula (the
    masked-LM head reuses the tied embedding — same vocab matmul the
    formula counts). seq 128 is BERT's phase-1 pretraining shape: the
    768-wide matmul story matches GPT-2-small, so expect the same MFU
    neighborhood."""
    import optax

    from pytorchdistributed_tpu.data import MLMDataset, SyntheticTokenDataset
    from pytorchdistributed_tpu.models import BertMLM, bert_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    import jax
    attention = "pallas" if jax.default_backend() == "tpu" else "dense"
    # fused_norms=True is BERT's committed-fastest config (the one family
    # where the r5 A/B favored the custom_vjp backward; see
    # _fused_norms_override)
    overlap = _overlap_override()
    cfg = bert_config(size, max_seq_len=seq_len, attention=attention,
                      remat=False, scan_layers=False,
                      fused_norms=_fused_norms_override(default=True),
                      quant=_quant_override(), overlap=overlap)
    trainer = Trainer(BertMLM(cfg), optax.adamw(1e-4),
                      token_cross_entropy_loss, mesh=create_mesh(),
                      strategy="dp", log_every=10**9, overlap=overlap)
    ds = MLMDataset(
        SyntheticTokenDataset(size=batch_size, seq_len=seq_len,
                              vocab_size=cfg.vocab_size, seed=0),
        vocab_size=cfg.vocab_size, seed=0)
    batch = ds[np.arange(batch_size)]
    sec = _time_steps(trainer, batch, steps=10)
    tag = {"base": "bert_base", "large": "bert_large"}.get(
        size, f"bert_{size}")
    result = {"metric": f"{tag}_mlm_samples_per_s",
              "value": round(batch_size / sec, 1), "unit": "samples/s",
              "tokens_per_s": round(batch_size * seq_len / sec, 1),
              "overlap": overlap}
    _stamp_overrides(result, ("PTD_FUSED_NORMS", "PTD_QUANT",
                              "PTD_OVERLAP", "PTD_DIAGNOSTICS"))
    mfu = _mfu(transformer_train_flops_per_token(cfg)
               * batch_size * seq_len, sec)
    if mfu is not None:
        result["mfu"] = mfu
    return _accounting_fields(trainer, batch, result, sec)


def bench_vit(size: str = "large", batch_size: int = 64) -> dict:
    """ViT-L/16 training throughput (BASELINE config[4]'s model on one
    chip; the pod run adds DCN data parallelism around the same step).
    bf16 compute, adamw, 224px/16px patches → seq 197. Attention is dense
    on purpose even on TPU: at seq 197 attention is ~2% of model FLOPs
    and the odd length sits badly in the flash kernels' block tiling.
    MFU uses the analytic transformer formula on the encoder (the patch
    embedding ≈ one extra 768-wide matmul and the 1000-class head are
    inside ~3% — the encoder dominates)."""
    import optax

    from pytorchdistributed_tpu.models import ViT, vit_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, cross_entropy_loss

    overlap = _overlap_override()
    cfg = vit_config(size, attention="dense", remat=False,
                     scan_layers=False,
                     fused_norms=_fused_norms_override(),
                     quant=_quant_override(), overlap=overlap)
    trainer = Trainer(ViT(cfg), optax.adamw(3e-4), cross_entropy_loss,
                      mesh=create_mesh(), strategy="dp", log_every=10**9,
                      overlap=overlap)
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.standard_normal(
            (batch_size, cfg.image_size, cfg.image_size, 3)).astype(
                np.float32),
        "label": rng.integers(0, cfg.num_classes, (batch_size,)).astype(
            np.int32),
    }
    sec = _time_steps(trainer, batch, steps=10)
    seq = cfg.num_patches + 1
    tag = {"large": "vit_l16"}.get(size, f"vit_{size}_p16")
    result = {"metric": f"{tag}_train_img_per_s",
              "value": round(batch_size / sec, 1), "unit": "img/s",
              "overlap": overlap}
    _stamp_overrides(result, ("PTD_FUSED_NORMS", "PTD_QUANT",
                              "PTD_OVERLAP", "PTD_DIAGNOSTICS"))
    mfu = _mfu(transformer_train_flops_per_token(cfg.transformer)
               * batch_size * seq, sec)
    if mfu is not None:
        result["mfu"] = mfu
    return _accounting_fields(trainer, batch, result, sec)


def bench_resnet50() -> dict:
    import optax

    from pytorchdistributed_tpu.models import resnet50
    from pytorchdistributed_tpu.parallel import Policy
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, cross_entropy_loss

    # bf16 compute + batch 256: measured sweep on v5e (BASELINE.md) —
    # fp32/64 1877, bf16/64 2046, bf16/256 2308 (peak), bf16/512 2183.
    batch_size = 256
    trainer = Trainer(resnet50(), optax.sgd(0.1, momentum=0.9),
                      cross_entropy_loss, mesh=create_mesh(),
                      strategy="dp", precision=Policy.bf16(),
                      log_every=10**9)
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.standard_normal(
            (batch_size, 224, 224, 3)).astype(np.float32),
        "label": rng.integers(0, 1000, (batch_size,)).astype(np.int32),
    }
    sec = _time_steps(trainer, batch, steps=10)
    result = {"metric": "resnet50_train_img_per_s",
              "value": round(batch_size / sec, 1), "unit": "img/s"}
    return _accounting_fields(trainer, batch, result, sec)


def bench_generate() -> dict:
    """GPT-2-small KV-cache decode throughput with a 512-token prompt.
    MARGINAL decode rate, prefill excluded: times 128-new-token and
    16-new-token runs (identical prefill) and divides the extra tokens by
    the extra time — repeat-5 means each, matching the module's
    repeat-and-mean methodology. Primary metric stays the committed batch-4
    point; a batch-32 point rides along as the serving-throughput scaling
    evidence."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.inference import generate
    from pytorchdistributed_tpu.models import GPT2, gpt2_config

    cfg = gpt2_config("small", scan_layers=False)
    rng = np.random.default_rng(0)
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(0), jnp.zeros((1, 64), jnp.int32))
    model = GPT2(dataclasses.replace(cfg, decode=True))

    def marginal_rate(batch):
        prompt = jnp.asarray(rng.integers(0, 50257, (batch, 512)), jnp.int32)

        def timed(n_new, repeats=5):
            kw = dict(max_new_tokens=n_new, temperature=0.8, top_k=40,
                      rng=jax.random.key(1))
            np.asarray(generate(model, params, prompt, **kw))  # compile
            t0 = time.perf_counter()
            for _ in range(repeats):
                out = np.asarray(generate(model, params, prompt, **kw))
            assert out.shape == (batch, 512 + n_new)
            return (time.perf_counter() - t0) / repeats

        t_long, t_short = timed(128), timed(16)
        per_tick = (t_long - t_short) / (128 - 16)
        return batch / per_tick

    r4 = marginal_rate(4)
    r32 = marginal_rate(32)
    return {"metric": "gpt2s_decode_tokens_per_s",
            "value": round(r4, 1), "unit": "tokens/s",
            "batch32_tokens_per_s": round(r32, 1)}


def _drive_serve_trace(engine, prompts, arrivals, max_new, *,
                       sampling_cls=None) -> tuple[dict, int]:
    """Feed a (seeded) arrival trace to an engine in wall-clock time and
    drain it; returns (engine.summary(), peak concurrently-RESIDENT
    requests) — the peak is the capacity number the paged-vs-dense A/B
    compares at a fixed HBM budget."""
    t0 = time.perf_counter()
    pending = list(zip(arrivals, prompts))
    peak = 0
    while (pending or engine.queue_depth or engine.active_count
           or engine.prefilling_count):
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, p = pending.pop(0)
            kw = {}
            if sampling_cls is not None:
                kw["sampling"] = sampling_cls(temperature=0.8, top_k=40,
                                              seed=engine.queue_depth)
            engine.submit(p, max_new_tokens=max_new, **kw)
        if (engine.queue_depth or engine.active_count
                or engine.prefilling_count):
            engine.step()
            peak = max(peak, engine.active_count)
        elif pending:
            time.sleep(min(0.01, max(0.0, pending[0][0] - now)))
    return engine.summary(), peak


def _serve_capacity_ab(block_size: int) -> dict:
    """The ISSUE 7 capacity claim, measured: a dense engine and a paged
    engine at the SAME KV-HBM budget (pool bytes == dense cache bytes,
    via inference.kv_cache_bytes on both) serve the same mixed-length
    Poisson trace; the paged engine's slot count is oversubscribed 4x,
    and because HBM now bounds actual resident tokens instead of
    slots x max_seq_len, its peak resident count should run >= 2x the
    dense engine's."""
    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import ServingEngine

    cfg = gpt2_config("test", num_layers=2, max_seq_len=512,
                      quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    dense_slots = 4
    pages = cfg.max_seq_len // block_size
    rng = np.random.default_rng(7)
    n = 24
    lens = rng.integers(16, 97, n)
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in lens]
    arrivals = np.cumsum(rng.exponential(1.0 / 64.0, n))  # near-burst

    dense = ServingEngine(model, params, num_slots=dense_slots,
                          prefill_bucket=128)
    dense.warmup(prompt_lens=(128,))
    d_sum, d_peak = _drive_serve_trace(dense, prompts, arrivals, 24)
    dense.close()

    paged = ServingEngine(model, params, num_slots=4 * dense_slots,
                          prefill_bucket=128, block_size=block_size,
                          num_blocks=dense_slots * pages)  # same HBM
    paged.warmup(prompt_lens=(128,))
    p_sum, p_peak = _drive_serve_trace(paged, prompts, arrivals, 24)
    paged.close()

    return {
        "kv_hbm_bytes_dense": d_sum["kv_hbm_bytes"],
        "kv_hbm_bytes_paged": p_sum["kv_hbm_bytes"],
        "dense_peak_resident": d_peak,
        "paged_peak_resident": p_peak,
        "resident_ratio": round(p_peak / max(1, d_peak), 2),
        "paged_block_utilization": p_sum["block_utilization"],
        "paged_preemptions": p_sum["preemptions"],
        "dense_ttft_ms_p50": d_sum.get("ttft_ms_p50"),
        "paged_ttft_ms_p50": p_sum.get("ttft_ms_p50"),
    }


def _serve_prefix_ab(block_size: int) -> dict:
    """The ISSUE 7 TTFT claim, measured: a shared-system-prompt trace
    (the chat-frontend shape) served by the paged engine with the radix
    prefix cache ON vs OFF. With reuse, every admission after the first
    skips the shared blocks' prefill compute — prefix_hit_rate > 0 and a
    lower TTFT p50 than the no-reuse twin."""
    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import ServingEngine

    cfg = gpt2_config("test", num_layers=2, max_seq_len=512,
                      quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    rng = np.random.default_rng(11)
    system = rng.integers(0, cfg.vocab_size, (256,)).astype(np.int32)
    prompts = [np.concatenate([
        system, rng.integers(0, cfg.vocab_size, (16,)).astype(np.int32)])
        for _ in range(12)]
    arrivals = np.cumsum(rng.exponential(1.0 / 64.0, len(prompts)))

    out = {}
    for name, reuse in (("prefix_on", True), ("prefix_off", False)):
        engine = ServingEngine(model, params, num_slots=4,
                               prefill_bucket=128, block_size=block_size,
                               prefill_chunk=128, prefix_cache=reuse)
        engine.warmup(prompt_lens=(128,))
        s, _ = _drive_serve_trace(engine, prompts, arrivals, 8)
        engine.close()
        out[name] = {"ttft_ms_p50": s.get("ttft_ms_p50"),
                     "prefix_hit_rate": s.get("prefix_hit_rate"),
                     "prefill_chunks": s.get("prefill_chunks")}
    on, off = out["prefix_on"], out["prefix_off"]
    if on["ttft_ms_p50"] and off["ttft_ms_p50"]:
        out["ttft_p50_speedup"] = round(
            off["ttft_ms_p50"] / on["ttft_ms_p50"], 3)
    return out


def _serve_spec_ab(block_size: int, spec_k: int) -> dict:
    """The ISSUE 8 claim, measured: the same greedy trace served with
    speculative decoding ON (self-drafted — the draft IS the target, so
    acceptance is ~1 and the stamp isolates the MECHANISM's ceiling:
    tokens_per_target_forward ≈ spec_k+1, bounded below by budget-
    truncated final rounds) vs OFF. On hardware the memory-bound target
    makes tokens/forward the decode-rate multiplier; on CPU-sim the
    tokens/s twin is stamped but the acceptance / tokens-per-forward
    pair is the portable number."""
    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import ServingEngine

    cfg = gpt2_config("test", num_layers=2, max_seq_len=512,
                      quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    rng = np.random.default_rng(13)
    n = 16
    lens = rng.integers(16, 97, n)
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in lens]
    arrivals = np.cumsum(rng.exponential(1.0 / 64.0, n))

    out = {}
    for name, k in (("spec_off", 0), ("spec_on", spec_k)):
        engine = ServingEngine(model, params, num_slots=4,
                               prefill_bucket=128, block_size=block_size,
                               spec_k=k)
        engine.warmup(prompt_lens=(128,))
        s, _ = _drive_serve_trace(engine, prompts, arrivals, 48)
        engine.close()
        out[name] = {
            "decode_tokens_per_s": s["decode_tokens_per_s"],
            "acceptance_rate": s.get("acceptance_rate"),
            "tokens_per_target_forward": s.get("tokens_per_target_forward",
                                               1.0),
        }
    on, off = out["spec_on"], out["spec_off"]
    out["spec_k"] = spec_k
    if on["decode_tokens_per_s"] and off["decode_tokens_per_s"]:
        out["decode_tokens_per_s_speedup"] = round(
            on["decode_tokens_per_s"] / off["decode_tokens_per_s"], 3)
    return out


def bench_serve() -> dict:
    """Continuous-batching serving (serving/ServingEngine) under a
    synthetic Poisson arrival trace: seeded exponential inter-arrivals at
    PTD_SERVE_RATE req/s feed the slot scheduler in wall-clock time, so
    queue waits are real. Stamps the steady-state decode rate
    (tokens/s over decode-tick wall time, prefills excluded) as the
    headline plus ``ttft_ms_p50/p99`` (queue wait included) and mean
    ``slot_occupancy`` — the same numbers the engine's telemetry bridge
    emits. Warmup compiles every prefill bucket + the tick before the
    clock starts; the record asserts-by-stamping ``recompiles`` (must be
    0 — the zero-retrace guarantee under load). PTD_SERVE_PAGED=1 runs
    the main trace on the PAGED engine (block-table KV + radix prefix
    cache + chunked prefill, ISSUE 7) and stamps kv_hbm_bytes /
    block_utilization / prefix_hit_rate / prefill_chunks next to the
    usual numbers; PTD_SERVE_SPEC=1 additionally serves it with
    SPECULATIVE decoding (ISSUE 8, self-drafted, k = PTD_SPEC_K,
    implies paged) and stamps acceptance_rate /
    tokens_per_target_forward. The record always carries the paged A/Bs
    — ``paged_capacity`` (>= 2x resident slots at the same HBM budget)
    and ``prefix_ab`` (shared-system-prompt TTFT with reuse on vs off) —
    plus the ``spec_ab`` twin (spec on vs off on the self-drafted
    trace). PTD_SERVE_AB=0 skips ALL of them; PTD_SPEC_AB=0 skips just
    spec_ab. Runs on
    CPU-sim or TPU unchanged; knobs via env:
    PTD_SERVE_SIZE/SLOTS/REQUESTS/RATE/MAX_NEW/PAGED/BLOCK/SPEC,
    PTD_SPEC_K, PTD_QUANT rides the model config like the training
    benches."""
    import os

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import SamplingParams, ServingEngine
    from pytorchdistributed_tpu.serving import engine as serving_engine

    size = os.environ.get("PTD_SERVE_SIZE", "small")
    num_slots = int(os.environ.get("PTD_SERVE_SLOTS", "8"))
    n_requests = int(os.environ.get("PTD_SERVE_REQUESTS", "32"))
    rate = float(os.environ.get("PTD_SERVE_RATE", "8.0"))
    max_new = int(os.environ.get("PTD_SERVE_MAX_NEW", "32"))
    spec = os.environ.get("PTD_SERVE_SPEC", "0") == "1"
    spec_k = int(os.environ.get("PTD_SPEC_K", "4"))
    paged = spec or os.environ.get("PTD_SERVE_PAGED", "0") == "1"
    block = int(os.environ.get("PTD_SERVE_BLOCK", "16"))
    cfg = gpt2_config(size, scan_layers=False, quant=_quant_override())
    params = jax.jit(GPT2(cfg).init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    engine = ServingEngine(GPT2(cfg), params, num_slots=num_slots,
                           prefill_bucket=128,
                           block_size=block if paged else 0,
                           spec_k=spec_k if spec else 0)

    rng = np.random.default_rng(0)
    lens = rng.integers(16, 97, n_requests)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in lens]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    engine.warmup(prompt_lens=(128,))
    traces0 = dict(serving_engine.TRACE_COUNTS)

    s, _ = _drive_serve_trace(engine, prompts, arrivals, max_new,
                              sampling_cls=SamplingParams)
    recompiles = sum(dict(serving_engine.TRACE_COUNTS).values()) \
        - sum(traces0.values())
    result = {"metric": "serve_decode_tokens_per_s",
              "value": s["decode_tokens_per_s"], "unit": "tokens/s",
              "ttft_ms_p50": s["ttft_ms_p50"],
              "ttft_ms_p99": s["ttft_ms_p99"],
              "slot_occupancy": s["slot_occupancy"],
              "requests": n_requests, "num_slots": num_slots,
              "arrival_rate_per_s": rate,
              "prefill_ms_mean": s["prefill_ms_mean"],
              "kv_hbm_bytes": s["kv_hbm_bytes"],
              "paged": paged,
              "recompiles": recompiles}
    if paged:
        result["block_size"] = block
        result["block_utilization"] = s["block_utilization"]
        result["prefix_hit_rate"] = s["prefix_hit_rate"]
        result["prefill_chunks"] = s["prefill_chunks"]
        result["preemptions"] = s["preemptions"]
    if spec:
        result["spec_k"] = spec_k
        result["acceptance_rate"] = s["acceptance_rate"]
        result["tokens_per_target_forward"] = s["tokens_per_target_forward"]
    engine.close()
    # PTD_SERVE_AB=0 is the master fast-path switch for ALL serving
    # A/Bs; PTD_SPEC_AB=0 skips just the speculative one
    if os.environ.get("PTD_SERVE_AB", "1") != "0":
        result["paged_capacity"] = _serve_capacity_ab(block)
        result["prefix_ab"] = _serve_prefix_ab(block)
        if os.environ.get("PTD_SPEC_AB", "1") != "0":
            result["spec_ab"] = _serve_spec_ab(block, spec_k)
    # request-tracing cost twin (ISSUE 17) — default OFF: it stands up
    # its own small fleet, so only pay for it when asked
    if os.environ.get("PTD_TRACE_AB", "0") == "1":
        result["trace_ab"] = _trace_overhead_ab()
    _stamp_overrides(result, ("PTD_SERVE_SIZE", "PTD_SERVE_SLOTS",
                              "PTD_SERVE_REQUESTS", "PTD_SERVE_RATE",
                              "PTD_SERVE_MAX_NEW", "PTD_SERVE_PAGED",
                              "PTD_SERVE_BLOCK", "PTD_SERVE_AB",
                              "PTD_SERVE_SPEC", "PTD_SPEC_K",
                              "PTD_SPEC_AB", "PTD_TRACE_AB",
                              "PTD_QUANT"))
    return result


def bench_specdraft() -> dict:
    """The ISSUE 16 learned-drafting claim, measured: the SAME seeded
    traffic.py trace served three times under spec_k=4 —

      * ``self``       — the draft IS the target (ISSUE 8's ceiling:
        acceptance ~1, tokens/forward ~ spec_k+1, but the draft forward
        costs as much as the target's, so the mechanism only);
      * ``truncated``  — inference.make_draft's free warm start (the
        target's first layers + zero-init proposal heads, UNTRAINED);
      * ``distilled``  — the same architecture after DistillTrainer
        runs KL-to-target distillation on a distill_corpus drawn from
        the same traffic generator (heads on, so one draft forward
        proposes the whole k-token window).

    Headline: the distilled draft's tokens_per_target_forward — a REAL
    (non-self) draft must clear 1.8x for learned drafting to beat the
    memory-bound baseline. Each leg stamps acceptance_rate,
    tokens_per_target_forward and decode tokens/s; the distilled leg
    additionally proves the serve loop stayed retrace-free while
    adaptive k varied (``recompiles`` must be 0). Knobs:
    PTD_SPECDRAFT_LAYERS (target depth), PTD_SPECDRAFT_DRAFT_LAYERS,
    PTD_SPECDRAFT_EPOCHS, PTD_SPECDRAFT_REQUESTS."""
    import os

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.inference import make_draft
    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import ServingEngine
    from pytorchdistributed_tpu.serving import engine as serving_engine
    from pytorchdistributed_tpu.serving.traffic import make_trace
    from pytorchdistributed_tpu.training import (
        DistillTrainer,
        distill_corpus,
    )

    num_layers = int(os.environ.get("PTD_SPECDRAFT_LAYERS", "4"))
    draft_layers = int(os.environ.get("PTD_SPECDRAFT_DRAFT_LAYERS", "1"))
    epochs = int(os.environ.get("PTD_SPECDRAFT_EPOCHS", "32"))
    n_requests = int(os.environ.get("PTD_SPECDRAFT_REQUESTS", "24"))
    spec_k = 4
    max_new = 32
    cfg = gpt2_config("test", num_layers=num_layers, max_seq_len=512,
                      quant=_quant_override())
    model = GPT2(cfg)

    # pre-train the target on a seeded successor-permutation language
    # (token t+1 = succ[token t]) before any leg runs: a RANDOM-init
    # target's upper layers barely move the residual stream, so the
    # truncated draft is trivially close to the teacher (initial KL
    # ~0.02 here) and distillation has nothing to learn but argmax
    # tie-breaking noise — a trained target makes depth do real work,
    # which is the regime learned drafting exists for
    import optax

    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    target_steps = int(os.environ.get("PTD_SPECDRAFT_TARGET_STEPS",
                                      "200"))
    succ = np.random.default_rng(11).permutation(cfg.vocab_size)

    def _rows(rng, n, s):
        out = np.empty((n, s), np.int32)
        out[:, 0] = rng.integers(0, cfg.vocab_size, n)
        for t in range(1, s):
            out[:, t] = succ[out[:, t - 1]]
        return out

    tr = Trainer(model, optax.adamw(3e-3), token_cross_entropy_loss,
                 log_every=10**9)
    rng_t = np.random.default_rng(5)

    def _lm_batch():
        rows = _rows(rng_t, 16, 128)
        return {"tokens": rows[:, :-1], "targets": rows[:, 1:]}

    tr.init(_lm_batch())
    m = None
    for _ in range(target_steps):
        m = tr.train_step(_lm_batch())
    target_ce = float(m["loss"])
    params = jax.device_get(tr.state.params)

    # the serve trace AND the distill corpus come from the same traffic
    # generator (different seeds): the student trains on the length/
    # content mix it will actually serve
    trace = make_trace(seed=29, duration_s=n_requests / 48.0 + 1.0,
                       base_qps=48.0, vocab_size=cfg.vocab_size,
                       prompt_cap=96, new_cap=max_new)[:n_requests]
    prompts = [np.asarray(r.prompt, np.int32) for r in trace]
    arrivals = np.asarray([r.at_s for r in trace])

    # distill the student: truncated warm start + proposal heads,
    # KL-to-target over a logged-traffic corpus
    corpus = distill_corpus(model, params, seed=7, num_batches=6,
                            batch_size=8, seq_len=96,
                            max_new_tokens=max_new)
    dt = DistillTrainer(model, params, num_layers=draft_layers,
                        spec_heads=spec_k - 1)
    dt.init(corpus[0])
    kl0 = kl1 = None
    for _ in range(epochs):
        for b in corpus:
            m = dt.train_step(b)
            if kl0 is None:
                kl0 = float(m["loss"])
    kl1 = float(m["loss"])
    distilled_cfg, distilled = dt.draft()
    warm_model, warm = make_draft(model, params, num_layers=draft_layers,
                                  spec_heads=spec_k - 1)
    warm_cfg = warm_model.cfg

    legs = (("self", None, None),
            ("truncated", warm_cfg, warm),
            ("distilled", distilled_cfg, distilled))
    out: dict = {}
    for name, dcfg, dparams in legs:
        engine = ServingEngine(model, params, num_slots=4,
                               prefill_bucket=128, block_size=16,
                               spec_k=spec_k, draft_config=dcfg,
                               draft_params=dparams,
                               adaptive_k=(name == "distilled"))
        engine.warmup(prompt_lens=(128,))
        traces0 = sum(dict(serving_engine.TRACE_COUNTS).values())
        s, _ = _drive_serve_trace(engine, prompts, arrivals, max_new)
        row = {
            "decode_tokens_per_s": s["decode_tokens_per_s"],
            "acceptance_rate": s.get("acceptance_rate"),
            "tokens_per_target_forward": s.get(
                "tokens_per_target_forward"),
            "draft_params_hash": s.get("draft_params_hash"),
        }
        if name == "distilled":
            row["recompiles"] = \
                sum(dict(serving_engine.TRACE_COUNTS).values()) - traces0
            row["accept_ema"] = s.get("accept_ema")
            row["effective_k"] = s.get("effective_k")
        out[name] = row
        engine.close()

    dist = out["distilled"]
    result = {"metric": "specdraft_tokens_per_target_forward",
              "value": dist["tokens_per_target_forward"],
              "unit": "tokens/target-forward",
              "spec_k": spec_k, "spec_heads": spec_k - 1,
              "target_layers": num_layers, "draft_layers": draft_layers,
              "distill_epochs": epochs,
              "target_pretrain_steps": target_steps,
              "target_pretrain_ce": round(target_ce, 5),
              "distill_kl_first": round(kl0, 5),
              "distill_kl_last": round(kl1, 5),
              "requests": n_requests, "max_new_tokens": max_new,
              **out}
    if (dist["tokens_per_target_forward"]
            and out["truncated"]["tokens_per_target_forward"]):
        result["distilled_vs_truncated"] = round(
            dist["tokens_per_target_forward"]
            / out["truncated"]["tokens_per_target_forward"], 3)
    _stamp_overrides(result, ("PTD_SPECDRAFT_LAYERS",
                              "PTD_SPECDRAFT_DRAFT_LAYERS",
                              "PTD_SPECDRAFT_EPOCHS",
                              "PTD_SPECDRAFT_TARGET_STEPS",
                              "PTD_SPECDRAFT_REQUESTS", "PTD_QUANT"))
    return result


def bench_kvcompress() -> dict:
    """The ISSUE 13 KV-compression claim, measured: the same bursty
    mixed-length trace served by a bf16-pool engine and an int8-pool
    engine (per-token-per-head fp32 scale planes INCLUDED in its byte
    count) at the SAME pool HBM budget — the int8 engine just gets the
    extra blocks the smaller tokens buy. Headline: the peak
    concurrently-resident stream ratio (>= ~1.9x is the geometric bound
    at head_dim 64: 2d / (d + 4) bytes per token-head), with the decode
    tokens/s ratio stamped beside it (the compressed tick must not give
    the capacity win back in rate; both engines tick the same slot
    batch, so >= 0.95x is the honesty bar, not a tautology). A
    sliding-window A/B rides along: one long stream decoded with
    sink+window retirement on vs off, stamping the high-water block
    footprint of each — the retired-middle-blocks win. Knobs:
    PTD_KVC_BLOCK / PTD_KVC_REQUESTS; PTD_KVC_WINDOW=0 skips the
    window leg."""
    import os

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import ServingEngine

    block = int(os.environ.get("PTD_KVC_BLOCK", "16"))
    # enough requests that the INT8 engine's larger capacity stays
    # backlogged too — a short queue lets it idle below capacity and
    # dilutes the ratio toward 1
    n = int(os.environ.get("PTD_KVC_REQUESTS", "64"))
    slots = int(os.environ.get("PTD_KVC_SLOTS", "40"))
    # head_dim 64: the committed serving models' head geometry, and the
    # regime where the fp32 scale plane costs 1/16th of the codes
    cfg = gpt2_config("test", num_layers=2, embed_dim=256, num_heads=4,
                      max_seq_len=256, quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    pages = cfg.max_seq_len // block
    base_blocks = 4 * pages + 1  # the shared HBM budget, in bf16 blocks

    def build(kv_dtype, num_blocks, **kw):
        return ServingEngine(model, params, num_slots=slots,
                             prefill_bucket=64, block_size=block,
                             num_blocks=num_blocks, kv_dtype=kv_dtype,
                             **kw)

    # price one int8 block (codes + scale planes) off a probe pool, then
    # give the int8 engine exactly the bf16 budget's worth of them
    probe = build("int8", base_blocks)
    int8_per_block = probe.kv_hbm_bytes // base_blocks
    probe.close()

    rng = np.random.default_rng(17)
    # trace shape matters: each stream's WHOLE life (prompt + 48 new
    # tokens <= the 64-token admission span) fits the blocks its
    # admission allocates, so no stream ever grows mid-decode and the
    # pool never preempts — sustained residency is then purely
    # pool-bound (capacity / 4 blocks per stream) instead of being
    # smeared by growth-preemption churn, and streams live long enough
    # (48 ticks) that the one-admission-per-step pipeline is not the
    # binding constraint at either engine's capacity
    lens = rng.integers(9, 17, n)
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in lens]
    arrivals = np.cumsum(rng.exponential(1.0 / 64.0, n))  # near-burst

    # blocks one admission claims (span = one prefill bucket)
    need = 64 // block

    def drive(eng):
        """_drive_serve_trace, plus a residency mean taken only over
        POOL-SATURATED steps (requests waiting AND too few free blocks
        to admit one): the phase where the pool is the binding
        constraint. The all-steps mean includes the ramp-up and tail
        -drain, which look the same at any capacity and drag both
        engines toward each other."""
        t0 = time.perf_counter()
        pend = list(zip(arrivals, prompts))
        peak = sat_steps = sat_sum = 0
        while (pend or eng.queue_depth or eng.active_count
               or eng.prefilling_count):
            now = time.perf_counter() - t0
            while pend and pend[0][0] <= now:
                eng.submit(pend.pop(0)[1], max_new_tokens=48)
            if (eng.queue_depth or eng.active_count
                    or eng.prefilling_count):
                free = round(eng.health()["pool_free_frac"]
                             * (eng.num_blocks - 1))
                if eng.queue_depth and free < need:
                    sat_steps += 1
                    sat_sum += eng.active_count
                eng.step()
                peak = max(peak, eng.active_count)
            elif pend:
                time.sleep(min(0.01, max(0.0, pend[0][0] - now)))
        sat = round(sat_sum / sat_steps, 2) if sat_steps else None
        return eng.summary(), peak, sat, sat_steps

    out = {}
    for name, kv_dtype in (("bf16", "bf16"), ("int8", "int8")):
        if name == "bf16":
            nb = base_blocks
            eng = build(kv_dtype, nb)
            budget = eng.kv_hbm_bytes
        else:
            nb = max(pages + 1, int(budget // int8_per_block))
            eng = build(kv_dtype, nb)
        eng.warmup(prompt_lens=(64,))
        s, peak, sat, sat_steps = drive(eng)
        eng.close()
        out[name] = {"kv_hbm_bytes": s["kv_hbm_bytes"],
                     "num_blocks": nb,
                     "peak_resident": peak,
                     "saturated_resident": sat,
                     "saturated_steps": sat_steps,
                     "mean_resident": round(
                         (s["slot_occupancy"] or 0) * slots, 2),
                     "kv_bytes_resident": s["kv_bytes_resident"],
                     "kv_tokens_capacity": s["kv_tokens_capacity"],
                     "decode_tokens_per_s": s["decode_tokens_per_s"],
                     "preemptions": s["preemptions"]}
    b, i = out["bf16"], out["int8"]
    # SATURATED residency: mean resident streams while demand exceeds
    # the pool — the capacity a tier can actually sell. (The all-steps
    # mean and the instantaneous peak are stamped alongside.)
    resident_ratio = round((i["saturated_resident"] or 0)
                           / max(1e-9, b["saturated_resident"] or 0), 2)
    decode_ratio = (round(i["decode_tokens_per_s"]
                          / b["decode_tokens_per_s"], 3)
                    if b["decode_tokens_per_s"]
                    and i["decode_tokens_per_s"] else None)

    result = {"metric": "kvcompress_resident_ratio",
              "value": resident_ratio, "unit": "x",
              "decode_tokens_per_s_ratio": decode_ratio,
              "bf16": b, "int8": i,
              "block_size": block, "requests": n}

    if os.environ.get("PTD_KVC_WINDOW", "1") != "0":
        # sliding-window retirement on one long stream: high-water
        # block count with sink+window vs full attention — the
        # footprint claim (outputs differ by design; the window IS a
        # different attention pattern)
        long_prompt = rng.integers(0, cfg.vocab_size, (32,)).astype(
            np.int32)
        wout = {}
        for name, kw in (("full", {}),
                         ("windowed", dict(kv_sink_tokens=block,
                                           kv_window_tokens=4 * block))):
            eng = ServingEngine(model, params, num_slots=2,
                                prefill_bucket=64, block_size=block,
                                num_blocks=base_blocks, kv_dtype="int8",
                                **kw)
            eng.warmup(prompt_lens=(64,))
            r = eng.submit(long_prompt, max_new_tokens=200)
            while not r.done:
                eng.step()
            s = eng.summary()
            eng.close()
            wout[name] = {"peak_blocks_used": s["peak_blocks_used"],
                          "retired_blocks": s["retired_blocks"]}
        wout["footprint_ratio"] = round(
            wout["full"]["peak_blocks_used"]
            / max(1, wout["windowed"]["peak_blocks_used"]), 2)
        result["window_ab"] = wout

    _stamp_overrides(result, ("PTD_KVC_BLOCK", "PTD_KVC_REQUESTS",
                              "PTD_KVC_SLOTS", "PTD_KVC_WINDOW",
                              "PTD_QUANT"))
    return result


def _drive_router_trace(router, prompts, arrivals, max_new,
                        on_step=None) -> list:
    """Feed a seeded arrival trace to a ReplicaRouter in wall-clock time
    and drain it; returns the request handles (shed ones included — the
    shed rate is part of the measurement). ``on_step(router, reqs)``
    runs once per loop iteration — the failover leg injects its
    mid-trace kill there without duplicating the pacing logic."""
    from pytorchdistributed_tpu.serving.router import DEAD

    t0 = time.perf_counter()
    pending = list(zip(arrivals, prompts))
    reqs = []
    while pending or router.queue_depth or router.in_flight:
        if all(s == DEAD for s in router._status):
            break  # whole fleet lost (1-replica kill leg): don't spin
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, p = pending.pop(0)
            reqs.append(router.submit(p, max_new_tokens=max_new))
        if on_step is not None:
            on_step(router, reqs)
        if router.queue_depth or router.in_flight:
            router.step()
        elif pending:
            time.sleep(min(0.01, max(0.0, pending[0][0] - now)))
    return reqs


def bench_router() -> dict:
    """Replicated serving (serving/ReplicaRouter, ISSUE 9): a seeded
    Poisson trace over N in-process replicas, measured three ways.

    1. BALANCE: the main trace runs with no faults; the stamp is the
       per-replica mean-occupancy spread (max - min) — the
       telemetry-driven dispatch should keep replicas within a few
       occupancy points of each other.
    2. FAILOVER: the same trace re-runs, and replica 0 is crashed once
       PTD_ROUTER_KILL_FRAC of the requests have completed AND it holds
       streams mid-flight; the stamp is ``failover_recovery_ticks`` /
       ``_s`` (kill → every redispatched request streaming again) plus
       the redispatch count, and ``unfinished_after_failover``
       asserts-by-stamping (must be 0) that every request still
       completed.
    3. OVERLOAD: a burst of 2x the fleet's instantaneous capacity
       (resident slots + dispatchable pending + the PTD_ROUTER_QUEUE
       bound) lands at once; the stamps are ``shed_rate`` (substantial
       — that's admission control working) and the ``ttft_ms_p99`` of
       ADMITTED requests (bounded by construction instead of growing
       with the line).

    Knobs: PTD_ROUTER_{REPLICAS,SLOTS,REQUESTS,RATE,MAX_NEW,KILL_FRAC,
    QUEUE}; PTD_QUANT rides the model config like every serving bench.
    """
    import os

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import ReplicaRouter
    from pytorchdistributed_tpu.serving import engine as serving_engine

    n_replicas = int(os.environ.get("PTD_ROUTER_REPLICAS", "2"))
    num_slots = int(os.environ.get("PTD_ROUTER_SLOTS", "4"))
    n_requests = int(os.environ.get("PTD_ROUTER_REQUESTS", "24"))
    rate = float(os.environ.get("PTD_ROUTER_RATE", "16.0"))
    max_new = int(os.environ.get("PTD_ROUTER_MAX_NEW", "16"))
    kill_frac = float(os.environ.get("PTD_ROUTER_KILL_FRAC", "0.33"))
    max_queue = int(os.environ.get("PTD_ROUTER_QUEUE", "6"))
    cfg = gpt2_config("test", num_layers=2, max_seq_len=256,
                      quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    rng = np.random.default_rng(17)
    lens = rng.integers(8, 49, n_requests)
    prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
               for m in lens]
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    ek = dict(num_slots=num_slots, prefill_bucket=64)

    def build(**kw):
        # every leg is a CONTROLLED measurement: chaos only ever comes
        # from the leg's own kill, never an ambient PTD_FAULTS spec
        kw.setdefault("faults", None)
        r = ReplicaRouter(model, params, replicas=n_replicas,
                          engine_kwargs=ek, warmup_lens=(64,), **kw)
        r.warmup()
        return r

    # -- leg 1: balance --------------------------------------------------
    router = build()
    traces0 = dict(serving_engine.TRACE_COUNTS)
    _drive_router_trace(router, prompts, arrivals, max_new)
    s1 = router.summary()
    recompiles = (sum(serving_engine.TRACE_COUNTS.values())
                  - sum(traces0.values()))
    router.close()

    # -- leg 2: mid-trace kill ------------------------------------------
    # the kill fires once the victim is genuinely mid-stream: after
    # kill_frac of the trace has completed AND replica 0 holds work —
    # killing an idle replica would stamp a recovery of nothing
    router = build()
    killed = [False]

    def kill_mid_trace(r, reqs):
        done = sum(1 for q in reqs if q.done)
        if (not killed[0] and done >= kill_frac * n_requests
                and r._assigned[0]):
            r._replicas[0].apply_fault("replica_crash")
            killed[0] = True

    reqs = _drive_router_trace(router, prompts, arrivals, max_new,
                               on_step=kill_mid_trace)
    s2 = router.summary()
    router.close()
    unfinished = sum(1 for r in reqs
                     if r.finish_reason not in ("length", "stop", "shed"))

    # -- leg 3: 2x overload, bounded queue ------------------------------
    # a burst of 2x what the fleet can hold at once (resident slots +
    # dispatchable pending + the bounded queue): the shed rate IS the
    # admission control working, and the admitted requests' TTFT p99
    # stays bounded by construction instead of growing with the line
    capacity = n_replicas * (num_slots + 1) + max_queue
    n_over = 2 * capacity
    over_prompts = [rng.integers(0, cfg.vocab_size, (m,)).astype(np.int32)
                    for m in rng.integers(8, 49, n_over)]
    router = build(max_queue=max_queue)
    _drive_router_trace(router, over_prompts, np.zeros(n_over), max_new)
    s3 = router.summary()
    router.close()

    result = {
        "metric": "router_failover_recovery_ticks",
        "value": s2["failover_recovery_ticks"], "unit": "ticks",
        "failover_recovery_s": s2["failover_recovery_s"],
        "redispatched_requests": s2["redispatched_requests"],
        "failovers": s2["failovers"],
        "unfinished_after_failover": unfinished,  # must stamp 0
        "replicas": n_replicas, "num_slots": num_slots,
        "requests": n_requests, "arrival_rate_per_s": rate,
        "occupancy_spread": s1["occupancy_spread"],
        "replica_occupancy": s1["replica_occupancy"],
        "served_by": s1["served_by"],
        "recompiles": recompiles,
        "ttft_ms_p50": s1.get("ttft_ms_p50"),
        "ttft_ms_p99": s1.get("ttft_ms_p99"),
        "overload": {
            "burst": n_over, "capacity": capacity,
            "max_queue": max_queue,
            "shed_rate": s3["shed_rate"],
            "shed_requests": s3["shed_requests"],
            "admitted_ttft_ms_p99": s3.get("ttft_ms_p99"),
        },
    }
    _stamp_overrides(result, ("PTD_ROUTER_REPLICAS", "PTD_ROUTER_SLOTS",
                              "PTD_ROUTER_REQUESTS", "PTD_ROUTER_RATE",
                              "PTD_ROUTER_MAX_NEW",
                              "PTD_ROUTER_KILL_FRAC", "PTD_ROUTER_QUEUE",
                              "PTD_QUANT"))
    return result


def bench_autoscale() -> dict:
    """Autoscaled-vs-static A/B (ISSUE 15): the SAME seeded flash-crowd
    trace over a hot(10x)/calm tenant mix, served two ways at identical
    engine geometry and queue bound:

      * ``static``     — the fleet pinned at 1 replica (the pre-ISSUE-15
        shape: whatever the crowd oversubscribes, the queue cap sheds);
      * ``autoscaled`` — the same 1-replica baseline plus the SLO
        control loop: sustained queue-depth breaches warm-join replicas
        into the crowd (in-process joins share the jit cache — the leg
        stamps ``recompiles`` = fresh XLA traces after warmup, must be
        0), and the drain-down after the crowd removes them gracefully.

    Both legs replay on the traffic harness's FakeClock (zero wall-clock
    sleeps: replay speed is whatever the engines can step), so arrivals
    are byte-identical across legs and runs. Stamps per leg: SLO
    attainment (completed / submitted — a shed request IS the SLO miss
    under a bounded queue), per-tenant shed split (calm must stamp 0 in
    both legs: weighted shedding never touches a compliant tenant),
    mean/peak healthy replicas over the replay (``replicas_per_qps`` =
    mean replicas / offered QPS — the capacity-efficiency stamp), and
    for the autoscaled leg the scale-up/-down counts plus the
    decision -> first-token ``reaction_s``. The headline metric is the
    autoscaled leg's attainment; ``attainment_delta`` (autoscaled -
    static) must stamp >= 0.

    Knobs: PTD_AUTO_{QPS,PEAK,DURATION,SLOTS,QUEUE,MAX_REPLICAS};
    PTD_QUANT rides the model config like every serving bench.
    """
    import os

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import (
        Autoscaler,
        FakeClock,
        ReplicaRouter,
        SLOConfig,
        TenantConfig,
        TenantTraffic,
        make_trace,
        replay,
    )
    from pytorchdistributed_tpu.serving import engine as serving_engine

    base_qps = float(os.environ.get("PTD_AUTO_QPS", "5.0"))
    peak_mult = float(os.environ.get("PTD_AUTO_PEAK", "30.0"))
    duration_s = float(os.environ.get("PTD_AUTO_DURATION", "4.0"))
    num_slots = int(os.environ.get("PTD_AUTO_SLOTS", "4"))
    max_queue = int(os.environ.get("PTD_AUTO_QUEUE", "8"))
    max_replicas = int(os.environ.get("PTD_AUTO_MAX_REPLICAS", "3"))
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128,
                      quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    trace = make_trace(
        seed=11, duration_s=duration_s, base_qps=base_qps, shape="flash",
        peak_mult=peak_mult, flash_at_s=duration_s / 4.0,
        flash_len_s=duration_s * 0.375,
        tenants=(TenantTraffic("hot", share=10.0),
                 TenantTraffic("calm", share=1.0)),
        vocab_size=cfg.vocab_size, prompt_cap=24, new_cap=8)
    qps_offered = len(trace) / duration_s

    def build(replicas):
        r = ReplicaRouter(
            model, params, replicas=replicas,
            engine_kwargs=dict(num_slots=num_slots, prefill_bucket=32),
            warmup_lens=(32,), max_queue=max_queue, faults=None,
            tenants={"hot": TenantConfig(weight=1.0),
                     "calm": TenantConfig(weight=1.0)})
        r.warmup()
        return r

    def run(router, autoscaler=None, clock=None):
        fleet = []  # healthy-count samples, one per replay tick

        def sample(ticks, clk):
            fleet.append(router.pool_state()["fleet"]["healthy"])

        replay(router, trace, clock=clock or FakeClock(), tick_s=0.02,
               autoscaler=autoscaler, on_tick=sample)
        s = router.summary()
        tens = s["tenants"]
        p99s = [t["ttft_ms_p99"] for t in tens.values()
                if t.get("ttft_ms_p99") is not None]
        return {
            "slo_attainment": (round(s["completed"] / s["submitted"], 4)
                               if s["submitted"] else None),
            "submitted": s["submitted"], "completed": s["completed"],
            "shed_requests": s["shed_requests"],
            "shed_by_tenant": {n: t["shed"] for n, t in tens.items()},
            "ttft_ms_p99_by_tenant": {
                n: t.get("ttft_ms_p99") for n, t in tens.items()},
            "tenant_p99_spread_ms": (round(max(p99s) - min(p99s), 3)
                                     if len(p99s) > 1 else None),
            "replicas_mean": round(float(np.mean(fleet)), 3),
            "replicas_peak": int(max(fleet)),
            "replicas_per_qps": round(
                float(np.mean(fleet)) / qps_offered, 4),
        }

    # -- leg 1: static at baseline --------------------------------------
    router = build(1)
    static = run(router)
    router.close()

    # -- leg 2: autoscaled from the same baseline -----------------------
    router = build(1)
    clk = FakeClock()
    # TTFT is wall-clock (not fake-clock) — neutralized so CPU step
    # timing isn't a control input; queue depth is the breach signal
    asc = Autoscaler(router,
                     SLOConfig(queue_high=3.0, occupancy_high=0.9,
                               occupancy_low=0.5, shed_rate_max=1.0,
                               ttft_target_ms=1e9),
                     min_replicas=1, max_replicas=max_replicas,
                     breach_ticks=2, clear_ticks=25, up_cooldown_s=0.3,
                     down_cooldown_s=0.2, clock=clk)
    traces0 = dict(serving_engine.TRACE_COUNTS)
    auto = run(router, autoscaler=asc, clock=clk)
    # keep ticking the idle fleet past the crowd: the graceful
    # drain-down back to baseline is part of the measurement
    for _ in range(3000):
        router.step()
        asc.step()
        clk.advance(0.02)
        if (router.pool_state()["fleet"]["healthy"] == 1
                and router.pool_state()["fleet"]["draining"] == 0):
            break
    recompiles = (sum(serving_engine.TRACE_COUNTS.values())
                  - sum(traces0.values()))
    asum = asc.summary()
    auto.update(scale_ups=asum["scale_ups"],
                scale_downs=asum["scale_downs"],
                drained_to_baseline=(
                    router.pool_state()["fleet"]["healthy"] == 1),
                reaction_s_mean=asum["reaction_s_mean"],
                reaction_s_max=asum["reaction_s_max"],
                recompiles=recompiles)
    router.close()

    result = {
        "metric": "autoscale_slo_attainment",
        "value": auto["slo_attainment"], "unit": "frac",
        "attainment_delta": round(auto["slo_attainment"]
                                  - static["slo_attainment"], 4),
        "trace": {"seed": 11, "shape": "flash", "requests": len(trace),
                  "base_qps": base_qps, "peak_mult": peak_mult,
                  "duration_s": duration_s,
                  "qps_offered": round(qps_offered, 2)},
        "num_slots": num_slots, "max_queue": max_queue,
        "max_replicas": max_replicas,
        "autoscaled": auto, "static": static,
    }
    _stamp_overrides(result, ("PTD_AUTO_QPS", "PTD_AUTO_PEAK",
                              "PTD_AUTO_DURATION", "PTD_AUTO_SLOTS",
                              "PTD_AUTO_QUEUE", "PTD_AUTO_MAX_REPLICAS",
                              "PTD_QUANT"))
    return result


def bench_sessions() -> dict:
    """Persistent sessions + the tiered KV hierarchy (ISSUE 18).

    Two measurements on the suite-shared test geometry:

      * ``reattach_ab`` — the headline A/B: N long-history sessions
        parked in the store's host-DRAM tier, each resumed on a FRESH
        engine two ways at identical geometry — ``session_id=`` reattach
        (seed the saved blocks, prefill only the new user tokens + the
        partial tail block) vs the full-history re-prefill a sessionless
        server pays. Every session's tokens are distinct so the
        re-prefill leg can't ride radix reuse — it measures the
        KV-is-gone path, which is exactly what reattach replaces.
        Headline = p50 TTFT ratio (re-prefill / reattach; > 1 =
        sessions win, acceptance floor 3x). Both legs step the same
        compiled programs; ``recompiles`` (fresh XLA traces after
        warmup, across ALL legs) must stamp 0.
      * ``fleet`` — the satellite-1 multi-turn conversation mix
        (seeded think-time gaps) replayed through a 2-replica sessioned
        router on the fake clock: stamps per-tier reattach counts,
        fallbacks, demote sweeps and the store's tier occupancy.

    ``sessions_per_gb`` derives capacity per tier from the measured
    mean payload size: host-DRAM and disk hold the wire payload
    (int8-aware — PTD_QUANT=int8 shrinks it ~2x), HBM holds the raw
    resident blocks. Knobs: PTD_SESS_{N,HIST,NEW,SLOTS,BLOCK,SEQ};
    PTD_QUANT rides the model config like every serving bench."""
    import os
    import time

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import (
        ReplicaRouter,
        ServingEngine,
        SessionStore,
        make_conversations,
        replay_conversations,
    )
    from pytorchdistributed_tpu.serving import engine as serving_engine

    n_sessions = int(os.environ.get("PTD_SESS_N", "12"))
    hist_len = int(os.environ.get("PTD_SESS_HIST", "224"))
    new_len = int(os.environ.get("PTD_SESS_NEW", "8"))
    num_slots = int(os.environ.get("PTD_SESS_SLOTS", "4"))
    block = int(os.environ.get("PTD_SESS_BLOCK", "8"))
    seq = int(os.environ.get("PTD_SESS_SEQ", "256"))
    cfg = gpt2_config("test", num_layers=2, max_seq_len=seq,
                      quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    kv_dtype = "int8" if cfg.quant == "int8" else None
    ekw = dict(num_slots=num_slots, prefill_bucket=16, block_size=block)
    if kv_dtype:
        ekw["kv_dtype"] = kv_dtype

    def build(store=None, hbm_max=4):
        e = ServingEngine(model, params, session_store=store,
                          session_hbm_max=hbm_max, **ekw)
        e.warmup(prompt_lens=(16, 32))
        e.warmup_kv_stream()
        return e

    rng = np.random.default_rng(18)
    hists = [rng.integers(1, cfg.vocab_size, hist_len).astype(np.int32)
             for _ in range(n_sessions)]
    news = [rng.integers(1, cfg.vocab_size, new_len).astype(np.int32)
            for _ in range(n_sessions)]

    # -- park N sessions into the store's DRAM tier ---------------------
    store = SessionStore(None, dram_bytes=1 << 30)
    builder = build(store=store, hbm_max=1)  # each park demotes the last
    traces0 = dict(serving_engine.TRACE_COUNTS)
    resumes = []
    for i, hist in enumerate(hists):
        h = builder.submit(hist, max_new_tokens=4,
                           session_id=f"sess-{i}")
        builder.run_until_idle()
        resumes.append(np.concatenate(
            [hist, np.asarray(h.new_tokens, np.int32), news[i]]))
    sess_summary = builder.summary()["sessions"]
    hbm_bytes_per = (sess_summary["resident_bytes"]
                     / max(sess_summary["resident"], 1))
    builder.close()
    payload_bytes = [store._dram[f"sess-{i}"].payload.nbytes
                     for i in range(n_sessions)
                     if f"sess-{i}" in store._dram]

    # -- A/B: reattach vs full re-prefill on fresh engines --------------
    def ttft(engine, prompt, **kw):
        t0 = time.perf_counter()
        h = engine.submit(prompt, max_new_tokens=4, **kw)
        while not h.new_tokens and not h.done:
            engine.step()
        dt = time.perf_counter() - t0
        engine.run_until_idle()
        return dt * 1e3

    reattach_e = build(store=store, hbm_max=n_sessions + 1)
    reprefill_e = build()
    re_ms, full_ms = [], []
    for i, prompt in enumerate(resumes):
        re_ms.append(ttft(reattach_e, prompt, session_id=f"sess-{i}"))
        full_ms.append(ttft(reprefill_e, prompt))
    seeded_tokens = reattach_e.summary()["sessions"]["seed_tokens"]
    reattach_e.close()
    reprefill_e.close()
    store_stats = store.stats()
    store.close()
    p50_re = float(np.percentile(re_ms, 50))
    p50_full = float(np.percentile(full_ms, 50))

    # -- fleet leg: the multi-turn conversation mix ---------------------
    convs = make_conversations(seed=18, duration_s=6.0,
                               session_rate=0.8,
                               vocab_size=cfg.vocab_size,
                               turns_cap=4, turn_cap=12, new_cap=6,
                               think_mean_s=0.3)
    fstore = SessionStore(None, dram_bytes=1 << 30)
    router = ReplicaRouter(
        model, params, replicas=2,
        engine_kwargs=dict(session_hbm_max=2, **ekw),
        warmup_lens=(16, 32), session_store=fstore, faults=None)
    router.warmup()
    out = replay_conversations(router, convs, tick_s=0.02,
                               max_seq_len=cfg.max_seq_len)
    fsum = router.summary()["sessions"]
    router.close()
    fstore.close()
    recompiles = (sum(serving_engine.TRACE_COUNTS.values())
                  - sum(traces0.values()))

    mean_payload = float(np.mean(payload_bytes)) if payload_bytes else 0
    result = {
        "metric": "session_reattach_ttft_speedup_p50",
        "value": round(p50_full / p50_re, 2) if p50_re else None,
        "unit": "x (re-prefill / reattach; > 1 = sessions win)",
        "reattach_ab": {
            "sessions": n_sessions, "history_tokens": hist_len,
            "new_tokens_per_turn": new_len,
            "reattach_ttft_ms_p50": round(p50_re, 3),
            "reprefill_ttft_ms_p50": round(p50_full, 3),
            "reattach_ttft_ms_p99": round(
                float(np.percentile(re_ms, 99)), 3),
            "reprefill_ttft_ms_p99": round(
                float(np.percentile(full_ms, 99)), 3),
            "seeded_tokens": seeded_tokens,
            "store_hits_dram": store_stats["hits_dram"],
        },
        "sessions_per_gb": {
            "payload_bytes_mean": round(mean_payload),
            "dram_or_disk": (round(1e9 / mean_payload)
                             if mean_payload else None),
            "hbm_resident_bytes_per_session": round(hbm_bytes_per),
            "hbm": (round(1e9 / hbm_bytes_per)
                    if hbm_bytes_per else None),
        },
        "fleet": {
            "conversations": len(convs),
            "turns": sum(len(v) for v in out.values()),
            "reattach": fsum["reattach"],
            "fallbacks": fsum["fallbacks"],
            "demotes": fsum["demotes"],
            "ships": fsum["ships"],
        },
        "num_slots": num_slots, "block_size": block,
        "max_seq_len": seq, "kv_dtype": kv_dtype or "bf16",
        "recompiles": recompiles,
    }
    _stamp_overrides(result, ("PTD_SESS_N", "PTD_SESS_HIST",
                              "PTD_SESS_NEW", "PTD_SESS_SLOTS",
                              "PTD_SESS_BLOCK", "PTD_SESS_SEQ",
                              "PTD_QUANT"))
    return result


def _trace_overhead_ab() -> dict:
    """Request-tracing on/off A/B (ISSUE 17 satellite): the SAME seeded
    traffic.py trace replayed through two identical warmed in-process
    disagg fleets — telemetry dir present in BOTH legs so the only
    delta is the tracer — stamping ``trace_overhead_frac`` (min-wall
    on / min-wall off - 1), which must land < 0.01: a span is one dict
    + one line-buffered host write, invisible next to the jit work."""
    import os
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import (
        ROLE_DECODE,
        ROLE_PREFILL,
        FakeClock,
        ReplicaRouter,
        TenantTraffic,
        make_trace,
        replay,
    )
    from pytorchdistributed_tpu.telemetry.tracing import critical_paths, \
        read_trace

    reps = int(os.environ.get("PTD_TRACE_AB_REPS", "8"))
    n_target = int(os.environ.get("PTD_TRACE_AB_REQUESTS", "36"))
    cfg = gpt2_config("test", num_layers=2, max_seq_len=128,
                      quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    traffic = make_trace(
        seed=17, duration_s=n_target / 18.0, base_qps=18.0,
        shape="steady",
        tenants=(TenantTraffic("hot", share=3.0),
                 TenantTraffic("calm", share=1.0)),
        vocab_size=cfg.vocab_size, prompt_cap=24, new_cap=8)

    def build(trace_on: bool):
        d = tempfile.mkdtemp(prefix="ptd_trace_ab_")
        router = ReplicaRouter(
            model, params, replicas=2,
            roles=(ROLE_PREFILL, ROLE_DECODE),
            engine_kwargs=dict(num_slots=4, prefill_bucket=32,
                               block_size=16),
            warmup_lens=(32,), faults=None,
            telemetry_dir=d, trace=trace_on)
        router.warmup()
        # one untimed replay pays jit compiles + warms every host path
        replay(router, traffic, clock=FakeClock(), tick_s=0.02)
        return router, d

    def timed(router) -> float:
        # one SAMPLE = three back-to-back replays: a single replay is
        # short enough (~0.3 s) that scheduler jitter alone is ±1-2%,
        # the same order as the bar being measured
        t0 = time.perf_counter()
        for _ in range(3):
            replay(router, traffic, clock=FakeClock(), tick_s=0.02)
        return time.perf_counter() - t0

    # PERSISTENT fleets (one per leg, warmed once) so router
    # construction/warmup jitter never enters the timing; then
    # INTERLEAVED timed replays (off, on, off, on, ...) so clock drift
    # / machine noise hits both legs evenly; min-of-reps is the
    # comparison — it converges on each leg's floor, where the only
    # remaining delta is the tracer itself
    r_off, d_off = build(False)
    r_on, d_on = build(True)
    off_s = on_s = None
    # GC pinned out of the timed region (identically for both legs):
    # in a process that has already run a full bench leg, a gen-2
    # collection landing inside one replay costs more than the tracer
    # does in total, which would swamp a < 1% comparison with
    # collector-scheduling noise
    import gc
    gc.collect()
    gc.disable()
    try:
        for i in range(reps):
            # alternate which leg goes first so slow drift (thermal,
            # background load ramps) cancels instead of biasing one leg
            legs = ((r_off, False), (r_on, True))
            for router, is_on in (legs if i % 2 == 0 else legs[::-1]):
                w = timed(router)
                if is_on:
                    on_s = w if on_s is None else min(on_s, w)
                else:
                    off_s = w if off_s is None else min(off_s, w)
    finally:
        gc.enable()
    r_off.close()
    r_on.close()
    paths = critical_paths(read_trace(d_on))
    out = {
        "requests": len(traffic), "reps": reps,
        "off_wall_s": round(off_s, 4), "on_wall_s": round(on_s, 4),
        "trace_overhead_frac": round(on_s / off_s - 1.0, 4),
        "traced_requests": len(paths),
        "connected": sum(p["connected"] for p in paths),
    }
    shutil.rmtree(d_off, ignore_errors=True)
    shutil.rmtree(d_on, ignore_errors=True)
    return out


def bench_disagg() -> dict:
    """Disaggregated serving A/B (ISSUE 12): the SAME bursty
    shared-prefix trace (one hot system prompt + unique tails, arriving
    in two near-simultaneous bursts — the chat-frontend worst case where
    long prefills stall resident decodes) served two ways at identical
    fleet size and HBM:

      * ``colocated``    — every replica role 'both' (the PR 9 shape);
      * ``disaggregated``— prefill-role replicas chunk-prefill and hand
        KV blocks to a decode-role replica over the KV stream, with the
        fleet prefix index steering siblings onto cached blocks (and
        shipping them on a remote hit).

    Stamps per leg: TTFT p50/p99 (queue wait included), decode
    tokens/s (mean over replicas that decoded), fleet-total
    prefill_chunks (the "shared prefix prefilled once per fleet" claim
    — fewer chunks at equal traffic), prefix/cross-replica hit rates,
    handoff + prefix-ship counters and kv_stream_bytes, plus the
    recompile tripwire (must stamp 0 — handoffs reuse the warmed KV
    stream programs). The headline is the disagg-vs-colocated TTFT p99
    ratio. PTD_DISAGG_AB=0 skips the colocated twin (stamps the disagg
    leg alone). Knobs: PTD_DISAGG_{PREFILL,DECODE,SLOTS,REQUESTS,
    MAX_NEW,BLOCK,PREFIX_LEN}; PTD_QUANT rides the model config.
    PTD_TRACE=1 runs both legs with request tracing on; PTD_TRACE_AB=1
    adds the tracing-cost twin (``trace_ab.trace_overhead_frac``)."""
    import os
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.serving import (
        ROLE_BOTH,
        ROLE_DECODE,
        ROLE_PREFILL,
        ReplicaRouter,
    )
    from pytorchdistributed_tpu.serving import engine as serving_engine

    n_prefill = int(os.environ.get("PTD_DISAGG_PREFILL", "2"))
    n_decode = int(os.environ.get("PTD_DISAGG_DECODE", "1"))
    num_slots = int(os.environ.get("PTD_DISAGG_SLOTS", "3"))
    n_requests = int(os.environ.get("PTD_DISAGG_REQUESTS", "18"))
    max_new = int(os.environ.get("PTD_DISAGG_MAX_NEW", "16"))
    block = int(os.environ.get("PTD_DISAGG_BLOCK", "16"))
    prefix_len = int(os.environ.get("PTD_DISAGG_PREFIX_LEN", "96"))
    cfg = gpt2_config("test", num_layers=2, max_seq_len=256,
                      quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(jax.random.key(0),
                                 jnp.zeros((1, 8), jnp.int32))
    rng = np.random.default_rng(23)
    system = rng.integers(0, cfg.vocab_size, (prefix_len,)).astype(np.int32)
    prompts = [np.concatenate([
        system, rng.integers(0, cfg.vocab_size, (8,)).astype(np.int32)])
        for _ in range(n_requests)]
    # one LEADER request warms the shared prefix on a single replica,
    # then two bursts — not a Poisson trickle: the second wave lands
    # while the first is still decoding, exactly the prefill/decode
    # interference disaggregation is supposed to remove. The stagger is
    # what makes fleet prefix reuse observable: with an all-at-once
    # burst every replica prefills the prefix itself before any
    # frontier publishes, and no steering or shipping can happen
    arrivals = np.concatenate([
        [0.0],
        np.full((n_requests - 1) // 2, 0.4),
        np.full(n_requests - 1 - (n_requests - 1) // 2, 0.65)])
    ek = dict(num_slots=num_slots, prefill_bucket=64, block_size=block,
              prefill_chunk=64)

    def leg(roles) -> dict:
        # PTD_TRACE=1 runs the leg with request tracing on (its own
        # scratch telemetry dir) and stamps the traced/connected counts
        # next to the serving numbers
        tracing_on = os.environ.get("PTD_TRACE", "0").lower() in (
            "1", "true", "yes", "on")
        tdir = tempfile.mkdtemp(prefix="ptd_disagg_trace_") \
            if tracing_on else None
        router = ReplicaRouter(model, params, replicas=len(roles),
                               roles=roles, engine_kwargs=ek,
                               warmup_lens=(64,), faults=None,
                               telemetry_dir=tdir)
        router.warmup()
        traces0 = dict(serving_engine.TRACE_COUNTS)
        reqs = _drive_router_trace(router, list(prompts),
                                   arrivals.copy(), max_new)
        recompiles = (sum(serving_engine.TRACE_COUNTS.values())
                      - sum(traces0.values()))
        s = router.summary()
        engines = [r.engine.summary() for r in router._replicas]
        router.close()
        trace_stats = None
        if tracing_on:
            from pytorchdistributed_tpu.telemetry.tracing import (
                critical_paths,
                read_trace,
            )

            paths = critical_paths(read_trace(tdir))
            trace_stats = {"traced_requests": len(paths),
                           "connected": sum(p["connected"]
                                            for p in paths)}
            shutil.rmtree(tdir, ignore_errors=True)
        decoded = [e["decode_tokens_per_s"] for e in engines
                   if e.get("decode_tokens_per_s")]
        unfinished = sum(1 for q in reqs
                         if q.finish_reason not in ("length", "stop"))
        return {
            "roles": roles,
            "ttft_ms_p50": s.get("ttft_ms_p50"),
            "ttft_ms_p99": s.get("ttft_ms_p99"),
            "decode_tokens_per_s": (round(sum(decoded) / len(decoded), 2)
                                    if decoded else None),
            "prefill_chunks_total": sum(e.get("prefill_chunks", 0)
                                        for e in engines),
            "prefix_hit_rate": round(sum(
                e.get("prefix_hit_tokens", 0) - e.get(
                    "remote_hit_tokens", 0) for e in engines) / max(1, sum(
                        e.get("admitted_tokens", 0) for e in engines)), 4),
            "cross_replica_hit_rate": s.get("cross_replica_hit_rate"),
            "handoffs": s.get("handoffs", 0),
            "handoff_failures": s.get("handoff_failures", 0),
            "prefix_ships": s.get("prefix_ships", 0),
            "kv_stream_bytes": s.get("kv_stream_bytes", 0),
            "unfinished": unfinished,        # must stamp 0
            "recompiles": recompiles,        # must stamp 0
            **({"trace": trace_stats} if trace_stats else {}),
        }

    disagg = leg([ROLE_PREFILL] * n_prefill + [ROLE_DECODE] * n_decode)
    result = {
        "metric": "disagg_ttft_p99_ratio",
        "value": None, "unit": "x (colocated / disagg; > 1 = disagg wins)",
        "requests": n_requests, "prefix_len": prefix_len,
        "block_size": block, "num_slots": num_slots,
        "disaggregated": disagg,
    }
    if os.environ.get("PTD_DISAGG_AB", "1") != "0":
        colo = leg([ROLE_BOTH] * (n_prefill + n_decode))
        result["colocated"] = colo
        if disagg["ttft_ms_p99"] and colo["ttft_ms_p99"]:
            result["value"] = round(
                colo["ttft_ms_p99"] / disagg["ttft_ms_p99"], 3)
        if (disagg["decode_tokens_per_s"]
                and colo["decode_tokens_per_s"]):
            result["decode_tokens_ratio"] = round(
                disagg["decode_tokens_per_s"]
                / colo["decode_tokens_per_s"], 3)
    if os.environ.get("PTD_TRACE_AB", "0") == "1":
        result["trace_ab"] = _trace_overhead_ab()
    _stamp_overrides(result, ("PTD_DISAGG_PREFILL", "PTD_DISAGG_DECODE",
                              "PTD_DISAGG_SLOTS", "PTD_DISAGG_REQUESTS",
                              "PTD_DISAGG_MAX_NEW", "PTD_DISAGG_BLOCK",
                              "PTD_DISAGG_PREFIX_LEN", "PTD_DISAGG_AB",
                              "PTD_TRACE", "PTD_TRACE_AB", "PTD_QUANT"))
    return result


def _coldstart_worker(cache_dir: str) -> None:
    """Child of bench_coldstart: ONE fresh process standing up a serving
    engine against ``cache_dir`` (jax import → model init → engine →
    warmup → first token), printing one JSON line with the wall-time
    breakdown, the greedy token stream (the parent asserts cold == warm
    bitwise) and the compile tripwires: engine TRACE_COUNTS, the jit
    wrappers' pjit ``_cache_size`` sum, and the compile-cache stats —
    on a warm run every one of them must read ZERO fresh compiles."""
    t_start = time.perf_counter()
    import os

    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.compile_cache import stats_snapshot
    from pytorchdistributed_tpu.serving import ServingEngine
    from pytorchdistributed_tpu.serving import engine as serving_engine

    size = os.environ.get("PTD_COLDSTART_SIZE", "test")
    num_slots = int(os.environ.get("PTD_COLDSTART_SLOTS", "4"))
    paged = os.environ.get("PTD_COLDSTART_PAGED", "0") == "1"
    block = int(os.environ.get("PTD_COLDSTART_BLOCK", "16"))
    cfg = gpt2_config(size, scan_layers=False, quant=_quant_override())
    model = GPT2(cfg)
    params = jax.jit(model.init)(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))
    t_built = time.perf_counter()
    engine = ServingEngine(model, params, num_slots=num_slots,
                           prefill_bucket=128,
                           block_size=block if paged else 0,
                           compile_cache=cache_dir)
    engine.warmup(prompt_lens=(128,))
    t_warm = time.perf_counter()
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, cfg.vocab_size, (64,)).astype(np.int32)
    req = engine.submit(prompt, max_new_tokens=8)
    stream = engine.stream(req)
    first = next(stream)
    t_first = time.perf_counter()
    tokens = [int(first)] + [int(t) for t in stream]
    outcomes = dict(engine.aot_outcomes)
    engine.close()
    jit_cache = sum(f._cache_size() for f in (
        serving_engine.decode_tick, serving_engine.prefill_into_slot,
        serving_engine.paged_decode_tick,
        serving_engine.paged_prefill_chunk,
        serving_engine.spec_decode_tick, serving_engine.params_finite))
    print(json.dumps({
        "start_to_first_token_s": round(t_first - t_start, 4),
        "model_build_s": round(t_built - t_start, 4),
        "warmup_s": round(t_warm - t_built, 4),
        "tokens": tokens,
        "trace_counts": dict(serving_engine.TRACE_COUNTS),
        "jit_cache_size": jit_cache,
        "cache_stats": stats_snapshot(),
        "aot_outcomes": outcomes,
    }))


def bench_coldstart() -> dict:
    """Cold start vs warm start A/B for the persistent AOT executable
    cache (ISSUE 10, runtime/compile_cache.py): two FRESH subprocesses
    stand up the same serving engine against the same cache directory —
    the first compiles + serializes every program (cold), the second
    deserializes them (warm). The headline is the start-to-first-token
    speedup; the record asserts-by-stamping that the warm run performed
    **zero** XLA compiles (``warm_fresh_compiles`` must be 0 — pinned
    three ways: compile-cache miss/store counters, engine TRACE_COUNTS,
    and the jit wrappers' pjit ``_cache_size``, all read inside the
    warm child) and that the two runs' greedy token streams are bitwise
    identical (``tokens_bitwise_equal``). Knobs:
    PTD_COLDSTART_{SIZE,SLOTS,PAGED,BLOCK,CACHE}; PTD_QUANT rides the
    model config like every serving bench."""
    import os
    import subprocess
    import sys
    import tempfile

    cache_dir = (os.environ.get("PTD_COLDSTART_CACHE")
                 or tempfile.mkdtemp(prefix="ptd_coldstart_cache_"))

    def leg() -> dict:
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--coldstart-worker", cache_dir],
            capture_output=True, text=True)
        wall = time.perf_counter() - t0
        if proc.returncode != 0:
            print(f"coldstart worker failed:\n{proc.stderr}",
                  file=sys.stderr)
            raise SystemExit(2)
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        out["process_wall_s"] = round(wall, 4)
        return out

    cold = leg()
    warm = leg()
    warm_fresh = (warm["cache_stats"].get("miss", 0)
                  + warm["cache_stats"].get("store", 0)
                  + warm["jit_cache_size"]
                  + sum(warm["trace_counts"].values()))
    cold_s = cold["start_to_first_token_s"]
    warm_s = warm["start_to_first_token_s"]
    result = {
        "metric": "serve_coldstart_speedup",
        "value": round(cold_s / warm_s, 2) if warm_s else None,
        "unit": "x",
        "cold_start_to_first_token_s": cold_s,
        "warm_start_to_first_token_s": warm_s,
        "cold_warmup_s": cold["warmup_s"],
        "warm_warmup_s": warm["warmup_s"],
        "cold_compiles": cold["cache_stats"].get("store", 0),
        "warm_cache_hits": warm["cache_stats"].get("hit", 0),
        "warm_fresh_compiles": warm_fresh,           # must stamp 0
        "tokens_bitwise_equal": cold["tokens"] == warm["tokens"],
        "cache_entries": sum(1 for f in os.listdir(cache_dir)
                             if f.endswith(".json")),
        "cache_dir": cache_dir,
    }
    _stamp_overrides(result, ("PTD_COLDSTART_SIZE", "PTD_COLDSTART_SLOTS",
                              "PTD_COLDSTART_PAGED", "PTD_COLDSTART_BLOCK",
                              "PTD_COLDSTART_CACHE", "PTD_QUANT"))
    return result


def bench_mlp() -> dict:
    import optax

    from pytorchdistributed_tpu.data import (
        DataLoader,
        SyntheticRegressionDataset,
    )
    from pytorchdistributed_tpu.models import MLP
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import Trainer, mse_loss

    batch_size = 8192
    model = MLP(features=(1024, 1024, 256))
    ds = SyntheticRegressionDataset(size=batch_size * 4, in_dim=256,
                                    out_dim=256, seed=0)
    trainer = Trainer(model, optax.adamw(1e-3), mse_loss,
                      mesh=create_mesh(), strategy="dp", log_every=10**9)
    loader = DataLoader(ds, batch_size=batch_size, num_replicas=1, rank=0)
    batch = next(iter(loader))
    sec = _time_steps(trainer, batch)
    result = {"metric": "mlp_dp_training_throughput",
              "value": round(batch_size / sec, 1), "unit": "samples/s"}
    return _accounting_fields(trainer, batch, result, sec)


def bench_sweep() -> dict:
    """The reference's split-size tradeoff sweep
    (03_model_parallel.ipynb:586-623): step time vs pipeline micro-batch
    count for a 2-stage GPT-2 on a 2-way pipe mesh. Always runs on a
    2-device CPU sim (the bench host has one TPU chip; the env override
    must happen before the first backend initialization, so no device
    query can precede it). Reports the best micro-batch count's
    throughput; the full table goes to stderr."""
    import sys

    from pytorchdistributed_tpu._jax_compat import (
        supports_partial_auto_shard_map,
    )
    from pytorchdistributed_tpu.config import select_backend

    if not supports_partial_auto_shard_map():
        print("bench: --bench sweep needs the pipeline schedules' "
              "partial-auto shard_map, which this jax cannot lower "
              "(same gate as tests/test_pipeline.py)", file=sys.stderr)
        raise SystemExit(2)
    select_backend("cpu-sim2")  # env + jax.config, before backend init
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 512, (32, 128)).astype(np.int32),
        "targets": rng.integers(0, 512, (32, 128)).astype(np.int32),
    }
    results = {}
    for sched in ("gpipe", "1f1b"):
        for m in [1, 2, 4, 8, 16, 32]:
            if sched == "1f1b" and m == 1:
                continue  # degenerate: no overlap to schedule
            model = GPT2(gpt2_config(
                "test", num_layers=4, vocab_size=512, pipeline_stages=2,
                pipeline_microbatches=m, pp_schedule=sched))
            tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                         mesh=create_mesh(pipe=2), strategy="dp",
                         log_every=10**9)
            results[(sched, m)] = _time_steps(tr, batch, warmup=1, steps=5)
    best = min(results, key=results.get)
    print(f"sweep step seconds: {results} (best schedule,microbatches={best})",
          file=sys.stderr, flush=True)
    try:
        _render_sweep_plot(results, "split_size_tradeoff.png")
        print("sweep plot written to split_size_tradeoff.png",
              file=sys.stderr, flush=True)
    except Exception as e:  # the number is the bench; the plot is a bonus
        print(f"sweep plot skipped: {e}", file=sys.stderr, flush=True)
    return {"metric": "pp_sweep_best_tokens_per_s",
            "value": round(32 * 128 / results[best], 1), "unit": "tokens/s"}


def _render_sweep_plot(results: dict, path: str) -> None:
    """The reference's `split_size_tradeoff.png` analog
    (03_model_parallel.ipynb:586-623, PNG at 03 模型并行/): step time vs
    micro-batch count, one line per schedule. Micro-batch count is our
    tunable where the reference sweeps `split_size` — same tradeoff (more
    splits shrink the bubble, too many drown in per-split overhead)."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    colors = {"gpipe": "#2a78d6", "1f1b": "#eb6834"}
    fig, ax = plt.subplots(figsize=(7, 4.2), dpi=120)
    fig.patch.set_facecolor("#fcfcfb")
    ax.set_facecolor("#fcfcfb")
    for sched in ("gpipe", "1f1b"):
        pts = sorted((m, t) for (s, m), t in results.items() if s == sched)
        xs = [m for m, _ in pts]
        ys = [t * 1e3 for _, t in pts]
        ax.plot(xs, ys, marker="o", markersize=6, linewidth=2,
                color=colors[sched], label=sched)
        ax.annotate(sched, (xs[-1], ys[-1]), textcoords="offset points",
                    xytext=(8, 0), color="#52514e", fontsize=9,
                    va="center")
    ax.set_xscale("log", base=2)
    ax.set_xticks([m for (s, m) in results if s == "gpipe"])
    ax.get_xaxis().set_major_formatter(plt.ScalarFormatter())
    ax.set_xlabel("pipeline micro-batches (reference: split_size)",
                  color="#0b0b0b")
    ax.set_ylabel("step time (ms)", color="#0b0b0b")
    ax.set_title("Pipeline split-size tradeoff (2-stage GPT-2, 2-dev sim)",
                 color="#0b0b0b", fontsize=11)
    ax.grid(True, which="major", color="#e8e7e4", linewidth=0.8)
    ax.tick_params(colors="#52514e")
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color("#c3c2b7")
    ax.legend(frameon=False, labelcolor="#0b0b0b")
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)


_SCALING_PER_PROC_BATCH = 8


def _scaling_worker(rank, out_path, steps):
    """One weak-scaling process: fixed per-process batch, multi-process DDP
    over jax.distributed (env contract from runtime.launch). Rank 0 writes
    its measured sec/step. Module-level so multiprocessing spawn can pickle
    it."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import optax

    from pytorchdistributed_tpu.data.loader import shard_batch
    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime import dist
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    dist.init_process_group()
    import jax.numpy as jnp

    model = GPT2(gpt2_config("test", num_layers=4, dtype=jnp.float32))
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(), strategy="dp", log_every=10**9,
                 watchdog=False)
    rng = np.random.default_rng(rank)
    b = _SCALING_PER_PROC_BATCH
    local = {
        "tokens": rng.integers(0, 128, (b, 64)).astype(np.int32),
        "targets": rng.integers(0, 128, (b, 64)).astype(np.int32),
    }
    batch = shard_batch(local, tr.batch_sharding)
    tr.init(batch)
    metrics = None
    for _ in range(2):
        metrics = tr.train_step(batch)
    float(metrics["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        metrics = tr.train_step(batch)
    float(metrics["loss"])
    sec = (time.perf_counter() - t0) / steps
    if rank == 0:
        with open(out_path, "w") as f:
            json.dump({"world": dist.get_world_size(),
                       "sec_per_step": sec}, f)
    dist.destroy_process_group()


def bench_scaling() -> dict:
    """Weak-scaling harness for the BASELINE north star ("DDP scaling eff
    8→256 chips ≥90%"): the same per-process workload on 1/2/4 REAL OS
    processes (each its own 1-device CPU sim, jax.distributed rendezvous
    via runtime.launch), efficiency = T_n / (n·T_1) = t_1/t_n
    (utils.metrics.scaling_efficiency). On the CPU sim the processes share
    one host's cores, so the absolute efficiency is pessimistic — the
    value here proves the measurement path; the pod run is the same code
    with the process count raised (a flag flip)."""
    import os
    import sys
    import tempfile

    from pytorchdistributed_tpu._jax_compat import (
        supports_multiprocess_cpu_collectives,
    )
    from pytorchdistributed_tpu.runtime.launch import launch
    from pytorchdistributed_tpu.utils.metrics import scaling_efficiency

    if not supports_multiprocess_cpu_collectives():
        print("bench: --bench scaling needs multi-process CPU collectives, "
              "unimplemented in this jaxlib (use --bench scaling_sim)",
              file=sys.stderr)
        raise SystemExit(2)

    sec = {}
    for n in (1, 2, 4):
        with tempfile.TemporaryDirectory() as td:
            out = os.path.join(td, "result.json")
            launch(_scaling_worker, n, args=(out, 12), devices_per_proc=1,
                   timeout=900)
            with open(out) as f:
                sec[n] = json.load(f)["sec_per_step"]
    b = _SCALING_PER_PROC_BATCH
    eff = {n: round(scaling_efficiency(n * b / sec[n], b / sec[1], n), 4)
           for n in sec}
    print(f"weak scaling: sec/step {sec} efficiency {eff}",
          file=sys.stderr, flush=True)
    return {"metric": "weak_scaling_eff_4proc", "value": eff[4],
            "unit": "efficiency",
            "sec_per_step": {str(k): round(v, 5) for k, v in sec.items()},
            "efficiency": {str(k): v for k, v in eff.items()}}


def _scaling_sim_worker(n: int, mode: str = "dp") -> None:
    """One weak-scaling point IN PROCESS: n sim devices (XLA_FLAGS set by
    the parent), one pjit'd train step over an n-device mesh with an
    n-scaled global batch. ``mode`` picks the sharding whose overhead the
    point isolates (VERDICT r4 #6 — the DP-only tripwire was blind to the
    collectives the intricate code paths add): "dp" (psum only), "fsdp"
    (ZeRO-3 all-gather/reduce-scatter), "tp_dp" (Megatron activation
    collectives x data), "pipe_dp" (1F1B ppermute x data). All modes share
    the same 4-layer test GPT-2 and global workload, so every mode's t_n
    compares against the SAME single-device t_1 (mode is meaningless at
    n=1). Prints JSON {sec_per_step: [3 windows]} to stdout."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) == n, (n, jax.devices())
    import jax.numpy as jnp
    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        token_cross_entropy_loss,
    )

    cfg_kw: dict = {}
    if n == 1 or mode == "dp":
        axes, strategy = dict(data=n), "dp"
    elif mode == "fsdp":
        axes, strategy = dict(fsdp=n), "fsdp"
    elif mode == "tp_dp":
        axes, strategy = dict(data=max(n // 4, 1), tensor=min(n, 4)), "tp"
    elif mode == "pipe_dp":
        axes, strategy = dict(data=max(n // 4, 1), pipe=min(n, 4)), "dp"
        cfg_kw = dict(pipeline_stages=min(n, 4), pipeline_microbatches=8,
                      pp_schedule="1f1b")
    else:
        raise SystemExit(f"unknown scaling_sim mode {mode!r}")
    model = GPT2(gpt2_config("test", num_layers=4, dtype=jnp.float32,
                             **cfg_kw))
    tr = Trainer(model, optax.adamw(1e-3), token_cross_entropy_loss,
                 mesh=create_mesh(**axes), strategy=strategy,
                 log_every=10**9, watchdog=False)
    rng = np.random.default_rng(0)
    b = _SCALING_PER_PROC_BATCH * n  # weak scaling: fixed per-device work
    batch = {
        "tokens": rng.integers(0, 128, (b, 64)).astype(np.int32),
        "targets": rng.integers(0, 128, (b, 64)).astype(np.int32),
    }
    tr.init(batch)
    metrics = None
    for _ in range(2):
        metrics = tr.train_step(batch)
    float(metrics["loss"])
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(8):
            metrics = tr.train_step(batch)
        float(metrics["loss"])  # sync the async dispatch queue
        windows.append((time.perf_counter() - t0) / 8)
    print(json.dumps({"sec_per_step": windows}))


def bench_scaling_sim() -> dict:
    """In-process weak scaling (VERDICT r3 #8): 1/2/4/8 SIM devices in one
    process each (a fresh subprocess per point so the device count can
    differ), same per-device workload, no jax.distributed / OS-process
    contention in the measurement. On a serialized CPU host, n devices run
    n× the compute back-to-back, so perfect sharding gives step-time
    inflation t_n/(n·t_1) ≈ 1 regardless of core count — anything above 1
    is per-step overhead the sharding added (collectives, scheduling,
    layout changes). That makes eff = n·t_1/t_n a STABLE tripwire for
    collective-overhead regressions where the real-process harness
    (--bench scaling) drowns in core contention on a 1-core rig; the pod
    run still uses the real-process harness."""
    import os
    import subprocess
    import sys

    def point(n, mode):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--scaling-sim-worker", str(n), "--scaling-sim-mode", mode],
            env=env, capture_output=True, text=True, timeout=900)
        if proc.returncode != 0:  # surface the child's reason, fail fast
            print(f"scaling_sim worker n={n} mode={mode} failed:\n"
                  f"{proc.stderr}", file=sys.stderr)
            raise SystemExit(2)
        windows = json.loads(proc.stdout.strip().splitlines()[-1])[
            "sec_per_step"]
        return float(np.mean(windows)), float(np.std(windows))

    sec, std = {}, {}
    for n in (1, 2, 4, 8):
        sec[n], std[n] = point(n, "dp")
    eff = {n: round(n * sec[1] / sec[n], 4) for n in sec}
    # the non-DP modes' 8-dev points, against the SAME t_1 (identical
    # model + global workload; only the sharding differs)
    mode_eff, mode_sec = {}, {}
    for mode in ("fsdp", "tp_dp", "pipe_dp"):
        s, d = point(8, mode)
        mode_sec[mode] = (round(s, 5), round(d, 5))
        mode_eff[mode] = round(8 * sec[1] / s, 4)
    print(f"sim weak scaling: sec/step {sec} (std {std}) efficiency {eff} "
          f"| 8-dev modes {mode_eff} (sec {mode_sec})",
          file=sys.stderr, flush=True)
    result = {"metric": "sim_weak_scaling_eff_8dev", "value": eff[8],
              "unit": "efficiency",
              "sec_per_step": {str(k): round(v, 5) for k, v in sec.items()},
              "sec_std": {str(k): round(v, 5) for k, v in std.items()},
              "efficiency": {str(k): v for k, v in eff.items()},
              "mode_eff_8dev": mode_eff}
    # per-mode committed tripwires ride the same record (the primary
    # metric's vs_baseline mechanism covers only "value")
    vs = {m: round(mode_eff[m]
                   / COMMITTED_BASELINES[f"sim_weak_scaling_eff_8dev_{m}"],
                   3)
          for m in mode_eff
          if f"sim_weak_scaling_eff_8dev_{m}" in COMMITTED_BASELINES}
    if vs:
        result["mode_vs_baseline"] = vs
    return result


def bench_moe() -> dict:
    """Expert-parallel MoE training throughput (ISSUE 14): a GPT-2-shaped
    Switch/top-k MoE LM on a dp x expert mesh, trained through the
    explicit all_to_all dispatch/combine (ops/overlap.expert_a2a_ffn).

    Three legs on the SAME model/batch:
      * headline — the a2a path with capacity chunking (``moe_chunks``
        from PTD_MOE_CHUNKS, default 2): dispatch/combine exchanges
        pipelined behind the expert matmuls;
      * overlap OFF — ``moe_dispatch="dense"``: the auto-partitioned
        one-hot einsums with a GLOBAL capacity buffer, i.e. the path
        every token took before the explicit exchange existed;
      * chunks=1 — the a2a path without pipelining, isolating the
        chunking term from the grouped-dispatch term.

    Stamps tokens/s for each leg, the a2a comm bytes of the compiled
    step (telemetry a2a_bytes_per_step), and the expert overflow
    fraction read from a diagnostics-enabled twin of the step. Knobs:
    PTD_MOE_{EXPERTS,TOP_K,CAPACITY,CHUNKS,DISPATCH,EP}, PTD_BENCH_BS/
    PTD_BENCH_SEQ, PTD_QUANT. On the CPU sim the numbers are regression
    pins (the grouped dispatch term dominates); the chunk-overlap
    multiplier needs a chip's async collectives."""
    import os
    import sys

    import optax

    from pytorchdistributed_tpu.models import GPT2, gpt2_config
    from pytorchdistributed_tpu.runtime.mesh import MeshConfig, create_mesh
    from pytorchdistributed_tpu.training import (
        Trainer,
        moe_token_cross_entropy_loss,
    )

    import jax
    experts = int(os.environ.get("PTD_MOE_EXPERTS", 8))
    top_k = int(os.environ.get("PTD_MOE_TOP_K", 1))
    cf = float(os.environ.get("PTD_MOE_CAPACITY", 1.25))
    chunks = int(os.environ.get("PTD_MOE_CHUNKS", 2))
    batch_size = int(os.environ.get("PTD_BENCH_BS", 8))
    seq_len = int(os.environ.get("PTD_BENCH_SEQ", 512))
    ndev = jax.device_count()
    # dp x expert: prefer a real data axis next to the expert axis (the
    # canonical MoE training mesh); ep must divide devices AND experts
    ep = int(os.environ.get("PTD_MOE_EP", 0)) or next(
        (e for e in (4, 2, 8) if ndev % e == 0 and experts % e == 0), 1)
    mesh = create_mesh(MeshConfig(data=ndev // ep, expert=ep))

    def make_trainer(moe_chunks, dispatch, diagnostics=None):
        cfg = gpt2_config(
            "test", num_layers=4, embed_dim=256, num_heads=8,
            mlp_dim=1024, vocab_size=2048, max_seq_len=seq_len,
            scan_layers=False, moe_experts=experts,
            moe_capacity_factor=cf, moe_top_k=top_k,
            moe_chunks=moe_chunks, moe_dispatch=dispatch,
            quant=_quant_override())
        return Trainer(GPT2(cfg), optax.adamw(3e-4),
                       moe_token_cross_entropy_loss, mesh=mesh,
                       strategy="dp", log_every=10**9,
                       diagnostics=diagnostics)

    rng = np.random.default_rng(0)
    batch = {
        "tokens": rng.integers(0, 2048, (batch_size, seq_len)).astype(
            np.int32),
        "targets": rng.integers(0, 2048, (batch_size, seq_len)).astype(
            np.int32),
    }
    dispatch = os.environ.get("PTD_MOE_DISPATCH", "auto")
    trainer = make_trainer(chunks, dispatch)
    sec = _time_steps(trainer, batch, steps=10)
    sec_dense = _time_steps(make_trainer(chunks, "dense"), batch, steps=10)
    sec_c1 = (sec if chunks == 1
              else _time_steps(make_trainer(1, dispatch), batch, steps=10))

    tokens = batch_size * seq_len
    result = {"metric": "moe_train_tokens_per_s",
              "value": round(tokens / sec, 1), "unit": "tokens/s",
              "mesh": {"data": ndev // ep, "expert": ep},
              "experts": experts, "top_k": top_k, "capacity_factor": cf,
              "chunks": chunks, "dispatch": dispatch,
              "overlap_off_tokens_per_s": round(tokens / sec_dense, 1),
              "overlap_speedup": round(sec_dense / sec, 3),
              "chunks1_tokens_per_s": round(tokens / sec_c1, 1)}
    _stamp_overrides(result, ("PTD_MOE_EXPERTS", "PTD_MOE_TOP_K",
                              "PTD_MOE_CAPACITY", "PTD_MOE_CHUNKS",
                              "PTD_MOE_DISPATCH", "PTD_MOE_EP",
                              "PTD_BENCH_BS", "PTD_BENCH_SEQ",
                              "PTD_QUANT"))
    result = _accounting_fields(trainer, batch, result, sec)
    try:
        result["a2a_bytes_per_step"] = trainer.step_accounting(
            batch).a2a_bytes_per_step
    except Exception as e:
        print(f"bench: a2a accounting skipped ({e})", file=sys.stderr)
    # overflow fraction from a diagnostics-enabled twin (one extra
    # compile; the timed legs stay diagnostics-off like every bench)
    try:
        diag = make_trainer(chunks, dispatch, diagnostics="scalars")
        diag.init(batch)
        m = diag.train_step(batch)
        result["moe_overflow_frac"] = round(
            float(m["diag/moe_overflow"]), 4)
    except Exception as e:
        print(f"bench: moe overflow probe skipped ({e})", file=sys.stderr)
    return result


def bench_soak() -> dict:
    """Chaos soak (ISSUE 19): a subprocess fleet rides a seeded diurnal
    trace in REAL time (WallClock — arrivals hold their cadence even
    when a fault slows the fleet) with the autoscaler live and a
    ChaosSchedule firing rate-based faults the whole run: replica
    crashes, hangs, slow ticks, and wire-level line mangling between
    router and worker. serving/soak.py's InvariantChecker watches
    continuously; the run FAILS (ok=false in the stamp) if any
    invariant breaks — compliant-tenant sheds, fresh XLA traces on a
    survivor, a non-terminal stream, an orphan worker process.

    Stamps: SLO attainment over admitted requests, the finish-reason
    split, the per-fault-class recovery table (injected → detected →
    recovered with MTTR percentiles), the invariant verdicts and the
    autoscaler's decisions. ``scripts/soak.py`` wraps this for
    multi-minute runs; the committed BENCH_soak.json is one such leg.

    Knobs: PTD_SOAK_{DURATION,QPS,PEAK,REPLICAS,MAX_REPLICAS,SEED,
    FAULTS,SLOTS,QUEUE}; PTD_SOAK_FAULTS takes the full fault grammar
    (see faults/chaos.py) — the default mixes three replica classes
    with two wire classes.
    """
    import os
    import tempfile

    from pytorchdistributed_tpu.faults import ChaosSchedule
    from pytorchdistributed_tpu.serving import (
        Autoscaler,
        ReplicaRouter,
        SLOConfig,
        TenantConfig,
        TenantTraffic,
        WallClock,
        make_trace,
        run_soak,
    )

    duration_s = float(os.environ.get("PTD_SOAK_DURATION", "45.0"))
    base_qps = float(os.environ.get("PTD_SOAK_QPS", "3.0"))
    peak_mult = float(os.environ.get("PTD_SOAK_PEAK", "3.0"))
    replicas = int(os.environ.get("PTD_SOAK_REPLICAS", "2"))
    max_replicas = int(os.environ.get("PTD_SOAK_MAX_REPLICAS", "3"))
    num_slots = int(os.environ.get("PTD_SOAK_SLOTS", "4"))
    max_queue = int(os.environ.get("PTD_SOAK_QUEUE", "24"))
    seed = int(os.environ.get("PTD_SOAK_SEED", "7"))
    # >= 3 fault classes incl. wire faults, rates sized so each class
    # fires a handful of times over the default duration
    faults_spec = os.environ.get(
        "PTD_SOAK_FAULTS",
        "replica_crash@rate=0.05;replica_hang@rate=0.02;"
        "replica_slow@rate=0.08,ms=150;"
        "wire_torn@rate=0.05;wire_delay@rate=0.08,ms=100")

    trace = make_trace(
        seed=seed, duration_s=duration_s, base_qps=base_qps,
        shape="diurnal", peak_mult=peak_mult,
        tenants=(TenantTraffic("hot", share=4.0),
                 TenantTraffic("calm", share=1.0)),
        vocab_size=50257, prompt_cap=24, new_cap=8)
    spec = {"model": "gpt2", "size": "test",
            "overrides": {"num_layers": 2, "max_seq_len": 64},
            "init_seed": 1,
            "engine": {"num_slots": num_slots, "prefill_bucket": 16}}
    clk = WallClock()
    chaos = ChaosSchedule(faults_spec, seed=seed, clock=clk)
    tmp = tempfile.mkdtemp(prefix="ptd_soak_")
    router = ReplicaRouter(
        workers=[spec] * replicas, warmup_lens=(16, 32),
        max_queue=max_queue, faults=chaos, respawn_budget=3,
        seed=seed, telemetry_dir=tmp,
        tenants={"hot": TenantConfig(weight=1.0),
                 "calm": TenantConfig(weight=1.0)})
    router.warmup()
    asc = Autoscaler(
        router,
        SLOConfig(queue_high=8.0, occupancy_high=0.95,
                  occupancy_low=0.3, shed_rate_max=1.0,
                  ttft_target_ms=1e9),
        min_replicas=1, max_replicas=max_replicas,
        breach_ticks=5, clear_ticks=100,
        up_cooldown_s=5.0, down_cooldown_s=10.0, clock=clk)
    report = run_soak(
        router, trace, clock=clk, tick_s=0.02, autoscaler=asc,
        compliant=("calm",), debt_budget_s=30.0, strict=False)

    result = {
        "metric": "soak_slo_attainment",
        "value": report["slo_attainment"], "unit": "frac",
        "ok": report["invariants"]["ok"],
        "duration_s": duration_s,
        "trace": {"seed": seed, "shape": "diurnal",
                  "requests": len(trace), "base_qps": base_qps,
                  "peak_mult": peak_mult},
        "faults": faults_spec,
        "replicas": replicas, "max_replicas": max_replicas,
        **{k: report[k] for k in (
            "requests", "admitted", "finish_reasons", "ttft_p50_s",
            "ttft_p95_s", "wall_s", "faults_injected",
            "injected_by_kind", "recovery", "invariants")},
        "router": {k: report["router"].get(k) for k in (
            "submitted", "completed", "shed_requests", "failovers",
            "redispatched_requests", "quarantines", "rejoins",
            "respawns", "handoff_aborts", "wire_faults",
            "faults_injected")},
    }
    if "autoscaler" in report:
        result["autoscaler"] = {
            k: report["autoscaler"].get(k)
            for k in ("scale_ups", "scale_downs")}
    _stamp_overrides(result, ("PTD_SOAK_DURATION", "PTD_SOAK_QPS",
                              "PTD_SOAK_PEAK", "PTD_SOAK_REPLICAS",
                              "PTD_SOAK_MAX_REPLICAS", "PTD_SOAK_SLOTS",
                              "PTD_SOAK_QUEUE", "PTD_SOAK_SEED",
                              "PTD_SOAK_FAULTS"))
    return result


BENCHES = {"gpt2": bench_gpt2, "llama1b": bench_llama1b,
           "gpt2medium": functools.partial(bench_gpt2, "medium"),
           "longcontext": functools.partial(
               bench_llama1b, batch_size=2, seq_len=4096,
               metric="llama1b_s4096_train_tokens_per_s"),
           "bert": bench_bert, "vit": bench_vit,
           "resnet50": bench_resnet50, "generate": bench_generate,
           "serve": bench_serve, "kvcompress": bench_kvcompress,
           "specdraft": bench_specdraft,
           "router": bench_router, "autoscale": bench_autoscale,
           "sessions": bench_sessions, "soak": bench_soak,
           "disagg": bench_disagg, "coldstart": bench_coldstart,
           "moe": bench_moe,
           "mlp": bench_mlp, "sweep": bench_sweep,
           "scaling": bench_scaling, "scaling_sim": bench_scaling_sim}


# benches that force the CPU sim in their own bodies and need no
# accelerator probe — extend alongside BENCHES
CPU_SIM_BENCHES = {"sweep", "scaling", "scaling_sim"}


def _probe_device(timeout_s: float = 120.0) -> None:
    """Fail fast if the accelerator is unreachable. The axon TPU tunnel can
    wedge so hard that even `jax.devices()` blocks forever INSIDE native
    code (observed r3: hours of downtime, unkillable from Python) — probe
    in a subprocess so a dead tunnel yields a clean error instead of a
    silently hung bench run. The child runs in its own session and is
    never waited on unboundedly: a D-state child that ignores SIGKILL (or
    forked grandchildren holding pipes open) must not re-hang the parent."""
    import os
    import signal
    import subprocess
    import sys

    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; print(jax.devices()[0].device_kind)"],
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        code = proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        print(f"bench: accelerator unreachable (device probe hung "
              f"{timeout_s:.0f}s — tunnel wedged?)", file=sys.stderr)
        raise SystemExit(2)
    if code != 0:
        print(f"bench: device probe failed:\n{proc.stderr.read()}",
              file=sys.stderr)
        raise SystemExit(2)


def main() -> None:
    parser = argparse.ArgumentParser()
    # --mode is an alias for --bench (the serving-engine docs say
    # `bench.py --mode serve`)
    parser.add_argument("--bench", "--mode", choices=sorted(BENCHES),
                        default="gpt2")
    parser.add_argument("--scaling-sim-worker", type=int, default=None,
                        help=argparse.SUPPRESS)  # bench_scaling_sim child
    parser.add_argument("--scaling-sim-mode", type=str, default="dp",
                        help=argparse.SUPPRESS)
    parser.add_argument("--coldstart-worker", type=str, default=None,
                        help=argparse.SUPPRESS)  # bench_coldstart child
    args = parser.parse_args()
    if args.scaling_sim_worker is not None:
        _scaling_sim_worker(args.scaling_sim_worker, args.scaling_sim_mode)
        return
    if args.coldstart_worker is not None:
        _coldstart_worker(args.coldstart_worker)
        return
    if args.bench not in CPU_SIM_BENCHES:
        _probe_device()
    result = BENCHES[args.bench]()
    vs = _vs_baseline(result["metric"], result["value"])
    if vs is not None:  # metrics without a committed baseline omit the ratio
        result["vs_baseline"] = vs
    print(json.dumps(result))


if __name__ == "__main__":
    main()
