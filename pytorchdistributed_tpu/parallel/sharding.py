"""Parameter-sharding rules: DDP/FSDP/TP as PartitionSpec choices.

This is the framework's L3 (SURVEY.md §1), replacing the reference's wrapper
classes: `DDP(model, ...)` (reference ddp_gpus.py:35) becomes "params
replicated, batch sharded on the data axes"; FSDP/ZeRO-3 (BASELINE.json
north star) becomes "each param's largest divisible dim sharded on the fsdp
axis"; Megatron TP becomes explicit per-layer logical axis annotations
(see parallel/tp.py). XLA then inserts the all-gather / reduce-scatter /
psum traffic that torch implements in the DDP Reducer and FSDP runtime.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorchdistributed_tpu.runtime.mesh import Axis


def replicated_shardings(params, mesh: Mesh):
    """DDP: every parameter fully replicated (grad sync happens because the
    batch is sharded and XLA psums the grads)."""
    return jax.tree.map(lambda _: NamedSharding(mesh, P()), params)


def _fsdp_spec(shape, fsdp_size: int, *, min_weight_size: int) -> P:
    """Shard the largest dim divisible by ``fsdp_size``; replicate tiny
    params (biases, norms) where sharding would only add latency."""
    if int(np.prod(shape)) < min_weight_size:
        return P()
    order = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for i in order:
        if shape[i] % fsdp_size == 0 and shape[i] >= fsdp_size:
            spec = [None] * len(shape)
            spec[i] = Axis.FSDP
            return P(*spec)
    return P()


def fsdp_param_shardings(params, mesh: Mesh, *, min_weight_size: int = 2**14):
    """ZeRO-3-style sharding over the "fsdp" mesh axis (BASELINE north star:
    "FSDP's all-gather/reduce-scatter ... ported to XLA collectives")."""
    fsdp_size = mesh.shape[Axis.FSDP]
    if fsdp_size == 1:
        return replicated_shardings(params, mesh)

    def spec(leaf):
        return NamedSharding(
            mesh, _fsdp_spec(leaf.shape, fsdp_size,
                             min_weight_size=min_weight_size)
        )

    return jax.tree.map(spec, params)


def shardings_for_strategy(strategy: str, params, mesh: Mesh):
    """Map a named strategy (the reference's wrapper-class choice) onto
    NamedShardings for the same single train step.

    ``params`` may be a boxed tree (leaves are `nn.Partitioned` carrying
    logical axis names — the model zoo) or a plain tree (toy models). Boxed
    trees go through the logical rule tables in parallel/tp.py, which is how
    TP/2D strategies exist; plain trees use shape heuristics (dp/fsdp only).
    """
    from pytorchdistributed_tpu.parallel import tp

    tp.logical_rules(strategy)  # validates the name against the one registry
    if tp.has_logical_annotations(params):
        return tp.logical_shardings(params, mesh, strategy)
    if strategy in ("dp", "ddp"):
        return replicated_shardings(params, mesh)
    if strategy in ("fsdp", "zero3"):
        return fsdp_param_shardings(params, mesh)
    raise ValueError(
        f"strategy {strategy!r} needs a model with logical axis "
        "annotations (nn.with_logical_partitioning); this param tree "
        "has none"
    )
