"""Mixed precision — the amp→bf16 port (BASELINE.json north star), extended
down the same axis to int8 quantized training.

CUDA amp (GradScaler + fp16 autocast) does not map to TPU: the MXU's native
wide type is bfloat16, which shares float32's exponent range, so no loss
scaling is needed. The policy is therefore just a dtype triple: keep master
params in fp32, run compute (matmuls/convs on the MXU) in bf16, accumulate
reductions in fp32.

The ``quant`` field continues the amp→bf16 progression to the MXU's ~2×
int8 rate (ops/quant.py — AQT-style dynamic per-channel scaling): params
and non-matmul math stay exactly the bf16 policy's; only the weight
contractions run int8×int8→int32 behind an injectable ``dot_general``.
``Policy.int8_fwd()`` quantizes forward matmuls with a bf16 backward (the
convergence-safe default); ``Policy.int8()`` also quantizes the backward
contractions with stochastic rounding on the gradient operand. Models wire
the injectable through ``TransformerConfig.quant`` (config.py keeps the
two in lockstep from one ``--quant`` flag); ``Policy.dot_general()``
exposes the same injectable for ad-hoc models (e.g. ``models.mlp.MLP``).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


def _cast_floating(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


@dataclasses.dataclass(frozen=True)
class Policy:
    """param_dtype: master copy; compute_dtype: forward/backward math;
    output_dtype: loss/metrics accumulation; quant: weight-matmul
    quantization mode ("none" | "int8_fwd" | "int8", ops/quant.py)."""

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32
    quant: str = "none"

    @staticmethod
    def bf16() -> "Policy":
        """The TPU mixed-precision default (amp equivalent)."""
        return Policy(compute_dtype=jnp.bfloat16)

    @staticmethod
    def full() -> "Policy":
        return Policy()

    @staticmethod
    def int8_fwd() -> "Policy":
        """bf16 policy + int8 forward weight matmuls (dynamic per-channel
        scales), backward in bf16 — the safe quantized-training default."""
        return Policy(compute_dtype=jnp.bfloat16, quant="int8_fwd")

    @staticmethod
    def int8() -> "Policy":
        """bf16 policy + int8 forward AND backward weight matmuls
        (stochastic rounding on the gradient operand)."""
        return Policy(compute_dtype=jnp.bfloat16, quant="int8")

    def dot_general(self):
        """The policy's injectable contraction: None for quant="none"
        (callers use ``lax.dot_general``), else the shared int8 drop-in —
        the same callable TransformerConfig.quant injects, exposed here
        for models built outside the transformer core."""
        from pytorchdistributed_tpu.ops.quant import dot_general_for

        return dot_general_for(self.quant)

    def cast_params_for_compute(self, params):
        """Cast floating leaves to the compute dtype — EXCEPT normalization
        running statistics ("batch_stats"): the EMA update must read its
        fp32 master each step, or per-step bf16 quantization noise
        accumulates in the eval stats (the same rule torch amp applies to
        BN running stats)."""
        if isinstance(params, dict) and "batch_stats" in params:
            out = _cast_floating(
                {k: v for k, v in params.items() if k != "batch_stats"},
                self.compute_dtype)
            return {**out, "batch_stats": params["batch_stats"]}
        return _cast_floating(params, self.compute_dtype)

    def cast_batch(self, batch):
        return _cast_floating(batch, self.compute_dtype)

    def cast_output(self, tree):
        return _cast_floating(tree, self.output_dtype)
