"""Mixed precision — the amp→bf16 port (BASELINE.json north star).

CUDA amp (GradScaler + fp16 autocast) does not map to TPU: the MXU's native
wide type is bfloat16, which shares float32's exponent range, so no loss
scaling is needed. The policy is therefore just a dtype triple: keep master
params in fp32, run compute (matmuls/convs on the MXU) in bf16, accumulate
reductions in fp32.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


def _cast_floating(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


@dataclasses.dataclass(frozen=True)
class Policy:
    """param_dtype: master copy; compute_dtype: forward/backward math;
    output_dtype: loss/metrics accumulation."""

    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32
    output_dtype: jnp.dtype = jnp.float32

    @staticmethod
    def bf16() -> "Policy":
        """The TPU mixed-precision default (amp equivalent)."""
        return Policy(compute_dtype=jnp.bfloat16)

    @staticmethod
    def full() -> "Policy":
        return Policy()

    def cast_params_for_compute(self, params):
        """Cast floating leaves to the compute dtype — EXCEPT normalization
        running statistics ("batch_stats"): the EMA update must read its
        fp32 master each step, or per-step bf16 quantization noise
        accumulates in the eval stats (the same rule torch amp applies to
        BN running stats)."""
        if isinstance(params, dict) and "batch_stats" in params:
            out = _cast_floating(
                {k: v for k, v in params.items() if k != "batch_stats"},
                self.compute_dtype)
            return {**out, "batch_stats": params["batch_stats"]}
        return _cast_floating(params, self.compute_dtype)

    def cast_batch(self, batch):
        return _cast_floating(batch, self.compute_dtype)

    def cast_output(self, tree):
        return _cast_floating(tree, self.output_dtype)
