"""Auto-placement — the TPU answer to HF's ``device_map="auto"``
(reference 03_model_parallel.ipynb:86-89 (cell 1); its cell-0 markdown
describes the GPU > CPU > Disk placement priority).

On GPU the auto-placer solves "model bigger than one card" by *spilling*:
put what fits on the GPU, overflow to CPU RAM, then disk. On TPU spilling
over PCIe/DCN would strand the MXU, so the idiomatic resource ladder is
*sharding axes*, grown until the training state fits per-chip HBM:

  1. replicate (pure DP) while it fits — zero extra collectives;
  2. grow the **fsdp** axis (ZeRO-3): state divides by the axis size, cost
     is an all-gather per layer that overlaps with compute;
  3. add **tensor** parallelism: also divides the big kernels, cost is
     activation psums on the fastest ICI axis;
  4. add **pipe** stages: divides the scanned layer stack, cost is the
     pipeline bubble.

The planner works on the model's *abstract* params (real shapes, logical
axis names) and the same rule tables the Trainer shards with
(parallel/tp.py), so "would fit" is computed from the actual sharding a
strategy produces, not a heuristic fraction.
"""

from __future__ import annotations

import dataclasses
import math

import flax.linen as nn
import jax
import numpy as np

from pytorchdistributed_tpu.parallel.tp import logical_rules
from pytorchdistributed_tpu.runtime.mesh import Axis, MeshConfig

# Per-parameter training-state bytes: fp32 master copy + fp32 gradient +
# optimizer slots (adam m,v / sgd momentum). Compute-dtype casts are
# transient and covered by the headroom factor.
_STATE_BYTES_PER_PARAM = {"adamw": 16, "adam": 16, "sgd": 12}


@dataclasses.dataclass(frozen=True)
class Leaf:
    """A parameter leaf as the planner sees it: shape + per-dim logical
    axis names (None = never sharded)."""

    shape: tuple
    names: tuple

    @property
    def size(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) if self.shape else 1


@dataclasses.dataclass(frozen=True)
class AutoPlan:
    mesh: MeshConfig
    strategy: str
    per_device_state_bytes: int
    total_state_bytes: int

    def describe(self) -> str:
        gb = self.per_device_state_bytes / 2**30
        return (f"strategy={self.strategy} mesh={self.mesh} "
                f"state/device={gb:.2f}GiB")


def leaves_of(abstract_boxed_params) -> list[Leaf]:
    """Flatten a boxed abstract param tree (what `jax.eval_shape` of
    `model.init` returns) into planner leaves."""
    out = []
    for leaf in jax.tree.leaves(
            abstract_boxed_params,
            is_leaf=lambda x: isinstance(x, nn.Partitioned)):
        if isinstance(leaf, nn.Partitioned):
            names = tuple(leaf.names)
            shape = leaf.value.shape
        else:
            names = (None,) * getattr(leaf, "ndim", 0)
            shape = getattr(leaf, "shape", ())
        out.append(Leaf(tuple(shape), names))
    return out


def _shard_factor(leaf: Leaf, rules: dict, sizes: dict) -> int:
    """How many ways the given mesh sizes split this leaf under the rules —
    mirrors NamedSharding semantics: a dim divides only if the mapped axis
    size divides it evenly, and a mesh axis can shard at most one dim of a
    leaf (first dim wins, like PartitionSpec construction)."""
    factor = 1
    used: set = set()
    for dim, name in zip(leaf.shape, leaf.names):
        axes = rules.get(name)
        if axes is None:
            continue
        if isinstance(axes, str):
            axes = (axes,)
        axes = tuple(a for a in axes if a not in used)
        n = math.prod(sizes.get(a, 1) for a in axes)
        if n > 1 and dim % n == 0:
            factor *= n
            used.update(axes)
    return factor


def _per_device_bytes(leaves, strategy: str, sizes: dict,
                      optimizer: str) -> int:
    rules = dict(logical_rules(strategy))
    per_param = _STATE_BYTES_PER_PARAM.get(optimizer, 16)
    return sum(
        leaf.size * per_param // _shard_factor(leaf, rules, sizes)
        for leaf in leaves)


def _pow2_divisors(n: int):
    d, out = 1, []
    while d <= n:
        if n % d == 0:
            out.append(d)
        d *= 2
    return out


def plan_auto_shard(
    leaves: list[Leaf],
    n_devices: int,
    device_memory_bytes: float,
    *,
    optimizer: str = "adamw",
    headroom: float = 0.35,
) -> AutoPlan:
    """Pick the smallest-sharding (MeshConfig, strategy) whose per-device
    training state fits in ``(1-headroom) * device_memory_bytes``.

    ``headroom`` reserves HBM for activations, collective buffers and XLA
    scratch — state is the statically-knowable part; activations depend on
    batch size, which the caller still controls.
    """
    budget = device_memory_bytes * (1.0 - headroom)
    total = _per_device_bytes(leaves, "dp", {}, optimizer)
    # pipe only helps models with a scanned (stage-stacked) layer axis
    from pytorchdistributed_tpu.parallel.tp import Logical

    has_stages = any(Logical.STAGE in leaf.names for leaf in leaves)

    candidates: list[tuple[str, dict]] = [("dp", {})]
    for f in _pow2_divisors(n_devices):
        if f > 1:
            candidates.append(("fsdp", {Axis.FSDP: f}))
    for t in _pow2_divisors(n_devices):
        if t > 1:
            candidates.append(
                ("tp_fsdp", {Axis.FSDP: n_devices // t, Axis.TENSOR: t}))
    if has_stages:
        for p in _pow2_divisors(n_devices):
            for t in _pow2_divisors(n_devices // p):
                if p > 1:
                    candidates.append(("tp_fsdp", {
                        Axis.FSDP: n_devices // (p * t), Axis.TENSOR: t,
                        Axis.PIPE: p}))

    for strategy, sizes in candidates:
        if math.prod(sizes.values()) > n_devices:
            continue
        per_dev = _per_device_bytes(leaves, strategy, sizes, optimizer)
        if per_dev <= budget:
            mesh = MeshConfig(
                data=-1,
                fsdp=sizes.get(Axis.FSDP, 1),
                tensor=sizes.get(Axis.TENSOR, 1),
                pipe=sizes.get(Axis.PIPE, 1),
            )
            return AutoPlan(mesh, strategy, per_dev, total)

    raise ValueError(
        f"model state ({total / 2**30:.2f}GiB replicated) does not fit "
        f"{n_devices} devices x {device_memory_bytes / 2**30:.2f}GiB even "
        f"fully sharded — more chips or a smaller model")


def auto_shard(model, sample_batch_inputs, *, n_devices: int | None = None,
               device_memory_bytes: float | None = None,
               optimizer: str = "adamw", seed: int = 0) -> AutoPlan:
    """`plan_auto_shard` from a live model: abstract-init (no memory
    allocated) to recover shapes + logical names, then plan.

    ``sample_batch_inputs``: the positional inputs ``model.init`` takes
    (e.g. a token array). Returns an AutoPlan whose ``mesh`` /
    ``strategy`` feed `create_mesh` and the Trainer.
    """
    if n_devices is None:
        n_devices = len(jax.devices())
    if device_memory_bytes is None:
        device_memory_bytes = _device_memory_bytes()
    abstract = jax.eval_shape(
        lambda r, *a: model.init(r, *a),
        jax.random.key(seed), *sample_batch_inputs)
    return plan_auto_shard(
        leaves_of(abstract), n_devices, device_memory_bytes,
        optimizer=optimizer)


def validate_plan(trainer, sample_batch, *,
                  device_memory_bytes: float | None = None,
                  headroom: float = 0.0) -> dict:
    """Compiler-verified fit check for a plan: AOT-compile the Trainer's
    ACTUAL train step from abstract state (`Trainer.lower_step` — no
    params materialized, nothing executed) and compare XLA's own memory
    analysis against per-chip HBM.

    `plan_auto_shard` estimates from training-state bytes with a
    headroom fraction standing in for activations; this closes the loop
    with the number that decides OOM in reality: the compiled
    executable's per-device inputs + outputs + scratch (donated state
    counted once via the alias bytes). Use it before burning pod time on
    a borderline plan:

        plan = auto_shard(model, (tokens,))
        tr = Trainer(model, opt, loss, mesh=create_mesh(plan.mesh),
                     strategy=plan.strategy)
        report = validate_plan(tr, batch)   # {'fits': ..., 'need_bytes'...}

    Costs one XLA compile (minutes for billion-parameter configs on a
    CPU host — still far cheaper than a failed pod launch). ``headroom``
    here defaults to 0: XLA's analysis already includes activations and
    scratch, the things the planner's headroom guessed at."""
    if device_memory_bytes is None:
        device_memory_bytes = _device_memory_bytes()
    mem = trainer.lower_step(sample_batch).compile().memory_analysis()
    need = (mem.argument_size_in_bytes + mem.output_size_in_bytes
            - mem.alias_size_in_bytes + mem.temp_size_in_bytes)
    budget = device_memory_bytes * (1.0 - headroom)
    # every component of need_bytes is surfaced, so the breakdown
    # reconstructs the headline: arg + out - aliased + temp
    return {
        "fits": need <= budget,
        "need_bytes": int(need),
        "budget_bytes": int(budget),
        "arg_bytes": int(mem.argument_size_in_bytes),
        "out_bytes": int(mem.output_size_in_bytes),
        "temp_bytes": int(mem.temp_size_in_bytes),
        "aliased_bytes": int(mem.alias_size_in_bytes),
    }


def _device_memory_bytes() -> float:
    """Per-chip HBM from the runtime, with a v5e-sized fallback when the
    backend doesn't report it (CPU sim)."""
    try:
        stats = jax.devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return float(stats["bytes_limit"])
    except Exception:
        pass
    return 16.0 * 2**30
