"""Pipeline parallelism — GPipe and 1F1B schedules, SPMD-style (SURVEY.md
§2c "PP").

The reference implements a manual 2-stage pipeline: split the batch into
micro-batches and overlap stage-2 of split k with stage-1 of split k+1
(reference 03_model_parallel.ipynb:538-560), with GPipe/1F1B schedule theory
in cells 14-15 (:637-710). On TPU the idiomatic equivalent is *not* device
placement + streams but a shard_map over the "pipe" mesh axis:

  * every device holds its stage's parameters (the scanned layer axis is
    sharded over "pipe" — parallel/tp.py rule STAGE→pipe);
  * one `lax.scan` runs T = M + P - 1 ticks; at each tick every device
    applies its stage to the activation it holds, then `ppermute` rotates
    activations one hop to the next stage (neighbor ICI transfer);
  * stage 0 injects micro-batch t at tick t, stage P-1 banks its result at
    tick t into the output buffer — the software pipeline the reference
    builds by hand with CUDA streams, expressed as one compiled collective
    loop;
  * backward is automatic: reverse-mode AD of scan+ppermute runs the
    mirror-image reverse pipeline (activations for each micro-batch are
    rematerialized per-stage when ``remat=True`` — GPipe's activation
    recomputation, reference :637-643).

Only the "pipe" axis goes manual (`axis_names={"pipe"}`): data/fsdp/tensor/
seq stay under the automatic partitioner, so PP composes with every other
strategy — inside a stage, XLA still inserts the TP psums and FSDP
all-gathers.

Bubble fraction is (P-1)/(M+P-1), the GPipe figure; the micro-batch count M
is the knob the reference sweeps in its split-size benchmark (:586-623).

GPipe's weakness is memory: it runs all M forwards before the first
backward, so every in-flight micro-batch holds residuals — O(M) activation
slots per device (remat only trades which tensors, not how many
micro-batches). The reference's cell 15 (03_model_parallel.ipynb:668-697)
describes the fix: the 1F1B schedule starts a micro-batch's backward as soon
as its forward clears the last stage, bounding in-flight activations by the
*stage count*. `one_f_one_b` below implements it. One JAX-specific truth
shapes the API: 1F1B interleaves backwards with forwards, so the loss
cotangent must exist while forwards are still running — it cannot be a
`custom_vjp` around a pure forward function. It is therefore a fused
train-grads primitive (forward + loss + backward in one compiled loop)
returning gradients directly, and the Trainer selects it as an alternative
step builder (``pp_schedule="1f1b"``) rather than an alternative forward.
PipeDream's weight stashing / vertical sync (:685-691) are deliberately NOT
implemented: they exist to hide gradient staleness in an *asynchronous*
pipeline, while this schedule is synchronous within one optimizer step — the
flush variant (PipeDream-flush ≙ non-interleaved 1F1B, :697), which has no
staleness to hide.

Megatron's interleaved-1F1B / virtual-pipeline (reference :699-705) is also
deliberately not implemented, after working the schedule out in this SPMD
formulation. Its bubble win divides the (P-1)-deep warmup/cooldown by the
virtual-stage count V — but that win exists only because each GPU runs its
own *asynchronous* F/B slot sequence over p2p sends, skipping idle slots.
In a `shard_map` + `lax.scan` pipeline every tick is a collective step all
devices execute in lockstep: a per-device F-or-B choice needs non-uniform
control flow around the ppermutes (illegal in SPMD), and masking both slots
per tick pays both slots' compute whether used or not. Worked example
(P=2, V=2, M=4, B=2F): the lockstep interleaved schedule and the lockstep
non-interleaved one waste exactly the same 8 chunk-slots — V cancels out.
The honest TPU answers to the bubble are the ones implemented: raise M (the
reference's own split-size sweep, bubble (P-1)/(M+P-1)) and keep P shallow
by preferring fsdp/tensor axes (parallel/auto.py plans in that order).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorchdistributed_tpu.runtime.mesh import Axis


def stage_microbatch_key(base, stage, microbatch):
    """The ONE key-derivation rule for stochastic layers inside pipeline
    schedules: both GPipe and 1F1B fold (micro-batch, stage) into the
    per-step base key, and the 1F1B backward slot re-derives the same key
    for its recompute — dropout masks are identical in forward and
    recompute by construction. Stage bodies fold the layer index on top
    (models/transformer.make_stage_apply)."""
    return jax.random.fold_in(jax.random.fold_in(base, microbatch), stage)


def gpipe_spmd(
    stage_apply: Callable,
    stage_params,
    x: jax.Array,
    *,
    num_microbatches: int,
    mesh=None,
    remat: bool = True,
    remat_policy: str = "full",
    dropout_rng=None,
    collect_aux: bool = False,
):
    """Run ``stage_apply(params_for_my_stage, h) -> h`` as a GPipe pipeline
    over the "pipe" mesh axis.

    ``stage_params``: pytree whose leaves have leading dim P (stage-stacked,
    sharded over "pipe"). ``x``: [batch, ...] global activations (any
    data/seq sharding — those axes stay automatic). ``num_microbatches``
    must divide the global batch. Returns activations with x's layout.

    ``dropout_rng``: when given, ``stage_apply`` is called as
    ``stage_apply(params, h, key)`` with ``key =
    stage_microbatch_key(dropout_rng, stage, microbatch)`` — every
    (stage, micro-batch) pair draws an independent dropout stream.

    ``collect_aux``: when True, ``stage_apply`` returns ``(h, aux)`` with
    ``aux`` a scalar per-stage auxiliary loss (the Switch-MoE load-balance
    term); the return becomes ``(activations, aux_mean)`` where aux_mean
    averages over micro-batches and sums over stages. Its gradient flows
    through ordinary AD of the schedule.
    """
    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or not mesh.axis_names:
            raise ValueError(
                "gpipe_spmd needs a mesh: call under jax.set_mesh(mesh) or "
                "pass mesh=")
    if Axis.PIPE not in mesh.axis_names:
        raise ValueError(
            f"gpipe_spmd needs a '{Axis.PIPE}' mesh axis; got axes "
            f"{mesh.axis_names} (build the mesh with runtime.mesh.create_mesh)")
    n_stages = mesh.shape[Axis.PIPE]
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stage_params leading dims {leading} must equal the mesh's "
            f"pipe axis size {n_stages}")
    if remat:
        from pytorchdistributed_tpu.models.transformer import (
            checkpoint_policy,
        )
        stage_apply = jax.checkpoint(
            stage_apply, policy=checkpoint_policy(remat_policy))

    param_spec = jax.tree.map(lambda _: P(Axis.PIPE), stage_params)

    args = (stage_params, x)
    in_specs = (param_spec, P())
    out_specs = (P(), P()) if collect_aux else P()
    if dropout_rng is not None:
        args += (dropout_rng,)
        in_specs += (P(),)
    fn = jax.shard_map(
        functools.partial(_gpipe_local, stage_apply,
                          num_microbatches=num_microbatches,
                          n_stages=n_stages, collect_aux=collect_aux),
        mesh=mesh,
        axis_names={Axis.PIPE},
        in_specs=in_specs,
        out_specs=out_specs,
    )
    return fn(*args)


def _gpipe_local(stage_apply, stage_params, x, rng=None, *,
                 num_microbatches: int, n_stages: int, collect_aux: bool):
    """Per-device pipeline body (inside shard_map, "pipe" axis manual)."""
    m = num_microbatches
    p = n_stages
    stage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), stage_params)
    my_stage = lax.axis_index(Axis.PIPE)

    b = x.shape[0]
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible by "
                         f"num_microbatches {m}")
    mb = b // m
    # Promote the invariant→varying boundary to fp32 explicitly: its
    # transpose is a psum of x's cotangents over "pipe", and XLA:CPU's
    # AllReducePromotion pass crashes on sub-fp32 all-reduces (the TPU
    # backend would promote it to fp32 anyway).
    if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != jnp.float32:
        x = lax.pcast(x.astype(jnp.float32), Axis.PIPE,
                      to="varying").astype(x.dtype)
    else:
        x = lax.pcast(x, Axis.PIPE, to="varying")
    x_mb = x.reshape(m, mb, *x.shape[1:])

    # pcast in fp32, cast after: a sub-fp32 pcast lowers to a copy-reduction
    # all-reduce that XLA:CPU's AllReducePromotion pass crashes cloning
    def varying_zeros(shape, dtype):
        z = lax.pcast(jnp.zeros(shape, jnp.float32), Axis.PIPE, to="varying")
        return z.astype(dtype)

    acts0 = varying_zeros(x_mb[0].shape, x.dtype)
    outs0 = varying_zeros(x_mb.shape, x.dtype)
    aux0 = varying_zeros((), jnp.float32)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        acts, outs, aux_acc = carry
        # stage 0 feeds micro-batch t; everyone else consumes the rotated
        # activation from the previous stage; this stage is processing
        # micro-batch t - my_stage (garbage outside [0, m), masked below)
        feed = x_mb[jnp.clip(t, 0, m - 1)]
        h_in = jnp.where(my_stage == 0, feed, acts)
        mb_idx = jnp.clip(t - my_stage, 0, m - 1)
        if rng is None:
            h_out = stage_apply(stage_params, h_in)
        else:
            h_out = stage_apply(stage_params, h_in,
                                stage_microbatch_key(rng, my_stage, mb_idx))
        if collect_aux:
            h_out, aux = h_out
            active = (t >= my_stage) & (t - my_stage < m)
            aux_acc = aux_acc + jnp.where(active, aux, 0.0)
        # last stage banks micro-batch t-(p-1) at tick t
        out_idx = jnp.clip(t - (p - 1), 0, m - 1)
        banked = lax.dynamic_update_index_in_dim(outs, h_out, out_idx, 0)
        write = (my_stage == p - 1) & (t >= p - 1)
        outs = jnp.where(write, banked, outs)
        acts = lax.ppermute(h_out, Axis.PIPE, perm)
        return (acts, outs, aux_acc), None

    (_, outs, aux_acc), _ = lax.scan(tick, (acts0, outs0, aux0),
                                     jnp.arange(m + p - 1))
    # only stage p-1 holds real outputs; psum over "pipe" replicates them
    # (and marks the result invariant over the axis for the out_spec).
    # fp32 for the wire: XLA promotes sub-fp32 all-reduces anyway, and its
    # CPU backend crashes doing so (AllReducePromotion on bf16).
    masked = jnp.where(my_stage == p - 1, outs, jnp.zeros_like(outs))
    outs = lax.psum(masked.astype(jnp.float32), Axis.PIPE).astype(outs.dtype)
    outs = outs.reshape(b, *outs.shape[2:])
    if collect_aux:
        # sum over stages (psum), mean over micro-batches
        return outs, lax.psum(aux_acc, Axis.PIPE) / m
    return outs


# ---------------------------------------------------------------------------
# 1F1B (PipeDream-flush / non-interleaved) schedule
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PipelineParts:
    """A model's decomposition for the 1F1B fused train step (the analog of
    the reference's manual seq1/seq2 stage split, 03_model_parallel.ipynb:
    325-349, generalized to pre/stages/head):

      * ``split(params) -> (pre, stage, head)`` param sub-trees — ``stage``
        leaves stacked ``[P, ...]``;
      * ``pre_apply(pre, batch_inputs) -> x``: everything before stage 0
        (embeddings) — differentiated by AD outside the pipeline via the
        ``dx`` that `one_f_one_b` returns;
      * ``stage_apply(stage_leaf, h) -> h``: one pipeline stage;
      * ``head_loss(head, h, targets) -> scalar fp32``: final projection +
        loss, fused into the last stage;
      * ``merge_grads(pre_g, stage_g, head_g)`` -> grads shaped like the full
        param tree (summing any tied leaves, e.g. GPT-2's tied embedding);
      * ``targets_of(batch)`` (optional): the pytree handed to head_loss per
        micro-batch — lets a model precompute globally-normalized loss
        weights (masked LM) so per-micro-batch losses still sum exactly to
        the full-batch loss. Default: ``batch["targets"]``.
      * ``stage_apply_aux`` (optional): ``(stage_leaf, h, key=None) ->
        (h, aux)`` variant returning a scalar per-stage auxiliary loss (the
        Switch-MoE load-balance term); selected by the Trainer when
        ``moe_experts > 0`` together with ``one_f_one_b(aux_weight=...)``.

    ``stage_apply`` may take an optional third ``key`` argument (dropout
    stream); the schedule passes ``stage_microbatch_key(rng, stage, mb)``
    when the Trainer supplies a ``dropout_rng``.
    """

    split: Callable
    pre_apply: Callable
    stage_apply: Callable
    head_loss: Callable
    merge_grads: Callable
    targets_of: Callable | None = None
    stage_apply_aux: Callable | None = None


def _require_pipe_mesh(mesh, who: str):
    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or not mesh.axis_names:
            raise ValueError(
                f"{who} needs a mesh: call under jax.set_mesh(mesh) or "
                f"pass mesh=")
    if Axis.PIPE not in mesh.axis_names:
        raise ValueError(
            f"{who} needs a '{Axis.PIPE}' mesh axis; got axes "
            f"{mesh.axis_names} (build the mesh with runtime.mesh.create_mesh)")
    return mesh


def one_f_one_b(
    stage_apply: Callable,
    stage_params,
    head_loss: Callable,
    head_params,
    x: jax.Array,
    targets,
    *,
    num_microbatches: int,
    mesh=None,
    dropout_rng=None,
    aux_weight: float = 0.0,
):
    """Non-interleaved 1F1B pipeline **train-grads** primitive (the
    reference's PipeDream-flush schedule, 03_model_parallel.ipynb:668-697).

    One compiled loop runs T = M + 2P - 2 pair-ticks; at each tick every
    device executes one forward slot and one backward slot. Micro-batch k's
    forward reaches stage s at tick k+s; its backward reaches stage s at
    tick k + 2P-2-s — so at the last stage the backward starts the same tick
    the forward finishes (the "one forward, one backward" steady state), and
    a stage holds at most 2(P-s)-1 in-flight residuals. Residuals live in a
    ring buffer of 2P-1 micro-batch slots — bounded by the *stage count* —
    versus GPipe's M+P-1 (AD of the forward scan saves one per tick). The
    backward slot rebuilds its VJP by re-running the stage forward from the
    stored stage *input* (activation recomputation, reference :637-643), so
    per-micro-batch compute is 2F+B — identical to GPipe with remat=True.

    Args:
      stage_apply: ``(stage_params_leaf, h) -> h`` — one stage's forward.
      stage_params: pytree, leaves ``[P, ...]`` stage-stacked (sharded over
        the "pipe" mesh axis).
      head_loss: ``(head_params, h, targets_mb) -> scalar fp32 loss`` (mean
        over the micro-batch) — the last stage's projection + loss, fused
        into the pipeline so its cotangent is born where the backward starts.
      head_params: pytree (replicated over "pipe").
      x: ``[batch, ...]`` activations entering stage 0 (e.g. embedded
        tokens). Other mesh axes (data/fsdp/tensor/seq) stay automatic.
      targets: ``[batch, ...]`` labels consumed by ``head_loss``.
      dropout_rng: optional per-step key; when given, stage_apply is called
        with ``stage_microbatch_key(dropout_rng, stage, microbatch)`` —
        and the backward slot re-derives the SAME key for its recompute,
        so dropout masks match between forward and recomputation.
      aux_weight: when nonzero, stage_apply must return ``(h, aux)``; the
        loss gains ``aux_weight · mean_mb(Σ_stages aux)``, whose gradient
        is seeded locally in each backward slot (the aux term never flows
        through later stages — it is a direct function of the stage).

    Returns:
      ``(loss, stage_grads, head_grads, dx)``: mean loss over micro-batches;
      grads for stage_params (``[P, ...]`` stacked) and head_params
      (replicated); and ``dx``, the loss cotangent w.r.t. ``x`` — feed it to
      the VJP of whatever produced ``x`` (embedding) to complete the step.
    """
    mesh = _require_pipe_mesh(mesh, "one_f_one_b")
    n_stages = mesh.shape[Axis.PIPE]
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stage_params leading dims {leading} must equal the mesh's "
            f"pipe axis size {n_stages}")
    if x.shape[0] % num_microbatches:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by num_microbatches "
            f"{num_microbatches}")

    param_spec = jax.tree.map(lambda _: P(Axis.PIPE), stage_params)
    rep = jax.tree.map(lambda _: P(), head_params)

    args = (stage_params, head_params, x, targets)
    in_specs = (param_spec, rep, P(), P())
    if dropout_rng is not None:
        args += (dropout_rng,)
        in_specs += (P(),)
    fn = jax.shard_map(
        functools.partial(_one_f_one_b_local, stage_apply, head_loss,
                          m=num_microbatches, p=n_stages,
                          aux_weight=aux_weight),
        mesh=mesh,
        axis_names={Axis.PIPE},
        in_specs=in_specs,
        out_specs=(P(), param_spec, rep, P()),
    )
    return fn(*args)


def _to_varying(v):
    """Mark a pipe-invariant value varying. Sub-fp32 floats ride the wire as
    fp32: a sub-fp32 pcast lowers to a copy-reduction all-reduce that
    XLA:CPU's AllReducePromotion pass crashes cloning (TPU would silently
    promote it anyway)."""
    if jnp.issubdtype(v.dtype, jnp.floating) and v.dtype != jnp.float32:
        return lax.pcast(v.astype(jnp.float32), Axis.PIPE,
                         to="varying").astype(v.dtype)
    return lax.pcast(v, Axis.PIPE, to="varying")


def _one_f_one_b_local(stage_apply, head_loss, stage_params, head_params,
                       x, targets, rng=None, *, m: int, p: int,
                       aux_weight: float = 0.0):
    """Per-device 1F1B body (inside shard_map, "pipe" axis manual)."""
    s = lax.axis_index(Axis.PIPE)
    r = 2 * p - 1  # residual ring-buffer slots: ≥ max in-flight (2P-2) + 1
    stage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), stage_params)
    # Every device takes the head vjp (masked out except at the last stage).
    # head_params must be explicitly varying first: a vjp w.r.t. a
    # pipe-INVARIANT input transposes the implicit invariant→varying
    # broadcast into a psum over "pipe", silently summing every stage's
    # masked-out garbage head-gradient into the real one.
    head_params = jax.tree.map(_to_varying, head_params)

    b = x.shape[0]
    mb = b // m
    x_mb = _to_varying(x.reshape(m, mb, *x.shape[1:]))
    t_mb = jax.tree.map(
        lambda t: _to_varying(t.reshape(m, b // m, *t.shape[1:])), targets)

    def vz(shape, dtype):
        return _to_varying(jnp.zeros(shape, dtype))

    act_shape, act_dtype = x_mb.shape[1:], x.dtype
    carry0 = (
        vz(act_shape, act_dtype),                       # f_recv
        vz(act_shape, act_dtype),                       # b_recv
        vz((r,) + act_shape, act_dtype),                # resid ring buffer
        jax.tree.map(lambda a: vz(a.shape, a.dtype), stage_params),
        jax.tree.map(lambda a: vz(a.shape, a.dtype), head_params),
        vz((), jnp.float32),                            # loss accumulator
        vz((), jnp.float32),                            # aux-loss accumulator
        vz(x_mb.shape, act_dtype),                      # dx per micro-batch
    )
    fwd = [(i, (i + 1) % p) for i in range(p)]
    bwd = [(i, (i - 1) % p) for i in range(p)]

    def masked_add(acc, g, active):
        return jax.tree.map(
            lambda a, d: a + jnp.where(active, d, jnp.zeros_like(d)), acc, g)

    def apply_stage(params, h, key):
        """stage_apply with the optional dropout key; normalizes the return
        to (h, aux) — aux is only consumed when aux_weight is set."""
        out = (stage_apply(params, h) if key is None
               else stage_apply(params, h, key))
        return out if aux_weight else (out, None)

    def tick(carry, u):
        (f_recv, b_recv, resid, stage_g, head_g, loss_acc, aux_acc,
         dx) = carry

        # ---- forward slot: micro-batch k_f = u - s ----
        k_f = u - s
        active_f = (k_f >= 0) & (k_f < m)
        kf = jnp.clip(k_f, 0, m - 1)
        h_in = jnp.where(s == 0, x_mb[kf], f_recv)
        key_f = None if rng is None else stage_microbatch_key(rng, s, kf)
        h_out, aux_f = apply_stage(stage_params, h_in, key_f)
        if aux_weight:
            aux_acc = aux_acc + jnp.where(active_f, aux_f, 0.0)
        resid = jnp.where(
            active_f,
            lax.dynamic_update_index_in_dim(resid, h_in, kf % r, 0), resid)
        # Last stage: fuse projection+loss and bear the cotangent that seeds
        # this same tick's backward slot (at stage P-1, k_b == k_f).
        mb_targets = jax.tree.map(lambda t: t[kf], t_mb)
        loss_k, head_vjp = jax.vjp(
            lambda hp, h: head_loss(hp, h, mb_targets), head_params, h_out)
        # Global loss = (1/M)·Σ per-micro-batch means, so each micro-batch's
        # cotangent is 1/M.
        dhead, dh_loss = head_vjp(_to_varying(jnp.full((), 1 / m,
                                                       loss_k.dtype)))
        at_last = active_f & (s == p - 1)
        loss_acc = loss_acc + jnp.where(at_last, loss_k, 0.0)
        head_g = masked_add(head_g, dhead, at_last)

        # ---- backward slot: micro-batch k_b = u - (2P-2-s) ----
        k_b = u - (2 * p - 2 - s)
        active_b = (k_b >= 0) & (k_b < m)
        kb = jnp.clip(k_b, 0, m - 1)
        g_in = jnp.where(s == p - 1, dh_loss.astype(act_dtype), b_recv)
        h_res = resid[kb % r]
        # Recompute the stage forward from the stored input to rebuild the
        # VJP — activation recomputation by construction. The SAME
        # (stage, micro-batch) key re-derives the forward's dropout masks.
        key_b = None if rng is None else stage_microbatch_key(rng, s, kb)
        _, stage_vjp = jax.vjp(
            lambda sp, h: apply_stage(sp, h, key_b), stage_params, h_res)
        if aux_weight:
            # The aux term is a direct function of this stage — its
            # cotangent (aux_weight / M, from loss = aux_weight·mean_mb)
            # is seeded here and never rides the inter-stage wires.
            aux_seed = _to_varying(jnp.full((), aux_weight / m, jnp.float32))
            dstage, dh_in = stage_vjp((g_in, aux_seed))
        else:
            dstage, dh_in = stage_vjp((g_in, None))
        stage_g = masked_add(stage_g, dstage, active_b)
        dx = jnp.where(
            active_b & (s == 0),
            lax.dynamic_update_index_in_dim(dx, dh_in, kb, 0), dx)

        # ---- rotate: activations one hop forward, cotangents one back ----
        f_recv = lax.ppermute(h_out, Axis.PIPE, fwd)
        b_recv = lax.ppermute(dh_in, Axis.PIPE, bwd)
        return (f_recv, b_recv, resid, stage_g, head_g, loss_acc, aux_acc,
                dx), None

    carry, _ = lax.scan(tick, carry0, jnp.arange(m + 2 * p - 2))
    _, _, _, stage_g, head_g, loss_acc, aux_acc, dx = carry

    def replicate_from(acc, holder):
        """psum the holder stage's accumulator to every device (fp32 wire:
        see _to_varying)."""
        def one(g):
            g32 = jnp.where(holder, g, jnp.zeros_like(g)).astype(jnp.float32)
            return lax.psum(g32, Axis.PIPE).astype(g.dtype)
        return jax.tree.map(one, acc)

    loss = lax.psum(jnp.where(s == p - 1, loss_acc, 0.0), Axis.PIPE) / m
    if aux_weight:
        loss = loss + aux_weight * lax.psum(aux_acc, Axis.PIPE) / m
    head_g = replicate_from(head_g, s == p - 1)
    dx = replicate_from(dx, s == 0)
    stage_g = jax.tree.map(lambda g: g[None], stage_g)  # [1,...] -> P-stacked
    return loss, stage_g, head_g, dx.reshape(b, *x.shape[1:])
