"""Pipeline parallelism — GPipe schedule, SPMD-style (SURVEY.md §2c "PP").

The reference implements a manual 2-stage pipeline: split the batch into
micro-batches and overlap stage-2 of split k with stage-1 of split k+1
(reference 03_model_parallel.ipynb:538-560), with GPipe/1F1B schedule theory
in cells 14-15 (:637-710). On TPU the idiomatic equivalent is *not* device
placement + streams but a shard_map over the "pipe" mesh axis:

  * every device holds its stage's parameters (the scanned layer axis is
    sharded over "pipe" — parallel/tp.py rule STAGE→pipe);
  * one `lax.scan` runs T = M + P - 1 ticks; at each tick every device
    applies its stage to the activation it holds, then `ppermute` rotates
    activations one hop to the next stage (neighbor ICI transfer);
  * stage 0 injects micro-batch t at tick t, stage P-1 banks its result at
    tick t into the output buffer — the software pipeline the reference
    builds by hand with CUDA streams, expressed as one compiled collective
    loop;
  * backward is automatic: reverse-mode AD of scan+ppermute runs the
    mirror-image reverse pipeline (activations for each micro-batch are
    rematerialized per-stage when ``remat=True`` — GPipe's activation
    recomputation, reference :637-643).

Only the "pipe" axis goes manual (`axis_names={"pipe"}`): data/fsdp/tensor/
seq stay under the automatic partitioner, so PP composes with every other
strategy — inside a stage, XLA still inserts the TP psums and FSDP
all-gathers.

Bubble fraction is (P-1)/(M+P-1), the GPipe figure; the micro-batch count M
is the knob the reference sweeps in its split-size benchmark (:586-623).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorchdistributed_tpu.runtime.mesh import Axis


def gpipe_spmd(
    stage_apply: Callable,
    stage_params,
    x: jax.Array,
    *,
    num_microbatches: int,
    mesh=None,
    remat: bool = True,
    remat_policy: str = "full",
):
    """Run ``stage_apply(params_for_my_stage, h) -> h`` as a GPipe pipeline
    over the "pipe" mesh axis.

    ``stage_params``: pytree whose leaves have leading dim P (stage-stacked,
    sharded over "pipe"). ``x``: [batch, ...] global activations (any
    data/seq sharding — those axes stay automatic). ``num_microbatches``
    must divide the global batch. Returns activations with x's layout.
    """
    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or not mesh.axis_names:
            raise ValueError(
                "gpipe_spmd needs a mesh: call under jax.set_mesh(mesh) or "
                "pass mesh=")
    if Axis.PIPE not in mesh.axis_names:
        raise ValueError(
            f"gpipe_spmd needs a '{Axis.PIPE}' mesh axis; got axes "
            f"{mesh.axis_names} (build the mesh with runtime.mesh.create_mesh)")
    n_stages = mesh.shape[Axis.PIPE]
    leading = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if leading != {n_stages}:
        raise ValueError(
            f"stage_params leading dims {leading} must equal the mesh's "
            f"pipe axis size {n_stages}")
    if remat:
        from pytorchdistributed_tpu.models.transformer import (
            checkpoint_policy,
        )
        stage_apply = jax.checkpoint(
            stage_apply, policy=checkpoint_policy(remat_policy))

    param_spec = jax.tree.map(lambda _: P(Axis.PIPE), stage_params)

    fn = jax.shard_map(
        functools.partial(_gpipe_local, stage_apply,
                          num_microbatches=num_microbatches,
                          n_stages=n_stages),
        mesh=mesh,
        axis_names={Axis.PIPE},
        in_specs=(param_spec, P()),
        out_specs=P(),
    )
    return fn(stage_params, x)


def _gpipe_local(stage_apply, stage_params, x, *, num_microbatches: int,
                 n_stages: int):
    """Per-device pipeline body (inside shard_map, "pipe" axis manual)."""
    m = num_microbatches
    p = n_stages
    stage_params = jax.tree.map(lambda a: jnp.squeeze(a, 0), stage_params)
    my_stage = lax.axis_index(Axis.PIPE)

    b = x.shape[0]
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible by "
                         f"num_microbatches {m}")
    mb = b // m
    # Promote the invariant→varying boundary to fp32 explicitly: its
    # transpose is a psum of x's cotangents over "pipe", and XLA:CPU's
    # AllReducePromotion pass crashes on sub-fp32 all-reduces (the TPU
    # backend would promote it to fp32 anyway).
    if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != jnp.float32:
        x = lax.pcast(x.astype(jnp.float32), Axis.PIPE,
                      to="varying").astype(x.dtype)
    else:
        x = lax.pcast(x, Axis.PIPE, to="varying")
    x_mb = x.reshape(m, mb, *x.shape[1:])

    # pcast in fp32, cast after: a sub-fp32 pcast lowers to a copy-reduction
    # all-reduce that XLA:CPU's AllReducePromotion pass crashes cloning
    def varying_zeros(shape, dtype):
        z = lax.pcast(jnp.zeros(shape, jnp.float32), Axis.PIPE, to="varying")
        return z.astype(dtype)

    acts0 = varying_zeros(x_mb[0].shape, x.dtype)
    outs0 = varying_zeros(x_mb.shape, x.dtype)
    perm = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        acts, outs = carry
        # stage 0 feeds micro-batch t; everyone else consumes the rotated
        # activation from the previous stage
        feed = x_mb[jnp.clip(t, 0, m - 1)]
        h_in = jnp.where(my_stage == 0, feed, acts)
        h_out = stage_apply(stage_params, h_in)
        # last stage banks micro-batch t-(p-1) at tick t
        out_idx = jnp.clip(t - (p - 1), 0, m - 1)
        banked = lax.dynamic_update_index_in_dim(outs, h_out, out_idx, 0)
        write = (my_stage == p - 1) & (t >= p - 1)
        outs = jnp.where(write, banked, outs)
        acts = lax.ppermute(h_out, Axis.PIPE, perm)
        return (acts, outs), None

    (_, outs), _ = lax.scan(tick, (acts0, outs0), jnp.arange(m + p - 1))
    # only stage p-1 holds real outputs; psum over "pipe" replicates them
    # (and marks the result invariant over the axis for the out_spec).
    # fp32 for the wire: XLA promotes sub-fp32 all-reduces anyway, and its
    # CPU backend crashes doing so (AllReducePromotion on bf16).
    masked = jnp.where(my_stage == p - 1, outs, jnp.zeros_like(outs))
    outs = lax.psum(masked.astype(jnp.float32), Axis.PIPE).astype(outs.dtype)
    return outs.reshape(b, *outs.shape[2:])
