"""Tensor parallelism — Megatron-style sharding as logical-axis rules.

The reference names Megatron only for its pipeline schedule
(reference 03_model_parallel.ipynb:699); intra-layer tensor parallelism is
absent there but required for framework completeness (SURVEY.md §2c). On TPU
it is NOT a wrapper class or hand-written f/g collectives: model parameters
carry *logical* axis names (via `nn.with_logical_partitioning`), and a rule
table maps logical axes onto mesh axes. XLA then derives the Megatron
communication pattern itself:

  * column-parallel Dense  = kernel ("embed", "mlp"→tensor): output stays
    sharded, no collective;
  * row-parallel Dense     = kernel ("mlp"→tensor, "embed"): XLA inserts the
    activation psum that Megatron's `g` operator performs;
  * sharded attention heads = ("embed", "heads"→tensor, "kv").

The same logical names serve FSDP (shard "embed" on the fsdp axis) and
sequence parallelism (activations' "seq" on the seq axis), so one model
definition supports every strategy combination — the design stance of
SURVEY.md §7 (strategies are PartitionSpec choices, not model rewrites).
"""

from __future__ import annotations

import flax.linen as nn
import jax
from jax.sharding import Mesh

from pytorchdistributed_tpu.runtime.mesh import Axis


class Logical:
    """Canonical logical axis names used by the model zoo."""

    BATCH = "batch"
    SEQ = "seq"          # activation sequence dim (context parallelism)
    EMBED = "embed"      # model/hidden dim
    MLP = "mlp"          # FFN intermediate dim (Megatron column dim)
    HEADS = "heads"      # attention heads (Megatron attention shard dim)
    KV = "kv"            # per-head dim (never sharded)
    VOCAB = "vocab"      # embedding/logit dim
    EXPERT = "expert"    # MoE expert dim
    EGROUP = "egroup"    # MoE routing-group dim (models/moe.py grouped
    #                      tokens: one group per data×fsdp×expert shard)
    CONV_IN = "conv_in"
    CONV_OUT = "conv_out"
    STAGE = "stage"      # pipeline stage dim (scanned-layer models)


# rule tables: logical axis -> mesh axis (or None = replicated). Written as
# tuple-of-pairs, the format `flax.linen.logical_axis_rules` accepts.
_COMMON_ACTIVATION_RULES = (
    (Logical.BATCH, (Axis.DATA, Axis.FSDP)),
    (Logical.SEQ, Axis.SEQ),
    (Logical.STAGE, Axis.PIPE),
    # MoE routing groups tile every batch-ish axis INCLUDING "expert":
    # the layout in which grouped dispatch is a pure permutation (a
    # literal all_to_all), and a free slice of the (data, fsdp)-sharded
    # tokens since they were replicated over the expert axis.
    (Logical.EGROUP, (Axis.DATA, Axis.FSDP, Axis.EXPERT)),
)

_PARAM_RULES = {
    # DDP: params fully replicated — except stacked expert kernels,
    # which shard over "expert" under EVERY strategy (a dp×expert mesh
    # is the canonical MoE training mesh; on an expert-less mesh the
    # rule is a no-op).
    "dp": (
        (Logical.EXPERT, Axis.EXPERT),
    ),
    # ZeRO-3: shard the embed dim of every large param over "fsdp".
    "fsdp": (
        (Logical.EMBED, Axis.FSDP),
        (Logical.VOCAB, Axis.FSDP),
        (Logical.CONV_OUT, Axis.FSDP),
        (Logical.EXPERT, Axis.EXPERT),
    ),
    # Megatron TP: FFN columns, attention heads and vocab over "tensor".
    "tp": (
        (Logical.MLP, Axis.TENSOR),
        (Logical.HEADS, Axis.TENSOR),
        (Logical.VOCAB, Axis.TENSOR),
        (Logical.EXPERT, Axis.EXPERT),
    ),
    # 2D: TP within, FSDP across — the large-model default.
    "tp_fsdp": (
        (Logical.MLP, Axis.TENSOR),
        (Logical.HEADS, Axis.TENSOR),
        (Logical.VOCAB, Axis.TENSOR),
        (Logical.EXPERT, Axis.EXPERT),
        (Logical.EMBED, Axis.FSDP),
        (Logical.CONV_OUT, Axis.FSDP),
    ),
}
_PARAM_RULES["ddp"] = _PARAM_RULES["dp"]
_PARAM_RULES["zero3"] = _PARAM_RULES["fsdp"]
_PARAM_RULES["2d"] = _PARAM_RULES["tp_fsdp"]


def logical_rules(strategy: str):
    """Full rule table (params + activations) for a named strategy."""
    if strategy not in _PARAM_RULES:
        raise ValueError(
            f"unknown strategy {strategy!r}; one of {sorted(_PARAM_RULES)}"
        )
    return _PARAM_RULES[strategy] + _COMMON_ACTIVATION_RULES


def has_logical_annotations(abstract_params) -> bool:
    """True if the (possibly abstract) param tree carries flax Partitioned
    boxes — i.e. the model declared logical axes."""
    found = False

    def visit(leaf):
        nonlocal found
        if isinstance(leaf, nn.Partitioned):
            found = True
        return leaf

    jax.tree.map(visit, abstract_params,
                 is_leaf=lambda x: isinstance(x, nn.Partitioned))
    return found


def logical_shardings(abstract_params, mesh: Mesh, strategy: str):
    """NamedShardings for a boxed (logically-annotated) param tree."""
    specs = nn.get_partition_spec(abstract_params)
    return nn.logical_to_mesh_sharding(specs, mesh, logical_rules(strategy))


