"""Overlap integration layer: route TP projections through the ring
collective matmuls (ops/overlap.py) as injectable ``dot_general``s.

The model zoo already funnels every weight matmul through an injectable
contraction (``flax.linen.Dense(dot_general=...)`` /
``jnp.einsum(_dot_general=...)`` — the channel ops/quant.py established).
This module supplies the overlap-aware injectable: a ``dot_general``
drop-in that, at trace time, looks at the ambient mesh
(``jax.set_mesh``, the same contract ring attention uses) and routes the
contraction through the all-gather→matmul or matmul→reduce-scatter ring
when a ring applies — a tp axis of size > 1, a plain last-dim⋅first-dim
contraction, and shapes that tile the ring — and otherwise falls
back to the exact monolithic path (the quantized dot under ``--quant``,
``lax.dot_general`` otherwise). The fallback is what makes the knob
safe: decode's s=1 steps, the GPipe stage bodies (already inside a
shard_map — a nested manual region cannot open another), toy shapes and
tensor-less meshes all degrade to today's program, never to an error.

``kind`` says which operand carries the tp shard:

  * "column" — w's trailing feature dim is tensor-sharded (QKV / q / kv
    fused projections, MLP wi): the all-gather→matmul ring.
  * "row" — the contraction dim is tensor-sharded (attention out
    projection, MLP wo): the matmul→reduce-scatter ring.

Cached per (kind, quant) so every call site shares ONE callable — flax
module attributes and jit caches key on identity, exactly like
quant.quantized_dot_general.
"""

from __future__ import annotations

import functools

import jax
from jax import lax

from pytorchdistributed_tpu.ops.overlap import (
    ring_column_matmul,
    ring_divisibility,
    ring_row_matmul,
)
from pytorchdistributed_tpu.ops.quant import dot_general_for

OVERLAP_MODES = ("ring", "xla", "off")

_SIMPLE_DIMS_BATCH = ((), ())


def validate_overlap(overlap: str) -> str:
    if overlap not in OVERLAP_MODES:
        raise ValueError(f"unknown overlap {overlap!r}; "
                         f"one of {OVERLAP_MODES}")
    return overlap


def _ambient_mesh():
    """The mesh the trace runs under (jax.set_mesh / the legacy
    thread-local the compat shim reads back); None when absent or
    axis-less — the ring then falls back monolithic."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
    except Exception:  # pragma: no cover - defensive: no mesh machinery
        return None
    if mesh is None or not getattr(mesh, "axis_names", ()):
        return None
    return mesh


@functools.lru_cache(maxsize=None)
def overlap_dot_general(kind: str, quant: str = "none"):
    """The overlap-aware ``lax.dot_general`` drop-in for one site kind.

    Signature-compatible with the real dot_general (``precision`` is
    accepted and ignored, like the quant injectable); the ring engages
    only for the projection-shaped contraction
    ``(((lhs.ndim-1,), (0,)), ((), ()))`` on a rank-3 activation whose
    shapes tile the ambient mesh's tensor axis."""
    if kind not in ("column", "row"):
        raise ValueError(f"unknown overlap site kind {kind!r}; "
                         f"'column' or 'row'")
    fallback = dot_general_for(quant) or lax.dot_general

    def dot_general(lhs, rhs, dimension_numbers, precision=None,
                    preferred_element_type=None):
        (lc, rc), (lb, rb) = dimension_numbers
        simple = (tuple(map(int, lc)) == (lhs.ndim - 1,)
                  and tuple(map(int, rc)) == (0,)
                  and (tuple(lb), tuple(rb)) == _SIMPLE_DIMS_BATCH)
        mesh = _ambient_mesh() if simple else None
        if mesh is None or not ring_divisibility(
                lhs.shape, rhs.shape, mesh, "tensor", kind):
            if fallback is lax.dot_general:
                return lax.dot_general(
                    lhs, rhs, dimension_numbers, precision=precision,
                    preferred_element_type=preferred_element_type)
            return fallback(
                lhs, rhs, dimension_numbers,
                preferred_element_type=preferred_element_type)
        ring = ring_column_matmul if kind == "column" else ring_row_matmul
        return ring(lhs, rhs, mesh=mesh, quant=quant,
                    preferred_element_type=preferred_element_type)

    dot_general.__name__ = f"overlap_{kind}_dot_general_{quant}"
    dot_general.__qualname__ = dot_general.__name__
    return dot_general


def site_dot_general(cfg, kind: str, default=None):
    """The per-site contraction for a TransformerConfig: the ring-routing
    injectable when ``cfg.overlap == "ring"`` applies to this config (no
    decode — s=1 ticks can't ring; no pipeline — stage bodies already
    run inside a manual region), else the quant injectable / ``default``
    exactly as before. The single accessor transformer.py's projection
    sites call, so the overlap and quant flags stay in lockstep."""
    if (getattr(cfg, "overlap", "xla") == "ring"
            and not getattr(cfg, "decode", False)
            and getattr(cfg, "pipeline_stages", 1) <= 1):
        return overlap_dot_general(kind, cfg.quant)
    return dot_general_for(cfg.quant) or default
