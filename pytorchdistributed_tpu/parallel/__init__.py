from pytorchdistributed_tpu.parallel.sharding import (  # noqa: F401
    fsdp_param_shardings,
    replicated_shardings,
    shardings_for_strategy,
)
from pytorchdistributed_tpu.parallel.precision import Policy  # noqa: F401
