from pytorchdistributed_tpu.parallel.overlap import (  # noqa: F401
    overlap_dot_general,
    validate_overlap,
)
from pytorchdistributed_tpu.parallel.precision import Policy  # noqa: F401
from pytorchdistributed_tpu.parallel.sharding import (  # noqa: F401
    fsdp_param_shardings,
    replicated_shardings,
    shardings_for_strategy,
)
