"""Compatibility shims for older jax releases.

The codebase targets the current stable jax API surface (``jax.set_mesh``,
``jax.shard_map`` with its ``check_vma`` varying-manual-axes checker,
``jax.sharding.get_abstract_mesh``, ``jax.typeof``); frozen images can pin
an older jax (0.4.x) where those names live under ``jax.experimental`` or
do not exist. Every patch below is a strict no-op when the running jax
already provides the name, so the shim is safe to install unconditionally
(the package ``__init__`` does, before any framework module touches jax).

Semantics notes for the backfilled names:

  * ``jax.set_mesh(mesh)`` — the repo only ever uses it as a context
    manager (``with jax.set_mesh(mesh): ...``). A concrete
    ``jax.sharding.Mesh`` is itself a context manager that binds the
    legacy thread-local physical mesh, which is exactly what the
    ``get_abstract_mesh`` shim reads back — so returning the mesh
    unchanged reproduces the ambient-mesh contract.
  * ``jax.shard_map(..., check_vma=...)`` — maps onto the experimental
    ``shard_map``'s ``check_rep``: the older replication checker is the
    predecessor of the varying-manual-axes checker, guarding the same
    class of bugs (unreplicated values escaping a manual region). Code
    that *queries* vma types (``jax.typeof(x).vma``) must treat "no vma
    attribute" as "checker off" — ``ops/ring_attention._vary_like``
    already does.
  * ``jax.typeof`` — ``jax.core.get_aval``; old avals carry no ``.vma``
    set, which downstream code reads as an empty set (see above).
"""

from __future__ import annotations

import functools

# Set by install(): False when jax.shard_map had to be backfilled from the
# experimental module. Partial-auto shard_map (axis_names ⊂ mesh axes — the
# pipeline schedules' shape) does not lower on those jax/jaxlib versions:
# axis_index inside the manual region emits a PartitionId the SPMD
# partitioner rejects, and a bare ppermute aborts the process on a
# spmd_partitioner.cc CHECK failure — probing by compiling is therefore not
# an option, so capability is keyed on the API vintage itself.
_NATIVE_SHARD_MAP = True


def supports_partial_auto_shard_map() -> bool:
    """False on jax versions whose shard_map cannot leave some mesh axes
    auto (jax 0.4.x) — the pipeline-parallel schedules need that. Tests and
    capture tooling gate on this instead of failing on an environment
    limitation."""
    return _NATIVE_SHARD_MAP


def supports_multiprocess_cpu_collectives() -> bool:
    """False on the 0.4.x-era jaxlib, which rejects multi-process
    programs on the CPU backend outright ("Multiprocess computations
    aren't implemented on the CPU backend") — the real-process launcher
    tests and `bench.py --bench scaling` need them. Same vintage marker
    as the shard_map backfill."""
    return _NATIVE_SHARD_MAP


def has_native_check_vma() -> bool:
    """False when check_vma is being emulated by the legacy check_rep
    checker (same vintage as the shard_map backfill). check_rep lacks
    replication rules for some primitives the vma checker handles (e.g.
    ``checkpoint_name``'s ``name`` primitive in a custom_vjp), so
    checked-path tests that exercise those gate on this."""
    return _NATIVE_SHARD_MAP


def install() -> None:
    global _NATIVE_SHARD_MAP
    import jax

    if not hasattr(jax, "set_mesh"):
        def set_mesh(mesh):
            """Context-manager use only (``with jax.set_mesh(mesh):``):
            the concrete Mesh's own context binds the thread-local mesh
            the get_abstract_mesh shim returns."""
            return mesh

        jax.set_mesh = set_mesh

    if not hasattr(jax.sharding, "get_abstract_mesh"):
        from jax._src import mesh as _mesh_lib

        def get_abstract_mesh():
            # the mesh bound by the legacy `with mesh:` context (an empty
            # Mesh — no axis names — when none is set, matching the new
            # API's "empty abstract mesh" sentinel closely enough for the
            # callers' `not mesh.axis_names` guards)
            return _mesh_lib.thread_resources.env.physical_mesh

        jax.sharding.get_abstract_mesh = get_abstract_mesh

    if not hasattr(jax, "shard_map"):
        _NATIVE_SHARD_MAP = False
        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                      axis_names=None):
            # new-API axis_names (the axes that go MANUAL) is the
            # complement of the experimental API's `auto` set
            auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
                    if axis_names is not None else frozenset())
            mapped = _shard_map(f, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, check_rep=check_vma,
                                auto=auto)
            if not auto:
                return mapped
            # partial-auto shard_map has no eager impl rule in older jax
            # ("if auto: raise NotImplementedError") but traces fine under
            # jit — route eager calls through a cached jit of the mapped fn
            jitted = jax.jit(mapped)

            def call(*args):
                if jax.core.trace_state_clean():
                    return jitted(*args)
                return mapped(*args)

            return call

        jax.shard_map = shard_map

    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # psum of the literal 1 over a named axis is special-cased to
            # the STATIC axis size (a Python int) — the old-API idiom the
            # new lax.axis_size canonicalized
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size

    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axis_name, *, to):
            # pcast annotates the NEW checker's varying-manual-axes type;
            # it is semantically the identity. The old check_rep machinery
            # tracks replication itself and auto-inserts conversions, so
            # the annotation simply drops out.
            del axis_name, to
            return x

        jax.lax.pcast = pcast

    if not hasattr(jax, "typeof"):
        def typeof(x):
            return jax.core.get_aval(x)

        jax.typeof = typeof
