"""Compiled-artifact invariants: what a train step's executable looks like.

The regression tripwires the chip can't give us when the TPU tunnel is
down (it wedged for all of rounds 3-4): instead of a throughput number,
assert properties of the COMPILED program that predict throughput —
per-device flops and peak temp memory from XLA's own analyses, and the
collective-op census of the optimized (post-SPMD-partitioning) HLO. Any
change that bloats memory, adds a collective, or changes the op mix fails
against committed numbers in tests/test_compiled_invariants.py on the CPU
sim, no hardware needed. This generalizes the round-4 one-off of
byte-diffing lowered HLO between commits (BASELINE.md "Pallas kernel
unification") into a harness; the committed-number discipline mirrors
bench.py's COMMITTED_BASELINES. Reference analog: the benchmark-as-test
harness at 03_model_parallel.ipynb:403-423 — this is its
works-without-a-chip half.
"""

from __future__ import annotations

import re

# The full XLA collective vocabulary a step can emit. Async pairs
# (`all-reduce-start`/`-done`) count once, as the -start; `-done` and
# fused variants with extra suffixes are excluded by requiring `(` right
# after the op name.
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "ragged-all-to-all",
    "collective-broadcast",
)


def collective_counts(hlo_text: str) -> dict[str, int]:
    """Census of collective ops in an HLO module's text, keyed by op name.

    Run it on OPTIMIZED HLO (`compiled.as_text()`): collectives are
    inserted by the SPMD partitioner during compilation, so pre-optimized
    (`lowered.as_text()`) modules show shardings but few/no collectives.
    Zero-count ops are included so equality against a committed dict also
    catches a collective *appearing* where none was."""
    return {
        op: len(re.findall(rf"{op}(?:-start)?\(", hlo_text))
        for op in COLLECTIVE_OPS
    }


def int8_counts(hlo_text: str) -> dict[str, int]:
    """Census of the int8 quantized-matmul op mix (ops/quant.py):
    ``s8_values`` — instructions producing an s8 tensor (the per-operand
    quantize converts; fusion bodies included, the text covers them);
    ``int_dots`` — dot instructions with s32 (int-accumulated) output.
    Both zero in an unquantized program, which is itself a tripwire: an
    int8 op appearing in a bf16 config's step is never an accident."""
    return {
        "s8_values": len(re.findall(r"= s8\[", hlo_text)),
        "int_dots": len(re.findall(r"= s32\[[^\]]*\]\S* dot\(", hlo_text)),
    }


def compiled_invariants(compiled) -> dict:
    """The committed-invariant dict for one compiled train step.

    * ``flops`` — XLA cost analysis, per device (post-partitioning).
    * ``temp_bytes`` — peak scratch memory of the executable: the
      activation / workspace footprint buffer assignment settled on.
    * ``arg_bytes`` — total input size: params + optimizer state + batch.
      The cheapest state-bloat tripwire there is (round 3's regression —
      BN buffers riding the optimizer tree — was exactly an arg_bytes
      growth).
    * ``alias_bytes`` — input bytes aliased to outputs: the DONATION
      tripwire. The train step donates its TrainState; if a jit change
      silently breaks donation (a dtype/sharding mismatch between the
      donated input and the output is enough — jax only warns), the step
      holds two copies of params+opt state and a model sized near HBM
      OOMs. alias ≈ state bytes is the proof donation still holds.
    * ``collectives`` — `collective_counts` of the optimized HLO.
    * ``int8_ops`` — `int8_counts`: the quantized-matmul convert/dot mix
      (all-zero for unquantized configs).
    """
    mem = compiled.memory_analysis()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):  # older jax wraps it in a list
        cost = cost[0] if cost else {}
    text = compiled.as_text()
    return {
        "flops": float(cost.get("flops", -1.0)),
        "temp_bytes": int(mem.temp_size_in_bytes),
        "arg_bytes": int(mem.argument_size_in_bytes),
        "alias_bytes": int(mem.alias_size_in_bytes),
        "collectives": collective_counts(text),
        "int8_ops": int8_counts(text),
    }
